"""Device telemetry ledger: per-kernel dispatch accounting.

The host side of the pipeline is thoroughly observed (spans, flight
recorder, SLO attribution); the device side — the jit roots that ARE the
system — was a black box beyond a recompile counter and two unattributed
aggregate totals.  The ``DispatchLedger`` closes that: every registered
jit root (the same roster the sanitizer's retrace hook sweeps —
``analysis.sanitizer._discover_jit_roots`` plus anything that arrives
through ``register_jit_root``) is wrapped with a ``_LedgerRoot`` proxy
that accounts each dispatch:

  * **execute wall time** — the wall clock of the dispatch call.  On an
    async backend this is the host-side submit (the same definition the
    ``device`` phase uses); synchronous work, first-trace time, and any
    blocking the call performs land here in full, and the device latency
    the host failed to hide shows up in the per-kernel d2h series below.
  * **first-trace compile time** — a dispatch that grew the root's
    compilation cache (``fn._cache_size()``) is a compile: its wall time
    counts into ``compiles``/``compile_s`` instead of the execute series,
    so a compile storm can't masquerade as a kernel regression.
  * **batch-shape buckets** — dispatches are keyed by the (shapes,
    dtypes, statics) of their arguments PLUS their device placement
    (device count + mesh axis shape off the most-sharded argument), so a
    mesh-partitioned dispatch never shares an execute-time series — or a
    sentinel baseline — with its single-chip twin; each kernel reports
    its bucket population, and the bucket's abstract args are retained (as
    ``ShapeDtypeStruct`` leaves — never the arrays, which may be donated)
    for cost analysis.
  * **XLA cost estimates** — ``fn.lower(*abstract).cost_analysis()``
    FLOPs / bytes-accessed per bucket, computed LAZILY on the first
    table request and memoized per (kernel, bucket): the lowering
    re-trace is far too slow for the dispatch path, and a repeat shape
    must never pay it twice.
  * **d2h attribution** — ``Scheduler._d2h`` threads a kernel tag
    through the choke point (ANALYSIS.md §d2h); the ledger splits
    ``scheduler_tpu_d2h_bytes_total`` into per-kernel bytes / seconds /
    fetches, with untagged fetches under ``_untagged`` so the per-kernel
    rows always sum to the aggregate counter.
  * **live HBM** — ``device.memory_stats()`` rows (bytes_in_use / peak /
    limit) surface in the table and as scrape-refreshed gauges where the
    backend supports them (CPU returns None; gated).
  * **regression sentinel** — a per-kernel rolling execute-time baseline
    (EWMA over non-compile dispatches, outliers excluded so a regression
    can't teach the baseline to accept it).  ``sustain`` consecutive
    dispatches past ``factor``× the warm baseline is a sustained breach:
    the ledger files a ``kernel_regression`` breach record NAMING the
    kernel through ``SLOEvaluator.external_breach`` — the PR-7 freeze →
    dump → re-arm machinery — and counts it in
    ``scheduler_tpu_kernel_regressions_total{kernel=}``.

Cost model: the ``kernelLedger`` kill switch reduces the disabled path
to the wrapper's single module-global read + branch per dispatch (the
tracer's discipline); enabled, each dispatch pays two clock reads, one
``_cache_size`` probe, a flat shape-key build, and one short lock hold —
per BATCH, not per pod, which keeps it unmeasurable next to the
dispatches themselves (measured numbers in OBSERVABILITY.md §5).

Attribution scope: the wrapped roots are process-global (module
attributes), the ledger is per-Scheduler; dispatches route to the
ACTIVE ledger (``activate``, weakly held — the normal one-scheduler
process routes exactly).  ``Scheduler._d2h`` records into its OWN
scheduler's ledger, so per-kernel d2h rows reconcile per scheduler.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

import jax

# Lock-discipline registry (kubernetes_tpu.analysis): the scheduling
# loop records dispatches, binding workers/HTTP handlers read tables,
# and the planner thread records d2h — all concurrently.
_KTPU_GUARDED = {
    "DispatchLedger": {
        "lock": "_mu",
        "guards": {
            "_kstats": None,
            "_cost_memo": None,
            "_cost_hits": None,
            "_cost_misses": None,
            "_regressions": None,
            "_breakers": None,
        },
    },
}

# ---------------------------------------------------------------------------
# per-kernel circuit breaker (ISSUE 15: the device-fault robustness tier)
# ---------------------------------------------------------------------------

# a kernel whose dispatches keep failing trips OPEN after this many
# consecutive failed dispatches (abandoned retries, real backend errors,
# watchdog stalls, poisoned readbacks, and sentinel sustained-breach
# verdicts all count one each; any success resets the streak)
BREAKER_TRIP_THRESHOLD = 3
# in-place retries per dispatch for faults raised BEFORE the kernel ran
# (injected errors: the args — possibly donated — are still live; a real
# backend error never retries in place, its buffers may be consumed)
BREAKER_RETRIES = 2
BREAKER_BACKOFF_S = 0.0  # per-attempt backoff (scaled by attempt number)
# cooldown is counted in DENIED dispatch-family requests, not wall time:
# routing checks are sequenced by the scheduling loop, so breaker state
# transitions — and therefore the chaos fault schedule that depends on
# dispatch ordinals — replay deterministically from the seed alone
BREAKER_HALF_OPEN_AFTER = 8

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"
_BREAKER_GAUGE = {BREAKER_CLOSED: 0, BREAKER_OPEN: 1, BREAKER_HALF_OPEN: 2}


class DispatchFailed(RuntimeError):
    """A kernel dispatch was abandoned (retries exhausted, a real backend
    error, or an un-retryable injected fault).  Callers route the batch
    to the kernel's registered fallback engine — the breaker fallback
    roster below names it — and, for ``kind == "mesh_device_loss"``,
    degrade the mesh first (Scheduler._degrade_mesh)."""

    def __init__(self, kernel: str, cause, kind: str = "dispatch_error"):
        super().__init__(f"dispatch of {kernel} failed ({kind}): {cause}")
        self.kernel = kernel
        self.cause = cause
        self.kind = kind


class BreakerOpen(DispatchFailed):
    """A dispatch reached an OPEN breaker (the routing gates normally
    prevent this; an ungated site falls back exactly like a failure)."""

    def __init__(self, kernel: str):
        super().__init__(kernel, "circuit breaker open", kind="breaker_open")


# Every registered jit root must declare how the scheduler drains when
# its breaker is open: a ``fallback(<engine>): <how>`` story naming the
# parity-certified engine that replaces it, or an explicit
# ``no_fallback: <why>`` waiver.  The static analyzer's ``breaker`` rule
# (kubernetes_tpu/analysis/breaker.py) gates this literal against the
# discovered jit-root surface — the same burn-down discipline as the
# shard rule's ``resolved(...)`` roster.
_KTPU_BREAKER_FALLBACKS = {
    "chain.chain_dispatch": (
        "fallback(direct): the chained pipeline drains and the live batch "
        "degrades to per-pod host-oracle cycles; later batches redispatch "
        "on the direct wave/scan path (same verdict kernels, no overlap)"
    ),
    "coscheduling.workloads_run": (
        "fallback(serial-oracle): the workloads gate refuses and the batch "
        "degrades to the per-pod host-plugin cycle — the gangDispatch "
        "kill-switch path (WORKLOADS.md; decision-identical for DRA/volume "
        "pods, gangs lose quorum semantics exactly as documented there)"
    ),
    "coscheduling.workloads_schedule": (
        "fallback(serial-oracle): inner admission scan of workloads_run — "
        "same routing gate, same per-pod host-plugin fallback"
    ),
    "counterfactual.counterfactual_run": (
        "fallback(serial-oracle): fork specs replay through "
        "oracle/planner.serial_plan — the plannerKernel kill-switch engine "
        "(decision-identical, plan_vs_serial_oracle)"
    ),
    "explain.explain_masks": (
        "no_fallback: read-only diagnosis endpoint — a failure surfaces as "
        "an error field in /debug/explain; no placement depends on it"
    ),
    "fastpath.sig_scan": (
        "fallback(host-committer): the FastCommitter lazy-heap greedy "
        "answers the batch bit-identically (tests/test_fastpath.py); the "
        "device lineage re-materializes from it at the next dispatch"
    ),
    "fastpath.static_eval": (
        "fallback(scan): a failed static eval fails the fast gate and the "
        "batch takes the direct gang-scan path, which reads no "
        "per-signature rows"
    ),
    "gang.gang_run": (
        "fallback(serial-oracle): pods degrade to one-pod host-oracle "
        "cycles (_schedule_one_extender) — the fallback ladder's floor, "
        "bit-identical by the parity property"
    ),
    "gang.gang_schedule": (
        "fallback(serial-oracle): inner scan of gang_run — same routing "
        "gate, same per-pod host-oracle fallback"
    ),
    "pipeline._pipeline": (
        "no_fallback: the standalone parity harness's reference engine — "
        "it IS the ladder's floor and runs outside the Scheduler"
    ),
    "preemption.narrow_candidates": (
        "fallback(superset): narrowing is an optimization — on failure the "
        "preemption evaluator walks the full candidate node set "
        "(superset-sound by construction)"
    ),
    "resident.resident_run": (
        "fallback(host-committer): the epoch-guarded resync drops the "
        "device lineage and the FastCommitter greedy finishes the run "
        "bit-identically (RESIDENT.md fallback matrix)"
    ),
    "resident.usage_checksum": (
        "no_fallback: the epoch guard's integrity probe — a failure here "
        "IS the fault signal, booked against the resident family's breaker"
    ),
    "wave.wave_run": (
        "fallback(scan): wave-shaped batches ride the gang scan — the "
        "waveDispatch kill-switch path, bit-identical to queue order by "
        "construction (WAVE.md)"
    ),
    "wave.wave_schedule": (
        "fallback(scan): inner conflict-resolution scan of wave_run — "
        "same gate, same gang-scan fallback"
    ),
}


def breaker_fallbacks() -> Dict[str, str]:
    """The breaker fallback roster (copy) — tests assert runtime jit-root
    coverage against it; the static analyzer reads the literal."""
    return dict(_KTPU_BREAKER_FALLBACKS)


class _BreakerState:
    """Per-kernel breaker bookkeeping; mutated under the ledger's _mu
    (the ``_breakers`` dict is the registered guarded state)."""

    __slots__ = (
        "state",
        "failures",
        "denials",
        "trips",
        "last_kind",
        "half_open_probes",
        "latched",
    )

    def __init__(self) -> None:
        self.state = BREAKER_CLOSED
        self.failures = 0  # consecutive, resets on success
        self.denials = 0  # while open — the count-based cooldown
        self.trips = 0
        self.last_kind = ""
        self.half_open_probes = 0
        self.latched = False  # force_breaker_open: no half-open cooldown


# chaos hook (chaos/device.py installs a DeviceFaultInjector; None in
# production).  Module-global like the active-ledger ref: the hot path
# reads one global and never imports the chaos package.
_fault_injector = None


def set_fault_injector(inj) -> None:
    global _fault_injector
    _fault_injector = inj


def fault_injector():
    return _fault_injector

# the sentinel's defaults: a kernel must have this many warm (non-compile)
# samples before its baseline judges anything; a sustained run of
# dispatches all past factor× baseline is a breach
SENTINEL_MIN_SAMPLES = 16
SENTINEL_FACTOR = 4.0
SENTINEL_SUSTAIN = 5
# dispatches faster than this never breach — µs-level submits jitter by
# factors without meaning anything
SENTINEL_FLOOR_S = 0.002
# EWMA step for the rolling baseline (slow: the baseline tracks drift,
# not noise)
BASELINE_ALPHA = 0.05

_UNTAGGED = "_untagged"


class _KernelStats:
    """Per-kernel accumulation; every field mutated under the ledger's
    ``_mu`` (the whole ``_kstats`` dict is the registered guarded
    state)."""

    __slots__ = (
        "dispatches",
        "execute_s",
        "last_execute_s",
        "compiles",
        "compile_s",
        "buckets",
        "cache_size",
        "d2h_fetches",
        "d2h_bytes",
        "d2h_s",
        "baseline_s",
        "baseline_n",
        "streak",
        "regressions",
    )

    def __init__(self) -> None:
        self.dispatches = 0
        self.execute_s = 0.0
        self.last_execute_s = 0.0
        self.compiles = 0
        self.compile_s = 0.0
        # bucket key → {"count": int, "spec": (args, kwargs) with arrays
        # replaced by ShapeDtypeStruct, or None when unbuildable}
        self.buckets: Dict[tuple, dict] = {}
        # high watermark of the root's jit compilation-cache size (-1 =
        # not yet seen): compile classification compares against THIS,
        # not the caller's own before-read, so a warm dispatch racing a
        # concurrent first-shape compile doesn't book the growth twice
        self.cache_size = -1
        self.d2h_fetches = 0
        self.d2h_bytes = 0
        self.d2h_s = 0.0
        self.baseline_s = 0.0
        self.baseline_n = 0
        self.streak = 0
        self.regressions = 0


def _leaf_key(leaf):
    """One flat, hashable token per argument leaf: (shape, dtype) for
    array-likes, the value itself for jit statics (strings/bools/ints/
    floats/enums — all hashable by the jit contract), repr as the
    fallback so an exotic static can never make the key unhashable."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    try:
        hash(leaf)
        return leaf
    except TypeError:
        return repr(leaf)


def _bucket_key(args, kwargs) -> tuple:
    """The dispatch's batch-shape bucket + its device placement: flat
    leaf tokens in pytree order (dict keys sort deterministically under
    tree_flatten), so two calls share a bucket exactly when jit would
    share an executable (modulo weak types) — PLUS the dispatch's device
    count and mesh axis shape, read off the most-sharded array argument.
    Single-chip and mesh-partitioned dispatches of the same shapes are
    different executables with different cost profiles; keying them apart
    keeps the execute-time series (and the regression sentinel's EWMA
    baseline) from smearing into one meaningless average.

    Returns ``(key, n_devices, mesh_shape)`` where mesh_shape is a tuple
    of (axis_name, size) pairs (empty off-mesh)."""
    ndev, mesh_shape = 1, ()
    toks = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        toks.append(_leaf_key(leaf))
        sh = getattr(leaf, "sharding", None)
        if sh is None:
            continue
        try:
            # a replicated placement spans the mesh's device set without
            # PARTITIONING anything — counting it would let a silently
            # replicated run satisfy every engagement guard (bench
            # collective_ratio, the paritycheck __engaged__ check)
            if sh.is_fully_replicated:
                continue
            n = len(sh.device_set)
        except Exception:  # noqa: BLE001 — placement probing is best-effort
            continue
        if n > ndev:
            ndev = n
            m = getattr(sh, "mesh", None)
            try:
                mesh_shape = tuple(
                    (str(k), int(v)) for k, v in m.shape.items()
                )
            except Exception:  # noqa: BLE001
                mesh_shape = ()
    return tuple(toks) + (("devices", ndev, mesh_shape),), ndev, mesh_shape


def _abstract_spec(args, kwargs):
    """(args, kwargs) with array leaves replaced by ShapeDtypeStruct —
    retained per bucket for the lazy cost lowering.  Never holds the
    arrays themselves: dispatch args may be DONATED, and pinning them
    here would defeat the donation."""

    def conv(leaf):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            return jax.ShapeDtypeStruct(tuple(shape), dtype)
        return leaf

    return jax.tree_util.tree_map(conv, (tuple(args), dict(kwargs)))


class DispatchLedger:
    """Per-kernel dispatch accounting + the regression sentinel.

    One per Scheduler (``sched.kernels``); the process-global root
    wrappers route through the ACTIVE ledger (``activate``).  ``prom``
    is the scheduler's ``SchedulerMetrics`` (optional — standalone
    ledgers in tests run without a registry); ``tracer`` feeds
    device-track spans when a capture is running; ``slo_getter`` returns
    the scheduler's SLOEvaluator (or None) at breach time.
    """

    def __init__(
        self,
        prom=None,
        tracer=None,
        slo_getter=None,
        clock=time.perf_counter,
        sentinel_factor: float = SENTINEL_FACTOR,
        sentinel_min_samples: int = SENTINEL_MIN_SAMPLES,
        sentinel_sustain: int = SENTINEL_SUSTAIN,
        sentinel_floor_s: float = SENTINEL_FLOOR_S,
        breaker_trip_threshold: int = BREAKER_TRIP_THRESHOLD,
        breaker_retries: int = BREAKER_RETRIES,
        breaker_backoff_s: float = BREAKER_BACKOFF_S,
        breaker_half_open_after: int = BREAKER_HALF_OPEN_AFTER,
        watchdog_s: Optional[float] = None,
    ):
        self.enabled = True
        self.prom = prom
        self.tracer = tracer
        self.slo_getter = slo_getter
        self._clock = clock
        self.sentinel_factor = sentinel_factor
        self.sentinel_min_samples = sentinel_min_samples
        self.sentinel_sustain = sentinel_sustain
        self.sentinel_floor_s = sentinel_floor_s
        self.breaker_trip_threshold = breaker_trip_threshold
        self.breaker_retries = breaker_retries
        self.breaker_backoff_s = breaker_backoff_s
        self.breaker_half_open_after = breaker_half_open_after
        # per-dispatch watchdog deadline: a warm (non-compile) dispatch
        # slower than this books a "dispatch_hang" breaker failure — the
        # hung-collective detector.  None = off (the default: CPU test
        # boxes jitter by seconds; chaos scenarios and accelerator
        # deployments set it).  An INJECTED hang always books the failure
        # regardless — the chaos contract defines its stall as past the
        # deadline, so the verdict never races a real clock.
        self.watchdog_s = watchdog_s
        self._mu = threading.Lock()
        self._kstats: Dict[str, _KernelStats] = {}
        # (kernel, bucket) → cost dict or None (lowering failed)
        self._cost_memo: Dict[tuple, Optional[dict]] = {}
        self._cost_hits = 0
        self._cost_misses = 0
        self._regressions: List[dict] = []
        self._breakers: Dict[str, _BreakerState] = {}

    # -- dispatch recording ---------------------------------------------------

    def dispatch(self, name: str, fn, args, kwargs):
        """Account one dispatch of jit root ``name`` and return its
        result.  Called by the ``_LedgerRoot`` wrappers; host-side calls
        only — an in-trace call (one root tracing through another, or an
        ``eval_shape`` of the wrapper) passes straight through, because
        it is not a dispatch and its tracer args have no dispatch cost.

        Fault boundary (ISSUE 15): an installed chaos injector draws a
        device fault per ATTEMPT; injected errors retry in place with
        backoff (the kernel never ran — the args, donated or not, are
        live), real backend errors never do (their buffers may be
        consumed).  Either way the per-kernel breaker books the failure,
        and an abandoned dispatch raises ``DispatchFailed`` for the
        caller's registered fallback engine."""
        if not jax.core.trace_state_clean():
            return fn(*args, **kwargs)
        # an OPEN breaker that a routing gate didn't consult: deny here
        # (counts toward the same half-open cooldown the gates feed)
        if not self._breaker_admit(name):
            raise BreakerOpen(name)
        attempt = 0
        while True:
            inj = _fault_injector
            stall = 0.0
            injected_hang = False
            if inj is not None:
                kind = inj.dispatch_fault(name)
                if kind == "dispatch_hang":
                    injected_hang = True
                    stall = inj.hang_s
                elif kind is not None:
                    # error/mesh-loss raised BEFORE the kernel runs
                    self._breaker_failure(name, kind)
                    if kind == "dispatch_error" and attempt < self.breaker_retries:
                        attempt += 1
                        if self.breaker_backoff_s:
                            time.sleep(self.breaker_backoff_s * attempt)
                        continue
                    try:
                        inj.raise_for(kind, name)
                    except RuntimeError as e:
                        raise DispatchFailed(name, e, kind=kind) from e
            try:
                return self._record_dispatch(
                    name,
                    fn,
                    args,
                    kwargs,
                    stall_s=stall,
                    injected_hang=injected_hang,
                )
            except DispatchFailed:
                raise
            except Exception as e:  # noqa: BLE001 — backend failure class
                # a REAL dispatch failure: the kernel may have consumed
                # its donated inputs, so no in-place retry — the breaker
                # books it and the caller's fallback engine (with the
                # epoch-guarded resync where resident state is involved)
                # takes the batch
                self._breaker_failure(name, "dispatch_error")
                raise DispatchFailed(name, e) from e

    def _record_dispatch(
        self, name: str, fn, args, kwargs, stall_s=0.0, injected_hang=False
    ):
        # the bucket key is built BEFORE the call: args may be donated,
        # and their metadata (shapes AND shardings) must be read while
        # they're live
        key, ndev, mesh_shape = _bucket_key(args, kwargs)
        size_before = fn._cache_size()
        with self._mu:
            ks = self._kstats.get(name)
            if ks is None:
                ks = self._kstats[name] = _KernelStats()
            if ks.cache_size < 0:
                ks.cache_size = size_before
            known_bucket = key in ks.buckets
        spec = None
        if not known_bucket:
            try:
                spec = _abstract_spec(args, kwargs)
            except Exception:  # noqa: BLE001 — cost analysis is optional
                spec = None
        t0 = self._clock()
        if stall_s:
            # injected dispatch_hang: the stall rides the execute wall
            # exactly where a hung collective's would
            time.sleep(stall_s)
        out = fn(*args, **kwargs)
        dt = self._clock() - t0
        size_after = fn._cache_size()
        breach = None
        with self._mu:
            ks = self._kstats[name]
            # watermark comparison (not size_before): with two threads
            # dispatching one root, only the FIRST to book the growth
            # counts as the compile.  A _clear_cache() shrink leaves the
            # watermark high (test-only; the next growth re-books).
            compiled = size_after > ks.cache_size
            if size_after > ks.cache_size:
                ks.cache_size = size_after
            ks.dispatches += 1
            b = ks.buckets.get(key)
            if b is None:
                b = ks.buckets[key] = {
                    "count": 0,
                    "spec": spec,
                    "devices": ndev,
                    "mesh": mesh_shape,
                }
            elif b["spec"] is None and spec is not None:
                b["spec"] = spec
            b["count"] += 1
            if compiled:
                ks.compiles += 1
                ks.compile_s += dt
            else:
                ks.execute_s += dt
                ks.last_execute_s = dt
                breach = self._sentinel_locked(name, ks, dt)
        prom = self.prom
        if prom is not None:
            prom.kernel_dispatches.inc(kernel=name)
            if compiled:
                prom.kernel_compiles.inc(kernel=name)
                prom.kernel_compile_seconds.inc(dt, kernel=name)
            else:
                prom.kernel_execute.observe(dt, kernel=name)
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.complete_track(
                "device",
                name,
                t0,
                t0 + dt,
                cat="device",
                compile=bool(compiled),
            )
        # watchdog verdict: an injected hang is a breach BY CONTRACT
        # (its stall is DEFINED as past the deadline, even when replay
        # skips the sleep itself); a real dispatch breaches only when
        # warm (compile storms are not hangs) and a deadline is set
        hung = injected_hang or (
            self.watchdog_s is not None
            and not compiled
            and dt > self.watchdog_s
        )
        if hung:
            self._breaker_failure(name, "dispatch_hang")
        else:
            self._breaker_success(name)
        if breach is not None:
            self._file_breach(name, breach)
        return out

    def _sentinel_locked(self, name: str, ks: _KernelStats, dt: float):
        """Rolling-baseline regression check for one warm sample; returns
        a breach record when the sustained-breach bar is crossed.  The
        baseline learns only from NON-breaching samples — a regression
        must not teach the baseline to accept it."""
        if ks.baseline_n < self.sentinel_min_samples:
            # warmup: establish the baseline unconditionally
            ks.baseline_n += 1
            ks.baseline_s += (dt - ks.baseline_s) / ks.baseline_n
            return None
        threshold = max(
            ks.baseline_s * self.sentinel_factor, self.sentinel_floor_s
        )
        if dt <= threshold:
            ks.streak = 0
            ks.baseline_s += BASELINE_ALPHA * (dt - ks.baseline_s)
            return None
        ks.streak += 1
        if ks.streak < self.sentinel_sustain:
            return None
        ks.streak = 0
        ks.regressions += 1
        record = {
            "objective": "kernel_regression",
            "kernel": name,
            "baseline_s": round(ks.baseline_s, 6),
            "measured_s": round(dt, 6),
            "factor": self.sentinel_factor,
            "sustained": self.sentinel_sustain,
        }
        self._regressions.append(record)
        del self._regressions[:-8]
        return record

    def _file_breach(self, name: str, record: dict) -> None:
        """Outside ``_mu``: count the regression and hand the record to
        the SLO tier's freeze→dump→re-arm machinery (when installed —
        the record is already retained in ``_regressions`` either way).
        A sustained-breach verdict also counts toward the kernel's
        breaker trip threshold: a kernel that got pathologically slow is
        drained through its fallback engine the same way a faulting one
        is (ISSUE 15 satellite)."""
        if self.prom is not None:
            self.prom.kernel_regressions.inc(kernel=name)
        self._breaker_failure(name, "sentinel")
        getter = self.slo_getter
        slo = getter() if getter is not None else None
        if slo is not None:
            try:
                slo.external_breach(dict(record))
            except Exception:  # noqa: BLE001 — accounting must not
                pass  # break the dispatch that happened to breach

    # -- circuit breaker (ISSUE 15) -------------------------------------------

    def _breaker_of_locked(self, name: str) -> _BreakerState:
        b = self._breakers.get(name)
        if b is None:
            b = self._breakers[name] = _BreakerState()
        return b

    def _set_breaker_gauge(self, name: str, state: str) -> None:
        prom = self.prom
        if prom is not None:
            prom.kernel_breaker_state.set(
                _BREAKER_GAUGE[state], kernel=name
            )

    def _breaker_failure(self, name: str, kind: str) -> None:
        """Book one failed dispatch/readback/verdict against ``name``'s
        breaker; trips it open at the threshold (a half-open probe's
        failure re-trips immediately)."""
        with self._mu:
            b = self._breaker_of_locked(name)
            b.last_kind = kind
            b.failures += 1
            tripped = False
            if b.state == BREAKER_HALF_OPEN or (
                b.state == BREAKER_CLOSED
                and b.failures >= self.breaker_trip_threshold
            ):
                b.state = BREAKER_OPEN
                b.denials = 0
                b.trips += 1
                tripped = True
            state = b.state
        prom = self.prom
        if prom is not None:
            prom.kernel_breaker_failures.inc(kernel=name, kind=kind)
            if tripped:
                prom.kernel_breaker_trips.inc(kernel=name)
        self._set_breaker_gauge(name, state)

    def _breaker_success(self, name: str) -> None:
        """A clean dispatch: reset the streak; a half-open probe's
        success closes the breaker (recovery)."""
        with self._mu:
            b = self._breakers.get(name)
            if b is None:
                return
            changed = b.state != BREAKER_CLOSED
            if b.state == BREAKER_HALF_OPEN:
                b.half_open_probes += 1
            b.failures = 0
            b.denials = 0
            b.state = BREAKER_CLOSED
        if changed:
            self._set_breaker_gauge(name, BREAKER_CLOSED)

    def _breaker_admit(self, name: str) -> bool:
        """Should a dispatch of ``name`` proceed?  Closed/half-open →
        yes; open → no, but the denial counts toward the COUNT-BASED
        cooldown (deterministic under replay — no wall clock), and the
        request that crosses it becomes the half-open probe."""
        with self._mu:
            b = self._breakers.get(name)
            if b is None or b.state == BREAKER_CLOSED:
                return True
            if b.state == BREAKER_HALF_OPEN:
                return True
            if b.latched:
                return False
            b.denials += 1
            if b.denials < self.breaker_half_open_after:
                return False
            b.state = BREAKER_HALF_OPEN
        self._set_breaker_gauge(name, BREAKER_HALF_OPEN)
        return True  # this request is the probe

    def breaker_allows(self, kernel: str) -> bool:
        """The routing-gate check: False routes the dispatch family to
        its registered fallback engine (the caller bumps
        ``scheduler_tpu_wave_fallback_total{reason="breaker"}``)."""
        if not self.enabled:
            return True
        return self._breaker_admit(kernel)

    def breaker_state(self, kernel: str) -> str:
        with self._mu:
            b = self._breakers.get(kernel)
            return b.state if b is not None else BREAKER_CLOSED

    def record_breaker_failure(self, kernel: str, kind: str) -> None:
        """Public failure feed for faults detected OUTSIDE the dispatch
        wrapper: poisoned readbacks (Scheduler's guarded fetches) and
        resident-snapshot placement failures."""
        self._breaker_failure(kernel, kind)

    def force_breaker_open(self, kernel: str) -> None:
        """Latch ``kernel``'s breaker open (tests / paritycheck's
        breaker-degraded parity run): denials never reach the half-open
        cooldown until ``reset_breaker``."""
        with self._mu:
            b = self._breaker_of_locked(kernel)
            b.state = BREAKER_OPEN
            b.latched = True
        self._set_breaker_gauge(kernel, BREAKER_OPEN)

    def reset_breaker(self, kernel: str) -> None:
        with self._mu:
            b = self._breakers.get(kernel)
            if b is None:
                return
            b.state = BREAKER_CLOSED
            b.failures = 0
            b.denials = 0
            b.latched = False
        self._set_breaker_gauge(kernel, BREAKER_CLOSED)

    def breaker_rows(self) -> Dict[str, dict]:
        """Per-kernel breaker snapshot (the /debug/kernels column)."""
        with self._mu:
            return {
                name: {
                    "state": b.state,
                    "failures": b.failures,
                    "denials": b.denials,
                    "trips": b.trips,
                    "half_open_probes": b.half_open_probes,
                    "last_kind": b.last_kind,
                }
                for name, b in self._breakers.items()
            }

    # -- d2h attribution (fed by Scheduler._d2h) ------------------------------

    def record_d2h(self, kernel: Optional[str], nbytes: int, dt: float) -> None:
        """One blocking device→host fetch, attributed to ``kernel`` (None
        → ``_untagged``, so per-kernel rows always sum to the aggregate
        d2h counters)."""
        name = kernel or _UNTAGGED
        with self._mu:
            ks = self._kstats.get(name)
            if ks is None:
                ks = self._kstats[name] = _KernelStats()
            ks.d2h_fetches += 1
            ks.d2h_bytes += nbytes
            ks.d2h_s += dt
        prom = self.prom
        if prom is not None:
            prom.kernel_d2h_bytes.inc(nbytes, kernel=name)
            prom.kernel_d2h_seconds.inc(dt, kernel=name)

    # -- cost analysis (lazy, memoized) ---------------------------------------

    def _cost_for(self, name: str, key: tuple, spec) -> Optional[dict]:
        """FLOPs / bytes-accessed estimate for one (kernel, bucket),
        memoized: the ``fn.lower`` re-trace is seconds-scale on the big
        kernels, so a repeat shape must hit the memo.  Returns None when
        the root is gone or the lowering fails (a cost estimate is never
        worth an error surface)."""
        memo_key = (name, key)
        with self._mu:
            if memo_key in self._cost_memo:
                self._cost_hits += 1
                return self._cost_memo[memo_key]
            self._cost_misses += 1
        cost: Optional[dict] = None
        fn = _wrapped_fn(name)
        if fn is not None and spec is not None:
            try:
                s_args, s_kwargs = spec
                ca = fn.lower(*s_args, **s_kwargs).cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                cost = {
                    "flops": float(ca.get("flops", 0.0)),
                    "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
                }
            except Exception:  # noqa: BLE001 — estimate only
                cost = None
        with self._mu:
            self._cost_memo[memo_key] = cost
        return cost

    # -- reporting ------------------------------------------------------------

    def table(self, cost: bool = True) -> List[dict]:
        """The per-kernel table /debug/kernels and the bench line serve:
        one row per kernel in the ROSTER (wrapped roots + the sanitizer's
        runtime registry) plus every kernel the ledger has seen, sorted
        by execute seconds descending — a registered root that never
        dispatched still shows, with zeros, so nothing is unobserved
        silently.  ``cost=True`` fills FLOPs/bytes estimates for each
        kernel's most-dispatched bucket (first call pays the lowering;
        memoized after)."""
        names = set(roster()) | self._seen()
        with self._mu:
            names |= set(self._breakers)  # breaker-only rows still show
        want_cost: List[Tuple[str, tuple, object]] = []
        rows = []
        with self._mu:
            for name in sorted(names):
                ks = self._kstats.get(name)
                if ks is None:
                    ks = _KernelStats()
                # device placement summary: which device counts / mesh
                # shapes this kernel's dispatches ran on (bucket-keyed, so
                # single-chip vs multichip series never smear — ISSUE 14)
                dev_counts = sorted(
                    {b.get("devices", 1) for b in ks.buckets.values()}
                ) or [1]
                mesh_shapes = sorted(
                    {
                        "x".join(str(s) for _a, s in b["mesh"])
                        for b in ks.buckets.values()
                        if b.get("mesh")
                    }
                )
                multi_dev = sum(
                    b["count"]
                    for b in ks.buckets.values()
                    if b.get("devices", 1) > 1
                )
                brk = self._breakers.get(name)
                row = {
                    "kernel": name,
                    "dispatches": ks.dispatches,
                    "execute_s": round(ks.execute_s, 6),
                    "last_execute_s": round(ks.last_execute_s, 6),
                    "compiles": ks.compiles,
                    "compile_s": round(ks.compile_s, 6),
                    "shape_buckets": len(ks.buckets),
                    "devices": dev_counts,
                    "mesh_shapes": mesh_shapes,
                    "multi_device_dispatches": multi_dev,
                    "d2h_fetches": ks.d2h_fetches,
                    "d2h_bytes": ks.d2h_bytes,
                    "d2h_s": round(ks.d2h_s, 6),
                    "baseline_s": round(ks.baseline_s, 6),
                    "regressions": ks.regressions,
                    # breaker column: closed kernels that never faulted
                    # show "closed"/0 so the table is uniformly shaped
                    "breaker": brk.state if brk is not None else BREAKER_CLOSED,
                    "breaker_trips": brk.trips if brk is not None else 0,
                }
                if cost and ks.buckets:
                    key, b = max(
                        ks.buckets.items(), key=lambda kv: kv[1]["count"]
                    )
                    want_cost.append((name, key, b["spec"]))
                rows.append(row)
        by_name = {r["kernel"]: r for r in rows}
        for name, key, spec in want_cost:
            c = self._cost_for(name, key, spec)
            if c is not None:
                by_name[name]["est_flops"] = c["flops"]
                by_name[name]["est_bytes_accessed"] = c["bytes_accessed"]
        prom = self.prom
        if prom is not None:
            for r in rows:
                p50 = prom.kernel_execute.percentile(0.5, kernel=r["kernel"])
                p99 = prom.kernel_execute.percentile(0.99, kernel=r["kernel"])
                r["execute_p50_s"] = None if p50 != p50 or p50 == float("inf") else round(p50, 6)
                r["execute_p99_s"] = None if p99 != p99 or p99 == float("inf") else round(p99, 6)
        rows.sort(key=lambda r: (-r["execute_s"], r["kernel"]))
        return rows

    def _seen(self) -> set:
        with self._mu:
            return set(self._kstats)

    def stats(self) -> dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "kernels": len(self._kstats),
                "dispatches": sum(
                    ks.dispatches for ks in self._kstats.values()
                ),
                # dispatches whose arguments were partitioned across >1
                # device — the bench tier's collective_ratio numerator
                "multi_device_dispatches": sum(
                    b["count"]
                    for ks in self._kstats.values()
                    for b in ks.buckets.values()
                    if b.get("devices", 1) > 1
                ),
                "cost_memo_hits": self._cost_hits,
                "cost_memo_misses": self._cost_misses,
                "regressions": list(self._regressions),
                "breakers_open": sum(
                    1
                    for b in self._breakers.values()
                    if b.state != BREAKER_CLOSED
                ),
                "breaker_trips": sum(
                    b.trips for b in self._breakers.values()
                ),
            }

    def hbm_rows(self) -> List[dict]:
        """Live per-device memory stats where the backend supports them
        (``device.memory_stats()`` — None on CPU backends, gated): the
        scrape-refreshed ``scheduler_tpu_device_hbm_bytes`` feed and the
        /debug/kernels header."""
        rows = []
        try:
            devices = jax.devices()
        except Exception:  # noqa: BLE001 — backend torn down
            return rows
        for d in devices:
            try:
                ms = d.memory_stats()
            except Exception:  # noqa: BLE001 — unsupported backend
                ms = None
            if not ms:
                continue
            rows.append(
                {
                    "device": str(d.id),
                    "platform": getattr(d, "platform", "?"),
                    "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                    "peak_bytes_in_use": int(ms.get("peak_bytes_in_use", 0)),
                    "bytes_limit": int(ms.get("bytes_limit", 0)),
                }
            )
        return rows

    def snapshot(self, cost: bool = True) -> dict:
        """The /debug/kernels body."""
        out = {
            "enabled": self.enabled,
            "kernels": self.table(cost=cost),
            "memory": self.hbm_rows(),
        }
        st = self.stats()
        out["dispatches"] = st["dispatches"]
        out["cost_memo_hits"] = st["cost_memo_hits"]
        out["cost_memo_misses"] = st["cost_memo_misses"]
        out["regressions"] = st["regressions"]
        out["breakers"] = self.breaker_rows()
        return out


# ---------------------------------------------------------------------------
# root wrapping (module-global: the roots are module attributes)
# ---------------------------------------------------------------------------

# name → (module, attr, original fn) for everything currently wrapped
_wrapped: Dict[str, tuple] = {}
# weakly-held active ledger: the wrappers' single global read.  Weak so a
# torn-down Scheduler's ledger (and its metrics registry) never outlives
# it just because it was the last one activated.
_active_ref: Optional["weakref.ref"] = None
_install_mu = threading.Lock()


class _LedgerRoot:
    """Instrumented stand-in for one module-level jit root.  Disabled
    path (no active ledger / kill switch off): one module-global read +
    branch, then the original call.  Everything else (``_cache_size``,
    ``lower``, ``trace``, ``eval_shape``) proxies to the wrapped
    PjitFunction so the sanitizer's retrace sweep and the shapecheck
    cross-check see the root unchanged.  ``__weakref__`` rides along:
    jax's tracing caches take weak references to the callable."""

    __slots__ = ("_fn", "_name", "__weakref__")

    def __init__(self, name: str, fn):
        self._fn = fn
        self._name = name

    def __call__(self, *args, **kwargs):
        ref = _active_ref
        led = ref() if ref is not None else None
        if led is None or not led.enabled:
            return self._fn(*args, **kwargs)
        return led.dispatch(self._name, self._fn, args, kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)

    @property
    def __wrapped__(self):
        return self._fn

    def __repr__(self):
        return f"<LedgerRoot {self._name} of {self._fn!r}>"


def activate(ledger: DispatchLedger) -> None:
    """Route dispatches through ``ledger`` (weakly held).  The normal
    process has ONE scheduler; with several, the last activation wins —
    dispatch attribution is process-wide, d2h attribution stays exact
    per scheduler (``Scheduler._d2h`` records into its own ledger)."""
    global _active_ref
    _active_ref = weakref.ref(ledger)


def deactivate(ledger: Optional[DispatchLedger] = None) -> None:
    """Stop routing (``ledger`` given: only if it is the active one)."""
    global _active_ref
    if ledger is not None:
        ref = _active_ref
        if ref is None or ref() is not ledger:
            return
    _active_ref = None


def active() -> Optional[DispatchLedger]:
    ref = _active_ref
    return ref() if ref is not None else None


def install() -> int:
    """Wrap every discovered module-level jit root (idempotent; returns
    the wrapped-root count).  Rides the sanitizer's discovery so the
    ledger's roster and the retrace hook's can never diverge, and
    subscribes to ``register_jit_root`` so runtime-created roots join
    the roster as they appear."""
    from kubernetes_tpu.analysis import sanitizer

    with _install_mu:
        for name, fn in sanitizer._discover_jit_roots().items():
            _wrap_under_install_mu(name, fn)
    # subscribe OUTSIDE the lock: add_jit_root_listener synchronously
    # replays already-registered roots into _on_registered, which takes
    # _install_mu itself — holding it here would self-deadlock on the
    # first install after a mark_jit_warm()/register_jit_root()
    sanitizer.add_jit_root_listener(_on_registered)
    with _install_mu:
        return len(_wrapped)


def _candidate_modules(short: str):
    """Full module names whose basename is ``short``, from the SAME
    roster the sanitizer's discovery walks (JIT_MODULES +
    device_mirror) — no prefix guessing, so a kernel module added
    anywhere in the tree wraps the day it lands in JIT_MODULES."""
    import os as _os

    from kubernetes_tpu.analysis import JIT_MODULES

    rels = list(JIT_MODULES) + [_os.path.join("cache", "device_mirror.py")]
    for rel in rels:
        modname = "kubernetes_tpu." + rel[:-3].replace(_os.sep, ".")
        if modname.rsplit(".", 1)[-1] == short:
            yield modname


def _wrap_under_install_mu(name: str, fn) -> None:
    if name in _wrapped or isinstance(fn, _LedgerRoot):
        return
    mod_short, attr = name.rsplit(".", 1)
    import importlib

    for modname in _candidate_modules(mod_short):
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        if getattr(mod, attr, None) is fn:
            wrapper = _LedgerRoot(name, fn)
            setattr(mod, attr, wrapper)
            _wrapped[name] = (mod, attr, fn)
            return
    # not a module attribute we can rebind (runtime-created root): it
    # still appears in roster() so coverage tests see it — its dispatches
    # just can't be intercepted at the module seam
    _wrapped[name] = (None, None, fn)


def _on_registered(name: str, fn) -> None:
    with _install_mu:
        if name not in _wrapped:
            _wrapped[name] = (None, None, fn)


def uninstall() -> None:
    """Restore every wrapped module attribute (tests)."""
    with _install_mu:
        for name, (mod, attr, fn) in list(_wrapped.items()):
            if mod is not None and isinstance(
                getattr(mod, attr, None), _LedgerRoot
            ):
                setattr(mod, attr, fn)
            del _wrapped[name]


def roster() -> List[str]:
    """Every jit root the ledger knows: wrapped module-level roots plus
    the sanitizer's runtime registry — the coverage tests assert the
    sanitizer's roster is a subset of this, so a new kernel cannot land
    unobserved."""
    from kubernetes_tpu.analysis import sanitizer

    with _install_mu:
        names = set(_wrapped)
    names |= set(sanitizer._jit_roots)
    return sorted(names)


def _wrapped_fn(name: str):
    """The ORIGINAL PjitFunction for ``name`` (cost lowering must not
    recurse through the wrapper)."""
    with _install_mu:
        rec = _wrapped.get(name)
    if rec is not None:
        return rec[2]
    from kubernetes_tpu.analysis import sanitizer

    fn = sanitizer._jit_roots.get(name)
    return fn._fn if isinstance(fn, _LedgerRoot) else fn
