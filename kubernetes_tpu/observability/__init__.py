"""Observability layer: tracing, flight recording, and explain mode.

Three operator-facing surfaces the reference scheduler spreads over
component tracing, the framework's Status/Diagnosis plumbing, and debug
endpoints, rebuilt for the batched TPU hot loop (see OBSERVABILITY.md):

  * ``Tracer`` — span-based tracing with Chrome trace-event JSON export
    (Perfetto-loadable); spans cover drains, batch dispatch/harvest
    halves, the per-phase breakdown, and binding-worker chunks, carrying
    pod uids, batch ids, and (when a chaos journal is attached) the
    journal's logical time.
  * ``FlightRecorder`` — a bounded ring of per-pod lifecycle events
    (enqueue → pop → assumed/unschedulable → bound/…), queryable by uid.
  * ``explain_pod`` / ``oracle_explain`` — per-node, per-plugin rejection
    reasons harvested from the filter kernels' feasibility masks
    (ops/explain.py) and validated against the serial host oracle.
  * ``SLOEvaluator`` — the steady-state SLO tier (slo.py): streaming
    per-stage latency attribution joined from the flight recorder's
    breadcrumbs, objective/burn-rate evaluation over rolling windows,
    and breach-triggered freeze+dump of the tracer's black-box ring.
  * ``DispatchLedger`` — the device telemetry ledger (kernels.py):
    per-kernel dispatch/compile/d2h accounting over every registered
    jit root, lazy XLA cost estimates, and the execute-time regression
    sentinel wired into the SLO tier's black-box dump.
  * ``ControlPlaneMonitor`` — the control-plane pipeline tier
    (controlplane.py): per-pod causal chains across the watch path
    (api_write → watch_delivery → informer_handler → enqueue → pop →
    assumed → bind_start → bound), apiserver per-request accounting,
    and the snapshot-staleness sentinel filing through the SLO tier's
    black-box machinery.

Served over HTTP by ``server.SchedulerServer`` (the full catalogue is
the JSON index at ``/debug/``):

    /debug/trace?action=start|stop|export   (default: status)
    /debug/flightrecorder?pod=<uid|name>    (default: stats + tail)
    /debug/explain?pod=<uid|name>
    /debug/slo?action=status|trace          (default: status)
    /debug/kernels?cost=0|1                 (the per-kernel table)
    /debug/pipeline?pod=<uid|name>          (default: hop summary)
"""

from kubernetes_tpu.observability.controlplane import (
    ControlPlaneConfig,
    ControlPlaneMonitor,
)
from kubernetes_tpu.observability.flightrecorder import FlightRecorder
from kubernetes_tpu.observability.kernels import DispatchLedger
from kubernetes_tpu.observability.tracer import Tracer
from kubernetes_tpu.observability.explain import (
    DIAG_PLUGINS,
    explain_pod,
    explain_whatif,
    find_pod,
    oracle_explain,
    reason_to_plugin,
)
from kubernetes_tpu.observability.slo import (
    SLOConfig,
    SLOEvaluator,
    SLOObjective,
)

__all__ = [
    "Tracer",
    "FlightRecorder",
    "DispatchLedger",
    "ControlPlaneConfig",
    "ControlPlaneMonitor",
    "SLOConfig",
    "SLOEvaluator",
    "SLOObjective",
    "explain_pod",
    "explain_whatif",
    "find_pod",
    "oracle_explain",
    "reason_to_plugin",
    "DIAG_PLUGINS",
]
