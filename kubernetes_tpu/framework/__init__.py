"""Scheduling framework: plugin API, runtime, registry, profiles, config.

The Python mirror of pkg/scheduler/framework — same 12 extension points,
Status codes, and CycleState semantics (framework/interface.go), with one
structural change: a plugin may be *device-backed* (contributes a batched
[P, N] mask/score kernel to the fused dispatch) or *host-backed* (scalar
per-(pod, node) callbacks, used for stateful plugins like volume binding
until they grow kernels).
"""

from kubernetes_tpu.framework.interface import (  # noqa: F401
    Code,
    CycleState,
    Plugin,
    Status,
)
from kubernetes_tpu.framework.registry import Registry, default_registry  # noqa: F401
from kubernetes_tpu.framework.runtime import Framework  # noqa: F401
