"""Preemption evaluator — the PostFilter dry-run machinery.

Reimplements the reference's generic evaluator
(/root/reference/pkg/scheduler/framework/preemption/preemption.go:148-212
Preempt, :216 findCandidates, :431 pickOneNodeForPreemption) and the
DefaultPreemption victim-selection semantics
(plugins/defaultpreemption/default_preemption.go:140-229
SelectVictimsOnNode, :239 PodEligibleToPreemptOthers):

  * eligibility (preemptionPolicy=Never, terminating victim on the
    nominated node);
  * candidate discovery by dry-running victim removal per node —
    remove ALL lower-priority pods, check fit, then reprieve victims
    highest-priority-first (PDB-violating victims reprieved first);
  * lexicographic candidate selection (fewest PDB violations → lowest
    max victim priority → lowest priority sum → fewest victims →
    latest earliest start time → first);
  * preparation: evict victims (reject waiting pods, delete the rest)
    and clear lower-priority nominations on the chosen node.

The dry-run re-filter runs against the host OracleState (the golden
semantics); the batched device path narrows candidates up front via
kubernetes_tpu.ops.preemption so only plausibly-feasible nodes reach the
scalar reprieve loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Pod, PodDisruptionBudget
from kubernetes_tpu.framework.interface import Code, CycleState, Status
from kubernetes_tpu.oracle import filters as OF
from kubernetes_tpu.oracle.state import NodeState, OracleState


@dataclass
class Victims:
    """extenderv1.Victims analogue: pods ordered most-important-first."""

    pods: List[Pod] = field(default_factory=list)
    num_pdb_violations: int = 0


@dataclass
class Candidate:
    name: str
    victims: Victims


def more_important(a: Pod, b: Pod) -> bool:
    """util.MoreImportantPod: higher priority first; ties → earlier start."""
    if a.priority != b.priority:
        return a.priority > b.priority
    sa = a.start_time if a.start_time is not None else float("inf")
    sb = b.start_time if b.start_time is not None else float("inf")
    return sa < sb


def _importance_key(p: Pod):
    return (-p.priority, p.start_time if p.start_time is not None else float("inf"))


class Evaluator:
    """framework/preemption.Evaluator. The handle provides oracle_state(),
    nominator, delete_pod, list_pdbs, get_waiting_pod, activate."""

    def __init__(
        self,
        plugin_name: str,
        handle,
        percentage: int = 10,
        min_candidates: int = 100,
    ):
        self.plugin_name = plugin_name
        self.handle = handle
        self.percentage = percentage
        self.min_candidates = min_candidates
        # host-filter / prefilter-extension context for the current
        # preempt() call
        self._hf_fwk = None
        self._hf_state = None
        self._ext_fwk = None
        self._ext_state = None

    # ----- entry point ------------------------------------------------------

    def preempt(
        self,
        pod: Pod,
        potential_nodes: Optional[Sequence[str]] = None,
        shortlist: Optional[set] = None,
    ) -> Tuple[Optional[str], Status]:
        """Returns (nominated_node_name, status).  nominated "" with an
        unschedulable status means "clear any existing nomination".
        ``shortlist`` bounds the potential-node walk (device narrow)."""
        state = self.handle.oracle_state()

        ok, msg = self.pod_eligible(pod, state)
        if not ok:
            return None, Status.unschedulable(msg, plugin=self.plugin_name)

        # Resource-only fast fit: when the pod carries no spread/affinity/
        # port constraints, no existing pod's required anti-affinity can
        # match it, and no host filters apply, every _fits re-check inside
        # the reprieve loop reduces to request arithmetic — the state-wide
        # interpod/spread scans (the dry-run's dominant cost) are provably
        # no-ops.  Static node filters were already verified by
        # potential_nodes/the device narrow.
        self._fast_fit = (
            not pod.topology_spread_constraints
            and not (
                pod.affinity
                and (pod.affinity.pod_affinity or pod.affinity.pod_anti_affinity)
            )
            and not pod.host_ports()
            and not any(
                p.affinity is not None
                and p.affinity.pod_anti_affinity is not None
                and p.affinity.pod_anti_affinity.required_during_scheduling_ignored_during_execution
                for ns in state.nodes.values()
                for p in ns.pods
            )
        )

        # Host-backed Filter plugins (volumebinding class) must judge the
        # dry-run too — otherwise preemption evicts victims on nodes the
        # pod's volumes can never bind to.  PreFilter runs once here; the
        # per-node veto happens inside _fits.  Plugins with PreFilter
        # extensions (interface.go:443-520) additionally get AddPod/
        # RemovePod notifications as the dry-run mutates its working copy.
        self._hf_fwk = self._hf_state = None
        self._ext_fwk = self._ext_state = None
        fwk = getattr(self.handle, "framework_for", lambda p: None)(pod)
        if fwk is not None and (
            fwk.has_host_filters() or fwk.has_pre_filter_extensions()
        ):
            cs = CycleState()
            failures = fwk.run_pre_filter(cs, [pod])
            if failures:
                return "", Status.unschedulable(
                    "preemption is not helpful for scheduling",
                    plugin=self.plugin_name,
                )
            if fwk.has_host_filters() and fwk.active_host_filters(cs, [pod]):
                self._hf_fwk, self._hf_state = fwk, cs
            if fwk.has_pre_filter_extensions():
                self._ext_fwk, self._ext_state = fwk, cs

        if potential_nodes is None:
            potential_nodes = self.potential_nodes(pod, state, shortlist)
        if not potential_nodes:
            # Preemption can't help anywhere: clear stale nomination.
            return "", Status.unschedulable(
                "preemption is not helpful for scheduling",
                plugin=self.plugin_name,
            )

        offset, num = self.offset_and_num_candidates(len(potential_nodes))
        pdbs = self.handle.list_pdbs()
        candidates = self.dry_run(
            pod, state, list(potential_nodes)[offset:], num, pdbs
        )
        if not candidates:
            return "", Status.unschedulable(
                "no preemption victims found for incoming pod",
                plugin=self.plugin_name,
            )

        candidates, err = self._call_extenders(pod, candidates)
        if err is not None:
            return None, Status.error(err, plugin=self.plugin_name)
        if not candidates:
            return "", Status.unschedulable(
                "no preemption victims survived extender processing",
                plugin=self.plugin_name,
            )

        best = self.select_candidate(candidates)
        prom = getattr(self.handle, "prom", None)
        if prom is not None:
            prom.preemption_attempts.inc()
            prom.preemption_victims.observe(len(best.victims.pods))
        self.prepare_candidate(pod, best)
        return best.name, Status.success()

    def _call_extenders(
        self, pod: Pod, candidates: List["Candidate"]
    ) -> Tuple[List["Candidate"], Optional[str]]:
        """callExtenders (preemption.go:255): preemption-capable interested
        extenders may shrink the candidate map; non-ignorable transport
        errors abort the preemption."""
        exts = getattr(self.handle, "list_extenders", lambda: [])()
        for ext in exts:
            if not candidates:
                break
            if not ext.supports_preemption() or not ext.is_interested(pod):
                continue
            victims_map = {c.name: c.victims for c in candidates}
            try:
                victims_map = ext.process_preemption(pod, victims_map)
            except Exception as e:  # noqa: BLE001 — ExtenderError class
                if getattr(ext, "ignorable", False):
                    continue
                return [], str(e)
            candidates = [
                Candidate(name=n, victims=v) for n, v in victims_map.items()
            ]
        return candidates, None

    # ----- eligibility (default_preemption.go:239) --------------------------

    def pod_eligible(self, pod: Pod, state: OracleState) -> Tuple[bool, str]:
        if pod.preemption_policy == "Never":
            return False, "not eligible due to preemptionPolicy=Never"
        nom = pod.nominated_node_name
        if nom:
            ns = state.nodes.get(nom)
            if ns is not None:
                for p in ns.pods:
                    if p.priority < pod.priority and p.deletion_timestamp is not None:
                        return (
                            False,
                            "not eligible due to a terminating pod on the nominated node",
                        )
        return True, ""

    # ----- candidate discovery ---------------------------------------------

    def offset_and_num_candidates(self, n: int) -> Tuple[int, int]:
        """GetOffsetAndNumCandidates (default_preemption.go): candidates =
        max(n·percentage/100, minCandidates), capped at n.  Offset is 0 for
        deterministic decisions (the reference randomizes to spread load)."""
        num = max(n * self.percentage // 100, self.min_candidates)
        return 0, min(num, n)

    def potential_nodes(
        self,
        pod: Pod,
        state: OracleState,
        shortlist: Optional[set] = None,
    ) -> List[str]:
        """Nodes where removing lower-priority pods COULD make the pod
        schedulable: has victims, and passes every filter no pod removal can
        fix (NodesForStatusCode(Unschedulable), preemption.go:216-230).

        ``shortlist`` is the device narrow's superset-safe candidate set
        (ops/preemption.py via the scheduler's batched dispatch); the walk
        keeps state.nodes iteration order either way so candidate
        truncation stays deterministic."""
        out = []
        for name, ns in state.nodes.items():
            if shortlist is not None and name not in shortlist:
                continue
            if not any(p.priority < pod.priority for p in ns.pods):
                continue
            if OF.filter_node_name(pod, ns):
                continue
            if OF.filter_node_unschedulable(pod, ns):
                continue
            if OF.filter_taints(pod, ns):
                continue
            if OF.filter_node_affinity(pod, ns):
                continue
            if self._hf_fwk is not None:
                # only UnschedulableAndUnresolvable excludes a node from the
                # dry-run (NodesForStatusCode semantics) — victim removal
                # may resolve a plain Unschedulable host veto
                s = self._hf_fwk.run_host_filters(self._hf_state, pod, ns)
                if s.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE:
                    continue
            out.append(name)
        return out

    def dry_run(
        self,
        pod: Pod,
        state: OracleState,
        nodes: Sequence[str],
        num_candidates: int,
        pdbs: Sequence[PodDisruptionBudget],
    ) -> List[Candidate]:
        """DryRunPreemption (preemption.go:548): stop once enough candidates
        are found (the reference splits violating/non-violating pools; we
        collect up to num_candidates in node order — deterministic)."""
        candidates: List[Candidate] = []
        for name in nodes:
            victims = self.select_victims_on_node(pod, state, name, pdbs)
            if victims is not None:
                candidates.append(Candidate(name=name, victims=victims))
                if len(candidates) >= num_candidates:
                    break
        return candidates

    def select_victims_on_node(
        self,
        pod: Pod,
        state: OracleState,
        node_name: str,
        pdbs: Sequence[PodDisruptionBudget],
    ) -> Optional[Victims]:
        """default_preemption.go:140 SelectVictimsOnNode on a working copy of
        the node: remove all lower-priority pods, check fit, reprieve
        highest-priority-first (violating victims first)."""
        orig = state.nodes[node_name]
        work = NodeState(node=orig.node)
        potential: List[Pod] = []
        for p in orig.pods:
            if p.priority < pod.priority:
                potential.append(p)
            else:
                work.add_pod(p)
        if not potential:
            return None

        ext = self._ext_fwk
        # Per-candidate CycleState isolation (DryRunPreemption clones the
        # state per node, preemption.go:548): extension AddPod/RemovePod
        # mutations on node A must not leak into node B's evaluation.
        base_cs = self._ext_state if self._ext_state is not None else self._hf_state
        prev_hf, prev_ext = self._hf_state, self._ext_state
        if base_cs is not None:
            node_cs = base_cs.clone()
            if self._hf_state is not None:
                self._hf_state = node_cs
            if self._ext_state is not None:
                self._ext_state = node_cs
        from kubernetes_tpu.oracle.state import bump_pod_set_version

        state.nodes[node_name] = work
        bump_pod_set_version()  # dict swap bypasses NodeState mutators
        try:
            if ext is not None:
                # RemovePod extension per removed victim (preemption.go:548
                # DryRunPreemption → RunPreFilterExtensionRemovePod)
                for v in potential:
                    ext.run_pre_filter_extension_remove_pod(
                        self._ext_state, pod, v, work
                    )
            if not self._fits(pod, work, state):
                return None
            potential.sort(key=_importance_key)
            violating, non_violating = self._split_pdb_violations(potential, pdbs)
            victims: List[Pod] = []
            num_violating = 0

            def reprieve(v: Pod) -> bool:
                work.add_pod(v)
                if ext is not None:
                    ext.run_pre_filter_extension_add_pod(
                        self._ext_state, pod, v, work
                    )
                if self._fits(pod, work, state):
                    return True
                work.remove_pod(v)
                if ext is not None:
                    ext.run_pre_filter_extension_remove_pod(
                        self._ext_state, pod, v, work
                    )
                victims.append(v)
                return False

            for v in violating:
                if not reprieve(v):
                    num_violating += 1
            for v in non_violating:
                reprieve(v)
            if not victims:
                # Everyone reprieved — nothing to preempt here.
                return None
            victims.sort(key=_importance_key)
            return Victims(pods=victims, num_pdb_violations=num_violating)
        finally:
            state.nodes[node_name] = orig
            bump_pod_set_version()
            self._hf_state, self._ext_state = prev_hf, prev_ext

    def _fits(self, pod: Pod, ns: NodeState, state: OracleState) -> bool:
        """RunFilterPluginsWithNominatedPods for one node: all default
        filters, with nominated pods of >= priority on this node counted
        (runtime/framework.go:973)."""
        nominated = [
            np
            for np in self.handle.nominator.pods_for_node(ns.node.name)
            if np.priority >= pod.priority and np.uid != pod.uid
        ]
        if (
            getattr(self, "_fast_fit", False)
            and not nominated
            and self._hf_fwk is None
        ):
            return not OF.filter_node_resources(pod, ns)
        for np in nominated:
            ns.add_pod(np)
            if self._ext_fwk is not None:
                self._ext_fwk.run_pre_filter_extension_add_pod(
                    self._ext_state, pod, np, ns
                )
        try:
            if OF.filter_node_name(pod, ns):
                return False
            if OF.filter_node_unschedulable(pod, ns):
                return False
            if OF.filter_taints(pod, ns):
                return False
            if OF.filter_node_affinity(pod, ns):
                return False
            if OF.filter_node_ports(pod, ns):
                return False
            if OF.filter_node_resources(pod, ns):
                return False
            if OF.filter_interpod_affinity(pod, ns, state):
                return False
            counts = OF.spread_pair_counts(pod, state)
            if OF.filter_topology_spread(pod, ns, state, counts):
                return False
            if self._hf_fwk is not None:
                if not self._hf_fwk.run_host_filters(
                    self._hf_state, pod, ns
                ).ok:
                    return False
            return True
        finally:
            for np in nominated:
                ns.remove_pod(np)
                if self._ext_fwk is not None:
                    self._ext_fwk.run_pre_filter_extension_remove_pod(
                        self._ext_state, pod, np, ns
                    )

    def _split_pdb_violations(
        self, victims: Sequence[Pod], pdbs: Sequence[PodDisruptionBudget]
    ) -> Tuple[List[Pod], List[Pod]]:
        """filterPodsWithPDBViolation (default_preemption.go:290): EVERY
        matching PDB's budget is decremented per victim — violating victims
        consume budgets too — and a victim violates when any matched budget
        goes negative.  (status.disruptedPods dedup is not modeled.)"""
        allowed = [p.disruptions_allowed for p in pdbs]
        violating: List[Pod] = []
        non_violating: List[Pod] = []
        for v in victims:
            is_violating = False
            if v.labels:
                for i, p in enumerate(pdbs):
                    if not p.matches(v):
                        continue
                    allowed[i] -= 1
                    if allowed[i] < 0:
                        is_violating = True
            (violating if is_violating else non_violating).append(v)
        return violating, non_violating

    # ----- candidate selection (preemption.go:431) --------------------------

    def select_candidate(self, candidates: List[Candidate]) -> Candidate:
        if len(candidates) == 1:
            return candidates[0]

        def highest_priority(c: Candidate) -> int:
            return c.victims.pods[0].priority if c.victims.pods else -(2**31)

        def sum_priorities(c: Candidate) -> int:
            return sum(p.priority + 2**31 + 1 for p in c.victims.pods)

        def earliest_start(c: Candidate) -> float:
            starts = [
                p.start_time if p.start_time is not None else float("-inf")
                for p in c.victims.pods
            ]
            return min(starts) if starts else float("-inf")

        pool = candidates
        for key, reverse in (
            (lambda c: c.victims.num_pdb_violations, False),
            (highest_priority, False),
            (sum_priorities, False),
            (lambda c: len(c.victims.pods), False),
            (earliest_start, True),  # LATEST earliest start wins
        ):
            vals = [key(c) for c in pool]
            best = max(vals) if reverse else min(vals)
            pool = [c for c, v in zip(pool, vals) if v == best]
            if len(pool) == 1:
                return pool[0]
        return pool[0]

    # ----- preparation (preemption.go:349 prepareCandidate) -----------------

    def prepare_candidate(self, pod: Pod, c: Candidate) -> None:
        from kubernetes_tpu import events as ev

        recorder = getattr(self.handle, "recorder_for", lambda p: ev.NullRecorder())(
            pod
        )
        for victim in c.victims.pods:
            wp = self.handle.get_waiting_pod(victim.uid)
            if wp is not None:
                wp.reject("preempted")
            else:
                self.handle.delete_pod(victim)
            # victim eviction event (preemption.go:395 Preempted)
            recorder.eventf(
                ev.ObjectRef.for_pod(victim),
                ev.TYPE_NORMAL,
                "Preempted",
                "Preempting",
                f"Preempted by pod {pod.uid} on node {c.name}",
                related=ev.ObjectRef.for_pod(pod),
            )
        # Lower-priority pods nominated here may no longer fit: clear their
        # nominations and reactivate them.
        demoted = [
            np
            for np in self.handle.nominator.pods_for_node(c.name)
            if np.priority < pod.priority
        ]
        for np in demoted:
            np.nominated_node_name = ""
            self.handle.nominator.delete(np)
        if demoted:
            self.handle.activate(demoted)
