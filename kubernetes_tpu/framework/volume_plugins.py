"""VolumeZone, VolumeRestrictions and NodeVolumeLimits.

Host-backed volume Filter plugins (the low-volume stateful tier — they veto
device decisions through the host-filter path rather than running as
kernels).  Semantics mirror:

  * pkg/scheduler/framework/plugins/volumezone/volume_zone.go (:109
    PreFilter/Skip, :188 Filter, :57 ErrReasonConflict)
  * pkg/scheduler/framework/plugins/volumerestrictions/
    volume_restrictions.go (:164 PreFilter, :308 Filter, disk conflicts +
    ReadWriteOncePod)
  * pkg/scheduler/framework/plugins/nodevolumelimits/csi.go (:152
    PreFilter, :170 Filter, :234 ErrReasonMaxVolumeCountExceeded)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from kubernetes_tpu.api import storage as st
from kubernetes_tpu.api.types import Pod, Volume
from kubernetes_tpu.framework.interface import (
    ActionType,
    ClusterEvent,
    ClusterEventWithHint,
    CycleState,
    EnqueueExtensions,
    EventResource,
    FilterPlugin,
    PreFilterPlugin,
    QueueingHint,
    Status,
)

REASON_ZONE_CONFLICT = "node(s) had no available volume zone"
REASON_DISK_CONFLICT = "node(s) had no available disk"
REASON_RWOP_CONFLICT = (
    "node has pod using PersistentVolumeClaim with the same name and "
    "ReadWriteOncePod access mode"
)
REASON_MAX_VOLUME_COUNT = "node(s) exceed max volume count"

# Volume kinds subject to the single-attach conflict rule
# (volume_restrictions.go isVolumeConflict: GCE PD / AWS EBS / Azure / ISCSI).
_SINGLE_ATTACH_KINDS = {"gce-pd", "aws-ebs", "azure-disk", "iscsi", "rbd"}


def _zone_value_set(v: str) -> Set[str]:
    """PV zone labels may carry a __-separated set of zones
    (volumehelpers.LabelZonesToSet)."""
    return set(v.split("__"))


class VolumeZone(PreFilterPlugin, FilterPlugin, EnqueueExtensions):
    """PV topology labels vs node topology labels."""

    name = "VolumeZone"
    # for claim-less/PVC-less (fast-gated) pods pre_filter is a spec-only
    # Skip — safe for per-signature grouping (enforced: kubernetes_tpu.
    # analysis plugin-purity checks the spec path stays handle/state-free)
    pre_filter_spec_pure = True
    _STATE_KEY = "VolumeZone"

    def maybe_relevant(self, pod: Pod) -> bool:
        return bool(pod.pvc_names())

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        """Resolve each claim's PV topology once per pod (:109); Skip when
        no PV carries zone/region labels."""
        if not pod.pvc_names():
            return Status.skip()
        topologies, status = self._pv_topologies(pod)
        if status is not None:
            return status
        if not topologies:
            return Status.skip()
        state.write((self._STATE_KEY, pod.uid), topologies)
        return Status.success()

    def _pv_topologies(
        self, pod: Pod
    ) -> Tuple[List[Tuple[str, Set[str]]], Optional[Status]]:
        out: List[Tuple[str, Set[str]]] = []
        for name in pod.pvc_names():
            pvc = self.handle.pvc_cache.get(f"{pod.namespace}/{name}")
            if pvc is None:
                return [], Status.unresolvable(
                    f'persistentvolumeclaim "{name}" not found', plugin=self.name
                )
            if not pvc.volume_name:
                # unbound: WaitForFirstConsumer claims are VolumeBinding's
                # job (:151 "Skip unbound volumes"); immediate-mode unbound
                # claims can't be judged yet
                sc = self.handle.get_storage_class(pvc.storage_class_name or "")
                if sc is not None and sc.is_wait_for_first_consumer():
                    continue
                return [], Status.unresolvable(
                    f'persistentvolumeclaim "{name}" is not bound', plugin=self.name
                )
            pv = self.handle.pv_cache.get(pvc.volume_name)
            if pv is None:
                return [], Status.unresolvable(
                    f'persistentvolume "{pvc.volume_name}" not found',
                    plugin=self.name,
                )
            for key in st.VOLUME_TOPOLOGY_LABELS:
                if key in pv.labels:
                    out.append((key, _zone_value_set(pv.labels[key])))
        return out, None

    def filter(self, state: CycleState, pod: Pod, node_state) -> Status:
        topologies = state.read((self._STATE_KEY, pod.uid))
        if not topologies:
            return Status.success()
        node = node_state.node
        for key, values in topologies:
            node_val = node.labels.get(key)
            if node_val is None or node_val not in values:
                return Status.unresolvable(REASON_ZONE_CONFLICT, plugin=self.name)
        return Status.success()

    def events_to_register(self) -> List[ClusterEventWithHint]:
        return [
            ClusterEventWithHint(
                ClusterEvent(EventResource.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL)
            ),
            ClusterEventWithHint(
                ClusterEvent(EventResource.PVC, ActionType.ADD | ActionType.UPDATE)
            ),
            ClusterEventWithHint(
                ClusterEvent(EventResource.PV, ActionType.ADD | ActionType.UPDATE)
            ),
            ClusterEventWithHint(
                ClusterEvent(EventResource.STORAGE_CLASS, ActionType.ADD)
            ),
        ]


class VolumeRestrictions(PreFilterPlugin, FilterPlugin, EnqueueExtensions):
    """Single-attach disk conflicts + ReadWriteOncePod exclusivity."""

    name = "VolumeRestrictions"
    # for claim-less/PVC-less (fast-gated) pods pre_filter is a spec-only
    # Skip — safe for per-signature grouping (enforced: kubernetes_tpu.
    # analysis plugin-purity checks the spec path stays handle/state-free)
    pre_filter_spec_pure = True
    _STATE_KEY = "VolumeRestrictions"

    def maybe_relevant(self, pod: Pod) -> bool:
        return bool(pod.pvc_names()) or any(
            v.source_kind in _SINGLE_ATTACH_KINDS for v in pod.volumes
        )

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        needs_check = any(
            v.source_kind in _SINGLE_ATTACH_KINDS for v in pod.volumes
        )
        if not needs_check and not pod.pvc_names():
            # spec-only gate FIRST: a fast-gated (PVC-less, no single-attach
            # volume) pod must Skip without touching the pvc_cache — the
            # per-signature PreFilter grouping replays this verdict for
            # every pod of the signature (pre_filter_spec_pure contract)
            return Status.skip()
        rwop: Set[str] = set()
        for name in pod.pvc_names():
            pvc = self.handle.pvc_cache.get(f"{pod.namespace}/{name}")
            if pvc is None:
                return Status.unresolvable(
                    f'persistentvolumeclaim "{name}" not found', plugin=self.name
                )
            if st.RWOP in pvc.access_modes:
                rwop.add(name)
        if not needs_check and not rwop:
            return Status.skip()
        state.write((self._STATE_KEY, pod.uid), rwop)
        return Status.success()

    def _inline_conflict(self, vol: Volume, existing: Volume) -> bool:
        """isVolumeConflict: same single-attach disk id conflicts unless
        both mounts are read-only for kinds that support multi-reader
        attach (GCE PD and ISCSI/RBD, volume_restrictions.go:104-140)."""
        if vol.source_kind != existing.source_kind:
            return False
        if vol.source_id != existing.source_id or not vol.source_id:
            return False
        if (
            vol.source_kind in ("gce-pd", "iscsi", "rbd")
            and vol.read_only
            and existing.read_only
        ):
            return False
        return True

    def filter(self, state: CycleState, pod: Pod, node_state) -> Status:
        rwop = state.read((self._STATE_KEY, pod.uid)) or set()
        own_inline = [
            v for v in pod.volumes if v.source_kind in _SINGLE_ATTACH_KINDS
        ]
        for existing_pod in node_state.pods:
            for ev in existing_pod.volumes:
                for v in own_inline:
                    if self._inline_conflict(v, ev):
                        return Status.unschedulable(
                            REASON_DISK_CONFLICT, plugin=self.name
                        )
                if (
                    ev.pvc_name
                    and ev.pvc_name in rwop
                    and existing_pod.namespace == pod.namespace
                ):
                    return Status.unschedulable(
                        REASON_RWOP_CONFLICT, plugin=self.name
                    )
        return Status.success()

    def events_to_register(self) -> List[ClusterEventWithHint]:
        def pod_deleted(pod: Pod, old, new) -> QueueingHint:
            # Freeing a conflicting disk/PVC is what can unblock us.
            return QueueingHint.QUEUE if old is not None else QueueingHint.SKIP

        return [
            ClusterEventWithHint(
                ClusterEvent(EventResource.ASSIGNED_POD, ActionType.DELETE),
                pod_deleted,
            ),
            ClusterEventWithHint(
                ClusterEvent(EventResource.PVC, ActionType.ADD)
            ),
            ClusterEventWithHint(ClusterEvent(EventResource.NODE, ActionType.ADD)),
        ]


class NodeVolumeLimits(PreFilterPlugin, FilterPlugin, EnqueueExtensions):
    """CSI attachable-volume count limits per driver (nodevolumelimits/csi.go).

    In-tree single-attach kinds count against their own per-kind limit when
    the node's CSINode advertises one under the migrated driver name."""

    name = "NodeVolumeLimits"
    # for claim-less/PVC-less (fast-gated) pods pre_filter is a spec-only
    # Skip — safe for per-signature grouping (enforced: kubernetes_tpu.
    # analysis plugin-purity checks the spec path stays handle/state-free)
    pre_filter_spec_pure = True

    def maybe_relevant(self, pod: Pod) -> bool:
        return bool(pod.pvc_names()) or any(
            v.source_kind == "csi" and v.driver for v in pod.volumes
        )

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        if not pod.pvc_names() and not any(
            v.source_kind == "csi" and v.driver for v in pod.volumes
        ):
            return Status.skip()
        return Status.success()

    def _volume_driver_handles(self, pod: Pod) -> Dict[str, Set[str]]:
        """driver name → set of unique volume handles this pod attaches."""
        out: Dict[str, Set[str]] = {}
        for v in pod.volumes:
            # inline (ephemeral) CSI volumes count against the limit too
            # (csi.go:314 checkAttachableInlineVolume)
            if v.source_kind == "csi" and v.driver:
                out.setdefault(v.driver, set()).add(
                    v.source_id or f"{pod.key}/{v.name}"
                )
        for name in pod.pvc_names():
            pvc = self.handle.pvc_cache.get(f"{pod.namespace}/{name}")
            if pvc is None:
                continue
            driver, handle = self._driver_of(pvc)
            if driver:
                out.setdefault(driver, set()).add(handle)
        return out

    def _driver_of(self, pvc: st.PersistentVolumeClaim) -> Tuple[str, str]:
        """getCSIDriverInfo: bound claim → PV's driver+handle; unbound →
        storage class provisioner + synthetic handle (:355,:408)."""
        if pvc.volume_name:
            pv = self.handle.pv_cache.get(pvc.volume_name)
            if pv is not None:
                if pv.csi_driver:
                    return pv.csi_driver, pv.source_id or pv.name
                if pv.source_kind in _SINGLE_ATTACH_KINDS:
                    return pv.source_kind, pv.source_id or pv.name
                return "", ""
        sc = self.handle.get_storage_class(pvc.storage_class_name or "")
        if sc is not None and sc.provisioner != st.NO_PROVISIONER:
            return sc.provisioner, f"{sc.provisioner}-{pvc.key}"
        return "", ""

    def filter(self, state: CycleState, pod: Pod, node_state) -> Status:
        csinode = self.handle.get_csinode(node_state.node.name)
        if csinode is None:
            return Status.success()  # no limits advertised
        new_volumes = self._volume_driver_handles(pod)
        if not new_volumes:
            return Status.success()
        # current attachments per driver (unique handles across node pods)
        attached: Dict[str, Set[str]] = {}
        for p in node_state.pods:
            for drv, handles in self._volume_driver_handles(p).items():
                if drv:
                    attached.setdefault(drv, set()).update(handles)
        for drv, handles in new_volumes.items():
            d = csinode.driver(drv)
            if d is None or d.allocatable_count is None:
                continue
            current = attached.get(drv, set())
            if len(current | handles) > d.allocatable_count:
                return Status.unschedulable(
                    REASON_MAX_VOLUME_COUNT, plugin=self.name
                )
        return Status.success()

    def events_to_register(self) -> List[ClusterEventWithHint]:
        return [
            ClusterEventWithHint(
                ClusterEvent(EventResource.ASSIGNED_POD, ActionType.DELETE)
            ),
            ClusterEventWithHint(
                ClusterEvent(EventResource.CSI_NODE, ActionType.ADD | ActionType.UPDATE)
            ),
            ClusterEventWithHint(
                ClusterEvent(EventResource.PVC, ActionType.ADD)
            ),
            ClusterEventWithHint(ClusterEvent(EventResource.NODE, ActionType.ADD)),
        ]
