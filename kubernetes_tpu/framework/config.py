"""Scheduler configuration API.

KubeSchedulerConfiguration-shaped (pkg/scheduler/apis/config/types.go:37-198)
with versioned defaulting and validation: profiles, per-extension-point
plugin enable/disable, MultiPoint expansion
(apis/config/v1/default_plugins.go:30-52, runtime/framework.go:511), plugin
args, extenders, and the scheduler-wide knobs (parallelism,
percentageOfNodesToScore, backoff bounds).  Loadable from YAML.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

EXTENSION_POINTS = (
    "preEnqueue",
    "queueSort",
    "preFilter",
    "filter",
    "postFilter",
    "preScore",
    "score",
    "reserve",
    "permit",
    "preBind",
    "bind",
    "postBind",
)

# Default MultiPoint plugin list with score weights
# (apis/config/v1/default_plugins.go:30-52).
DEFAULT_MULTI_POINT: List[Tuple[str, int]] = [
    ("SchedulingGates", 0),
    ("PrioritySort", 0),
    ("NodeUnschedulable", 0),
    ("NodeName", 0),
    ("TaintToleration", 3),
    ("NodeAffinity", 2),
    ("NodePorts", 0),
    ("NodeResourcesFit", 1),
    ("VolumeRestrictions", 0),
    ("NodeVolumeLimits", 0),
    ("VolumeBinding", 0),
    ("VolumeZone", 0),
    ("PodTopologySpread", 2),
    ("InterPodAffinity", 2),
    ("DefaultPreemption", 0),
    ("NodeResourcesBalancedAllocation", 1),
    ("ImageLocality", 1),
    ("DefaultBinder", 0),
]

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# Scheduler-relevant feature gates and their reference defaults
# (pkg/features/kube_features.go @ v1.31).
DEFAULT_FEATURE_GATES: List[Tuple[str, bool]] = [
    ("DynamicResourceAllocation", False),  # alpha
    ("SchedulerQueueingHints", True),
    ("VolumeCapacityPriority", False),  # alpha
]


@dataclass
class PluginRef:
    name: str
    weight: int = 0


@dataclass
class PluginSet:
    enabled: List[PluginRef] = field(default_factory=list)
    disabled: List[PluginRef] = field(default_factory=list)


@dataclass
class Plugins:
    """Per-extension-point sets + multiPoint (apis/config/types.go)."""

    multi_point: PluginSet = field(default_factory=PluginSet)
    pre_enqueue: PluginSet = field(default_factory=PluginSet)
    queue_sort: PluginSet = field(default_factory=PluginSet)
    pre_filter: PluginSet = field(default_factory=PluginSet)
    filter: PluginSet = field(default_factory=PluginSet)
    post_filter: PluginSet = field(default_factory=PluginSet)
    pre_score: PluginSet = field(default_factory=PluginSet)
    score: PluginSet = field(default_factory=PluginSet)
    reserve: PluginSet = field(default_factory=PluginSet)
    permit: PluginSet = field(default_factory=PluginSet)
    pre_bind: PluginSet = field(default_factory=PluginSet)
    bind: PluginSet = field(default_factory=PluginSet)
    post_bind: PluginSet = field(default_factory=PluginSet)

    def points(self):
        """(wire name, PluginSet) pairs for every extension point —
        derived from EXTENSION_POINTS/_SNAKE so a new point automatically
        participates in validation and dump_config."""
        return [("multiPoint", self.multi_point)] + [
            (ep, getattr(self, _SNAKE[ep])) for ep in EXTENSION_POINTS
        ]


@dataclass
class Extender:
    """HTTP extender config (apis/config/types.go Extender)."""

    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    enable_https: bool = False
    http_timeout_s: float = 30.0
    node_cache_capable: bool = False
    ignorable: bool = False
    managed_resources: List[str] = field(default_factory=list)


@dataclass
class Profile:
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    plugins: Plugins = field(default_factory=Plugins)
    plugin_config: Dict[str, dict] = field(default_factory=dict)
    percentage_of_nodes_to_score: Optional[int] = None


API_VERSION = "kubescheduler.config.k8s.io/v1"
SUPPORTED_API_VERSIONS = {
    API_VERSION,
    # v1beta3 reads convert to v1; for the modeled fields the shapes match
    "kubescheduler.config.k8s.io/v1beta3",
}


@dataclass
class SchedulerConfiguration:
    """KubeSchedulerConfiguration (types.go:37)."""

    parallelism: int = 16
    profiles: List[Profile] = field(default_factory=lambda: [Profile()])
    extenders: List[Extender] = field(default_factory=list)
    percentage_of_nodes_to_score: int = 0  # 0 = adaptive
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    batch_size: int = 512  # TPU extension: gang batch width
    # TPU extension: fast-path batches EXTEND up to this many pods when the
    # queue head stays signature-eligible — per-pod host cost is flat on
    # the sig_scan path, so bigger batches amortize the device round trip.
    fast_batch_max: int = 4096
    # TPU extension: fast batches SMALLER than this with an idle pipeline
    # commit on the host greedy (zero device round trips — the interactive
    # case); larger or pipelined batches take the device sig_scan kernel.
    fast_device_min: int = 1024
    # TPU extension: speculative wave dispatch for cross-pod-constraint
    # batches (spread / inter-pod terms): one parallel (P × N) speculation
    # pass + a term-factored conflict-resolution pass replaces the gang
    # scan's per-step peer contractions (ops/wave.py; bit-identical to the
    # serial order).  Off = every such batch takes the gang scan.
    wave_dispatch: bool = True
    # TPU extension: device-resident drain loop (ops/resident.py) for
    # signature-gated runs — usage state stays in HBM across runs via
    # donated buffers and whole runs place through a multi-round
    # speculation/admission fixed point, one d2h readback of packed
    # placements per run (bit-identical to the serial greedy; see
    # RESIDENT.md).  Off = large fast batches take the sig_scan kernel.
    resident_drain: bool = True
    # resident RUN width: fast batches extend up to this many pods when
    # the resident path is engaged (supersedes fast_batch_max there) —
    # bigger runs amortize the per-run host round trip.
    resident_run_max: int = 16384
    # speculation window per fixed-point round (clamped to the node
    # bucket): bounds the agreement prefix one round can admit.
    resident_window: int = 2048
    # finish unresolved run tails IN-KERNEL with the serial sig_scan
    # replay (fully device-resident; right when serial device steps are
    # cheap — accelerator backends).  Off = tails come back UNRESOLVED
    # and the host committer finishes them (right when host heaps beat
    # serial device steps — CPU backends).
    resident_serial_tail: bool = False
    # TPU extension: epoch-guarded crash consistency for the resident/
    # carry HBM state (ISSUE 15) — every device-path fast batch rides a
    # tiny usage_checksum dispatch, validated against the host-tracked
    # exact sum BEFORE the round's commits touch the committer; a
    # mismatch (dispatch died mid-round, clobbered donation) resyncs the
    # lineage from the host committer instead of committing torn usage
    # rows.  Off = no checksum dispatch (the epoch counter alone still
    # guards cross-dispatch staleness).
    resident_epoch_guard: bool = True
    # TPU extension: the workloads tier (ops/coscheduling.py) — gang/
    # coscheduling all-or-nothing admission + batched DRA claim allocation
    # + volume-topology kernel masks ride one fused dispatch with
    # device-side gang rollback (see WORKLOADS.md).  Off = gang pods
    # schedule individually (no quorum semantics) and DRA/volume pods fall
    # back to the serial one-pod host-plugin path — decision-identical for
    # DRA/volume (kill-switch identity, tests/test_coscheduling.py).
    gang_dispatch: bool = True
    # TPU extension: the counterfactual planner tier (ops/counterfactual.py,
    # kubernetes_tpu/planner/) — /debug/plan what-ifs ride one batched
    # [K, P, N] kernel dispatch.  Off = the same fork specs replay through
    # the serial forked-snapshot oracle (oracle/planner.py) — decision-
    # identical (kill-switch identity, tests/test_planner.py).
    planner_kernel: bool = True
    # TPU extension: the device telemetry ledger (observability/
    # kernels.py) — per-kernel dispatch/compile/d2h accounting over every
    # registered jit root, served at /debug/kernels and /metrics, with
    # the execute-time regression sentinel wired into the SLO tier's
    # black-box dump.  Off = the root wrappers reduce to one global read
    # + branch per dispatch and nothing records (decision-identical
    # either way: the ledger only observes).
    kernel_ledger: bool = True
    # TPU extension: mesh-partitioned dispatch (parallel/mesh.py,
    # MULTICHIP.md) — the unified admission engine's inputs are placed on
    # the ('pods', 'nodes') device mesh, so every hot kernel (wave /
    # workloads / resident / counterfactual) runs SPMD-partitioned: pod
    # batches shard the pods axis (zero-collective speculation), node-major
    # snapshot tensors shard the nodes axis (per-term carries reduce
    # across shards; GSPMD inserts the psum/all-gather at the conflict
    # compare and final argmax).  None = AUTO: on whenever the backend
    # exposes more than one device.  Decisions are bit-identical in every
    # mode (multichip_vs_singlechip paritycheck, tests/test_multichip.py).
    mesh_dispatch: Optional[bool] = None
    # pods axis of the mesh (devices / pods_axis = nodes axis).  None =
    # make_mesh default: all devices on the pods axis — the layout with
    # zero collectives in the hot path (right for small clusters / big
    # batches); 1 puts every device on the nodes axis (right for huge
    # clusters).
    mesh_pods_axis: Optional[int] = None
    # Bit-compat knobs (SURVEY §7 "decision-identical tie-breaking"):
    # full-width evaluation is the TPU-native default; these opt into the
    # reference's sampling + randomized-tie semantics.
    #   reference_sampling_compat: apply numFeasibleNodesToFind's adaptive
    #     formula even when percentageOfNodesToScore is 0 (the reference
    #     always samples; our default is full width).
    #   tie_break_seed: seeded uniform tie-break among max-score nodes (the
    #     deterministic analogue of selectHost's reservoir sampling); None
    #     keeps first-max-in-node-order.
    reference_sampling_compat: bool = False
    tie_break_seed: Optional[int] = None
    # component-base/featuregate tier (pkg/features/kube_features.go) —
    # only the scheduler-relevant gates exist
    feature_gates: Dict[str, bool] = field(
        default_factory=lambda: dict(DEFAULT_FEATURE_GATES)
    )

    def validate(self) -> None:
        """The apis/config/validation table, scaled to this build's
        surface (validation.go ValidateKubeSchedulerConfiguration)."""
        names = [p.scheduler_name for p in self.profiles]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate profile names: {names}")
        if not self.profiles:
            raise ValueError("at least one profile required")
        if self.parallelism <= 0:
            raise ValueError("parallelism must be positive")
        if self.pod_initial_backoff_seconds <= 0:
            raise ValueError("podInitialBackoffSeconds must be positive")
        if self.pod_max_backoff_seconds < self.pod_initial_backoff_seconds:
            raise ValueError("podMaxBackoffSeconds < podInitialBackoffSeconds")
        if not 0 <= self.percentage_of_nodes_to_score <= 100:
            raise ValueError("percentageOfNodesToScore must be in [0, 100]")
        if self.batch_size <= 0:
            raise ValueError("batchSize must be positive")
        if self.mesh_pods_axis is not None and self.mesh_pods_axis <= 0:
            raise ValueError("meshPodsAxis must be positive")
        for p in self.profiles:
            if not p.scheduler_name:
                raise ValueError("profile schedulerName must be non-empty")
            if p.percentage_of_nodes_to_score is not None and not (
                0 <= p.percentage_of_nodes_to_score <= 100
            ):
                raise ValueError(
                    "profile percentageOfNodesToScore must be in [0, 100]"
                )
            for point_name, plugin_set in p.plugins.points():
                enabled = [r.name for r in plugin_set.enabled]
                if len(set(enabled)) != len(enabled):
                    raise ValueError(
                        f"duplicate plugin in {point_name} enabled list: "
                        f"{enabled}"
                    )
        binders = [e for e in self.extenders if e.bind_verb]
        if len(binders) > 1:
            raise ValueError("only one extender may implement bind")
        for e in self.extenders:
            if not e.url_prefix:
                raise ValueError("extender urlPrefix is required")
            if not 0 < e.weight:
                raise ValueError("extender weight must be positive")
            if e.ignorable and e.bind_verb:
                raise ValueError("a binding extender cannot be ignorable")


# ---------------------------------------------------------------------------
# Defaulting + MultiPoint expansion (runtime/framework.go:511 expandMultiPoint)
# ---------------------------------------------------------------------------

# Which extension points each in-tree plugin actually implements.
PLUGIN_POINTS: Dict[str, Tuple[str, ...]] = {
    "SchedulingGates": ("preEnqueue",),
    "PrioritySort": ("queueSort",),
    "NodeUnschedulable": ("filter",),
    "NodeName": ("filter",),
    "TaintToleration": ("filter", "preScore", "score"),
    "NodeAffinity": ("preFilter", "filter", "preScore", "score"),
    "NodePorts": ("preFilter", "filter"),
    "NodeResourcesFit": ("preFilter", "filter", "preScore", "score"),
    "VolumeRestrictions": ("preFilter", "filter"),
    "NodeVolumeLimits": ("preFilter", "filter"),
    "VolumeBinding": ("preFilter", "filter", "reserve", "preBind", "score"),
    "VolumeZone": ("preFilter", "filter"),
    "PodTopologySpread": ("preFilter", "filter", "preScore", "score"),
    "InterPodAffinity": ("preFilter", "filter", "preScore", "score"),
    "DefaultPreemption": ("postFilter",),
    "DynamicResources": ("preEnqueue", "preFilter", "filter", "reserve", "preBind"),
    "NodeResourcesBalancedAllocation": ("preScore", "score"),
    "ImageLocality": ("score",),
    "DefaultBinder": ("bind",),
}

_SNAKE = {
    "preEnqueue": "pre_enqueue",
    "queueSort": "queue_sort",
    "preFilter": "pre_filter",
    "filter": "filter",
    "postFilter": "post_filter",
    "preScore": "pre_score",
    "score": "score",
    "reserve": "reserve",
    "permit": "permit",
    "preBind": "pre_bind",
    "bind": "bind",
    "postBind": "post_bind",
}


def default_plugins(feature_gates: Optional[Dict[str, bool]] = None) -> Plugins:
    """Default plugin set, adjusted for feature gates
    (apis/config/v1/default_plugins.go getDefaultPlugins/applyFeatureGates)."""
    p = Plugins()
    refs = [PluginRef(n, w) for n, w in DEFAULT_MULTI_POINT]
    if (feature_gates or {}).get("DynamicResourceAllocation"):
        binder = next(i for i, r in enumerate(refs) if r.name == "DefaultBinder")
        refs.insert(binder, PluginRef("DynamicResources", 0))
    p.multi_point.enabled = refs
    return p


def _merge_plugin_set(default: PluginSet, custom: PluginSet) -> PluginSet:
    """mergePluginSet (apis/config/v1/default_plugins.go:107): defaults
    minus custom-disabled, with same-named custom entries replacing the
    default IN PLACE (order preserved), then remaining custom appended."""
    disabled_names = {d.name for d in custom.disabled}
    custom_by_name = {e.name: (i, e) for i, e in enumerate(custom.enabled)}
    replaced = set()
    enabled: List[PluginRef] = []
    if "*" not in disabled_names:
        for d in default.enabled:
            if d.name in disabled_names:
                continue
            hit = custom_by_name.get(d.name)
            if hit is not None:
                i, e = hit
                enabled.append(e)
                replaced.add(i)
            else:
                enabled.append(d)
    enabled.extend(
        e for i, e in enumerate(custom.enabled) if i not in replaced
    )
    return PluginSet(enabled=enabled, disabled=list(custom.disabled))


def expand_profile(
    profile: Profile, feature_gates: Optional[Dict[str, bool]] = None
) -> Dict[str, List[PluginRef]]:
    """MultiPoint expansion + per-point enable/disable merge.

    Returns extensionPoint → ordered [PluginRef] with effective weights.
    Rules (runtime/framework.go:511-600): per-point Enabled appends after
    multipoint expansion; per-point Disabled removes multipoint entries for
    that point only; '*' disables all; per-point weight overrides multipoint
    weight.
    """
    plugins = profile.plugins
    # Defaults are merged before expansion (apis/config/v1
    # default_plugins.go:107 mergePluginSet): user-enabled plugins override
    # same-named defaults in place or append; disabled names (or '*') drop
    # defaults.
    mp = _merge_plugin_set(
        default_plugins(feature_gates).multi_point, plugins.multi_point
    )
    mp_disabled = {d.name for d in mp.disabled}
    mp_all_disabled = "*" in mp_disabled

    out: Dict[str, List[PluginRef]] = {ep: [] for ep in EXTENSION_POINTS}
    for ep in EXTENSION_POINTS:
        point_set: PluginSet = getattr(plugins, _SNAKE[ep])
        point_disabled = {d.name for d in point_set.disabled}
        point_all_disabled = "*" in point_disabled
        seen = set()

        if not mp_all_disabled:
            for ref in mp.enabled:
                if ref.name in mp_disabled or ref.name in seen:
                    continue
                if ep not in PLUGIN_POINTS.get(ref.name, ()):
                    continue
                if point_all_disabled or ref.name in point_disabled:
                    continue
                # per-point weight overrides multipoint weight
                override = next(
                    (e for e in point_set.enabled if e.name == ref.name), None
                )
                weight = override.weight if override and override.weight else ref.weight
                out[ep].append(PluginRef(ref.name, weight or _default_weight(ref.name, ep)))
                seen.add(ref.name)

        for ref in point_set.enabled:
            if ref.name in seen:
                continue
            out[ep].append(PluginRef(ref.name, ref.weight or _default_weight(ref.name, ep)))
            seen.add(ref.name)
    return out


def _default_weight(name: str, ep: str) -> int:
    if ep != "score":
        return 0
    return dict(DEFAULT_MULTI_POINT).get(name, 1) or 1


# ---------------------------------------------------------------------------
# YAML loading (cmd/kube-scheduler/app/options/configfile.go analogue)
# ---------------------------------------------------------------------------


def _plugin_set_from(d: Optional[dict]) -> PluginSet:
    d = d or {}
    return PluginSet(
        enabled=[
            PluginRef(e["name"], e.get("weight", 0)) for e in d.get("enabled", [])
        ],
        disabled=[
            PluginRef(e["name"], e.get("weight", 0)) for e in d.get("disabled", [])
        ],
    )


def _plugins_from(d: Optional[dict]) -> Plugins:
    d = d or {}
    p = Plugins()
    p.multi_point = _plugin_set_from(d.get("multiPoint"))
    for ep in EXTENSION_POINTS:
        setattr(p, _SNAKE[ep], _plugin_set_from(d.get(ep)))
    return p


def load_config(source) -> SchedulerConfiguration:
    """Load from a YAML string / path / dict."""
    from kubernetes_tpu.util.yamlsource import load_yaml_source

    d = load_yaml_source(source)
    kind = d.get("kind", "KubeSchedulerConfiguration")
    if kind != "KubeSchedulerConfiguration":
        raise ValueError(f"unexpected kind {kind!r}")
    # Versioned-kind tier (apis/config/scheme: v1 is served; v1beta3
    # converts on read — its wire shape for the fields this build models
    # is identical, so conversion is the identity here; unknown versions
    # fail loudly instead of half-applying).
    api_version = d.get("apiVersion", API_VERSION)
    if api_version not in SUPPORTED_API_VERSIONS:
        raise ValueError(
            f"unsupported apiVersion {api_version!r} "
            f"(supported: {sorted(SUPPORTED_API_VERSIONS)})"
        )

    profiles = []
    for pd in d.get("profiles", [{}]):
        plugin_config = {
            e["name"]: e.get("args", {}) for e in pd.get("pluginConfig", [])
        }
        profiles.append(
            Profile(
                scheduler_name=pd.get("schedulerName", DEFAULT_SCHEDULER_NAME),
                plugins=_plugins_from(pd.get("plugins")),
                plugin_config=plugin_config,
                percentage_of_nodes_to_score=pd.get("percentageOfNodesToScore"),
            )
        )
    extenders = [
        Extender(
            url_prefix=e.get("urlPrefix", ""),
            filter_verb=e.get("filterVerb", ""),
            prioritize_verb=e.get("prioritizeVerb", ""),
            bind_verb=e.get("bindVerb", ""),
            preempt_verb=e.get("preemptVerb", ""),
            weight=e.get("weight", 1),
            enable_https=e.get("enableHTTPS", False),
            http_timeout_s=e.get("httpTimeout", 30.0),
            node_cache_capable=e.get("nodeCacheCapable", False),
            ignorable=e.get("ignorable", False),
            managed_resources=[
                r.get("name") for r in e.get("managedResources", [])
            ],
        )
        for e in d.get("extenders", [])
    ]
    cfg = SchedulerConfiguration(
        parallelism=d.get("parallelism", 16),
        profiles=profiles or [Profile()],
        extenders=extenders,
        percentage_of_nodes_to_score=d.get("percentageOfNodesToScore", 0),
        pod_initial_backoff_seconds=d.get("podInitialBackoffSeconds", 1.0),
        pod_max_backoff_seconds=d.get("podMaxBackoffSeconds", 10.0),
        batch_size=d.get("batchSize", 512),
        fast_batch_max=d.get("fastBatchMax", 4096),
        fast_device_min=d.get("fastDeviceMin", 1024),
        wave_dispatch=d.get("waveDispatch", True),
        resident_drain=d.get("residentDrain", True),
        resident_run_max=d.get("residentRunMax", 16384),
        resident_window=d.get("residentWindow", 2048),
        resident_serial_tail=d.get("residentSerialTail", False),
        resident_epoch_guard=d.get("residentEpochGuard", True),
        gang_dispatch=d.get("gangDispatch", True),
        planner_kernel=d.get("plannerKernel", True),
        kernel_ledger=d.get("kernelLedger", True),
        mesh_dispatch=d.get("meshDispatch"),
        mesh_pods_axis=d.get("meshPodsAxis"),
        reference_sampling_compat=d.get("referenceSamplingCompat", False),
        tie_break_seed=d.get("tieBreakSeed"),
    )
    if "featureGates" in d:
        cfg.feature_gates = dict(DEFAULT_FEATURE_GATES)
        cfg.feature_gates.update(d["featureGates"])
    cfg.validate()
    return cfg


def dump_config(cfg: SchedulerConfiguration) -> dict:
    """Serialize back to the v1 wire shape — load_config(dump_config(c))
    round-trips (the write half of the conversion tier)."""

    def plugin_set(ps: PluginSet):
        out = {}
        if ps.enabled:
            out["enabled"] = [
                {"name": r.name, **({"weight": r.weight} if r.weight else {})}
                for r in ps.enabled
            ]
        if ps.disabled:
            out["disabled"] = [{"name": r.name} for r in ps.disabled]
        return out

    profiles = []
    for p in cfg.profiles:
        pd = {"schedulerName": p.scheduler_name}
        plugins = {
            wire: plugin_set(ps)
            for wire, ps in p.plugins.points()
            if ps.enabled or ps.disabled
        }
        if plugins:
            pd["plugins"] = plugins
        if p.plugin_config:
            pd["pluginConfig"] = [
                {"name": name, "args": args}
                for name, args in p.plugin_config.items()
            ]
        if p.percentage_of_nodes_to_score is not None:
            pd["percentageOfNodesToScore"] = p.percentage_of_nodes_to_score
        profiles.append(pd)
    out = {
        "apiVersion": API_VERSION,
        "kind": "KubeSchedulerConfiguration",
        "parallelism": cfg.parallelism,
        "percentageOfNodesToScore": cfg.percentage_of_nodes_to_score,
        "podInitialBackoffSeconds": cfg.pod_initial_backoff_seconds,
        "podMaxBackoffSeconds": cfg.pod_max_backoff_seconds,
        "batchSize": cfg.batch_size,
        "fastBatchMax": cfg.fast_batch_max,
        "fastDeviceMin": cfg.fast_device_min,
        "waveDispatch": cfg.wave_dispatch,
        "residentDrain": cfg.resident_drain,
        "residentRunMax": cfg.resident_run_max,
        "residentWindow": cfg.resident_window,
        "residentSerialTail": cfg.resident_serial_tail,
        "residentEpochGuard": cfg.resident_epoch_guard,
        "gangDispatch": cfg.gang_dispatch,
        "plannerKernel": cfg.planner_kernel,
        "kernelLedger": cfg.kernel_ledger,
        "meshDispatch": cfg.mesh_dispatch,
        "meshPodsAxis": cfg.mesh_pods_axis,
        "referenceSamplingCompat": cfg.reference_sampling_compat,
        "tieBreakSeed": cfg.tie_break_seed,
        "featureGates": dict(cfg.feature_gates),
        "profiles": profiles,
    }
    if cfg.extenders:
        out["extenders"] = [
            {
                "urlPrefix": e.url_prefix,
                "filterVerb": e.filter_verb,
                "prioritizeVerb": e.prioritize_verb,
                "bindVerb": e.bind_verb,
                "preemptVerb": e.preempt_verb,
                "weight": e.weight,
                "enableHTTPS": e.enable_https,
                "httpTimeout": e.http_timeout_s,
                "nodeCacheCapable": e.node_cache_capable,
                "ignorable": e.ignorable,
                "managedResources": [
                    {"name": n} for n in e.managed_resources
                ],
            }
            for e in cfg.extenders
        ]
    return out
