"""In-tree plugins as framework plugin classes.

Each default plugin (SURVEY.md §2.3) exists here with:
  * its extension points and EventsToRegister (queueing hints),
  * a scalar host fallback delegating to the oracle (golden semantics),
  * for device-backed plugins, the name of the fused-kernel component it
    enables (the actual math lives in kubernetes_tpu.ops and runs as one
    dispatch — plugins toggle and weight it, mirroring how the reference's
    profile config enables plugins without changing their code).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.framework.interface import (
    ActionType,
    BindPlugin,
    ClusterEvent,
    ClusterEventWithHint,
    Code,
    CycleState,
    EnqueueExtensions,
    EventResource,
    FilterPlugin,
    Plugin,
    PostFilterPlugin,
    PreEnqueuePlugin,
    PreFilterPlugin,
    QueueingHint,
    QueueSortPlugin,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.framework.preemption import Evaluator
from kubernetes_tpu.oracle import filters as OF
from kubernetes_tpu.oracle import scores as OS


class DevicePluginMixin:
    """Marks a plugin whose Filter/Score runs inside the fused device
    dispatch.  ``kernel`` is the component name the ops layer recognizes."""

    kernel: str = ""


# ---------------------------------------------------------------------------
# QueueSort / PreEnqueue / Bind
# ---------------------------------------------------------------------------


class PrioritySort(QueueSortPlugin):
    """queuesort/priority_sort.go:43 — priority desc, then enqueue time."""

    name = "PrioritySort"

    def less(self, a, b) -> bool:
        pa, pb = a.pod.priority, b.pod.priority
        if pa != pb:
            return pa > pb
        return a.timestamp < b.timestamp

    def sort_key(self, qp):
        """Optional QueueSort protocol: a totally-ordered tuple consistent
        with less() — lets the activeQ heap compare at C speed instead of
        going through a Python comparator per sift step."""
        return (-qp.pod.priority, qp.timestamp)


class SchedulingGates(PreEnqueuePlugin, EnqueueExtensions):
    """schedulinggates/scheduling_gates.go:48 — gated pods never enqueue."""

    name = "SchedulingGates"

    def pre_enqueue(self, pod: Pod) -> Status:
        if pod.scheduling_gates:
            return Status.unresolvable(
                f"waiting for scheduling gates: {list(pod.scheduling_gates)}",
                plugin=self.name,
            )
        return Status.success()

    def events_to_register(self):
        def hint(pod: Pod, old, new) -> QueueingHint:
            # Pod update removing the last gate makes it schedulable.
            if isinstance(new, Pod) and new.uid == pod.uid and not new.scheduling_gates:
                return QueueingHint.QUEUE
            return QueueingHint.SKIP

        return [
            ClusterEventWithHint(
                ClusterEvent(
                    EventResource.UNSCHEDULED_POD,
                    ActionType.UPDATE_POD_SCHEDULING_GATES,
                ),
                hint,
            )
        ]


class DefaultBinder(BindPlugin):
    """defaultbinder/default_binder.go — POST the binding via the handle's
    binding sink (the API-write boundary)."""

    name = "DefaultBinder"

    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        try:
            self.handle.bind(pod, node_name)
        except Exception as e:  # noqa: BLE001 — surfaced as Status
            return Status.error(str(e), plugin=self.name)
        return Status.success()


# ---------------------------------------------------------------------------
# Device-backed Filter/Score plugins (fused kernels)
# ---------------------------------------------------------------------------


def _node_event(action: ActionType) -> ClusterEventWithHint:
    return ClusterEventWithHint(ClusterEvent(EventResource.NODE, action))


class NodeName(DevicePluginMixin, FilterPlugin, EnqueueExtensions):
    name = "NodeName"
    kernel = "NodeName"

    def filter(self, state, pod, ns) -> Status:
        r = OF.filter_node_name(pod, ns)
        return Status.unresolvable(r, plugin=self.name) if r else Status.success()

    def events_to_register(self):
        return [_node_event(ActionType.ADD)]


class NodeUnschedulable(DevicePluginMixin, FilterPlugin, EnqueueExtensions):
    name = "NodeUnschedulable"
    kernel = "NodeUnschedulable"

    def filter(self, state, pod, ns) -> Status:
        r = OF.filter_node_unschedulable(pod, ns)
        return Status.unresolvable(r, plugin=self.name) if r else Status.success()

    def events_to_register(self):
        return [_node_event(ActionType.ADD | ActionType.UPDATE_NODE_TAINT)]


class TaintToleration(DevicePluginMixin, FilterPlugin, ScorePlugin, EnqueueExtensions):
    name = "TaintToleration"
    kernel = "TaintToleration"

    def filter(self, state, pod, ns) -> Status:
        r = OF.filter_taints(pod, ns)
        return Status.unresolvable(r, plugin=self.name) if r else Status.success()

    def score(self, state, pod, ns) -> int:
        return OS.score_taint_toleration(pod, ns)

    def normalize(self, state, pod, scores):
        return OS.normalize_taint_toleration(scores)

    def events_to_register(self):
        return [_node_event(ActionType.ADD | ActionType.UPDATE_NODE_TAINT)]


class NodeAffinity(
    DevicePluginMixin, PreFilterPlugin, FilterPlugin, ScorePlugin, EnqueueExtensions
):
    name = "NodeAffinity"
    kernel = "NodeAffinity"
    # spec-only pre_filter: safe for per-signature grouping on the fast path
    # (enforced: kubernetes_tpu.analysis plugin-purity checks the spec path)
    pre_filter_spec_pure = True

    def pre_filter(self, state, pod) -> Status:
        aff = pod.affinity
        required = (
            aff.node_affinity.required_during_scheduling_ignored_during_execution
            if aff and aff.node_affinity
            else None
        )
        if required is None and not pod.node_selector:
            return Status.skip()  # node_affinity.go:128
        return Status.success()

    def pre_filter_result(self, pod):
        """metadata.name In-term narrowing (node_affinity.go:140-171):
        terms are ORed; a term without a node-name matchField makes every
        node eligible; In-requirements within a term intersect."""
        aff = pod.affinity
        required = (
            aff.node_affinity.required_during_scheduling_ignored_during_execution
            if aff and aff.node_affinity
            else None
        )
        if required is None or not required.node_selector_terms:
            return None
        node_names = None
        for t in required.node_selector_terms:
            term_names = None
            for r in t.match_fields:
                if r.key == "metadata.name" and r.operator == "In":
                    s = set(r.values)
                    term_names = s if term_names is None else (term_names & s)
            if term_names is None:
                return None  # ORed terms: this one admits every node
            node_names = (
                term_names if node_names is None else (node_names | term_names)
            )
        return node_names

    def filter(self, state, pod, ns) -> Status:
        r = OF.filter_node_affinity(pod, ns)
        return Status.unschedulable(r, plugin=self.name) if r else Status.success()

    def score(self, state, pod, ns) -> int:
        return OS.score_node_affinity(pod, ns)

    def normalize(self, state, pod, scores):
        return OS.normalize_node_affinity(scores)

    def events_to_register(self):
        return [_node_event(ActionType.ADD | ActionType.UPDATE_NODE_LABEL)]


class NodePorts(DevicePluginMixin, FilterPlugin, EnqueueExtensions):
    name = "NodePorts"
    kernel = "NodePorts"

    def filter(self, state, pod, ns) -> Status:
        r = OF.filter_node_ports(pod, ns)
        return Status.unschedulable(r, plugin=self.name) if r else Status.success()

    def events_to_register(self):
        def pod_deleted_hint(pod: Pod, old, new) -> QueueingHint:
            # A deleted pod frees host ports only if it used one we want.
            if isinstance(old, Pod):
                used = {(p.protocol, p.host_port) for p in old.host_ports()}
                want = {(p.protocol, p.host_port) for p in pod.host_ports()}
                return (
                    QueueingHint.QUEUE if used & want else QueueingHint.SKIP
                )
            return QueueingHint.QUEUE

        return [
            ClusterEventWithHint(
                ClusterEvent(EventResource.ASSIGNED_POD, ActionType.DELETE),
                pod_deleted_hint,
            ),
            _node_event(ActionType.ADD),
        ]


class NodeResourcesFit(DevicePluginMixin, FilterPlugin, ScorePlugin, EnqueueExtensions):
    """noderesources/fit.go with all three scoring strategies
    (LeastAllocated default, MostAllocated, RequestedToCapacityRatio —
    requested_to_capacity_ratio.go:32).  Strategy parameters flow into the
    device dispatch as static args (Framework.fit_strategy); resource
    specs beyond cpu/memory flip scoring to the exact host path
    (device_score=False) instead of diverging on device."""

    name = "NodeResourcesFit"
    kernel = "NodeResourcesFit"

    STRATEGY_IDS = {
        "LeastAllocated": 0,
        "MostAllocated": 1,
        "RequestedToCapacityRatio": 2,
    }
    # config.MaxCustomPriorityScore: shape scores are 0-10, scaled to 0-100
    MAX_CUSTOM_PRIORITY_SCORE = 10

    def __init__(self, args=None, handle=None):
        super().__init__(args, handle)
        ss = self.args.get("scoringStrategy", {}) or {}
        self.strategy = ss.get("type", "LeastAllocated")
        if self.strategy not in self.STRATEGY_IDS:
            raise ValueError(f"unknown scoringStrategy {self.strategy!r}")
        res = ss.get("resources") or [
            {"name": "cpu", "weight": 1},
            {"name": "memory", "weight": 1},
        ]
        w = {r["name"]: int(r.get("weight", 1)) for r in res}
        self.fit_res_weights = (w.get("cpu", 0), w.get("memory", 0))
        # The device fit-score kernel computes over the cpu/memory lanes;
        # strategies weighing ephemeral-storage or extended resources
        # (resource_allocation.go:37-115 accepts any resource) score
        # host-side instead: device_score=False routes affected pods
        # through the exact one-pod oracle cycle (fit_scorer), matching
        # the reference bit for bit.  Filtering handles every lane on
        # device either way.
        self.device_score = all(name in ("cpu", "memory") for name in w)
        scale = 100 // self.MAX_CUSTOM_PRIORITY_SCORE
        raw_shape = ss.get("requestedToCapacityRatio", {}).get(
            "shape",
            [{"utilization": 0, "score": 0}, {"utilization": 100, "score": 10}],
        )
        # apis/config/validation: utilization strictly increasing in
        # [0, 100], score in [0, MaxCustomPriorityScore]
        prev = -1
        for p in raw_shape:
            u, s = int(p["utilization"]), int(p["score"])
            if not 0 <= u <= 100:
                raise ValueError(f"shape utilization {u} outside [0, 100]")
            if u <= prev:
                raise ValueError("shape utilization must be strictly increasing")
            if not 0 <= s <= self.MAX_CUSTOM_PRIORITY_SCORE:
                raise ValueError(
                    f"shape score {s} outside [0, {self.MAX_CUSTOM_PRIORITY_SCORE}]"
                )
            prev = u
        self.fit_shape = tuple(
            (int(p["utilization"]), int(p["score"]) * scale) for p in raw_shape
        )
        self.fit_resources = tuple(
            (name, weight) for name, weight in w.items() if weight
        )

    def filter(self, state, pod, ns) -> Status:
        rs = OF.filter_node_resources(pod, ns)
        return (
            Status.unschedulable(*rs, plugin=self.name) if rs else Status.success()
        )

    def score(self, state, pod, ns) -> int:
        if self.strategy == "MostAllocated":
            return OS.score_most_allocated(pod, ns, self.fit_resources)
        if self.strategy == "RequestedToCapacityRatio":
            return OS.score_requested_to_capacity_ratio(
                pod, ns, self.fit_shape, self.fit_resources
            )
        return OS.score_least_allocated(pod, ns, self.fit_resources)

    def events_to_register(self):
        def pod_hint(pod: Pod, old, new) -> QueueingHint:
            # Deleted/scaled-down pods free resources (fit.go:250-365).
            return QueueingHint.QUEUE

        return [
            ClusterEventWithHint(
                ClusterEvent(
                    EventResource.ASSIGNED_POD,
                    ActionType.DELETE | ActionType.UPDATE_POD_SCALE_DOWN,
                ),
                pod_hint,
            ),
            _node_event(ActionType.ADD | ActionType.UPDATE_NODE_ALLOCATABLE),
        ]


class NodeResourcesBalancedAllocation(DevicePluginMixin, ScorePlugin):
    name = "NodeResourcesBalancedAllocation"
    kernel = "NodeResourcesBalancedAllocation"

    def score(self, state, pod, ns) -> int:
        return OS.score_balanced_allocation(pod, ns)


class ImageLocality(DevicePluginMixin, ScorePlugin):
    name = "ImageLocality"
    kernel = "ImageLocality"

    def score(self, state, pod, ns) -> int:
        # needs cluster state; host fallback resolved through handle
        return OS.score_image_locality(pod, ns, self.handle.oracle_state())


class InterPodAffinity(DevicePluginMixin, FilterPlugin, ScorePlugin, EnqueueExtensions):
    name = "InterPodAffinity"
    kernel = "InterPodAffinity"

    def filter(self, state, pod, ns) -> Status:
        r = OF.filter_interpod_affinity(pod, ns, self.handle.oracle_state())
        return Status.unschedulable(r, plugin=self.name) if r else Status.success()

    def events_to_register(self):
        return [
            ClusterEventWithHint(
                ClusterEvent(
                    EventResource.ASSIGNED_POD,
                    ActionType.ADD | ActionType.DELETE | ActionType.UPDATE_POD_LABEL,
                )
            ),
            _node_event(ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
        ]


class PodTopologySpread(DevicePluginMixin, FilterPlugin, ScorePlugin, EnqueueExtensions):
    name = "PodTopologySpread"
    kernel = "PodTopologySpread"

    def filter(self, state, pod, ns) -> Status:
        r = OF.filter_topology_spread(pod, ns, self.handle.oracle_state())
        return Status.unschedulable(r, plugin=self.name) if r else Status.success()

    def events_to_register(self):
        return [
            ClusterEventWithHint(
                ClusterEvent(
                    EventResource.ASSIGNED_POD,
                    ActionType.ADD | ActionType.DELETE | ActionType.UPDATE_POD_LABEL,
                )
            ),
            _node_event(
                ActionType.ADD
                | ActionType.DELETE
                | ActionType.UPDATE_NODE_LABEL
                | ActionType.UPDATE_NODE_TAINT
            ),
        ]


class DefaultPreemption(PostFilterPlugin, EnqueueExtensions):
    """defaultpreemption/default_preemption.go — the PostFilter shim over
    the preemption evaluator (framework/preemption.py)."""

    name = "DefaultPreemption"

    def __init__(self, args: Optional[dict] = None, handle=None):
        super().__init__(args, handle)
        a = self.args or {}
        self.evaluator = Evaluator(
            self.name,
            handle,
            percentage=a.get("minCandidateNodesPercentage", 10),
            min_candidates=a.get("minCandidateNodesAbsolute", 100),
        )

    def post_filter(self, state, pod, filtered_node_status):
        # The batched path pre-computes a device-narrowed candidate
        # shortlist (ops/preemption.py via _batched_preemption_narrow);
        # without one the evaluator derives candidates itself.
        potential = state.read(("preemption_potential", pod.uid))
        if potential is not None and not potential:
            # the device mask proved no node can host the pod even after
            # removing every lower-priority victim
            return "", Status.unschedulable(
                "preemption is not helpful for scheduling", plugin=self.name
            )
        return self.evaluator.preempt(pod, shortlist=potential)

    def events_to_register(self):
        # Victim deletion is what unblocks the nominated preemptor.
        return [
            ClusterEventWithHint(
                ClusterEvent(EventResource.ASSIGNED_POD, ActionType.DELETE)
            )
        ]


from kubernetes_tpu.framework.dynamicresources import DynamicResources  # noqa: E402
from kubernetes_tpu.framework.volume_plugins import (  # noqa: E402
    NodeVolumeLimits,
    VolumeRestrictions,
    VolumeZone,
)
from kubernetes_tpu.framework.volumebinding import VolumeBinding  # noqa: E402

DEFAULT_PLUGINS = [
    PrioritySort,
    SchedulingGates,
    DefaultPreemption,
    NodeName,
    NodeUnschedulable,
    TaintToleration,
    NodeAffinity,
    NodePorts,
    NodeResourcesFit,
    NodeResourcesBalancedAllocation,
    ImageLocality,
    InterPodAffinity,
    PodTopologySpread,
    DefaultBinder,
    VolumeBinding,
    VolumeRestrictions,
    VolumeZone,
    NodeVolumeLimits,
    DynamicResources,
]
