"""Framework runtime: one profile's plugin set, wired and runnable.

Mirrors pkg/scheduler/framework/runtime/framework.go: NewFramework
instantiates the profile's plugins per extension point with score weights
(:260-396); the Run* methods execute each point.  Host-backed plugins run
as scalar loops; device-backed plugins contribute their kernel name +
weight to the fused dispatch (the runtime hands ``device_enabled()`` /
``device_weights()`` to kubernetes_tpu.ops, replacing the reference's
three-pass parallel Score machinery :1101-1207 with one jit call).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.framework import config as cfg
from kubernetes_tpu.framework.interface import (
    BindPlugin,
    ClusterEventWithHint,
    Code,
    CycleState,
    EnqueueExtensions,
    FilterPlugin,
    Plugin,
    PostBindPlugin,
    PostFilterPlugin,
    PreBindPlugin,
    PreEnqueuePlugin,
    PreFilterPlugin,
    PreScorePlugin,
    PermitPlugin,
    QueueSortPlugin,
    ReservePlugin,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.framework.plugins import DevicePluginMixin
from kubernetes_tpu.framework.registry import Registry


class WaitingPod:
    """An entry in the Permit wait map (waiting_pods_map.go).

    Event-based: WaitOnPermit blocks a BINDING worker thread (the async
    bindingCycle, schedule_one.go:263) until allow/reject/timeout — it never
    stalls the scheduling loop."""

    def __init__(self, pod: Pod, node_name: str, deadline: float):
        import threading

        self.pod = pod
        self.node_name = node_name
        self.deadline = deadline
        self.decision: Optional[Status] = None
        self._event = threading.Event()

    def allow(self) -> None:
        self.decision = Status.success()
        self._event.set()

    def reject(self, reason: str) -> None:
        self.decision = Status.unschedulable(reason)
        self._event.set()


class Framework:
    """One scheduler profile's executable plugin set (runtime/framework.go)."""

    def __init__(
        self,
        profile: cfg.Profile,
        registry: Registry,
        handle=None,
        feature_gates=None,
    ):
        self.profile_name = profile.scheduler_name
        self.percentage_of_nodes_to_score = profile.percentage_of_nodes_to_score
        self.handle = handle
        self._expanded = cfg.expand_profile(profile, feature_gates)
        self._instances: Dict[str, Plugin] = {}
        self.score_weights: Dict[str, int] = {}
        self.waiting_pods: Dict[str, WaitingPod] = {}

        def instantiate(name: str) -> Optional[Plugin]:
            if name in self._instances:
                return self._instances[name]
            factory = registry.get(name)
            if factory is None:
                return None  # plugin not available in this build
            inst = factory(profile.plugin_config.get(name, {}), handle)
            self._instances[name] = inst
            return inst

        self._by_point: Dict[str, List[Plugin]] = {}
        for ep, refs in self._expanded.items():
            plugins = []
            for ref in refs:
                inst = instantiate(ref.name)
                if inst is None:
                    continue
                plugins.append(inst)
                if ep == "score" and ref.weight:
                    self.score_weights[ref.name] = ref.weight
            self._by_point[ep] = plugins

        qs = self._by_point.get("queueSort") or []
        self.queue_sort: Optional[QueueSortPlugin] = (
            qs[0] if qs and isinstance(qs[0], QueueSortPlugin) else None
        )

    # ----- device view ----------------------------------------------------

    def device_enabled(self) -> frozenset:
        """Kernel names of enabled device-backed Filter/Score plugins."""
        names = set()
        for ep in ("filter", "score"):
            for p in self._by_point.get(ep, []):
                if isinstance(p, DevicePluginMixin) and p.kernel:
                    names.add(p.kernel)
        return frozenset(names)

    def device_weights(self) -> Dict[str, int]:
        return dict(self.score_weights)

    def fit_strategy(self) -> tuple:
        """(strategy_id, shape, lane_weights) — the NodeResourcesFit
        scoring-strategy statics for the device dispatch (ops/gang.py
        DEFAULT_FIT_STRATEGY shape)."""
        inst = self._instances.get("NodeResourcesFit")
        if inst is None:
            return (0, (), (1, 1))
        return (
            inst.STRATEGY_IDS[inst.strategy],
            inst.fit_shape if inst.strategy == "RequestedToCapacityRatio" else (),
            inst.fit_res_weights,
        )

    def plugin_instance(self, name: str):
        """The enabled plugin instance by name, or None (keeps callers off
        the private _instances map)."""
        return self._instances.get(name)

    def host_filter_plugins(self) -> List[FilterPlugin]:
        """Enabled Filter plugins with NO device kernel (the host-veto set)."""
        return [
            p
            for p in self._by_point.get("filter", [])
            if isinstance(p, FilterPlugin) and not isinstance(p, DevicePluginMixin)
        ]

    def host_score_plugins(self) -> List[ScorePlugin]:
        """Enabled Score plugins with NO device kernel — executed host-side
        and merged into the batched selection (runtime/framework.go:1101)."""
        return [
            p
            for p in self._by_point.get("score", [])
            if isinstance(p, ScorePlugin) and not isinstance(p, DevicePluginMixin)
        ]

    # ----- extension-point execution --------------------------------------

    def _observe_point(self, point: str, ok: bool, dt: float) -> None:
        """framework_extension_point_duration_seconds (metrics.go:150,
        recorded through the async recorder like instrumented_plugins.go)."""
        prom = getattr(self.handle, "prom", None) if self.handle else None
        if prom is None:
            return
        prom.recorder.observe(
            prom.extension_point_duration,
            dt,
            extension_point=point,
            status="Success" if ok else "Unschedulable",
            profile=self.profile_name,
        )

    def _observe_plugin(self, plugin: str, point: str, ok: bool, dt: float) -> None:
        """plugin_execution_duration_seconds, 1-in-10 sampled like the
        reference (schedule_one.go:48 pluginMetricsSamplePercent)."""
        self._plugin_sample = getattr(self, "_plugin_sample", 0) + 1
        if self._plugin_sample % 10:
            return
        prom = getattr(self.handle, "prom", None) if self.handle else None
        if prom is None:
            return
        prom.recorder.observe(
            prom.plugin_execution_duration,
            dt,
            plugin=plugin,
            extension_point=point,
            status="Success" if ok else "Unschedulable",
        )

    def run_pre_enqueue(self, pod: Pod) -> Status:
        for p in self._by_point.get("preEnqueue", []):
            if isinstance(p, PreEnqueuePlugin):
                s = p.pre_enqueue(pod)
                if not s.ok:
                    return s
        return Status.success()

    def run_pre_filter(
        self, state: CycleState, pods: Sequence[Pod]
    ) -> Dict[str, Status]:
        """RunPreFilterPlugins per pod (runtime/framework.go:698): returns
        uid → rejecting Status for pods that must not reach Filter; Skip
        marks the plugin's coupled Filter skipped for that pod only."""
        failures: Dict[str, Status] = {}
        plugins = [
            p
            for p in self._by_point.get("preFilter", [])
            if isinstance(p, PreFilterPlugin)
        ]
        if not plugins:
            return failures
        t0 = time.perf_counter()
        for pod in pods:
            allowed = None  # PreFilterResult.NodeNames intersection
            for p in plugins:
                t1 = time.perf_counter()
                s = p.pre_filter(state, pod)
                self._observe_plugin(p.name, "PreFilter", s.ok, time.perf_counter() - t1)
                if s.code == Code.SKIP:
                    state.mark_skip_filter(pod.uid, p.name)
                    continue
                if not s.ok:
                    if not s.plugin:
                        s.plugin = p.name
                    failures[pod.uid] = s
                    break
                r = p.pre_filter_result(pod)
                if r is not None:
                    allowed = r if allowed is None else (allowed & r)
                    if not allowed:
                        # findNodesThatFitPod: empty PreFilterResult ⇒
                        # every node rejected unresolvably (interface.go:855)
                        failures[pod.uid] = Status.unresolvable(
                            "node(s) didn't satisfy plugin "
                            f"{p.name}'s node-name narrowing",
                            plugin=p.name,
                        )
                        break
            else:
                if allowed is not None:
                    state.write(("pre_filter_result", pod.uid), allowed)
        self._observe_point("PreFilter", not failures, time.perf_counter() - t0)
        return failures

    def has_pre_filter_extensions(self) -> bool:
        return any(
            isinstance(p, PreFilterPlugin)
            and p.pre_filter_extensions() is not None
            for p in self._by_point.get("preFilter", [])
        )

    def run_pre_filter_extension_add_pod(
        self, state: CycleState, pod: Pod, pod_to_add: Pod, node_state
    ) -> Status:
        """RunPreFilterExtensionAddPod (runtime/framework.go:743): notify
        every non-skipped PreFilter plugin with extensions that
        ``pod_to_add`` is hypothetically placed on ``node_state``."""
        for p in self._by_point.get("preFilter", []):
            if not isinstance(p, PreFilterPlugin):
                continue
            if state.is_filter_skipped(pod.uid, p.name):
                continue
            ext = p.pre_filter_extensions()
            if ext is None:
                continue
            s = ext.add_pod(state, pod, pod_to_add, node_state)
            if not s.ok:
                if not s.plugin:
                    s.plugin = p.name
                return s
        return Status.success()

    def run_pre_filter_extension_remove_pod(
        self, state: CycleState, pod: Pod, pod_to_remove: Pod, node_state
    ) -> Status:
        """RunPreFilterExtensionRemovePod (runtime/framework.go:770) — the
        preemption dry-run's victim-removal notification
        (preemption.go:548 DryRunPreemption)."""
        for p in self._by_point.get("preFilter", []):
            if not isinstance(p, PreFilterPlugin):
                continue
            if state.is_filter_skipped(pod.uid, p.name):
                continue
            ext = p.pre_filter_extensions()
            if ext is None:
                continue
            s = ext.remove_pod(state, pod, pod_to_remove, node_state)
            if not s.ok:
                if not s.plugin:
                    s.plugin = p.name
                return s
        return Status.success()

    def run_host_filters(self, state: CycleState, pod: Pod, node_state) -> Status:
        """Host-backed Filter plugins as a per-(pod, node) veto — the path
        device kernels can't take (stateful plugins, runtime:861)."""
        for p in self.host_filter_plugins():
            if state.is_filter_skipped(pod.uid, p.name):
                continue
            s = p.filter(state, pod, node_state)
            if not s.ok:
                if not s.plugin:
                    s.plugin = p.name
                return s
        return Status.success()

    def has_host_filters(self) -> bool:
        return bool(self.host_filter_plugins())

    def active_host_filters(self, state: CycleState, pods: Sequence[Pod]) -> List[FilterPlugin]:
        """Host Filter plugins NOT PreFilter-skipped for every pod in the
        batch.  Stateful plugins (volumebinding class) Skip when a pod has
        no relevant spec, so volume-less batches keep the device fast path."""
        return [
            p
            for p in self.host_filter_plugins()
            if any(not state.is_filter_skipped(pod.uid, p.name) for pod in pods)
        ]

    def has_post_filter(self) -> bool:
        return bool(self._by_point.get("postFilter"))

    def post_filter_plugins(self) -> List:
        """The profile's PostFilter plugins (preemption what-if explain
        reaches the DefaultPreemption evaluator through this)."""
        return list(self._by_point.get("postFilter", []))

    def lean_bind_ok(self) -> bool:
        """True when the binding cycle can take the direct-sink path for a
        fast-gated batch: every PreBind plugin is also a host Filter (a
        no-op for pods the gate proved spec-irrelevant) and DefaultBinder
        is the only Bind plugin."""
        cached = self.__dict__.get("_lean_bind")
        if cached is None:
            hf = {p.name for p in self.host_filter_plugins()}
            binds = [
                p
                for p in self._by_point.get("bind", [])
                if isinstance(p, BindPlugin)
            ]
            cached = self.__dict__["_lean_bind"] = (
                all(p.name in hf for p in self._by_point.get("preBind", []))
                and len(binds) == 1
                and binds[0].name == "DefaultBinder"
            )
        return cached

    def run_bind_direct(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """DefaultBinder's bind without the extension-point walk — the
        lean_bind_ok fast-batch path.  binding_duration is sampled 1-in-10
        here (the full path observes per pod) to keep the histogram fed
        without a recorder call per pod."""
        t0 = time.perf_counter()
        try:
            self.handle.bind(pod, node_name)
        except Exception as e:  # noqa: BLE001 — surfaced as Status
            return Status.error(str(e), plugin="DefaultBinder")
        self._bind_sample = getattr(self, "_bind_sample", 0) + 1
        if self._bind_sample % 10 == 0:
            prom = getattr(self.handle, "prom", None) if self.handle else None
            if prom is not None:
                prom.recorder.observe(
                    prom.binding_duration, time.perf_counter() - t0
                )
        return Status.success()

    def pre_filter_spec_pure(self) -> bool:
        """True when every enabled PreFilter plugin's verdict for a
        signature-gated (fast-path) pod is a pure function of the pod SPEC:
        either the plugin never overrode the base no-op ``pre_filter``, or
        it declares ``pre_filter_spec_pure = True`` (every in-tree override
        does — for PVC-less/claim-less/term-less pods they all reduce to a
        spec-only Skip).  Lets the fast path run PreFilter once per
        signature instead of once per pod; custom plugins that keep mutable
        cross-pod state (quota counters) simply don't declare the flag and
        keep the per-pod walk."""
        cached = self.__dict__.get("_pf_pure")
        if cached is None:
            cached = self.__dict__["_pf_pure"] = all(
                type(p).pre_filter is PreFilterPlugin.pre_filter
                or getattr(p, "pre_filter_spec_pure", False)
                for p in self._by_point.get("preFilter", [])
                if isinstance(p, PreFilterPlugin)
            )
        return cached

    def has_post_bind(self) -> bool:
        """True when any PostBind plugin is enabled — the bulk binding
        tail skips the per-pod walk entirely otherwise."""
        cached = self.__dict__.get("_has_post_bind")
        if cached is None:
            cached = self.__dict__["_has_post_bind"] = any(
                isinstance(p, PostBindPlugin)
                for p in self._by_point.get("postBind", [])
            )
        return cached

    def reserve_permit_covered_by_host_filters(self) -> bool:
        """True when every Reserve/Permit plugin is also a host Filter
        plugin (the volumebinding/DRA shape).  For a batch the fast gate
        already proved spec-irrelevant to every host filter, those plugins'
        Reserve/Permit are no-ops by the stateful-plugin contract — the
        commit loop may skip both extension-point walks wholesale."""
        cached = self.__dict__.get("_rp_covered")
        if cached is None:
            hf = {p.name for p in self.host_filter_plugins()}
            cached = self.__dict__["_rp_covered"] = all(
                p.name in hf
                for p in (
                    list(self._by_point.get("reserve", []))
                    + list(self._by_point.get("permit", []))
                )
            )
        return cached

    def run_pre_score(self, state: CycleState, pods: Sequence[Pod], nodes) -> None:
        """RunPreScorePlugins (runtime/framework.go:1052) for HOST-backed
        score plugins: a Skip status marks the plugin's coupled Score
        skipped for the batch's pods (device-backed plugins' PreScore work
        lives inside the fused dispatch's precompute)."""
        t0 = time.perf_counter()
        host_names = {p.name for p in self.host_score_plugins()}
        for p in self._by_point.get("preScore", []):
            if not isinstance(p, PreScorePlugin) or p.name not in host_names:
                continue
            s = p.pre_score(state, pods, nodes)
            if s.code == Code.SKIP:
                for pod in pods:
                    state.mark_skip_score(pod.uid, p.name)
        self._observe_point("PreScore", True, time.perf_counter() - t0)

    def run_host_scores(
        self, state: CycleState, pod: Pod, node_states: Sequence
    ) -> Dict[str, List[int]]:
        """Host Score plugins over a node list (runtime/framework.go:1128):
        returns plugin name → per-node raw scores with NormalizeScore
        (:1158) already applied.  Weighting (:1177) is the caller's job so
        the batched merge can reuse self.score_weights."""
        out: Dict[str, List[int]] = {}
        for p in self.host_score_plugins():
            if state.is_score_skipped(pod.uid, p.name):
                continue
            t1 = time.perf_counter()
            scores = [
                p.score(state, pod, ns) if ns is not None else 0
                for ns in node_states
            ]
            scores = p.normalize(state, pod, scores)
            self._observe_plugin(p.name, "Score", True, time.perf_counter() - t1)
            out[p.name] = scores
        return out

    def active_host_scores(
        self, state: CycleState, pods: Sequence[Pod]
    ) -> List[ScorePlugin]:
        """Host Score plugins that could contribute for ANY pod of the batch
        (spec-relevant, not PreScore-skipped for every pod, non-zero
        weight)."""
        return [
            p
            for p in self.host_score_plugins()
            if self.score_weights.get(p.name, 0)
            and any(
                not state.is_score_skipped(pod.uid, p.name)
                and p.score_relevant(pod)
                for pod in pods
            )
        ]

    def has_reserve_or_permit(self) -> bool:
        """True when Reserve or Permit plugins exist — lets the batched
        commit loop skip two extension-point walks per pod otherwise."""
        cached = self.__dict__.get("_has_rp")
        if cached is None:
            cached = self.__dict__["_has_rp"] = bool(
                any(
                    isinstance(p, ReservePlugin)
                    for p in self._by_point.get("reserve", [])
                )
                or any(
                    isinstance(p, PermitPlugin)
                    for p in self._by_point.get("permit", [])
                )
            )
        return cached

    def run_reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        t0 = time.perf_counter()
        for p in self._by_point.get("reserve", []):
            if isinstance(p, ReservePlugin):
                s = p.reserve(state, pod, node_name)
                if not s.ok:
                    self.run_unreserve(state, pod, node_name)
                    self._observe_point("Reserve", False, time.perf_counter() - t0)
                    return s
        self._observe_point("Reserve", True, time.perf_counter() - t0)
        return Status.success()

    def run_unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in reversed(self._by_point.get("reserve", [])):
            if isinstance(p, ReservePlugin):
                p.unreserve(state, pod, node_name)

    def run_permit(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """Runs Permit plugins; Wait registers the pod in the waiting map
        (runtime:1443)."""
        max_timeout = 0.0
        waiting = False
        for p in self._by_point.get("permit", []):
            if isinstance(p, PermitPlugin):
                s, timeout = p.permit(state, pod, node_name)
                if s.rejected or s.code == Code.ERROR:
                    return s
                if s.code == Code.WAIT:
                    waiting = True
                    max_timeout = max(max_timeout, timeout)
        if waiting:
            self.waiting_pods[pod.uid] = WaitingPod(
                pod, node_name, time.monotonic() + max_timeout
            )
            return Status.wait()
        return Status.success()

    def wait_on_permit(self, pod: Pod) -> Status:
        """Blocks until the waiting pod is allowed/rejected/timed out
        (runtime:1503) — event wait, no polling."""
        wp = self.waiting_pods.get(pod.uid)
        if wp is None:
            return Status.success()
        wp._event.wait(timeout=max(wp.deadline - time.monotonic(), 0.0))
        self.waiting_pods.pop(pod.uid, None)
        if wp.decision is None:
            return Status.unschedulable("permit wait timeout")
        return wp.decision

    def run_pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        t0 = time.perf_counter()
        for p in self._by_point.get("preBind", []):
            if isinstance(p, PreBindPlugin):
                s = p.pre_bind(state, pod, node_name)
                if not s.ok:
                    self._observe_point("PreBind", False, time.perf_counter() - t0)
                    return s
        self._observe_point("PreBind", True, time.perf_counter() - t0)
        return Status.success()

    def run_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        t0 = time.perf_counter()
        try:
            for p in self._by_point.get("bind", []):
                if isinstance(p, BindPlugin):
                    s = p.bind(state, pod, node_name)
                    if s.code == Code.SKIP:
                        continue
                    return s
            return Status.error("no bind plugin handled the pod")
        finally:
            prom = getattr(self.handle, "prom", None) if self.handle else None
            if prom is not None:
                prom.recorder.observe(
                    prom.binding_duration, time.perf_counter() - t0
                )

    def run_post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in self._by_point.get("postBind", []):
            if isinstance(p, PostBindPlugin):
                p.post_bind(state, pod, node_name)

    def run_post_filter(
        self, state: CycleState, pod: Pod, filtered_node_status
    ) -> Tuple[Optional[str], Status]:
        """RunPostFilterPlugins (runtime:908).  A plugin returning "" as the
        nominated node signals "clear any stale nomination" even when the
        status stays unschedulable (PostFilterResult.NominatingMode)."""
        clear_seen = False
        for p in self._by_point.get("postFilter", []):
            if isinstance(p, PostFilterPlugin):
                nominated, s = p.post_filter(state, pod, filtered_node_status)
                if s.ok or s.code == Code.ERROR:
                    return nominated, s
                if nominated == "":
                    clear_seen = True
        if clear_seen:
            return "", Status.unschedulable("preemption is not helpful")
        return None, Status.unschedulable("no postFilter plugin made the pod schedulable")

    # ----- queueing-hint registration (eventhandlers.go:431) ---------------

    def events_to_register(self) -> Dict[str, List[ClusterEventWithHint]]:
        out: Dict[str, List[ClusterEventWithHint]] = {}
        for name, inst in self._instances.items():
            if isinstance(inst, EnqueueExtensions):
                evs = inst.events_to_register()
                if evs:
                    out[name] = evs
        return out
