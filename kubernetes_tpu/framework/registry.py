"""Plugin registry: name → factory (framework/runtime/registry.go).

In-tree plugins register at import; out-of-tree plugins merge the same way
the reference merges frameworkruntime.Registry (scheduler.go:278-280).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Type

from kubernetes_tpu.framework.interface import Plugin
from kubernetes_tpu.framework.plugins import DEFAULT_PLUGINS

PluginFactory = Callable[[Optional[dict], object], Plugin]


class Registry(Dict[str, PluginFactory]):
    def register(self, name: str, factory: PluginFactory) -> None:
        if name in self:
            raise ValueError(f"plugin {name!r} already registered")
        self[name] = factory

    def merge(self, other: "Registry") -> "Registry":
        for name, factory in other.items():
            self.register(name, factory)
        return self


def _factory_of(cls: Type[Plugin]) -> PluginFactory:
    return lambda args, handle: cls(args=args, handle=handle)


def default_registry() -> Registry:
    """The in-tree set (framework/plugins/registry.go:47)."""
    r = Registry()
    for cls in DEFAULT_PLUGINS:
        r.register(cls.name, _factory_of(cls))
    return r
