"""VolumeBinding: PVC↔PV matching + dynamic-provisioning decisions.

The host-backed stateful plugin path (SURVEY.md §7 "stateful plugins"):
volume feasibility is low-volume, string/object-heavy control logic that
gates the device pipeline through the host Filter veto, so it stays on the
host by design — the batched kernels never see it.

Semantics mirror pkg/scheduler/framework/plugins/volumebinding/
volume_binding.go (:322 PreFilter, :394 Filter, :476 Reserve, :501 PreBind)
and binder.go (FindPodVolumes :281, AssumePodVolumes :441, BindPodVolumes
:512), re-expressed over the generic assume caches.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api import labels as k8slabels
from kubernetes_tpu.api import storage as st
from kubernetes_tpu.api.types import Node, Pod, node_selector_matches
from kubernetes_tpu.framework.interface import (
    ActionType,
    ClusterEvent,
    ClusterEventWithHint,
    CycleState,
    EnqueueExtensions,
    EventResource,
    FilterPlugin,
    PreBindPlugin,
    PreFilterPlugin,
    QueueingHint,
    ReservePlugin,
    ScorePlugin,
    Status,
)

# Conflict reasons (binder.go:66-74)
REASON_BIND_CONFLICT = "node(s) didn't find available persistent volumes to bind"
REASON_NODE_CONFLICT = "node(s) had volume node affinity conflict"
REASON_NOT_ENOUGH_SPACE = "node(s) did not have enough free storage"
REASON_PV_NOT_EXIST = (
    "node(s) unavailable due to one or more pvc(s) bound to non-existent pv(s)"
)


@dataclass
class BindingInfo:
    """One static binding decision: this claim onto this PV (binder.go:77)."""

    pvc: st.PersistentVolumeClaim
    pv: st.PersistentVolume


@dataclass
class PodVolumes:
    static_bindings: List[BindingInfo] = field(default_factory=list)
    dynamic_provisions: List[st.PersistentVolumeClaim] = field(default_factory=list)


@dataclass
class PodVolumeClaims:
    """GetPodVolumeClaims output (binder.go:205)."""

    bound_claims: List[st.PersistentVolumeClaim] = field(default_factory=list)
    claims_to_bind: List[st.PersistentVolumeClaim] = field(default_factory=list)
    unbound_claims_immediate: List[st.PersistentVolumeClaim] = field(
        default_factory=list
    )
    # storage class → available PVs for delayed binding (binder.go:861)
    unbound_volumes_delay_binding: Dict[str, List[st.PersistentVolume]] = field(
        default_factory=dict
    )


def pv_matches_claim(
    pv: st.PersistentVolume, pvc: st.PersistentVolumeClaim
) -> bool:
    """FindMatchingVolume's per-PV eligibility (pkg/volume/util): class,
    volumeMode, access modes subset, selector, capacity, and not bound to a
    different claim."""
    if (pvc.storage_class_name or "") != pv.storage_class_name:
        return False
    if pv.volume_mode != pvc.volume_mode:
        return False
    if not set(pvc.access_modes).issubset(set(pv.access_modes)):
        return False
    if pv.claim_ref is not None and not (
        pv.claim_ref.namespace == pvc.namespace and pv.claim_ref.name == pvc.name
    ):
        return False
    if pv.phase not in (st.PV_AVAILABLE, st.PV_BOUND):
        return False
    if pv.capacity < pvc.request:
        return False
    if pvc.selector is not None:
        sel = k8slabels.selector_from_label_selector(pvc.selector)
        if not sel.matches(pv.labels):
            return False
    return True


def pv_node_affinity_matches(pv: st.PersistentVolume, node: Node) -> bool:
    """CheckVolumeNodeAffinity: nil affinity matches everywhere."""
    if pv.node_affinity is None:
        return True
    return node_selector_matches(pv.node_affinity, node)


class VolumeBinder:
    """SchedulerVolumeBinder (binder.go:152) over assume caches.

    ``handle`` supplies: pv_cache, pvc_cache (AssumeCache), storage_class /
    csi_driver / capacity listers, and the pv/pvc API writers.
    """

    def __init__(self, handle):
        self.handle = handle

    # -- claim classification (binder.go:825 GetPodVolumeClaims) -------------

    def get_pod_volume_claims(self, pod: Pod) -> Tuple[Optional[PodVolumeClaims], Optional[Status]]:
        claims = PodVolumeClaims()
        for name in pod.pvc_names():
            pvc = self.handle.pvc_cache.get(f"{pod.namespace}/{name}")
            if pvc is None:
                return None, Status.unresolvable(
                    f'persistentvolumeclaim "{name}" not found',
                    plugin=VolumeBinding.name,
                )
            if pvc.deletion_timestamp is not None:
                return None, Status.unresolvable(
                    f'persistentvolumeclaim "{name}" is being deleted',
                    plugin=VolumeBinding.name,
                )
            if pvc.is_fully_bound():
                claims.bound_claims.append(pvc)
            else:
                sc = self.handle.get_storage_class(pvc.storage_class_name or "")
                if sc is not None and sc.is_wait_for_first_consumer():
                    claims.claims_to_bind.append(pvc)
                else:
                    claims.unbound_claims_immediate.append(pvc)
        for pvc in claims.claims_to_bind:
            cls = pvc.storage_class_name or ""
            if cls not in claims.unbound_volumes_delay_binding:
                claims.unbound_volumes_delay_binding[cls] = [
                    pv
                    for pv in self.handle.pv_cache.list()
                    if pv.storage_class_name == cls
                ]
        return claims, None

    # -- per-node feasibility (binder.go:281 FindPodVolumes) -----------------

    def find_pod_volumes(
        self, pod: Pod, claims: PodVolumeClaims, node: Node
    ) -> Tuple[PodVolumes, List[str]]:
        reasons: List[str] = []
        volumes = PodVolumes()

        # bound claims: PV must exist and its node affinity must admit the
        # node (binder.go:868 checkBoundClaims)
        for pvc in claims.bound_claims:
            pv = self.handle.pv_cache.get(pvc.volume_name)
            if pv is None:
                return volumes, [REASON_PV_NOT_EXIST]
            if not pv_node_affinity_matches(pv, node):
                return volumes, [REASON_NODE_CONFLICT]

        unbound: List[st.PersistentVolumeClaim] = []
        if claims.claims_to_bind:
            # static matching: smallest eligible PV per claim, largest
            # claims first so they see the full pool (FindMatchingVolume)
            matched_pvs: set = set()
            for pvc in sorted(claims.claims_to_bind, key=lambda c: -c.request):
                pool = claims.unbound_volumes_delay_binding.get(
                    pvc.storage_class_name or "", []
                )
                best = None
                for pv in pool:
                    if pv.name in matched_pvs:
                        continue
                    if not pv_matches_claim(pv, pvc):
                        continue
                    if not pv_node_affinity_matches(pv, node):
                        continue
                    if best is None or pv.capacity < best.capacity:
                        best = pv
                if best is not None:
                    matched_pvs.add(best.name)
                    volumes.static_bindings.append(BindingInfo(pvc, best))
                else:
                    unbound.append(pvc)

        if unbound:
            # dynamic provisioning (binder.go:945 checkVolumeProvisions)
            provision_ok = True
            space_ok = True
            for pvc in unbound:
                sc = self.handle.get_storage_class(pvc.storage_class_name or "")
                if sc is None or sc.provisioner == st.NO_PROVISIONER:
                    provision_ok = False
                    continue
                if not sc.topology_allows(node.labels):
                    provision_ok = False
                    continue
                if not self._has_enough_capacity(sc, pvc, node):
                    space_ok = False
                    continue
                volumes.dynamic_provisions.append(pvc)
            if not provision_ok:
                reasons.append(REASON_BIND_CONFLICT)
            if not space_ok:
                reasons.append(REASON_NOT_ENOUGH_SPACE)
        return volumes, reasons

    def _has_enough_capacity(
        self, sc: st.StorageClass, pvc: st.PersistentVolumeClaim, node: Node
    ) -> bool:
        """binder.go:1005 hasEnoughCapacity: only checked when the CSI
        driver opts in via spec.storageCapacity."""
        driver = self.handle.get_csi_driver(sc.provisioner)
        if driver is None or not driver.storage_capacity:
            return True
        for cap in self.handle.list_capacities():
            if cap.storage_class_name != sc.name:
                continue
            if not cap.topology_matches(node.labels):
                continue
            if cap.maximum_volume_size is not None and pvc.request > cap.maximum_volume_size:
                continue
            if cap.capacity >= pvc.request:
                return True
        return False

    # -- assume / revert / bind (binder.go:441,504,512) -----------------------

    def assume_pod_volumes(
        self, pod: Pod, node_name: str, volumes: PodVolumes
    ) -> bool:
        """Installs the decisions into the assume caches; returns
        all_bound=True when there was nothing to do."""
        if not volumes.static_bindings and not volumes.dynamic_provisions:
            return True
        new_bindings = []
        for b in volumes.static_bindings:
            pv = b.pv.clone()
            pv.claim_ref = st.ObjectRef(b.pvc.namespace, b.pvc.name)
            self.handle.pv_cache.assume(pv)
            new_bindings.append(BindingInfo(b.pvc, pv))
        volumes.static_bindings = new_bindings
        new_provisions = []
        for pvc in volumes.dynamic_provisions:
            npvc = pvc.clone()
            npvc.annotations[st.ANN_SELECTED_NODE] = node_name
            self.handle.pvc_cache.assume(npvc)
            new_provisions.append(npvc)
        volumes.dynamic_provisions = new_provisions
        return False

    def revert_assumed_pod_volumes(self, volumes: PodVolumes) -> None:
        for b in volumes.static_bindings:
            self.handle.pv_cache.restore(b.pv.key)
        for pvc in volumes.dynamic_provisions:
            self.handle.pvc_cache.restore(pvc.key)

    def bind_pod_volumes(self, pod: Pod, volumes: PodVolumes) -> Optional[str]:
        """bindAPIUpdate + checkBindings: write the assumed objects through
        the API, then verify the PV controller completed the binding.
        Returns an error string or None.  The in-proc fake controller reacts
        synchronously inside the write, so one post-write check replaces the
        reference's poll loop (binder.go:512-538)."""
        for b in volumes.static_bindings:
            self.handle.write_pv(b.pv)
        for pvc in volumes.dynamic_provisions:
            self.handle.write_pvc(pvc)
        return self._check_bindings(pod, volumes)

    def _check_bindings(self, pod: Pod, volumes: PodVolumes) -> Optional[str]:
        for b in volumes.static_bindings:
            pvc = self.handle.pvc_cache.get_api_obj(b.pvc.key)
            if pvc is None:
                return f"pvc {b.pvc.key} lost while binding"
            if not pvc.is_fully_bound() or pvc.volume_name != b.pv.name:
                return f"pvc {b.pvc.key} not bound to pv {b.pv.name} yet"
        for p in volumes.dynamic_provisions:
            pvc = self.handle.pvc_cache.get_api_obj(p.key)
            if pvc is None:
                return f"pvc {p.key} lost while provisioning"
            if pvc.annotations.get(st.ANN_SELECTED_NODE) != p.annotations.get(
                st.ANN_SELECTED_NODE
            ):
                return f"pvc {p.key} selected-node annotation was reset"
            if not pvc.is_fully_bound():
                return f"pvc {p.key} not provisioned yet"
        return None


class VolumeBinding(
    PreFilterPlugin, FilterPlugin, ScorePlugin, ReservePlugin, PreBindPlugin, EnqueueExtensions
):
    """volume_binding.go — the plugin shim over VolumeBinder."""

    name = "VolumeBinding"
    # for claim-less/PVC-less (fast-gated) pods pre_filter is a spec-only
    # Skip — safe for per-signature grouping (enforced: kubernetes_tpu.
    # analysis plugin-purity checks the spec path stays handle/state-free)
    pre_filter_spec_pure = True

    _STATE_KEY = "VolumeBinding"

    def __init__(self, args: Optional[dict] = None, handle=None):
        super().__init__(args, handle)
        self.binder = VolumeBinder(handle)
        # VolumeCapacityPriority-gated scorer; shape points as
        # [(utilization, score)], None = disabled (the default)
        self.shape = self.args.get("shape")

    def maybe_relevant(self, pod: Pod) -> bool:
        return bool(pod.pvc_names())

    def score_relevant(self, pod: Pod) -> bool:
        # VolumeCapacityPriority only contributes when the shape is
        # configured and the pod has claims (volume_binding.go:441).
        return self.shape is not None and bool(pod.pvc_names())

    # -- PreFilter (volume_binding.go:322) -----------------------------------

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        if not pod.pvc_names():
            return Status.skip()
        claims, status = self.binder.get_pod_volume_claims(pod)
        if status is not None:
            return status
        if claims.unbound_claims_immediate:
            return Status.unresolvable(
                "pod has unbound immediate PersistentVolumeClaims",
                plugin=self.name,
            )
        state.write((self._STATE_KEY, pod.uid), {"claims": claims, "by_node": {}})
        return Status.success()

    # -- Filter (volume_binding.go:394) ----------------------------------------

    def filter(self, state: CycleState, pod: Pod, node_state) -> Status:
        data = state.read((self._STATE_KEY, pod.uid))
        if data is None:  # PreFilter skipped — no PVCs
            return Status.success()
        node = node_state.node
        volumes, reasons = self.binder.find_pod_volumes(pod, data["claims"], node)
        if reasons:
            # UnschedulableAndUnresolvable (volume_binding.go:414): no
            # victim eviction frees a PV / fixes node affinity, so these
            # nodes must not enter preemption dry-runs.
            return Status.unresolvable(*reasons, plugin=self.name)
        data["by_node"][node.name] = volumes
        return Status.success()

    # -- Score (volume_binding.go:441; VolumeCapacityPriority) -----------------

    def score(self, state: CycleState, pod: Pod, node_state) -> int:
        if self.shape is None:
            return 0
        data = state.read((self._STATE_KEY, pod.uid))
        if data is None:
            return 0
        volumes = data["by_node"].get(node_state.node.name)
        if volumes is None or not volumes.static_bindings:
            return 0
        classes: Dict[str, List[int]] = {}
        for b in volumes.static_bindings:
            req, cap = classes.setdefault(b.pv.storage_class_name, [0, 0])
            classes[b.pv.storage_class_name] = [req + b.pvc.request, cap + b.pv.capacity]
        if not classes:
            return 0
        total = 0.0
        for req, cap in classes.values():
            util = 100 if (cap == 0 or req > cap) else req * 100 // cap
            total += self._shape_value(util)
        return int(round(total / len(classes)))

    def _shape_value(self, utilization: int) -> float:
        """helper.BuildBrokenLinearFunction over self.shape points."""
        pts = sorted(self.shape)
        if utilization <= pts[0][0]:
            return pts[0][1]
        for (x0, y0), (x1, y1) in zip(pts, pts[1:]):
            if utilization <= x1:
                return y0 + (y1 - y0) * (utilization - x0) / (x1 - x0)
        return pts[-1][1]

    # -- Reserve / Unreserve (volume_binding.go:476,528) -----------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        data = state.read((self._STATE_KEY, pod.uid))
        if data is None:
            return Status.success()
        volumes = data["by_node"].get(node_name)
        if volumes is None:
            return Status.error(
                f"no volume decisions recorded for node {node_name}", plugin=self.name
            )
        data["all_bound"] = self.binder.assume_pod_volumes(pod, node_name, volumes)
        data["reserved_node"] = node_name
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        data = state.read((self._STATE_KEY, pod.uid))
        if data is None:
            return
        volumes = data["by_node"].get(node_name)
        if volumes is not None and not data.get("all_bound", True):
            self.binder.revert_assumed_pod_volumes(volumes)

    # -- PreBind (volume_binding.go:501) ----------------------------------------

    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        data = state.read((self._STATE_KEY, pod.uid))
        if data is None or data.get("all_bound", True):
            return Status.success()
        volumes = data["by_node"].get(node_name)
        err = self.binder.bind_pod_volumes(pod, volumes)
        if err is not None:
            return Status.error(err, plugin=self.name)
        return Status.success()

    # -- queueing hints (volume_binding.go:97 EventsToRegister) -----------------

    def events_to_register(self) -> List[ClusterEventWithHint]:
        def pvc_hint(pod: Pod, old, new) -> QueueingHint:
            # Only this pod's own claims becoming bindable matter
            # (:159 isSchedulableAfterPersistentVolumeClaimChange).
            if new is None:
                return QueueingHint.SKIP
            if new.namespace != pod.namespace:
                return QueueingHint.SKIP
            return (
                QueueingHint.QUEUE
                if new.name in pod.pvc_names()
                else QueueingHint.SKIP
            )

        return [
            ClusterEventWithHint(
                ClusterEvent(EventResource.PVC, ActionType.ADD | ActionType.UPDATE),
                pvc_hint,
            ),
            ClusterEventWithHint(
                ClusterEvent(EventResource.PV, ActionType.ADD | ActionType.UPDATE)
            ),
            ClusterEventWithHint(
                ClusterEvent(EventResource.STORAGE_CLASS, ActionType.ADD | ActionType.UPDATE)
            ),
            ClusterEventWithHint(
                ClusterEvent(EventResource.CSI_NODE, ActionType.ADD | ActionType.UPDATE)
            ),
            ClusterEventWithHint(
                ClusterEvent(
                    EventResource.CSI_STORAGE_CAPACITY,
                    ActionType.ADD | ActionType.UPDATE,
                )
            ),
            ClusterEventWithHint(
                ClusterEvent(
                    EventResource.CSI_DRIVER,
                    ActionType.UPDATE | ActionType.DELETE,
                )
            ),
            ClusterEventWithHint(ClusterEvent(EventResource.NODE, ActionType.ADD)),
        ]
