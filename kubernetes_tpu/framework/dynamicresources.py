"""DynamicResources (DRA) plugin — structured-parameters claim allocation.

Host-backed stateful plugin mirroring pkg/scheduler/framework/plugins/
dynamicresources/dynamicresources.go (:419 PreEnqueue, :709 PreFilter, :902
Filter, :1156 Reserve, :1306 Unreserve, :1367 PreBind) over the generic
assume cache, with the structured allocator reduced to its scheduling
semantics: a claim's device requests are satisfied by free devices from the
node's ResourceSlices whose attributes pass the DeviceClass + request
selectors; cross-claim exclusivity comes from the allocated-device set of
every other claim in the cache.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from kubernetes_tpu.api import dra
from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.framework.interface import (
    ActionType,
    ClusterEvent,
    ClusterEventWithHint,
    CycleState,
    EnqueueExtensions,
    EventResource,
    FilterPlugin,
    PreBindPlugin,
    PreEnqueuePlugin,
    PreFilterPlugin,
    QueueingHint,
    ReservePlugin,
    Status,
)


class DynamicResources(
    PreEnqueuePlugin,
    PreFilterPlugin,
    FilterPlugin,
    ReservePlugin,
    PreBindPlugin,
    EnqueueExtensions,
):
    name = "DynamicResources"
    # for claim-less/PVC-less (fast-gated) pods pre_filter is a spec-only
    # Skip — safe for per-signature grouping (enforced: kubernetes_tpu.
    # analysis plugin-purity checks the spec path stays handle/state-free)
    pre_filter_spec_pure = True
    _STATE_KEY = "DynamicResources"

    def maybe_relevant(self, pod: Pod) -> bool:
        return bool(pod.resource_claims)

    # -- PreEnqueue (:419): claims must exist before the pod may queue -------

    def pre_enqueue(self, pod: Pod) -> Status:
        for name in pod.resource_claims:
            if self.handle.claim_cache.get(f"{pod.namespace}/{name}") is None:
                return Status.unresolvable(
                    f'waiting for resource claim "{name}" to be created',
                    plugin=self.name,
                )
        return Status.success()

    # -- PreFilter (:709) -------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        if not pod.resource_claims:
            return Status.skip()
        claims: List[dra.ResourceClaim] = []
        for name in pod.resource_claims:
            claim = self.handle.claim_cache.get(f"{pod.namespace}/{name}")
            if claim is None:
                return Status.unresolvable(
                    f'resourceclaim "{name}" not found', plugin=self.name
                )
            if claim.deletion_timestamp is not None:
                return Status.unresolvable(
                    f'resourceclaim "{name}" is being deleted', plugin=self.name
                )
            if claim.allocation is not None:
                if (
                    pod.uid not in claim.reserved_for
                    and len(claim.reserved_for) >= dra.ResourceClaim.MAX_RESERVED
                ):
                    return Status.unschedulable(
                        f'resourceclaim "{name}" is reserved by too many pods',
                        plugin=self.name,
                    )
            claims.append(claim)
        # Per-cycle precomputes so Filter is O(node's slices), not
        # O(all claims + all slices) per node: the cluster-wide
        # allocated-device set (own allocated claims included — their
        # devices are taken too) and a node_name → slices index.
        slices_by_node: Dict[str, List] = {}
        for sl in self.handle.list_resource_slices():
            slices_by_node.setdefault(sl.node_name, []).append(sl)
        state.write(
            (self._STATE_KEY, pod.uid),
            {
                "claims": claims,
                "by_node": {},
                "taken_base": self._allocated_devices(),
                "slices_by_node": slices_by_node,
            },
        )
        return Status.success()

    # -- allocator ---------------------------------------------------------------

    def _allocated_devices(self) -> Set[Tuple[str, str, str]]:
        """(driver, pool, device) triples held by ANY allocated claim —
        the in-memory allocated-state the structured allocator checks.
        A pod's own allocated claims count too (their devices are taken;
        only its UNallocated claims receive new grants)."""
        out: Set[Tuple[str, str, str]] = set()
        for claim in self.handle.claim_cache.list():
            if claim.allocation is None:
                continue
            for r in claim.allocation.results:
                out.add((r.driver, r.pool, r.device))
        return out

    def _allocate_on_node(
        self,
        claim: dra.ResourceClaim,
        node_name: str,
        node_slices: List[dra.ResourceSlice],
        taken: Set[Tuple[str, str, str]],
    ) -> Optional[dra.AllocationResult]:
        """Try to satisfy every request of the claim from the node's slices;
        ``taken`` accumulates devices granted earlier in this pod's own
        allocation so claims don't double-book."""
        results: List[dra.DeviceRequestAllocationResult] = []
        granted: List[Tuple[str, str, str]] = []

        def fail() -> None:
            for key in granted:  # give back this claim's partial grants
                taken.discard(key)

        for req in claim.requests:
            device_class = self.handle.get_device_class(req.device_class_name)
            if device_class is None:
                fail()
                return None
            found: List[dra.DeviceRequestAllocationResult] = []
            want = req.count if req.allocation_mode == dra.ALLOCATION_MODE_EXACT else None
            ok = True
            for sl in node_slices:
                for dev in sl.devices:
                    key = (sl.driver, sl.pool, dev.name)
                    attrs = dev.attr_map()
                    if not device_class.admits(attrs):
                        continue
                    if not all(s.matches(attrs) for s in req.selectors):
                        continue
                    if key in taken:
                        if want is None:
                            # AllocationMode=All requires EVERY matching
                            # device allocatable (structured/allocator.go:
                            # 530-552) — one in use fails the node
                            ok = False
                            break
                        continue
                    found.append(
                        dra.DeviceRequestAllocationResult(
                            request=req.name,
                            driver=sl.driver,
                            pool=sl.pool,
                            device=dev.name,
                        )
                    )
                    taken.add(key)
                    granted.append(key)
                    if want is not None and len(found) >= want:
                        break
                if not ok or (want is not None and len(found) >= want):
                    break
            if not ok or (want is not None and len(found) < want) or (
                want is None and not found
            ):
                fail()
                return None
            results.extend(found)
        return dra.AllocationResult(results=tuple(results), node_name=node_name)

    # -- Filter (:902) -------------------------------------------------------------

    def filter(self, state: CycleState, pod: Pod, node_state) -> Status:
        data = state.read((self._STATE_KEY, pod.uid))
        if data is None:
            return Status.success()
        node_name = node_state.node.name
        taken = set(data["taken_base"])
        node_slices = data["slices_by_node"].get(node_name, [])
        allocations: List[Optional[dra.AllocationResult]] = []
        for claim in data["claims"]:
            if claim.allocation is not None:
                # already allocated: usable only on the allocation's node
                if claim.allocation.node_name and claim.allocation.node_name != node_name:
                    return Status.unschedulable(
                        f'resourceclaim "{claim.name}" is allocated for node '
                        f"{claim.allocation.node_name}",
                        plugin=self.name,
                    )
                allocations.append(None)  # nothing new to allocate
                continue
            alloc = self._allocate_on_node(claim, node_name, node_slices, taken)
            if alloc is None:
                return Status.unschedulable(
                    f'cannot allocate all devices for resourceclaim "{claim.name}"',
                    plugin=self.name,
                )
            allocations.append(alloc)
        data["by_node"][node_name] = allocations
        return Status.success()

    # -- Reserve / Unreserve (:1156, :1306) ------------------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        data = state.read((self._STATE_KEY, pod.uid))
        if data is None:
            return Status.success()
        allocations = data["by_node"].get(node_name)
        if allocations is None:
            return Status.error(
                f"no DRA decisions recorded for node {node_name}", plugin=self.name
            )
        assumed: List[Tuple[dra.ResourceClaim, bool]] = []
        for claim, alloc in zip(data["claims"], allocations):
            nc = claim.clone()
            if alloc is not None:
                nc.allocation = alloc
            if pod.uid not in nc.reserved_for:
                nc.reserved_for = nc.reserved_for + (pod.uid,)
            self.handle.claim_cache.assume(nc)
            assumed.append((nc, alloc is not None))
        data["assumed"] = assumed
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        """:1306 — restore the cache view AND undo any API writes PreBind
        already made (the reference's Unreserve patches claims to drop the
        reservation / deallocate a scheduler-made allocation)."""
        data = state.read((self._STATE_KEY, pod.uid))
        if data is None:
            return
        for claim, allocated_by_us in data.get("assumed", []):
            self.handle.claim_cache.restore(claim.key)
            api_obj = self.handle.claim_cache.get_api_obj(claim.key)
            if api_obj is None or pod.uid not in api_obj.reserved_for:
                continue  # never persisted — cache restore is enough
            rb = api_obj.clone()
            rb.reserved_for = tuple(u for u in rb.reserved_for if u != pod.uid)
            if allocated_by_us and not rb.reserved_for:
                rb.allocation = None
            try:
                self.handle.write_claim(rb)
            except Exception:  # noqa: BLE001 — rollback is best-effort
                pass
        data.pop("assumed", None)

    # -- PreBind (:1367): persist allocation + reservation through the API ----

    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        data = state.read((self._STATE_KEY, pod.uid))
        if data is None:
            return Status.success()
        for claim, _ in data.get("assumed", []):
            try:
                self.handle.write_claim(claim)
            except Exception as e:  # noqa: BLE001 — surfaced as Status
                return Status.error(str(e), plugin=self.name)
        return Status.success()

    # -- queueing hints (:379 EventsToRegister) ---------------------------------

    def events_to_register(self) -> List[ClusterEventWithHint]:
        def claim_hint(pod: Pod, old, new) -> QueueingHint:
            # A claim change helps only pods referencing that claim (:434).
            if new is None or new.namespace != pod.namespace:
                return QueueingHint.SKIP
            return (
                QueueingHint.QUEUE
                if new.name in pod.resource_claims
                else QueueingHint.SKIP
            )

        return [
            ClusterEventWithHint(
                ClusterEvent(
                    EventResource.RESOURCE_CLAIM,
                    ActionType.ADD | ActionType.UPDATE | ActionType.DELETE,
                ),
                claim_hint,
            ),
            ClusterEventWithHint(
                ClusterEvent(
                    EventResource.RESOURCE_SLICE,
                    ActionType.ADD | ActionType.UPDATE,
                )
            ),
            ClusterEventWithHint(
                ClusterEvent(EventResource.DEVICE_CLASS, ActionType.ADD | ActionType.UPDATE)
            ),
            ClusterEventWithHint(ClusterEvent(EventResource.NODE, ActionType.ADD)),
        ]
