"""Plugin API: extension points, Status codes, CycleState.

Mirrors pkg/scheduler/framework/interface.go — the 12 extension points
(PreEnqueue, QueueSort, PreFilter, Filter, PostFilter, PreScore, Score,
Reserve, Permit, PreBind, Bind, PostBind) and the Status code lattice
(:190-244).  Two deliberate differences for the TPU execution model:

  * Filter/Score have BATCH variants (``filter_batch``/``score_batch``)
    returning [P, N] device arrays — a device-backed plugin implements
    those; the scalar variants remain for host-backed plugins and parity
    testing.
  * PreFilter's node-narrowing result (PreFilterResult.NodeNames,
    interface.go:837) is expressed as a [P, N] mask contribution instead of
    a name set.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Node, Pod


class Code(enum.IntEnum):
    """Status codes (interface.go:190)."""

    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5
    PENDING = 6


@dataclass
class Status:
    code: Code = Code.SUCCESS
    reasons: Tuple[str, ...] = ()
    plugin: str = ""

    @classmethod
    def success(cls) -> "Status":
        return cls()

    @classmethod
    def unschedulable(cls, *reasons: str, plugin: str = "") -> "Status":
        return cls(Code.UNSCHEDULABLE, tuple(reasons), plugin)

    @classmethod
    def unresolvable(cls, *reasons: str, plugin: str = "") -> "Status":
        return cls(Code.UNSCHEDULABLE_AND_UNRESOLVABLE, tuple(reasons), plugin)

    @classmethod
    def error(cls, msg: str, plugin: str = "") -> "Status":
        return cls(Code.ERROR, (msg,), plugin)

    @classmethod
    def skip(cls) -> "Status":
        return cls(Code.SKIP)

    @classmethod
    def wait(cls, plugin: str = "") -> "Status":
        return cls(Code.WAIT, plugin=plugin)

    @property
    def ok(self) -> bool:
        return self.code == Code.SUCCESS

    @property
    def rejected(self) -> bool:
        return self.code in (
            Code.UNSCHEDULABLE,
            Code.UNSCHEDULABLE_AND_UNRESOLVABLE,
        )

    def merge_reason(self) -> str:
        return "; ".join(self.reasons)


class CycleState:
    """Per-scheduling-cycle scratch space (framework/cycle_state.go:44).

    Keyed read/write plus the Skip sets PreFilter/PreScore populate.  One
    CycleState serves a whole BATCH here; per-pod data is stored under
    (key, pod_uid) to keep host plugins independent.
    """

    def __init__(self) -> None:
        self._data: Dict[Any, Any] = {}
        # Per-pod skip sets: (pod_uid, plugin_name).  The reference's
        # SkipFilterPlugins/SkipScorePlugins are per-cycle (= per-pod); one
        # CycleState here serves a whole batch, so the pod uid is part of
        # the key.
        self.skip_filter_plugins: set[tuple[str, str]] = set()
        self.skip_score_plugins: set[tuple[str, str]] = set()

    def write(self, key: Any, value: Any) -> None:
        self._data[key] = value

    def read(self, key: Any) -> Any:
        return self._data.get(key)

    def delete(self, key: Any) -> None:
        self._data.pop(key, None)

    def mark_skip_filter(self, pod_uid: str, plugin: str) -> None:
        self.skip_filter_plugins.add((pod_uid, plugin))

    def is_filter_skipped(self, pod_uid: str, plugin: str) -> bool:
        return (pod_uid, plugin) in self.skip_filter_plugins

    def mark_skip_score(self, pod_uid: str, plugin: str) -> None:
        self.skip_score_plugins.add((pod_uid, plugin))

    def is_score_skipped(self, pod_uid: str, plugin: str) -> bool:
        return (pod_uid, plugin) in self.skip_score_plugins

    def clone(self) -> "CycleState":
        """cycle_state.go Clone: values providing their own clone() are
        deep-cloned (the reference calls StateData.Clone per entry); plain
        values are shared — plugins mutating stored state in AddPod/
        RemovePod extensions must store clonable objects, or the
        preemption dry-run's per-node isolation leaks across candidates."""
        cs = CycleState()
        cs._data = {
            k: (v.clone() if hasattr(v, "clone") else v)
            for k, v in self._data.items()
        }
        cs.skip_filter_plugins = set(self.skip_filter_plugins)
        cs.skip_score_plugins = set(self.skip_score_plugins)
        return cs


# ---------------------------------------------------------------------------
# Plugin base classes (one per extension point, interface.go:443-682)
# ---------------------------------------------------------------------------


class Plugin:
    """Base: every plugin has a name (interface.go:443)."""

    name: str = ""

    def __init__(self, args: Optional[dict] = None, handle=None):
        self.args = args or {}
        self.handle = handle


class PreEnqueuePlugin(Plugin):
    def pre_enqueue(self, pod: Pod) -> Status:
        raise NotImplementedError


class QueueSortPlugin(Plugin):
    def less(self, a, b) -> bool:
        """a, b are QueuedPodInfo-shaped objects."""
        raise NotImplementedError


class PreFilterExtensions:
    """interface.go:443-520 PreFilterExtensions: incremental updates to a
    plugin's per-cycle PreFilter state when the evaluated cluster view is
    hypothetically modified — nominated pods counted as placed
    (RunFilterPluginsWithNominatedPods, runtime/framework.go:973) and
    preemption dry-run victim removal/reprieve (preemption.go:548)."""

    def add_pod(
        self, state: CycleState, pod_to_schedule: Pod, pod_to_add: Pod, node_state
    ) -> Status:
        return Status.success()

    def remove_pod(
        self,
        state: CycleState,
        pod_to_schedule: Pod,
        pod_to_remove: Pod,
        node_state,
    ) -> Status:
        return Status.success()


class PreFilterPlugin(Plugin):
    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        """Per-pod PreFilter (interface.go RunPreFilterPlugins semantics):
        Status.skip() disables the coupled Filter for this pod;
        unschedulable/unresolvable rejects the pod for the whole cycle."""
        return Status.success()

    def pre_filter_result(self, pod: Pod) -> Optional[set]:
        """PreFilterResult.NodeNames (interface.go:837-865): an optional
        node-name set the pod could EVER land on; None = all nodes.  The
        runtime intersects results across plugins; an empty intersection
        rejects the pod UnschedulableAndUnresolvable before Filter."""
        return None

    def pre_filter_extensions(self) -> Optional[PreFilterExtensions]:
        """interface.go PreFilterExtensions(): nil when the plugin's cycle
        state needs no incremental maintenance."""
        return None


class FilterPlugin(Plugin):
    """Host-backed per-(pod, node) filter."""

    def filter(self, state: CycleState, pod: Pod, node_state) -> Status:
        raise NotImplementedError

    def maybe_relevant(self, pod: Pod) -> bool:
        """Cheap spec-only predicate: could this plugin's Filter possibly
        act on the pod?  Used by the batch dispatcher to decide host-filter
        serialization BEFORE PreFilter runs; must be a superset of
        "PreFilter would not Skip".  Default: always relevant."""
        return True


class DeviceFilterPlugin(Plugin):
    """Device-backed filter: contributes a [P, N] feasibility mask.

    ``mask_fn(dc, db, ctx) -> jnp.ndarray`` is invoked inside the fused jit
    dispatch; ctx carries v_cap and shared precomputes.
    """

    def device_mask(self, dc, db, ctx) -> Any:
        raise NotImplementedError


class PostFilterPlugin(Plugin):
    def post_filter(self, state: CycleState, pod: Pod, filtered_node_status) -> Tuple[Optional[str], Status]:
        """Returns (nominated_node_name, status) — the preemption hook."""
        raise NotImplementedError


class PreScorePlugin(Plugin):
    def pre_score(self, state: CycleState, pods: Sequence[Pod], nodes) -> Status:
        """Per-batch PreScore (runtime/framework.go:1052 semantics):
        Status.skip() disables the coupled Score for these pods."""
        return Status.success()


class ScorePlugin(Plugin):
    """Host-backed per-(pod, node) score with optional normalize."""

    def score(self, state: CycleState, pod: Pod, node_state) -> int:
        raise NotImplementedError

    def normalize(self, state: CycleState, pod: Pod, scores: List[int]) -> List[int]:
        return scores

    def score_relevant(self, pod: Pod) -> bool:
        """Cheap spec-only predicate: could this plugin's Score produce a
        non-constant contribution for the pod?  Lets the batch dispatcher
        keep the device fast paths when no host score applies."""
        return True


class DeviceScorePlugin(Plugin):
    """Device-backed score: contributes a normalized [P, N] int score."""

    def device_score(self, dc, db, feasible, ctx) -> Any:
        raise NotImplementedError


class ReservePlugin(Plugin):
    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        pass


class PermitPlugin(Plugin):
    def permit(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[Status, float]:
        """Returns (status, timeout_seconds); Wait parks the pod
        (waiting_pods_map semantics)."""
        return Status.success(), 0.0


class PreBindPlugin(Plugin):
    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        return Status.success()


class BindPlugin(Plugin):
    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """Status.skip() passes to the next bind plugin (interface.go)."""
        raise NotImplementedError


class PostBindPlugin(Plugin):
    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        pass


class EnqueueExtensions(Plugin):
    """EventsToRegister (interface.go): which cluster events can make a pod
    rejected by this plugin schedulable again."""

    def events_to_register(self) -> List["ClusterEventWithHint"]:
        return []


# ---------------------------------------------------------------------------
# Cluster events (framework/types.go:48-187)
# ---------------------------------------------------------------------------


class ActionType(enum.IntFlag):
    ADD = 1
    DELETE = 2
    UPDATE_NODE_ALLOCATABLE = 4
    UPDATE_NODE_LABEL = 8
    UPDATE_NODE_TAINT = 16
    UPDATE_NODE_CONDITION = 32
    UPDATE_NODE_ANNOTATION = 64
    UPDATE_POD_LABEL = 128
    UPDATE_POD_SCALE_DOWN = 256
    UPDATE_POD_TOLERATIONS = 512
    UPDATE_POD_SCHEDULING_GATES = 1024
    UPDATE = (
        UPDATE_NODE_ALLOCATABLE
        | UPDATE_NODE_LABEL
        | UPDATE_NODE_TAINT
        | UPDATE_NODE_CONDITION
        | UPDATE_NODE_ANNOTATION
        | UPDATE_POD_LABEL
        | UPDATE_POD_SCALE_DOWN
        | UPDATE_POD_TOLERATIONS
        | UPDATE_POD_SCHEDULING_GATES
    )
    ALL = ADD | DELETE | UPDATE


class EventResource(str, enum.Enum):
    POD = "Pod"
    ASSIGNED_POD = "AssignedPod"
    UNSCHEDULED_POD = "UnscheduledPod"
    NODE = "Node"
    PVC = "PersistentVolumeClaim"
    PV = "PersistentVolume"
    STORAGE_CLASS = "StorageClass"
    CSI_NODE = "CSINode"
    CSI_DRIVER = "CSIDriver"
    CSI_STORAGE_CAPACITY = "CSIStorageCapacity"
    RESOURCE_CLAIM = "ResourceClaim"
    RESOURCE_SLICE = "ResourceSlice"
    DEVICE_CLASS = "DeviceClass"
    POD_GROUP = "PodGroup"
    WILDCARD = "*"


@dataclass(frozen=True)
class ClusterEvent:
    resource: EventResource
    action: ActionType
    label: str = ""

    def match(self, other: "ClusterEvent") -> bool:
        res_ok = (
            self.resource == EventResource.WILDCARD
            or other.resource == EventResource.WILDCARD
            or self.resource == other.resource
        )
        return res_ok and bool(self.action & other.action)


class QueueingHint(enum.IntEnum):
    """QueueingHintFn result (types.go:145)."""

    SKIP = 0
    QUEUE = 1


# hint_fn(pod, old_obj, new_obj) -> QueueingHint
QueueingHintFn = Callable[[Pod, Any, Any], QueueingHint]


@dataclass
class ClusterEventWithHint:
    event: ClusterEvent
    hint_fn: Optional[QueueingHintFn] = None


WILDCARD_EVENT = ClusterEvent(EventResource.WILDCARD, ActionType.ALL)
