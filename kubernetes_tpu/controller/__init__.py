"""Controller tier: the non-scheduler control loops this build ships.

Only the loops that generate the scheduler's reactive events are in
scope (SURVEY §1 L5b): node lifecycle (NotReady → taint → evict).
"""

from kubernetes_tpu.controller.node_lifecycle import NodeLifecycleController

__all__ = ["NodeLifecycleController"]
