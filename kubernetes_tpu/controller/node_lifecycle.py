"""Node lifecycle controller: stale heartbeat → NotReady taint → evict.

The slice of pkg/controller/nodelifecycle/node_lifecycle_controller.go
that generates the scheduler's most important reactive events:

  * a node whose lastHeartbeatTime is older than the GRACE period is
    marked NotReady and tainted ``node.kubernetes.io/unreachable``
    with NoExecute (the controller's monitorNodeHealth + the taint
    manager's work, collapsed to one loop);
  * NoExecute taint-based eviction: pods bound to an unreachable node
    that don't tolerate the taint are DELETED (TaintManager's eviction;
    a workload controller recreates them as pending, and the scheduler
    places the replacements on healthy nodes);
  * a node that heartbeats again gets the taint removed and Ready
    restored.

Runs against the HTTP API tier through its own client + reflectors, like
a separate kube-controller-manager process would.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from kubernetes_tpu.api.types import Node, Pod, Taint

UNREACHABLE_TAINT_KEY = "node.kubernetes.io/unreachable"


_UNREACHABLE_TAINT = Taint(
    key=UNREACHABLE_TAINT_KEY, value="", effect="NoExecute"
)


def _tolerates_unreachable(pod: Pod) -> bool:
    """ToleratesTaint over the NoExecute unreachable taint — the taint
    manager's eviction predicate, via the shared Toleration semantics."""
    return any(t.tolerates(_UNREACHABLE_TAINT) for t in pod.tolerations)


class NodeLifecycleController:
    """monitorNodeHealth + taint-based eviction against the API tier."""

    def __init__(
        self,
        endpoint: str,
        grace_s: float = 40.0,
        tick_s: float = 1.0,
        clock=time.time,
        chaos_client=None,
    ):
        from kubernetes_tpu.client import ApiClient, Reflector

        # chaos_client: a fault-injecting ApiClient (chaos subsystem) so
        # the controller's own taint/evict writes ride the same failure
        # plan as the scheduler's reads
        self.client = chaos_client or ApiClient(endpoint)
        self.grace_s = grace_s
        self.tick_s = tick_s
        self.clock = clock
        self.nodes: Dict[str, Node] = {}
        self.pods_by_node: Dict[str, Dict[str, Pod]] = {}
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.tainted: set = set()
        self.evicted = 0

        def node_add(n: Node) -> None:
            with self._mu:
                self.nodes[n.name] = n

        def node_update(old: Node, new: Node) -> None:
            with self._mu:
                self.nodes[new.name] = new

        def node_delete(n: Node) -> None:
            with self._mu:
                self.nodes.pop(n.name, None)
                self.tainted.discard(n.name)

        def pod_add(p: Pod) -> None:
            if p.node_name:
                with self._mu:
                    self.pods_by_node.setdefault(p.node_name, {})[p.uid] = p

        def pod_update(old: Pod, new: Pod) -> None:
            with self._mu:
                if old.node_name and old.node_name != new.node_name:
                    self.pods_by_node.get(old.node_name, {}).pop(old.uid, None)
                if new.node_name:
                    self.pods_by_node.setdefault(new.node_name, {})[new.uid] = new

        def pod_delete(p: Pod) -> None:
            if p.node_name:
                with self._mu:
                    self.pods_by_node.get(p.node_name, {}).pop(p.uid, None)

        self._reflectors = [
            Reflector(self.client, "nodes", node_add, node_update, node_delete),
            Reflector(self.client, "pods", pod_add, pod_update, pod_delete),
        ]

    # ----- the loop --------------------------------------------------------

    def _tick(self) -> None:
        now = self.clock()
        with self._mu:
            nodes = list(self.nodes.values())
        for node in nodes:
            stale = (
                node.last_heartbeat > 0
                and now - node.last_heartbeat > self.grace_s
            )
            has_taint = any(
                t.key == UNREACHABLE_TAINT_KEY for t in node.taints
            )
            if stale and not has_taint:
                # NotReady: taint NoExecute + flip the Ready condition
                # (monitorNodeHealth → markNodeAsReachable's inverse) via
                # the ATOMIC taint patch — a full-object PUT from this
                # possibly-stale view would regress concurrent heartbeats
                try:
                    self.client.patch_node_taints(
                        node.name, add=[_UNREACHABLE_TAINT], ready=False
                    )
                    self.tainted.add(node.name)
                except Exception:  # noqa: BLE001 — server hiccup: next tick
                    continue
                self._evict(node.name)
            elif not stale and has_taint:
                # kubelet came back: lift the taint, restore Ready
                try:
                    self.client.patch_node_taints(
                        node.name,
                        remove_keys=[UNREACHABLE_TAINT_KEY],
                        ready=True,
                    )
                    self.tainted.discard(node.name)
                except Exception:  # noqa: BLE001
                    continue
            elif stale:
                # still down: keep evicting pods that landed or lingered
                self._evict(node.name)

    def _evict(self, node_name: str) -> None:
        """NoExecute eviction: delete non-tolerating pods on the node."""
        with self._mu:
            pods = list(self.pods_by_node.get(node_name, {}).values())
        for p in pods:
            if _tolerates_unreachable(p):
                continue
            try:
                self.client.delete_pod(p.uid)
                self.evicted += 1
            except Exception:  # noqa: BLE001 — already gone
                pass

    def tick(self) -> None:
        """One health-check pass — the deterministic drive surface the
        chaos runner uses instead of the wall-clock loop."""
        self._tick()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return all(r.synced.wait(timeout) for r in self._reflectors)

    def start(self, run_loop: bool = True) -> "NodeLifecycleController":
        for r in self._reflectors:
            r.start()
        if not run_loop:
            # reflectors only; the caller ticks the health check itself
            return self

        def loop():
            while not self._stop.wait(self.tick_s):
                try:
                    self._tick()
                except Exception:  # noqa: BLE001 — controller must survive
                    pass

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for r in self._reflectors:
            r.stop()
