"""Bridge: host cluster state → one packed, device-ready snapshot.

Mirrors what the reference's Cache.UpdateSnapshot produces (a consistent
NodeInfo list with per-node accounting, pkg/scheduler/backend/cache/cache.go:185)
as a single batch pack.  The incremental generation-based variant lives in
kubernetes_tpu.cache; this module is the from-scratch path used by tests,
bench setup, and cache re-sync.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import numpy as np

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.oracle.state import OracleState
from kubernetes_tpu.snapshot.interner import ABSENT, PAD, Vocab
from kubernetes_tpu.snapshot.schema import (
    MEM_UNIT,
    ExistingPodTensors,
    NodeTensors,
    ResourceLanes,
    bucket_cap,
    encode_port,
    pack_existing_pods,
    pack_nodes,
)


@dataclass
class PackedCluster:
    nodes: NodeTensors
    existing: ExistingPodTensors
    vocab: Vocab


def accumulate_node_usage(
    nt: NodeTensors,
    placed_pods: Sequence[Pod],
    vocab: Vocab,
) -> None:
    """Fold placed pods into per-node requested/non-zero/pod-count/port
    accounting (NodeInfo.AddPodInfo, framework/types.go:829)."""
    lanes = ResourceLanes(vocab)
    R = nt.allocatable.shape[1]
    nt.requested[:] = 0
    nt.nonzero_req[:] = 0
    nt.num_pods[:] = 0

    port_rows: Dict[int, list] = {}
    for pod in placed_pods:
        i = nt.name_to_idx.get(pod.node_name)
        if i is None:
            continue
        req = pod.compute_requests()
        nt.requested[i] += lanes.request_row(req, R)
        nz = req.non_zero_defaulted()
        nt.nonzero_req[i, 0] += nz.milli_cpu
        nt.nonzero_req[i, 1] += -(-nz.memory // MEM_UNIT)
        nt.num_pods[i] += 1
        for p in pod.host_ports():
            port_rows.setdefault(i, []).append(encode_port(vocab, p))

    U = bucket_cap(max((len(r) for r in port_rows.values()), default=1), 1)
    N = nt.n_cap
    nt.used_ppk = np.full((N, U), PAD, dtype=np.int32)
    nt.used_ip = np.full((N, U), PAD, dtype=np.int32)
    nt.used_wild = np.zeros((N, U), dtype=bool)
    for i, rows in port_rows.items():
        for j, (ppk, ip, wild) in enumerate(rows[:U]):
            nt.used_ppk[i, j] = ppk
            nt.used_ip[i, j] = ip
            nt.used_wild[i, j] = wild


def pack_cluster(
    state: OracleState,
    vocab: Optional[Vocab] = None,
    n_cap: Optional[int] = None,
    e_cap: Optional[int] = None,
    pending_pods: Sequence[Pod] = (),
) -> PackedCluster:
    """``pending_pods`` pre-interns the label keys of pods that will later be
    packed with pack_pod_batch against this snapshot, so the label-matrix
    width K covers every key carried by a real object.  (Selector-only keys
    need no column: an out-of-range key id reads as "label absent", which is
    exactly the right semantics.)"""
    vocab = vocab or Vocab()
    nodes = [ns.node for ns in state.nodes.values()]
    placed = state.all_pods()
    for p in list(placed) + list(pending_pods):
        for k, v in p.labels.items():
            vocab.intern_label(k, v)
        vocab.namespaces.intern(p.namespace)
    nt = pack_nodes(nodes, vocab, n_cap=n_cap)
    accumulate_node_usage(nt, placed, vocab)
    ep = pack_existing_pods(
        placed,
        nt.name_to_idx,
        vocab,
        e_cap=e_cap,
        k_cap=nt.k_cap,
        namespace_labels=state.namespace_labels,
    )
    return PackedCluster(nodes=nt, existing=ep, vocab=vocab)
