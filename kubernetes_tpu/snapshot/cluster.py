"""Bridge: host cluster state → one packed, device-ready snapshot.

Mirrors what the reference's Cache.UpdateSnapshot produces (a consistent
NodeInfo list with per-node accounting, pkg/scheduler/backend/cache/cache.go:185)
as a single batch pack.  The incremental generation-based variant lives in
kubernetes_tpu.cache; this module is the from-scratch path used by tests,
bench setup, and cache re-sync.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import numpy as np

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.oracle.state import OracleState
from kubernetes_tpu.snapshot.interner import ABSENT, PAD, Vocab
from kubernetes_tpu.snapshot.schema import (
    MEM_UNIT,
    ExistingPodTensors,
    NodeTensors,
    ResourceLanes,
    bucket_cap,
    encode_port,
    pack_existing_pods,
    pack_nodes,
)


@dataclass
class PackedCluster:
    nodes: NodeTensors
    existing: ExistingPodTensors
    vocab: Vocab


def accumulate_node_usage(
    nt: NodeTensors,
    placed_pods: Sequence[Pod],
    vocab: Vocab,
) -> None:
    """Fold placed pods into per-node requested/non-zero/pod-count/port
    accounting (NodeInfo.AddPodInfo, framework/types.go:829).

    Batched: per-pod request rows are built once per DISTINCT memoized
    request object (pods stamped from one template share it — the 100k-pod
    full-pack shape) and folded into the per-node accumulators with one
    np.add.at sweep per tensor instead of a numpy row-add per pod."""
    lanes = ResourceLanes(vocab)
    R = nt.allocatable.shape[1]
    nt.requested[:] = 0
    nt.nonzero_req[:] = 0
    nt.num_pods[:] = 0

    port_rows: Dict[int, list] = {}
    idxs: list = []
    rows: list = []
    nz_rows: list = []
    row_cache: Dict[int, tuple] = {}
    name_to_idx = nt.name_to_idx
    for pod in placed_pods:
        i = name_to_idx.get(pod.node_name)
        if i is None:
            continue
        req = pod.compute_requests()
        ent = row_cache.get(id(req))
        if ent is None:
            nz = req.non_zero_defaulted()
            ent = row_cache[id(req)] = (
                lanes.request_row(req, R),
                (nz.milli_cpu, -(-nz.memory // MEM_UNIT)),
            )
        idxs.append(i)
        rows.append(ent[0])
        nz_rows.append(ent[1])
        for p in pod.host_ports():
            port_rows.setdefault(i, []).append(encode_port(vocab, p))
    if idxs:
        ii = np.asarray(idxs, np.intp)
        np.add.at(nt.requested, ii, np.stack(rows))
        np.add.at(nt.nonzero_req, ii, np.asarray(nz_rows, nt.nonzero_req.dtype))
        np.add.at(nt.num_pods, ii, 1)

    U = bucket_cap(max((len(r) for r in port_rows.values()), default=1), 1)
    N = nt.n_cap
    nt.used_ppk = np.full((N, U), PAD, dtype=np.int32)
    nt.used_ip = np.full((N, U), PAD, dtype=np.int32)
    nt.used_wild = np.zeros((N, U), dtype=bool)
    for i, rows in port_rows.items():
        for j, (ppk, ip, wild) in enumerate(rows[:U]):
            nt.used_ppk[i, j] = ppk
            nt.used_ip[i, j] = ip
            nt.used_wild[i, j] = wild


def pack_cluster(
    state: OracleState,
    vocab: Optional[Vocab] = None,
    n_cap: Optional[int] = None,
    e_cap: Optional[int] = None,
    pending_pods: Sequence[Pod] = (),
) -> PackedCluster:
    """``pending_pods`` pre-interns the label keys of pods that will later be
    packed with pack_pod_batch against this snapshot, so the label-matrix
    width K covers every key carried by a real object.  (Selector-only keys
    need no column: an out-of-range key id reads as "label absent", which is
    exactly the right semantics.)"""
    vocab = vocab or Vocab()
    nodes = [ns.node for ns in state.nodes.values()]
    placed = state.all_pods()
    for p in list(placed) + list(pending_pods):
        for k, v in p.labels.items():
            vocab.intern_label(k, v)
        vocab.namespaces.intern(p.namespace)
    nt = pack_nodes(nodes, vocab, n_cap=n_cap)
    accumulate_node_usage(nt, placed, vocab)
    ep = pack_existing_pods(
        placed,
        nt.name_to_idx,
        vocab,
        e_cap=e_cap,
        k_cap=nt.k_cap,
        namespace_labels=state.namespace_labels,
    )
    return PackedCluster(nodes=nt, existing=ep, vocab=vocab)
