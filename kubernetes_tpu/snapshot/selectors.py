"""Selector compilation: API selector trees → interned requirement rows.

A *conjunction* (CompiledRequirements) is the unit: a LabelSelector compiles
to one conjunction; a NodeSelector (OR of terms) compiles to a list of them
(DNF).  The schema packer pads these into dense int32 tensors; the kernels
evaluate them with pure vectorized compares (kubernetes_tpu/ops/selectors.py).

Node field selectors (metadata.name) are folded into the label tables: every
packed node carries an implicit pseudo-label ``metadata.name`` → its name, so
matchFields evaluates through the same path as matchExpressions (the
reference special-cases this in component-helpers nodeaffinity; we make it
uniform, which also preserves the O(1) PreFilterResult narrowing as a plain
mask).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from kubernetes_tpu.api import labels as k8slabels
from kubernetes_tpu.api.types import (
    LabelSelector,
    NodeSelector,
    NodeSelectorTerm,
)
from kubernetes_tpu.snapshot.interner import INT_INVALID, PAD, Vocab

OP_IN = 0
OP_NOT_IN = 1
OP_EXISTS = 2
OP_DOES_NOT_EXIST = 3
OP_GT = 4
OP_LT = 5

_OP_CODE = {
    k8slabels.IN: OP_IN,
    k8slabels.NOT_IN: OP_NOT_IN,
    k8slabels.EXISTS: OP_EXISTS,
    k8slabels.DOES_NOT_EXIST: OP_DOES_NOT_EXIST,
    k8slabels.GT: OP_GT,
    k8slabels.LT: OP_LT,
}

METADATA_NAME_KEY = "metadata.name"


@dataclass
class CompiledRequirements:
    """One conjunction of interned requirements.

    ``match_nothing`` encodes both the nil-LabelSelector case and the empty
    NodeSelectorTerm case.  With no requirements and not match_nothing, the
    conjunction matches everything.
    """

    keys: List[int] = field(default_factory=list)
    ops: List[int] = field(default_factory=list)
    vals: List[List[int]] = field(default_factory=list)  # per-req value-id set
    rhs_int: List[int] = field(default_factory=list)  # Gt/Lt right-hand side
    match_nothing: bool = False

    def add(self, key: str, op: str, values: Sequence[str], vocab: Vocab) -> None:
        self.keys.append(vocab.label_keys.intern(key))
        code = _OP_CODE[op]
        self.ops.append(code)
        self.vals.append([vocab.intern_val(v) for v in values])
        if code in (OP_GT, OP_LT) and values:
            try:
                self.rhs_int.append(int(values[0]))
            except ValueError:
                self.rhs_int.append(INT_INVALID)
        else:
            self.rhs_int.append(0)

    @property
    def n_reqs(self) -> int:
        return len(self.keys)


MATCH_NOTHING = CompiledRequirements(match_nothing=True)
MATCH_EVERYTHING = CompiledRequirements()


def compile_label_selector(
    ls: Optional[LabelSelector], vocab: Vocab
) -> CompiledRequirements:
    """LabelSelector → one conjunction (None ⇒ match nothing)."""
    if ls is None:
        return CompiledRequirements(match_nothing=True)
    c = CompiledRequirements()
    if ls.match_labels:
        for k, v in sorted(ls.match_labels.items()):
            c.add(k, k8slabels.IN, (v,), vocab)
    for e in ls.match_expressions or ():
        c.add(e.key, e.operator, tuple(e.values or ()), vocab)
    return c


def compile_node_selector_term(
    term: NodeSelectorTerm, vocab: Vocab
) -> CompiledRequirements:
    if not term.match_expressions and not term.match_fields:
        return CompiledRequirements(match_nothing=True)
    c = CompiledRequirements()
    for e in term.match_expressions:
        c.add(e.key, e.operator, tuple(e.values), vocab)
    for f in term.match_fields:
        # Only metadata.name In/NotIn are valid field selectors; anything else
        # can never match (api validation rejects it anyway).
        if f.key != METADATA_NAME_KEY or f.operator not in (
            k8slabels.IN,
            k8slabels.NOT_IN,
        ):
            return CompiledRequirements(match_nothing=True)
        c.add(METADATA_NAME_KEY, f.operator, tuple(f.values), vocab)
    return c


def compile_node_selector_dnf(
    sel: Optional[NodeSelector], vocab: Vocab
) -> List[CompiledRequirements]:
    """NodeSelector → DNF (list of ORed conjunctions).

    Returns [] for None (caller treats as "no constraint").
    """
    if sel is None:
        return []
    return [compile_node_selector_term(t, vocab) for t in sel.node_selector_terms]


def compile_match_labels_conjunction(
    match_labels: Optional[dict], vocab: Vocab
) -> CompiledRequirements:
    """pod.spec.nodeSelector (plain map) → conjunction."""
    c = CompiledRequirements()
    for k, v in sorted((match_labels or {}).items()):
        c.add(k, k8slabels.IN, (v,), vocab)
    return c
