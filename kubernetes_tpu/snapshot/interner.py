"""String interning tables.

Every string the kernels touch becomes a dense int32 id.  Separate namespaces
keep the hot tables small:

- label *keys* index the columns of the per-node / per-pod dense label-value
  matrices, so their id space must stay compact;
- label *values* share one table, with a side array of parsed-integer values
  to support Gt/Lt selector operators on device;
- namespaces and extended-resource names get their own tables.

Interners are append-only: ids are stable for the life of the process, which
is what lets the HBM mirror be updated incrementally (a label seen once keeps
its column forever).  Sentinels: -1 = "absent", -2 = "padding".
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

ABSENT = -1
PAD = -2

# Sentinel for label values that don't parse as integers (Gt/Lt never match).
INT_INVALID = -(2**31) + 1


class Interner:
    """Append-only str → int32 id table."""

    def __init__(self) -> None:
        self._ids: Dict[str, int] = {}
        self._strs: List[str] = []

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strs)
            self._ids[s] = i
            self._strs.append(s)
        return i

    def lookup(self, s: str) -> int:
        """Id for s, or ABSENT if never interned (read-only path)."""
        return self._ids.get(s, ABSENT)

    def string(self, i: int) -> str:
        return self._strs[i]

    def __len__(self) -> int:
        return len(self._strs)

    def __contains__(self, s: str) -> bool:
        return s in self._ids


def _parse_label_int(s: str) -> int:
    """Label value as integer for Gt/Lt, or INT_INVALID."""
    try:
        v = int(s)
    except ValueError:
        return INT_INVALID
    # Clamp into int32 so device compares stay valid.
    return max(min(v, 2**31 - 1), -(2**31) + 2)


@dataclass
class Vocab:
    """The full interning state shared by cache, snapshot and kernels."""

    label_keys: Interner = field(default_factory=Interner)
    label_vals: Interner = field(default_factory=Interner)
    namespaces: Interner = field(default_factory=Interner)
    resources: Interner = field(default_factory=Interner)  # extended resources
    node_names: Interner = field(default_factory=Interner)
    ports: Interner = field(default_factory=Interner)  # "proto:port" and host IPs
    images: Interner = field(default_factory=Interner)  # container image names

    # Parsed-integer view of label_vals (same indexing), grown lazily.
    _val_ints: List[int] = field(default_factory=list)

    def intern_label(self, key: str, val: str) -> tuple[int, int]:
        return self.label_keys.intern(key), self.intern_val(val)

    def intern_val(self, val: str) -> int:
        i = self.label_vals.intern(val)
        while len(self._val_ints) < len(self.label_vals):
            self._val_ints.append(
                _parse_label_int(self.label_vals.string(len(self._val_ints)))
            )
        return i

    def val_ints(self) -> List[int]:
        """Dense id → parsed-int table (len == len(label_vals))."""
        while len(self._val_ints) < len(self.label_vals):
            self._val_ints.append(
                _parse_label_int(self.label_vals.string(len(self._val_ints)))
            )
        return self._val_ints
