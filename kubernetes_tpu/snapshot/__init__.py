"""String interning and packed device-tensor schema for the cluster snapshot.

This is the TPU-native replacement for the reference's
pkg/scheduler/backend/cache/snapshot.go: instead of a list of Go NodeInfo
structs, the cluster state lives as padded int32/float32 arrays in HBM.
Everything string-shaped (label keys/values, namespaces, taint keys,
resource names) is interned to dense int ids (SURVEY.md §7.1).
"""

from kubernetes_tpu.snapshot.interner import Interner, Vocab  # noqa: F401
from kubernetes_tpu.snapshot.selectors import (  # noqa: F401
    OP_IN,
    OP_NOT_IN,
    OP_EXISTS,
    OP_DOES_NOT_EXIST,
    OP_GT,
    OP_LT,
    CompiledRequirements,
    compile_node_selector_dnf,
    compile_label_selector,
)
from kubernetes_tpu.snapshot.schema import (  # noqa: F401
    NodeTensors,
    ExistingPodTensors,
    PodBatch,
    ResourceLanes,
    pack_nodes,
    pack_existing_pods,
    pack_pod_batch,
)
