"""Packed tensor schema for the cluster snapshot and pod batches.

The reference's Snapshot (pkg/scheduler/backend/cache/snapshot.go:29) is a
list of NodeInfo structs; here it is a struct-of-arrays, padded to capacity
and ready for HBM:

  NodeTensors          per-node resources/labels/taints/flags     [N, …]
  ExistingPodTensors   per placed-pod labels/namespace/node index [E, …]
  PodBatch             per pending-pod requests + compiled
                       selector/toleration/affinity/spread terms  [P, …]

Conventions:
  - int32 everywhere; ABSENT = -1 (missing label), PAD = -2 (unused slot).
  - resource lanes: 0=cpu millicores, 1=memory MiB, 2=ephemeral MiB, then one
    lane per extended resource (vocab.resources).  Requests round *up*,
    allocatable rounds *down* — feasibility on device is conservative within
    1MiB (real workloads are Mi-aligned so decisions match the reference);
    MiB units keep multi-TiB hosts inside int32 (up to 2048 TiB).  Extended
    resource counts are clamped into int32.
  - capacities are bucketed to powers of two so recurring pack calls hit the
    same XLA program (static shapes; SURVEY.md §7 "dynamic shapes").
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import (
    Node,
    Pod,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE,
    TOLERATION_OP_EXISTS,
    DO_NOT_SCHEDULE,
    NODE_INCLUSION_HONOR,
)
from kubernetes_tpu.snapshot.interner import ABSENT, PAD, Vocab
from kubernetes_tpu.snapshot.selectors import (
    METADATA_NAME_KEY,
    CompiledRequirements,
    compile_label_selector,
    compile_match_labels_conjunction,
    compile_node_selector_dnf,
)

# Resource lanes
LANE_CPU = 0
LANE_MEM = 1
LANE_EPH = 2
N_FIXED_LANES = 3

MEM_UNIT = 1 << 20  # memory/ephemeral lane granularity: 1 MiB
_I32_MAX = 2**31 - 1


def _i32(v: int) -> int:
    return min(v, _I32_MAX)

# Taint effects
EFFECT_NO_SCHEDULE = 0
EFFECT_PREFER_NO_SCHEDULE = 1
EFFECT_NO_EXECUTE = 2
EFFECT_ALL = -1  # toleration with empty effect

_EFFECT_CODE = {
    TAINT_NO_SCHEDULE: EFFECT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE: EFFECT_PREFER_NO_SCHEDULE,
    TAINT_NO_EXECUTE: EFFECT_NO_EXECUTE,
}

TOL_OP_EQUAL = 0
TOL_OP_EXISTS = 1

# Inter-pod affinity term kinds
TERM_REQUIRED_AFFINITY = 0
TERM_REQUIRED_ANTI = 1
TERM_PREFERRED_AFFINITY = 2
TERM_PREFERRED_ANTI = 3


def bucket_cap(n: int, minimum: int = 8) -> int:
    """Round up to a stable bucket: powers of two up to 2048, then multiples
    of 1024 (pure pow2 wastes up to 2× at cluster scale — 5000 nodes would
    pad to 8192; this pads to 5120)."""
    n = max(n, minimum, 1)
    if n <= 2048:
        return 1 << math.ceil(math.log2(n))
    return -(-n // 1024) * 1024


# ---------------------------------------------------------------------------
# Resource lanes
# ---------------------------------------------------------------------------


class ResourceLanes:
    """Maps Resource structs onto fixed int32 lanes (see module docstring)."""

    def __init__(self, vocab: Vocab):
        self.vocab = vocab

    @property
    def n_lanes(self) -> int:
        return N_FIXED_LANES + len(self.vocab.resources)

    def request_row(self, r: Resource, n_lanes: Optional[int] = None) -> np.ndarray:
        row = np.zeros(n_lanes or self.n_lanes, dtype=np.int32)
        row[LANE_CPU] = _i32(r.milli_cpu)
        row[LANE_MEM] = _i32(-(-r.memory // MEM_UNIT))  # ceil MiB
        row[LANE_EPH] = _i32(-(-r.ephemeral_storage // MEM_UNIT))
        for name, v in r.scalars.items():
            lane = N_FIXED_LANES + self.vocab.resources.intern(name)
            if lane < len(row):
                row[lane] = _i32(v)
        return row

    def allocatable_row(self, r: Resource, n_lanes: Optional[int] = None) -> np.ndarray:
        row = np.zeros(n_lanes or self.n_lanes, dtype=np.int32)
        row[LANE_CPU] = _i32(r.milli_cpu)
        row[LANE_MEM] = _i32(r.memory // MEM_UNIT)  # floor MiB
        row[LANE_EPH] = _i32(r.ephemeral_storage // MEM_UNIT)
        for name, v in r.scalars.items():
            lane = N_FIXED_LANES + self.vocab.resources.intern(name)
            if lane < len(row):
                row[lane] = _i32(v)
        return row


# ---------------------------------------------------------------------------
# Conjunction tables (shared by node-selector / spread / inter-pod kernels)
# ---------------------------------------------------------------------------


@dataclass
class ConjunctionTable:
    """Padded DNF: [P, T] terms of [R] requirements with [V]-value sets.

    term_valid=False covers both padding and match-nothing terms.  A padded
    requirement slot (op == PAD) evaluates to True inside a valid term.
    """

    req_key: np.ndarray  # i32 [P, T, R]
    req_op: np.ndarray  # i32 [P, T, R]
    req_vals: np.ndarray  # i32 [P, T, R, V]
    req_rhs: np.ndarray  # i32 [P, T, R]
    term_valid: np.ndarray  # bool [P, T]


def pack_conjunction_table(
    per_row_terms: Sequence[Sequence[CompiledRequirements]],
    t_cap: Optional[int] = None,
    r_cap: Optional[int] = None,
    v_cap: Optional[int] = None,
) -> ConjunctionTable:
    p = len(per_row_terms)
    t_need = max((len(ts) for ts in per_row_terms), default=1) or 1
    r_need = max(
        (c.n_reqs for ts in per_row_terms for c in ts), default=1
    ) or 1
    v_need = max(
        (len(vs) for ts in per_row_terms for c in ts for vs in c.vals), default=1
    ) or 1
    T = t_cap or bucket_cap(t_need, 1)
    R = r_cap or bucket_cap(r_need, 1)
    V = v_cap or bucket_cap(v_need, 1)

    req_key = np.full((p, T, R), PAD, dtype=np.int32)
    req_op = np.full((p, T, R), PAD, dtype=np.int32)
    req_vals = np.full((p, T, R, V), PAD, dtype=np.int32)
    req_rhs = np.zeros((p, T, R), dtype=np.int32)
    term_valid = np.zeros((p, T), dtype=bool)

    for i, terms in enumerate(per_row_terms):
        for j, c in enumerate(terms[:T]):
            if c.match_nothing:
                continue
            term_valid[i, j] = True
            for k in range(min(c.n_reqs, R)):
                req_key[i, j, k] = c.keys[k]
                req_op[i, j, k] = c.ops[k]
                req_rhs[i, j, k] = c.rhs_int[k]
                for m, v in enumerate(c.vals[k][:V]):
                    req_vals[i, j, k, m] = v
    return ConjunctionTable(req_key, req_op, req_vals, req_rhs, term_valid)


# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------


@dataclass
class NodeTensors:
    """Struct-of-arrays node snapshot (the HBM mirror of []NodeInfo)."""

    allocatable: np.ndarray  # i32 [N, R]
    requested: np.ndarray  # i32 [N, R]  (by scheduled+assumed pods)
    nonzero_req: np.ndarray  # i32 [N, 2] cpu,mem with spreading defaults
    num_pods: np.ndarray  # i32 [N]
    allowed_pods: np.ndarray  # i32 [N]
    label_vals: np.ndarray  # i32 [N, K]  val id or ABSENT
    val_ints: np.ndarray  # i32 [Vv]     label-val id → parsed int
    taint_key: np.ndarray  # i32 [N, T]
    taint_val: np.ndarray  # i32 [N, T]
    taint_effect: np.ndarray  # i32 [N, T]
    unschedulable: np.ndarray  # bool [N]
    valid: np.ndarray  # bool [N]
    # host-port usage by placed pods: interned (proto:port) id, host-ip id,
    # and whether the ip is the 0.0.0.0 wildcard (NodeInfo.UsedPorts)
    used_ppk: np.ndarray = None  # i32 [N, U]
    used_ip: np.ndarray = None  # i32 [N, U]
    used_wild: np.ndarray = None  # bool [N, U]
    # image id → size bytes present on node (NodeInfo.ImageStates)
    img_sizes: np.ndarray = None  # i64 [N, IMG]
    # zone-round-robin visit rank (node_tree.go ordering; -1 invalid) —
    # packed SLOTS stay stable for delta uploads, order-sensitive paths
    # (sampling windows, rotation, compat tie-breaks) read this instead
    visit_rank: np.ndarray = None  # i32 [N]
    names: List[str] = field(default_factory=list)
    name_to_idx: Dict[str, int] = field(default_factory=dict)

    @property
    def n_cap(self) -> int:
        return self.allocatable.shape[0]

    @property
    def k_cap(self) -> int:
        return self.label_vals.shape[1]


def _node_label_row(node: Node, vocab: Vocab, k_cap: int) -> np.ndarray:
    row = np.full(k_cap, ABSENT, dtype=np.int32)
    for k, v in node.labels.items():
        ki, vi = vocab.intern_label(k, v)
        if ki < k_cap:
            row[ki] = vi
    ki, vi = vocab.intern_label(METADATA_NAME_KEY, node.name)
    if ki < k_cap:
        row[ki] = vi
    return row


def pack_nodes(
    nodes: Sequence[Node],
    vocab: Vocab,
    n_cap: Optional[int] = None,
    k_cap: Optional[int] = None,
    t_cap: Optional[int] = None,
    n_multiple: int = 1,
) -> NodeTensors:
    # Intern everything first so capacities cover the content.
    for node in nodes:
        for k, v in node.labels.items():
            vocab.intern_label(k, v)
        vocab.intern_label(METADATA_NAME_KEY, node.name)
        for t in node.taints:
            vocab.label_keys.intern(t.key)
            vocab.intern_val(t.value)
        for name in node.allocatable.scalars:
            vocab.resources.intern(name)
        for img in node.images:
            vocab.images.intern(img)

    # n_multiple: device-mesh nodes-axis divisibility — the node bucket
    # must split evenly across shards (parallel/mesh.py cluster_shardings
    # ASSERTS it rather than silently replicating).  Power-of-two buckets
    # already satisfy power-of-two meshes; this covers the rest (e.g. a
    # 3-wide nodes axis on 6 devices).
    N = n_cap or -(-bucket_cap(len(nodes)) // max(n_multiple, 1)) * max(
        n_multiple, 1
    )
    K = k_cap or bucket_cap(len(vocab.label_keys))
    T = t_cap or bucket_cap(max((len(n.taints) for n in nodes), default=1), 1)
    lanes = ResourceLanes(vocab)
    R = bucket_cap(lanes.n_lanes, 4)

    nt = NodeTensors(
        allocatable=np.zeros((N, R), dtype=np.int32),
        requested=np.zeros((N, R), dtype=np.int32),
        nonzero_req=np.zeros((N, 2), dtype=np.int32),
        num_pods=np.zeros(N, dtype=np.int32),
        allowed_pods=np.zeros(N, dtype=np.int32),
        label_vals=np.full((N, K), ABSENT, dtype=np.int32),
        # bucket-padded: an unbucketed table would change shape on EVERY
        # new label value (e.g. each added node's hostname), recompiling
        # every consumer of the cluster snapshot
        val_ints=_padded_val_ints(vocab),
        taint_key=np.full((N, T), PAD, dtype=np.int32),
        taint_val=np.full((N, T), PAD, dtype=np.int32),
        taint_effect=np.full((N, T), PAD, dtype=np.int32),
        unschedulable=np.zeros(N, dtype=bool),
        valid=np.zeros(N, dtype=bool),
        used_ppk=np.full((N, 1), PAD, dtype=np.int32),
        used_ip=np.full((N, 1), PAD, dtype=np.int32),
        used_wild=np.zeros((N, 1), dtype=bool),
        img_sizes=np.zeros((N, bucket_cap(len(vocab.images), 1)), dtype=np.int64),
        visit_rank=np.full(N, -1, dtype=np.int32),
    )
    for i, node in enumerate(nodes[:N]):
        write_node_row(nt, i, node, vocab)
    refresh_visit_rank(nt, nodes[:N])
    return nt


def refresh_visit_rank(
    nt: NodeTensors, nodes: Sequence[Node], slots: Optional[Sequence[int]] = None
) -> None:
    """Recompute the zone-round-robin visit ranks (node_tree.go:119-143
    ordering; see kubernetes_tpu.util.nodetree).  ``slots[i]`` is node i's
    packed row (defaults to 0..n-1, the fresh-pack layout); delta updates
    pass the name_to_idx-resolved slots since removals leave holes."""
    from kubernetes_tpu.util.nodetree import ZONE_LABEL, node_tree_order

    nt.visit_rank[:] = -1
    order = node_tree_order([n.labels.get(ZONE_LABEL) for n in nodes])
    if slots is None:
        for rank, i in enumerate(order):
            nt.visit_rank[i] = rank
    else:
        for rank, i in enumerate(order):
            nt.visit_rank[slots[i]] = rank


def _padded_val_ints(vocab: Vocab) -> np.ndarray:
    """label-val id → parsed int, padded to the value-vocab bucket (new
    values within the bucket get INT_INVALID rows until the next pack —
    the mirror's val-growth check forces that pack before Gt/Lt reads)."""
    from kubernetes_tpu.snapshot.interner import INT_INVALID

    raw = np.asarray(vocab.val_ints(), dtype=np.int32)
    cap = bucket_cap(max(len(raw), 1))
    out = np.full(cap, INT_INVALID, dtype=np.int32)
    out[: len(raw)] = raw
    return out


def write_node_row(nt: NodeTensors, i: int, node: Node, vocab: Vocab) -> bool:
    """(Re)pack one node into row i — the incremental-update primitive.

    Returns False when any slot axis (labels, resource lanes, taints,
    images) truncated the node's content: the caller must force a full
    repack at grown bucket sizes before scheduling against the snapshot.
    """
    fits = True
    lanes = ResourceLanes(vocab)
    R = nt.allocatable.shape[1]
    nt.allocatable[i] = lanes.allocatable_row(node.allocatable, R)
    if lanes.n_lanes > R:  # after allocatable_row interned new scalars
        fits = False
    nt.allowed_pods[i] = node.allocatable.allowed_pod_number or 110
    nt.label_vals[i] = _node_label_row(node, vocab, nt.k_cap)
    if any(
        vocab.intern_label(k, v)[0] >= nt.k_cap for k, v in node.labels.items()
    ):
        fits = False
    if len(vocab.label_vals) > nt.val_ints.shape[0]:
        # new label VALUE ids outrun the packed parsed-int table's BUCKET —
        # Gt/Lt selector evaluation would read stale entries
        fits = False
    else:
        # in-place refresh of parsed ints for values interned since the
        # pack (within the bucket) — keeps incremental node adds cheap
        ints = vocab.val_ints()
        if len(ints) <= nt.val_ints.shape[0]:
            nt.val_ints[: len(ints)] = ints
    T = nt.taint_key.shape[1]
    if len(node.taints) > T:
        fits = False
    nt.taint_key[i] = PAD
    nt.taint_val[i] = PAD
    nt.taint_effect[i] = PAD
    for j, t in enumerate(node.taints[:T]):
        nt.taint_key[i, j] = vocab.label_keys.intern(t.key)
        nt.taint_val[i, j] = vocab.intern_val(t.value)
        nt.taint_effect[i, j] = _EFFECT_CODE.get(t.effect, EFFECT_NO_SCHEDULE)
    nt.unschedulable[i] = node.unschedulable
    nt.valid[i] = True
    IMG = nt.img_sizes.shape[1]
    nt.img_sizes[i] = 0
    for img, size in node.images.items():
        ii = vocab.images.intern(img)
        if ii < IMG:
            nt.img_sizes[i, ii] = size
        else:
            fits = False
    if i < len(nt.names):
        old = nt.names[i]
        if old in nt.name_to_idx and old != node.name:
            del nt.name_to_idx[old]
        nt.names[i] = node.name
    else:
        while len(nt.names) < i:
            nt.names.append("")
        nt.names.append(node.name)
    nt.name_to_idx[node.name] = i
    return fits


# ---------------------------------------------------------------------------
# Existing (placed) pods
# ---------------------------------------------------------------------------


@dataclass
class ExistingPodTensors:
    """Placed pods (scheduled or assumed) — the quadratic-kernel operand."""

    node_idx: np.ndarray  # i32 [E]  (ABSENT = empty slot)
    ns_id: np.ndarray  # i32 [E]
    label_vals: np.ndarray  # i32 [E, K]
    valid: np.ndarray  # bool [E]
    deleting: np.ndarray  # bool [E]  (deletionTimestamp set)
    # All (anti-)affinity terms of existing pods, flattened to rows — the
    # generalization of HavePodsWithAffinityList /
    # HavePodsWithRequiredAntiAffinityList (snapshot.go:34).  kind is TERM_*;
    # weight is nonzero for preferred terms (and the hard-pod-affinity weight
    # for required affinity, applied by the score kernel).
    term_pod: np.ndarray  # i32 [M]  → index into E (ABSENT = padding)
    term_kind: np.ndarray  # i32 [M]  TERM_* or PAD
    term_topo_key: np.ndarray  # i32 [M]
    term_weight: np.ndarray  # i32 [M]
    term_table: ConjunctionTable  # [M, 1, R, V] label-selector conjunction
    term_ns_all: np.ndarray  # bool [M]  (empty namespaceSelector ⇒ all)
    term_ns_ids: np.ndarray  # i32 [M, NS]
    keys: List[str] = field(default_factory=list)

    @property
    def e_cap(self) -> int:
        return self.node_idx.shape[0]


def _pod_label_row(pod: Pod, vocab: Vocab, k_cap: int) -> np.ndarray:
    row = np.full(k_cap, ABSENT, dtype=np.int32)
    for k, v in pod.labels.items():
        ki, vi = vocab.intern_label(k, v)
        if ki < k_cap:
            row[ki] = vi
    return row


def resolve_term_namespaces(
    term, pod: Pod, vocab: Vocab, namespace_labels: Optional[Dict[str, Dict[str, str]]]
) -> Tuple[bool, List[int]]:
    """PodAffinityTerm namespace set → (all_namespaces, ns_id list).

    Defaults to the pod's own namespace when neither namespaces nor
    namespaceSelector are set (GetNamespaceLabelsSnapshot semantics).
    A present-but-empty namespaceSelector selects ALL namespaces.
    """
    ns_ids = [vocab.namespaces.intern(n) for n in (term.namespaces or ())]
    sel = term.namespace_selector
    if sel is not None:
        from kubernetes_tpu.api.labels import selector_from_label_selector

        s = selector_from_label_selector(sel)
        if s.empty:
            return True, []
        for ns_name, labels in (namespace_labels or {}).items():
            if s.matches(labels):
                ns_ids.append(vocab.namespaces.intern(ns_name))
    if not ns_ids and sel is None:
        ns_ids = [vocab.namespaces.intern(pod.namespace)]
    return False, sorted(set(ns_ids))


def iter_pod_affinity_terms(pod: Pod, vocab: Vocab, namespace_labels):
    """Every (anti-)affinity term of a pod, flattened and compiled:
    yields (compiled_selector, kind, topo_key_id, weight, ns_all, ns_ids).

    The single source of truth for term flattening — used for both placed
    pods (pack_existing_pods) and pending batches (pack_pod_batch), mirroring
    the reference's shared AffinityTerm pre-parsing (framework/types.go:350).
    """
    if not pod.affinity:
        return
    groups = []
    if pod.affinity.pod_affinity:
        pa = pod.affinity.pod_affinity
        groups.append(
            (pa.required_during_scheduling_ignored_during_execution, TERM_REQUIRED_AFFINITY, False)
        )
        groups.append(
            (pa.preferred_during_scheduling_ignored_during_execution, TERM_PREFERRED_AFFINITY, True)
        )
    if pod.affinity.pod_anti_affinity:
        pa = pod.affinity.pod_anti_affinity
        groups.append(
            (pa.required_during_scheduling_ignored_during_execution, TERM_REQUIRED_ANTI, False)
        )
        groups.append(
            (pa.preferred_during_scheduling_ignored_during_execution, TERM_PREFERRED_ANTI, True)
        )
    for terms, kind, weighted in groups:
        for t in terms:
            term = t.pod_affinity_term if weighted else t
            compiled = compile_label_selector(term.label_selector, vocab)
            topo = vocab.label_keys.intern(term.topology_key)
            weight = t.weight if weighted else 0
            ns_all, ns_ids = resolve_term_namespaces(
                term, pod, vocab, namespace_labels
            )
            yield compiled, kind, topo, weight, ns_all, ns_ids


def pack_existing_pods(
    pods: Sequence[Pod],
    node_name_to_idx: Dict[str, int],
    vocab: Vocab,
    e_cap: Optional[int] = None,
    k_cap: Optional[int] = None,
    namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
    m_cap: Optional[int] = None,
) -> ExistingPodTensors:
    """``e_cap``/``m_cap`` hints pre-size the pod/term axes: every distinct
    (E, M) shape costs an XLA recompile of the gang pipeline, so callers
    that can predict growth (queue pressure) should size ONCE."""
    for pod in pods:
        for k, v in pod.labels.items():
            vocab.intern_label(k, v)
        vocab.namespaces.intern(pod.namespace)

    E = max(e_cap or 0, bucket_cap(len(pods)))
    K = k_cap or bucket_cap(len(vocab.label_keys))

    node_idx = np.full(E, ABSENT, dtype=np.int32)
    ns_id = np.full(E, ABSENT, dtype=np.int32)
    label_vals = np.full((E, K), ABSENT, dtype=np.int32)
    valid = np.zeros(E, dtype=bool)
    deleting = np.zeros(E, dtype=bool)
    keys: List[str] = []

    rows: List[CompiledRequirements] = []
    r_pod: List[int] = []
    r_kind: List[int] = []
    r_topo: List[int] = []
    r_weight: List[int] = []
    r_all: List[bool] = []
    r_ns: List[List[int]] = []

    for i, pod in enumerate(pods[:E]):
        node_idx[i] = node_name_to_idx.get(pod.node_name, ABSENT)
        ns_id[i] = vocab.namespaces.intern(pod.namespace)
        label_vals[i] = _pod_label_row(pod, vocab, K)
        valid[i] = node_idx[i] != ABSENT
        deleting[i] = pod.deletion_timestamp is not None
        keys.append(pod.key)
        for compiled, kind, topo, weight, ns_all, ns_ids_ in iter_pod_affinity_terms(
            pod, vocab, namespace_labels
        ):
            rows.append(compiled)
            r_pod.append(i)
            r_kind.append(kind)
            r_topo.append(topo)
            r_weight.append(weight)
            r_all.append(ns_all)
            r_ns.append(ns_ids_)

    M = max(m_cap or 0, bucket_cap(len(rows), 1))
    NS = bucket_cap(max((len(x) for x in r_ns), default=1), 1)
    term_pod = np.full(M, ABSENT, dtype=np.int32)
    term_kind = np.full(M, PAD, dtype=np.int32)
    term_topo_key = np.full(M, PAD, dtype=np.int32)
    term_weight = np.zeros(M, dtype=np.int32)
    term_ns_all = np.zeros(M, dtype=bool)
    term_ns_ids = np.full((M, NS), PAD, dtype=np.int32)
    for j in range(len(rows)):
        term_pod[j] = r_pod[j]
        term_kind[j] = r_kind[j]
        term_topo_key[j] = r_topo[j]
        term_weight[j] = r_weight[j]
        term_ns_all[j] = r_all[j]
        for m, nsid in enumerate(r_ns[j][:NS]):
            term_ns_ids[j, m] = nsid
    table = pack_conjunction_table(
        [[c] for c in rows] + [[] for _ in range(M - len(rows))],
        t_cap=1,
    )

    return ExistingPodTensors(
        node_idx=node_idx,
        ns_id=ns_id,
        label_vals=label_vals,
        valid=valid,
        deleting=deleting,
        term_pod=term_pod,
        term_kind=term_kind,
        term_topo_key=term_topo_key,
        term_weight=term_weight,
        term_table=table,
        term_ns_all=term_ns_all,
        term_ns_ids=term_ns_ids,
        keys=keys,
    )


def append_existing_pods(
    ep: ExistingPodTensors,
    pods: Sequence[Pod],
    start_slot: int,
    term_start: int,
    node_name_to_idx: Dict[str, int],
    vocab: Vocab,
    namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
) -> Optional[int]:
    """Append rows for NEW placed pods in place (the common between-full-
    packs case: the placed-pod set only grows).  Returns the new term row
    count, or None when any axis would overflow (caller falls back to a
    full pack_existing_pods at grown buckets)."""
    E = ep.node_idx.shape[0]
    K = ep.label_vals.shape[1]
    if start_slot + len(pods) > E:
        return None
    # compile terms first so overflow aborts before any mutation
    compiled = []
    for i, pod in enumerate(pods):
        if any(
            vocab.intern_label(k, v)[0] >= K for k, v in pod.labels.items()
        ):
            return None
        for row in iter_pod_affinity_terms(pod, vocab, namespace_labels):
            compiled.append((start_slot + i, row))
    M = ep.term_pod.shape[0]
    NS = ep.term_ns_ids.shape[1]
    tbl = ep.term_table
    R = tbl.req_key.shape[2]
    V = tbl.req_vals.shape[3]
    if term_start + len(compiled) > M:
        return None
    for _, (c, kind, topo, weight, ns_all, ns_ids_) in compiled:
        if len(ns_ids_) > NS:
            return None
        if not c.match_nothing and (
            c.n_reqs > R or any(len(vs) > V for vs in c.vals)
        ):
            return None

    for i, pod in enumerate(pods):
        s = start_slot + i
        ep.node_idx[s] = node_name_to_idx.get(pod.node_name, ABSENT)
        ep.ns_id[s] = vocab.namespaces.intern(pod.namespace)
        ep.label_vals[s] = _pod_label_row(pod, vocab, K)
        ep.valid[s] = ep.node_idx[s] != ABSENT
        ep.deleting[s] = pod.deletion_timestamp is not None
        if s < len(ep.keys):
            ep.keys[s] = pod.key
        else:
            while len(ep.keys) < s:
                ep.keys.append("")
            ep.keys.append(pod.key)
    for j, (slot, (c, kind, topo, weight, ns_all, ns_ids_)) in enumerate(
        compiled, start=term_start
    ):
        ep.term_pod[j] = slot
        ep.term_kind[j] = kind
        ep.term_topo_key[j] = topo
        ep.term_weight[j] = weight
        ep.term_ns_all[j] = ns_all
        ep.term_ns_ids[j] = PAD
        for m, nsid in enumerate(ns_ids_[:NS]):
            ep.term_ns_ids[j, m] = nsid
        tbl.req_key[j, 0] = PAD
        tbl.req_op[j, 0] = PAD
        tbl.req_vals[j, 0] = PAD
        tbl.req_rhs[j, 0] = 0
        tbl.term_valid[j, 0] = False
        if not c.match_nothing:
            tbl.term_valid[j, 0] = True
            for k in range(min(c.n_reqs, R)):
                tbl.req_key[j, 0, k] = c.keys[k]
                tbl.req_op[j, 0, k] = c.ops[k]
                tbl.req_rhs[j, 0, k] = c.rhs_int[k]
                for m, v in enumerate(c.vals[k][:V]):
                    tbl.req_vals[j, 0, k, m] = v
    return term_start + len(compiled)


# ---------------------------------------------------------------------------
# Pending-pod batch
# ---------------------------------------------------------------------------


@dataclass
class PodBatch:
    """One batch of pending pods, fully compiled for device dispatch."""

    requests: np.ndarray  # i32 [P, R]
    nonzero_req: np.ndarray  # i32 [P, 2]
    ns_id: np.ndarray  # i32 [P]
    priority: np.ndarray  # i32 [P]
    label_vals: np.ndarray  # i32 [P, K]
    valid: np.ndarray  # bool [P]  (slot holds a real pod)
    # merged nodeSelector ∧ required node-affinity DNF
    node_sel: ConjunctionTable  # [P, T, R, V]
    # preferred node affinity
    pref_node: ConjunctionTable  # [P, PT, R, V]
    pref_weight: np.ndarray  # i32 [P, PT]
    # tolerations
    tol_key: np.ndarray  # i32 [P, TL]  (-1 wildcard, PAD unused)
    tol_op: np.ndarray  # i32 [P, TL]
    tol_val: np.ndarray  # i32 [P, TL]
    tol_effect: np.ndarray  # i32 [P, TL] (EFFECT_ALL=-1 or code; PAD unused)
    # topology spread constraints
    tsc_table: ConjunctionTable  # [P, C, R, V] selector per constraint
    tsc_topo_key: np.ndarray  # i32 [P, C]
    tsc_max_skew: np.ndarray  # i32 [P, C]
    tsc_hard: np.ndarray  # bool [P, C] (DoNotSchedule)
    tsc_min_domains: np.ndarray  # i32 [P, C] (0 = unset)
    tsc_honor_affinity: np.ndarray  # bool [P, C] nodeAffinityPolicy Honor
    tsc_honor_taints: np.ndarray  # bool [P, C] nodeTaintsPolicy Honor
    # inter-pod (anti-)affinity terms of the incoming pods
    aff_table: ConjunctionTable  # [P, AT, AR, AV]
    aff_kind: np.ndarray  # i32 [P, AT] TERM_* or PAD
    aff_topo_key: np.ndarray  # i32 [P, AT]
    aff_weight: np.ndarray  # i32 [P, AT]
    aff_ns_all: np.ndarray  # bool [P, AT]
    aff_ns_ids: np.ndarray  # i32 [P, AT, NS]
    # spec.nodeName as an interned label-value id (matched against the
    # metadata.name pseudo-label; ABSENT = unset)
    target_name_val: np.ndarray = None  # i32 [P]
    # requested host ports (same encoding as NodeTensors.used_*)
    want_ppk: np.ndarray = None  # i32 [P, W]
    want_ip: np.ndarray = None  # i32 [P, W]
    want_wild: np.ndarray = None  # bool [P, W]
    # container images for ImageLocality
    img_ids: np.ndarray = None  # i32 [P, I]
    n_containers: np.ndarray = None  # i32 [P]
    pods: List[Pod] = field(default_factory=list)

    @property
    def p_cap(self) -> int:
        return self.requests.shape[0]


def encode_port(vocab: Vocab, p) -> Tuple[int, int, bool]:
    """ContainerPort → (proto:port id, host-ip id, ip-is-wildcard)."""
    ppk = vocab.ports.intern(f"{p.protocol}:{p.host_port}")
    ip = p.host_ip or "0.0.0.0"
    return ppk, vocab.ports.intern(ip), ip == "0.0.0.0"


def _merged_node_dnf(pod: Pod, vocab: Vocab) -> List[CompiledRequirements]:
    """spec.nodeSelector AND required node affinity, distributed into DNF."""
    base = compile_match_labels_conjunction(pod.node_selector, vocab)
    terms: List[CompiledRequirements] = []
    if pod.affinity and pod.affinity.node_affinity:
        req = pod.affinity.node_affinity.required_during_scheduling_ignored_during_execution
        if req is not None:
            terms = compile_node_selector_dnf(req, vocab)
    if not terms:
        return [base]
    merged = []
    for t in terms:
        if t.match_nothing:
            merged.append(t)
            continue
        c = CompiledRequirements(
            keys=base.keys + t.keys,
            ops=base.ops + t.ops,
            vals=[list(v) for v in base.vals] + [list(v) for v in t.vals],
            rhs_int=base.rhs_int + t.rhs_int,
        )
        merged.append(c)
    return merged


def _spread_selector(tsc, pod: Pod, vocab: Vocab) -> CompiledRequirements:
    """Constraint selector with matchLabelKeys folded in (KEP-3243)."""
    c = compile_label_selector(tsc.label_selector, vocab)
    if c.match_nothing:
        return c
    from kubernetes_tpu.api import labels as k8slabels

    for key in tsc.match_label_keys or ():
        if key in pod.labels:
            c.add(key, k8slabels.IN, (pod.labels[key],), vocab)
    return c


def pack_pod_batch(
    pods: Sequence[Pod],
    vocab: Vocab,
    k_cap: int,
    p_cap: Optional[int] = None,
    namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
) -> PodBatch:
    for pod in pods:
        for k, v in pod.labels.items():
            vocab.intern_label(k, v)
        vocab.namespaces.intern(pod.namespace)
        for name in pod.compute_requests().scalars:
            vocab.resources.intern(name)

    P = p_cap or bucket_cap(len(pods), 1)
    lanes = ResourceLanes(vocab)
    R = bucket_cap(lanes.n_lanes, 4)

    requests = np.zeros((P, R), dtype=np.int32)
    nonzero = np.zeros((P, 2), dtype=np.int32)
    ns_id = np.full(P, ABSENT, dtype=np.int32)
    priority = np.zeros(P, dtype=np.int32)
    label_vals = np.full((P, k_cap), ABSENT, dtype=np.int32)

    target_name_val = np.full(P, ABSENT, dtype=np.int32)
    n_containers = np.zeros(P, dtype=np.int32)

    node_dnfs: List[List[CompiledRequirements]] = []
    pref_terms: List[List[CompiledRequirements]] = []
    pref_weights: List[List[int]] = []
    tols: List[List[Tuple[int, int, int, int]]] = []
    tscs: List[List] = []
    tsc_sels: List[List[CompiledRequirements]] = []
    aff_terms: List[List[CompiledRequirements]] = []
    aff_meta: List[List[Tuple[int, int, int, bool, List[int]]]] = []
    port_rows: List[List[Tuple[int, int, bool]]] = []
    img_rows: List[List[int]] = []

    for i, pod in enumerate(pods[:P]):
        req = pod.compute_requests()
        requests[i] = lanes.request_row(req, R)
        nz = req.non_zero_defaulted()
        nonzero[i] = (_i32(nz.milli_cpu), _i32(-(-nz.memory // MEM_UNIT)))
        ns_id[i] = vocab.namespaces.intern(pod.namespace)
        priority[i] = pod.priority
        label_vals[i] = _pod_label_row(pod, vocab, k_cap)
        if pod.node_name:
            target_name_val[i] = vocab.intern_val(pod.node_name)
        # image_locality.go: len(initContainers) + len(containers)
        n_containers[i] = max(len(pod.containers) + len(pod.init_containers), 1)
        port_rows.append([encode_port(vocab, p) for p in pod.host_ports()])
        img_rows.append([vocab.images.intern(img) for img in pod.images])

        node_dnfs.append(_merged_node_dnf(pod, vocab))

        pt: List[CompiledRequirements] = []
        pw: List[int] = []
        if pod.affinity and pod.affinity.node_affinity:
            for term in (
                pod.affinity.node_affinity.preferred_during_scheduling_ignored_during_execution
            ):
                from kubernetes_tpu.snapshot.selectors import (
                    compile_node_selector_term,
                )

                pt.append(compile_node_selector_term(term.preference, vocab))
                pw.append(term.weight)
        pref_terms.append(pt)
        pref_weights.append(pw)

        trow: List[Tuple[int, int, int, int]] = []
        for tol in pod.tolerations:
            key = vocab.label_keys.intern(tol.key) if tol.key else ABSENT
            op = TOL_OP_EXISTS if tol.operator == TOLERATION_OP_EXISTS else TOL_OP_EQUAL
            # "" is interned like any other value so Equal("") == taint("").
            val = vocab.intern_val(tol.value)
            eff = _EFFECT_CODE.get(tol.effect, EFFECT_ALL) if tol.effect else EFFECT_ALL
            trow.append((key, op, val, eff))
        tols.append(trow)

        crow = []
        csel = []
        for tsc in pod.topology_spread_constraints:
            crow.append(tsc)
            csel.append(_spread_selector(tsc, pod, vocab))
            vocab.label_keys.intern(tsc.topology_key)
        tscs.append(crow)
        tsc_sels.append(csel)

        arow: List[CompiledRequirements] = []
        ameta: List[Tuple[int, int, int, bool, List[int]]] = []
        for compiled, kind, topo, w, all_ns, ids in iter_pod_affinity_terms(
            pod, vocab, namespace_labels
        ):
            arow.append(compiled)
            ameta.append((kind, topo, w, all_ns, ids))
        aff_terms.append(arow)
        aff_meta.append(ameta)

    while len(node_dnfs) < P:
        node_dnfs.append([])
        pref_terms.append([])
        pref_weights.append([])
        tols.append([])
        tscs.append([])
        tsc_sels.append([])
        aff_terms.append([])
        aff_meta.append([])
        port_rows.append([])
        img_rows.append([])

    W = bucket_cap(max((len(r) for r in port_rows), default=1), 1)
    want_ppk = np.full((P, W), PAD, dtype=np.int32)
    want_ip = np.full((P, W), PAD, dtype=np.int32)
    want_wild = np.zeros((P, W), dtype=bool)
    for i, prow in enumerate(port_rows):
        for j, (ppk, ip, wild) in enumerate(prow[:W]):
            want_ppk[i, j] = ppk
            want_ip[i, j] = ip
            want_wild[i, j] = wild

    I = bucket_cap(max((len(r) for r in img_rows), default=1), 1)
    img_ids = np.full((P, I), PAD, dtype=np.int32)
    for i, irow in enumerate(img_rows):
        for j, ii in enumerate(irow[:I]):
            img_ids[i, j] = ii

    node_sel = pack_conjunction_table(node_dnfs)
    pref_node = pack_conjunction_table(pref_terms)
    PT = pref_node.term_valid.shape[1]
    pref_weight = np.zeros((P, PT), dtype=np.int32)
    for i, ws in enumerate(pref_weights):
        for j, w in enumerate(ws[:PT]):
            pref_weight[i, j] = w

    TL = bucket_cap(max((len(t) for t in tols), default=1), 1)
    tol_key = np.full((P, TL), PAD, dtype=np.int32)
    tol_op = np.full((P, TL), PAD, dtype=np.int32)
    tol_val = np.full((P, TL), PAD, dtype=np.int32)
    tol_effect = np.full((P, TL), PAD, dtype=np.int32)
    for i, trow in enumerate(tols):
        for j, (k, o, v, e) in enumerate(trow[:TL]):
            tol_key[i, j] = k
            tol_op[i, j] = o
            tol_val[i, j] = v
            tol_effect[i, j] = e

    tsc_table = pack_conjunction_table([list(cs) for cs in tsc_sels])
    C = tsc_table.term_valid.shape[1]
    tsc_topo_key = np.full((P, C), PAD, dtype=np.int32)
    tsc_max_skew = np.zeros((P, C), dtype=np.int32)
    tsc_hard = np.zeros((P, C), dtype=bool)
    tsc_min_domains = np.zeros((P, C), dtype=np.int32)
    tsc_honor_affinity = np.ones((P, C), dtype=bool)
    tsc_honor_taints = np.zeros((P, C), dtype=bool)
    for i, crow in enumerate(tscs):
        for j, tsc in enumerate(crow[:C]):
            tsc_topo_key[i, j] = vocab.label_keys.intern(tsc.topology_key)
            tsc_max_skew[i, j] = tsc.max_skew
            tsc_hard[i, j] = tsc.when_unsatisfiable == DO_NOT_SCHEDULE
            tsc_min_domains[i, j] = tsc.min_domains or 0
            tsc_honor_affinity[i, j] = tsc.node_affinity_policy == NODE_INCLUSION_HONOR
            tsc_honor_taints[i, j] = tsc.node_taints_policy == NODE_INCLUSION_HONOR

    aff_table = pack_conjunction_table(aff_terms)
    AT = aff_table.term_valid.shape[1]
    NS = bucket_cap(
        max((len(m[4]) for ms in aff_meta for m in ms), default=1), 1
    )
    aff_kind = np.full((P, AT), PAD, dtype=np.int32)
    aff_topo_key = np.full((P, AT), PAD, dtype=np.int32)
    aff_weight = np.zeros((P, AT), dtype=np.int32)
    aff_ns_all = np.zeros((P, AT), dtype=bool)
    aff_ns_ids = np.full((P, AT, NS), PAD, dtype=np.int32)
    for i, ms in enumerate(aff_meta):
        for j, (kind, topo, w, all_ns, ids) in enumerate(ms[:AT]):
            aff_kind[i, j] = kind
            aff_topo_key[i, j] = topo
            aff_weight[i, j] = w
            aff_ns_all[i, j] = all_ns
            for m, nsid in enumerate(ids[:NS]):
                aff_ns_ids[i, j, m] = nsid

    valid = np.zeros(P, dtype=bool)
    valid[: len(pods)] = True

    return PodBatch(
        requests=requests,
        nonzero_req=nonzero,
        ns_id=ns_id,
        priority=priority,
        label_vals=label_vals,
        valid=valid,
        node_sel=node_sel,
        pref_node=pref_node,
        pref_weight=pref_weight,
        tol_key=tol_key,
        tol_op=tol_op,
        tol_val=tol_val,
        tol_effect=tol_effect,
        tsc_table=tsc_table,
        tsc_topo_key=tsc_topo_key,
        tsc_max_skew=tsc_max_skew,
        tsc_hard=tsc_hard,
        tsc_min_domains=tsc_min_domains,
        tsc_honor_affinity=tsc_honor_affinity,
        tsc_honor_taints=tsc_honor_taints,
        aff_table=aff_table,
        aff_kind=aff_kind,
        aff_topo_key=aff_topo_key,
        aff_weight=aff_weight,
        aff_ns_all=aff_ns_all,
        aff_ns_ids=aff_ns_ids,
        target_name_val=target_name_val,
        want_ppk=want_ppk,
        want_ip=want_ip,
        want_wild=want_wild,
        img_ids=img_ids,
        n_containers=n_containers,
        pods=list(pods),
    )
