"""Device-resident cluster snapshot with delta uploads.

The host SnapshotMirror (mirror.py) is the source of truth; this cache keeps
its DeviceCluster image alive across batches and ships only what changed:

  * node USAGE rows (requested/nonzero/num_pods/ports) — small, re-uploaded
    every sync (they change with every commit);
  * placed-pod and term rows — append-only between rebuilds (the mirror's
    `_epod_slots` cursor discipline), so only the newly appended row range
    is uploaded and spliced in with dynamic_update_slice on device;
  * static node tensors / vocab tables — re-uploaded only when the mirror
    key (static generation, full packs, existing rebuilds, vocab sizes)
    changes.

This is the host→HBM half of SURVEY.md §2.4's "informer delta stream →
append-only update buffer DMA'd into HBM" design, replacing the previous
full `DeviceCluster.from_host` per batch (hundreds of ms over a remote
device link at 5k-node scale; the delta is ~100 KB).
"""

from __future__ import annotations

import functools
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.ops.common import DeviceCluster, DTable, I32
from kubernetes_tpu.snapshot.schema import bucket_cap


def _dus(full, delta, start):
    """dynamic_update_slice of leading-axis rows."""
    start = jnp.asarray(start, I32)
    zero = jnp.zeros((), I32)
    starts = (start,) + (zero,) * (full.ndim - 1)
    # ktpu: allow(slice-clamp) — e0/m0 are clamped HOST-side before upload
    # (_row_range: start = min(lo, cap - size)), so start + size <= cap by
    # construction and the device splice can never reach the array end
    return jax.lax.dynamic_update_slice(full, delta, starts)


@functools.lru_cache(maxsize=64)
def _delta_applier(spec, treedef, with_rows: bool):
    """One jitted splice per delta signature: unpacks the single wire
    buffer (usage rows + appended pod/term rows + cursors) and merges it
    into the donated DeviceCluster — one transfer, one dispatch.

    Mesh note: under meshDispatch the incoming ``dc`` is mesh-committed
    and ``buf`` is replicated on the same mesh; GSPMD propagates the
    input shardings through the splice, so the output stays partitioned
    (sync() re-asserts the placement — a no-op when propagation held)."""
    from kubernetes_tpu.ops import wire

    # ktpu: axes(dc=DeviceCluster, buf=u8[B])
    # ktpu: noinstantiate — the delta layout lives in the lru_cache key
    #   (spec, treedef, with_rows); the splice is exercised end-to-end by
    #   test_device_mirror instead
    @functools.partial(jax.jit, donate_argnums=(0,))
    def apply(dc: DeviceCluster, buf) -> DeviceCluster:
        tree = jax.tree_util.tree_unflatten(treedef, wire.unpack(buf, spec))
        out = dict(tree["usage"])
        if with_rows:
            e0, m0 = tree["e0"], tree["m0"]
            for name, delta in tree["ep"].items():
                out[name] = _dus(getattr(dc, name), delta, e0)
            tm = dict(tree["tm"])
            tt = dc.term_table
            out["term_table"] = DTable(
                req_key=_dus(tt.req_key, tm.pop("tt_req_key"), m0),
                req_op=_dus(tt.req_op, tm.pop("tt_req_op"), m0),
                req_vals=_dus(tt.req_vals, tm.pop("tt_req_vals"), m0),
                req_rhs=_dus(tt.req_rhs, tm.pop("tt_req_rhs"), m0),
                term_valid=_dus(tt.term_valid, tm.pop("tt_term_valid"), m0),
            )
            for name, delta in tm.items():
                out[name] = _dus(getattr(dc, name), delta, m0)
        return replace(dc, **out)

    return apply


_EPOD_FIELDS = {
    "epod_node": ("node_idx", np.int32),
    "epod_ns": ("ns_id", np.int32),
    "epod_labels": ("label_vals", np.int32),
    "epod_valid": ("valid", bool),
    "epod_deleting": ("deleting", bool),
}

_TERM_FIELDS = {
    "term_pod": ("term_pod", np.int32),
    "term_kind": ("term_kind", np.int32),
    "term_topo": ("term_topo_key", np.int32),
    "term_weight": ("term_weight", np.int32),
    "term_ns_all": ("term_ns_all", bool),
    "term_ns_ids": ("term_ns_ids", np.int32),
}


class DeviceClusterCache:
    """Keeps one DeviceCluster in HBM, synced incrementally from the host
    mirror.  `sync()` returns the up-to-date device snapshot.

    With a ``mesh``, the snapshot is PLACED on it (parallel/mesh.py
    cluster_shardings: node-major tensors partitioned over the 'nodes'
    axis, everything else replicated) so every consumer kernel runs
    SPMD-partitioned; delta uploads ride a replicated wire buffer."""

    def __init__(self, mesh=None) -> None:
        self._dc = None
        self._key = None
        self._e_done = 0
        self._m_done = 0
        self._mesh = mesh

    def invalidate(self) -> None:
        self._dc = None

    def _row_range(self, lo: int, hi: int, cap: int):
        """Bucketed [start, start+size) covering [lo, hi) — size is a stable
        bucket so delta uploads hit a handful of jit shapes; rows below lo
        re-uploaded by the clamp carry identical content."""
        size = min(bucket_cap(hi - lo, 1), cap)
        start = min(lo, cap - size)
        return start, size

    def sync(self, mirror, vocab) -> DeviceCluster:
        # chaos seam (ISSUE 15 hbm_oom): an installed device-fault
        # injector can fail this donation/placement the way a real
        # RESOURCE_EXHAUSTED would; Scheduler._sync_device_cluster owns
        # the recovery (invalidate → rebuild-from-mirror, bounded retry)
        from kubernetes_tpu.observability.kernels import fault_injector

        inj = fault_injector()
        if inj is not None and inj.sync_fault() is not None:
            self.invalidate()
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: out of memory placing resident "
                "cluster snapshot (chaos hbm_oom)"
            )
        nt = mirror.nodes
        ep = mirror.existing  # materializes/append-updates the host tensors
        key = (
            mirror.static_generation,
            mirror._full_packs,
            mirror._existing_rebuilds,
            len(vocab.label_vals),
            len(vocab.label_keys),
        )
        if self._dc is None or key != self._key:
            dc = DeviceCluster.from_host(nt, ep, vocab)
            if self._mesh is not None:
                from kubernetes_tpu.parallel.mesh import place_cluster

                dc = place_cluster(self._mesh, dc)
            self._dc = dc
            self._key = key
            self._e_done = mirror.e_used
            self._m_done = mirror.m_used
            return self._dc

        from kubernetes_tpu.ops import wire

        tree = {
            "usage": dict(
                requested=np.asarray(nt.requested, np.int32),
                nonzero_req=np.asarray(nt.nonzero_req, np.int32),
                num_pods=np.asarray(nt.num_pods, np.int32),
                used_ppk=np.asarray(nt.used_ppk, np.int32),
                used_ip=np.asarray(nt.used_ip, np.int32),
                used_wild=np.asarray(nt.used_wild, bool),
            )
        }
        e1, m1 = mirror.e_used, mirror.m_used
        with_rows = not (e1 == self._e_done and m1 == self._m_done)
        if with_rows:
            e_cap = ep.node_idx.shape[0]
            m_cap = ep.term_pod.shape[0]
            e0, de = self._row_range(self._e_done, e1, e_cap)
            m0, dm = self._row_range(self._m_done, m1, m_cap)
            tree["ep"] = {
                dc_name: np.asarray(getattr(ep, host)[e0 : e0 + de], dt)
                for dc_name, (host, dt) in _EPOD_FIELDS.items()
            }
            tm_delta = {
                dc_name: np.asarray(getattr(ep, host)[m0 : m0 + dm], dt)
                for dc_name, (host, dt) in _TERM_FIELDS.items()
            }
            tt = ep.term_table
            tm_delta.update(
                tt_req_key=np.asarray(tt.req_key[m0 : m0 + dm], np.int32),
                tt_req_op=np.asarray(tt.req_op[m0 : m0 + dm], np.int32),
                tt_req_vals=np.asarray(tt.req_vals[m0 : m0 + dm], np.int32),
                tt_req_rhs=np.asarray(tt.req_rhs[m0 : m0 + dm], np.int32),
                tt_term_valid=np.asarray(tt.term_valid[m0 : m0 + dm], bool),
            )
            tree["tm"] = tm_delta
            tree["e0"] = np.asarray(e0, np.int32)
            tree["m0"] = np.asarray(m0, np.int32)
        buf, spec, treedef = wire.pack_tree(tree)
        if self._mesh is not None:
            from kubernetes_tpu.parallel.mesh import place_cluster, replicated

            # the wire buffer must commit to the SAME mesh as the resident
            # snapshot (mixed device sets are a jit error); re-asserting
            # the cluster placement after the splice is a no-op when GSPMD
            # propagation kept it, and repairs it when it didn't
            buf_dev = jax.device_put(buf, replicated(self._mesh))
            applied = _delta_applier(spec, treedef, with_rows)(
                self._dc, buf_dev
            )
            self._dc = place_cluster(self._mesh, applied)
        else:
            self._dc = _delta_applier(spec, treedef, with_rows)(
                self._dc, jax.device_put(buf)
            )
        self._e_done, self._m_done = e1, m1
        return self._dc
