"""Host scheduler cache (pkg/scheduler/backend/cache/cache.go).

Holds the authoritative view of nodes and pods between the informer stream
and the scheduling loop:

  * ``assume_pod``/``forget_pod``/``finish_binding`` implement the
    optimistic-binding protocol (cache.go:360-422): a scheduled pod is
    charged to its node immediately so the next cycle sees it, before the
    API write round-trips.
  * informer Add/Update/RemovePod reconcile against assumed state,
    including the assumed-vs-informer races (cache.go:484-568).
  * every mutation bumps the node's ``generation``; the device mirror
    repacks only nodes newer than its own generation (cache.go:185-279's
    incremental UpdateSnapshot, reproduced for HBM).
  * assumed pods that never confirm expire after a TTL (cache.go:721-752;
    the reference default is "never", kept configurable here).
"""

from __future__ import annotations

import copy
import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.analysis import sanitizer
from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import Node, Pod

# Lock-discipline registry (kubernetes_tpu.analysis): Cache has no lock of
# its own — every mutating method is contractually entered with the owning
# Scheduler's _mu held (cache.mu in the reference lives inside the cache;
# here the scheduler's one lock covers cache+queue+mirror so commit tails
# settle under a single acquisition).  Methods listed read-only are safe to
# call without the lock.
_KTPU_GUARDED = {
    "Cache": {
        "external_lock": "Scheduler._mu",
        "readonly": ["is_assumed", "real_nodes", "placed_pods", "stats", "_pod_flags"],
    },
}

_generation = itertools.count(1)


def next_generation() -> int:
    return next(_generation)


@dataclass
class CachedNode:
    """NodeInfo analogue (framework/types.go:585): node + accounting."""

    node: Optional[Node]  # None for a "ghost" node that only hosts pods
    pods: Dict[str, Pod] = field(default_factory=dict)  # uid → pod
    requested: Resource = field(default_factory=Resource)
    non_zero_requested: Resource = field(default_factory=Resource)
    generation: int = 0
    # bumped only when the Node OBJECT changes (labels/taints/capacity) —
    # not on pod accounting; device caches keyed on this skip re-uploads
    # for usage-only churn
    static_generation: int = 0

    def add_pod(self, pod: Pod) -> None:
        self.requested.add(pod.compute_requests())
        self.non_zero_requested.add(pod.non_zero_requests())
        self.pods[pod.uid] = pod
        self.generation = next_generation()

    def remove_pod(self, pod: Pod) -> bool:
        if pod.uid not in self.pods:
            return False
        old = self.pods.pop(pod.uid)
        self.requested.sub(old.compute_requests())
        self.non_zero_requested.sub(old.non_zero_requests())
        self.generation = next_generation()
        return True


@dataclass
class _PodState:
    pod: Pod
    binding_finished: bool = False
    deadline: Optional[float] = None


class CacheError(RuntimeError):
    """Cache invariant violation — the reference fatals on these
    (cache.go:537-541); we raise and let the caller decide."""


class Cache:
    def __init__(self, assumed_pod_ttl_s: Optional[float] = None):
        # ttl None reproduces durationToExpireAssumedPod=0 (never expire,
        # scheduler.go:57)
        self.ttl = assumed_pod_ttl_s
        self.nodes: Dict[str, CachedNode] = {}
        self.pod_states: Dict[str, _PodState] = {}
        self.assumed: set[str] = set()
        # O(1) feature counters + change version so consumers (device
        # mirror, fast path) can gate expensive rebuilds without scans
        self.pod_version = 0
        self.n_term_pods = 0  # placed pods carrying (anti-)affinity terms
        self.n_port_pods = 0  # placed pods using host ports
        # registry of the term-carrying placed pods themselves: the fast
        # path's per-batch gate asks "could any placed term admit this
        # pod" instead of disabling itself cluster-globally
        self.term_pods: Dict[str, Pod] = {}
        self.term_version = 0

    @staticmethod
    def _pod_flags(pod: Pod) -> Tuple[bool, bool]:
        has_terms = pod.affinity is not None and (
            pod.affinity.pod_affinity is not None
            or pod.affinity.pod_anti_affinity is not None
        )
        return has_terms, bool(pod.host_ports())

    def _count_pod(self, pod: Pod, sign: int) -> None:
        self.pod_version += 1
        has_terms, has_ports = self._pod_flags(pod)
        if has_terms:
            self.n_term_pods += sign
            self.term_version += 1
            if sign > 0:
                self.term_pods[pod.uid] = pod
            else:
                self.term_pods.pop(pod.uid, None)
        if has_ports:
            self.n_port_pods += sign

    # ----- nodes (informer) -----------------------------------------------

    def add_node(self, node: Node) -> None:
        cn = self.nodes.get(node.name)
        if cn is None:
            g = next_generation()
            self.nodes[node.name] = CachedNode(
                node=node, generation=g, static_generation=g
            )
        else:
            cn.node = node
            cn.generation = next_generation()
            cn.static_generation = cn.generation

    def update_node(self, node: Node) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> None:
        cn = self.nodes.get(name)
        if cn is None:
            return
        if cn.pods:
            # Ghost node: keep accounting until its pods are deleted
            # (cache.go:601-668).
            cn.node = None
            cn.generation = next_generation()
        else:
            del self.nodes[name]

    # ----- assume protocol (scheduler) ------------------------------------

    def assume_pod(self, pod: Pod, node_name: str) -> None:
        """Assumes a COPY of the pod (schedule_one.go:943 assumes
        podInfo.DeepCopy()): the queued object stays pristine, so a failed
        reserve/permit/bind never leaves a stale node_name pinning the pod
        to the node it just failed on."""
        if pod.uid in self.pod_states:
            raise CacheError(f"pod {pod.key} already assumed/added")
        # shallow copy without __reduce_ex__ dispatch (copy.copy costs ~5×
        # on dataclasses; this runs once per scheduled pod)
        assumed = object.__new__(type(pod))
        assumed.__dict__.update(pod.__dict__)
        assumed.node_name = node_name
        cn = self.nodes.setdefault(node_name, CachedNode(node=None))
        cn.add_pod(assumed)
        self._count_pod(assumed, +1)
        self.pod_states[pod.uid] = _PodState(assumed)
        self.assumed.add(pod.uid)

    def assume_pods_bulk(self, pairs) -> List[object]:
        """assume_pod for one dispatch's worth of placements in one pass.

        Same protocol and invariants as the per-pod assume, minus the
        per-pod overhead: callers guarantee the pods are signature-gated
        (no (anti-)affinity terms, no host ports — the fast path's
        eligibility), so the feature-flag probes collapse, and the
        generation bump aggregates to one per TOUCHED NODE instead of one
        per pod (the mirror repacks per node row, so per-pod bumps carry
        no extra information).  Returns a list aligned with ``pairs``:
        the assumed pod copy, or an error STRING for pods that violated
        the protocol (already assumed/added) — those are not assumed,
        exactly like the per-pod path's CacheError."""
        # KTPU_SANITIZE probe: memoized enabled() check + getattr, once per
        # bulk dispatch (not per pod).  The owning scheduler stamps
        # _ktpu_lock at construction when the sanitizer is on; a standalone
        # Cache has no discipline to enforce.
        sanitizer.assert_owned(
            getattr(self, "_ktpu_lock", None), "cache.assume_pods_bulk"
        )
        out: List[object] = []
        pod_states = self.pod_states
        nodes = self.nodes
        assumed_set = self.assumed
        touched: Dict[str, CachedNode] = {}
        n_ok = 0
        for pod, node_name in pairs:
            if pod.uid in pod_states:
                out.append(f"pod {pod.key} already assumed/added")
                continue
            assumed = object.__new__(type(pod))
            assumed.__dict__.update(pod.__dict__)
            assumed.node_name = node_name
            cn = nodes.get(node_name)
            if cn is None:
                cn = nodes[node_name] = CachedNode(node=None)
            cn.requested.add(assumed.compute_requests())
            cn.non_zero_requested.add(assumed.non_zero_requests())
            cn.pods[pod.uid] = assumed
            touched[node_name] = cn
            pod_states[pod.uid] = _PodState(assumed)
            assumed_set.add(pod.uid)
            out.append(assumed)
            n_ok += 1
        self.pod_version += n_ok
        for cn in touched.values():
            cn.generation = next_generation()
        return out

    def finish_binding(self, pod: Pod, now: Optional[float] = None) -> None:
        ps = self.pod_states.get(pod.uid)
        if ps is None or pod.uid not in self.assumed:
            return
        ps.binding_finished = True
        if self.ttl is not None:
            ps.deadline = (now or time.monotonic()) + self.ttl

    def forget_pod(self, pod: Pod) -> None:
        ps = self.pod_states.get(pod.uid)
        if ps is None:
            return
        if pod.uid not in self.assumed:
            raise CacheError(f"pod {pod.key} was added, not assumed; cannot forget")
        self._remove_pod_internal(ps.pod)
        del self.pod_states[pod.uid]
        self.assumed.discard(pod.uid)

    def cleanup_expired_assumed(self, now: Optional[float] = None) -> List[Pod]:
        """TTL janitor (cache.go:729 cleanupAssumedPods)."""
        now = now or time.monotonic()
        expired = []
        for uid in list(self.assumed):
            ps = self.pod_states[uid]
            if ps.binding_finished and ps.deadline is not None and now >= ps.deadline:
                expired.append(ps.pod)
                self._remove_pod_internal(ps.pod)
                del self.pod_states[uid]
                self.assumed.discard(uid)
        return expired

    # ----- pods (informer) -------------------------------------------------

    def add_pod(self, pod: Pod) -> None:
        """Informer confirmation of a (possibly assumed) pod
        (cache.go:484)."""
        ps = self.pod_states.get(pod.uid)
        if ps is not None and pod.uid in self.assumed:
            if ps.pod.node_name != pod.node_name:
                # Assumed to another node than the API says: trust the API
                # (the race in cache.go:498-516).
                self._remove_pod_internal(ps.pod)
                self._add_pod_internal(pod)
            else:
                # Same node: adopt the API object (it is the truth).
                self.nodes[pod.node_name].pods[pod.uid] = pod
                self.pod_version += 1
            # Confirmed: no longer assumed.
            self.assumed.discard(pod.uid)
            ps.pod = pod
            ps.deadline = None
        elif ps is None:
            self._add_pod_internal(pod)
            self.pod_states[pod.uid] = _PodState(pod)
        else:
            raise CacheError(f"pod {pod.key} added twice")

    def update_pod(self, old: Pod, new: Pod) -> None:
        ps = self.pod_states.get(old.uid)
        if ps is None:
            raise CacheError(f"updating unknown pod {old.key}")
        if old.uid in self.assumed:
            raise CacheError(f"updating assumed pod {old.key}")
        self._remove_pod_internal(ps.pod)
        self._add_pod_internal(new)
        ps.pod = new

    def remove_pod(self, pod: Pod) -> None:
        ps = self.pod_states.get(pod.uid)
        if ps is None:
            return
        self._remove_pod_internal(ps.pod)
        del self.pod_states[pod.uid]
        self.assumed.discard(pod.uid)
        # Drop ghost nodes whose last pod left.
        cn = self.nodes.get(ps.pod.node_name)
        if cn is not None and cn.node is None and not cn.pods:
            del self.nodes[ps.pod.node_name]

    def _add_pod_internal(self, pod: Pod) -> None:
        cn = self.nodes.setdefault(pod.node_name, CachedNode(node=None))
        cn.add_pod(pod)
        self._count_pod(pod, +1)

    def _remove_pod_internal(self, pod: Pod) -> None:
        cn = self.nodes.get(pod.node_name)
        if cn is None or not cn.remove_pod(pod):
            raise CacheError(f"pod {pod.key} not found on node {pod.node_name!r}")
        self._count_pod(pod, -1)

    # ----- introspection ----------------------------------------------------

    def is_assumed(self, uid: str) -> bool:
        return uid in self.assumed

    def real_nodes(self) -> List[CachedNode]:
        return [cn for cn in self.nodes.values() if cn.node is not None]

    def placed_pods(self) -> List[Pod]:
        return [
            p
            for cn in self.nodes.values()
            for p in cn.pods.values()
        ]

    def stats(self) -> Dict[str, int]:
        return {
            "nodes": len(self.real_nodes()),
            "pods": sum(len(cn.pods) for cn in self.nodes.values()),
            "assumed": len(self.assumed),
        }
