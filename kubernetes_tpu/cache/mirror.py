"""Incremental device mirror of the cache (UpdateSnapshot, cache.go:185).

The reference walks its generation-ordered node list head-first and copies
only NodeInfos newer than the snapshot's generation.  Here the same delta
discipline drives HBM tensor maintenance:

  * node rows with ``generation > mirror.generation`` are repacked in place
    (write_node_row + usage rows);
  * the placed-pod tensors are rebuilt only when the pod population changed
    (their rows are append-only between full repacks);
  * capacity growth (more nodes/pods/labels than the buckets hold) forces a
    full repack at the next bucket size — amortized O(1) by doubling.

Returns numpy tensors; the scheduler converts to DeviceCluster (upload).
Uploading only dirty rows via device-side dynamic_update_slice is a planned
optimization; the delta protocol here is the prerequisite.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from kubernetes_tpu.cache.cache import Cache
from kubernetes_tpu.snapshot.cluster import accumulate_node_usage
from kubernetes_tpu.snapshot.interner import Vocab
from kubernetes_tpu.snapshot.schema import (
    MEM_UNIT,
    NodeTensors,
    ResourceLanes,
    bucket_cap,
    pack_existing_pods,
    pack_nodes,
    write_node_row,
)


class SnapshotMirror:
    def __init__(self, vocab: Optional[Vocab] = None):
        self.vocab = vocab or Vocab()
        self.generation = 0
        self.nodes: Optional[NodeTensors] = None
        self.existing = None
        self._pod_population: tuple = ()
        self._full_packs = 0
        self._row_updates = 0
        self._force_full = False

    def update(self, cache: Cache, namespace_labels=None) -> None:
        """Bring the mirror up to date with the cache (incremental)."""
        real = cache.real_nodes()
        names = [cn.node.name for cn in real]
        placed = cache.placed_pods()

        need_full = (
            self._force_full
            or self.nodes is None
            or len(real) > self.nodes.n_cap
            or bucket_cap(len(self.vocab.label_keys)) > self.nodes.k_cap
            or set(names) != set(self.nodes.name_to_idx)
        )
        if need_full:
            self._force_full = False
            self._full_pack(cache, namespace_labels)
            return

        lanes = ResourceLanes(self.vocab)
        dirty = 0
        for cn in real:
            if cn.generation <= self.generation:
                continue
            i = self.nodes.name_to_idx[cn.node.name]
            if not write_node_row(self.nodes, i, cn.node, self.vocab):
                self._force_full = True  # slot axis truncated (taints/labels/…)
            self._write_usage_row(cn, i, lanes)
            if self._force_full:
                break  # overflow: everything below is repacked anyway
            dirty += 1
        self._row_updates += dirty

        if self._force_full:
            # A row write overflowed its slot capacity (e.g. host-port rows
            # > U): the snapshot is missing entries RIGHT NOW, so repack at
            # grown bucket sizes before this batch schedules against it.
            self._force_full = False
            self._full_pack(cache, namespace_labels)
            return

        # id() is part of the key: update_pod replaces the stored object, so
        # label-only changes still trigger a placed-pod tensor rebuild.
        population = tuple(sorted((p.uid, id(p)) for p in placed))
        if population != self._pod_population:
            # Pod set changed: rebuild placed-pod tensors (+ per-node usage
            # accounting rows were already updated above via generations).
            self.existing = pack_existing_pods(
                placed,
                self.nodes.name_to_idx,
                self.vocab,
                k_cap=self.nodes.k_cap,
                namespace_labels=namespace_labels,
            )
            self._pod_population = population

        self.generation = max(
            (cn.generation for cn in real), default=self.generation
        )

    def _write_usage_row(self, cn, i: int, lanes: ResourceLanes) -> None:
        nt = self.nodes
        R = nt.allocatable.shape[1]
        nt.requested[i] = lanes.request_row(cn.requested, R)
        nt.nonzero_req[i, 0] = cn.non_zero_requested.milli_cpu
        nt.nonzero_req[i, 1] = -(-cn.non_zero_requested.memory // MEM_UNIT)
        nt.num_pods[i] = len(cn.pods)
        U = nt.used_ppk.shape[1]
        nt.used_ppk[i] = -2
        nt.used_ip[i] = -2
        nt.used_wild[i] = False
        from kubernetes_tpu.snapshot.schema import encode_port

        rows = [
            encode_port(self.vocab, hp)
            for pod in cn.pods.values()
            for hp in pod.host_ports()
        ]
        if len(rows) > U:
            # port slots overflow → grow on next full pack
            self._force_full = True
        for j, (ppk, ip, wild) in enumerate(rows[:U]):
            nt.used_ppk[i, j] = ppk
            nt.used_ip[i, j] = ip
            nt.used_wild[i, j] = wild

    def _full_pack(self, cache: Cache, namespace_labels) -> None:
        real = cache.real_nodes()
        placed = cache.placed_pods()
        for p in placed:
            for k, v in p.labels.items():
                self.vocab.intern_label(k, v)
            self.vocab.namespaces.intern(p.namespace)
        self.nodes = pack_nodes([cn.node for cn in real], self.vocab)
        accumulate_node_usage(self.nodes, placed, self.vocab)
        self.existing = pack_existing_pods(
            placed,
            self.nodes.name_to_idx,
            self.vocab,
            k_cap=self.nodes.k_cap,
            namespace_labels=namespace_labels,
        )
        self._pod_population = tuple(sorted((p.uid, id(p)) for p in placed))
        self.generation = max((cn.generation for cn in real), default=0)
        self._full_packs += 1

    def stats(self) -> Dict[str, int]:
        return {
            "full_packs": self._full_packs,
            "row_updates": self._row_updates,
            "generation": self.generation,
        }
