"""Incremental device mirror of the cache (UpdateSnapshot, cache.go:185).

The reference walks its generation-ordered node list head-first and copies
only NodeInfos newer than the snapshot's generation.  Here the same delta
discipline drives HBM tensor maintenance:

  * node rows with ``generation > mirror.generation`` are repacked in place
    (write_node_row + usage rows);
  * the placed-pod tensors are rebuilt only when the pod population changed
    (their rows are append-only between full repacks);
  * capacity growth (more nodes/pods/labels than the buckets hold) forces a
    full repack at the next bucket size — amortized O(1) by doubling.

Returns numpy tensors; the scheduler converts to DeviceCluster (upload).
Uploading only dirty rows via device-side dynamic_update_slice is a planned
optimization; the delta protocol here is the prerequisite.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from kubernetes_tpu.cache.cache import Cache
from kubernetes_tpu.snapshot.cluster import accumulate_node_usage
from kubernetes_tpu.snapshot.interner import PAD, Vocab
from kubernetes_tpu.snapshot.schema import (
    MEM_UNIT,
    NodeTensors,
    ResourceLanes,
    append_existing_pods,
    bucket_cap,
    pack_existing_pods,
    pack_nodes,
    refresh_visit_rank,
    write_node_row,
)


# Lock-discipline registry (kubernetes_tpu.analysis): the mirror is
# externally guarded by the owning Scheduler's _mu — update()/apply_fast_
# usage() and even the lazy `existing` property REBUILD tensors in place.
_KTPU_GUARDED = {
    "SnapshotMirror": {
        "external_lock": "Scheduler._mu",
        "readonly": ["stats"],
    },
}


class SnapshotMirror:
    def __init__(self, vocab: Optional[Vocab] = None):
        self.vocab = vocab or Vocab()
        self.generation = 0
        self.static_generation = 0  # max CachedNode.static_generation seen
        self.nodes: Optional[NodeTensors] = None
        self._existing = None
        self._existing_version = -1  # cache.pod_version it was built at
        self._full_packs = 0
        self._row_updates = 0
        self._force_full = False
        self._cache = None  # last cache seen (lazy existing rebuild)
        self._ns_labels = None
        self._epod_slots = None  # uid → (slot, id(pod)) in _existing
        self._eterm_count = 0
        # bumped whenever the existing-pod tensors are REBUILT (not
        # appended) — the device-mirror cache invalidation signal
        self._existing_rebuilds = 0
        self._m_cap_max = 1  # sticky: term axis never shrinks (recompiles)
        # expected total placed pods (queue pressure) — pre-sizes the E/M
        # axes so the gang pipeline compiles ONCE instead of per doubling
        self.e_cap_hint = 0
        # node-bucket divisibility for mesh-partitioned dispatch: the
        # scheduler sets this to the mesh's nodes-axis size so every pack
        # pads N to a shardable multiple (parallel/mesh.py asserts it)
        self.node_pad_multiple = 1

    @property
    def e_used(self) -> int:
        """Occupied placed-pod slots (append cursor)."""
        return len(self._epod_slots or {})

    @property
    def m_used(self) -> int:
        """Occupied term rows (append cursor)."""
        return self._eterm_count

    @property
    def existing(self):
        """Placed-pod tensors, materialized LAZILY: only the quadratic
        (inter-pod) kernels read them, so resource-only batches never pay
        the O(all placed pods) repack.  Pure additions (the steady state
        between full packs) APPEND rows in place instead of rebuilding."""
        if (
            self._cache is not None
            and self._existing_version != self._cache.pod_version
        ):
            self._rebuild_existing()
        return self._existing

    def _rebuild_existing(self) -> None:
        placed = self._cache.placed_pods()
        slots = self._epod_slots
        if (
            self._existing is not None
            and slots is not None
            # a raised capacity hint forces one rebuild at the final shape
            # instead of a recompile per doubling
            and self._existing.node_idx.shape[0] >= self._e_cap(len(placed))
        ):
            cur = {p.uid: p for p in placed}
            if len(cur) >= len(slots) and self._adopt_equivalent(cur, slots):
                new = [p for p in placed if p.uid not in slots]
                n_terms = append_existing_pods(
                    self._existing,
                    new,
                    len(slots),
                    self._eterm_count,
                    self.nodes.name_to_idx,
                    self.vocab,
                    self._ns_labels,
                )
                if n_terms is not None:
                    base = len(slots)
                    for i, p in enumerate(new):
                        slots[p.uid] = (base + i, p)
                    self._eterm_count = n_terms
                    self._existing_version = self._cache.pod_version
                    return
        for p in placed:
            for k, v in p.labels.items():
                self.vocab.intern_label(k, v)
            self.vocab.namespaces.intern(p.namespace)
        self._existing = pack_existing_pods(
            placed,
            self.nodes.name_to_idx,
            self.vocab,
            e_cap=self._e_cap(len(placed)),
            k_cap=self.nodes.k_cap,
            namespace_labels=self._ns_labels,
            m_cap=self._m_cap_for(placed),
        )
        self._epod_slots = {p.uid: (i, p) for i, p in enumerate(placed)}
        self._eterm_count = int((self._existing.term_kind != PAD).sum())
        self._existing_version = self._cache.pod_version
        self._existing_rebuilds += 1

    @staticmethod
    def _adopt_equivalent(cur, slots) -> bool:
        """True when every slotted pod is still present with a pack-
        equivalent object (the API confirmation of an assumed pod replaces
        the object without changing any packed field, cache.go:484) —
        adopting the new objects keeps the append-only discipline instead
        of forcing a full repack per bind confirmation."""
        adopted = []
        for uid, (slot, old) in slots.items():
            now = cur.get(uid)
            if now is None:
                return False
            if now is old:
                continue
            if (
                now.node_name == old.node_name
                and now.labels == old.labels
                and now.namespace == old.namespace
                and now.deletion_timestamp == old.deletion_timestamp
            ):
                adopted.append((uid, slot, now))
                continue
            return False
        for uid, slot, now in adopted:
            slots[uid] = (slot, now)
        return True

    def _e_cap(self, n_placed: int) -> int:
        return bucket_cap(max(self.e_cap_hint, n_placed))

    def _m_cap_for(self, placed) -> int:
        # scale expected term rows by the same growth ratio as pods
        n = max(len(placed), 1)
        n_terms = sum(
            1
            for p in placed
            if p.affinity is not None
            and (p.affinity.pod_affinity or p.affinity.pod_anti_affinity)
        )
        # upper-bound terms/pod at observed density (x4 slack for multi-term)
        est = self._e_cap(len(placed)) * (n_terms * 4) // n
        self._m_cap_max = max(self._m_cap_max, bucket_cap(max(est, 1), 1))
        return self._m_cap_max

    @property
    def hostnames_unique(self) -> bool:
        """True when no two nodes share a hostname label value — the
        precondition of the wave/workloads factored algebra's
        hostname-topology ≡ node-identity trick.  Computed once per
        SNAPSHOT (memoized on the static lineage: full packs, static
        generation, node population) instead of re-derived per batch;
        node usage churn never invalidates it because hostname labels are
        static row content."""
        nt = self.nodes
        if nt is None:
            return True
        key = (self._full_packs, self.static_generation, len(nt.name_to_idx))
        memo = getattr(self, "_hostnames_unique_memo", None)
        if memo is not None and memo[0] == key:
            return memo[1]
        from kubernetes_tpu.oracle.scores import HOSTNAME_LABEL

        hk = self.vocab.label_keys.lookup(HOSTNAME_LABEL)
        unique = True
        lv = nt.label_vals
        if 0 <= hk < lv.shape[1]:
            col = lv[:, hk]
            vals = col[col >= 0]
            unique = len(vals) == len(np.unique(vals))
        self._hostnames_unique_memo = (key, unique)
        return unique

    def apply_fast_usage(self, fc, cache: Cache) -> bool:
        """Vectorized usage refresh from a live FastCommitter: one numpy
        assignment per tensor instead of update()'s per-dirty-node Python
        walk (a 100k-pod fast drain dirties every node, and the walk cost
        ~30µs/row lands on the NEXT non-fast batch).

        Sound only when every usage change since the mirror's generation
        watermark came from fast commits the committer tracked — the
        caller (Scheduler._repack_mirror) verifies the lineage epoch
        (no external mutations / non-fast commits / full packs) and that
        no device batch is unharvested.  Fast pods carry no host ports, so
        the port rows the walk would rewrite are untouched by definition.
        Returns False when tensor shapes moved (caller falls back to the
        walk)."""
        nt = self.nodes
        if nt is None:
            return False
        if fc.n != nt.valid.shape[0] or fc.rn != nt.allocatable.shape[1]:
            return False
        nt.requested[:] = np.asarray(fc.used_rows, dtype=nt.requested.dtype)
        nt.nonzero_req[:, 0] = np.asarray(fc.nz0, dtype=nt.nonzero_req.dtype)
        nt.nonzero_req[:, 1] = np.asarray(fc.nz1, dtype=nt.nonzero_req.dtype)
        nt.num_pods[:] = np.asarray(fc.num_pods, dtype=nt.num_pods.dtype)
        # advance the watermark past the fast commits' generation bumps so
        # update()'s walk doesn't redo these rows; static changes can't be
        # pending here (they'd have bumped the external-mutation epoch)
        self.generation = max(
            (cn.generation for cn in cache.real_nodes()),
            default=self.generation,
        )
        self._row_updates += len(fc.touched)
        return True

    def update(self, cache: Cache, namespace_labels=None) -> None:
        """Bring the mirror up to date with the cache (incremental)."""
        self._cache = cache
        self._ns_labels = namespace_labels
        real = cache.real_nodes()
        names = [cn.node.name for cn in real]

        need_full = (
            self._force_full
            or self.nodes is None
            or len(real) > self.nodes.n_cap
            or bucket_cap(len(self.vocab.label_keys)) > self.nodes.k_cap
            # new label VALUES (e.g. from pending pods) outran the packed
            # parsed-int table — Gt/Lt selector eval would read stale rows
            or len(self.vocab.label_vals) > self.nodes.val_ints.shape[0]
        )
        order_dirty = False  # membership/zone changes move visit ranks
        if not need_full:
            known = set(self.nodes.name_to_idx)
            current = set(names)
            if known - current:
                # node REMOVALS compact slots via a full repack (rare)
                need_full = True
            else:
                # pure node ADDITIONS within capacity append rows in place
                # — the common churn case must not trigger repack storms
                for cn in real:
                    if cn.node.name in known:
                        continue
                    slot = len(self.nodes.name_to_idx)
                    if not write_node_row(
                        self.nodes, slot, cn.node, self.vocab
                    ):
                        need_full = True
                        break
                    order_dirty = True
                    # static_generation intentionally NOT advanced here:
                    # the dirty-row loop below must still see pending
                    # updates of OTHER nodes (it advances the watermark
                    # once at the end)
        if need_full:
            self._force_full = False
            self._full_pack(cache, namespace_labels)
            return

        lanes = ResourceLanes(self.vocab)
        dirty = 0
        for cn in real:
            if cn.generation <= self.generation:
                continue
            i = self.nodes.name_to_idx[cn.node.name]
            if cn.static_generation > self.static_generation:
                # node OBJECT changed — rewrite the static row too (a zone
                # label could have moved, so the visit order refreshes)
                if not write_node_row(self.nodes, i, cn.node, self.vocab):
                    self._force_full = True  # slot axis truncated
                order_dirty = True
            self._write_usage_row(cn, i, lanes)
            if self._force_full:
                break  # overflow: everything below is repacked anyway
            dirty += 1
        self._row_updates += dirty

        if self._force_full:
            # A row write overflowed its slot capacity (e.g. host-port rows
            # > U): the snapshot is missing entries RIGHT NOW, so repack at
            # grown bucket sizes before this batch schedules against it.
            self._force_full = False
            self._full_pack(cache, namespace_labels)
            return

        if order_dirty:
            refresh_visit_rank(
                self.nodes,
                [cn.node for cn in real],
                [self.nodes.name_to_idx[n] for n in names],
            )

        # Placed-pod tensors rebuild lazily via the `existing` property —
        # cache.pod_version (bumped on every pod add/remove/replace) is the
        # staleness signal.

        self.generation = max(
            (cn.generation for cn in real), default=self.generation
        )
        self.static_generation = max(
            (cn.static_generation for cn in real), default=self.static_generation
        )

    def _write_usage_row(self, cn, i: int, lanes: ResourceLanes) -> None:
        nt = self.nodes
        R = nt.allocatable.shape[1]
        nt.requested[i] = lanes.request_row(cn.requested, R)
        nt.nonzero_req[i, 0] = cn.non_zero_requested.milli_cpu
        nt.nonzero_req[i, 1] = -(-cn.non_zero_requested.memory // MEM_UNIT)
        nt.num_pods[i] = len(cn.pods)
        U = nt.used_ppk.shape[1]
        nt.used_ppk[i] = -2
        nt.used_ip[i] = -2
        nt.used_wild[i] = False
        from kubernetes_tpu.snapshot.schema import encode_port

        rows = [
            encode_port(self.vocab, hp)
            for pod in cn.pods.values()
            for hp in pod.host_ports()
        ]
        if len(rows) > U:
            # port slots overflow → grow on next full pack
            self._force_full = True
        for j, (ppk, ip, wild) in enumerate(rows[:U]):
            nt.used_ppk[i, j] = ppk
            nt.used_ip[i, j] = ip
            nt.used_wild[i, j] = wild

    def _full_pack(self, cache: Cache, namespace_labels) -> None:
        real = cache.real_nodes()
        placed = cache.placed_pods()
        for p in placed:
            for k, v in p.labels.items():
                self.vocab.intern_label(k, v)
            self.vocab.namespaces.intern(p.namespace)
        self.nodes = pack_nodes(
            [cn.node for cn in real],
            self.vocab,
            n_multiple=self.node_pad_multiple,
        )
        accumulate_node_usage(self.nodes, placed, self.vocab)
        self._existing = pack_existing_pods(
            placed,
            self.nodes.name_to_idx,
            self.vocab,
            e_cap=self._e_cap(len(placed)),
            k_cap=self.nodes.k_cap,
            namespace_labels=namespace_labels,
            m_cap=self._m_cap_for(placed),
        )
        self._existing_version = cache.pod_version
        self._epod_slots = {p.uid: (i, p) for i, p in enumerate(placed)}
        self._eterm_count = int((self._existing.term_kind != PAD).sum())
        self._existing_rebuilds += 1
        self.generation = max((cn.generation for cn in real), default=0)
        self.static_generation = max(
            (cn.static_generation for cn in real), default=0
        )
        self._full_packs += 1

    def stats(self) -> Dict[str, int]:
        return {
            "full_packs": self._full_packs,
            "row_updates": self._row_updates,
            "generation": self.generation,
        }
