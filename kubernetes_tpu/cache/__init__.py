"""Scheduler cache: authoritative in-memory cluster state + device mirror.

The host side mirrors pkg/scheduler/backend/cache (assume/forget/
finish-binding protocol, informer reconciliation, per-node generations);
the device side replaces the reference's Snapshot struct copy
(cache.go:185 UpdateSnapshot) with generation-gated repacking of only the
dirty node rows into the HBM tensors.
"""

from kubernetes_tpu.cache.cache import Cache  # noqa: F401
from kubernetes_tpu.cache.mirror import SnapshotMirror  # noqa: F401
