"""PodGroups + the gang batch planner — the host half of the workloads tier.

Mirrors the scheduler-plugins coscheduling surface (sigs.k8s.io
scheduler-plugins pkg/coscheduling): a ``PodGroup`` names a gang with a
``minMember`` quorum and a ``scheduleTimeoutSeconds`` budget; pods join by
spec field (``Pod.pod_group``) or by the conventional label.  The
reference plugin enforces the quorum with a Permit-time waiting barrier
(pods park at Permit until minMember of them have reserved, then release
together; on timeout every waiter is rejected).  Here the barrier
collapses into one batched admission pass (ops/coscheduling.py): the
planner below lays each gang's members out contiguously in the batch, the
kernel snapshots/restores its carried state around the member run, and a
gang whose members cannot cover the remaining quorum THIS batch rolls
back wholesale — same all-or-nothing outcome, no cross-cycle waiting
state.

``plan_batch`` defines the CANONICAL member order both the kernel and the
serial oracle (oracle/workloads.py) replay, so bit-identity is an
ordering contract, not a coincidence.

GangDirectory state is guarded by the owning Scheduler's ``_mu`` (its
mutators are called from informer handlers and the commit walk, which
already hold it) — registered in scheduler.py's ``_KTPU_GUARDED``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

# the conventional membership label (scheduler-plugins
# pkg/apis/scheduling/v1alpha1 — pod-group.scheduling.sigs.k8s.io/name)
GROUP_LABEL = "pod-group.scheduling.sigs.k8s.io/name"

# PermitWaitingTimeSeconds default of the reference coscheduling plugin
DEFAULT_SCHEDULE_TIMEOUT_S = 600.0


@dataclass
class PodGroup:
    """scheduling.x-k8s.io/v1alpha1 PodGroup, scheduler-relevant fields."""

    name: str
    namespace: str = "default"
    min_member: int = 1
    schedule_timeout_s: float = DEFAULT_SCHEDULE_TIMEOUT_S
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def group_key_of(pod) -> Optional[str]:
    """Namespace-scoped gang key of a pod, or None for ordinary pods."""
    name = getattr(pod, "pod_group", "") or pod.labels.get(GROUP_LABEL, "")
    if not name:
        return None
    return f"{pod.namespace}/{name}"


class GangDirectory:
    """PodGroup registry + per-gang admission bookkeeping.

    ``bound`` tracks member pod uids placed (assumed or bound) per gang —
    maintained by uid-set semantics from the scheduler's commit walk and
    informer handlers, so double notification cannot double-count.
    ``first_attempt`` opens a gang's scheduling window at its first
    admission attempt; the window closes on admission (quorum met) or on
    timeout (members rejected unresolvable, window reset so a later
    cluster event retries fresh)."""

    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self.groups: Dict[str, PodGroup] = {}
        self.bound: Dict[str, Set[str]] = {}
        self.first_attempt: Dict[str, float] = {}

    # -- registry -----------------------------------------------------------

    def upsert(self, pg: PodGroup) -> None:
        self.groups[pg.key] = pg

    def delete(self, key: str) -> None:
        self.groups.pop(key, None)
        self.first_attempt.pop(key, None)

    def get(self, key: str) -> Optional[PodGroup]:
        return self.groups.get(key)

    # -- membership bookkeeping ---------------------------------------------

    def note_placed(self, pod) -> None:
        key = group_key_of(pod)
        if key is not None:
            self.bound.setdefault(key, set()).add(pod.uid)

    def note_removed(self, pod) -> None:
        key = group_key_of(pod)
        if key is not None:
            s = self.bound.get(key)
            if s is not None:
                s.discard(pod.uid)

    def bound_count(self, key: str) -> int:
        s = self.bound.get(key)
        return len(s) if s else 0

    # -- scheduling window ---------------------------------------------------

    def note_attempt(self, key: str) -> None:
        self.first_attempt.setdefault(key, self.clock())

    def timed_out(self, key: str) -> bool:
        pg = self.groups.get(key)
        if pg is None or pg.schedule_timeout_s <= 0:
            return False
        start = self.first_attempt.get(key)
        return start is not None and (
            self.clock() - start > pg.schedule_timeout_s
        )

    def close_window(self, key: str) -> None:
        self.first_attempt.pop(key, None)


def plan_batch(
    pods: Sequence, group_of=group_key_of
) -> Tuple[List[int], Dict[str, List[int]]]:
    """The canonical workloads order: walk the batch in queue order and, at
    the FIRST member of each gang, splice in every member of that gang
    present in the batch (members keep their relative queue order);
    ordinary pods keep their positions between gangs.  Returns
    (order, gang_positions): ``order[i]`` is the original index scheduled
    at position i, ``gang_positions[key]`` the positions (in the NEW
    order) of that gang's members — contiguous by construction.

    Both the admission kernel and the serial oracle replay exactly this
    order, so gang contiguity is a planning invariant, not a kernel
    assumption."""
    members: Dict[str, List[int]] = {}
    for i, pod in enumerate(pods):
        key = group_of(pod)
        if key is not None:
            members.setdefault(key, []).append(i)
    order: List[int] = []
    gang_positions: Dict[str, List[int]] = {}
    emitted: Set[str] = set()
    for i, pod in enumerate(pods):
        key = group_of(pod)
        if key is None:
            order.append(i)
            continue
        if key in emitted:
            continue
        emitted.add(key)
        gang_positions[key] = list(
            range(len(order), len(order) + len(members[key]))
        )
        order.extend(members[key])
    return order, gang_positions


def gang_arrays(
    p_cap: int,
    gang_positions: Dict[str, List[int]],
    needs: Dict[str, int],
):
    """Pack the planner's output into the kernel's per-slot gang arrays
    (numpy; the scheduler device_puts them with the batch).  Returns
    (gang_id [p_cap], gang_first, gang_last, gang_need, g_cap, slot_keys)
    where slot_keys maps gang slot id → group key."""
    import numpy as np

    from kubernetes_tpu.snapshot.schema import bucket_cap

    gang_id = np.full(p_cap, -1, np.int32)
    gang_first = np.zeros(p_cap, bool)
    gang_last = np.zeros(p_cap, bool)
    gang_need = np.zeros(p_cap, np.int32)
    slot_keys: List[str] = []
    for key, positions in gang_positions.items():
        gid = len(slot_keys)
        slot_keys.append(key)
        for pos in positions:
            gang_id[pos] = gid
            gang_need[pos] = needs.get(key, 0)
        gang_first[positions[0]] = True
        gang_last[positions[-1]] = True
    g_cap = bucket_cap(max(len(slot_keys), 1), 1)
    return gang_id, gang_first, gang_last, gang_need, g_cap, slot_keys
