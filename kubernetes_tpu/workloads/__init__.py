"""Synthetic workload generation (the analogue of scheduler_perf's YAML op
DSL workload templates, test/integration/scheduler_perf/scheduler_perf.go:447)."""

from kubernetes_tpu.workloads.synthetic import (  # noqa: F401
    make_cluster,
    make_node,
    make_pod,
)
