"""Random cluster/pod generators for property tests.

Plays the role of the reference's testing/wrappers.go fluent builders plus
scheduler_perf's workload templates: quantities are Mi-aligned (matching the
packed snapshot's KiB-lane exactness contract, snapshot/schema.py docstring).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    ContainerPort,
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)

ZONES = ["zone-a", "zone-b", "zone-c"]
REGIONS = ["region-1", "region-2"]
DISKS = ["ssd", "hdd", "nvme"]
APPS = ["web", "db", "cache", "batch"]
NAMESPACES = ["default", "prod", "dev"]
TAINT_KEYS = ["dedicated", "gpu", "spot"]
IMAGES = ["img/web:1", "img/db:2", "img/cache:3"]
HOSTNAME = "kubernetes.io/hostname"


def make_node(rng: random.Random, i: int) -> Node:
    labels = {
        "topology.kubernetes.io/zone": rng.choice(ZONES),
        "topology.kubernetes.io/region": rng.choice(REGIONS),
        HOSTNAME: f"node-{i}",
    }
    if rng.random() < 0.5:
        labels["disk"] = rng.choice(DISKS)
    if rng.random() < 0.3:
        labels["tier"] = str(rng.randrange(1, 5))
    taints: List[Taint] = []
    if rng.random() < 0.2:
        taints.append(
            Taint(
                key=rng.choice(TAINT_KEYS),
                value=rng.choice(["", "true", "team-a"]),
                effect=rng.choice(["NoSchedule", "PreferNoSchedule", "NoExecute"]),
            )
        )
    images = {}
    for img in IMAGES:
        if rng.random() < 0.4:
            images[img] = rng.randrange(50, 900) * 1024 * 1024
    return Node(
        name=f"node-{i}",
        labels=labels,
        capacity=Resource.from_map(
            {
                "cpu": f"{rng.choice([2, 4, 8, 16])}",
                "memory": f"{rng.choice([4, 8, 16, 32])}Gi",
                "pods": rng.choice([16, 32, 110]),
            }
        ),
        taints=tuple(taints),
        unschedulable=rng.random() < 0.05,
        images=images,
    )


def _label_selector(rng: random.Random) -> Optional[LabelSelector]:
    r = rng.random()
    if r < 0.5:
        return LabelSelector(match_labels={"app": rng.choice(APPS)})
    if r < 0.8:
        return LabelSelector(
            match_expressions=(
                LabelSelectorRequirement(
                    "app",
                    rng.choice(["In", "NotIn", "Exists", "DoesNotExist"]),
                    tuple(rng.sample(APPS, rng.randrange(1, 3))),
                ),
            )
        )
    return LabelSelector()  # empty ⇒ matches everything


def _affinity_term(rng: random.Random) -> PodAffinityTerm:
    topo = rng.choice(["topology.kubernetes.io/zone", HOSTNAME])
    kwargs = dict(topology_key=topo, label_selector=_label_selector(rng))
    r = rng.random()
    if r < 0.2:
        kwargs["namespaces"] = tuple(rng.sample(NAMESPACES, rng.randrange(1, 3)))
    elif r < 0.3:
        kwargs["namespace_selector"] = LabelSelector()  # all namespaces
    return PodAffinityTerm(**kwargs)


def make_pod(
    rng: random.Random,
    name: str,
    node_name: str = "",
    hard: bool = False,
) -> Pod:
    labels = {"app": rng.choice(APPS)}
    if rng.random() < 0.3:
        labels["tier"] = str(rng.randrange(1, 5))
    containers = [
        Container(
            name="c0",
            requests={
                "cpu": f"{rng.choice([0, 100, 250, 500, 1000])}m",
                "memory": f"{rng.choice([0, 128, 256, 512, 1024])}Mi",
            },
        )
    ]
    kwargs = dict(
        name=name,
        namespace=rng.choice(NAMESPACES),
        labels=labels,
        node_name=node_name,
        containers=containers,
        priority=rng.randrange(0, 3) * 100,
        images=tuple(rng.sample(IMAGES, rng.randrange(0, 3))),
    )

    if rng.random() < 0.35:
        kwargs["node_selector"] = (
            {"disk": rng.choice(DISKS)}
            if rng.random() < 0.7
            else {"topology.kubernetes.io/zone": rng.choice(ZONES)}
        )
    if rng.random() < 0.35:
        req = None
        if rng.random() < 0.7:
            op = rng.choice(["In", "NotIn", "Exists", "Gt", "Lt"])
            vals: Tuple[str, ...]
            if op in ("Gt", "Lt"):
                key, vals = "tier", (str(rng.randrange(1, 5)),)
            else:
                key, vals = "disk", tuple(rng.sample(DISKS, rng.randrange(1, 3)))
            req = NodeSelector(
                (
                    NodeSelectorTerm(
                        match_expressions=(NodeSelectorRequirement(key, op, vals),)
                    ),
                )
            )
        pref = ()
        if rng.random() < 0.5:
            pref = (
                PreferredSchedulingTerm(
                    weight=rng.randrange(1, 100),
                    preference=NodeSelectorTerm(
                        match_expressions=(
                            NodeSelectorRequirement(
                                "disk", "In", (rng.choice(DISKS),)
                            ),
                        )
                    ),
                ),
            )
        kwargs["affinity"] = Affinity(
            node_affinity=NodeAffinity(
                required_during_scheduling_ignored_during_execution=req,
                preferred_during_scheduling_ignored_during_execution=pref,
            )
        )
    if rng.random() < 0.3:
        kwargs["tolerations"] = (
            Toleration(
                key=rng.choice(TAINT_KEYS + [""]),
                operator=rng.choice(["Exists", "Equal"]),
                value=rng.choice(["", "true"]),
                effect=rng.choice(["", "NoSchedule", "PreferNoSchedule"]),
            ),
        )
    if rng.random() < 0.3:
        aff = kwargs.get("affinity") or Affinity()
        pa = None
        paa = None
        if rng.random() < 0.6:
            req_terms = (_affinity_term(rng),) if rng.random() < 0.6 else ()
            pref_terms = (
                (
                    WeightedPodAffinityTerm(
                        weight=rng.randrange(1, 100),
                        pod_affinity_term=_affinity_term(rng),
                    ),
                )
                if rng.random() < 0.6
                else ()
            )
            if req_terms or pref_terms:
                pa = PodAffinity(
                    required_during_scheduling_ignored_during_execution=req_terms,
                    preferred_during_scheduling_ignored_during_execution=pref_terms,
                )
        if rng.random() < 0.6:
            req_terms = (_affinity_term(rng),) if rng.random() < 0.5 else ()
            pref_terms = (
                (
                    WeightedPodAffinityTerm(
                        weight=rng.randrange(1, 100),
                        pod_affinity_term=_affinity_term(rng),
                    ),
                )
                if rng.random() < 0.6
                else ()
            )
            if req_terms or pref_terms:
                paa = PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=req_terms,
                    preferred_during_scheduling_ignored_during_execution=pref_terms,
                )
        if pa or paa:
            kwargs["affinity"] = Affinity(
                node_affinity=aff.node_affinity,
                pod_affinity=pa,
                pod_anti_affinity=paa,
            )
    if rng.random() < 0.25:
        kwargs["topology_spread_constraints"] = (
            TopologySpreadConstraint(
                max_skew=rng.randrange(1, 3),
                topology_key=rng.choice(
                    ["topology.kubernetes.io/zone", HOSTNAME]
                ),
                when_unsatisfiable=rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
                label_selector=_label_selector(rng),
                min_domains=rng.choice([None, 2]),
                node_affinity_policy=rng.choice(["Honor", "Ignore"]),
                node_taints_policy=rng.choice(["Honor", "Ignore"]),
            ),
        )
    if rng.random() < 0.15:
        kwargs["containers"] = containers + [
            Container(
                name="c1",
                ports=(
                    ContainerPort(
                        container_port=8080,
                        host_port=rng.choice([8080, 9090]),
                        protocol="TCP",
                    ),
                ),
            )
        ]
    if hard and rng.random() < 0.2:
        kwargs["node_name"] = f"node-{rng.randrange(0, 4)}"
    return Pod(**kwargs)


def make_cluster(
    rng: random.Random, n_nodes: int, n_placed: int
) -> Tuple[List[Node], List[Pod]]:
    nodes = [make_node(rng, i) for i in range(n_nodes)]
    placed = []
    for j in range(n_placed):
        node = rng.choice(nodes)
        placed.append(make_pod(rng, f"placed-{j}", node_name=node.name))
    return nodes, placed
