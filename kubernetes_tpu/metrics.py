"""Scheduler metrics: Prometheus-compatible series + async recorder.

Mirrors pkg/scheduler/metrics/metrics.go:86-260 (the ~25 scheduler series,
stability labels dropped) and metric_recorder.go (the lock-free buffered
async recorder, flush interval 1s).  The TPU build adds device-path series
(gang dispatch timing, fast-path batch counts, HBM upload bytes) because
the hot loop is one fused kernel dispatch rather than per-pod goroutines.

Export is the Prometheus text exposition format (``registry.expose()``) —
what the server wrapper serves at /metrics.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# core metric types
# ---------------------------------------------------------------------------


def _esc_label(v: str) -> str:
    """Prometheus text-exposition label-value escaping: backslash, double
    quote, and newline (exposition format spec).  Pod names and plugin
    reason strings flow into labels, so raw interpolation would corrupt
    the scrape on the first quote or newline."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc_label(str(v))}"' for k, v in labels)
    return "{" + inner + "}"


class Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str = "", label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        # binding workers record series concurrently with the scheduling
        # loop; the read-modify-write below (dict get + add) loses updates
        # without it.  Frequency is per batch/slice, not per pod, so the
        # uncontended acquire is noise next to the observed phases.
        self._mu = threading.Lock()

    def expose(self) -> List[str]:
        raise NotImplementedError

    def _key(self, labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
        if not self.label_names:  # hot unlabeled counters skip the genexpr
            return ()
        return tuple((k, str(labels.get(k, ""))) for k in self.label_names)


class Counter(Metric):
    kind = "counter"

    def __init__(self, name, help_="", label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = self._key(labels)
        with self._mu:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        # snapshot under the metric lock: a concurrent inc from a binding
        # worker mid-scrape would otherwise raise "dictionary changed size
        # during iteration" (and could expose a torn series list)
        with self._mu:
            items = sorted(self._values.items())
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        for k, v in items:
            out.append(f"{self.name}{_fmt_labels(k)} {v:g}")
        return out


class Gauge(Metric):
    kind = "gauge"

    def __init__(self, name, help_="", label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        k = self._key(labels)
        with self._mu:
            self._values[k] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = self._key(labels)
        with self._mu:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        with self._mu:
            items = sorted(self._values.items())
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        for k, v in items:
            out.append(f"{self.name}{_fmt_labels(k)} {v:g}")
        return out


# the reference's default scheduler duration buckets: 0.001 → ~16s
def duration_buckets() -> List[float]:
    return [0.001 * (2**i) for i in range(15)]


# widened buckets for the serving-tier latency SLIs (0.001 → ~17.5 min):
# at saturation the open-loop harness drives queue waits far past the
# default 16 s ceiling, and a p99 that lands in the overflow bucket comes
# back as +Inf (Histogram.percentile) — the SLO series use these so the
# sentinel only fires when latency is truly off the scale
def wide_duration_buckets() -> List[float]:
    return [0.001 * (2**i) for i in range(21)]


# per-kernel execute buckets (the dispatch ledger, observability/
# kernels.py): submits range from tens of µs (a warm static_eval) to
# tens of seconds (a first-trace compile on a cold cache), so the span
# is wider at both ends than the scheduler duration buckets
def kernel_duration_buckets() -> List[float]:
    return [0.00001 * (2**i) for i in range(24)]


# coarse batch-size label values for the per-pod attempt-latency series:
# one batched dispatch smears its latency uniformly over the batch, so the
# serving analysis needs to know HOW MUCH smear a sample carries (batch=1
# is a real per-pod latency; batch=4096+ is a drain average).  Coarse
# powers-of-16 keep the label cardinality at 5.
def batch_size_bucket(n: int) -> str:
    if n <= 1:
        return "1"
    if n < 16:
        return "2-15"
    if n < 256:
        return "16-255"
    if n < 4096:
        return "256-4095"
    return "4096+"


def bucket_quantile(bounds, counts, q: float) -> Tuple[float, int]:
    """``(estimate, n)``: the promql histogram_quantile bucket
    interpolation over ``counts`` aligned with ``bounds`` plus one
    overflow slot last.  A rank landing in the overflow bucket returns
    ``math.inf`` — an explicit sentinel, NOT the top finite bound:
    clamping silently under-reports the quantile exactly when the series
    saturates.  The ONE copy of this estimate — ``Histogram.percentile``
    and the SLO evaluator's windowed quantiles both delegate here, so
    breach decisions can never diverge from /metrics-derived values."""
    n = int(sum(counts))
    if n == 0:
        return 0.0, 0
    rank = q * n
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank:
            if i >= len(bounds):
                return math.inf, n
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - (cum - c)) / c if c else 0.0
            return float(lo + (hi - lo) * frac), n
    return math.inf, n


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, help_="", label_names=(), buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help_, label_names)
        self.buckets = sorted(buckets if buckets is not None else duration_buckets())
        self._counts: Dict[Tuple, List[int]] = {}
        self._sum: Dict[Tuple, float] = {}
        self._n: Dict[Tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        self.observe_n(value, 1, **labels)

    def observe_n(self, value: float, n: int, **labels) -> None:
        """n identical observations in one bucket update — the batched
        dispatch amortizes one latency over a whole batch, so per-pod
        series would otherwise pay len(batch) bucket walks per cycle."""
        if n <= 0:
            return
        k = self._key(labels)
        with self._mu:
            counts = self._counts.get(k)
            if counts is None:
                counts = self._counts[k] = [0] * (len(self.buckets) + 1)
                self._sum[k] = 0.0
                self._n[k] = 0
            counts[bisect.bisect_left(self.buckets, value)] += n
            self._sum[k] += value * n
            self._n[k] += n

    def merge_counts(self, counts, sum_, n, **labels) -> None:
        """Merge PRE-BUCKETED observations: ``counts`` aligns with
        ``len(buckets)+1`` (overflow last).  The SLO tier's batched feed —
        its ingest loop buckets into plain arrays off the registry lock
        and syncs deltas here on scrape, so the hot join never pays a
        per-observation metric-lock acquisition."""
        if n <= 0:
            return
        k = self._key(labels)
        with self._mu:
            cur = self._counts.get(k)
            if cur is None:
                cur = self._counts[k] = [0] * (len(self.buckets) + 1)
                self._sum[k] = 0.0
                self._n[k] = 0
            for i, c in enumerate(counts):
                if c:
                    cur[i] += c
            self._sum[k] += sum_
            self._n[k] += n

    def count(self, **labels) -> int:
        return self._n.get(self._key(labels), 0)

    def total_sum(self, **labels) -> float:
        return self._sum.get(self._key(labels), 0.0)

    def percentile(self, q: float, **labels) -> float:
        """Bucket-interpolated quantile (the promql histogram_quantile
        estimate) over ALL label sets when none given, else one set.

        A rank landing in the overflow (+Inf) bucket returns ``math.inf``
        — an explicit sentinel, NOT the top finite bound (see
        ``bucket_quantile``).  Callers that want a finite display value
        clamp explicitly; latency SLIs widen their buckets
        (``wide_duration_buckets``) instead."""
        if self.label_names and not labels:
            # aggregate across label sets (snapshot under the lock — a
            # concurrent observe can add a label set mid-iteration)
            counts = [0] * (len(self.buckets) + 1)
            with self._mu:
                rows = [list(c) for c in self._counts.values()]
            for row in rows:
                for i, c in enumerate(row):
                    counts[i] += c
        else:
            k = self._key(labels)
            with self._mu:
                counts = list(
                    self._counts.get(k, [0] * (len(self.buckets) + 1))
                )
        est, _ = bucket_quantile(self.buckets, counts, q)
        return est

    def expose(self) -> List[str]:
        # consistent snapshot under the lock (see Counter.expose): bucket
        # rows, _sum and _count must come from ONE moment or a concurrent
        # observe_n mid-scrape yields sum/count that disagree with buckets
        with self._mu:
            snap = [
                (k, list(self._counts[k]), self._sum[k], self._n[k])
                for k in sorted(self._counts)
            ]
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        for k, counts, total, n in snap:
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                lab = k + (("le", f"{b:g}"),)
                out.append(f"{self.name}_bucket{_fmt_labels(lab)} {cum}")
            cum += counts[-1]
            lab = k + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lab)} {cum}")
            out.append(f"{self.name}_sum{_fmt_labels(k)} {total:g}")
            out.append(f"{self.name}_count{_fmt_labels(k)} {n}")
        return out


class Registry:
    def __init__(self) -> None:
        self._metrics: List[Metric] = []

    def register(self, metric: Metric) -> Metric:
        # duplicate names would expose two HELP/TYPE headers for one series
        # family — rejected by Prometheus parsers mid-scrape
        if any(m.name == metric.name for m in self._metrics):
            raise ValueError(f"metric {metric.name!r} already registered")
        self._metrics.append(metric)
        return metric

    def expose(self) -> str:
        lines: List[str] = []
        for m in self._metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# async recorder (metric_recorder.go)
# ---------------------------------------------------------------------------


@dataclass
class _Observation:
    metric: Histogram
    value: float
    labels: Dict[str, str]


class MetricAsyncRecorder:
    """Buffered histogram recorder: observations append to a bounded buffer
    and flush on interval or overflow (metric_recorder.go: bufferSize 1000,
    interval 1s).  The scheduler loop is single-threaded here, so flushing
    happens inline rather than on a goroutine; the buffer still decouples
    the hot path from histogram bucket math."""

    BUFFER_SIZE = 1000

    def __init__(self, flush_interval_s: float = 1.0, clock=time.monotonic):
        self._buf: List[_Observation] = []
        self._interval = flush_interval_s
        self._clock = clock
        self._last_flush = clock()

    def observe(self, metric: Histogram, value: float, **labels) -> None:
        self._buf.append(_Observation(metric, value, labels))
        if (
            len(self._buf) >= self.BUFFER_SIZE
            or self._clock() - self._last_flush >= self._interval
        ):
            self.flush()

    def flush(self) -> None:
        for obs in self._buf:
            obs.metric.observe(obs.value, **obs.labels)
        self._buf.clear()
        self._last_flush = self._clock()


# ---------------------------------------------------------------------------
# per-phase attribution (the scheduler_perf collector's per-op breakdown:
# test/integration/scheduler_perf reports steady-state throughput WITH the
# time attributed to each phase of the hot loop, so a regression names its
# phase instead of hiding in a total)
# ---------------------------------------------------------------------------

# The canonical hot-loop phases of one batched scheduling cycle.  Async
# dispatch makes two of them subtle: ``device`` is the host-side submit of
# the jitted kernel (the XLA work itself overlaps later host phases), and
# ``d2h`` is the time the harvest BLOCKS waiting for results — i.e. the
# device+copy latency that host work failed to hide.  ``bind`` accumulates
# worker-thread time, so it can exceed the drain's wall clock.
PHASES = (
    "queue_pop",  # activeQ pop + batch-extension predicate
    "pack",  # signature keys, PreFilter/PreScore, row packing, mirror sync
    "h2d",  # host→device uploads (committer state, ids, stacked sigs)
    "device",  # jitted dispatch submit (async: XLA overlaps host work)
    "d2h",  # blocked time fetching results the async copy hadn't landed
    "commit",  # assume/reserve/permit walk + committer replay
    "bind",  # binding-cycle worker time (sink + post-bind bookkeeping)
)


class PhaseAccumulator:
    """Cumulative per-phase wall seconds + per-observation histogram feed.

    ``add`` is called from the scheduling loop AND binding workers, so it
    takes a lock; the frequency is per batch / per bind chunk (not per
    pod), which keeps the overhead unmeasurable next to the phases
    themselves.  ``snapshot`` returns a plain dict — bench.py diffs two
    snapshots around the timed drain to report ``config0_phases``.
    """

    def __init__(self, hist: Optional[Histogram] = None):
        self._mu = threading.Lock()
        self._totals: Dict[str, float] = {}
        self.hist = hist
        # optional observability.Tracer: when tracing is enabled every
        # accumulated phase interval ALSO lands as a complete span on the
        # recording thread's track — one hook covers all dispatch paths
        self.tracer = None

    def add(self, phase: str, dt: float) -> None:
        with self._mu:
            self._totals[phase] = self._totals.get(phase, 0.0) + dt
            if self.hist is not None:
                self.hist.observe(dt, phase=phase)
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.complete_tail(phase, dt)

    def timer(self, phase: str):
        """Context manager: accumulate the block's wall time."""
        return _PhaseTimer(self, phase)

    def snapshot(self) -> Dict[str, float]:
        with self._mu:
            return dict(self._totals)

    @staticmethod
    def diff(after: Dict[str, float], before: Dict[str, float]) -> Dict[str, float]:
        out = {}
        for k, v in after.items():
            d = v - before.get(k, 0.0)
            if d > 0.0:
                out[k] = d
        return out


class _PhaseTimer:
    __slots__ = ("acc", "phase", "_t0")

    def __init__(self, acc: PhaseAccumulator, phase: str):
        self.acc = acc
        self.phase = phase

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.acc.add(self.phase, time.perf_counter() - self._t0)
        return False


# ---------------------------------------------------------------------------
# the scheduler's series (metrics.go:86-260)
# ---------------------------------------------------------------------------

SCHEDULED = "scheduled"
UNSCHEDULABLE = "unschedulable"
ERROR = "error"


class SchedulerMetrics:
    def __init__(self) -> None:
        r = self.registry = Registry()
        self.schedule_attempts = r.register(
            Counter(
                "scheduler_schedule_attempts_total",
                "Number of attempts to schedule pods, by result and profile.",
                ("result", "profile"),
            )
        )
        self.attempt_duration = r.register(
            Histogram(
                "scheduler_scheduling_attempt_duration_seconds",
                "Scheduling attempt latency (algorithm + binding).  The "
                "batched dispatch amortizes one latency over the batch; "
                "the coarse batch label (batch_size_bucket) says how much "
                "smear a sample carries (batch=1 is a real per-pod "
                "latency, batch=4096+ a drain average).",
                ("result", "profile", "batch"),
            )
        )
        self.algorithm_duration = r.register(
            Histogram(
                "scheduler_scheduling_algorithm_duration_seconds",
                "Scheduling algorithm latency.",
                ("profile",),
            )
        )
        self.pod_scheduling_sli_duration = r.register(
            Histogram(
                "scheduler_pod_scheduling_sli_duration_seconds",
                "E2e latency for a pod being scheduled, from first attempt.",
                ("attempts",),
            )
        )
        self.pod_scheduling_attempts = r.register(
            Histogram(
                "scheduler_pod_scheduling_attempts",
                "Number of attempts to successfully schedule a pod.",
                (),
                buckets=[1, 2, 4, 8, 16],
            )
        )
        self.extension_point_duration = r.register(
            Histogram(
                "scheduler_framework_extension_point_duration_seconds",
                "Latency for running all plugins of an extension point.",
                ("extension_point", "status", "profile"),
            )
        )
        self.plugin_execution_duration = r.register(
            Histogram(
                "scheduler_plugin_execution_duration_seconds",
                "Duration for running a plugin at an extension point.",
                ("plugin", "extension_point", "status"),
                buckets=[0.00001 * (1.5**i) for i in range(20)],
            )
        )
        self.queue_incoming_pods = r.register(
            Counter(
                "scheduler_queue_incoming_pods_total",
                "Number of pods added to scheduling queues by event and queue type.",
                ("queue", "event"),
            )
        )
        self.pending_pods = r.register(
            Gauge(
                "scheduler_pending_pods",
                "Pending pods by queue: active, backoff, unschedulable, gated.",
                ("queue",),
            )
        )
        self.cache_size = r.register(
            Gauge(
                "scheduler_scheduler_cache_size",
                "Number of nodes, pods and assumed pods in the scheduler cache.",
                ("type",),
            )
        )
        self.preemption_attempts = r.register(
            Counter(
                "scheduler_preemption_attempts_total",
                "Total preemption attempts in the cluster until now.",
            )
        )
        self.preemption_victims = r.register(
            Histogram(
                "scheduler_preemption_victims",
                "Number of selected preemption victims.",
                (),
                buckets=[1, 2, 4, 8, 16, 32, 64],
            )
        )
        self.goroutines = r.register(
            Gauge(
                "scheduler_goroutines",
                "Number of running goroutines split by work type (threads here).",
                ("work",),
            )
        )
        self.event_handling_duration = r.register(
            Histogram(
                "scheduler_event_handling_duration_seconds",
                "Event handling latency by resource and action.",
                ("event",),
                buckets=[0.00001 * (1.5**i) for i in range(20)],
            )
        )
        self.queueing_hint_duration = r.register(
            Histogram(
                "scheduler_queueing_hint_execution_duration_seconds",
                "Latency of QueueingHintFn execution.",
                ("plugin", "event", "hint"),
                buckets=[0.00001 * (1.5**i) for i in range(20)],
            )
        )
        self.binding_duration = r.register(
            Histogram(
                "scheduler_binding_duration_seconds",
                "Binding latency.",
                (),
            )
        )
        self.permit_wait_duration = r.register(
            Histogram(
                "scheduler_permit_wait_duration_seconds",
                "Latency of waiting on Permit.",
                ("result",),
            )
        )
        self.unschedulable_reasons = r.register(
            Gauge(
                "scheduler_unschedulable_pods",
                "Number of unschedulable pods by plugin name.",
                ("plugin",),
            )
        )
        # --- TPU-path extensions (no reference counterpart: the hot loop
        # is a fused device dispatch, not per-pod goroutines) ---
        self.gang_dispatch_duration = r.register(
            Histogram(
                "scheduler_tpu_gang_dispatch_duration_seconds",
                "Device time for one fused gang dispatch (batch filter+score+select).",
                ("path",),  # fast / scan
            )
        )
        self.batch_size_hist = r.register(
            Histogram(
                "scheduler_tpu_batch_size",
                "Pods per gang batch.",
                (),
                buckets=[1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024],
            )
        )
        self.wave_admitted = r.register(
            Counter(
                "scheduler_tpu_wave_admitted_total",
                "Pods whose speculative wave placement survived the "
                "conflict-resolution pass unchanged (ops/wave.py).",
            )
        )
        self.wave_conflicts = r.register(
            Counter(
                "scheduler_tpu_wave_conflicts_total",
                "Pods demoted by the wave's conflict-resolution pass, by "
                "conflicting constraint kind "
                "(spread / affinity / ports / fit / score).",
                ("kind",),
            )
        )
        self.wave_fallback = r.register(
            Counter(
                "scheduler_tpu_wave_fallback_total",
                "Wave-shaped work (pods/batches carrying cross-pod "
                "constraint terms or in-batch host ports) that fell off "
                "the factored wave engine, by reason (dup_hostname / "
                "kill_switch / nominated / extender / host_filters / "
                "host_scores / ...).  reason=ports and "
                "reason=sampling_compat are RETIRED rungs — the factored "
                "engine carries both — and must stay zero; a bump is a "
                "fallback-ladder regression.",
                ("reason",),
            )
        )
        self.gang_admitted = r.register(
            Counter(
                "scheduler_tpu_gang_admitted_total",
                "Gang (PodGroup) member pods admitted by the workloads "
                "tier's all-or-nothing admission pass (ops/coscheduling.py).",
            )
        )
        self.gang_rollbacks = r.register(
            Counter(
                "scheduler_tpu_gang_rollbacks_total",
                "Gangs whose members could not cover the remaining "
                "minMember quorum this batch — every member placement, "
                "topology count, and device grant restored in-kernel.",
            )
        )
        self.dra_allocations = r.register(
            Counter(
                "scheduler_tpu_dra_allocations_total",
                "ResourceClaims allocated through the batched DRA "
                "device-matching kernel (ops/dra.py).",
            )
        )
        self.plan_forks = r.register(
            Counter(
                "scheduler_tpu_plan_forks_total",
                "Counterfactual snapshot forks simulated by the planner "
                "tier (ops/counterfactual.py) — K forks per fused "
                "[K, P, N] dispatch.",
            )
        )
        self.plan_duration = r.register(
            Histogram(
                "scheduler_tpu_plan_duration_seconds",
                "End-to-end planner runs (fork packing + one fused "
                "dispatch + readback) by planner.",
                ("planner",),
            )
        )
        self.resident_rounds = r.register(
            Counter(
                "scheduler_tpu_resident_rounds_total",
                "Speculation/admission rounds run by the device-resident "
                "drain loop (ops/resident.py) across all runs.",
            )
        )
        self.host_roundtrips = r.register(
            Counter(
                "scheduler_tpu_host_roundtrips_total",
                "Blocking device→host result fetches across all paths "
                "(dispatch harvests plus static-eval / preemption-narrow / "
                "diagnosis reads) — the traffic the resident drain "
                "amortizes.",
            )
        )
        self.d2h_bytes = r.register(
            Counter(
                "scheduler_tpu_d2h_bytes_total",
                "Bytes copied device→host by blocking result fetches.",
            )
        )
        self.snapshot_pack_duration = r.register(
            Histogram(
                "scheduler_tpu_snapshot_pack_duration_seconds",
                "Host time packing the incremental snapshot mirror.",
                (),
            )
        )
        self.phase_duration = r.register(
            Histogram(
                "scheduler_tpu_phase_duration_seconds",
                "Per-batch hot-loop time by phase (queue_pop/pack/h2d/"
                "device/d2h/wave_resolve/resident_rounds/commit/bind).",
                ("phase",),
            )
        )
        self.sanitizer_violations = r.register(
            Counter(
                "scheduler_tpu_sanitizer_violations_total",
                "Invariant violations detected by the KTPU_SANITIZE runtime "
                "mode (kind: lock / mirror).",
                ("kind",),
            )
        )
        self.jit_recompiles = r.register(
            Counter(
                "scheduler_tpu_jit_recompiles_total",
                "Unexpected post-warmup jit compilation-cache misses per "
                "root (KTPU_SANITIZE=1 retrace hook; fn: module.function).",
                ("fn",),
            )
        )
        self.shape_check_failures = r.register(
            Counter(
                "scheduler_tpu_shape_check_failures_total",
                "eval_shape cross-check mismatches against the symbolic "
                "shape interpreter, per jit root (KTPU_SANITIZE=1; fn: "
                "module.function).",
                ("fn",),
            )
        )
        self.chaos_injected = r.register(
            Counter(
                "scheduler_tpu_chaos_injected_total",
                "Faults delivered by the chaos subsystem, by kind "
                "(watch_cut / compact / api_error / api_timeout / "
                "bind_conflict / bind_slow / node_flap / lease_contention / "
                "clock_skew).",
                ("kind",),
            )
        )
        self.chaos_recovery = r.register(
            Histogram(
                "scheduler_tpu_chaos_recovery_seconds",
                "Latency from a fault injection to the next fully drained "
                "scheduling queue, by fault kind.",
                ("kind",),
            )
        )
        # --- observability-layer overhead accounting (observability/) ---
        # refreshed on scrape from Tracer.stats()/FlightRecorder.stats()
        # (Scheduler.refresh_gauges) so the hot recording path never touches
        # the registry.
        self.trace_buffered = r.register(
            Gauge(
                "scheduler_tpu_trace_buffered_events",
                "Trace events currently buffered by the span tracer.",
            )
        )
        self.trace_dropped = r.register(
            Gauge(
                "scheduler_tpu_trace_dropped_events",
                "Trace events dropped by the tracer's bounded buffer since "
                "the trace started.",
            )
        )
        self.tracer_overhead = r.register(
            Gauge(
                "scheduler_tpu_tracer_overhead_seconds",
                "Cumulative host seconds spent appending trace events "
                "(the tracer's own cost, for overhead audits).",
            )
        )
        self.flightrec_events = r.register(
            Gauge(
                "scheduler_tpu_flightrecorder_events",
                "Pod lifecycle events currently retained in the flight "
                "recorder ring.",
            )
        )
        self.flightrec_evicted = r.register(
            Gauge(
                # scrape-refreshed snapshot of a monotonic count — exposed
                # as a gauge, so no _total suffix (OpenMetrics lint rejects
                # a _total-named gauge)
                "scheduler_tpu_flightrecorder_evicted_events",
                "Pod lifecycle events evicted from the flight recorder "
                "ring since process start (monotonic, sampled on scrape).",
            )
        )
        # --- steady-state SLO tier (observability/slo.py) ---
        self.slo_stage_duration = r.register(
            Histogram(
                "scheduler_tpu_slo_stage_duration_seconds",
                "Per-pod latency attribution joined from flight-recorder "
                "breadcrumbs by stage (queue_wait / backoff / dispatch / "
                "commit / bind) plus the e2e SLI — monotonic-clock "
                "durations, widened buckets.",
                ("stage",),
                buckets=wide_duration_buckets(),
            )
        )
        self.slo_burn_rate = r.register(
            Gauge(
                "scheduler_tpu_slo_burn_rate",
                "Error-budget burn rate per SLO objective over the rolling "
                "window (1.0 = burning exactly the budget), sampled on "
                "scrape.",
                ("objective",),
            )
        )
        self.slo_breaches = r.register(
            Counter(
                "scheduler_tpu_slo_breaches_total",
                "SLO breaches that froze and dumped the black-box trace "
                "ring, by objective.",
                ("objective",),
            )
        )
        self.trace_evicted = r.register(
            Gauge(
                "scheduler_tpu_trace_evicted_events",
                "Trace events evicted from the black-box ring since it was "
                "armed (monotonic, sampled on scrape).",
            )
        )
        # --- device telemetry ledger (observability/kernels.py): the
        # per-kernel split of the device path the aggregate
        # host_roundtrips/d2h_bytes counters can't attribute ---
        self.kernel_dispatches = r.register(
            Counter(
                "scheduler_tpu_kernel_dispatches_total",
                "Dispatches per jit root (kernel: module.function, the "
                "sanitizer's jit-root roster).",
                ("kernel",),
            )
        )
        self.kernel_execute = r.register(
            Histogram(
                "scheduler_tpu_kernel_execute_seconds",
                "Per-dispatch execute wall time by kernel — the dispatch "
                "call's wall clock (host submit on async backends; the "
                "device latency the host failed to hide shows in the "
                "kernel d2h series).  First-trace compiles are excluded "
                "(they count into the compile series).",
                ("kernel",),
                buckets=kernel_duration_buckets(),
            )
        )
        self.kernel_compiles = r.register(
            Counter(
                "scheduler_tpu_kernel_compiles_total",
                "Dispatches that grew a kernel's jit compilation cache "
                "(first trace of a new shape/static bucket).",
                ("kernel",),
            )
        )
        self.kernel_compile_seconds = r.register(
            Counter(
                "scheduler_tpu_kernel_compile_seconds_total",
                "Wall seconds spent in compiling dispatches, by kernel.",
                ("kernel",),
            )
        )
        self.kernel_d2h_bytes = r.register(
            Counter(
                "scheduler_tpu_kernel_d2h_bytes_total",
                "Blocking device→host readback bytes attributed per "
                "kernel through the Scheduler._d2h choke point "
                "(kernel=_untagged: fetches with no kernel context, so "
                "the rows sum to scheduler_tpu_d2h_bytes_total).",
                ("kernel",),
            )
        )
        self.kernel_d2h_seconds = r.register(
            Counter(
                "scheduler_tpu_kernel_d2h_seconds_total",
                "Seconds blocked in device→host readbacks per kernel.",
                ("kernel",),
            )
        )
        self.kernel_regressions = r.register(
            Counter(
                "scheduler_tpu_kernel_regressions_total",
                "Sustained per-kernel execute-time regressions detected "
                "by the dispatch ledger's sentinel (each one files a "
                "kernel_regression breach through the SLO tier's "
                "black-box freeze→dump machinery when installed).",
                ("kernel",),
            )
        )
        self.device_hbm_bytes = r.register(
            Gauge(
                "scheduler_tpu_device_hbm_bytes",
                "Live device memory from device.memory_stats() where the "
                "backend supports it (absent on CPU), sampled on scrape "
                "(kind: bytes_in_use / peak_bytes_in_use / bytes_limit).",
                ("device", "kind"),
            )
        )
        # --- device-fault tier (ISSUE 15): per-kernel circuit breakers +
        # epoch-guarded resident-state recovery ---
        self.kernel_breaker_state = r.register(
            Gauge(
                "scheduler_tpu_kernel_breaker_state",
                "Per-kernel circuit breaker state (0=closed, 1=open, "
                "2=half_open).  Open routes the dispatch family to its "
                "registered fallback engine — every trip is also visible "
                'in scheduler_tpu_wave_fallback_total{reason="breaker"}.',
                ("kernel",),
            )
        )
        self.kernel_breaker_trips = r.register(
            Counter(
                "scheduler_tpu_kernel_breaker_trips_total",
                "Breaker trips (closed/half_open → open) per kernel.",
                ("kernel",),
            )
        )
        self.kernel_breaker_failures = r.register(
            Counter(
                "scheduler_tpu_kernel_breaker_failures_total",
                "Failures booked against per-kernel breakers, by kind "
                "(dispatch_error / dispatch_hang / mesh_device_loss / "
                "poisoned_output / hbm_oom / sentinel).",
                ("kernel", "kind"),
            )
        )
        self.resident_resyncs = r.register(
            Counter(
                "scheduler_tpu_resident_resyncs_total",
                "Epoch-guarded resident-state resyncs: the device usage "
                "lineage was dropped and rebuilt from the host committer "
                "(reason: dispatch_failed / checksum_mismatch / "
                "epoch_stale / mesh_degraded / hbm_oom).",
                ("reason",),
            )
        )
        # --- control-plane pipeline tier (observability/controlplane.py):
        # the serving/watch path's accounting, synced on scrape ---
        self.apiserver_request_duration = r.register(
            Histogram(
                "scheduler_tpu_apiserver_request_duration_seconds",
                "API server request latency by verb/resource/status "
                "(apiserver_request_duration_seconds's shape), accumulated "
                "off-registry in the handler threads and merged on scrape.",
                ("verb", "resource", "status"),
                buckets=wide_duration_buckets(),
            )
        )
        self.watch_window_events = r.register(
            Gauge(
                "scheduler_tpu_watch_window_events",
                "Watch-cache sliding-window occupancy per resource "
                "(events retained; 410s start when watchers fall behind "
                "the window), sampled on scrape.",
                ("resource",),
            )
        )
        self.watch_fanout_lag = r.register(
            Gauge(
                "scheduler_tpu_watch_fanout_lag_events",
                "Max per-watcher fanout lag in events (cache head rv minus "
                "the slowest active watcher's delivered rv), sampled on "
                "scrape.",
                ("resource",),
            )
        )
        self.watch_compactions = r.register(
            Counter(
                "scheduler_tpu_watch_compactions_total",
                "Watch-cache compactions that dropped retained events "
                "(the etcd-compaction shape; the chaos runner's forced-410 "
                "lever), refreshed on scrape.",
                ("resource",),
            )
        )
        self.watch_relists = r.register(
            Counter(
                "scheduler_tpu_watch_relists_total",
                "410 Gone responses served by the watch cache (each one "
                "forces a client relist — reflector.go:340), refreshed on "
                "scrape.",
                ("resource",),
            )
        )
        self.wire_bytes_total = r.register(
            Counter(
                "scheduler_tpu_wire_bytes_total",
                "Bytes the API server moved over the list/watch/bind wire, "
                "split by codec (json vs the length-prefixed binary frames) "
                "and direction (tx/rx as the server sees them), refreshed "
                "on scrape.",
                ("codec", "direction"),
            )
        )
        self.informer_delivery_lag = r.register(
            Histogram(
                "scheduler_tpu_informer_delivery_lag_seconds",
                "API-write to reflector-delivery lag per resource (the "
                "watch cache's rv stamp joined against the client's decode "
                "time — in-process clocks).",
                ("resource",),
                buckets=wide_duration_buckets(),
            )
        )
        self.pipeline_hop_duration = r.register(
            Histogram(
                "scheduler_tpu_pipeline_hop_seconds",
                "Per-hop duration of the end-to-end pod pipeline "
                "(api_write → watch_delivery → informer_handler → enqueue "
                "→ pop → assumed → bind_start → bound), joined per pod "
                "from causal-chain breadcrumbs when the chain closes.",
                ("hop",),
                buckets=wide_duration_buckets(),
            )
        )
        self.snapshot_staleness = r.register(
            Gauge(
                "scheduler_tpu_snapshot_staleness_seconds",
                "Newest-delivered minus newest-applied informer event at "
                "the last batch dispatch — how stale the scheduling "
                "snapshot ran; sustained breaches file a "
                "snapshot_staleness black-box dump.",
            )
        )
        self.queue_depth = r.register(
            Gauge(
                "scheduler_tpu_queue_depth",
                "Scheduling-queue depth per sub-queue (active / backoff / "
                "unschedulable / gated), sampled on scrape under the "
                "scheduler lock.",
                ("queue",),
            )
        )
        self.queue_oldest_age = r.register(
            Gauge(
                "scheduler_tpu_queue_oldest_age_seconds",
                "Age of the oldest pod per sub-queue (monotonic clock "
                "since first enqueue), sampled on scrape under the "
                "scheduler lock.",
                ("queue",),
            )
        )
        self.recorder = MetricAsyncRecorder()

    def expose(self) -> str:
        self.recorder.flush()
        return self.registry.expose()


# ---------------------------------------------------------------------------
# slow-cycle tracing (utiltrace: schedule_one.go:409-449 — any scheduling
# cycle over 100ms dumps its per-step timings).  This is the LOG-side
# surface: one text dump per slow cycle.  The span-based tracer with
# Perfetto export, per-batch context, and HTTP control lives in
# kubernetes_tpu/observability/tracer.py — see OBSERVABILITY.md for how the
# two relate (Trace stays as the always-on cheap outlier dump; the span
# tracer is the on-demand full-timeline capture).
# ---------------------------------------------------------------------------

SLOW_CYCLE_THRESHOLD_S = 0.100


class Trace:
    """k8s.io/utils/trace analogue: named steps, dumped when the total
    exceeds a threshold."""

    def __init__(self, name: str, clock=time.monotonic, sink=None, **fields):
        self.name = name
        self.fields = fields
        self._clock = clock
        self._start = clock()
        self._steps: List[Tuple[float, str]] = []
        self._sink = sink  # callable(str); default logging

    def step(self, msg: str) -> None:
        self._steps.append((self._clock(), msg))

    def log_if_long(self, threshold_s: float = SLOW_CYCLE_THRESHOLD_S) -> Optional[str]:
        total = self._clock() - self._start
        if total < threshold_s:
            return None
        parts = [
            f'Trace "{self.name}" '
            + ",".join(f"{k}:{v}" for k, v in self.fields.items())
            + f" (total {total * 1000:.1f}ms):"
        ]
        prev = self._start
        for t, msg in self._steps:
            parts.append(f"  +{(t - prev) * 1000:.1f}ms {msg}")
            prev = t
        text = "\n".join(parts)
        if self._sink is not None:
            self._sink(text)
        else:
            import logging

            logging.getLogger("kubernetes_tpu.trace").info(text)
        return text
