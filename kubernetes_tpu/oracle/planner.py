"""Serial forked-snapshot oracle — the reference-shaped replay every
planner fork must match bit-for-bit (tools/paritycheck.py
``plan_vs_serial_oracle``; PLANNER.md).

For each fork the host snapshot is forked the way a real cluster mutation
would land: removed nodes (and their pods) vanish, cordons flip
``unschedulable``, capacities scale in LANE space (planner/forks.
``scale_node_lanes`` — the same integer arithmetic the kernel plane
applies), clones materialize via ``clone_node``, and evicted pods are
simply not placed.  The fork's live batch pods then replay through a
``WorkloadOracle`` in the shared canonical order (workloads/gang.
plan_batch) — gang undo logs included — which is exactly the engine the
workloads kernel is already proven against, so planner parity reduces to
fork-application parity.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.oracle.state import OracleState
from kubernetes_tpu.oracle.workloads import WorkloadOracle
from kubernetes_tpu.planner.forks import Fork, clone_node, scale_node_lanes
from kubernetes_tpu.snapshot.schema import MEM_UNIT

# density fixed-point scale — must match ops/counterfactual.DENSITY_SCALE
DENSITY_SCALE = 1_000_000


def fork_cluster_host(nodes, placed, fork: Fork):
    """Apply one fork to host objects: returns (nodes', placed') — new
    Node objects where mutated, original pods filtered (never mutated)."""
    by_name = {n.name: n for n in nodes}
    removed = set(fork.remove)
    cordoned = set(fork.cordon)
    scaled = {name: (num, den) for name, num, den in fork.scale}
    out_nodes = []
    for n in nodes:
        if n.name in removed:
            continue
        if n.name in scaled:
            num, den = scaled[n.name]
            n = scale_node_lanes(n, num, den)
        if n.name in cordoned:
            n = copy.copy(n)
            n.labels = dict(n.labels)
            n.unschedulable = True
        out_nodes.append(n)
    for template, clone_name in fork.add:
        tmpl = by_name.get(template)
        if tmpl is None:
            raise ValueError(f"fork {fork.label!r}: unknown template {template!r}")
        if not any(n.name == clone_name for n in out_nodes):
            out_nodes.append(clone_node(tmpl, clone_name))
    evicted = set(fork.evict)
    out_placed = [
        p
        for p in placed
        if p.uid not in evicted and p.node_name not in removed
    ]
    return out_nodes, out_placed


def host_density_ppm(state: OracleState) -> int:
    """The kernel's fork_density in host space: mean cpu+mem utilization
    over schedulable-capacity nodes, computed in the same pack-lane units
    (milli-cpu; ceil-MiB requested vs floor-MiB allocatable)."""
    total = 0
    n = 0
    for ns in state.nodes.values():
        a_cpu = ns.node.allocatable.milli_cpu
        a_mem = ns.node.allocatable.memory // MEM_UNIT
        if a_cpu <= 0 or a_mem <= 0:
            continue
        req = Resource()
        for p in ns.pods:
            req.add(p.compute_requests())
        u_cpu = req.milli_cpu
        u_mem = -(-req.memory // MEM_UNIT)
        total += (
            u_cpu * DENSITY_SCALE // max(a_cpu, 1)
            + u_mem * DENSITY_SCALE // max(a_mem, 1)
        ) // 2
        n += 1
    return total // max(n, 1)


def serial_plan(
    nodes,
    placed,
    pods: Sequence,
    forks: Sequence[Fork],
    groups: Optional[Dict] = None,
    needs: Optional[Dict[str, int]] = None,
    pvs=None,
    pvcs=None,
    namespace_labels=None,
    target_node: Optional[str] = None,
) -> List[dict]:
    """Replay every fork through a fresh WorkloadOracle.  Returns one dict
    per fork: placements (live pods only), admitted/unschedulable counts,
    density_ppm, gang_admitted, and (with ``target_node``) per-pod
    feasibility at the target."""
    groups = groups or {}
    out: List[dict] = []
    for fork in forks:
        f_nodes, f_placed = fork_cluster_host(nodes, placed, fork)
        state = OracleState.build(
            f_nodes, f_placed, namespace_labels=namespace_labels
        )
        # bound counts pre-credited: the kernel's gang_need arrays carry
        # the remaining need, so the oracle's window starts from the same
        # quorum arithmetic
        bound = {}
        for key, pg in groups.items():
            if needs is not None and pg is not None:
                bound[key] = max(0, pg.min_member - needs.get(key, pg.min_member))
        oracle = WorkloadOracle(
            state=state,
            pvs=dict(_items(pvs)) if pvs is not None else {},
            pvcs=dict(_items(pvcs)) if pvcs is not None else {},
            groups=dict(groups),
            bound=bound,
        )
        live = (
            {uid for uid in fork.live}
            if fork.live is not None
            else {p.uid for p in pods}
        )
        # Non-live pods are inert in the kernel scan (they commit nothing
        # and influence nothing), so replaying only the live subset in its
        # preserved relative order is exactly equivalent.
        batch = [copy.deepcopy(p) for p in pods if p.uid in live]
        live_names = {p.name for p in batch}
        res = oracle.schedule(batch)
        placements = {
            name: node
            for name, node in res.placements.items()
            if name in live_names
        }
        admitted = sum(1 for v in placements.values() if v)
        fork_out = {
            "label": fork.label,
            "placements": placements,
            "admitted": admitted,
            "unschedulable": len(placements) - admitted,
            "density_ppm": host_density_ppm(state),
            "gang_admitted": {
                k: (1 if v else 0) for k, v in res.gang_admitted.items()
            },
        }
        if target_node is not None:
            # feasibility-at-target is judged against the FORKED initial
            # state (the K=1 what-if contract: single-pod batches)
            t_ok = {}
            f2_nodes, f2_placed = fork_cluster_host(nodes, placed, fork)
            st2 = OracleState.build(
                f2_nodes, f2_placed, namespace_labels=namespace_labels
            )
            probe = WorkloadOracle(
                state=st2,
                pvs=dict(_items(pvs)) if pvs is not None else {},
                pvcs=dict(_items(pvcs)) if pvcs is not None else {},
                groups=dict(groups),
            )
            from kubernetes_tpu.oracle.pipeline import feasible_nodes

            for p in pods:
                if p.uid not in live:
                    continue
                fit = feasible_nodes(p, st2)
                ok = target_node in fit.feasible and probe._vol_ok(
                    p, target_node
                )
                t_ok[p.name] = bool(ok)
            fork_out["target_ok"] = t_ok
        out.append(fork_out)
    return out


def _items(cache):
    """dict(...) over either a mapping or an AssumeCache-style object."""
    if cache is None:
        return ()
    if hasattr(cache, "items"):
        return cache.items()
    if hasattr(cache, "list"):
        return ((getattr(o, "key", getattr(o, "name", None)), o) for o in cache.list())
    return ()
