"""Host-side cluster state the oracle evaluates against.

Equivalent in role to the reference's Snapshot (a consistent view of nodes +
placed pods, pkg/scheduler/backend/cache/snapshot.go) but kept as plain
Python objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import Node, Pod


_POD_SET_VERSION = [0]  # global NodeState mutation counter (cache key)


def bump_pod_set_version() -> None:
    """Invalidate pod-set-derived caches (anti_term_pods) after a
    mutation that bypasses the NodeState mutators — e.g. preemption's
    working-copy dict swap."""
    _POD_SET_VERSION[0] += 1


@dataclass
class NodeState:
    """Per-node accounting mirroring framework.NodeInfo (types.go:585)."""

    node: Node
    pods: List[Pod] = field(default_factory=list)
    requested: Resource = field(default_factory=Resource)
    non_zero_requested: Resource = field(default_factory=Resource)

    def add_pod(self, pod: Pod) -> None:
        req = pod.compute_requests()
        self.requested.add(req)
        self.non_zero_requested.add(req.non_zero_defaulted())
        self.pods.append(pod)
        _POD_SET_VERSION[0] += 1

    def remove_pod(self, pod: Pod) -> bool:
        _POD_SET_VERSION[0] += 1
        for i, p in enumerate(self.pods):
            if p.uid == pod.uid:
                req = p.compute_requests()
                self.requested.sub(req)
                self.non_zero_requested.sub(req.non_zero_defaulted())
                del self.pods[i]
                return True
        return False


@dataclass
class OracleState:
    nodes: Dict[str, NodeState] = field(default_factory=dict)
    namespace_labels: Dict[str, Dict[str, str]] = field(default_factory=dict)

    @classmethod
    def build(
        cls,
        nodes: Iterable[Node],
        placed_pods: Iterable[Pod] = (),
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
    ) -> "OracleState":
        st = cls(namespace_labels=dict(namespace_labels or {}))
        for n in nodes:
            st.nodes[n.name] = NodeState(node=n)
        for p in placed_pods:
            st.place(p)
        return st

    def place(self, pod: Pod) -> None:
        ns = self.nodes.get(pod.node_name)
        if ns is None:
            raise KeyError(f"pod {pod.key} placed on unknown node {pod.node_name!r}")
        ns.add_pod(pod)

    def unplace(self, pod: Pod) -> None:
        ns = self.nodes.get(pod.node_name)
        if ns is not None:
            ns.remove_pod(pod)

    def anti_term_pods(self):
        """[(node_state, pod, required-anti-terms)] for every PLACED pod
        that carries required anti-affinity — cached per pod-set version.
        satisfyExistingPodsAntiAffinity walks exactly these (the reference
        precomputes topologyToMatchedExistingAntiAffinityTerms the same
        way, filtering.go:141); without the cache the serial oracle costs
        O(nodes × placed) per (pod, node) check, which is unusable at
        parity-evidence scale."""
        from kubernetes_tpu.oracle.filters import _required_terms

        version = _POD_SET_VERSION[0]
        cached = getattr(self, "_anti_cache", None)
        if cached is not None and cached[0] == version:
            return cached[1]
        out = []
        for ns in self.nodes.values():
            for epod in ns.pods:
                terms = _required_terms(epod, anti=True)
                if terms:
                    out.append((ns, epod, terms))
        self._anti_cache = (version, out)
        return out

    def node_list(self) -> List[NodeState]:
        return list(self.nodes.values())

    def all_pods(self) -> List[Pod]:
        return [p for ns in self.nodes.values() for p in ns.pods]
