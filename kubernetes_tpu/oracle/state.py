"""Host-side cluster state the oracle evaluates against.

Equivalent in role to the reference's Snapshot (a consistent view of nodes +
placed pods, pkg/scheduler/backend/cache/snapshot.go) but kept as plain
Python objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import Node, Pod


@dataclass
class NodeState:
    """Per-node accounting mirroring framework.NodeInfo (types.go:585)."""

    node: Node
    pods: List[Pod] = field(default_factory=list)
    requested: Resource = field(default_factory=Resource)
    non_zero_requested: Resource = field(default_factory=Resource)

    def add_pod(self, pod: Pod) -> None:
        req = pod.compute_requests()
        self.requested.add(req)
        self.non_zero_requested.add(req.non_zero_defaulted())
        self.pods.append(pod)

    def remove_pod(self, pod: Pod) -> bool:
        for i, p in enumerate(self.pods):
            if p.uid == pod.uid:
                req = p.compute_requests()
                self.requested.sub(req)
                self.non_zero_requested.sub(req.non_zero_defaulted())
                del self.pods[i]
                return True
        return False


@dataclass
class OracleState:
    nodes: Dict[str, NodeState] = field(default_factory=dict)
    namespace_labels: Dict[str, Dict[str, str]] = field(default_factory=dict)

    @classmethod
    def build(
        cls,
        nodes: Iterable[Node],
        placed_pods: Iterable[Pod] = (),
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
    ) -> "OracleState":
        st = cls(namespace_labels=dict(namespace_labels or {}))
        for n in nodes:
            st.nodes[n.name] = NodeState(node=n)
        for p in placed_pods:
            st.place(p)
        return st

    def place(self, pod: Pod) -> None:
        ns = self.nodes.get(pod.node_name)
        if ns is None:
            raise KeyError(f"pod {pod.key} placed on unknown node {pod.node_name!r}")
        ns.add_pod(pod)

    def unplace(self, pod: Pod) -> None:
        ns = self.nodes.get(pod.node_name)
        if ns is not None:
            ns.remove_pod(pod)

    def node_list(self) -> List[NodeState]:
        return list(self.nodes.values())

    def all_pods(self) -> List[Pod]:
        return [p for ns in self.nodes.values() for p in ns.pods]
