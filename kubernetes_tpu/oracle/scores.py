"""Scalar Score semantics (golden model).

Every function returns raw per-node int64 scores plus (where the reference
has one) a normalize step, reproducing the exact integer/float arithmetic so
device kernels can be bit-checked against it.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api import labels as k8slabels
from kubernetes_tpu.api.types import (
    Pod,
    TAINT_PREFER_NO_SCHEDULE,
    node_selector_term_matches,
)
from kubernetes_tpu.oracle.filters import (
    _required_terms,
    _spread_selector_matches,
    _term_matches_pod,
    _node_eligible_for_constraint,
)
from kubernetes_tpu.oracle.state import NodeState, OracleState

MAX_NODE_SCORE = 100


def default_normalize(scores: List[int], reverse: bool = False) -> List[int]:
    """plugins/helper/normalize_score.go DefaultNormalizeScore."""
    max_count = max(scores) if scores else 0
    if max_count == 0:
        return [MAX_NODE_SCORE if reverse else s for s in scores]
    out = []
    for s in scores:
        v = MAX_NODE_SCORE * s // max_count
        if reverse:
            v = MAX_NODE_SCORE - v
        out.append(v)
    return out


# ---------------------------------------------------------------------------
# NodeResourcesFit — LeastAllocated (noderesources/least_allocated.go:29-60)
# ---------------------------------------------------------------------------


def _alloc_and_requested(
    pod: Pod, ns: NodeState, resource: str, use_requested: bool
) -> Tuple[int, int]:
    """resource_allocation.go:89 calculateResourceAllocatableRequest."""
    req = pod.compute_requests()
    pod_req = req.non_zero_defaulted() if not use_requested else req
    node_req = ns.requested if use_requested else ns.non_zero_requested
    if resource == "cpu":
        return ns.node.allocatable.milli_cpu, node_req.milli_cpu + pod_req.milli_cpu
    if resource == "memory":
        return ns.node.allocatable.memory, node_req.memory + pod_req.memory
    if resource == "ephemeral-storage":
        return (
            ns.node.allocatable.ephemeral_storage,
            ns.requested.ephemeral_storage + req.ephemeral_storage,
        )
    # extended: bypass when pod doesn't request it
    if req.scalars.get(resource, 0) == 0:
        return 0, 0
    if resource not in ns.node.allocatable.scalars:
        return 0, 0
    return (
        ns.node.allocatable.scalars[resource],
        ns.requested.scalars.get(resource, 0) + req.scalars[resource],
    )


def score_least_allocated(
    pod: Pod,
    ns: NodeState,
    resources: Sequence[Tuple[str, int]] = (("cpu", 1), ("memory", 1)),
) -> int:
    node_score = 0
    weight_sum = 0
    for name, weight in resources:
        alloc, requested = _alloc_and_requested(pod, ns, name, use_requested=False)
        if alloc == 0:
            continue
        if requested > alloc:
            r = 0
        else:
            r = (alloc - requested) * MAX_NODE_SCORE // alloc
        node_score += r * weight
        weight_sum += weight
    if weight_sum == 0:
        return 0
    return node_score // weight_sum


def score_most_allocated(
    pod: Pod,
    ns: NodeState,
    resources: Sequence[Tuple[str, int]] = (("cpu", 1), ("memory", 1)),
) -> int:
    """noderesources/most_allocated.go: requested*100/capacity, 0 if over."""
    node_score = 0
    weight_sum = 0
    for name, weight in resources:
        alloc, requested = _alloc_and_requested(pod, ns, name, use_requested=False)
        if alloc == 0:
            continue
        r = 0 if requested > alloc else requested * MAX_NODE_SCORE // alloc
        node_score += r * weight
        weight_sum += weight
    if weight_sum == 0:
        return 0
    return node_score // weight_sum


def broken_linear(points: Sequence[Tuple[int, int]], p: int) -> int:
    """helper.BuildBrokenLinearFunction (plugins/helper/shape_score.go:40)
    with Go's truncating integer division."""
    for i, (x1, y1) in enumerate(points):
        if p <= x1:
            if i == 0:
                return points[0][1]
            x0, y0 = points[i - 1]
            num = (y1 - y0) * (p - x0)
            den = x1 - x0
            q = num // den if num >= 0 else -((-num) // den)
            return y0 + q
    return points[-1][1]


def score_requested_to_capacity_ratio(
    pod: Pod,
    ns: NodeState,
    shape: Sequence[Tuple[int, int]],
    resources: Sequence[Tuple[str, int]] = (("cpu", 1), ("memory", 1)),
) -> int:
    """noderesources/requested_to_capacity_ratio.go:32-58: per-resource
    broken-linear score over utilization (shape scores pre-scaled to the
    0-100 range), weight-averaged over resources with a positive score;
    math.Round on the final mean."""
    node_score = 0
    weight_sum = 0
    for name, weight in resources:
        alloc, requested = _alloc_and_requested(pod, ns, name, use_requested=False)
        if alloc == 0:
            continue
        if requested > alloc:
            util = MAX_NODE_SCORE
        else:
            util = requested * MAX_NODE_SCORE // alloc
        r = broken_linear(shape, util)
        if r > 0:
            node_score += r * weight
            weight_sum += weight
    if weight_sum == 0:
        return 0
    return (2 * node_score + weight_sum) // (2 * weight_sum)


# ---------------------------------------------------------------------------
# NodeResourcesBalancedAllocation (balanced_allocation.go:138-160)
# ---------------------------------------------------------------------------


def score_balanced_allocation(
    pod: Pod,
    ns: NodeState,
    resources: Sequence[str] = ("cpu", "memory"),
) -> int:
    fractions: List[float] = []
    for name in resources:
        alloc, requested = _alloc_and_requested(pod, ns, name, use_requested=True)
        if alloc == 0:
            continue
        f = min(requested / alloc, 1.0)
        fractions.append(f)
    if len(fractions) == 2:
        std = abs(fractions[0] - fractions[1]) / 2
    elif len(fractions) > 2:
        mean = sum(fractions) / len(fractions)
        std = math.sqrt(sum((f - mean) ** 2 for f in fractions) / len(fractions))
    else:
        std = 0.0
    return int((1 - std) * MAX_NODE_SCORE)


# ---------------------------------------------------------------------------
# NodeAffinity preferred terms (nodeaffinity/node_affinity.go:239)
# ---------------------------------------------------------------------------


def score_node_affinity(pod: Pod, ns: NodeState) -> int:
    score = 0
    if pod.affinity and pod.affinity.node_affinity:
        for t in (
            pod.affinity.node_affinity.preferred_during_scheduling_ignored_during_execution
        ):
            if t.weight and node_selector_term_matches(t.preference, ns.node):
                score += t.weight
    return score


def normalize_node_affinity(scores: List[int]) -> List[int]:
    return default_normalize(scores, reverse=False)


# ---------------------------------------------------------------------------
# TaintToleration (tainttoleration/taint_toleration.go:164-196)
# ---------------------------------------------------------------------------


def score_taint_toleration(pod: Pod, ns: NodeState) -> int:
    """Count of intolerable PreferNoSchedule taints (lower is better)."""
    tolerations = [
        t
        for t in pod.tolerations
        if t.effect == "" or t.effect == TAINT_PREFER_NO_SCHEDULE
    ]
    count = 0
    for taint in ns.node.taints:
        if taint.effect != TAINT_PREFER_NO_SCHEDULE:
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            count += 1
    return count


def normalize_taint_toleration(scores: List[int]) -> List[int]:
    return default_normalize(scores, reverse=True)


# ---------------------------------------------------------------------------
# InterPodAffinity (interpodaffinity/scoring.go)
# ---------------------------------------------------------------------------


def _preferred_terms(pod: Pod, anti: bool):
    if not pod.affinity:
        return ()
    a = pod.affinity.pod_anti_affinity if anti else pod.affinity.pod_affinity
    if not a:
        return ()
    return a.preferred_during_scheduling_ignored_during_execution


def score_interpod_affinity_all(
    pod: Pod,
    state: OracleState,
    node_names: Sequence[str],
    hard_pod_affinity_weight: int = 1,
    ignore_preferred_terms_of_existing: bool = False,
) -> List[int]:
    """Raw scores for each node (scoring.go:50-224 processExistingPod +
    topology aggregation). Positive for affinity, negative for anti."""
    topo_score: Dict[Tuple[str, str], int] = {}

    def bump(topo_key: str, node, w: int):
        v = node.labels.get(topo_key)
        if v is not None and w != 0:
            topo_score[(topo_key, v)] = topo_score.get((topo_key, v), 0) + w

    has_constraints = bool(
        _preferred_terms(pod, False)
        or _preferred_terms(pod, True)
        or _required_terms(pod, False)
        or _required_terms(pod, True)
    )

    for ens in state.nodes.values():
        enode = ens.node
        for epod in ens.pods:
            e_has_required_aff = bool(_required_terms(epod, False))
            e_has_pref = bool(
                _preferred_terms(epod, False) or _preferred_terms(epod, True)
            )
            # The reference only processes existing pods that have affinity
            # constraints, or all pods when the incoming pod has constraints
            # (scoring.go PreScore: podsToProcess).
            if not (has_constraints or e_has_required_aff or e_has_pref):
                continue
            # incoming preferred terms vs existing pod
            for wt in _preferred_terms(pod, False):
                if _term_matches_pod(wt.pod_affinity_term, epod, pod, state):
                    bump(wt.pod_affinity_term.topology_key, enode, wt.weight)
            for wt in _preferred_terms(pod, True):
                if _term_matches_pod(wt.pod_affinity_term, epod, pod, state):
                    bump(wt.pod_affinity_term.topology_key, enode, -wt.weight)
            # symmetry: existing pod's required affinity terms matching pod
            if hard_pod_affinity_weight > 0:
                for term in _required_terms(epod, False):
                    if _term_matches_pod(term, pod, epod, state):
                        bump(term.topology_key, enode, hard_pod_affinity_weight)
            # symmetry: existing pod's preferred terms matching pod
            if not ignore_preferred_terms_of_existing:
                for wt in _preferred_terms(epod, False):
                    if _term_matches_pod(wt.pod_affinity_term, pod, epod, state):
                        bump(wt.pod_affinity_term.topology_key, enode, wt.weight)
                for wt in _preferred_terms(epod, True):
                    if _term_matches_pod(wt.pod_affinity_term, pod, epod, state):
                        bump(wt.pod_affinity_term.topology_key, enode, -wt.weight)

    out = []
    for name in node_names:
        node = state.nodes[name].node
        s = 0
        for (k, v), w in topo_score.items():
            if node.labels.get(k) == v:
                s += w
        out.append(s)
    return out


def normalize_interpod_affinity(scores: List[int]) -> List[int]:
    """scoring.go:265 NormalizeScore: map [min,max] → [0,100]."""
    if not scores:
        return scores
    mx, mn = max(scores), min(scores)
    diff = mx - mn
    out = []
    for s in scores:
        if diff == 0:
            out.append(0)
        else:
            out.append(int(MAX_NODE_SCORE * (s - mn) / diff))
    return out


# ---------------------------------------------------------------------------
# PodTopologySpread (podtopologyspread/scoring.go)
# ---------------------------------------------------------------------------

HOSTNAME_LABEL = "kubernetes.io/hostname"


def score_topology_spread_all(
    pod: Pod,
    state: OracleState,
    filtered_node_names: Sequence[str],
) -> List[int]:
    """Raw scores (matching-pod counts weighted by log-domain-size) for the
    filtered nodes; pair with normalize_topology_spread."""
    constraints = [
        c
        for c in pod.topology_spread_constraints
        if c.when_unsatisfiable == "ScheduleAnyway"
    ]
    if not constraints:
        return [0] * len(filtered_node_names)

    filtered = [state.nodes[n] for n in filtered_node_names]
    ignored = set()
    pair_counts: Dict[Tuple[str, str], int] = {}
    topo_size = [0] * len(constraints)
    for ns in filtered:
        labels = ns.node.labels
        if not all(c.topology_key in labels for c in constraints):
            ignored.add(ns.node.name)
            continue
        for i, c in enumerate(constraints):
            if c.topology_key == HOSTNAME_LABEL:
                continue
            pair = (c.topology_key, labels[c.topology_key])
            if pair not in pair_counts:
                pair_counts[pair] = 0
                topo_size[i] += 1

    weights = []
    for i, c in enumerate(constraints):
        sz = topo_size[i]
        if c.topology_key == HOSTNAME_LABEL:
            sz = len(filtered) - len(ignored)
        weights.append(math.log(sz + 2))

    # Count matching pods over ALL nodes (PreScore walks allNodes).
    for ens in state.nodes.values():
        labels = ens.node.labels
        if not all(c.topology_key in labels for c in constraints):
            continue
        for c in constraints:
            if not _node_eligible_for_constraint(c, pod, ens.node):
                continue
            pair = (c.topology_key, labels[c.topology_key])
            if pair not in pair_counts:
                continue
            pair_counts[pair] += sum(
                1
                for ep in ens.pods
                if ep.namespace == pod.namespace
                and ep.deletion_timestamp is None
                and _spread_selector_matches(c, ep, pod)
            )

    out = []
    for ns in filtered:
        if ns.node.name in ignored:
            out.append(None)  # invalidScore marker
            continue
        score = 0.0
        labels = ns.node.labels
        for i, c in enumerate(constraints):
            tp_val = labels.get(c.topology_key)
            if tp_val is None:
                continue
            if c.topology_key == HOSTNAME_LABEL:
                cnt = sum(
                    1
                    for ep in ns.pods
                    if ep.namespace == pod.namespace
                    and ep.deletion_timestamp is None
                    and _spread_selector_matches(c, ep, pod)
                )
            else:
                cnt = pair_counts.get((c.topology_key, tp_val), 0)
            score += cnt * weights[i] + (c.max_skew - 1)
        out.append(int(round(score)))
    return out


def normalize_topology_spread(scores: List[Optional[int]]) -> List[int]:
    """scoring.go:227 NormalizeScore (None = ignored node → 0)."""
    valid = [s for s in scores if s is not None]
    if not valid:
        return [0 for _ in scores]
    mn, mx = min(valid), max(valid)
    out = []
    for s in scores:
        if s is None:
            out.append(0)
        elif mx == 0:
            out.append(MAX_NODE_SCORE)
        else:
            out.append(MAX_NODE_SCORE * (mx + mn - s) // mx)
    return out


# ---------------------------------------------------------------------------
# ImageLocality (imagelocality/image_locality.go:54-96)
# ---------------------------------------------------------------------------

_MB = 1024 * 1024
_MIN_THRESHOLD = 23 * _MB
_MAX_CONTAINER_THRESHOLD = 1000 * _MB


def score_image_locality(pod: Pod, ns: NodeState, state: OracleState) -> int:
    total_nodes = len(state.nodes)
    if total_nodes == 0 or not pod.images:
        return 0
    sum_scores = 0
    for image in pod.images:
        if image in ns.node.images:
            spread = sum(
                1 for e in state.nodes.values() if image in e.node.images
            )
            sum_scores += int(ns.node.images[image] * spread / total_nodes)
    # image_locality.go: init containers count toward the thresholds too.
    num_containers = max(len(pod.containers) + len(pod.init_containers), 1)
    max_threshold = _MAX_CONTAINER_THRESHOLD * num_containers
    min_threshold = _MIN_THRESHOLD * num_containers
    if sum_scores < min_threshold:
        sum_scores = min_threshold
    elif sum_scores > max_threshold:
        sum_scores = max_threshold
    return int(
        MAX_NODE_SCORE * (sum_scores - min_threshold) / (max_threshold - min_threshold)
    )
