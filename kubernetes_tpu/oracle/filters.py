"""Scalar Filter semantics (golden model).

Each filter returns None (fits) or a reason string mirroring the reference's
Status messages.  File:line citations point at the reference implementation
whose behavior is reproduced.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api import labels as k8slabels
from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import (
    Node,
    Pod,
    PodAffinityTerm,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    Toleration,
    find_untolerated_taint,
    required_node_affinity_matches,
)
from kubernetes_tpu.oracle.state import NodeState, OracleState

REASON_NODE_NAME = "node(s) didn't match the requested node name"
REASON_UNSCHEDULABLE = "node(s) were unschedulable"
REASON_AFFINITY = "node(s) didn't match Pod's node affinity/selector"
REASON_TAINT = "node(s) had untolerated taint"
REASON_PODS_LIMIT = "Too many pods"
REASON_PORTS = "node(s) didn't have free ports for the requested pod ports"
REASON_EXISTING_ANTI = (
    "node(s) didn't satisfy existing pods anti-affinity rules"
)
REASON_POD_AFFINITY = "node(s) didn't match pod affinity rules"
REASON_POD_ANTI = "node(s) didn't match pod anti-affinity rules"
REASON_SPREAD = "node(s) didn't match pod topology spread constraints"
REASON_SPREAD_LABEL = (
    "node(s) didn't match pod topology spread constraints (missing required label)"
)


def insufficient(resource: str) -> str:
    return f"Insufficient {resource}"


# ---------------------------------------------------------------------------
# NodeName (plugins/nodename/node_name.go)
# ---------------------------------------------------------------------------


def filter_node_name(pod: Pod, ns: NodeState) -> Optional[str]:
    if pod.node_name and pod.node_name != ns.node.name:
        return REASON_NODE_NAME
    return None


# ---------------------------------------------------------------------------
# NodeUnschedulable (plugins/nodeunschedulable/node_unschedulable.go)
# ---------------------------------------------------------------------------

_UNSCHEDULABLE_TAINT_KEY = "node.kubernetes.io/unschedulable"


def filter_node_unschedulable(pod: Pod, ns: NodeState) -> Optional[str]:
    if not ns.node.unschedulable:
        return None
    # Tolerated iff pod tolerates the synthetic unschedulable:NoSchedule taint.
    from kubernetes_tpu.api.types import Taint

    t = Taint(key=_UNSCHEDULABLE_TAINT_KEY, effect=TAINT_NO_SCHEDULE)
    if any(tol.tolerates(t) for tol in pod.tolerations):
        return None
    return REASON_UNSCHEDULABLE


# ---------------------------------------------------------------------------
# NodeResourcesFit (plugins/noderesources/fit.go:423-503)
# ---------------------------------------------------------------------------


def filter_node_resources(
    pod: Pod,
    ns: NodeState,
    ignored_extended_prefixes: Tuple[str, ...] = (),
) -> List[str]:
    """Returns ALL insufficient-resource reasons (fitsRequest returns the
    full list, fit.go:460)."""
    reasons: List[str] = []
    alloc = ns.node.allocatable
    if len(ns.pods) + 1 > (alloc.allowed_pod_number or 110):
        reasons.append(REASON_PODS_LIMIT)
    req = pod.compute_requests()
    if (
        req.milli_cpu == 0
        and req.memory == 0
        and req.ephemeral_storage == 0
        and not req.scalars
    ):
        return reasons
    if req.milli_cpu > alloc.milli_cpu - ns.requested.milli_cpu:
        reasons.append(insufficient("cpu"))
    if req.memory > alloc.memory - ns.requested.memory:
        reasons.append(insufficient("memory"))
    if req.ephemeral_storage > alloc.ephemeral_storage - ns.requested.ephemeral_storage:
        reasons.append(insufficient("ephemeral-storage"))
    for name, v in req.scalars.items():
        if any(name.startswith(p) for p in ignored_extended_prefixes):
            continue
        if v > alloc.scalars.get(name, 0) - ns.requested.scalars.get(name, 0):
            reasons.append(insufficient(name))
    return reasons


# ---------------------------------------------------------------------------
# NodeAffinity (plugins/nodeaffinity/node_affinity.go:182-203)
# ---------------------------------------------------------------------------


def filter_node_affinity(pod: Pod, ns: NodeState) -> Optional[str]:
    if not required_node_affinity_matches(pod, ns.node):
        return REASON_AFFINITY
    return None


# ---------------------------------------------------------------------------
# TaintToleration (plugins/tainttoleration/taint_toleration.go:103-113)
# ---------------------------------------------------------------------------


def filter_taints(pod: Pod, ns: NodeState) -> Optional[str]:
    t = find_untolerated_taint(
        ns.node.taints, pod.tolerations, (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE)
    )
    if t is not None:
        return f"{REASON_TAINT} {{{t.key}: {t.value}}}"
    return None


# ---------------------------------------------------------------------------
# NodePorts (plugins/nodeports/node_ports.go)
# ---------------------------------------------------------------------------


def _ports_conflict(wanted, existing) -> bool:
    # Conflict when protocol+port equal and host IPs overlap (0.0.0.0 ⊇ all).
    if wanted.protocol != existing.protocol or wanted.host_port != existing.host_port:
        return False
    wip = wanted.host_ip or "0.0.0.0"
    eip = existing.host_ip or "0.0.0.0"
    return wip == eip or wip == "0.0.0.0" or eip == "0.0.0.0"


def filter_node_ports(pod: Pod, ns: NodeState) -> Optional[str]:
    wanted = pod.host_ports()
    if not wanted:
        return None
    existing = [p for ep in ns.pods for p in ep.host_ports()]
    for w in wanted:
        if any(_ports_conflict(w, e) for e in existing):
            return REASON_PORTS
    return None


# ---------------------------------------------------------------------------
# InterPodAffinity (plugins/interpodaffinity/filtering.go)
# ---------------------------------------------------------------------------


def _term_namespaces(term: PodAffinityTerm, pod: Pod, state: OracleState) -> Optional[set]:
    """Namespace set the term selects; None ⇒ all namespaces."""
    names = set(term.namespaces or ())
    if term.namespace_selector is not None:
        sel = k8slabels.selector_from_label_selector(term.namespace_selector)
        if sel.empty:
            return None  # empty selector ⇒ all namespaces
        for ns_name, lbls in state.namespace_labels.items():
            if sel.matches(lbls):
                names.add(ns_name)
    if not names and term.namespace_selector is None:
        names = {pod.namespace}
    return names


def _term_matches_pod(
    term: PodAffinityTerm, candidate: Pod, incoming: Pod, state: OracleState
) -> bool:
    nss = _term_namespaces(term, incoming, state)
    if nss is not None and candidate.namespace not in nss:
        return False
    sel = k8slabels.selector_from_label_selector(term.label_selector)
    return sel.matches(candidate.labels)


def _required_terms(pod: Pod, anti: bool) -> Tuple[PodAffinityTerm, ...]:
    if not pod.affinity:
        return ()
    a = pod.affinity.pod_anti_affinity if anti else pod.affinity.pod_affinity
    if not a:
        return ()
    return a.required_during_scheduling_ignored_during_execution


def filter_interpod_affinity(
    pod: Pod, ns: NodeState, state: OracleState
) -> Optional[str]:
    """satisfyExistingPodsAntiAffinity + satisfyPodAntiAffinity +
    satisfyPodAffinity (filtering.go:306-365)."""
    node = ns.node

    # 1. Existing pods' required anti-affinity terms matching the incoming pod
    #    forbid nodes in the same topology domain as the existing pod.
    #    Walk only the placed pods that HAVE such terms (state-level cache,
    #    the reference's precomputed existing-anti map, filtering.go:141).
    for ens, epod, terms in state.anti_term_pods():
        for term in terms:
            if not _term_matches_pod(term, pod, epod, state):
                continue
            ev = ens.node.labels.get(term.topology_key)
            nv = node.labels.get(term.topology_key)
            if ev is not None and nv is not None and ev == nv:
                return REASON_EXISTING_ANTI

    # 2. Incoming pod's required anti-affinity vs existing pods.
    for term in _required_terms(pod, anti=True):
        nv = node.labels.get(term.topology_key)
        if nv is None:
            continue
        for ens in state.nodes.values():
            ev = ens.node.labels.get(term.topology_key)
            if ev != nv:
                continue
            for epod in ens.pods:
                if _term_matches_pod(term, epod, pod, state):
                    return REASON_POD_ANTI

    # 3. Incoming pod's required affinity: every term needs a matching
    #    existing pod co-located in the term's topology (filtering.go:336).
    aff_terms = _required_terms(pod, anti=False)
    if aff_terms:
        any_match_anywhere = False
        all_satisfied = True
        for term in aff_terms:
            nv = node.labels.get(term.topology_key)
            if nv is None:
                return REASON_POD_AFFINITY  # all topology labels must exist
            satisfied = False
            for ens in state.nodes.values():
                ev = ens.node.labels.get(term.topology_key)
                for epod in ens.pods:
                    if _term_matches_pod(term, epod, pod, state):
                        any_match_anywhere = True
                        if ev is not None and ev == nv:
                            satisfied = True
            if not satisfied:
                all_satisfied = False
        if not all_satisfied:
            # First-pod-in-series escape hatch: no pod anywhere matches any
            # term AND the pod matches all its own terms.
            if not any_match_anywhere and all(
                _term_matches_pod(t, pod, pod, state) for t in aff_terms
            ):
                return None
            return REASON_POD_AFFINITY
    return None


# ---------------------------------------------------------------------------
# PodTopologySpread (plugins/podtopologyspread/filtering.go)
# ---------------------------------------------------------------------------


def _spread_selector_matches(tsc, target: Pod, incoming: Pod) -> bool:
    sel = k8slabels.selector_from_label_selector(tsc.label_selector)
    if not sel.matches(target.labels):
        return False
    for key in tsc.match_label_keys or ():
        if key in incoming.labels and target.labels.get(key) != incoming.labels[key]:
            return False
    return True


def _node_eligible_for_constraint(tsc, pod: Pod, node: Node) -> bool:
    """matchNodeInclusionPolicies (common.go)."""
    if tsc.node_affinity_policy == "Honor":
        if not required_node_affinity_matches(pod, node):
            return False
    if tsc.node_taints_policy == "Honor":
        if find_untolerated_taint(node.taints, pod.tolerations) is not None:
            return False
    return True


def spread_pair_counts(
    pod: Pod, state: OracleState
) -> Dict[Tuple[str, str], int]:
    """TpPairToMatchNum over eligible nodes (calcPreFilterState)."""
    constraints = [
        c
        for c in pod.topology_spread_constraints
        if c.when_unsatisfiable == "DoNotSchedule"
    ]
    counts: Dict[Tuple[str, str], int] = {}
    for ens in state.nodes.values():
        node = ens.node
        if not all(c.topology_key in node.labels for c in constraints):
            continue
        for c in constraints:
            if not _node_eligible_for_constraint(c, pod, node):
                continue
            pair = (c.topology_key, node.labels[c.topology_key])
            n = sum(
                1
                for ep in ens.pods
                if ep.namespace == pod.namespace
                and ep.deletion_timestamp is None
                and _spread_selector_matches(c, ep, pod)
            )
            counts[pair] = counts.get(pair, 0) + n
    return counts


def filter_topology_spread(
    pod: Pod,
    ns: NodeState,
    state: OracleState,
    pair_counts: Optional[Dict[Tuple[str, str], int]] = None,
) -> Optional[str]:
    constraints = [
        c
        for c in pod.topology_spread_constraints
        if c.when_unsatisfiable == "DoNotSchedule"
    ]
    if not constraints:
        return None
    counts = pair_counts if pair_counts is not None else spread_pair_counts(pod, state)
    node = ns.node
    for c in constraints:
        tp_val = node.labels.get(c.topology_key)
        if tp_val is None:
            return REASON_SPREAD_LABEL
        self_match = 1 if _spread_selector_matches(c, pod, pod) else 0
        pair = (c.topology_key, tp_val)
        if pair not in counts:
            # Node's domain wasn't tracked at PreFilter (node ineligible);
            # the reference skips the constraint then (filtering.go:340).
            continue
        match_num = counts[pair]
        domain_counts = [v for (k, _), v in counts.items() if k == c.topology_key]
        min_match = min(domain_counts) if domain_counts else 0
        if c.min_domains and len(domain_counts) < c.min_domains:
            min_match = 0
        skew = match_num + self_match - min_match
        if skew > c.max_skew:
            return REASON_SPREAD
    return None
