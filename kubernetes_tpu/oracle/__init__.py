"""Scalar golden model of the north-star plugins (SURVEY.md §7.2).

A direct, slow, obviously-correct Python implementation of the reference
plugin *semantics* — the per-plugin ground truth the batched device kernels
(kubernetes_tpu/ops) are property-tested against, and the host fallback for
plugins without kernels.
"""

from kubernetes_tpu.oracle.state import OracleState  # noqa: F401
from kubernetes_tpu.oracle import filters, scores  # noqa: F401
from kubernetes_tpu.oracle.pipeline import (  # noqa: F401
    DEFAULT_SCORE_WEIGHTS,
    feasible_nodes,
    prioritize,
    schedule_one,
)
