"""Oracle scheduling pipeline: filter → score → select.

Serial reimplementation of findNodesThatFitPod / prioritizeNodes /
selectHost (reference schedule_one.go:408-917) with the default plugin set
and weights (apis/config/v1/default_plugins.go:30-52):

    TaintToleration 3, NodeAffinity 2, PodTopologySpread 2,
    InterPodAffinity 2, NodeResourcesFit 1, BalancedAllocation 1,
    ImageLocality 1.

Tie-breaking: the reference reservoir-samples among max-score nodes
(schedule_one.go:870).  The oracle (and the device pipeline) default to the
deterministic "first max in node order" policy; an optional seeded RNG
reproduces reservoir sampling when bit-compat with a recorded run is needed.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.oracle import filters as F
from kubernetes_tpu.oracle import scores as S
from kubernetes_tpu.oracle.state import NodeState, OracleState

DEFAULT_SCORE_WEIGHTS = {
    "TaintToleration": 3,
    "NodeAffinity": 2,
    "PodTopologySpread": 2,
    "InterPodAffinity": 2,
    "NodeResourcesFit": 1,
    "NodeResourcesBalancedAllocation": 1,
    "ImageLocality": 1,
}


@dataclass
class FitResult:
    feasible: List[str]
    # node name → list of reasons (Diagnosis.NodeToStatusMap analogue)
    reasons: Dict[str, List[str]] = field(default_factory=dict)
    # nodes visited before the sampling cutoff (drives nextStartNodeIndex,
    # schedule_one.go:625)
    processed: int = 0
    # size of the node list actually walked (PreFilterResult-narrowed) —
    # the modulus for nextStartNodeIndex advancement
    n_considered: int = 0


MIN_FEASIBLE_NODES_TO_FIND = 100  # schedule_one.go minFeasibleNodesToFind


def num_feasible_nodes_to_find(percentage: int, num_all: int) -> int:
    """numFeasibleNodesToFind (schedule_one.go:673-699): adaptive percentage
    `50 - nodes/125` (floor 5%) when the configured percentage is 0."""
    if num_all < MIN_FEASIBLE_NODES_TO_FIND:
        return num_all
    if percentage == 0:
        percentage = 50 - num_all // 125
        if percentage < 5:
            percentage = 5
    if percentage >= 100:
        return num_all
    num = num_all * percentage // 100
    return max(num, MIN_FEASIBLE_NODES_TO_FIND)


ALL_FILTERS = frozenset(
    {
        "NodeName",
        "NodeUnschedulable",
        "TaintToleration",
        "NodeAffinity",
        "NodePorts",
        "NodeResourcesFit",
        "InterPodAffinity",
        "PodTopologySpread",
    }
)


def feasible_nodes(
    pod: Pod,
    state: OracleState,
    enabled: frozenset = ALL_FILTERS,
    allowed: Optional[frozenset] = None,
    sample_k: Optional[int] = None,
    start_index: int = 0,
    sample_pct: Optional[int] = None,
) -> FitResult:
    """Filter plugins in the reference's iteration shape (every node, all
    reasons collected).  ``enabled`` limits evaluation to a profile's
    enabled plugin set (kernel names); ``allowed`` is the PreFilterResult
    node-name narrowing — applied BEFORE sampling, like the reference
    (findNodesThatFitPod narrows the node list first, then
    findNodesThatPassFilters sizes numFeasibleNodesToFind and the
    nextStartNodeIndex rotation over the narrowed list,
    schedule_one.go:478-486,588-669).

    ``sample_k``/``start_index`` reproduce the adaptive sampling: nodes
    are visited in rotation order from start_index and the walk stops once
    sample_k feasible nodes are found; FitResult.processed reports how
    many nodes were visited.  ``sample_pct`` instead derives sample_k from
    the NARROWED list length (the correct sizing when combined with
    ``allowed``); it overrides sample_k."""
    spread_counts = (
        F.spread_pair_counts(pod, state) if "PodTopologySpread" in enabled else None
    )
    checks = [
        ("NodeName", lambda ns: F.filter_node_name(pod, ns)),
        ("NodeUnschedulable", lambda ns: F.filter_node_unschedulable(pod, ns)),
        ("TaintToleration", lambda ns: F.filter_taints(pod, ns)),
        ("NodeAffinity", lambda ns: F.filter_node_affinity(pod, ns)),
        ("NodePorts", lambda ns: F.filter_node_ports(pod, ns)),
        ("InterPodAffinity", lambda ns: F.filter_interpod_affinity(pod, ns, state)),
        (
            "PodTopologySpread",
            lambda ns: F.filter_topology_spread(pod, ns, state, spread_counts),
        ),
    ]
    checks = [c for c in checks if c[0] in enabled]
    check_resources = "NodeResourcesFit" in enabled
    feasible: List[str] = []
    reasons: Dict[str, List[str]] = {}
    names = list(state.nodes)
    if sample_k is not None or sample_pct is not None:
        # sampling-compat mode walks nodes in the reference's nodeTree
        # order — zone round-robin (node_tree.go:119-143); the rotation
        # below and first-max selection both ride this order
        from kubernetes_tpu.util.nodetree import ZONE_LABEL, node_tree_order

        order = node_tree_order(
            [state.nodes[n].node.labels.get(ZONE_LABEL) for n in names]
        )
        names = [names[i] for i in order]
    if allowed is not None:
        names = [n for n in names if n in allowed]
    n_considered = len(names)
    if sample_pct is not None:
        k = num_feasible_nodes_to_find(sample_pct, n_considered)
        sample_k = k if k < n_considered else None
    if sample_k is not None and names:
        start = start_index % len(names)
        names = names[start:] + names[:start]
    processed = 0
    for name in names:
        ns = state.nodes[name]
        processed += 1
        rs: List[str] = []
        for _, fn in checks:
            r = fn(ns)
            if r:
                rs.append(r)
        if check_resources:
            rs.extend(F.filter_node_resources(pod, ns))
        if rs:
            reasons[name] = rs
        else:
            feasible.append(name)
            if sample_k is not None and len(feasible) >= sample_k:
                break
    return FitResult(
        feasible=feasible,
        reasons=reasons,
        processed=processed,
        n_considered=n_considered,
    )


def prioritize(
    pod: Pod,
    state: OracleState,
    feasible: Sequence[str],
    weights: Optional[Dict[str, int]] = None,
    fit_scorer=None,
) -> Dict[str, int]:
    """Weighted sum of normalized plugin scores per feasible node
    (prioritizeNodes, schedule_one.go:752).  ``fit_scorer(pod, ns)``
    overrides the NodeResourcesFit strategy (default LeastAllocated)."""
    w = dict(DEFAULT_SCORE_WEIGHTS if weights is None else weights)
    nodes = [state.nodes[n] for n in feasible]
    totals = {n: 0 for n in feasible}

    def accumulate(name: str, scores: List[int]):
        weight = w.get(name, 0)
        for node_name, s in zip(feasible, scores):
            totals[node_name] += s * weight

    if w.get("TaintToleration"):
        raw = [S.score_taint_toleration(pod, ns) for ns in nodes]
        accumulate("TaintToleration", S.normalize_taint_toleration(raw))
    if w.get("NodeAffinity"):
        raw = [S.score_node_affinity(pod, ns) for ns in nodes]
        accumulate("NodeAffinity", S.normalize_node_affinity(raw))
    if w.get("PodTopologySpread"):
        raw = S.score_topology_spread_all(pod, state, list(feasible))
        accumulate("PodTopologySpread", S.normalize_topology_spread(raw))
    if w.get("InterPodAffinity"):
        raw = S.score_interpod_affinity_all(pod, state, list(feasible))
        accumulate("InterPodAffinity", S.normalize_interpod_affinity(raw))
    if w.get("NodeResourcesFit"):
        scorer = fit_scorer or S.score_least_allocated
        accumulate(
            "NodeResourcesFit",
            [scorer(pod, ns) for ns in nodes],
        )
    if w.get("NodeResourcesBalancedAllocation"):
        accumulate(
            "NodeResourcesBalancedAllocation",
            [S.score_balanced_allocation(pod, ns) for ns in nodes],
        )
    if w.get("ImageLocality"):
        accumulate(
            "ImageLocality",
            [S.score_image_locality(pod, ns, state) for ns in nodes],
        )
    return totals


def select_host(
    totals: Dict[str, int], rng: Optional[random.Random] = None
) -> Optional[str]:
    """Max score; ties broken deterministically by node order, or by
    reservoir sampling when an rng is supplied (schedule_one.go:870)."""
    if not totals:
        return None
    best = max(totals.values())
    tied = [n for n, s in totals.items() if s == best]
    if rng is None or len(tied) == 1:
        return tied[0]
    selected = tied[0]
    cnt = 1
    for cand in tied[1:]:
        cnt += 1
        if rng.randrange(cnt) == 0:
            selected = cand
    return selected


@dataclass
class ScheduleResult:
    node: Optional[str]
    feasible: List[str] = field(default_factory=list)
    reasons: Dict[str, List[str]] = field(default_factory=dict)
    scores: Dict[str, int] = field(default_factory=dict)


def schedule_one(
    pod: Pod,
    state: OracleState,
    weights: Optional[Dict[str, int]] = None,
    rng: Optional[random.Random] = None,
) -> ScheduleResult:
    fit = feasible_nodes(pod, state)
    if not fit.feasible:
        return ScheduleResult(node=None, feasible=[], reasons=fit.reasons)
    if len(fit.feasible) == 1:
        return ScheduleResult(
            node=fit.feasible[0], feasible=fit.feasible, reasons=fit.reasons
        )
    totals = prioritize(pod, state, fit.feasible, weights)
    return ScheduleResult(
        node=select_host(totals, rng),
        feasible=fit.feasible,
        reasons=fit.reasons,
        scores=totals,
    )
