"""Serial gang/DRA/volume oracle — the reference-shaped replay the
workloads kernel (ops/coscheduling.py) must match bit-for-bit.

One pod at a time in the canonical planner order (workloads/gang.py
plan_batch), each pod's feasible set is the oracle pipeline's verdict
(oracle/pipeline.py) narrowed by:

  * DRA claim allocation — the structured allocator's greedy walk in
    slice/device enumeration order (framework/dynamicresources.py
    _allocate_on_node semantics: DeviceClass + request selectors must all
    admit, ExactCount takes the first ``count`` free matches, All requires
    every match free, one pod's earlier requests shadow its later ones);
  * volume topology — every bound PVC's PV node-affinity must admit the
    node (the VolumeBinding bound-claims check, binder.go:868).

Placements commit into the oracle state AND the allocation ledger
(claims pin to their node, granted devices join the taken set) so
in-batch contention resolves in queue order, and each gang's member run
executes under an undo log: if the members placed cannot cover the
gang's remaining minMember need, every placement, claim grant, and taken
device of the gang is rolled back before the next pod runs — exactly the
kernel's checkpoint/restore.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from kubernetes_tpu.api import dra
from kubernetes_tpu.oracle.pipeline import prioritize, feasible_nodes, select_host
from kubernetes_tpu.oracle.state import OracleState
from kubernetes_tpu.workloads.gang import PodGroup, group_key_of, plan_batch


def allocate_on_node(
    claim: dra.ResourceClaim,
    node_name: str,
    node_slices: List[dra.ResourceSlice],
    device_classes: Dict[str, dra.DeviceClass],
    taken: Set[Tuple[str, str, str]],
) -> Optional[dra.AllocationResult]:
    """The structured allocator's per-(claim, node) walk — semantics
    identical to DynamicResources._allocate_on_node; ``taken`` accumulates
    grants (earlier claims/requests of the same pod shadow later ones) and
    is unwound on failure."""
    results: List[dra.DeviceRequestAllocationResult] = []
    granted: List[Tuple[str, str, str]] = []

    def fail() -> None:
        for key in granted:
            taken.discard(key)

    for req in claim.requests:
        device_class = device_classes.get(req.device_class_name)
        if device_class is None:
            fail()
            return None
        found: List[dra.DeviceRequestAllocationResult] = []
        want = (
            req.count if req.allocation_mode == dra.ALLOCATION_MODE_EXACT else None
        )
        ok = True
        for sl in node_slices:
            for dev in sl.devices:
                key = (sl.driver, sl.pool, dev.name)
                attrs = dev.attr_map()
                if not device_class.admits(attrs):
                    continue
                if not all(s.matches(attrs) for s in req.selectors):
                    continue
                if key in taken:
                    if want is None:
                        ok = False
                        break
                    continue
                found.append(
                    dra.DeviceRequestAllocationResult(
                        request=req.name,
                        driver=sl.driver,
                        pool=sl.pool,
                        device=dev.name,
                    )
                )
                taken.add(key)
                granted.append(key)
                if want is not None and len(found) >= want:
                    break
            if not ok or (want is not None and len(found) >= want):
                break
        if not ok or (want is not None and len(found) < want) or (
            want is None and not found
        ):
            fail()
            return None
        results.extend(found)
    return dra.AllocationResult(results=tuple(results), node_name=node_name)


@dataclass
class WorkloadResult:
    placements: Dict[str, Optional[str]] = field(default_factory=dict)
    rolled_back: Set[str] = field(default_factory=set)  # pod names
    gang_admitted: Dict[str, bool] = field(default_factory=dict)
    # claim key → node the oracle allocated it to
    claim_nodes: Dict[str, str] = field(default_factory=dict)


@dataclass
class WorkloadOracle:
    """Mutable serial replay state over an OracleState + allocation ledger."""

    state: OracleState
    slices: List[dra.ResourceSlice] = field(default_factory=list)
    device_classes: Dict[str, dra.DeviceClass] = field(default_factory=dict)
    claims: Dict[str, dra.ResourceClaim] = field(default_factory=dict)
    pvs: Dict[str, object] = field(default_factory=dict)
    pvcs: Dict[str, object] = field(default_factory=dict)
    groups: Dict[str, PodGroup] = field(default_factory=dict)
    bound: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        # working copies: allocation state mutates during the replay
        self.claims = {k: copy.deepcopy(c) for k, c in self.claims.items()}
        self.taken: Set[Tuple[str, str, str]] = set()
        for c in self.claims.values():
            if c.allocation is not None:
                for r in c.allocation.results:
                    self.taken.add((r.driver, r.pool, r.device))
        self._slices_by_node: Dict[str, List[dra.ResourceSlice]] = {}
        for sl in self.slices:
            self._slices_by_node.setdefault(sl.node_name, []).append(sl)

    # -- per-node workload narrowing ----------------------------------------

    def _dra_ok(self, pod, node_name: str) -> bool:
        """Feasibility probe against a throwaway taken-set copy — the
        probe's grants are discarded wholesale, no unwind needed."""
        sim_taken = set(self.taken)
        for name in pod.resource_claims:
            claim = self.claims.get(f"{pod.namespace}/{name}")
            if claim is None:
                return False
            if claim.allocation is not None:
                if (
                    claim.allocation.node_name
                    and claim.allocation.node_name != node_name
                ):
                    return False
                continue
            alloc = allocate_on_node(
                claim,
                node_name,
                self._slices_by_node.get(node_name, []),
                self.device_classes,
                sim_taken,
            )
            if alloc is None:
                return False
        return True

    def _dra_commit(self, pod, node_name: str, undo: List) -> None:
        for name in pod.resource_claims:
            claim = self.claims.get(f"{pod.namespace}/{name}")
            if claim is None or claim.allocation is not None:
                continue
            alloc = allocate_on_node(
                claim,
                node_name,
                self._slices_by_node.get(node_name, []),
                self.device_classes,
                self.taken,
            )
            # feasibility was proven before commit
            assert alloc is not None, f"oracle DRA commit lost {claim.key}"
            claim.allocation = alloc
            keys = [(r.driver, r.pool, r.device) for r in alloc.results]
            undo.append(("claim", claim, keys))

    def _vol_ok(self, pod, node_name: str) -> bool:
        from kubernetes_tpu.api import storage as st
        from kubernetes_tpu.framework.volume_plugins import _zone_value_set
        from kubernetes_tpu.framework.volumebinding import (
            pv_node_affinity_matches,
        )

        names = pod.pvc_names() if hasattr(pod, "pvc_names") else []
        for name in names:
            pvc = self.pvcs.get(f"{pod.namespace}/{name}")
            if pvc is None:
                return False
            if pvc.is_fully_bound():
                pv = self.pvs.get(pvc.volume_name)
                if pv is None:
                    return False
                ns = self.state.nodes.get(node_name)
                if ns is None or not pv_node_affinity_matches(pv, ns.node):
                    return False
                # zone/region-LABELED PVs (volume_zone.go:109): every
                # topology label must match the node's — the kernel packs
                # these as per-label In-conjunctions in _vol_tables
                for key in st.VOLUME_TOPOLOGY_LABELS:
                    if key in pv.labels:
                        node_val = ns.node.labels.get(key)
                        if node_val is None or node_val not in _zone_value_set(
                            pv.labels[key]
                        ):
                            return False
            else:
                return False  # unbound claims never reach the kernel path
        return True

    # -- the serial replay ---------------------------------------------------

    def _schedule_pod(self, pod) -> Optional[str]:
        fit = feasible_nodes(pod, self.state)
        narrowed = [
            n
            for n in fit.feasible
            if (not pod.resource_claims or self._dra_ok(pod, n))
            and self._vol_ok(pod, n)
        ]
        if not narrowed:
            return None
        totals = prioritize(pod, self.state, narrowed)
        return select_host(totals)

    def schedule(self, pods) -> WorkloadResult:
        """Replay the batch in canonical planner order with gang undo."""
        out = WorkloadResult()

        def group_of(pod):
            # pods referencing an UNREGISTERED group schedule as ordinary
            # pods — same contract as the scheduler's _workloads_group_of
            key = group_key_of(pod)
            return key if key is not None and key in self.groups else None

        order, gang_positions = plan_batch(pods, group_of=group_of)
        gang_at: Dict[int, str] = {}
        for key, positions in gang_positions.items():
            gang_at[positions[0]] = key
        pos_to_key: Dict[int, str] = {}
        for key, positions in gang_positions.items():
            for pos in positions:
                pos_to_key[pos] = key

        undo: List = []
        landed = 0

        def rollback() -> None:
            for kind, obj, extra in reversed(undo):
                if kind == "place":
                    self.state.unplace(obj)
                    obj.node_name = ""
                    out.placements[obj.name] = None
                    out.rolled_back.add(obj.name)
                else:  # claim
                    obj.allocation = None
                    for k in extra:
                        self.taken.discard(k)

        for pos, idx in enumerate(order):
            pod = pods[idx]
            key = pos_to_key.get(pos)
            if key is not None and gang_at.get(pos) == key:
                undo = []
                landed = 0
            node = self._schedule_pod(pod)
            out.placements[pod.name] = node
            if node is not None:
                self._dra_commit(pod, node, undo)
                pod.node_name = node
                self.state.place(pod)
                undo.append(("place", pod, None))
                landed += 1 if key is not None else 0
            if key is not None and pos == gang_positions[key][-1]:
                pg = self.groups.get(key)
                need = max(
                    0,
                    (pg.min_member if pg else 0) - self.bound.get(key, 0),
                )
                if landed < need:
                    rollback()
                    out.gang_admitted[key] = False
                else:
                    out.gang_admitted[key] = True
                    self.bound[key] = self.bound.get(key, 0) + landed
                undo = []
        for k, c in self.claims.items():
            if c.allocation is not None and c.allocation.node_name:
                out.claim_nodes[k] = c.allocation.node_name
        return out
