"""Scheduler extenders — the legacy HTTP webhook protocol.

Mirrors pkg/scheduler/extender.go (HTTPExtender :78-140, Filter :455,
Prioritize, Bind, ProcessPreemption) and the staging kube-scheduler
extender/v1 wire types: Filter/Prioritize POST ``ExtenderArgs`` JSON and
read ``ExtenderFilterResult`` / ``HostPriorityList``; Bind POSTs
``ExtenderBindingArgs``.

Extender-interested pods leave the batched device path and run one-pod
cycles over the host oracle (kubernetes_tpu/oracle/pipeline.py) — webhooks
are serial per-pod HTTP round-trips in the reference too
(schedule_one.go:701-745), so nothing is lost by not batching them.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.framework import config as cfg


class ExtenderError(Exception):
    pass


class Extender:
    """framework.Extender interface (extender.go / interface.go)."""

    name: str = ""
    weight: int = 1
    ignorable: bool = False

    def is_interested(self, pod: Pod) -> bool:
        """IsInterested: true when the extender manages no specific
        resources, or the pod requests one of its managed resources."""
        raise NotImplementedError

    def is_filter(self) -> bool:
        return False

    def is_prioritizer(self) -> bool:
        return False

    def is_binder(self) -> bool:
        return False

    def supports_preemption(self) -> bool:
        return False

    def filter(
        self, pod: Pod, node_names: Sequence[str]
    ) -> Tuple[List[str], Dict[str, str], Dict[str, str]]:
        """Returns (feasible, failed{node: reason},
        failed_and_unresolvable{node: reason}); raises ExtenderError on
        transport/protocol errors."""
        raise NotImplementedError

    def prioritize(
        self, pod: Pod, node_names: Sequence[str]
    ) -> Dict[str, int]:
        """Node → score on the extender's own 0-10 scale (the caller
        multiplies by self.weight)."""
        raise NotImplementedError

    def bind(self, pod: Pod, node_name: str) -> None:
        raise NotImplementedError

    def process_preemption(
        self, pod: Pod, victims_by_node: Dict[str, list]
    ) -> Dict[str, list]:
        """ProcessPreemption: may shrink the candidate map (extender.go).
        Default passthrough."""
        return victims_by_node


def _managed_resource_interest(managed: Sequence[str], pod: Pod) -> bool:
    if not managed:
        return True
    wanted = set(managed)
    for c in list(pod.containers) + list(pod.init_containers):
        for m in (c.requests, c.limits):
            if m and any(name in wanted for name in m):
                return True
    return False


class HTTPExtender(Extender):
    """extender.go HTTPExtender: JSON POST per verb."""

    def __init__(self, spec: cfg.Extender):
        self.spec = spec
        self.name = spec.url_prefix
        self.weight = spec.weight or 1
        self.ignorable = spec.ignorable

    def is_interested(self, pod: Pod) -> bool:
        return _managed_resource_interest(self.spec.managed_resources, pod)

    def is_filter(self) -> bool:
        return bool(self.spec.filter_verb)

    def is_prioritizer(self) -> bool:
        return bool(self.spec.prioritize_verb)

    def is_binder(self) -> bool:
        return bool(self.spec.bind_verb)

    def supports_preemption(self) -> bool:
        return bool(self.spec.preempt_verb)

    # -- wire ------------------------------------------------------------------

    def _post(self, verb: str, payload: dict) -> dict:
        url = self.spec.url_prefix.rstrip("/") + "/" + verb
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.spec.http_timeout_s
            ) as resp:
                return json.loads(resp.read().decode())
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ExtenderError(f"extender {self.name} {verb}: {e}") from e

    @staticmethod
    def _pod_payload(pod: Pod) -> dict:
        return {
            "metadata": {
                "name": pod.name,
                "namespace": pod.namespace,
                "uid": pod.uid,
            }
        }

    def filter(self, pod, node_names):
        """extender.go:149-293: a nodeCacheCapable extender exchanges bare
        node NAMES; a non-capable one exchanges full NodeList payloads and
        answers with a NodeList."""
        if self.spec.node_cache_capable:
            args = {"pod": self._pod_payload(pod), "nodenames": list(node_names)}
        else:
            args = {
                "pod": self._pod_payload(pod),
                "nodes": {
                    "items": [{"metadata": {"name": n}} for n in node_names]
                },
            }
        result = self._post(self.spec.filter_verb, args)
        if result.get("error"):
            raise ExtenderError(f"extender {self.name}: {result['error']}")
        if self.spec.node_cache_capable:
            feasible = list(result.get("nodenames") or [])
        else:
            feasible = [
                name
                for item in (result.get("nodes") or {}).get("items", [])
                if (name := item.get("metadata", {}).get("name"))
            ]
        failed = dict(result.get("failedNodes") or {})
        unresolvable = dict(result.get("failedAndUnresolvableNodes") or {})
        return feasible, failed, unresolvable

    def prioritize(self, pod, node_names):
        if self.spec.node_cache_capable:
            args = {"pod": self._pod_payload(pod), "nodenames": list(node_names)}
        else:  # same NodeList split as Filter (extender.go Prioritize)
            args = {
                "pod": self._pod_payload(pod),
                "nodes": {
                    "items": [{"metadata": {"name": n}} for n in node_names]
                },
            }
        result = self._post(self.spec.prioritize_verb, args)
        out: Dict[str, int] = {}
        for entry in result or []:
            out[entry.get("host", "")] = int(entry.get("score", 0))
        return out

    def bind(self, pod, node_name):
        result = self._post(
            self.spec.bind_verb,
            {
                "podName": pod.name,
                "podNamespace": pod.namespace,
                "podUID": pod.uid,
                "node": node_name,
            },
        )
        err = (result or {}).get("error")
        if err:
            raise ExtenderError(f"extender {self.name} bind: {err}")

    def process_preemption(self, pod, victims_by_node):
        result = self._post(
            self.spec.preempt_verb,
            {
                "pod": self._pod_payload(pod),
                "nodeNameToVictims": {
                    node: {
                        "pods": [self._pod_payload(v) for v in victims.pods],
                        "numPDBViolations": victims.num_pdb_violations,
                    }
                    for node, victims in victims_by_node.items()
                },
            },
        )
        kept = set((result or {}).get("nodeNameToMetaVictims") or {})
        return {n: v for n, v in victims_by_node.items() if n in kept}


def build_extenders(specs: Sequence[cfg.Extender]) -> List[Extender]:
    """buildExtenders (scheduler.go:285)."""
    return [HTTPExtender(s) for s in specs if s.url_prefix]
