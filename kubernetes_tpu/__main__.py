"""``python -m kubernetes_tpu`` — the kube-scheduler binary analogue."""

from kubernetes_tpu.server import main

raise SystemExit(main())
