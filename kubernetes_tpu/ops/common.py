"""Shared kernel machinery: device containers and primitive evaluators.

The conjunction-table evaluator here is the device analogue of
labels.Selector.Matches / nodeaffinity.RequiredNodeAffinity.Match in the
reference (staging/src/k8s.io/apimachinery/pkg/labels/selector.go,
component-helpers/scheduling/corev1/nodeaffinity) — one vectorized pass
instead of per-object interpreter loops.
"""

from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.snapshot.interner import ABSENT, INT_INVALID, PAD
from kubernetes_tpu.snapshot.schema import (
    ConjunctionTable,
    ExistingPodTensors,
    NodeTensors,
    PodBatch,
)
from kubernetes_tpu.snapshot.selectors import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
)

I32 = jnp.int32
I64 = jnp.int64


def _register_pytree(cls):
    """Register a plain dataclass of arrays as a JAX pytree."""
    names = [f.name for f in fields(cls)]

    def flatten(x):
        return tuple(getattr(x, n) for n in names), None

    def unflatten(_, children):
        return cls(*children)

    jax.tree_util.register_pytree_node(cls, flatten, unflatten)
    return cls


@_register_pytree
@dataclass
class DTable:
    """Device copy of a ConjunctionTable."""

    req_key: Any  # i32 [..., R]
    req_op: Any  # i32 [..., R]
    req_vals: Any  # i32 [..., R, V]
    req_rhs: Any  # i32 [..., R]
    term_valid: Any  # bool [...]

    @classmethod
    def from_host(cls, t: ConjunctionTable) -> "DTable":
        return cls(
            req_key=jnp.asarray(t.req_key, I32),
            req_op=jnp.asarray(t.req_op, I32),
            req_vals=jnp.asarray(t.req_vals, I32),
            req_rhs=jnp.asarray(t.req_rhs, I32),
            term_valid=jnp.asarray(t.term_valid, bool),
        )


@_register_pytree
@dataclass
class DeviceCluster:
    """HBM-resident cluster snapshot (nodes + placed pods + their terms)."""

    # nodes
    allocatable: Any  # i32 [N, R]
    requested: Any  # i32 [N, R]
    nonzero_req: Any  # i32 [N, 2]
    num_pods: Any  # i32 [N]
    allowed_pods: Any  # i32 [N]
    node_labels: Any  # i32 [N, K]
    val_ints: Any  # i32 [V]
    taint_key: Any  # i32 [N, T]
    taint_val: Any  # i32 [N, T]
    taint_effect: Any  # i32 [N, T]
    unschedulable: Any  # bool [N]
    node_valid: Any  # bool [N]
    used_ppk: Any  # i32 [N, U]
    used_ip: Any  # i32 [N, U]
    used_wild: Any  # bool [N, U]
    img_sizes: Any  # i64 [N, IMG]
    # placed pods
    epod_node: Any  # i32 [E]
    epod_ns: Any  # i32 [E]
    epod_labels: Any  # i32 [E, K]
    epod_valid: Any  # bool [E]
    epod_deleting: Any  # bool [E]
    # flattened (anti-)affinity terms of placed pods
    term_pod: Any  # i32 [M]
    term_kind: Any  # i32 [M]
    term_topo: Any  # i32 [M]
    term_weight: Any  # i32 [M]
    term_table: DTable  # [M, 1, ...]
    term_ns_all: Any  # bool [M]
    term_ns_ids: Any  # i32 [M, NS]
    # scalar ids resolved from the vocab (traced so vocab growth ≠ recompile)
    name_key: Any  # i32  label-key id of metadata.name
    unsched_key: Any  # i32  label-key id of node.kubernetes.io/unschedulable
    empty_val: Any  # i32  label-val id of ""
    n_valid_nodes: Any  # i32  number of real nodes
    log_tab: Any  # i64 [N+2]  fixed-point round(log(i+2)·2^32) table

    @classmethod
    def from_host(cls, nt: NodeTensors, ep: ExistingPodTensors, vocab) -> "DeviceCluster":
        from kubernetes_tpu.snapshot.selectors import METADATA_NAME_KEY

        n = int(nt.valid.sum())
        log_tab = np.round(
            np.log(np.arange(nt.n_cap + 2, dtype=np.float64) + 2.0) * (1 << 32)
        ).astype(np.int64)
        return cls(
            allocatable=jnp.asarray(nt.allocatable, I32),
            requested=jnp.asarray(nt.requested, I32),
            nonzero_req=jnp.asarray(nt.nonzero_req, I32),
            num_pods=jnp.asarray(nt.num_pods, I32),
            allowed_pods=jnp.asarray(nt.allowed_pods, I32),
            node_labels=jnp.asarray(nt.label_vals, I32),
            val_ints=jnp.asarray(nt.val_ints, I32),
            taint_key=jnp.asarray(nt.taint_key, I32),
            taint_val=jnp.asarray(nt.taint_val, I32),
            taint_effect=jnp.asarray(nt.taint_effect, I32),
            unschedulable=jnp.asarray(nt.unschedulable, bool),
            node_valid=jnp.asarray(nt.valid, bool),
            used_ppk=jnp.asarray(nt.used_ppk, I32),
            used_ip=jnp.asarray(nt.used_ip, I32),
            used_wild=jnp.asarray(nt.used_wild, bool),
            img_sizes=jnp.asarray(nt.img_sizes, I64),
            epod_node=jnp.asarray(ep.node_idx, I32),
            epod_ns=jnp.asarray(ep.ns_id, I32),
            epod_labels=jnp.asarray(ep.label_vals, I32),
            epod_valid=jnp.asarray(ep.valid, bool),
            epod_deleting=jnp.asarray(ep.deleting, bool),
            term_pod=jnp.asarray(ep.term_pod, I32),
            term_kind=jnp.asarray(ep.term_kind, I32),
            term_topo=jnp.asarray(ep.term_topo_key, I32),
            term_weight=jnp.asarray(ep.term_weight, I32),
            term_table=DTable.from_host(ep.term_table),
            term_ns_all=jnp.asarray(ep.term_ns_all, bool),
            term_ns_ids=jnp.asarray(ep.term_ns_ids, I32),
            name_key=jnp.asarray(vocab.label_keys.lookup(METADATA_NAME_KEY), I32),
            unsched_key=jnp.asarray(
                vocab.label_keys.lookup("node.kubernetes.io/unschedulable"), I32
            ),
            empty_val=jnp.asarray(vocab.label_vals.lookup(""), I32),
            n_valid_nodes=jnp.asarray(n, I32),
            log_tab=jnp.asarray(log_tab),
        )


@_register_pytree
@dataclass
class DeviceBatch:
    """Pending-pod batch on device."""

    requests: Any  # i32 [P, R]
    nonzero_req: Any  # i32 [P, 2]
    ns_id: Any  # i32 [P]
    priority: Any  # i32 [P]
    labels: Any  # i32 [P, K]
    valid: Any  # bool [P]
    node_sel: DTable  # [P, T, ...]
    pref_node: DTable  # [P, PT, ...]
    pref_weight: Any  # i32 [P, PT]
    tol_key: Any  # i32 [P, TL]
    tol_op: Any  # i32 [P, TL]
    tol_val: Any  # i32 [P, TL]
    tol_effect: Any  # i32 [P, TL]
    tsc_table: DTable  # [P, C, ...]
    tsc_topo: Any  # i32 [P, C]
    tsc_max_skew: Any  # i32 [P, C]
    tsc_hard: Any  # bool [P, C]
    tsc_min_domains: Any  # i32 [P, C]
    tsc_honor_affinity: Any  # bool [P, C]
    tsc_honor_taints: Any  # bool [P, C]
    aff_table: DTable  # [P, AT, ...]
    aff_kind: Any  # i32 [P, AT]
    aff_topo: Any  # i32 [P, AT]
    aff_weight: Any  # i32 [P, AT]
    aff_ns_all: Any  # bool [P, AT]
    aff_ns_ids: Any  # i32 [P, AT, NS]
    target_name_val: Any  # i32 [P]
    want_ppk: Any  # i32 [P, W]
    want_ip: Any  # i32 [P, W]
    want_wild: Any  # bool [P, W]
    img_ids: Any  # i32 [P, I]
    n_containers: Any  # i32 [P]

    @classmethod
    def from_host(cls, pb: PodBatch) -> "DeviceBatch":
        return cls(
            requests=jnp.asarray(pb.requests, I32),
            nonzero_req=jnp.asarray(pb.nonzero_req, I32),
            ns_id=jnp.asarray(pb.ns_id, I32),
            priority=jnp.asarray(pb.priority, I32),
            labels=jnp.asarray(pb.label_vals, I32),
            valid=jnp.asarray(pb.valid, bool),
            node_sel=DTable.from_host(pb.node_sel),
            pref_node=DTable.from_host(pb.pref_node),
            pref_weight=jnp.asarray(pb.pref_weight, I32),
            tol_key=jnp.asarray(pb.tol_key, I32),
            tol_op=jnp.asarray(pb.tol_op, I32),
            tol_val=jnp.asarray(pb.tol_val, I32),
            tol_effect=jnp.asarray(pb.tol_effect, I32),
            tsc_table=DTable.from_host(pb.tsc_table),
            tsc_topo=jnp.asarray(pb.tsc_topo_key, I32),
            tsc_max_skew=jnp.asarray(pb.tsc_max_skew, I32),
            tsc_hard=jnp.asarray(pb.tsc_hard, bool),
            tsc_min_domains=jnp.asarray(pb.tsc_min_domains, I32),
            tsc_honor_affinity=jnp.asarray(pb.tsc_honor_affinity, bool),
            tsc_honor_taints=jnp.asarray(pb.tsc_honor_taints, bool),
            aff_table=DTable.from_host(pb.aff_table),
            aff_kind=jnp.asarray(pb.aff_kind, I32),
            aff_topo=jnp.asarray(pb.aff_topo_key, I32),
            aff_weight=jnp.asarray(pb.aff_weight, I32),
            aff_ns_all=jnp.asarray(pb.aff_ns_all, bool),
            aff_ns_ids=jnp.asarray(pb.aff_ns_ids, I32),
            target_name_val=jnp.asarray(pb.target_name_val, I32),
            want_ppk=jnp.asarray(pb.want_ppk, I32),
            want_ip=jnp.asarray(pb.want_ip, I32),
            want_wild=jnp.asarray(pb.want_wild, bool),
            img_ids=jnp.asarray(pb.img_ids, I32),
            n_containers=jnp.asarray(pb.n_containers, I32),
        )


# ---------------------------------------------------------------------------
# Conjunction evaluation
# ---------------------------------------------------------------------------


def eval_table(table: DTable, label_vals, val_ints):
    """Evaluate every conjunction against every label row.

    table arrays have shape ``lead + (R,)`` / ``lead + (R, V)``; ``label_vals``
    is ``[N, K]``.  Returns matches ``lead + (N,)`` — term_valid is already
    folded in (invalid/padding terms match nothing).

    Requirement semantics mirror labels.Requirement.Matches (selector.go):
    NotIn also matches absent keys; Gt/Lt need integer-parsing both sides.
    The static R/V loops keep peak memory at one ``lead+(N,)`` buffer per op.
    """
    R = table.req_key.shape[-1]
    V = table.req_vals.shape[-1]
    N, K = label_vals.shape
    cols = label_vals.T  # [K, N]

    ok = None
    for r in range(R):
        key = table.req_key[..., r]  # lead
        op = table.req_op[..., r]
        rhs = table.req_rhs[..., r]
        key_known = (key >= 0) & (key < K)
        safe_key = jnp.clip(key, 0, K - 1)
        val = jnp.where(key_known[..., None], cols[safe_key], ABSENT)  # lead+(N,)
        present = val >= 0

        in_any = jnp.zeros_like(present)
        for v in range(V):
            rv = table.req_vals[..., r, v]
            in_any = in_any | (present & (val == rv[..., None]) & (rv >= 0)[..., None])

        iv = jnp.where(
            present,
            val_ints[jnp.clip(val, 0, val_ints.shape[0] - 1)],
            INT_INVALID,
        )
        int_ok = (iv != INT_INVALID) & (rhs != INT_INVALID)[..., None]

        opb = op[..., None]
        res = jnp.where(
            opb == OP_IN,
            in_any,
            jnp.where(
                opb == OP_NOT_IN,
                ~in_any,
                jnp.where(
                    opb == OP_EXISTS,
                    present,
                    jnp.where(
                        opb == OP_DOES_NOT_EXIST,
                        ~present,
                        jnp.where(
                            opb == OP_GT,
                            int_ok & (iv > rhs[..., None]),
                            int_ok & (iv < rhs[..., None]),  # OP_LT
                        ),
                    ),
                ),
            ),
        )
        res = jnp.where(opb == PAD, True, res)  # padded requirement slot
        ok = res if ok is None else (ok & res)
    if ok is None:
        ok = jnp.ones(table.req_key.shape[:-1] + (N,), bool)
    return ok & table.term_valid[..., None]


def dnf_any(term_matches):
    """OR over the term axis (second-to-last): ``lead+(T, N)`` → ``lead+(N,)``."""
    return jnp.any(term_matches, axis=-2)


def ns_member(ns_all, ns_ids, target_ns):
    """Namespace-set membership: ``lead`` bools / ``lead+(S,)`` ids vs ``[E]``
    namespaces → ``lead+(E,)``."""
    S = ns_ids.shape[-1]
    ok = jnp.broadcast_to(
        ns_all[..., None], ns_all.shape + (target_ns.shape[0],)
    )
    for s in range(S):
        nid = ns_ids[..., s]
        ok = ok | ((nid >= 0)[..., None] & (nid[..., None] == target_ns))
    return ok


# ---------------------------------------------------------------------------
# Segment helpers (per-node and per-domain aggregation)
# ---------------------------------------------------------------------------


def per_node_counts(values_e, node_idx, n_nodes: int):
    """Sum values over placed pods grouped by their node:
    ``lead+(E,)`` → ``lead+(N,)``.  Invalid node_idx rows are dropped."""
    lead = values_e.shape[:-1]
    E = values_e.shape[-1]
    seg = jnp.where((node_idx >= 0) & (node_idx < n_nodes), node_idx, n_nodes)
    flat = values_e.reshape((-1, E))
    out = jax.vmap(
        lambda d: jax.ops.segment_sum(d, seg, num_segments=n_nodes + 1)
    )(flat)
    return out[:, :n_nodes].reshape(lead + (n_nodes,))


def domain_stats(count_n, present_n, dv, v_cap: int):
    """Aggregate per-node values by topology-domain id and read them back
    per node.

    count_n:   lead+(N,) int — per-node quantity to sum per domain
    present_n: lead+(N,) bool — nodes whose domain "exists" (pair tracked)
    dv:        lead+(N,) int — domain id per node (label-value id; <0 absent)
    v_cap:     static domain-id bound (label-value vocab capacity)

    Returns (per_node_total, per_node_domain_present, min_over_present,
    n_domains): the first two gathered back at each node's domain, the last
    two reduced over present domains (min is INT32_MAX when none present).
    """
    lead = count_n.shape[:-1]
    N = count_n.shape[-1]
    seg = jnp.where((dv >= 0) & (dv < v_cap), dv, v_cap)
    flat_cnt = count_n.reshape((-1, N))
    flat_pres = present_n.reshape((-1, N)).astype(I32)
    flat_seg = seg.reshape((-1, N))

    def one(cnt, pres, s):
        tot = jax.ops.segment_sum(cnt, s, num_segments=v_cap + 1)
        dpres = jax.ops.segment_max(pres, s, num_segments=v_cap + 1) > 0
        dpres = dpres.at[v_cap].set(False)
        per_node_tot = tot[s]
        per_node_pres = dpres[s]
        big = jnp.iinfo(jnp.int32).max
        mn = jnp.min(jnp.where(dpres, tot, big))
        ndom = jnp.sum(dpres.astype(I32))
        return per_node_tot, per_node_pres, mn, ndom

    tot, pres, mn, ndom = jax.vmap(one)(flat_cnt, flat_pres, flat_seg)
    return (
        tot.reshape(lead + (N,)),
        pres.reshape(lead + (N,)),
        mn.reshape(lead),
        ndom.reshape(lead),
    )


def gather_rows(matrix, idx):
    """``matrix[idx]`` with negative indices masked to a sentinel row of
    ABSENT values: [N, K] gathered by lead-shaped idx → lead+(K,)."""
    safe = jnp.clip(idx, 0, matrix.shape[0] - 1)
    out = matrix[safe]
    return jnp.where((idx >= 0)[..., None], out, ABSENT)


def gather_at(cols_t, key):
    """cols_t: [K, N]; key: lead → lead+(N,) of label values (ABSENT when the
    key id is out of range/padding)."""
    K = cols_t.shape[0]
    known = (key >= 0) & (key < K)
    safe = jnp.clip(key, 0, K - 1)
    return jnp.where(known[..., None], cols_t[safe], ABSENT)
