"""Shared kernel machinery: device containers and primitive evaluators.

The conjunction-table evaluator here is the device analogue of
labels.Selector.Matches / nodeaffinity.RequiredNodeAffinity.Match in the
reference (staging/src/k8s.io/apimachinery/pkg/labels/selector.go,
component-helpers/scheduling/corev1/nodeaffinity) — one vectorized pass
instead of per-object interpreter loops.
"""

from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.snapshot.interner import ABSENT, INT_INVALID, PAD
from kubernetes_tpu.snapshot.schema import (
    ConjunctionTable,
    ExistingPodTensors,
    NodeTensors,
    PodBatch,
)
from kubernetes_tpu.snapshot.selectors import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
)

I32 = jnp.int32
I64 = jnp.int64


def _register_pytree(cls):
    """Register a plain dataclass of arrays as a JAX pytree."""
    names = [f.name for f in fields(cls)]

    def flatten(x):
        return tuple(getattr(x, n) for n in names), None

    def unflatten(_, children):
        return cls(*children)

    jax.tree_util.register_pytree_node(cls, flatten, unflatten)
    return cls


@_register_pytree
@dataclass
class DTable:
    """Device copy of a ConjunctionTable."""

    req_key: Any  # i32 [..., R]
    req_op: Any  # i32 [..., R]
    req_vals: Any  # i32 [..., R, V]
    req_rhs: Any  # i32 [..., R]
    term_valid: Any  # bool [...]

    @classmethod
    def host_tree(cls, t: ConjunctionTable) -> "DTable":
        """numpy-leaved instance — callers device_put whole pytrees at once
        (ONE transfer instead of one per field; remote device links care)."""
        return cls(
            req_key=np.asarray(t.req_key, np.int32),
            req_op=np.asarray(t.req_op, np.int32),
            req_vals=np.asarray(t.req_vals, np.int32),
            req_rhs=np.asarray(t.req_rhs, np.int32),
            term_valid=np.asarray(t.term_valid, bool),
        )

    @classmethod
    def from_host(cls, t: ConjunctionTable) -> "DTable":
        from kubernetes_tpu.ops import wire

        return wire.device_put_packed(cls.host_tree(t))


@_register_pytree
@dataclass
class DeviceCluster:
    """HBM-resident cluster snapshot (nodes + placed pods + their terms)."""

    # nodes
    allocatable: Any  # i32 [N, R]
    requested: Any  # i32 [N, R]
    nonzero_req: Any  # i32 [N, 2]
    num_pods: Any  # i32 [N]
    allowed_pods: Any  # i32 [N]
    node_labels: Any  # i32 [N, K]
    val_ints: Any  # i32 [V]
    taint_key: Any  # i32 [N, T]
    taint_val: Any  # i32 [N, T]
    taint_effect: Any  # i32 [N, T]
    unschedulable: Any  # bool [N]
    node_valid: Any  # bool [N]
    used_ppk: Any  # i32 [N, U]
    used_ip: Any  # i32 [N, U]
    used_wild: Any  # bool [N, U]
    img_sizes: Any  # i64 [N, IMG]
    # zone-round-robin visit rank (node_tree.go order; -1 invalid) — the
    # sampling-compat window/rotation and compat tie-breaks read this
    visit_rank: Any  # i32 [N]
    # placed pods
    epod_node: Any  # i32 [E]
    epod_ns: Any  # i32 [E]
    epod_labels: Any  # i32 [E, K]
    epod_valid: Any  # bool [E]
    epod_deleting: Any  # bool [E]
    # flattened (anti-)affinity terms of placed pods
    term_pod: Any  # i32 [M]
    term_kind: Any  # i32 [M]
    term_topo: Any  # i32 [M]
    term_weight: Any  # i32 [M]
    term_table: DTable  # [M, 1, ...]
    term_ns_all: Any  # bool [M]
    term_ns_ids: Any  # i32 [M, NS]
    # scalar ids resolved from the vocab (traced so vocab growth ≠ recompile)
    name_key: Any  # i32  label-key id of metadata.name
    unsched_key: Any  # i32  label-key id of node.kubernetes.io/unschedulable
    empty_val: Any  # i32  label-val id of ""
    n_valid_nodes: Any  # i32  number of real nodes
    log_tab: Any  # i64 [N+2]  fixed-point round(log(i+2)·2^32) table

    @classmethod
    def from_host(cls, nt: NodeTensors, ep: ExistingPodTensors, vocab) -> "DeviceCluster":
        from kubernetes_tpu.ops import wire
        from kubernetes_tpu.snapshot.selectors import METADATA_NAME_KEY

        n = int(nt.valid.sum())
        log_tab = np.round(
            np.log(np.arange(nt.n_cap + 2, dtype=np.float64) + 2.0) * (1 << 32)
        ).astype(np.int64)
        return wire.device_put_packed(cls(
            allocatable=np.asarray(nt.allocatable, np.int32),
            requested=np.asarray(nt.requested, np.int32),
            nonzero_req=np.asarray(nt.nonzero_req, np.int32),
            num_pods=np.asarray(nt.num_pods, np.int32),
            allowed_pods=np.asarray(nt.allowed_pods, np.int32),
            node_labels=np.asarray(nt.label_vals, np.int32),
            val_ints=np.asarray(nt.val_ints, np.int32),
            taint_key=np.asarray(nt.taint_key, np.int32),
            taint_val=np.asarray(nt.taint_val, np.int32),
            taint_effect=np.asarray(nt.taint_effect, np.int32),
            unschedulable=np.asarray(nt.unschedulable, bool),
            node_valid=np.asarray(nt.valid, bool),
            used_ppk=np.asarray(nt.used_ppk, np.int32),
            used_ip=np.asarray(nt.used_ip, np.int32),
            used_wild=np.asarray(nt.used_wild, bool),
            img_sizes=np.asarray(nt.img_sizes, np.int64),
            visit_rank=np.asarray(nt.visit_rank, np.int32),
            epod_node=np.asarray(ep.node_idx, np.int32),
            epod_ns=np.asarray(ep.ns_id, np.int32),
            epod_labels=np.asarray(ep.label_vals, np.int32),
            epod_valid=np.asarray(ep.valid, bool),
            epod_deleting=np.asarray(ep.deleting, bool),
            term_pod=np.asarray(ep.term_pod, np.int32),
            term_kind=np.asarray(ep.term_kind, np.int32),
            term_topo=np.asarray(ep.term_topo_key, np.int32),
            term_weight=np.asarray(ep.term_weight, np.int32),
            term_table=DTable.host_tree(ep.term_table),
            term_ns_all=np.asarray(ep.term_ns_all, bool),
            term_ns_ids=np.asarray(ep.term_ns_ids, np.int32),
            name_key=np.asarray(vocab.label_keys.lookup(METADATA_NAME_KEY), np.int32),
            unsched_key=np.asarray(
                vocab.label_keys.lookup("node.kubernetes.io/unschedulable"), I32
            ),
            empty_val=np.asarray(vocab.label_vals.lookup(""), np.int32),
            n_valid_nodes=np.asarray(n, np.int32),
            log_tab=np.asarray(log_tab),
        ))


@_register_pytree
@dataclass
class DeviceBatch:
    """Pending-pod batch on device."""

    requests: Any  # i32 [P, R]
    nonzero_req: Any  # i32 [P, 2]
    ns_id: Any  # i32 [P]
    priority: Any  # i32 [P]
    labels: Any  # i32 [P, K]
    valid: Any  # bool [P]
    node_sel: DTable  # [P, T, ...]
    pref_node: DTable  # [P, PT, ...]
    pref_weight: Any  # i32 [P, PT]
    tol_key: Any  # i32 [P, TL]
    tol_op: Any  # i32 [P, TL]
    tol_val: Any  # i32 [P, TL]
    tol_effect: Any  # i32 [P, TL]
    tsc_table: DTable  # [P, C, ...]
    tsc_topo: Any  # i32 [P, C]
    tsc_max_skew: Any  # i32 [P, C]
    tsc_hard: Any  # bool [P, C]
    tsc_min_domains: Any  # i32 [P, C]
    tsc_honor_affinity: Any  # bool [P, C]
    tsc_honor_taints: Any  # bool [P, C]
    aff_table: DTable  # [P, AT, ...]
    aff_kind: Any  # i32 [P, AT]
    aff_topo: Any  # i32 [P, AT]
    aff_weight: Any  # i32 [P, AT]
    aff_ns_all: Any  # bool [P, AT]
    aff_ns_ids: Any  # i32 [P, AT, NS]
    target_name_val: Any  # i32 [P]
    want_ppk: Any  # i32 [P, W]
    want_ip: Any  # i32 [P, W]
    want_wild: Any  # bool [P, W]
    img_ids: Any  # i32 [P, I]
    n_containers: Any  # i32 [P]

    @classmethod
    def from_host(cls, pb: PodBatch) -> "DeviceBatch":
        from kubernetes_tpu.ops import wire

        return wire.device_put_packed(cls(
            requests=np.asarray(pb.requests, np.int32),
            nonzero_req=np.asarray(pb.nonzero_req, np.int32),
            ns_id=np.asarray(pb.ns_id, np.int32),
            priority=np.asarray(pb.priority, np.int32),
            labels=np.asarray(pb.label_vals, np.int32),
            valid=np.asarray(pb.valid, bool),
            node_sel=DTable.host_tree(pb.node_sel),
            pref_node=DTable.host_tree(pb.pref_node),
            pref_weight=np.asarray(pb.pref_weight, np.int32),
            tol_key=np.asarray(pb.tol_key, np.int32),
            tol_op=np.asarray(pb.tol_op, np.int32),
            tol_val=np.asarray(pb.tol_val, np.int32),
            tol_effect=np.asarray(pb.tol_effect, np.int32),
            tsc_table=DTable.host_tree(pb.tsc_table),
            tsc_topo=np.asarray(pb.tsc_topo_key, np.int32),
            tsc_max_skew=np.asarray(pb.tsc_max_skew, np.int32),
            tsc_hard=np.asarray(pb.tsc_hard, bool),
            tsc_min_domains=np.asarray(pb.tsc_min_domains, np.int32),
            tsc_honor_affinity=np.asarray(pb.tsc_honor_affinity, bool),
            tsc_honor_taints=np.asarray(pb.tsc_honor_taints, bool),
            aff_table=DTable.host_tree(pb.aff_table),
            aff_kind=np.asarray(pb.aff_kind, np.int32),
            aff_topo=np.asarray(pb.aff_topo_key, np.int32),
            aff_weight=np.asarray(pb.aff_weight, np.int32),
            aff_ns_all=np.asarray(pb.aff_ns_all, bool),
            aff_ns_ids=np.asarray(pb.aff_ns_ids, np.int32),
            target_name_val=np.asarray(pb.target_name_val, np.int32),
            want_ppk=np.asarray(pb.want_ppk, np.int32),
            want_ip=np.asarray(pb.want_ip, np.int32),
            want_wild=np.asarray(pb.want_wild, bool),
            img_ids=np.asarray(pb.img_ids, np.int32),
            n_containers=np.asarray(pb.n_containers, np.int32),
        ))


# ---------------------------------------------------------------------------
# Named-axis schema (consumed by the static analyzer's shape/dtype/shard
# interpreter — `python -m kubernetes_tpu.analysis`, ANALYSIS.md glossary).
# One entry per device dataclass; dims use the canonical axis names
# (P pods, N nodes, Rn/Rp resource lanes, K label keys, V value vocab,
# TA taints, U/UP ports, E placed pods, M terms, NS namespaces, C spread
# slots, A inter-pod slots, NT/PT selector terms, TL tolerations,
# IMG/IP images, L log table).  A trailing underscore marks a dim PRIVATE
# to the class schema (each DTable instance is bucketed independently);
# `*` splices the owning field's lead dims.
# ---------------------------------------------------------------------------

_KTPU_AXES = {
    "DTable": {
        "req_key": "i32[*,Q_]",
        "req_op": "i32[*,Q_]",
        "req_vals": "i32[*,Q_,Y_]",
        "req_rhs": "i32[*,Q_]",
        "term_valid": "bool[*]",
    },
    "DeviceCluster": {
        "allocatable": "i32[N,Rn]",
        "requested": "i32[N,Rn]",
        "nonzero_req": "i32[N,2]",
        "num_pods": "i32[N]",
        "allowed_pods": "i32[N]",
        "node_labels": "i32[N,K]",
        "val_ints": "i32[V]",
        "taint_key": "i32[N,TA]",
        "taint_val": "i32[N,TA]",
        "taint_effect": "i32[N,TA]",
        "unschedulable": "bool[N]",
        "node_valid": "bool[N]",
        "used_ppk": "i32[N,U]",
        "used_ip": "i32[N,U]",
        "used_wild": "bool[N,U]",
        "img_sizes": "i64[N,IMG]",
        "visit_rank": "i32[N]",
        "epod_node": "i32[E]",
        "epod_ns": "i32[E]",
        "epod_labels": "i32[E,K]",
        "epod_valid": "bool[E]",
        "epod_deleting": "bool[E]",
        "term_pod": "i32[M]",
        "term_kind": "i32[M]",
        "term_topo": "i32[M]",
        "term_weight": "i32[M]",
        "term_table": "DTable[M,1]",
        "term_ns_all": "bool[M]",
        "term_ns_ids": "i32[M,NS]",
        "name_key": "i32",
        "unsched_key": "i32",
        "empty_val": "i32",
        "n_valid_nodes": "i32",
        # NOT the node axis: a value-indexed fixed-point log table (its
        # length happens to be N+2) — gathers into it are shard-neutral
        "log_tab": "i64[L]",
    },
    "DeviceBatch": {
        "requests": "i32[P,Rp]",
        "nonzero_req": "i32[P,2]",
        "ns_id": "i32[P]",
        "priority": "i32[P]",
        "labels": "i32[P,K]",
        "valid": "bool[P]",
        "node_sel": "DTable[P,NT]",
        "pref_node": "DTable[P,PT]",
        "pref_weight": "i32[P,PT]",
        "tol_key": "i32[P,TL]",
        "tol_op": "i32[P,TL]",
        "tol_val": "i32[P,TL]",
        "tol_effect": "i32[P,TL]",
        "tsc_table": "DTable[P,C]",
        "tsc_topo": "i32[P,C]",
        "tsc_max_skew": "i32[P,C]",
        "tsc_hard": "bool[P,C]",
        "tsc_min_domains": "i32[P,C]",
        "tsc_honor_affinity": "bool[P,C]",
        "tsc_honor_taints": "bool[P,C]",
        "aff_table": "DTable[P,A]",
        "aff_kind": "i32[P,A]",
        "aff_topo": "i32[P,A]",
        "aff_weight": "i32[P,A]",
        "aff_ns_all": "bool[P,A]",
        "aff_ns_ids": "i32[P,A,NS]",
        "target_name_val": "i32[P]",
        "want_ppk": "i32[P,UP]",
        "want_ip": "i32[P,UP]",
        "want_wild": "bool[P,UP]",
        "img_ids": "i32[P,IP]",
        "n_containers": "i32[P]",
    },
}

# Declared N-axis collectives (shard rule): these helpers deliberately
# cross the node axis — segment-scatters into per-node rows and
# domain-id spaces.  Under a sharded N mesh each becomes a cross-shard
# collective; the multichip refactor (ROADMAP item 2) routes exactly
# this roster through jax collectives.
_KTPU_N_COLLECTIVES = {
    "per_node_counts": "resolved(collective): segment-scatter of per-pod "
    "values into [N] rows — contributions route to the owning node shard "
    "(all-to-all + local scatter-add; integer counts, order-free)",
    "domain_stats": "resolved(collective): segment-reduce of [N] rows "
    "into topology domains and gather back per node — per-shard partial "
    "domain sums psum into the small replicated [D] domain table, then "
    "the per-node gather reads it shard-locally",
}


# ---------------------------------------------------------------------------
# Conjunction evaluation
# ---------------------------------------------------------------------------


def eval_table(table: DTable, label_vals, val_ints):
    """Evaluate every conjunction against every label row.

    table arrays have shape ``lead + (R,)`` / ``lead + (R, V)``; ``label_vals``
    is ``[N, K]``.  Returns matches ``lead + (N,)`` — term_valid is already
    folded in (invalid/padding terms match nothing).

    Requirement semantics mirror labels.Requirement.Matches (selector.go):
    NotIn also matches absent keys; Gt/Lt need integer-parsing both sides.
    The static R/V loops keep peak memory at one ``lead+(N,)`` buffer per op.
    """
    R = table.req_key.shape[-1]
    V = table.req_vals.shape[-1]
    N, K = label_vals.shape
    cols = label_vals.T  # [K, N]

    ok = None
    for r in range(R):
        key = table.req_key[..., r]  # lead
        op = table.req_op[..., r]
        rhs = table.req_rhs[..., r]
        key_known = (key >= 0) & (key < K)
        safe_key = jnp.clip(key, 0, K - 1)
        val = jnp.where(key_known[..., None], cols[safe_key], ABSENT)  # lead+(N,)
        present = val >= 0

        in_any = jnp.zeros_like(present)
        for v in range(V):
            rv = table.req_vals[..., r, v]
            in_any = in_any | (present & (val == rv[..., None]) & (rv >= 0)[..., None])

        iv = jnp.where(
            present,
            val_ints[jnp.clip(val, 0, val_ints.shape[0] - 1)],
            INT_INVALID,
        )
        int_ok = (iv != INT_INVALID) & (rhs != INT_INVALID)[..., None]

        opb = op[..., None]
        res = jnp.where(
            opb == OP_IN,
            in_any,
            jnp.where(
                opb == OP_NOT_IN,
                ~in_any,
                jnp.where(
                    opb == OP_EXISTS,
                    present,
                    jnp.where(
                        opb == OP_DOES_NOT_EXIST,
                        ~present,
                        jnp.where(
                            opb == OP_GT,
                            int_ok & (iv > rhs[..., None]),
                            int_ok & (iv < rhs[..., None]),  # OP_LT
                        ),
                    ),
                ),
            ),
        )
        res = jnp.where(opb == PAD, True, res)  # padded requirement slot
        ok = res if ok is None else (ok & res)
    if ok is None:
        ok = jnp.ones(table.req_key.shape[:-1] + (N,), bool)
    return ok & table.term_valid[..., None]


def dnf_any(term_matches):
    """OR over the term axis (second-to-last): ``lead+(T, N)`` → ``lead+(N,)``."""
    return jnp.any(term_matches, axis=-2)


def ns_member(ns_all, ns_ids, target_ns):
    """Namespace-set membership: ``lead`` bools / ``lead+(S,)`` ids vs ``[E]``
    namespaces → ``lead+(E,)``."""
    S = ns_ids.shape[-1]
    ok = jnp.broadcast_to(
        ns_all[..., None], ns_all.shape + (target_ns.shape[0],)
    )
    for s in range(S):
        nid = ns_ids[..., s]
        ok = ok | ((nid >= 0)[..., None] & (nid[..., None] == target_ns))
    return ok


# ---------------------------------------------------------------------------
# Segment helpers (per-node and per-domain aggregation)
# ---------------------------------------------------------------------------


def per_node_counts(values_e, node_idx, n_nodes: int):
    """Sum values over placed pods grouped by their node:
    ``lead+(E,)`` → ``lead+(N,)``.  Invalid node_idx rows are dropped."""
    lead = values_e.shape[:-1]
    E = values_e.shape[-1]
    seg = jnp.where((node_idx >= 0) & (node_idx < n_nodes), node_idx, n_nodes)
    flat = values_e.reshape((-1, E))
    out = jax.vmap(
        lambda d: jax.ops.segment_sum(d, seg, num_segments=n_nodes + 1)
    )(flat)
    return out[:, :n_nodes].reshape(lead + (n_nodes,))


def domain_stats(count_n, present_n, dv, v_cap: int):
    """Aggregate per-node values by topology-domain id and read them back
    per node.

    count_n:   lead+(N,) int — per-node quantity to sum per domain
    present_n: lead+(N,) bool — nodes whose domain "exists" (pair tracked)
    dv:        lead+(N,) int — domain id per node (label-value id; <0 absent)
    v_cap:     static domain-id bound (label-value vocab capacity)

    Returns (per_node_total, per_node_domain_present, min_over_present,
    n_domains): the first two gathered back at each node's domain, the last
    two reduced over present domains (min is INT32_MAX when none present).
    """
    lead = count_n.shape[:-1]
    N = count_n.shape[-1]
    seg = jnp.where((dv >= 0) & (dv < v_cap), dv, v_cap)
    flat_cnt = count_n.reshape((-1, N))
    flat_pres = present_n.reshape((-1, N)).astype(I32)
    flat_seg = seg.reshape((-1, N))

    def one(cnt, pres, s):
        tot = jax.ops.segment_sum(cnt, s, num_segments=v_cap + 1)
        dpres = jax.ops.segment_max(pres, s, num_segments=v_cap + 1) > 0
        dpres = dpres.at[v_cap].set(False)
        per_node_tot = tot[s]
        per_node_pres = dpres[s]
        big = jnp.iinfo(jnp.int32).max
        mn = jnp.min(jnp.where(dpres, tot, big))
        ndom = jnp.sum(dpres.astype(I32))
        return per_node_tot, per_node_pres, mn, ndom

    tot, pres, mn, ndom = jax.vmap(one)(flat_cnt, flat_pres, flat_seg)
    return (
        tot.reshape(lead + (N,)),
        pres.reshape(lead + (N,)),
        mn.reshape(lead),
        ndom.reshape(lead),
    )


def gather_rows(matrix, idx):
    """``matrix[idx]`` with negative indices masked to a sentinel row of
    ABSENT values: [N, K] gathered by lead-shaped idx → lead+(K,)."""
    safe = jnp.clip(idx, 0, matrix.shape[0] - 1)
    out = matrix[safe]
    return jnp.where((idx >= 0)[..., None], out, ABSENT)


def gather_at(cols_t, key):
    """cols_t: [K, N]; key: lead → lead+(N,) of label values (ABSENT when the
    key id is out of range/padding)."""
    K = cols_t.shape[0]
    known = (key >= 0) & (key < K)
    safe = jnp.clip(key, 0, K - 1)
    return jnp.where(known[..., None], cols_t[safe], ABSENT)


# ---------------------------------------------------------------------------
# The shared usage carry update — ONE serial-recurrence commit
# ---------------------------------------------------------------------------


def usage_carry_update(rows, deltas, nodes, live):
    """THE per-commit node-usage update shared by every serial-recurrence
    replayer: the gang scan / wave admission / workloads admission (via
    gang.pod_step), the sig_scan serial tail (fastpath.make_sig_step), and
    the resident fixed point's round commit (ops/resident.py).

    rows:   dict name → [N, ...] carried usage tensor
    deltas: dict name → per-commit row delta (broadcastable against the
            trailing dims of rows[name]; scalar for counters)
    nodes:  committed node index — a scalar i32 choice, or an [W] window of
            per-slot choices (the resident loop commits a whole agreement
            prefix at once)
    live:   bool commit gate, same leading shape as ``nodes``

    Scalar commits are scatter-free rank-1 one-hot updates — scan bodies
    must never scatter (the TPU op-latency discipline of ops/gang.py).
    Windowed commits scatter-add: within a resident round each walk
    position commits at most once, so the adds are disjoint and the result
    equals replaying the scalar form per slot.
    """
    if nodes.ndim == 0:
        N = next(iter(rows.values())).shape[0]
        onehot = (jnp.arange(N, dtype=I32) == nodes) & live
        out = {}
        # ktpu: allow(jit-boundary) — rows' KEYS are static python
        # structure fixed per call site; only the values are traced
        for k, row in rows.items():
            d = jnp.asarray(deltas[k], row.dtype)
            oh = onehot.reshape((N,) + (1,) * (row.ndim - 1)).astype(row.dtype)
            out[k] = row + oh * d
        return out
    out = {}
    # ktpu: allow(jit-boundary) — rows' KEYS are static python structure
    # fixed per call site; only the values are traced
    for k, row in rows.items():
        d = jnp.asarray(deltas[k], row.dtype)
        gate = live.reshape(live.shape + (1,) * (row.ndim - 1))
        d = jnp.broadcast_to(d, nodes.shape + row.shape[1:]) * gate.astype(
            row.dtype
        )
        out[k] = row.at[nodes].add(d)
    return out
