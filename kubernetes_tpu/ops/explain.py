"""Explain-mode kernel: per-plugin feasibility masks for a pod batch.

The batched filter pipeline (ops/gang.py) computes a per-kernel pass mask
for every (pod, node) pair but returns only the winner and aggregate
rejection counts — the per-node, per-plugin verdicts (the reference's
Diagnosis/NodeToStatusMap, framework/types.go:367) are thrown away on
device.  ``explain_masks`` recomputes exactly those masks for a diagnosed
batch and returns the FULL [N_DIAG, P, N] tensor, so one gated d2h fetch
answers "why is this pod unschedulable on each node" per plugin.

Semantics: verdicts are judged against the CURRENT cluster snapshot with
no in-batch peers and no nominated-pod charges — the state a fresh
one-pod scheduling attempt (and the host oracle's ``feasible_nodes``)
would see.  The mask stack is ordered exactly like ``gang.DIAG_KERNELS``:

    NodeUnschedulable, NodeName, TaintToleration, NodeAffinity, NodePorts,
    HostFilters, NodeResourcesFit, PodTopologySpread, InterPodAffinity

Each row is the kernel's independent pass/fail (NOT first-failure
attributed): a node rejected by three plugins is False in three rows,
matching the oracle's collect-all-reasons walk.

Cost model: this is a separate jitted entry point dispatched only from the
/debug/explain path — the scheduling hot loop never calls it, so its d2h
(the one blocking fetch of the [N_DIAG, P, N] stack) happens exclusively
for diagnosed pods.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops import gang
from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster, I32
from kubernetes_tpu.snapshot.schema import N_FIXED_LANES

# shard-rule roster: diagnosis recomputes minMatch over the tracked
# node set per constraint — inherently a full-N reduction
_KTPU_N_COLLECTIVES = {
    "explain_masks._spread_one": "resolved(replicated): per-constraint "
    "min-match over the tracked N axis (filtering.go:313 semantics) — "
    "the explain/debug tier builds its own single-device snapshot view "
    "(one diagnosed pod per d2h, latency-bound not throughput-bound), "
    "so the crossed operand is whole-array by construction; were it "
    "mesh-placed, the min-match would ride a cross-shard min-reduce",
}


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch, hostname_key=i32, extra_mask=bool[P,N])
# ktpu: axes(sp_keys=i32[Kd], sp_cdv_tab=i32[Kd,N], ip_keys=i32[Kd2])
# ktpu: static(v_cap=16)
@functools.partial(
    jax.jit,
    static_argnames=(
        "v_cap",
        "has_interpod",
        "has_spread",
        "has_ports",
        "enabled",
        "check_fit",
    ),
)
def explain_masks(
    dc: DeviceCluster,
    db: DeviceBatch,
    hostname_key,
    v_cap: int,
    has_interpod: bool = True,
    has_spread: bool = True,
    has_ports: bool = True,
    enabled: frozenset = F.ALL_FILTER_KERNELS,
    check_fit: bool = True,
    extra_mask=None,
    sp_keys=None,
    sp_cdv_tab=None,
    ip_keys=None,
):
    """Returns bool [N_DIAG, P, N] per-kernel pass masks (gang.DIAG_KERNELS
    row order) plus the combined feasibility [P, N] as the last element of
    a 2-tuple.  Table kwargs come from ``gang.batch_tables``."""
    g = gang.precompute(
        dc,
        db,
        hostname_key,
        v_cap,
        has_interpod=has_interpod,
        has_spread=has_spread,
        has_ports=has_ports,
        has_images=False,
        enabled=enabled,
        extra_mask=extra_mask,
        sp_keys=sp_keys,
        sp_cdv_tab=sp_cdv_tab,
        ip_keys=ip_keys,
    )
    P, N = g.static_mask.shape
    Rn = dc.requested.shape[1]
    Rp = db.requests.shape[1]
    true_pn = jnp.ones((P, N), bool)

    # ---- NodeResourcesFit against the snapshot usage (the state-dependent
    # half of gang_schedule's cheap_body, with zero in-batch commits)
    if check_fit:
        fits = dc.num_pods + 1 <= dc.allowed_pods  # [N]
        req = db.requests  # [P, Rp]
        all_zero = jnp.all(req == 0, axis=1)  # [P]
        avail = dc.allocatable - dc.requested  # [N, Rn]
        if Rp > Rn:
            avail = jnp.concatenate(
                [avail, jnp.zeros((N, Rp - Rn), I32)], axis=1
            )
        conflict = req[:, None, :] > avail[None, :, :]  # [P, N, Rp]
        # extended-resource lanes only count when actually requested
        scalar_lane = jnp.arange(Rp) >= N_FIXED_LANES
        conflict = conflict & (
            ~scalar_lane[None, None, :] | (req[:, None, :] > 0)
        )
        lane_ok = ~jnp.any(conflict, axis=2)  # [P, N]
        m_fit = fits[None, :] & (all_zero[:, None] | lane_ok)
    else:
        m_fit = true_pn

    # ---- PodTopologySpread hard constraints vs existing pods only
    C = g.sp_dv.shape[1]
    if C:
        big32 = jnp.iinfo(jnp.int32).max

        def _spread_one(hard, dv, te, dom_cnt, dom_pres, ndom, selfm, mind, mskew):
            total = dom_cnt  # [C, N] — no batch-peer contributions
            min_match = jnp.min(jnp.where(te, total, big32), axis=1)  # [C]
            min_match = jnp.where((mind > 0) & (ndom < mind), 0, min_match)
            skew = total + selfm.astype(I32)[:, None] - min_match[:, None]
            c_ok = (dv >= 0) & (~dom_pres | (skew <= mskew[:, None]))
            return jnp.all(~hard[:, None] | c_ok, axis=0)  # [N]

        m_spread = jax.vmap(_spread_one)(
            g.sp_hard,
            g.sp_dv,
            g.sp_te,
            g.sp_dom_cnt,
            g.sp_dom_pres,
            g.sp_ndom,
            g.sp_self,
            db.tsc_min_domains,
            db.tsc_max_skew,
        )
    else:
        m_spread = true_pn

    # ---- InterPodAffinity vs existing pods only
    AT = g.ip_dv.shape[1]
    if AT:

        def _interpod_one(dv, dom_cnt, is_aff, is_anti, any_static, self_all):
            topo_present = dv >= 0  # [AT, N]
            total = dom_cnt
            viol2 = jnp.any(
                is_anti[:, None] & topo_present & (total > 0), axis=0
            )
            aff_ok = jnp.all(
                ~is_aff[:, None] | (topo_present & (total > 0)), axis=0
            )
            topo_all = jnp.all(~is_aff[:, None] | topo_present, axis=0)
            escape = jnp.any(is_aff) & ~any_static & self_all
            ok3 = aff_ok | (escape & topo_all)
            return ~viol2 & ok3  # [N]

        m_interpod = ~g.ip_viol_existing & jax.vmap(_interpod_one)(
            g.ip_dv,
            g.ip_dom_cnt,
            g.ip_is_aff,
            g.ip_is_anti,
            g.ip_any_static,
            g.ip_self_all,
        )
    else:
        m_interpod = ~g.ip_viol_existing

    base = dc.node_valid[None, :] & db.valid[:, None]
    stack = jnp.stack(
        [
            g.d_unsched,
            g.d_nodename,
            g.d_taints,
            g.d_nodeaff,
            g.d_ports,  # static port conflicts only: no in-batch peers
            g.d_extra,
            m_fit,
            m_spread,
            m_interpod,
        ]
    )  # [N_DIAG, P, N]
    feasible = base & jnp.all(stack, axis=0)
    return stack, feasible
