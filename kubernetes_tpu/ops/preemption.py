"""Device-assisted preemption narrowing.

ONE dispatch computes, for every pod that failed its scheduling attempt,
the per-node mask of PLAUSIBLE preemption candidates — the batched front of
DryRunPreemption (preemption.go:548).  A node survives for pod p iff:

  * the victim-independent filters pass (NodesForStatusCode(Unschedulable)
    semantics: unschedulable/name/taints/node-affinity — what no victim
    removal can fix);
  * the node carries at least one strictly-lower-priority victim;
  * p FITS after removing every lower-priority pod — the dry-run's most
    optimistic state (remove-all, default_preemption.go:140), so the mask
    is a strict SUPERSET of true candidates: narrowing is sound.

The host reprieve loop (framework/preemption.py dry_run) then runs the
exact reference semantics (inter-pod/spread re-filtering, PDB classes,
highest-priority-first reprieve) on the shortlisted nodes only.

Victim removal totals are factored by DISTINCT preemptor priority (usually
a handful of PriorityClasses): per group, a segment-sum over placed pods
yields the per-node requests that remain — O(G·E) scatter work instead of
a P×E×N contraction.

The batch's OWN committed placements (the admission scan's carried state,
handed over as the dispatch's ``chosen`` output) join the victim plane as
``batch_*`` rows instead of being re-derived from the cache — at narrowing
time they are not yet assumed, so the placed-pod walk cannot see them.
Charging is deliberately asymmetric to stay a SUPERSET of the host
reprieve walk each failed pod later runs (queue order is priority-ordered,
so peers of strictly higher priority committed BEFORE every failed pod and
the walk sees them assumed; equal-priority peers may commit after the
failed pod's walk and must not be charged; strictly-lower peers commit
after it and can only be future victims):

  * strictly higher priority  → charged as kept usage (exact);
  * equal priority            → ignored (loose, sound);
  * strictly lower priority   → counts as a removable victim (loose).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster, I32

# shard-rule roster: victim-removal totals are segment-sums of placed
# pods INTO per-node rows — a scatter across a sharded N axis
_KTPU_N_COLLECTIVES = {
    "narrow_candidates.per_group": "resolved(collective): "
    "per-priority-group segment-sum of victim AND committed-batch-peer "
    "requests/counts into [N] rows — victim contributions route to the "
    "owning node shard (GSPMD lowers the segment scatter to "
    "all-to-all + local scatter-add; integer sums, order-free)",
}


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch, victim_node=i32[E], victim_prio=i32[E])
# ktpu: axes(victim_req=i32[E,Rn], prio_groups=i32[G], pod_group=i32[P])
# ktpu: axes(batch_node=i32[B2], batch_prio=i32[B2], batch_req=i32[B2,Rn])
@jax.jit
def narrow_candidates(
    dc: DeviceCluster,
    db: DeviceBatch,
    victim_node,  # i32 [E]   placed-pod node index (<0 pads)
    victim_prio,  # i32 [E]   placed-pod priority
    victim_req,   # i32 [E,R] placed-pod request rows
    prio_groups,  # i32 [G]   distinct preemptor priorities (pad: INT32_MIN)
    pod_group,    # i32 [P]   index into prio_groups per batch pod
    batch_node=None,  # i32 [B2]   this batch's committed placements
    batch_prio=None,  # i32 [B2]   (<0 node pads; see module docstring)
    batch_req=None,   # i32 [B2,R]
):
    """bool [P, N]: nodes worth dry-running per failed pod."""
    N = dc.node_valid.shape[0]
    Rn = dc.allocatable.shape[1]

    static = (
        dc.node_valid[None, :]
        & db.valid[:, None]
        & F.mask_node_name(dc, db)
        & F.mask_unschedulable(dc, db)
        & F.mask_taints(dc, db)
        & F.mask_node_affinity(dc, db)
    )  # [P, N]

    valid = victim_node >= 0
    seg = jnp.where(valid, victim_node, N)  # dump row N
    if batch_node is not None:
        bvalid = batch_node >= 0
        bseg = jnp.where(bvalid, batch_node, N)

    def per_group(threshold):
        lower = (victim_prio < threshold) & valid  # victims that go
        keep = (~lower & valid).astype(I32)
        kept_req = jax.vmap(
            lambda col: jax.ops.segment_sum(col * keep, seg, num_segments=N + 1)
        )(victim_req.T).T[:N]  # [N, R]
        kept_cnt = jax.ops.segment_sum(keep, seg, num_segments=N + 1)[:N]
        victim_here = (
            jax.ops.segment_sum(lower.astype(I32), seg, num_segments=N + 1)[:N]
            > 0
        )
        if batch_node is not None:
            # committed batch peers: the asymmetric charging of the module
            # docstring — strictly-higher kept, equal ignored, lower victim
            bkeep = (bvalid & (batch_prio > threshold)).astype(I32)
            blower = bvalid & (batch_prio < threshold)
            kept_req = kept_req + jax.vmap(
                lambda col: jax.ops.segment_sum(
                    col * bkeep, bseg, num_segments=N + 1
                )
            )(batch_req.T).T[:N]
            kept_cnt = kept_cnt + jax.ops.segment_sum(
                bkeep, bseg, num_segments=N + 1
            )[:N]
            victim_here = victim_here | (
                jax.ops.segment_sum(
                    blower.astype(I32), bseg, num_segments=N + 1
                )[:N]
                > 0
            )
        return kept_req, kept_cnt, victim_here

    kept_req_g, kept_cnt_g, victim_g = jax.vmap(per_group)(prio_groups)

    gid = jnp.clip(pod_group, 0, prio_groups.shape[0] - 1)
    kept_req = kept_req_g[gid]  # [P, N, R]
    kept_cnt = kept_cnt_g[gid]  # [P, N]
    has_victim = victim_g[gid]  # [P, N]

    req = db.requests[:, :Rn]  # [P, R]
    fits_cnt = kept_cnt + 1 <= dc.allowed_pods[None, :]
    avail = dc.allocatable[None, :, :] - kept_req
    fits_res = jnp.all(req[:, None, :] <= avail, axis=2) | jnp.all(
        req == 0, axis=1
    )[:, None]

    return static & has_victim & fits_cnt & fits_res
