"""DRA claim allocation as a batched device-matching kernel.

The reference's structured allocator (staging DRA structured/allocator.go,
mirrored serially by framework/dynamicresources.py) walks every node's
ResourceSlices per pod, evaluating selector requirements against device
attributes one (claim, node, device) triple at a time — the per-pod host
path the workloads tier replaces.  Here the whole surface is tensorized:

  * ResourceSlice devices pack into ``[N, DD, DA]`` attribute key/value
    tensors (one device slot axis per node, one attribute slot axis per
    device, both bucketed);
  * claim requests pack into ``[P, DQ]`` slots whose (attribute, op,
    values) selector triples — DeviceClass selectors concatenated with the
    request's own — become ``[P, DQ, DS(, DV)]`` requirement tensors, so
    matching is one vectorized compare + all-reduce producing the full
    ``[P, DQ, N, DD]`` match tensor (selector semantics identical to
    dra.DeviceSelector.matches: In / NotIn / Exists / DoesNotExist, NotIn
    admitting absent attributes);
  * allocation state is two carried arrays — ``free [N, DD]`` (device not
    held by any allocated claim) and ``claim_node [CL]`` (node an
    in-batch-referenced claim is allocated to, -1 unallocated) — that ride
    the admission scan's state dict like any other usage row, so claims
    participate in conflict resolution (and gang rollback) exactly like
    CPU/memory do;
  * per-node feasibility + the greedy take mask are one fused pass over
    the static DQ request slots: ExactCount needs ``count`` matching free
    devices (taken lowest-slot-first — the reference's slice/device
    enumeration order, which the host packer preserves), All needs EVERY
    matching device free (allocator.go:530-552).

The kernels here are pure functions invoked from the workloads admission
root (ops/coscheduling.py); the serial oracle (oracle/workloads.py) and
the DynamicResources plugin path define the same semantics object-by-object
— property-tested equal in tests/test_dra.py / tests/test_coscheduling.py.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.api.dra import ALLOCATION_MODE_ALL
from kubernetes_tpu.ops.common import I32
from kubernetes_tpu.snapshot.interner import ABSENT, PAD
from kubernetes_tpu.snapshot.schema import bucket_cap
from kubernetes_tpu.snapshot.selectors import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
)

_SEL_OPS = {
    "In": OP_IN,
    "NotIn": OP_NOT_IN,
    "Exists": OP_EXISTS,
    "DoesNotExist": OP_DOES_NOT_EXIST,
}


# ---------------------------------------------------------------------------
# Host-side packing
# ---------------------------------------------------------------------------


def dra_tables(
    pods,
    name_to_idx,
    n_cap: int,
    p_cap: int,
    slices,
    device_classes,
    claims_by_key,
):
    """Pack the batch's DRA surface into device-ready tensors.

    ``slices`` is the scheduler's ResourceSlice list in lister order (the
    enumeration order the greedy take and the plugin's serial allocator
    share), ``device_classes`` maps name → DeviceClass, ``claims_by_key``
    maps "ns/name" → the WHOLE claim-cache view (assumed state included)
    — not just batch-referenced claims: ``free0`` must exclude devices
    held by ANY allocated claim (the serial plugin's _allocated_devices
    contract), so a batch-local view would hand out devices earlier
    drains already granted.  Request slots are still built only for the
    claims the batch references.

    Returns None when no pod references claims, else a dict of jnp arrays
    + static caps + host-side bookkeeping:

      dev_key/dev_val  i32 [N, DD, DA]   device attribute pairs (-1 pad)
      dev_valid        bool [N, DD]
      free0            bool [N, DD]      not held by any allocated claim
      sel_key/sel_op   i32 [P, DQ, DS]   packed selector requirements
      sel_vals         i32 [P, DQ, DS, DV]
      req_count        i32 [P, DQ]       ExactCount count
      req_all          bool [P, DQ]      AllocationMode=All
      req_cl           i32 [P, DQ]       owning claim slot (-1 pad)
      req_bad          bool [P, DQ]      device class missing → never fits
      q_valid          bool [P, DQ]
      ref_cl           i32 [P, CQ]       claim slots the pod references
      claim_node0      i32 [CL]          pre-batch allocation node (-1 none)
      claim_keys       [CL] list         slot → "ns/name" (host bookkeeping)
      has_claims       bool [P] numpy    host-side routing bit
    """
    referenced = []  # claim keys in first-reference order
    ref_idx = {}
    per_pod_claims = []
    for pod in pods:
        keys = []
        for name in pod.resource_claims:
            key = f"{pod.namespace}/{name}"
            if key not in ref_idx:
                claim = claims_by_key.get(key)
                if claim is None:
                    # PreFilter already rejected the pod; don't pack a slot
                    continue
                ref_idx[key] = len(referenced)
                referenced.append(key)
            keys.append(ref_idx[key])
        per_pod_claims.append(keys)
    if not referenced:
        return None

    # -- attribute vocab over slice devices + selector keys/values ----------
    key_ids: dict = {}
    val_ids: dict = {}

    def _k(s):
        return key_ids.setdefault(s, len(key_ids))

    def _v(s):
        return val_ids.setdefault(s, len(val_ids))

    # node-grouped slices in lister order; devices flatten per node
    per_node = [[] for _ in range(n_cap)]
    for sl in slices:
        idx = name_to_idx.get(sl.node_name)
        if idx is None or idx >= n_cap:
            continue
        for dev in sl.devices:
            per_node[idx].append((sl.driver, sl.pool, dev))
    dd_need = max((len(devs) for devs in per_node), default=1) or 1
    da_need = 1
    for devs in per_node:
        for _, _, dev in devs:
            da_need = max(da_need, len(dev.attributes))

    # selector tables: class selectors first, then request selectors —
    # "all must admit" is order-independent, but keep the reference order
    def _sels(req):
        cls = device_classes.get(req.device_class_name)
        if cls is None:
            return None  # missing class: the slot can never fit
        return tuple(cls.selectors) + tuple(req.selectors)

    per_pod_slots = []  # [(cl_slot, count, is_all, sels-or-None)]
    dq_need, ds_need, dv_need, cq_need = 1, 1, 1, 1
    for pod, cl_slots in zip(pods, per_pod_claims):
        slots = []
        for cl in cl_slots:
            claim = claims_by_key[referenced[cl]]
            if claim.allocation is not None:
                continue  # allocated claims consume nothing new
            for req in claim.requests:
                sels = _sels(req)
                slots.append(
                    (
                        cl,
                        int(req.count),
                        req.allocation_mode == ALLOCATION_MODE_ALL,
                        sels,
                    )
                )
                if sels is not None:
                    ds_need = max(ds_need, len(sels))
                    for s in sels:
                        dv_need = max(dv_need, len(s.values))
        per_pod_slots.append(slots)
        dq_need = max(dq_need, len(slots))
        cq_need = max(cq_need, len(cl_slots))

    DD = bucket_cap(dd_need, 1)
    DA = bucket_cap(da_need, 1)
    DQ = bucket_cap(dq_need, 1)
    DS = bucket_cap(ds_need, 1)
    DV = bucket_cap(dv_need, 1)
    CQ = bucket_cap(cq_need, 1)
    CL = bucket_cap(len(referenced), 1)

    dev_key = np.full((n_cap, DD, DA), ABSENT, np.int32)
    dev_val = np.full((n_cap, DD, DA), ABSENT, np.int32)
    dev_valid = np.zeros((n_cap, DD), bool)
    dev_ident = {}  # (driver, pool, device-name) → (node, slot)
    for n, devs in enumerate(per_node):
        for d, (driver, pool, dev) in enumerate(devs[:DD]):
            dev_valid[n, d] = True
            dev_ident[(driver, pool, dev.name)] = (n, d)
            for a, (k, v) in enumerate(dev.attributes[:DA]):
                dev_key[n, d, a] = _k(k)
                dev_val[n, d, a] = _v(v)

    # devices held by ANY allocated claim in the cache view are taken
    free0 = dev_valid.copy()
    for claim in claims_by_key.values():
        if claim.allocation is None:
            continue
        for r in claim.allocation.results:
            pos = dev_ident.get((r.driver, r.pool, r.device))
            if pos is not None:
                free0[pos] = False

    sel_key = np.full((p_cap, DQ, DS), PAD, np.int32)
    sel_op = np.full((p_cap, DQ, DS), PAD, np.int32)
    sel_vals = np.full((p_cap, DQ, DS, DV), PAD, np.int32)
    req_count = np.zeros((p_cap, DQ), np.int32)
    req_all = np.zeros((p_cap, DQ), bool)
    req_cl = np.full((p_cap, DQ), -1, np.int32)
    req_bad = np.zeros((p_cap, DQ), bool)
    q_valid = np.zeros((p_cap, DQ), bool)
    ref_cl = np.full((p_cap, CQ), -1, np.int32)
    has_claims = np.zeros((p_cap,), bool)
    for i, (slots, cl_slots) in enumerate(
        zip(per_pod_slots, per_pod_claims)
    ):
        has_claims[i] = bool(cl_slots)
        for c, cl in enumerate(cl_slots[:CQ]):
            ref_cl[i, c] = cl
        for q, (cl, count, is_all, sels) in enumerate(slots[:DQ]):
            q_valid[i, q] = True
            req_cl[i, q] = cl
            req_count[i, q] = count
            req_all[i, q] = is_all
            if sels is None:
                req_bad[i, q] = True
                continue
            for s, sel in enumerate(sels[:DS]):
                # unseen attribute keys/values still intern: they simply
                # match no device (Exists on an unknown key is never true)
                sel_key[i, q, s] = _k(sel.attribute)
                sel_op[i, q, s] = _SEL_OPS.get(sel.operator, PAD)
                for v, val in enumerate(sel.values[:DV]):
                    sel_vals[i, q, s, v] = _v(val)

    claim_node0 = np.full((CL,), -1, np.int32)
    for cl, key in enumerate(referenced):
        claim = claims_by_key[key]
        if claim.allocation is not None and claim.allocation.node_name:
            claim_node0[cl] = name_to_idx.get(claim.allocation.node_name, n_cap)

    return dict(
        dev_key=jnp.asarray(dev_key),
        dev_val=jnp.asarray(dev_val),
        dev_valid=jnp.asarray(dev_valid),
        free0=jnp.asarray(free0),
        sel_key=jnp.asarray(sel_key),
        sel_op=jnp.asarray(sel_op),
        sel_vals=jnp.asarray(sel_vals),
        req_count=jnp.asarray(req_count),
        req_all=jnp.asarray(req_all),
        req_cl=jnp.asarray(req_cl),
        req_bad=jnp.asarray(req_bad),
        q_valid=jnp.asarray(q_valid),
        ref_cl=jnp.asarray(ref_cl),
        claim_node0=jnp.asarray(claim_node0),
        claim_keys=list(referenced),
        has_claims=has_claims,
    )


# ---------------------------------------------------------------------------
# Device kernels (pure functions under the workloads admission jit root)
# ---------------------------------------------------------------------------


def selector_match(dev_key, dev_val, dev_valid, sel_key, sel_op, sel_vals):
    """The batched device-matching pass: ``[P, DQ, N, DD]`` bool — device
    slot (n, d) satisfies EVERY selector requirement of request slot
    (p, q).  Static loops over the DS/DV/DA axes keep the live buffer at
    one [P, DQ, N, DD] plane per op (the eval_table discipline)."""
    P, DQ, DS = sel_key.shape
    DV = sel_vals.shape[3]
    N, DD, DA = dev_key.shape
    ok = jnp.ones((P, DQ, N, DD), bool)
    for s in range(DS):
        key = sel_key[:, :, s]  # [P, DQ]
        op = sel_op[:, :, s]
        present = jnp.zeros((P, DQ, N, DD), bool)
        val_at = jnp.full((P, DQ, N, DD), ABSENT, I32)
        for a in range(DA):
            k_a = dev_key[:, :, a]  # [N, DD]
            hit = (k_a[None, None] == key[:, :, None, None]) & (
                k_a >= 0
            )[None, None]
            present = present | hit
            val_at = jnp.where(hit, dev_val[:, :, a][None, None], val_at)
        in_any = jnp.zeros((P, DQ, N, DD), bool)
        for v in range(DV):
            sv = sel_vals[:, :, s, v]  # [P, DQ]
            in_any = in_any | (
                present
                & (val_at == sv[:, :, None, None])
                & (sv >= 0)[:, :, None, None]
            )
        opb = op[:, :, None, None]
        res = jnp.where(
            opb == OP_IN,
            in_any,
            jnp.where(
                opb == OP_NOT_IN,
                ~in_any,  # NotIn admits absent attributes (in_any ⊆ present)
                jnp.where(opb == OP_EXISTS, present, ~present),
            ),
        )
        res = jnp.where(opb == PAD, True, res)  # padded requirement slot
        ok = ok & res
    return ok & dev_valid[None, None]


def node_feasible(
    match_p,
    free,
    claim_node,
    req_count_p,
    req_all_p,
    req_cl_p,
    q_valid_p,
    req_bad_p,
    ref_cl_p,
):
    """Per-node DRA verdict + greedy take mask for ONE pod against the
    carried allocation state.

    match_p [DQ, N, DD]; free [N, DD]; claim_node [CL].  Returns
    (ok [N] bool, take [N, DD] bool): ok requires every referenced
    ALLOCATED claim to pin to the node and every ACTIVE request slot
    (claim still unallocated) to be satisfiable from the node's free
    devices — requests of one pod allocate greedily in slot order, so a
    device granted to slot q is unavailable to q+1 (the reference's
    ``taken`` accumulation)."""
    DQ, N, DD = match_p.shape
    CL = claim_node.shape[0]
    CQ = ref_cl_p.shape[0]
    n_ids = jnp.arange(N, dtype=I32)
    ok = jnp.ones((N,), bool)
    for c in range(CQ):
        cl = ref_cl_p[c]
        pin = jnp.where(
            cl >= 0, claim_node[jnp.clip(cl, 0, CL - 1)], -1
        )
        ok = ok & ((pin < 0) | (pin == n_ids))
    free_sim = free
    take_acc = jnp.zeros((N, DD), bool)
    for q in range(DQ):
        cl = req_cl_p[q]
        unalloc = jnp.where(
            cl >= 0, claim_node[jnp.clip(cl, 0, CL - 1)] < 0, False
        )
        active = q_valid_p[q] & unalloc
        m = match_p[q] & free_sim  # [N, DD]
        cnt = jnp.sum(m.astype(I32), axis=1)  # [N]
        total_m = jnp.sum(match_p[q].astype(I32), axis=1)
        # AllocationMode=All requires EVERY matching device allocatable
        # (structured/allocator.go:530-552) — one in use fails the node
        ok_all = (total_m > 0) & (cnt == total_m)
        ok_q = jnp.where(req_all_p[q], ok_all, cnt >= req_count_p[q])
        ok_q = ok_q & ~req_bad_p[q]
        ok = ok & jnp.where(active, ok_q, True)
        rank = jnp.cumsum(m.astype(I32), axis=1)
        take = m & jnp.where(
            req_all_p[q], True, rank <= req_count_p[q]
        )
        take = take & active
        free_sim = free_sim & ~take
        take_acc = take_acc | take
    return ok, take_acc


def dra_commit(free, claim_node, choice, take_p, ref_cl_p):
    """Commit pod p's placement into the allocation carries: the chosen
    node's take row leaves ``free`` and every referenced still-unallocated
    claim pins to the chosen node.  Dense one-hot row updates — no
    scatters.  Returns (new_free, new_claim_node)."""
    N = free.shape[0]
    CL = claim_node.shape[0]
    CQ = ref_cl_p.shape[0]
    committed = choice >= 0
    row = (jnp.arange(N, dtype=I32) == choice) & committed  # [N]
    new_free = free & ~(take_p & row[:, None])
    newly = jnp.zeros((CL,), bool)
    for c in range(CQ):
        cl = ref_cl_p[c]
        oh = jnp.arange(CL, dtype=I32) == cl  # cl<0 matches no slot
        newly = newly | (oh & (claim_node < 0))
    new_claim_node = jnp.where(
        newly & committed, choice.astype(I32), claim_node
    )
    return new_free, new_claim_node
