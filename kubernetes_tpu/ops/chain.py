"""Chained batch dispatch: gang + on-device self-append of placements.

The throughput ceiling of the batched scheduler on a remote device link is
host↔device round trips — with a naive loop every batch pays upload + sync +
dispatch + fetch latencies.  `chain_dispatch` removes the host from the
inter-batch critical path: one jit call runs the gang pipeline AND splices
the batch's own committed pods (rows + flattened affinity terms, the device
analogue of schema.append_existing_pods) into the donated DeviceCluster, so
the NEXT batch can dispatch against the returned cluster immediately —
before this batch's results have even been fetched.  The scheduling loop
becomes a software pipeline: dispatch batch k+1, then harvest batch k.

Consistency model (matches the reference's assume-until-forget,
cache.go:360-422): in-flight batches see every earlier batch's placements
as assumed pods.  Anything the device can't see — informer events, bind
failures (forget), fast-path or one-pod commits — breaks the chain via the
scheduler's epoch check, forcing a fresh host upload; decisions made by
batches already in flight used the pre-event snapshot, exactly like
reference scheduling cycles racing an informer update.

Layout note: unlike the host packer, the device append keeps each pod's
term rows at a fixed stride (P·AT rows per batch, PAD rows for empty term
slots).  Term evaluation is row-order independent and gated on
term_kind/epod_valid, so PAD gaps are inert; they only consume term-row
capacity, which the capacity check in the scheduler guards.
"""

from __future__ import annotations

import functools
from dataclasses import replace

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops import gang
from kubernetes_tpu.ops.common import DTable, DeviceBatch, DeviceCluster, I32
from kubernetes_tpu.snapshot.interner import ABSENT, PAD


def _dus(full, delta, start):
    start = jnp.asarray(start, I32)
    zero = jnp.zeros((), I32)
    starts = (start,) + (zero,) * (full.ndim - 1)
    # ktpu: allow(slice-clamp) — e_cursor/m_cursor are host ints checked
    # against the CHAINED cluster's own capacity before every dispatch
    # (scheduler._chain_dispatch: `ch["e"] + P > E or ch["m"] + P*AT > M`
    # compacts-and-grows or falls back to the direct path), so start +
    # delta rows <= len(full) holds for every splice XLA ever sees
    return jax.lax.dynamic_update_slice(full, delta, starts)


def _pad_axis(x, axis, target, fill):
    cur = x.shape[axis]
    if cur == target:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, target - cur)
    return jnp.pad(x, pads, constant_values=fill)


def caps_compatible(dc_shapes, pb) -> bool:
    """Host-side check that the batch's term tables fit the cluster's row
    width (else the append would truncate selector conjunctions)."""
    (Rc, Vc, NSc, Kc) = dc_shapes
    bt = pb.aff_table
    return (
        bt.req_key.shape[2] <= Rc
        and bt.req_vals.shape[3] <= Vc
        and pb.aff_ns_ids.shape[2] <= NSc
        and pb.label_vals.shape[1] == Kc
    )


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch, hostname_key=i32, e_cursor=i32, m_cursor=i32)
# ktpu: axes(nom_node=i32[G], nom_prio=i32[G], nom_req=i32[G,Rn])
# ktpu: axes(sp_keys=i32[Kd], sp_cdv_tab=i32[Kd,N], ip_keys=i32[Kd2])
# ktpu: axes(tid_sp=i32[P,C], rep_sp_p=i32[Tsp], rep_sp_c=i32[Tsp])
# ktpu: axes(tid_ip=i32[P,A], rep_ip_p=i32[Tip], rep_ip_u=i32[Tip], ip_cdv_tab=i32[Kd2,N])
# ktpu: axes(tid_pt=i32[P,UP], port_conf=bool[Tpt,Tpt])
# ktpu: accum(i64, i32, bool)
# ktpu: static(v_cap=16)
# ktpu: noinstantiate — donates and splices the cluster at host-checked
#   cursors; the representative instantiation would need a consistent
#   (e_cursor, m_cursor, capacity) triple the schema cannot express
@functools.partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=(
        "v_cap",
        "hard_pod_affinity_weight",
        "has_interpod",
        "has_spread",
        "has_ports",
        "has_images",
        "enabled",
        "weights",
        "d_cap",
        "d2_cap",
        "append_terms",
        "fit_strategy",
        "wave",
        "wave_ports",
    ),
)
def chain_dispatch(
    dc: DeviceCluster,
    db: DeviceBatch,
    hostname_key,
    e_cursor,
    m_cursor,
    v_cap: int,
    hard_pod_affinity_weight: int = 1,
    has_interpod: bool = True,
    has_spread: bool = True,
    has_ports: bool = True,
    has_images: bool = True,
    enabled: frozenset = F.ALL_FILTER_KERNELS,
    weights: tuple = gang.DEFAULT_WEIGHTS,
    nom_node=None,
    nom_prio=None,
    nom_req=None,
    sp_keys=None,
    sp_cdv_tab=None,
    ip_keys=None,
    d_cap: int = 8,
    append_terms: bool = True,
    fit_strategy: tuple = gang.DEFAULT_FIT_STRATEGY,
    wave: bool = False,
    tid_sp=None,
    rep_sp_p=None,
    rep_sp_c=None,
    tid_ip=None,
    rep_ip_p=None,
    rep_ip_u=None,
    ip_cdv_tab=None,
    d2_cap: int = 8,
    wave_ports: bool = False,
    tid_pt=None,
    port_conf=None,
):
    """One fused dispatch: gang schedule the batch, then append its
    committed pods into the (donated) cluster at the given cursors.

    ``append_terms=False`` skips the term-row splice for batches with no
    affinity terms — the bucketed AT axis would otherwise burn P·AT PAD
    rows of term capacity per batch.

    ``wave=True`` schedules via the speculative wave (ops/wave.py: one
    parallel speculation pass + the term-factored admission pass) instead
    of the gang scan — same decisions, a fraction of the per-step cost —
    and appends a fourth output: the [3, P] wave stats block.
    ``wave_ports`` compiles in the wave's [Tpt, N] port-occupancy carry
    for batches with in-batch host ports (tid_pt/port_conf from
    wave_tables).  NOT YET REACHABLE from the scheduler: the chained
    router refuses port batches outright because the device append below
    does not splice committed pods' port rows into used_ppk, so a LATER
    chained batch would miss their conflicts (scheduler._chain_quickcheck)
    — port batches take the direct wave instead.  The plumbing keeps the
    wave call signature uniform and is the landing slot for a future
    port-row splice.

    Returns (next_dc, stacked [2, P] (chosen, n_feas), reason_counts
    [, wave_stats])."""
    g = gang.precompute(
        dc,
        db,
        hostname_key,
        v_cap,
        hard_pod_affinity_weight,
        has_interpod=has_interpod,
        has_spread=has_spread,
        # the wave never reads the scan's pod×pod port matrix — in-batch
        # ports ride its factored [Tpt, N] occupancy carry instead
        has_ports=has_ports and not wave,
        has_images=has_images,
        enabled=enabled,
        sp_keys=sp_keys,
        sp_cdv_tab=sp_cdv_tab,
        ip_keys=ip_keys,
    )
    wave_stats = None
    if wave:
        from kubernetes_tpu.ops import wave as wave_ops

        chosen, n_feas, reason_counts, tallies, wave_stats = (
            wave_ops.wave_schedule(
                dc,
                db,
                g,
                hostname_key,
                v_cap,
                tid_sp,
                rep_sp_p,
                rep_sp_c,
                tid_ip,
                rep_ip_p,
                rep_ip_u,
                ip_cdv_tab,
                weights=weights,
                check_fit="NodeResourcesFit" in enabled,
                nom_node=nom_node,
                nom_prio=nom_prio,
                nom_req=nom_req,
                d_cap=d_cap,
                d2_cap=d2_cap,
                fit_strategy=fit_strategy,
                has_ports=wave_ports,
                tid_pt=tid_pt,
                port_conf=port_conf,
            )
        )
    else:
        chosen, n_feas, reason_counts, tallies = gang.gang_schedule(
            dc,
            db,
            g,
            v_cap,
            weights=weights,
            check_fit="NodeResourcesFit" in enabled,
            nom_node=nom_node,
            nom_prio=nom_prio,
            nom_req=nom_req,
            d_cap=d_cap,
            fit_strategy=fit_strategy,
        )
    P = db.valid.shape[0]
    committed = (chosen >= 0) & db.valid
    upd = dict(
        requested=tallies["requested"],
        nonzero_req=tallies["nonzero"],
        num_pods=tallies["num_pods"],
        epod_node=_dus(
            dc.epod_node, jnp.where(committed, chosen, ABSENT), e_cursor
        ),
        epod_ns=_dus(dc.epod_ns, db.ns_id, e_cursor),
        epod_labels=_dus(dc.epod_labels, db.labels, e_cursor),
        epod_valid=_dus(dc.epod_valid, committed, e_cursor),
        epod_deleting=_dus(dc.epod_deleting, jnp.zeros((P,), bool), e_cursor),
    )
    AT = db.aff_kind.shape[1]
    if AT and append_terms:
        real = db.aff_kind != PAD  # [P, AT]
        pod_idx = e_cursor + jnp.arange(P, dtype=I32)[:, None]
        term_pod = jnp.where(real, pod_idx, ABSENT).reshape(P * AT)
        tt = dc.term_table
        Rc = tt.req_key.shape[2]
        Vc = tt.req_vals.shape[3]
        NSc = dc.term_ns_ids.shape[1]
        bt = db.aff_table
        rk = _pad_axis(bt.req_key.reshape(P * AT, 1, -1), 2, Rc, PAD)
        ro = _pad_axis(bt.req_op.reshape(P * AT, 1, -1), 2, Rc, PAD)
        rr = _pad_axis(bt.req_rhs.reshape(P * AT, 1, -1), 2, Rc, 0)
        rv = bt.req_vals.reshape(
            P * AT, 1, bt.req_vals.shape[2], bt.req_vals.shape[3]
        )
        rv = _pad_axis(_pad_axis(rv, 3, Vc, PAD), 2, Rc, PAD)
        upd.update(
            term_pod=_dus(dc.term_pod, term_pod, m_cursor),
            term_kind=_dus(dc.term_kind, db.aff_kind.reshape(P * AT), m_cursor),
            term_topo=_dus(dc.term_topo, db.aff_topo.reshape(P * AT), m_cursor),
            term_weight=_dus(
                dc.term_weight, db.aff_weight.reshape(P * AT), m_cursor
            ),
            term_ns_all=_dus(
                dc.term_ns_all, db.aff_ns_all.reshape(P * AT), m_cursor
            ),
            term_ns_ids=_dus(
                dc.term_ns_ids,
                _pad_axis(db.aff_ns_ids.reshape(P * AT, -1), 1, NSc, PAD),
                m_cursor,
            ),
            term_table=DTable(
                req_key=_dus(tt.req_key, rk, m_cursor),
                req_op=_dus(tt.req_op, ro, m_cursor),
                req_vals=_dus(tt.req_vals, rv, m_cursor),
                req_rhs=_dus(tt.req_rhs, rr, m_cursor),
                term_valid=_dus(
                    tt.term_valid, bt.term_valid.reshape(P * AT, 1), m_cursor
                ),
            ),
        )
    next_dc = replace(dc, **upd)
    results = jnp.stack([chosen, n_feas])
    if wave:
        return next_dc, results, reason_counts, wave_stats
    return next_dc, results, reason_counts
