"""Device scheduling pipeline: masks → scores → host selection.

The batched replacement for schedulePod (reference schedule_one.go:408-456):
one fused dispatch evaluates every (pending pod, node) pair.  Selection is
argmax with first-max tie-breaking — the deterministic policy of the oracle
(selectHost's reservoir sampling, schedule_one.go:870, is reproduced host-side
when bit-compat with a recorded run is required).

``schedule_independent`` treats each pod against the same snapshot (no
intra-batch conflicts) — the building block validated against the oracle.
The sequential-equivalent gang commit lives in kubernetes_tpu.ops.gang.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops import scores as S
from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster, I32, I64
from kubernetes_tpu.snapshot.cluster import PackedCluster
from kubernetes_tpu.snapshot.interner import PAD as PAD_
from kubernetes_tpu.snapshot.schema import PodBatch, bucket_cap


# shard-rule roster: the one-shot pipeline ends in selectHost — a
# full-width argmax over N (single-chip path; the batched paths shard)
_KTPU_N_COLLECTIVES = {
    "_pipeline": "resolved(collective): final per-pod argmax/any/sum over "
    "the full node axis — per-shard partial (key, first-index) max / "
    "partial sums + one cross-shard all-reduce at the readback",
}


class PipelineResult(NamedTuple):
    chosen: jnp.ndarray  # i32 [P] node index or -1
    feasible: jnp.ndarray  # bool [P, N]
    totals: jnp.ndarray  # i64 [P, N] weighted scores (0 where infeasible)
    n_feasible: jnp.ndarray  # i32 [P]


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch, hostname_key=i32)
# ktpu: static(v_cap=16)
@functools.partial(
    jax.jit,
    static_argnames=("v_cap", "has_interpod", "has_spread", "has_images"),
)
def _pipeline(
    dc: DeviceCluster,
    db: DeviceBatch,
    hostname_key,
    v_cap: int,
    has_interpod: bool = True,
    has_spread: bool = True,
    has_images: bool = True,
):
    masks = F.all_masks(
        dc, db, v_cap, has_interpod=has_interpod, has_spread=has_spread
    )
    feasible = masks["_combined"]
    totals, _ = S.all_scores(
        dc,
        db,
        feasible,
        masks["_interpod_pre"],
        masks["_spread_pre"],
        v_cap,
        hostname_key,
        has_images=has_images,
    )
    big = jnp.iinfo(jnp.int64).min
    ranked = jnp.where(feasible, totals, big)
    chosen = jnp.argmax(ranked, axis=1).astype(I32)
    any_ok = jnp.any(feasible, axis=1)
    chosen = jnp.where(any_ok, chosen, -1)
    return PipelineResult(
        chosen=chosen,
        feasible=feasible,
        totals=jnp.where(feasible, totals, 0),
        n_feasible=jnp.sum(feasible.astype(I32), axis=1),
    )


def batch_feature_flags(pc: PackedCluster, pb: PodBatch):
    """Host-side static flags: which constraint families does this
    (snapshot, batch) pair actually use?  Lets the jit drop whole kernels
    (the reference's PreFilter-Skip, made a compile-time decision).

    Returns (has_interpod, has_spread, has_images, has_ports)."""
    has_interpod = bool(
        (pb.aff_kind != PAD_).any() or (pc.existing.term_kind != PAD_).any()
    )
    has_spread = bool((pb.tsc_topo_key != PAD_).any())
    has_images = bool((pb.img_ids >= 0).any())
    has_ports = bool(
        (pb.want_ppk != PAD_).any() or (pc.nodes.used_ppk != PAD_).any()
    )
    return has_interpod, has_spread, has_images, has_ports


def schedule_independent(
    pc: PackedCluster, pb: PodBatch
) -> PipelineResult:
    """Schedule each pod of the batch against the unmodified snapshot."""
    from kubernetes_tpu.oracle.scores import HOSTNAME_LABEL

    dc = DeviceCluster.from_host(pc.nodes, pc.existing, pc.vocab)
    db = DeviceBatch.from_host(pb)
    v_cap = bucket_cap(len(pc.vocab.label_vals))
    hostname_key = jnp.asarray(
        pc.vocab.label_keys.lookup(HOSTNAME_LABEL), I32
    )
    has_interpod, has_spread, has_images, _ = batch_feature_flags(pc, pb)
    return jax.device_get(
        _pipeline(
            dc,
            db,
            hostname_key,
            v_cap,
            has_interpod=has_interpod,
            has_spread=has_spread,
            has_images=has_images,
        )
    )
