"""Batched [K, P, N] counterfactual simulation — the planner tier's kernel.

The reference scheduler answers every "what would happen if…" question in
satellite projects (cluster-autoscaler, descheduler) that each re-implement
a slow serial simulator over Filter/Score semantics.  Here the question is
a SHAPE: ``counterfactual_run`` vmaps the workloads admission engine
(ops/coscheduling.workloads_run — speculation + the term-factored serial
admission scan) over a leading fork axis K, stepping K mutated snapshots
through ONE fused dispatch.

A fork is a set of per-fork planes over the SHARED packed snapshot:

  * ``fk_alive``      [K, N]      node exists in this fork (removals clear
                                  it; clone slots set it only in the forks
                                  that add them)
  * ``fk_unsched``    [K, N]      cordons
  * ``fk_alloc``      [K, N, Rn]  capacity (scaled per fork)
  * ``fk_req/_nz/_npods``         usage rows with the fork's evictions
                                  subtracted (host-recomputed per touched
                                  node in exact pack arithmetic)
  * ``fk_epod_valid`` [K, E]      evicted / removed-node placed pods
  * ``fk_pod_live``   [K, P]      which batch pods this fork simulates

Inside the vmap each fork materializes a per-fork ``DeviceCluster`` view:
usage/validity planes substituted, and — crucially — the label/taint rows
of non-alive slots neutralized to ABSENT/PAD so a removed (or not-added)
node is EXACTLY equivalent to a node that never existed: it drops out of
spread domain tracking, inter-pod topology membership, and min-match the
same way a repack without the node would.  Everything downstream is the
UNMODIFIED workloads engine — gang checkpoint/rollback, the factored
[T, N] carries committed through ``wave.factored_carry_update``, usage
rows through ``common.usage_carry_update`` — so fork semantics cannot
drift from the production admission path, and every fork is bit-identical
to the serial forked-snapshot oracle (oracle/planner.py) by the same
argument as the workloads tier itself (tools/paritycheck.py
``plan_vs_serial_oracle``).

Per-fork outcomes (placements, unschedulable counts, first-failure reason
sums, bin-packing density, gang admissions) pack into ONE d2h readback
through ``Scheduler._d2h`` — K what-ifs cost one host round trip where
the serial formulation costs K.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import coscheduling as cos
from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops import gang
from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster, I32, I64
from kubernetes_tpu.snapshot.interner import ABSENT, PAD
from kubernetes_tpu.snapshot.schema import LANE_CPU, LANE_MEM

# Fixed-point scale of the density readout (parts per million).
DENSITY_SCALE = 1_000_000

# shard-rule roster: the per-fork summary reductions collapse the node
# axis (admitted/unschedulable counts are P-reductions, but density and
# the per-fork workloads engine underneath contract over N).  Under a
# sharded N mesh each is a cross-shard collective; the K axis itself is
# embarrassingly parallel and would shard cleanly (ROADMAP item 1).
_KTPU_N_COLLECTIVES = {
    "counterfactual_run.one_fork": "resolved(local): per-fork "
    "snapshot-view substitution + density/utilization reductions over "
    "the alive N axis — the FORK axis is the sharded one (planner/plan.py "
    "places the fk_* planes P('pods'): each device simulates its own "
    "forks against the replicated snapshot, zero cross-fork collectives); "
    "the admission engine inside is workloads_schedule, whose own roster "
    "entries govern any in-fork N crossings",
}


def fork_cluster_view(dc: DeviceCluster, alive, unsched, alloc, req, nz, npods, epod_valid, n_valid):
    """One fork's DeviceCluster: usage/validity planes substituted and the
    static rows of non-alive slots NEUTRALIZED (labels → ABSENT, taints →
    PAD, visit rank → -1) so absence is indistinguishable from a repack
    without the node — spread/inter-pod domain tracking included."""
    gone = ~alive
    labels = jnp.where(gone[:, None], ABSENT, dc.node_labels)
    return dataclasses.replace(
        dc,
        allocatable=alloc,
        requested=req,
        nonzero_req=nz,
        num_pods=npods,
        node_valid=alive,
        unschedulable=unsched,
        node_labels=labels,
        taint_key=jnp.where(gone[:, None], PAD, dc.taint_key),
        taint_val=jnp.where(gone[:, None], PAD, dc.taint_val),
        taint_effect=jnp.where(gone[:, None], PAD, dc.taint_effect),
        visit_rank=jnp.where(gone, -1, dc.visit_rank),
        epod_valid=epod_valid,
        n_valid_nodes=n_valid,
    )


def fork_density(alive, alloc, used):
    """Mean cpu+mem utilization over alive nodes with nonzero capacity, in
    DENSITY_SCALE fixed point — the descheduler's bin-packing objective as
    one integer per fork."""
    a_cpu = alloc[:, LANE_CPU].astype(I64)
    a_mem = alloc[:, LANE_MEM].astype(I64)
    u_cpu = used[:, LANE_CPU].astype(I64)
    u_mem = used[:, LANE_MEM].astype(I64)
    counted = alive & (a_cpu > 0) & (a_mem > 0)
    util = (
        u_cpu * DENSITY_SCALE // jnp.maximum(a_cpu, 1)
        + u_mem * DENSITY_SCALE // jnp.maximum(a_mem, 1)
    ) // 2
    total = jnp.sum(jnp.where(counted, util, 0))
    n = jnp.sum(counted.astype(I32))
    return total // jnp.maximum(n.astype(I64), 1)


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch, hostname_key=i32)
# ktpu: axes(tid_sp=i32[P,C], rep_sp_p=i32[Tsp], rep_sp_c=i32[Tsp])
# ktpu: axes(tid_ip=i32[P,A], rep_ip_p=i32[Tip], rep_ip_u=i32[Tip], ip_cdv_tab=i32[Kd2,N])
# ktpu: axes(gang_id=i32[P], gang_first=bool[P], gang_last=bool[P], gang_need=i32[P])
# ktpu: axes(fk_alive=bool[KF,N], fk_unsched=bool[KF,N], fk_alloc=i32[KF,N,Rn], fk_req=i32[KF,N,Rn])
# ktpu: axes(fk_nz=i32[KF,N,2], fk_npods=i32[KF,N], fk_epod_valid=bool[KF,E], fk_nvalid=i32[KF])
# ktpu: axes(fk_pod_live=bool[KF,P])
# ktpu: axes(vol_table=DTable[P,PV2,VT], vol_valid=bool[P,PV2], vol_bad=bool[P])
# ktpu: axes(sp_keys=i32[Kd], sp_cdv_tab=i32[Kd,N], ip_keys=i32[Kd2], extra_score=i64[P,N])
# ktpu: accum(i64, i32, bool)
# ktpu: static(v_cap=16, g_cap=4)
@functools.partial(
    jax.jit,
    static_argnames=(
        "v_cap",
        "g_cap",
        "hard_pod_affinity_weight",
        "has_interpod",
        "has_spread",
        "has_images",
        "enabled",
        "weights",
        "d_cap",
        "d2_cap",
        "fit_strategy",
    ),
)
def counterfactual_run(
    dc: DeviceCluster,
    db: DeviceBatch,
    hostname_key,
    v_cap: int,
    g_cap: int,
    tid_sp,
    rep_sp_p,
    rep_sp_c,
    tid_ip,
    rep_ip_p,
    rep_ip_u,
    ip_cdv_tab,
    gang_id,
    gang_first,
    gang_last,
    gang_need,
    fk_alive,
    fk_unsched,
    fk_alloc,
    fk_req,
    fk_nz,
    fk_npods,
    fk_epod_valid,
    fk_nvalid,
    fk_pod_live,
    vol_table=None,
    vol_valid=None,
    vol_bad=None,
    hard_pod_affinity_weight: int = 1,
    has_interpod: bool = True,
    has_spread: bool = True,
    has_images: bool = True,
    enabled: frozenset = F.ALL_FILTER_KERNELS,
    weights: tuple = gang.DEFAULT_WEIGHTS,
    extra_score=None,
    sp_keys=None,
    sp_cdv_tab=None,
    ip_keys=None,
    d_cap: int = 8,
    d2_cap: int = 8,
    fit_strategy: tuple = gang.DEFAULT_FIT_STRATEGY,
):
    """K forked snapshots × one batch through one fused dispatch.

    Returns a dict of per-fork outcomes (everything leads with the KF
    axis; the caller fetches the whole dict in ONE ``Scheduler._d2h``):

      chosen       [KF, P]   post-rollback placements (-1 unschedulable)
      n_feas       [KF, P]   per-pod feasible-node counts
      reasons      [KF, ND]  summed first-failure diagnosis lanes
      admitted     [KF]      live batch pods placed
      unschedulable[KF]      live batch pods left pending
      density_ppm  [KF]      mean cpu+mem utilization after placements
      gang_admit   [KF, G2]  per-gang verdicts (-1/0/1)
      gang_landed  [KF, G2]  members placed per gang
    """

    def one_fork(alive, unsched, alloc, req, nz, npods, epv, n_valid, live):
        dc_k = fork_cluster_view(
            dc, alive, unsched, alloc, req, nz, npods, epv, n_valid
        )
        db_k = dataclasses.replace(db, valid=db.valid & live)
        chosen, n_feas, reason_counts, tallies, wl = cos.workloads_run(
            dc_k,
            db_k,
            hostname_key,
            v_cap,
            g_cap,
            tid_sp,
            rep_sp_p,
            rep_sp_c,
            tid_ip,
            rep_ip_p,
            rep_ip_u,
            ip_cdv_tab,
            gang_id,
            gang_first,
            gang_last,
            gang_need,
            vol_table=vol_table,
            vol_valid=vol_valid,
            vol_bad=vol_bad,
            hard_pod_affinity_weight=hard_pod_affinity_weight,
            has_interpod=has_interpod,
            has_spread=has_spread,
            has_images=has_images,
            enabled=enabled,
            weights=weights,
            extra_mask=None,
            nom_node=None,
            nom_prio=None,
            nom_req=None,
            sp_keys=sp_keys,
            sp_cdv_tab=sp_cdv_tab,
            ip_keys=ip_keys,
            d_cap=d_cap,
            d2_cap=d2_cap,
            extra_score=extra_score,
            fit_strategy=fit_strategy,
        )
        is_live = db.valid & live
        admitted = jnp.sum((is_live & (chosen >= 0)).astype(I32))
        unsched_n = jnp.sum((is_live & (chosen < 0)).astype(I32))
        reasons = jnp.sum(
            jnp.where(is_live[:, None], reason_counts, 0), axis=0
        )  # [ND]
        density = fork_density(alive, alloc, tallies["requested"])
        return (
            chosen,
            n_feas,
            reasons,
            admitted,
            unsched_n,
            density,
            wl["gang_admit"],
            wl["gang_landed"],
        )

    outs = jax.vmap(one_fork)(
        fk_alive,
        fk_unsched,
        fk_alloc,
        fk_req,
        fk_nz,
        fk_npods,
        fk_epod_valid,
        fk_nvalid,
        fk_pod_live,
    )
    keys = (
        "chosen",
        "n_feas",
        "reasons",
        "admitted",
        "unschedulable",
        "density_ppm",
        "gang_admit",
        "gang_landed",
    )
    # ktpu: allow(jit-boundary) — static python zip over fixed output names
    return dict(zip(keys, outs))
