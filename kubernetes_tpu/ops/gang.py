"""Sequential-equivalent gang scheduling: one lax.scan step per pod.

The reference schedules strictly one pod at a time, each cycle seeing all
previous placements through the assume-cache (schedule_one.go:65,
cache.go:360).  Batch evaluation must reproduce those semantics or decisions
diverge (SURVEY.md §7 "intra-batch conflicts").  The design:

  * everything state-INdependent is computed batched up front — all
    selector/term matching, the pod×existing quadratic terms, and the
    pod×pod batch-cross match matrices (the expensive MXU work);
  * a lax.scan walks the batch in queue order; each step is an [N]-wide
    vectorized re-evaluation of only the state-DEPENDENT pieces (resource
    tallies, spread/inter-pod counts contributed by batch placements, score
    normalization over the current feasible set) followed by argmax commit.

The scan step is built for TPU op latency: NO scatters, segment-sums or
vocab-wide gathers in the loop body.  Every state-dependent count is a fused
dense equality-contraction over small axes ([C,N,J]-shaped compare+reduce
against the assigned-node domain values), so the per-step cost is a handful
of VPU/MXU passes over row slices instead of serialized scatter ops.  The
only per-step dynamic indexing is row slices of the per-pod statics and
[C,J]-sized gathers of the assigned nodes' domain values.

The scan step mirrors, piece by piece, what the serial oracle recomputes
between pods, so gang results are identical to scheduling the pods one by
one — property-tested against the serial oracle in tests/test_gang.py.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops import scores as S
from kubernetes_tpu.ops.common import (
    DeviceBatch,
    DeviceCluster,
    I32,
    I64,
    domain_stats,
    eval_table,
    gather_at,
    ns_member,
    per_node_counts,
    usage_carry_update,
)
from kubernetes_tpu.snapshot.interner import ABSENT, PAD
from kubernetes_tpu.snapshot.schema import (
    LANE_CPU,
    LANE_MEM,
    N_FIXED_LANES,
    TERM_PREFERRED_AFFINITY,
    TERM_PREFERRED_ANTI,
    TERM_REQUIRED_AFFINITY,
    TERM_REQUIRED_ANTI,
    bucket_cap,
)

MAX = S.MAX_NODE_SCORE
_FX = S._FX

# Named-axis schema of the precompute product (analyzer shape rules).
# J — the batch-peer view of the P axis — is spelled P here: the two are
# the same size by construction and must unify in the shape algebra
# (ANALYSIS.md glossary).
_KTPU_AXES = {
    "GangStatics": {
        "static_mask": "bool[P,N]",
        "sp_hard": "bool[P,C]",
        "sp_soft": "bool[P,C]",
        "sp_dv": "i32[P,C,N]",
        "sp_te": "bool[P,C,N]",
        "sp_dom_cnt": "i32[P,C,N]",
        "sp_dom_pres": "bool[P,C,N]",
        "sp_ndom": "i32[P,C]",
        "sp_self": "bool[P,C]",
        "sp_bmatch": "bool[P,C,P]",
        "sp_is_host": "bool[P,C]",
        "sp_counting": "bool[P,C,N]",
        "sp_node_cnt": "i32[P,C,N]",
        "sp_sc_dom": "i32[P,C,N]",
        "sp_all_keys": "bool[P,N]",
        "sp_cdv": "i32[P,C,N]",
        "ip_dv": "i32[P,A,N]",
        "ip_dom_cnt": "i32[P,A,N]",
        "ip_viol_existing": "bool[P,N]",
        "ip_sym": "i64[P,N]",
        "ip_any_static": "bool[P]",
        "ip_self_all": "bool[P]",
        "ip_bmatch": "bool[P,A,P]",
        "ip_is_aff": "bool[P,A]",
        "ip_is_anti": "bool[P,A]",
        "ip_pref_w": "i64[P,A]",
        "ip_sym_w": "i64[P,A]",
        "ip_key_idx": "i32[P,A]",
        "ip_key_cols": "i32[Kd2,N]",
        "sc_taint": "i64[P,N]",
        "sc_nodeaff": "i64[P,N]",
        "sc_image": "i64[P,N]",
        "port_b": "bool[P,P]",
        "d_nodename": "bool[P,N]",
        "d_unsched": "bool[P,N]",
        "d_taints": "bool[P,N]",
        "d_nodeaff": "bool[P,N]",
        "d_ports": "bool[P,N]",
        "d_extra": "bool[P,N]",
    },
}

# shard-rule roster: the serial verdict core and its per-pod helpers are
# full-node-width by design.  Every entry carries its resolved sharding
# story (MULTICHIP.md inventory): under meshDispatch the DeviceCluster's
# node-major tensors are partitioned over the mesh's 'nodes' axis and
# GSPMD lowers each rostered op to per-shard work + the named collective;
# integer-exact arithmetic makes every reduction order-free, so the
# partitioned result is bit-identical to the single-chip kernel.
_KTPU_N_COLLECTIVES = {
    "pod_step": "resolved(collective): per-pod argmax/select over all N "
    "nodes + sampling-window rotation gathers (selectHost / nodeTree "
    "order semantics) — GSPMD all-reduces the packed (key, first-index) "
    "max across node shards; the index tiebreak in the packed key keeps "
    "first-max semantics exact, and the chosen row gather is an "
    "owning-shard broadcast",
    "spread_constraints": "resolved(collective): min-match over the "
    "tracked N axis (filtering.go:313 minMatch) — per-shard partial min "
    "+ cross-shard min-reduce",
    "interpod_constraints": "resolved(collective): per-term verdicts "
    "collapse over N-wide rows — per-shard partial any/all + cross-shard "
    "reduce",
    "_spread_raw": "resolved(collective): counted-node totals + "
    "per-domain [C,N,d_cap] compare+reduce over N — per-shard partial "
    "sums psum across node shards (integer counts, order-free)",
    "_norm_default": "resolved(collective): score normalization max over "
    "the feasible N axis — cross-shard max-reduce",
    "_norm_minmax": "resolved(collective): score normalization min+max "
    "over the feasible N axis — cross-shard min/max-reduce",
    "_norm_spread": "resolved(collective): spread normalization min+max "
    "over the valid N axis — cross-shard min/max-reduce",
    "gang_schedule.heavy_parts": "resolved(collective): peer-count einsum "
    "contractions over N (the [C,N,J]/[AT,N,J] dense compare+reduce) — "
    "per-shard partial contractions + psum of the [C,J] partials",
}


class GangStatics(NamedTuple):
    """State-independent precompute for one (cluster, batch) pair."""

    static_mask: jnp.ndarray  # bool [P, N]
    # spread filter (hard constraints, filtering.go:236-362)
    sp_hard: jnp.ndarray  # bool [P, C]
    sp_soft: jnp.ndarray  # bool [P, C]
    sp_dv: jnp.ndarray  # i32 [P, C, N]
    sp_te: jnp.ndarray  # bool [P, C, N] tracked & eligible (filter counting)
    sp_dom_cnt: jnp.ndarray  # i32 [P, C, N] per-domain counts (existing pods)
    sp_dom_pres: jnp.ndarray  # bool [P, C, N]
    sp_ndom: jnp.ndarray  # i32 [P, C]
    sp_self: jnp.ndarray  # bool [P, C]
    sp_bmatch: jnp.ndarray  # bool [P, C, J]
    # spread score (scoring.go)
    sp_is_host: jnp.ndarray  # bool [P, C]
    sp_counting: jnp.ndarray  # bool [P, C, N] all-keys ∧ eligible (score gate)
    sp_node_cnt: jnp.ndarray  # i32 [P, C, N] raw per-node matching counts
    sp_sc_dom: jnp.ndarray  # i32 [P, C, N] score-gated per-domain counts
    sp_all_keys: jnp.ndarray  # bool [P, N] node has every soft topo key
    sp_cdv: jnp.ndarray  # i32 [P, C, N] compact domain ids (<0: host/absent)
    # inter-pod
    ip_dv: jnp.ndarray  # i32 [P, AT, N]
    ip_dom_cnt: jnp.ndarray  # i32 [P, AT, N] matching existing in node's domain
    ip_viol_existing: jnp.ndarray  # bool [P, N]
    ip_sym: jnp.ndarray  # i64 [P, N] symmetric score from existing terms
    ip_any_static: jnp.ndarray  # bool [P]
    ip_self_all: jnp.ndarray  # bool [P]
    ip_bmatch: jnp.ndarray  # bool [P, AT, J]  (read [j,u,p]: p matches j's term u)
    ip_is_aff: jnp.ndarray  # bool [P, AT]
    ip_is_anti: jnp.ndarray  # bool [P, AT]
    ip_pref_w: jnp.ndarray  # i64 [P, AT]
    ip_sym_w: jnp.ndarray  # i64 [P, AT] weight of p's terms once p is placed
    ip_key_idx: jnp.ndarray  # i32 [P, AT] index into ip_key_cols (<0 absent)
    ip_key_cols: jnp.ndarray  # i32 [Kd, N] node label value per distinct key
    # static raw scores
    sc_taint: jnp.ndarray  # i64 [P, N]
    sc_nodeaff: jnp.ndarray  # i64 [P, N]
    sc_image: jnp.ndarray  # i64 [P, N]
    # batch port conflicts
    port_b: jnp.ndarray  # bool [P, J]
    # per-kernel masks kept separate for failure diagnosis (FitError reason
    # counts, framework/types.go:367-465).  All-True when a kernel is
    # disabled so it is never blamed.
    d_nodename: jnp.ndarray  # bool [P, N]
    d_unsched: jnp.ndarray  # bool [P, N]
    d_taints: jnp.ndarray  # bool [P, N]
    d_nodeaff: jnp.ndarray  # bool [P, N]
    d_ports: jnp.ndarray  # bool [P, N]
    d_extra: jnp.ndarray  # bool [P, N] (host-filter veto mask)


def batch_tables(tsc_topo, aff_topo, node_label_vals, hostname_id: int):
    """Host-side per-batch key tables for the scan's dense domain math.

    tsc_topo/aff_topo: numpy [P, C]/[P, AT] interned topology-key ids of the
    batch (PAD in empty slots); node_label_vals: numpy [N, K] interned node
    label values (the mirror's column-per-key layout).

    Returns a dict of gang_run kwargs:
      sp_keys    i32 [Kd]   distinct NON-hostname spread topology keys
      sp_cdv_tab i32 [Kd,N] per-key compact domain id per node (-1: absent)
      ip_keys    i32 [Kd2]  distinct inter-pod topology keys (incl hostname)
      d_cap      int        static bucket over the max distinct-domain count

    Compact ids let the scan count distinct-domains-with-feasible-nodes as a
    [C, N, d_cap] fused compare+reduce instead of a vocab-wide segment op
    (the TPU-hostile pattern this file avoids); hostname-topology constraints
    use node identity directly so their domain count never inflates d_cap.
    """
    import numpy as np

    lv = np.asarray(node_label_vals)
    n_cap, K = lv.shape

    def _distinct(keys_arr, exclude_host: bool):
        ids = np.unique(np.asarray(keys_arr).reshape(-1))
        out = []
        for k in ids:
            k = int(k)
            if k < 0 or k >= K:
                continue
            if exclude_host and k == hostname_id:
                continue
            out.append(k)
        return out

    sp_ids = _distinct(tsc_topo, exclude_host=True)
    d_max = 1
    rows = []
    for k in sp_ids:
        col = lv[:, k]
        cdv = np.full(n_cap, -1, np.int32)
        pos = col >= 0
        if pos.any():
            uniq, inv = np.unique(col[pos], return_inverse=True)
            cdv[pos] = inv.astype(np.int32)
            d_max = max(d_max, len(uniq))
        rows.append(cdv)
    kd = bucket_cap(max(len(sp_ids), 1), 1)
    sp_keys = np.full(kd, -1, np.int32)
    sp_keys[: len(sp_ids)] = sp_ids
    sp_cdv_tab = np.full((kd, n_cap), -1, np.int32)
    for i, r in enumerate(rows):
        sp_cdv_tab[i] = r

    ip_ids = _distinct(aff_topo, exclude_host=False)
    kd2 = bucket_cap(max(len(ip_ids), 1), 1)
    ip_keys = np.full(kd2, -1, np.int32)
    ip_keys[: len(ip_ids)] = ip_ids

    return dict(
        sp_keys=jnp.asarray(sp_keys),
        sp_cdv_tab=jnp.asarray(sp_cdv_tab),
        ip_keys=jnp.asarray(ip_keys),
        d_cap=bucket_cap(d_max, 8),
    )


def precompute(
    dc: DeviceCluster,
    db: DeviceBatch,
    hostname_key,
    v_cap: int,
    hard_pod_affinity_weight: int = 1,
    has_interpod: bool = True,
    has_spread: bool = True,
    has_ports: bool = True,
    has_images: bool = True,
    enabled: frozenset = F.ALL_FILTER_KERNELS,
    extra_mask=None,
    sp_keys=None,
    sp_cdv_tab=None,
    ip_keys=None,
) -> GangStatics:
    """When a has_* flag is False the corresponding statics are built with a
    ZERO-width constraint axis; the scan step's reductions over that axis
    vanish at compile time (the PreFilter-Skip of the gang path — shape-
    driven rather than flag-plumbed).  ``enabled`` reflects the profile's
    Filter plugin set.  sp_keys/sp_cdv_tab/ip_keys come from batch_tables();
    they are required whenever the matching has_* flag is set."""
    P = db.valid.shape[0]
    N = dc.node_valid.shape[0]
    tolerated = F._tolerated(dc, db)
    node_affinity = F.mask_node_affinity(dc, db)
    taints = F.mask_taints(dc, db, tolerated)
    base = dc.node_valid[None, :] & db.valid[:, None]
    true_pn = jnp.ones((P, N), bool)
    # host-plugin vetoes (run_host_filters) fold in as a static [P, N]
    # feasibility contribution
    d_extra = extra_mask if extra_mask is not None else true_pn
    d_nodename = F.mask_node_name(dc, db) if "NodeName" in enabled else true_pn
    d_unsched = (
        F.mask_unschedulable(dc, db) if "NodeUnschedulable" in enabled else true_pn
    )
    d_taints = taints if "TaintToleration" in enabled else true_pn
    d_nodeaff = node_affinity if "NodeAffinity" in enabled else true_pn
    d_ports = F.mask_ports(dc, db) if "NodePorts" in enabled else true_pn
    static_mask = (
        base & d_extra & d_nodename & d_unsched & d_taints & d_nodeaff & d_ports
    )
    has_interpod = has_interpod and "InterPodAffinity" in enabled
    has_spread = has_spread and "PodTopologySpread" in enabled

    # ---- spread ----
    if has_spread:
        spre = F.spread_precompute(dc, db, node_affinity, taints)
        _, C, _ = spre.dv.shape
        cnt_n = per_node_counts(spre.sel_match.astype(I32), dc.epod_node, N)
        te = spre.tracked[:, None, :] & spre.eligible
        dom_tot, dom_pres, _, n_dom = domain_stats(
            jnp.where(te, cnt_n, 0), te, spre.dv, v_cap
        )
        soft = spre.exists & ~db.tsc_hard
        topo_present = spre.dv >= 0
        all_keys = jnp.all(~soft[:, :, None] | topo_present, axis=1)  # [P, N]
        counting = all_keys[:, None, :] & spre.eligible
        sc_dom, _, _, _ = domain_stats(
            jnp.where(counting, cnt_n, 0), counting, spre.dv, v_cap
        )
        b_sel = eval_table(db.tsc_table, db.labels, dc.val_ints)  # [P, C, J]
        same_ns = db.ns_id[:, None] == db.ns_id[None, :]
        sp_bmatch = b_sel & same_ns[:, None, :] & db.valid[None, None, :]
        if sp_keys is None:
            # Missing tables would silently zero n_dom for every non-host
            # soft constraint (wrong topologyNormalizingWeight) — fail loud.
            raise ValueError(
                "precompute: sp_keys/sp_cdv_tab (from batch_tables) are "
                "required when has_spread is set"
            )
        else:
            k_eq = (db.tsc_topo[:, :, None] == sp_keys[None, None, :]) & (
                sp_keys >= 0
            )[None, None, :]  # [P, C, Kd]
            any_k = jnp.any(k_eq, axis=-1)
            ki = jnp.argmax(k_eq, axis=-1)
            sp_cdv = jnp.where(
                any_k[:, :, None], sp_cdv_tab[ki], -1
            )  # [P, C, N]
        sp = dict(
            sp_hard=spre.exists & db.tsc_hard,
            sp_soft=soft,
            sp_dv=spre.dv,
            sp_te=te,
            sp_dom_cnt=jnp.where(dom_pres, dom_tot, 0),
            sp_dom_pres=dom_pres,
            sp_ndom=n_dom,
            sp_self=spre.self_match,
            sp_bmatch=sp_bmatch,
            sp_is_host=db.tsc_topo == hostname_key,
            sp_counting=counting,
            sp_node_cnt=cnt_n,
            sp_sc_dom=jnp.where(spre.dv >= 0, sc_dom, 0),
            sp_all_keys=all_keys,
            sp_cdv=sp_cdv,
        )
    else:
        z2 = jnp.zeros((P, 0), bool)
        z3b = jnp.zeros((P, 0, N), bool)
        z3i = jnp.zeros((P, 0, N), I32)
        sp = dict(
            sp_hard=z2,
            sp_soft=z2,
            sp_dv=z3i,
            sp_te=z3b,
            sp_dom_cnt=z3i,
            sp_dom_pres=z3b,
            sp_ndom=jnp.zeros((P, 0), I32),
            sp_self=z2,
            sp_bmatch=jnp.zeros((P, 0, P), bool),
            sp_is_host=z2,
            sp_counting=z3b,
            sp_node_cnt=z3i,
            sp_sc_dom=z3i,
            sp_all_keys=jnp.ones((P, N), bool),
            sp_cdv=z3i,
        )

    # ---- inter-pod ----
    if has_interpod:
        ipre = F.interpod_precompute(dc, db)
        viol_existing = F.interpod_existing_violation(dc, ipre)
        sym = S.interpod_symmetric_score(dc, ipre, hard_pod_affinity_weight)
        ip_dom_cnt, _, _, _ = domain_stats(
            ipre.inc_cnt, jnp.zeros_like(ipre.inc_cnt, bool), ipre.inc_dv, v_cap
        )
        ip_dom_cnt = jnp.where(ipre.inc_dv >= 0, ip_dom_cnt, 0)
        is_aff = db.aff_kind == TERM_REQUIRED_AFFINITY
        is_anti = db.aff_kind == TERM_REQUIRED_ANTI
        any_static = jnp.any(is_aff[:, :, None] & ipre.inc_match, axis=(1, 2))
        self_sel = jax.vmap(
            lambda tbl, lbl: eval_table(tbl, lbl[None, :], dc.val_ints)[..., 0]
        )(db.aff_table, db.labels)
        self_ns = jax.vmap(
            lambda a, ids, ns: ns_member(a, ids, ns[None])[..., 0]
        )(db.aff_ns_all, db.aff_ns_ids, db.ns_id)
        self_all = jnp.all(~is_aff | (self_sel & self_ns), axis=1)
        b_aff_sel = eval_table(db.aff_table, db.labels, dc.val_ints)
        b_aff_ns = ns_member(db.aff_ns_all, db.aff_ns_ids, db.ns_id)
        ip_bmatch = b_aff_sel & b_aff_ns & db.valid[None, None, :]
        pref_w = jnp.where(
            db.aff_kind == TERM_PREFERRED_AFFINITY,
            db.aff_weight,
            jnp.where(db.aff_kind == TERM_PREFERRED_ANTI, -db.aff_weight, 0),
        ).astype(I64)
        sym_w = jnp.where(
            db.aff_kind == TERM_REQUIRED_AFFINITY,
            hard_pod_affinity_weight,
            pref_w.astype(I32),
        ).astype(I64)
        AT = is_aff.shape[1]
        if ip_keys is None:
            # Without the key table the batch-cross (pod vs already-committed
            # batch peer) term evaluation has nothing to factor over and
            # anti-affinity between batch members would silently vanish.
            raise ValueError(
                "precompute: ip_keys (from batch_tables) is required when "
                "has_interpod is set"
            )
        else:
            k_eq = (db.aff_topo[:, :, None] == ip_keys[None, None, :]) & (
                ip_keys >= 0
            )[None, None, :]
            any_k = jnp.any(k_eq, axis=-1)
            ip_key_idx = jnp.where(
                any_k, jnp.argmax(k_eq, axis=-1).astype(I32), -1
            )
            ip_key_cols = gather_at(dc.node_labels.T, ip_keys)  # [Kd2, N]
        ip = dict(
            ip_dv=ipre.inc_dv,
            ip_dom_cnt=ip_dom_cnt,
            ip_viol_existing=viol_existing,
            ip_sym=sym,
            ip_any_static=any_static,
            ip_self_all=self_all,
            ip_bmatch=ip_bmatch,
            ip_is_aff=is_aff,
            ip_is_anti=is_anti,
            ip_pref_w=pref_w,
            ip_sym_w=sym_w,
            ip_key_idx=ip_key_idx,
            ip_key_cols=ip_key_cols,
        )
    else:
        ip = dict(
            ip_dv=jnp.zeros((P, 0, N), I32),
            ip_dom_cnt=jnp.zeros((P, 0, N), I32),
            ip_viol_existing=jnp.zeros((P, N), bool),
            ip_sym=jnp.zeros((P, N), I64),
            ip_any_static=jnp.zeros((P,), bool),
            ip_self_all=jnp.ones((P,), bool),
            ip_bmatch=jnp.zeros((P, 0, P), bool),
            ip_is_aff=jnp.zeros((P, 0), bool),
            ip_is_anti=jnp.zeros((P, 0), bool),
            ip_pref_w=jnp.zeros((P, 0), I64),
            ip_sym_w=jnp.zeros((P, 0), I64),
            ip_key_idx=jnp.zeros((P, 0), I32),
            ip_key_cols=jnp.full((1, N), ABSENT, I32),
        )

    # ---- batch port conflicts (node_ports.go semantics, pod×pod) ----
    if has_ports:
        W = db.want_ppk.shape[1]
        port_b = jnp.zeros((P, P), bool)
        for w in range(W):
            wk = db.want_ppk[:, w][:, None]
            wi = db.want_ip[:, w][:, None]
            ww = db.want_wild[:, w][:, None]
            wv = wk != PAD
            for u in range(W):
                uk = db.want_ppk[:, u][None, :]
                ui = db.want_ip[:, u][None, :]
                uw = db.want_wild[:, u][None, :]
                uv = uk != PAD
                port_b = port_b | (
                    wv & uv & (wk == uk) & ((wi == ui) | ww | uw)
                )
    else:
        port_b = jnp.zeros((P, 0), bool)

    if has_images:
        sc_image = S.score_image_locality(dc, db)
    else:
        sc_image = jnp.zeros((P, N), I64)

    return GangStatics(
        static_mask=static_mask,
        **sp,
        **ip,
        sc_taint=S.score_taint_toleration(dc, db),
        sc_nodeaff=S.score_node_affinity(dc, db),
        sc_image=sc_image,
        port_b=port_b,
        d_nodename=d_nodename,
        d_unsched=d_unsched,
        d_taints=d_taints,
        d_nodeaff=d_nodeaff,
        d_ports=d_ports,
        d_extra=d_extra,
    )


# ---------------------------------------------------------------------------
# Per-step helpers (single pod, [N]-wide)
# ---------------------------------------------------------------------------


def _norm_default(raw, feas, reverse=False):
    raw = raw.astype(I64)
    mx = jnp.max(jnp.where(feas, raw, 0))
    out = jnp.where(mx > 0, MAX * raw // jnp.maximum(mx, 1), raw)
    if reverse:
        out = jnp.where(mx > 0, MAX - out, MAX)
    return out


def _norm_minmax(raw, feas):
    raw = raw.astype(I64)
    big = jnp.iinfo(jnp.int64).max
    mn = jnp.min(jnp.where(feas, raw, big))
    mx = jnp.max(jnp.where(feas, raw, -big))
    diff = mx - mn
    return jnp.where(diff > 0, MAX * (raw - mn) // jnp.maximum(diff, 1), 0)


def _norm_spread(raw, valid, feas):
    raw = raw.astype(I64)
    use = valid & feas
    big = jnp.iinfo(jnp.int64).max
    mn = jnp.min(jnp.where(use, raw, big))
    mx = jnp.max(jnp.where(use, raw, -big))
    any_valid = jnp.any(use)
    out = jnp.where(
        mx == 0, MAX, MAX * (mx + mn - raw) // jnp.maximum(mx, 1)
    )
    return jnp.where(use & any_valid, out, 0)


# Diagnosis rows of the [P, N_DIAG] reason-count output, in chain order.
DIAG_KERNELS = (
    "NodeUnschedulable",
    "NodeName",
    "TaintToleration",
    "NodeAffinity",
    "NodePorts",
    "HostFilters",
    "NodeResourcesFit",
    "PodTopologySpread",
    "InterPodAffinity",
)
# literal so the shape interpreter resolves [P, N_DIAG] buffers concretely
N_DIAG = 9
assert N_DIAG == len(DIAG_KERNELS)

# Positional weight order for the gang scan's static `weights` tuple — the
# single source of truth is scores.DEFAULT_SCORE_WEIGHTS.
WEIGHT_ORDER = (
    "TaintToleration",
    "NodeAffinity",
    "PodTopologySpread",
    "InterPodAffinity",
    "NodeResourcesFit",
    "NodeResourcesBalancedAllocation",
    "ImageLocality",
)
DEFAULT_WEIGHTS = tuple(S.DEFAULT_SCORE_WEIGHTS[n] for n in WEIGHT_ORDER)


def _trunc_div(num, den):
    """Go-style truncation toward zero (den > 0)."""
    return jnp.where(num >= 0, num // den, -((-num) // den))


def _broken_linear_dev(points: tuple, x):
    """BuildBrokenLinearFunction (helper/shape_score.go:40) over an [N]
    integer array; ``points`` is a static ((utilization, score), ...)."""
    out = jnp.full_like(x, points[0][1])
    for (x0, y0), (x1, y1) in zip(points, points[1:]):
        seg = y0 + _trunc_div((y1 - y0) * (x - x0), x1 - x0)
        out = jnp.where((x > x0) & (x <= x1), seg, out)
    return jnp.where(x > points[-1][0], points[-1][1], out)


# (strategy id, shape, per-lane weights) defaults — LeastAllocated with
# cpu/memory weight 1, matching resource_allocation.go defaults.
DEFAULT_FIT_STRATEGY = (0, (), (1, 1))


# ---------------------------------------------------------------------------
# Shared count→constraint algebra (one definition for every dispatch path)
#
# The scan step (heavy_parts), the wave kernels (ops/wave.py), and any other
# batch-dynamic evaluator differ ONLY in how they produce the per-pod
# BATCH-PEER count tensors; everything downstream of the counts — skew
# checks, min-match, the inter-pod violation/escape ladder, preferred-term
# scoring — is defined once here so the paths cannot drift apart.
# ---------------------------------------------------------------------------


class SpreadDyn(NamedTuple):
    """Batch-peer contributions to pod p's spread counts (all [C, N] i32)."""

    dyn_f: jnp.ndarray  # filter-side counts (bm ∧ te-at-peer ∧ same-domain)
    dyn_host: jnp.ndarray  # score-side per-node counts (bm only)
    dyn_dom: jnp.ndarray  # score-side domain counts (bm ∧ counting-at-peer)


class InterpodDyn(NamedTuple):
    """Batch-peer contributions to pod p's inter-pod state."""

    ip_dyn: jnp.ndarray  # i32 [AT, N] incoming matches per term domain
    viol_b: jnp.ndarray  # bool [N] anti-affinity of committed peers' terms
    sym_b: jnp.ndarray  # i64 [N] symmetric score from committed peers' terms
    any_dyn: jnp.ndarray  # bool [] any committed peer matches an aff term


def spread_constraints(db: DeviceBatch, g: "GangStatics", p, sd: SpreadDyn):
    """Filter verdict + score counts for pod p's spread constraints given
    the batch-peer count contributions (filtering.go:236-362 semantics on
    static existing counts + ``sd``).  Returns (m_spread [N], sp_cnt [C,N],
    c_ok [C,N]) — c_ok per constraint for failure attribution."""
    total = g.sp_dom_cnt[p] + sd.dyn_f  # [C, N]
    big32 = jnp.iinfo(jnp.int32).max
    min_match = jnp.min(jnp.where(g.sp_te[p], total, big32), axis=1)
    min_match = jnp.where(
        (db.tsc_min_domains[p] > 0) & (g.sp_ndom[p] < db.tsc_min_domains[p]),
        0,
        min_match,
    )
    skew = total + g.sp_self[p].astype(I32)[:, None] - min_match[:, None]
    c_ok = (g.sp_dv[p] >= 0) & (
        ~g.sp_dom_pres[p] | (skew <= db.tsc_max_skew[p][:, None])
    )
    m_spread = jnp.all(~g.sp_hard[p][:, None] | c_ok, axis=0)
    sp_cnt = jnp.where(
        g.sp_is_host[p][:, None],
        g.sp_node_cnt[p] + sd.dyn_host,
        g.sp_sc_dom[p] + sd.dyn_dom,
    )  # [C, N]
    return m_spread, sp_cnt, c_ok


def interpod_constraints(g: "GangStatics", p, idyn: InterpodDyn):
    """Filter verdict + raw score for pod p's inter-pod terms given the
    batch-peer contributions (interpodaffinity filtering/scoring over
    static existing counts + ``idyn``).  Returns (m_interpod [N],
    ip_raw [N], anti_viol [AT, N]) — anti_viol per term for attribution."""
    ip_total = g.ip_dom_cnt[p] + idyn.ip_dyn  # [AT, N]
    topo_present = g.ip_dv[p] >= 0
    anti_viol = g.ip_is_anti[p][:, None] & topo_present & (ip_total > 0)
    viol2 = jnp.any(anti_viol, axis=0)
    aff_ok = jnp.all(
        ~g.ip_is_aff[p][:, None] | (topo_present & (ip_total > 0)), axis=0
    )
    any_match = g.ip_any_static[p] | idyn.any_dyn
    topo_all = jnp.all(~g.ip_is_aff[p][:, None] | topo_present, axis=0)
    escape = jnp.any(g.ip_is_aff[p]) & ~any_match & g.ip_self_all[p]
    ok3 = aff_ok | (escape & topo_all)
    m_interpod = ~g.ip_viol_existing[p] & ~viol2 & ok3 & ~idyn.viol_b
    pref = jnp.sum(
        jnp.where(
            topo_present,
            ip_total.astype(I64) * g.ip_pref_w[p][:, None],
            0,
        ),
        axis=0,
    )
    ip_raw = g.ip_sym[p] + pref + idyn.sym_b.astype(I64)
    return m_interpod, ip_raw, anti_viol


def pod_step(
    dc: DeviceCluster,
    db: DeviceBatch,
    g: "GangStatics",
    p,
    state,
    hv,
    active,
    *,
    check_fit: bool,
    weights: tuple,
    d_cap: int,
    fit_strategy: tuple,
    extra_score=None,
    nom_oh=None,
    nom_prio=None,
    nom_req=None,
    sample_k=None,
    tie_key=None,
    attempt_base=None,
    commit: bool = True,
):
    """One pod's full Filter→Score→Select→commit against ``state`` — the
    single definition of the per-pod decision shared by the gang scan, the
    wave admission scan, and the wave speculation pass (ops/wave.py).  The
    state-dependent constraint tensors arrive in ``hv`` (m_portb, m_spread,
    sp_cnt, m_interpod, ip_raw); how they were produced is the caller's
    business.  ``state`` carries requested [N,Rn] / nonzero [N,2] /
    num_pods [N] / assigned [P] (+ sample_start in sampling mode).  With
    ``commit=False`` the returned state is the input untouched (speculation
    evaluates without placing).  Returns
    (new_state, (choice, n_feas, reason_counts))."""
    P, N = g.static_mask.shape
    Rn = dc.requested.shape[1]
    Rp = db.requests.shape[1]
    C = g.sp_dv.shape[1]
    true_n = jnp.ones((N,), bool)

    # ---------------- dynamic filters ----------------
    req = db.requests[p]  # [Rp]
    mask = g.static_mask[p] & hv["m_portb"]
    m_fit = true_n
    if check_fit:
        nom_cnt = 0
        nom_delta = 0
        if nom_oh is not None:
            gate = (nom_prio >= db.priority[p]).astype(I32)  # [G]
            nom_cnt = jnp.einsum("g,gn->n", gate, nom_oh)
            nom_delta = jnp.einsum(
                "gr,gn->nr", nom_req * gate[:, None], nom_oh
            )  # [N, Rn]
        fits = state["num_pods"] + nom_cnt + 1 <= dc.allowed_pods
        all_zero = jnp.all(req == 0)
        avail = dc.allocatable - state["requested"] - nom_delta  # [N, Rn]
        if Rp > Rn:
            avail = jnp.concatenate(
                [avail, jnp.zeros((N, Rp - Rn), I32)], axis=1
            )
        conflict = req[None, :] > avail  # [N, Rp]
        # extended-resource lanes only count when actually requested
        scalar_lane = jnp.arange(Rp) >= N_FIXED_LANES
        conflict = conflict & (~scalar_lane | (req > 0))[None, :]
        lane_ok = ~jnp.any(conflict, axis=1)
        m_fit = fits & (all_zero | lane_ok)
        mask = mask & m_fit

    m_portb = hv["m_portb"]
    m_spread = hv["m_spread"]
    m_interpod = hv["m_interpod"]
    mask = mask & m_spread & m_interpod
    feas = mask
    if sample_k is not None:
        # adaptive-sampling cut: keep the first sample_k feasible nodes
        # in ZONE-ROUND-ROBIN rotation order from the carried start
        # index — dc.visit_rank is the nodeTree order
        # (node_tree.go:119-143) that the reference's sampling,
        # rotation, and tie-breaks all ride
        nv = jnp.maximum(dc.n_valid_nodes, 1)
        start = state["sample_start"]
        vr = dc.visit_rank
        valid_vr = vr >= 0
        rank = jnp.where(valid_vr, (vr - start) % nv, N)
        rot = (
            jnp.zeros((N + 1,), bool)
            .at[rank]
            .set(feas & valid_vr, mode="drop")[:N]
        )
        cum = jnp.cumsum(rot.astype(I32))
        keep_rot = rot & (cum <= sample_k)
        feas = (
            jnp.concatenate([keep_rot, jnp.zeros((1,), bool)])[rank]
            & feas
        )
        total_feas = cum[N - 1]
        processed = jnp.where(
            total_feas >= sample_k,
            jnp.sum((cum < sample_k).astype(I32)) + 1,
            nv,
        )
    n_feas = jnp.sum(feas.astype(I32))

    # ---------------- failure diagnosis ----------------
    # Per-kernel rejected-node counts with first-failure attribution in
    # the reference's filter chain order (findNodesThatPassFilters
    # early-exits per node; FitError aggregates counts per reason).
    remaining = dc.node_valid & db.valid[p]
    reason_counts = []
    for comp in (
        g.d_unsched[p],
        g.d_nodename[p],
        g.d_taints[p],
        g.d_nodeaff[p],
        g.d_ports[p] & m_portb,
        g.d_extra[p],
        m_fit,
        m_spread,
        m_interpod,
    ):
        rejected = remaining & ~comp
        reason_counts.append(jnp.sum(rejected.astype(I32)))
        remaining = remaining & comp
    reason_counts = jnp.stack(reason_counts)  # [N_DIAG]

    # ---------------- scores ----------------
    # NodeResourcesFit scoring strategy on non-zero-defaulted requests
    # (resource_allocation.go:37-115): LeastAllocated (default),
    # MostAllocated, or RequestedToCapacityRatio over cpu/memory.
    strat_id, fit_shape, fit_w = fit_strategy
    nz = (
        state["nonzero"].astype(I64)
        + db.nonzero_req[p][None, :].astype(I64)
    )  # [N, 2]
    alloc2 = jnp.stack(
        [dc.allocatable[:, LANE_CPU], dc.allocatable[:, LANE_MEM]], axis=1
    ).astype(I64)
    lane_has = alloc2 > 0
    if strat_id == 1:  # MostAllocated (most_allocated.go)
        frac = jnp.where(
            nz > alloc2, 0, nz * MAX // jnp.maximum(alloc2, 1)
        )
    elif strat_id == 2:  # RequestedToCapacityRatio
        util = jnp.where(
            ~lane_has | (nz > alloc2),
            MAX,
            nz * MAX // jnp.maximum(alloc2, 1),
        )
        frac = _broken_linear_dev(fit_shape, util)
    else:  # LeastAllocated (least_allocated.go:29-60)
        frac = jnp.where(
            nz > alloc2, 0, (alloc2 - nz) * MAX // jnp.maximum(alloc2, 1)
        )
    w2 = jnp.asarray(fit_w, I64)[None, :]
    # RTCR only counts resources whose score is positive
    # (requested_to_capacity_ratio.go:46-52)
    use = lane_has & (frac > 0) if strat_id == 2 else lane_has
    wsum = jnp.sum(jnp.where(use, w2, 0), axis=1)
    total_fit = jnp.sum(jnp.where(use, frac * w2, 0), axis=1)
    if strat_id == 2:  # math.Round of the weighted mean
        least = jnp.where(
            wsum > 0,
            (2 * total_fit + wsum) // jnp.maximum(2 * wsum, 1),
            0,
        )
    else:
        least = jnp.where(
            wsum > 0, total_fit // jnp.maximum(wsum, 1), 0
        )

    # BalancedAllocation on real requests
    a0 = dc.allocatable[:, LANE_CPU].astype(I64)
    a1 = dc.allocatable[:, LANE_MEM].astype(I64)
    r0 = jnp.minimum(
        state["requested"][:, LANE_CPU].astype(I64)
        + db.requests[p, LANE_CPU].astype(I64),
        a0,
    )
    r1 = jnp.minimum(
        state["requested"][:, LANE_MEM].astype(I64)
        + db.requests[p, LANE_MEM].astype(I64),
        a1,
    )
    d = jnp.abs(r0 * a1 - r1 * a0)
    den = jnp.maximum(a0 * a1, 1)
    balanced = jnp.where(
        (a0 > 0) & (a1 > 0), MAX - (50 * d + den - 1) // den, MAX
    )

    # InterPodAffinity: static symmetric + incoming preferred (with batch
    # contributions) + symmetric from batch-assigned pods' terms —
    # carried in hv.
    ip_raw = hv["ip_raw"]

    # PodTopologySpread score: the count rows come from hv; the
    # log-weight normalization depends on the LIVE feasible set, so it
    # runs here per pod.
    if C:
        sp_raw, sp_valid = _spread_raw(
            dc, db, g, p, feas, hv["sp_cnt"], d_cap
        )
    else:
        sp_raw = jnp.zeros((N,), I64)
        sp_valid = feas

    w_taint, w_naff, w_spread, w_ip, w_fit, w_bal, w_img = weights
    total_score = jnp.zeros((N,), I64)
    if w_taint:
        total_score += w_taint * _norm_default(
            g.sc_taint[p], feas, reverse=True
        )
    if w_naff:
        total_score += w_naff * _norm_default(g.sc_nodeaff[p], feas)
    if w_spread:
        total_score += w_spread * _norm_spread(sp_raw, sp_valid, feas)
    if w_ip:
        total_score += w_ip * _norm_minmax(ip_raw, feas)
    if w_fit:
        total_score += w_fit * least
    if w_bal:
        total_score += w_bal * balanced
    if w_img:
        total_score += w_img * g.sc_image[p]
    if extra_score is not None:
        total_score += extra_score[p]

    neg = jnp.iinfo(jnp.int64).min
    if tie_key is not None:
        # seeded uniform tie-break: lexicographic (score, hash) argmax
        # — every max-score node equally likely, deterministic per
        # (seed, attempt) (selectHost reservoir analogue)
        k_p = jax.random.fold_in(tie_key, attempt_base + p)
        h = jax.random.bits(k_p, (N,), dtype=jnp.uint32).astype(I64)
        ranked = jnp.where(feas, total_score * (1 << 33) + h, neg)
        choice = jnp.argmax(ranked).astype(I32)
    elif sample_k is not None:
        # compat first-max: among max-score nodes, pick the first in
        # the zone-round-robin VISIT order (the reference appends
        # feasible nodes in nodeTree walk order, so "first max" means
        # first visited, not lowest packed slot)
        ranked = jnp.where(feas, total_score, neg)
        best = jnp.max(ranked)
        tie_rank = jnp.where(feas & (ranked == best), rank, N + 1)
        choice = jnp.argmin(tie_rank).astype(I32)
    else:
        ranked = jnp.where(feas, total_score, neg)
        choice = jnp.argmax(ranked).astype(I32)
    choice = jnp.where((n_feas > 0) & active, choice, ABSENT)
    n_feas = jnp.where(active, n_feas, 0)

    if not commit:
        return state, (choice, n_feas, reason_counts)

    # ---------------- commit ----------------
    committed = choice >= 0
    new_state = dict(
        state,
        **usage_carry_update(
            {k: state[k] for k in ("requested", "nonzero", "num_pods")},
            {
                "requested": db.requests[p][:Rn],
                "nonzero": db.nonzero_req[p],
                "num_pods": 1,
            },
            choice,
            committed,
        ),
        # inactive (pad) slots must not clobber row p's assignment.
        # p is the scan/vmap index over the batch axis — in range by
        # construction; mode="drop" (the default, spelled out) documents
        # the out-of-bounds semantics for the slice-clamp rule
        assigned=state["assigned"]
        .at[p]
        .set(jnp.where(active, choice, state["assigned"][p]), mode="drop"),
    )
    if sample_k is not None:
        # nextStartNodeIndex advances by nodes visited, per attempt
        # (schedule_one.go:625), padded batch rows included like the
        # reference's no-op cycles would be skipped: only real pods
        # advance the rotation
        new_state["sample_start"] = jnp.where(
            db.valid[p],
            (state["sample_start"] + processed) % nv,
            state["sample_start"],
        ).astype(I32)
    return new_state, (choice, n_feas, reason_counts)


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch, g=GangStatics)
# ktpu: axes(nom_node=i32[G], nom_prio=i32[G], nom_req=i32[G,Rn], extra_score=i64[P,N])
# ktpu: axes(sample_k=i32, sample_start=i32, tie_key=key, attempt_base=i32)
# ktpu: accum(i64, i32, bool)
# ktpu: static(v_cap=16)
@functools.partial(
    jax.jit,
    static_argnames=("v_cap", "weights", "check_fit", "d_cap", "fit_strategy"),
)
def gang_schedule(
    dc: DeviceCluster,
    db: DeviceBatch,
    g: GangStatics,
    v_cap: int,
    weights: tuple = DEFAULT_WEIGHTS,
    check_fit: bool = True,
    nom_node=None,
    nom_prio=None,
    nom_req=None,
    d_cap: int = 8,
    extra_score=None,
    fit_strategy: tuple = DEFAULT_FIT_STRATEGY,
    sample_k=None,
    sample_start=None,
    tie_key=None,
    attempt_base=None,
):
    """Scan the batch in order; each pod sees all prior in-batch placements.

    Bit-compat sampling mode (schedule_one.go:588-699,870-917): when
    sample_k (traced scalar) is given, each pod's Filter result is cut to
    the first sample_k feasible nodes in rotation order from the carried
    start index (nextStartNodeIndex semantics — the carry advances by the
    number of nodes "visited" per pod and is returned in the tallies dict
    under "sample_start").  When tie_key (a jax PRNG key) is given,
    max-score ties break by a per-attempt seeded hash instead of
    first-index — the deterministic, device-reproducible analogue of
    selectHost's reservoir sampling (the host oracle draws the same hash).

    extra_score (optional i64 [P, N]) carries host-plugin Score
    contributions, already normalized and weighted (run_host_scores) — the
    post-device merge point of RunScorePlugins (runtime/framework.go:1177)
    for plugins without kernels.

    nom_* (optional [G] / [G, Rn] arrays) carry NOMINATED pods — preemptors
    whose victims are still terminating.  Their resources are charged to
    their nominated node for every pod of lower-or-equal priority
    (RunFilterPluginsWithNominatedPods, runtime/framework.go:973: nominated
    pods with priority >= the evaluated pod count as present).

    Returns (chosen [P] i32 node index or -1, n_feasible [P] i32).
    """
    P, N = g.static_mask.shape
    Rn = dc.requested.shape[1]
    Rp = db.requests.shape[1]
    C = g.sp_dv.shape[1]
    AT = g.ip_dv.shape[1]
    Kd2 = g.ip_key_cols.shape[0]
    # Nominated-pod node charge matrix, built once outside the scan: per-step
    # work is a tiny [G]·[G,N] contraction instead of a segment scatter.
    if nom_node is not None:
        nom_oh = (
            nom_node[:, None] == jnp.arange(N, dtype=I32)[None, :]
        ).astype(I32)  # [G, N]

    init = dict(
        requested=dc.requested,
        nonzero=dc.nonzero_req,
        num_pods=dc.num_pods,
        assigned=jnp.full((P,), ABSENT, I32),
        # Per-pod outputs ride CARRY buffers written at the pod's own
        # slot instead of scan-stacked ys: jaxlib 0.4.37's SPMD
        # partitioner mis-clamps the ys-stacking dynamic_update_slice
        # (s64 scan counter vs its own s32 shard arithmetic) whenever
        # propagation shards the stacking axis — carry scatter writes at
        # an i32 index partition correctly (`assigned` always has).
        out_choice=jnp.full((P,), ABSENT, I32),
        out_nfeas=jnp.zeros((P,), I64),
        out_rc=jnp.zeros((P, N_DIAG), I64),
    )
    if sample_k is not None:
        init["sample_start"] = jnp.asarray(sample_start, I32)

    true_n = jnp.ones((N,), bool)

    def peer_view(assigned):
        """Shared per-state tensors describing already-placed batch peers."""
        assigned_valid = assigned >= 0  # [J]
        a_clip = jnp.clip(assigned, 0, N - 1)
        # [J, N] node-identity of each assigned batch peer — shared by the
        # port-conflict check and the hostname-topology spread counts.
        eqJ = (a_clip[:, None] == jnp.arange(N, dtype=I32)[None, :]) & (
            assigned_valid[:, None]
        )
        return assigned_valid, eqJ

    def heavy_parts(p, assigned_valid, eqJ):
        """State-dependent tensors whose value cannot change while no
        INTERACTING peer commits: spread/inter-pod masks, count rows, and
        port conflicts.  The per-pod scan calls this every step."""
        av = assigned_valid[None, :]
        m_portb = true_n
        if g.port_b.shape[1]:
            port_conf = jnp.any(g.port_b[p][:, None] & eqJ, axis=0)
            m_portb = ~port_conf

        if C:
            dv = g.sp_dv[p]  # [C, N]
            # value-at-assigned-node via one-hot matmul instead of a gather
            # (TPU gathers serialize; einsum rides the MXU).  Invalid peers
            # produce 0 rows — every consumer is gated on av/bm.
            eqJ_i = eqJ.astype(I32)
            dv_at = jnp.einsum("cn,jn->cj", dv, eqJ_i)  # [C, J]
            te_at = jnp.einsum("cn,jn->cj", g.sp_te[p].astype(I32), eqJ_i) > 0
            bm = g.sp_bmatch[p] & av  # [C, J]
            # Same-domain indicator of each node vs each assigned peer's
            # node, as a fused dense compare (dv space): [C, N, J].
            eq_dom = (
                (dv[:, :, None] >= 0)
                & (dv_at[:, None, :] >= 0)
                & (dv[:, :, None] == dv_at[:, None, :])
            )
            dyn_f = jnp.sum(
                (eq_dom & (bm & te_at)[:, None, :]).astype(I32), axis=2
            )  # [C, N]
            # score-side counts: _spread_cnt
            dyn_host = jnp.einsum("cj,jn->cn", bm.astype(I32), eqJ_i)
            cg_at = (
                jnp.einsum(
                    "cn,jn->cj", g.sp_counting[p].astype(I32), eqJ_i
                )
                > 0
            )
            dyn_dom = jnp.sum(
                (eq_dom & (bm & cg_at)[:, None, :]).astype(I32), axis=2
            )
            m_spread, sp_cnt, _ = spread_constraints(
                db, g, p, SpreadDyn(dyn_f, dyn_host, dyn_dom)
            )
        else:
            m_spread = true_n
            sp_cnt = jnp.zeros((C, N), I32)

        if AT:
            ip_dv = g.ip_dv[p]  # [AT, N]
            ip_dv_at = jnp.einsum("tn,jn->tj", ip_dv, eqJ.astype(I32))
            ip_eq = (
                (ip_dv[:, :, None] >= 0)
                & (ip_dv_at[:, None, :] >= 0)
                & (ip_dv[:, :, None] == ip_dv_at[:, None, :])
            )  # [AT, N, J]
            ip_bm = g.ip_bmatch[p] & av  # [AT, J]
            ip_dyn = jnp.sum((ip_eq & ip_bm[:, None, :]).astype(I32), axis=2)
            any_dyn = jnp.any(g.ip_is_aff[p][:, None] & ip_bm)

            # Batch-assigned peers' terms vs p, factored by distinct topology
            # key so the contraction reads [Kd2, N] columns instead of the
            # full [P, AT, N] domain tensor each step.  dv_ju[j, u] = the
            # topology value at j's assigned node for j's term u.
            m_jp = g.ip_bmatch[:, :, p] & assigned_valid[:, None]  # [J, AT]
            cols_at_a = jnp.einsum(
                "kn,jn->kj", g.ip_key_cols, eqJ.astype(I32)
            )  # [Kd2, J]
            ki = g.ip_key_idx  # [J, AT]
            ki_clip = jnp.clip(ki, 0, Kd2 - 1)
            ki_oh = (
                ki_clip[:, :, None] == jnp.arange(Kd2, dtype=I32)[None, None, :]
            ).astype(I32)  # [J, AT, Kd2]
            dv_ju = jnp.einsum("jk,juk->ju", cols_at_a.T, ki_oh)  # [J, AT]
            term_live = m_jp & (ki >= 0) & (dv_ju >= 0)
            g_anti = (term_live & g.ip_is_anti).reshape(-1)  # [J·AT]
            w_sym = jnp.where(term_live, g.ip_sym_w, 0).astype(I32).reshape(-1)
            ki_f = ki_clip.reshape(-1)
            live_f = (ki >= 0).reshape(-1)
            dvf = dv_ju.reshape(-1)
            viol_b = jnp.zeros((N,), bool)
            sym_b = jnp.zeros((N,), I32)
            for k in range(Kd2):
                in_k = live_f & (ki_f == k)
                eqk = (dvf[:, None] == g.ip_key_cols[k][None, :]) & (
                    g.ip_key_cols[k] >= 0
                )[None, :]  # [J·AT, N]
                viol_b = viol_b | jnp.any(
                    (g_anti & in_k)[:, None] & eqk, axis=0
                )
                sym_b = sym_b + jnp.einsum(
                    "t,tn->n",
                    jnp.where(in_k, w_sym, 0),
                    eqk.astype(I32),
                )
            m_interpod, ip_raw, _ = interpod_constraints(
                g, p, InterpodDyn(ip_dyn, viol_b, sym_b.astype(I64), any_dyn)
            )
        else:
            m_interpod = true_n
            ip_raw = g.ip_sym[p]
        return dict(
            m_portb=m_portb,
            m_spread=m_spread,
            sp_cnt=sp_cnt,
            m_interpod=m_interpod,
            ip_raw=ip_raw,
        )

    def step(state, p):
        assigned_valid, eqJ = peer_view(state["assigned"])
        hv = heavy_parts(p, assigned_valid, eqJ)
        new_state, (choice, n_feas, reason_counts) = cheap_body(
            state, p, hv, jnp.asarray(True)
        )
        # p in range by construction; mode="drop" for the clamp rule
        new_state["out_choice"] = (
            state["out_choice"].at[p].set(choice, mode="drop")
        )
        new_state["out_nfeas"] = (
            state["out_nfeas"].at[p].set(n_feas, mode="drop")
        )
        new_state["out_rc"] = (
            state["out_rc"].at[p].set(reason_counts, mode="drop")
        )
        return new_state, None

    def cheap_body(state, p, hv, active):
        return pod_step(
            dc,
            db,
            g,
            p,
            state,
            hv,
            active,
            check_fit=check_fit,
            weights=weights,
            d_cap=d_cap,
            fit_strategy=fit_strategy,
            extra_score=extra_score,
            nom_oh=nom_oh if nom_node is not None else None,
            nom_prio=nom_prio,
            nom_req=nom_req,
            sample_k=sample_k,
            tie_key=tie_key,
            attempt_base=attempt_base,
        )

    state, _ = jax.lax.scan(step, init, jnp.arange(P, dtype=I32))
    chosen = state["out_choice"]
    n_feas = state["out_nfeas"]
    reason_counts = state["out_rc"]
    # Final node tallies let the caller chain batches without a host round
    # trip: feed them back as the next DeviceCluster's requested/nonzero/
    # num_pods (the across-batch analogue of the assume cache).
    tallies = {
        "requested": state["requested"],
        "nonzero": state["nonzero"],
        "num_pods": state["num_pods"],
    }
    if sample_k is not None:
        tallies["sample_start"] = state["sample_start"]
    return chosen, n_feas, reason_counts, tallies


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch, hostname_key=i32, extra_mask=bool[P,N])
# ktpu: axes(nom_node=i32[G], nom_prio=i32[G], nom_req=i32[G,Rn], extra_score=i64[P,N])
# ktpu: axes(sp_keys=i32[Kd], sp_cdv_tab=i32[Kd,N], ip_keys=i32[Kd2])
# ktpu: axes(sample_k=i32, sample_start=i32, tie_key=key, attempt_base=i32)
# ktpu: accum(i64, i32, bool)
# ktpu: static(v_cap=16)
@functools.partial(
    jax.jit,
    static_argnames=(
        "v_cap",
        "hard_pod_affinity_weight",
        "has_interpod",
        "has_spread",
        "has_ports",
        "has_images",
        "enabled",
        "weights",
        "d_cap",
        "fit_strategy",
    ),
)
def gang_run(
    dc: DeviceCluster,
    db: DeviceBatch,
    hostname_key,
    v_cap: int,
    hard_pod_affinity_weight: int = 1,
    has_interpod: bool = True,
    has_spread: bool = True,
    has_ports: bool = True,
    has_images: bool = True,
    enabled: frozenset = F.ALL_FILTER_KERNELS,
    weights: tuple = DEFAULT_WEIGHTS,
    extra_mask=None,
    nom_node=None,
    nom_prio=None,
    nom_req=None,
    sp_keys=None,
    sp_cdv_tab=None,
    ip_keys=None,
    d_cap: int = 8,
    extra_score=None,
    fit_strategy: tuple = DEFAULT_FIT_STRATEGY,
    sample_k=None,
    sample_start=None,
    tie_key=None,
    attempt_base=None,
):
    """Fused precompute + scan: ONE device dispatch per batch."""
    g = precompute(
        dc,
        db,
        hostname_key,
        v_cap,
        hard_pod_affinity_weight,
        has_interpod=has_interpod,
        has_spread=has_spread,
        has_ports=has_ports,
        has_images=has_images,
        enabled=enabled,
        extra_mask=extra_mask,
        sp_keys=sp_keys,
        sp_cdv_tab=sp_cdv_tab,
        ip_keys=ip_keys,
    )
    return gang_schedule(
        dc,
        db,
        g,
        v_cap,
        weights=weights,
        check_fit="NodeResourcesFit" in enabled,
        nom_node=nom_node,
        nom_prio=nom_prio,
        nom_req=nom_req,
        d_cap=d_cap,
        extra_score=extra_score,
        fit_strategy=fit_strategy,
        sample_k=sample_k,
        sample_start=sample_start,
        tie_key=tie_key,
        attempt_base=attempt_base,
    )


def _spread_raw(dc, db, g, p, feas, cnt, d_cap):
    """ScheduleAnyway scoring for one pod (podtopologyspread/scoring.go,
    fixed-point log weights), given the per-constraint count rows ``cnt``
    [C, N] (static existing-pod counts + batch contributions — computed in
    heavy_parts; hostname constraints count per assigned node directly, the
    ungated path, domain constraints are gated by the score-counting mask
    at the assigned node).

    The per-domain machinery of the original formulation is replaced by
    dense equivalents:
      * domain presence (``pair_pres``) is dropped outright — a node whose
        score is ever consumed is ``counted`` (feasible ∧ has all soft topo
        keys), and a counted node's own domain trivially contains it, so the
        where(pair_pres, ., 0) gate was a no-op at every consumed node;
      * the count of domains containing counted nodes uses the host-built
        compact domain ids (g.sp_cdv, batch_tables()) as a [C, N, d_cap]
        compare+reduce.
    This half stays per pod in the scan: ``counted`` (and so the
    topologyNormalizingWeight) depends on the LIVE feasible set.
    """
    soft = g.sp_soft[p]  # [C]
    has_soft = jnp.any(soft)

    ignored = feas & ~g.sp_all_keys[p]
    counted = feas & g.sp_all_keys[p]  # filtered, non-ignored
    n_counted = jnp.sum(counted.astype(I32))

    cdv = g.sp_cdv[p]  # [C, N]
    dom_hit = (cdv[:, :, None] == jnp.arange(d_cap, dtype=I32)[None, None, :]) & (
        counted[None, :, None]
    )  # [C, N, D]
    n_dom = jnp.sum(jnp.any(dom_hit, axis=1).astype(I32), axis=1)  # [C]
    size = jnp.where(g.sp_is_host[p], n_counted, n_dom)  # [C]
    w_fx = dc.log_tab[jnp.clip(size, 0, dc.log_tab.shape[0] - 1)]  # [C] i64

    contrib_fx = cnt.astype(I64) * w_fx[:, None] + (
        (db.tsc_max_skew[p].astype(I64) - 1)[:, None] << _FX
    )
    total_fx = jnp.sum(jnp.where(soft[:, None], contrib_fx, 0), axis=0)  # [N]
    k = total_fx >> _FX
    frac = total_fx & ((1 << _FX) - 1)
    half = 1 << (_FX - 1)
    up = (frac > half) | ((frac == half) & ((k & 1) == 1))
    raw = k + up.astype(I64)
    raw = jnp.where(has_soft, raw, 0)
    valid = jnp.where(has_soft, ~ignored, feas)
    return raw, valid
