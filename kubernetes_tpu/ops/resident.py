"""Device-resident drain loop: the signature fast path as a multi-round
speculation/admission fixed point (ROADMAP item 1).

sig_scan (ops/fastpath.py) already keeps the node-usage state in HBM, but
replays the sequential greedy one pod per ``lax.scan`` step — O(N) score
work and one argmax per pod.  This module schedules the SAME runs with the
wave's speculation+admission structure (ops/wave.py): each ROUND freezes
the usage state, speculates a whole window of pods in parallel against it,
verifies exactly which prefix of the window the serial recurrence would
have placed identically, commits that agreement prefix with vectorized
scatters, and re-speculates the conflict tail from the updated state.  Per
round the heavy work is one [S, N] score pass + one sort; the per-pod work
collapses to O(S) vector arithmetic — no per-pod argmax, no per-pod scan
step.

Bit-identity argument (decisions == the serial one-pod-at-a-time greedy,
shared verdict code with sig_scan via make_sig_step):

* Scores and feasibility are packed into per-(signature, node) KEYS
  ``key = total_score * n_cap + (n_cap - 1 - n)`` (-1 when infeasible), so
  "max key" == "first-max score" exactly (smaller node index wins ties)
  and keys are unique per node.
* The round speculates a shared consumption walk: nodes sorted by the
  window-head signature's keys; the i-th *scheduled* pod of the window
  takes the i-th node of the walk.  A pod's speculated placement equals
  its serial argmax iff
    (1) its own position IS its signature's best untouched node:
        ``skey[s_i, pos_i] == suffix_max(skey[s_i])[pos_i]``, and
    (2) no already-committed node beats it after its commit:
        ``skey[s_i, pos_i] > max_{j<i committed} upd_key[s_i](n_j)``.
  Within a round each walk position is consumed at most once, so a
  committed node's post-commit key is exact (frozen state + exactly one
  commit), and both conditions are evaluated with vectorized cumulative
  maxima — condition (2) is the same term-factored delta idea the wave's
  admission pass uses, with per-node usage rows as the only "terms".
* Signatures with NO feasible node at round start ("dead") stay dead for
  the whole round (usage only grows), so their pods are admitted as
  unschedulable without consuming walk positions.
* The first window pod always agrees (the walk starts at ITS signature's
  argmax and nothing is committed yet), so every round makes progress and
  the fixed point terminates.  A round cap bounds adversarial workloads;
  any unresolved tail falls back — inside the same dispatch — to the
  sig_scan step function (make_sig_step), i.e. the exact serial replay.

One dispatch per RUN (thousands of pods), one d2h readback of the packed
placements per run; the usage state is donated and never leaves HBM
between runs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops.common import usage_carry_update
from kubernetes_tpu.ops.fastpath import make_sig_step
from kubernetes_tpu.snapshot.schema import LANE_CPU, LANE_MEM, N_FIXED_LANES

MAX = 100  # MaxNodeScore
I32 = jnp.int32
I64 = jnp.int64

# shard-rule roster: the resident fixed point is the serial core made
# wide — per-round it sorts/gathers the node axis wholesale and commits
# with scatters into the N-resident usage rows.  Single-chip by design;
# sharding N means replacing exactly these with collectives.
_KTPU_N_COLLECTIVES = {
    "_upd_keys": "resolved(replicated): gathers committed nodes' "
    "usage/alloc rows ([W]-indexed reads of N-leading state) — the "
    "resident lineage's usage state is materialized whole-array per "
    "dispatch from the host committer (not node-sharded), so the reads "
    "are shard-local by layout; node-sharded residency across batches is "
    "ROADMAP item 1's open remainder",
    "resident_run.round_body": "resolved(replicated): walk-order "
    "argsort/gather over N + scatter-add commits into the N-resident "
    "usage rows — same whole-array lineage as _upd_keys: every replica "
    "applies identical rank-1 commits, so the round needs no collective "
    "(the [S,N] speculation keys partition over the pods axis instead)",
    "usage_checksum": "resolved(replicated): full reductions over the "
    "N-leading resident usage rows (the ISSUE 15 epoch guard's integrity "
    "probe) — the lineage is whole-array per dispatch (not node-sharded, "
    "see _upd_keys), so every replica computes the identical scalar and "
    "no collective is inserted",
}
NEG = jnp.iinfo(jnp.int64).min // 4  # "no committed node yet" threshold
UNRESOLVED = -2  # choice sentinel: pod not reached before the round cap


def _score_keys(feas, a0, a1, c0, c1, r0, r1, img, node_ids, n_total,
                w_fit: int, w_bal: int, w_img: int):
    """Packed (score, first-max index) keys from broadcast-ready operands
    — THE integer score formulas of make_sig_step/score_int, in one place
    for both key builders.  ``a0/a1`` are cpu/mem allocatable, ``c0/c1``
    nonzero-request sums (node + signature), ``r0/r1`` UNCLAMPED
    used+request cpu/mem, ``img`` the gathered ImageLocality term, and
    ``node_ids`` the i64 node index per element; every operand broadcasts
    against ``feas``'s shape.  Returns keys with -1 where infeasible."""
    total = jnp.zeros(feas.shape, I64)
    h0 = a0 > 0
    h1 = a1 > 0
    if w_fit:
        fit_w = h0.astype(I64) + h1.astype(I64)
        f0 = jnp.where(c0 > a0, 0, (a0 - c0) * MAX // jnp.maximum(a0, 1))
        f1 = jnp.where(c1 > a1, 0, (a1 - c1) * MAX // jnp.maximum(a1, 1))
        least = jnp.where(
            fit_w > 0,
            (jnp.where(h0, f0, 0) + jnp.where(h1, f1, 0))
            // jnp.maximum(fit_w, 1),
            0,
        )
        total = total + w_fit * least
    if w_bal:
        den = jnp.maximum(a0 * a1, 1)
        rr0 = jnp.minimum(r0, a0)
        rr1 = jnp.minimum(r1, a1)
        d = jnp.abs(rr0 * a1 - rr1 * a0)
        bal = jnp.where(h0 & h1, MAX - (50 * d + den - 1) // den, MAX)
        total = total + w_bal * bal
    if w_img:
        total = total + w_img * img
    key = total * n_total + (n_total - 1 - node_ids)
    return jnp.where(feas, key, -1)


def _sig_node_keys(
    sig_req,  # i64 [S, R]
    sig_nz,  # i64 [S, 2]
    sig_allzero,  # bool [S]
    sig_ok,  # bool [S, N]
    sig_img,  # i64 [S, N]
    alloc,  # i64 [N, R]
    allowed,  # i32 [N]
    used,  # i64 [N, R]
    nz0,  # i64 [N]
    nz1,  # i64 [N]
    num_pods,  # i32 [N]
    w_fit: int,
    w_bal: int,
    w_img: int,
    check_fit: bool,
):
    """[S, N] packed (score, first-max index) keys under the CURRENT usage
    state; -1 where infeasible.  The vectorized twin of make_sig_step's
    per-pod score/feasibility math — same integer formulas (_score_keys),
    evaluated for every signature at once."""
    R = alloc.shape[1]
    N = alloc.shape[0]
    a0 = alloc[:, LANE_CPU]  # [N]
    a1 = alloc[:, LANE_MEM]
    if check_fit:
        fits_count = (num_pods + 1 <= allowed)[None, :]  # [1, N]
        avail = alloc - used  # [N, R]
        ext_lane = jnp.arange(R) >= N_FIXED_LANES
        lane_ok = jnp.where(
            (ext_lane[None, :] & (sig_req == 0))[:, None, :],
            True,
            sig_req[:, None, :] <= avail[None, :, :],
        )  # [S, N, R]
        fits_lanes = jnp.where(
            sig_allzero[:, None], True, jnp.all(lane_ok, axis=2)
        )
        feas = sig_ok & fits_count & fits_lanes
    else:
        feas = sig_ok
    return _score_keys(
        feas,
        a0[None, :],
        a1[None, :],
        nz0[None, :] + sig_nz[:, 0, None],
        nz1[None, :] + sig_nz[:, 1, None],
        used[:, LANE_CPU][None, :] + sig_req[:, LANE_CPU, None],
        used[:, LANE_MEM][None, :] + sig_req[:, LANE_MEM, None],
        sig_img,
        jnp.arange(N, dtype=I64)[None, :],
        N,
        w_fit, w_bal, w_img,
    )


def _upd_keys(
    cnode,  # i32 [W] node each window slot would commit
    csig,  # i32 [W] committing signature per slot
    sig_req,
    sig_nz,
    sig_allzero,
    sig_ok,
    sig_img,
    alloc,
    allowed,
    used,
    nz0,
    nz1,
    num_pods,
    w_fit: int,
    w_bal: int,
    w_img: int,
    check_fit: bool,
):
    """[W, S] keys of each slot's committed node under EVERY signature
    AFTER that slot's commit — the rank-1 delta the admission pass ranks
    committed nodes by.  Exact because a walk position commits at most
    once per round.  Same formulas as _sig_node_keys (_score_keys) on
    gathered rows."""
    R = alloc.shape[1]
    N = alloc.shape[0]
    a0 = alloc[cnode, LANE_CPU]  # [W]
    a1 = alloc[cnode, LANE_MEM]
    n_used = used[cnode] + sig_req[csig]  # [W, R]
    n_nz0 = nz0[cnode] + sig_nz[csig, 0]  # [W]
    n_nz1 = nz1[cnode] + sig_nz[csig, 1]
    n_np = num_pods[cnode] + 1
    if check_fit:
        fits_count = (n_np + 1 <= allowed[cnode])[:, None]  # [W, 1]
        avail = alloc[cnode][:, None, :] - n_used[:, None, :]  # [W, 1, R]
        ext_lane = jnp.arange(R) >= N_FIXED_LANES
        lane_ok = jnp.where(
            (ext_lane[None, :] & (sig_req == 0))[None, :, :],
            True,
            sig_req[None, :, :] <= avail,
        )  # [W, S, R]
        fits_lanes = jnp.where(
            sig_allzero[None, :], True, jnp.all(lane_ok, axis=2)
        )
        feas = sig_ok[:, cnode].T & fits_count & fits_lanes  # [W, S]
    else:
        feas = sig_ok[:, cnode].T
    return _score_keys(
        feas,
        a0[:, None],
        a1[:, None],
        n_nz0[:, None] + sig_nz[None, :, 0],
        n_nz1[:, None] + sig_nz[None, :, 1],
        n_used[:, LANE_CPU][:, None] + sig_req[None, :, LANE_CPU],
        n_used[:, LANE_MEM][:, None] + sig_req[None, :, LANE_MEM],
        sig_img[:, cnode].T,
        cnode.astype(I64)[:, None],
        N,
        w_fit, w_bal, w_img,
    )


# adaptive-stop tuning: every GRACE rounds the loop must have admitted at
# least GRACE*MIN_YIELD pods since the last checkpoint, or it stops and
# hands the tail over (serial tail or host committer).  MIN_YIELD is the
# approximate break-even between one round's [S, N] prep and the host
# committer's per-pod cost.
STOP_GRACE = 4
MIN_YIELD = 64


# ktpu: axes(sig_ids=i32[P], sig_req=i64[S,Rn], sig_nz=i64[S,2], sig_allzero=bool[S])
# ktpu: axes(sig_ok=bool[S,N], sig_img=i64[S,N], alloc=i64[N,Rn], allowed=i32[N])
# ktpu: axes(used=i64[N,Rn], nz0=i64[N], nz1=i64[N], num_pods=i32[N])
# ktpu: accum(i64, i32, bool)
# ktpu: static(w_fit=1, w_bal=1, w_img=1, check_fit=True, window=8, serial_tail=True)
@functools.partial(
    jax.jit,
    static_argnames=(
        "w_fit", "w_bal", "w_img", "check_fit", "window", "serial_tail"
    ),
    donate_argnames=("used", "nz0", "nz1", "num_pods"),
)
def resident_run(
    sig_ids,  # i32 [P] per-pod signature id in queue order, -1 pads (suffix)
    sig_req,  # i64 [S, R]
    sig_nz,  # i64 [S, 2]
    sig_allzero,  # bool [S]
    sig_ok,  # bool [S, N]
    sig_img,  # i64 [S, N]
    alloc,  # i64 [N, R]
    allowed,  # i32 [N]
    used,  # i64 [N, R] — donated, resident across runs
    nz0,  # i64 [N]     — donated
    nz1,  # i64 [N]     — donated
    num_pods,  # i32 [N] — donated
    w_fit: int,
    w_bal: int,
    w_img: int,
    check_fit: bool,
    window: int,
    serial_tail: bool = True,
):
    """One dispatch = one resident RUN: the ``sig_ids`` feed is placed on
    device through the speculation/admission fixed point.  With
    ``serial_tail`` (the fully-device-resident mode), anything the round
    cap or adaptive stop leaves unresolved is finished in-kernel by the
    exact sig_scan replay; without it, unresolved pods come back as
    UNRESOLVED (-2) and the caller finishes them on the host committer —
    the right trade when serial device steps are slower than host heaps.

    Returns (choices i32 [P], new_state tuple, stats i64 [3]) where stats
    is (rounds, pods_resolved_by_fixed_point, tail_left 0/1).  With
    serial_tail the returned STATE always covers the whole run; without
    it the state covers exactly the resolved prefix.
    """
    P = sig_ids.shape[0]
    N = alloc.shape[0]
    W = min(window, N)
    # pads are a suffix by construction (host packs live pods first)
    p_live = jnp.sum((sig_ids >= 0).astype(I32))
    ids_pad = jnp.concatenate([sig_ids, jnp.full((W,), -1, I32)])
    iota_w = jnp.arange(W, dtype=I32)
    # round cap: the fixed point admits >=1 pod per round, but an
    # adversarial interleaving could degenerate to exactly that — cap the
    # rounds at a small multiple of the best case and let the tail
    # finish, so the worst case is one tail replay + bounded overhead.
    r_cap = 64 + 8 * (P // W + 1)
    # stop quota scaled by the window: on small clusters (W < MIN_YIELD)
    # even perfect full-window rounds cannot admit MIN_YIELD pods — and
    # their per-round [S, N] prep is proportionally cheaper, so the
    # break-even admission rate is lower too
    min_yield = min(MIN_YIELD, max(1, W // 4))

    score_kw = dict(
        w_fit=w_fit, w_bal=w_bal, w_img=w_img, check_fit=check_fit
    )

    def round_body(carry):
        q, used, nz0, nz1, num_pods, choices, rounds, q_ckpt, stop = carry
        keys = _sig_node_keys(
            sig_req, sig_nz, sig_allzero, sig_ok, sig_img,
            alloc, allowed, used, nz0, nz1, num_pods, **score_kw
        )  # [S, N]
        win = jax.lax.dynamic_slice(ids_pad, (q,), (W,))  # [W]
        live = win >= 0
        sig_w = jnp.maximum(win, 0)
        # shared consumption walk: nodes in the window head's preference
        # order (keys are unique, so argsort is deterministic)
        order = jnp.argsort(-keys[sig_w[0]]).astype(I32)  # [N]
        skey = keys[:, order]  # [S, N] every sig's keys along the walk
        sufmax = jnp.flip(
            jax.lax.cummax(jnp.flip(skey, axis=1), axis=1), axis=1
        )  # [S, N] best untouched key at-or-after each position
        dead = sufmax[:, 0] < 0  # [S] no feasible node at all this round
        dead_w = dead[sig_w] & live
        sched_spec = live & ~dead_w  # speculated to consume a position
        si = sched_spec.astype(I32)
        pos = jnp.minimum(jnp.cumsum(si) - si, N - 1)  # exclusive count
        ckey = skey[sig_w, pos]  # [W] speculated placement's key
        csuf = sufmax[sig_w, pos]  # [W] its sig's true untouched max
        cnode = order[pos]  # [W]
        u = _upd_keys(
            cnode, sig_w, sig_req, sig_nz, sig_allzero, sig_ok, sig_img,
            alloc, allowed, used, nz0, nz1, num_pods, **score_kw
        )  # [W, S] post-commit keys of each slot's node
        u = jnp.where(sched_spec[:, None], u, NEG)
        # exclusive running max over predecessors' committed nodes
        thr = jax.lax.cummax(u, axis=0)
        thr = jnp.concatenate([jnp.full((1, u.shape[1]), NEG, I64), thr[:-1]])
        thr_i = thr[iota_w, sig_w]  # [W]
        ok_sched = sched_spec & (ckey >= 0) & (ckey == csuf) & (ckey > thr_i)
        agree = ok_sched | dead_w
        disagree = ~agree
        any_dis = jnp.any(disagree)
        first = jnp.argmax(disagree).astype(I32)
        A = jnp.where(any_dis, first, W)  # admitted prefix length (>= 1)
        adm = iota_w < A
        commit = adm & ok_sched
        # windowed form of THE shared usage commit (ops/common.py): each
        # walk position commits at most once per round, so the scatter-add
        # equals replaying the scalar rank-1 form per admitted slot
        rows = usage_carry_update(
            {"used": used, "nz0": nz0, "nz1": nz1, "num_pods": num_pods},
            {
                "used": sig_req[sig_w],
                "nz0": sig_nz[sig_w, 0],
                "nz1": sig_nz[sig_w, 1],
                "num_pods": 1,
            },
            cnode,
            commit,
        )
        used, nz0, nz1, num_pods = (
            rows["used"], rows["nz0"], rows["nz1"], rows["num_pods"]
        )
        cvals = jnp.where(commit, cnode, -1)  # admitted dead pods: -1
        # choices is padded by W so this window write NEVER reaches the
        # array end — XLA CLAMPS out-of-range dynamic_update_slice starts,
        # which would silently shift the write onto earlier results
        old = jax.lax.dynamic_slice(choices, (q,), (W,))
        choices = jax.lax.dynamic_update_slice(
            choices, jnp.where(adm & live, cvals, old), (q,)
        )
        q = q + A
        rounds = rounds + 1
        # adaptive stop: every STOP_GRACE rounds the loop must have
        # yielded STOP_GRACE*MIN_YIELD admissions since the checkpoint —
        # workloads whose agreement prefixes collapse (adversarial sig
        # interleavings) hand over to the tail instead of burning rounds
        at_ckpt = rounds % STOP_GRACE == 0
        stop = at_ckpt & (q - q_ckpt < STOP_GRACE * min_yield)
        q_ckpt = jnp.where(at_ckpt, q, q_ckpt)
        return (q, used, nz0, nz1, num_pods, choices, rounds, q_ckpt, stop)

    def round_cond(carry):
        q, _, _, _, _, _, rounds, _, stop = carry
        return (q < p_live) & (rounds < r_cap) & ~stop

    choices0 = jnp.full((P + W,), UNRESOLVED, I32)
    (
        q, used, nz0, nz1, num_pods, choices, rounds, _, _
    ) = jax.lax.while_loop(
        round_cond,
        round_body,
        (
            jnp.zeros((), I32), used, nz0, nz1, num_pods, choices0,
            jnp.zeros((), I64), jnp.zeros((), I32), jnp.zeros((), bool),
        ),
    )
    choices = choices[:P]
    tail_left = q < p_live

    if serial_tail:
        # fully-device-resident mode: finish unresolved pods with the
        # EXACT sig_scan replay (shared step) inside the same dispatch,
        # entered only when needed so the common case pays nothing.
        def run_tail(args):
            used, nz0, nz1, num_pods, choices = args
            step = make_sig_step(
                sig_req, sig_nz, sig_allzero, sig_ok, sig_img,
                alloc, allowed, **score_kw
            )
            masked = jnp.where(jnp.arange(P, dtype=I32) < q, -1, sig_ids)
            carry, tail_choices = jax.lax.scan(
                step, (used, nz0, nz1, num_pods), masked
            )
            used, nz0, nz1, num_pods = carry
            choices = jnp.where(choices == UNRESOLVED, tail_choices, choices)
            return used, nz0, nz1, num_pods, choices

        used, nz0, nz1, num_pods, choices = jax.lax.cond(
            tail_left,
            run_tail,
            lambda args: args,
            (used, nz0, nz1, num_pods, choices),
        )
    stats = jnp.stack([rounds, q.astype(I64), tail_left.astype(I64)])
    return choices, (used, nz0, nz1, num_pods), stats


# ---------------------------------------------------------------------------
# epoch guard (ISSUE 15): cheap device-side integrity probe of the
# resident usage lineage
# ---------------------------------------------------------------------------

# ktpu: axes(used=i64[N,Rn], nz0=i64[N], nz1=i64[N], num_pods=i32[N])
# ktpu: accum(i64, i32, bool)
@jax.jit
def usage_checksum(used, nz0, nz1, num_pods):
    """Cheap device-side checksum of the resident usage state: the exact
    i64 sum of every row.  The host committer tracks the same quantity
    incrementally (base sum + per-harvest commit deltas — the commit
    arithmetic is identical int math on both sides), so before a round's
    commits are applied the two MUST agree; a mismatch means the lineage
    is torn (a dispatch died mid-round, or a donated buffer was clobbered)
    and the harvest resyncs from the host committer instead of silently
    committing torn usage rows.  One tiny dispatch per device-path batch,
    async-fetched alongside the choices readback."""
    return (
        jnp.sum(used)
        + jnp.sum(nz0)
        + jnp.sum(nz1)
        + jnp.sum(num_pods.astype(I64))
    )
