"""Single-buffer host→device transport.

Over a remote device link (TPU behind a network tunnel) every `device_put`
leaf costs a round trip, so a 40-field pytree pays 40 RTTs per upload — far
more than the bytes themselves.  This module flattens any pytree of numpy
arrays into ONE contiguous byte buffer on the host, ships it in a single
transfer, and reconstructs the tree on device inside a cached jit (static
offsets → XLA slices + bitcasts, fused with whatever consumes them).

This is the host↔HBM half of the snapshot delta protocol (SURVEY.md §2.4):
the informer delta stream becomes one append-only buffer DMA'd per batch.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

_ALIGN = 8


def pack_tree(tree) -> Tuple[np.ndarray, tuple, object]:
    """Flatten a pytree of numpy arrays into (byte_buffer, spec, treedef).

    spec is hashable (dtype/shape/offset per leaf) — the jit cache key for
    the device-side unpacker.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    metas = []
    chunks = []
    off = 0
    for a in leaves:
        shape = np.shape(a)  # before ascontiguousarray (it promotes 0-d → 1-d)
        a = np.ascontiguousarray(a)
        off += (-off) % _ALIGN
        metas.append((str(a.dtype), shape, off))
        chunks.append((off, a))
        off += a.nbytes
    buf = np.zeros(off, np.uint8)
    for o, a in chunks:
        if a.nbytes:
            buf[o : o + a.nbytes] = np.frombuffer(a.tobytes(), np.uint8)
    return buf, tuple(metas), treedef


def unpack(buf, spec):
    """Device-side leaf reconstruction (inside jit): static slices of the
    uint8 buffer, bitcast to each leaf's dtype and shape."""
    leaves = []
    for dtype_str, shape, off in spec:
        dt = np.dtype(dtype_str)
        n = int(np.prod(shape, dtype=np.int64))
        nb = n * dt.itemsize
        raw = jax.lax.slice(buf, (off,), (off + nb,))
        if dt == np.bool_:
            leaf = raw.astype(jnp.bool_)
        elif dt.itemsize == 1:
            leaf = jax.lax.bitcast_convert_type(raw, jnp.dtype(dt))
        else:
            leaf = jax.lax.bitcast_convert_type(
                raw.reshape(n, dt.itemsize), jnp.dtype(dt)
            )
        leaves.append(leaf.reshape(shape))
    return leaves


@functools.lru_cache(maxsize=512)
def _unpacker(spec, treedef):
    # ktpu: axes(buf=u8[B])
    # ktpu: noinstantiate — shapes live in the lru_cache key (spec,
    #   treedef), not in the signature; nothing to instantiate statically
    @jax.jit
    def run(buf):
        return jax.tree_util.tree_unflatten(treedef, unpack(buf, spec))

    return run


def device_put_packed(tree):
    """device_put an entire numpy pytree in ONE transfer."""
    buf, spec, treedef = pack_tree(tree)
    return _unpacker(spec, treedef)(jax.device_put(buf))
