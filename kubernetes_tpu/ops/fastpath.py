"""Device half of the fast commit path: per-SIGNATURE static evaluation.

The gang scan (ops/gang.py) is sequential-equivalent but pays one scan step
per pod.  For batches whose only batch-dynamic constraints are resources
(no inter-pod terms, no spread constraints, no host ports, no nominations),
pods collapse into a handful of SIGNATURES (identical requests + static
constraints), and the per-pod work factors as

    total(p, n) = static(sig(p), n) + dynamic_resources(state(n), sig(p))

This module evaluates the static half ONCE per signature on device —
[S, N] instead of [P, N] with S ~ 10 — and ships it to the host, where
kubernetes_tpu.fastpath replays the exact sequential greedy with integer
score math identical to the kernels.  Mirrors the role of
findNodesThatFitPod's static predicate subset (schedule_one.go:460) without
the per-pod loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops import scores as S
from kubernetes_tpu.ops.common import usage_carry_update
from kubernetes_tpu.snapshot.schema import LANE_CPU, LANE_MEM, N_FIXED_LANES

MAX = 100  # MaxNodeScore
I32 = jnp.int32
I64 = jnp.int64

# shard-rule roster: the sequential-equivalent argmax commit is the
# serial core — per-step first-max argmax over all N nodes plus the
# chosen node's gather; inherently a full-width collective per pod
_KTPU_N_COLLECTIVES = {
    "make_sig_step.step": "resolved(collective): per-pod argmax/gather "
    "over the full node axis (selectHost first-max semantics) — the "
    "packed (score, first-max-index) key all-reduces across node shards "
    "(index tiebreak keeps first-max exact); the committed node's rank-1 "
    "usage update stays local to the owning shard",
}


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch)
# ktpu: static(enabled=("NodeName", "NodeUnschedulable", "TaintToleration", "NodeAffinity"), has_images=True)
@functools.partial(jax.jit, static_argnames=("enabled", "has_images"))
def static_eval(dc, db, enabled: frozenset, has_images: bool):
    """Static filters + raw static scores for a representative batch.

    Returns dict of [S, N] arrays:
      mask        — statics-feasible (node valid, name, unschedulable,
                    taints, node affinity)
      m_taints / m_nodeaff / m_nodename / m_unsched — per-kernel masks
                    (failure diagnosis)
      taint_raw / naff_raw — raw score inputs (the host verifies they are
                    CONSTANT over the feasible set, which makes their
                    normalized contribution argmax-neutral)
      img         — ImageLocality contribution (already weight-free raw,
                    no normalization pass in the reference)
    """
    P = db.valid.shape[0]
    N = dc.node_valid.shape[0]
    true_pn = jnp.ones((P, N), bool)
    tolerated = F._tolerated(dc, db)
    m_nodename = F.mask_node_name(dc, db) if "NodeName" in enabled else true_pn
    m_unsched = (
        F.mask_unschedulable(dc, db)
        if "NodeUnschedulable" in enabled
        else true_pn
    )
    m_taints = (
        F.mask_taints(dc, db, tolerated)
        if "TaintToleration" in enabled
        else true_pn
    )
    m_nodeaff = (
        F.mask_node_affinity(dc, db) if "NodeAffinity" in enabled else true_pn
    )
    mask = (
        dc.node_valid[None, :]
        & db.valid[:, None]
        & m_nodename
        & m_unsched
        & m_taints
        & m_nodeaff
    )
    taint_raw = S.score_taint_toleration(dc, db)
    naff_raw = S.score_node_affinity(dc, db)
    img = (
        S.score_image_locality(dc, db)
        if has_images
        else jnp.zeros((P, N), jnp.int64)
    )
    return {
        "mask": mask,
        "m_nodename": m_nodename,
        "m_unsched": m_unsched,
        "m_taints": m_taints,
        "m_nodeaff": m_nodeaff,
        "taint_raw": taint_raw,
        "naff_raw": naff_raw,
        "img": img,
    }


# ---------------------------------------------------------------------------
# Device half of the COMMIT loop: the sequential-equivalent greedy as a
# lax.scan over signature ids.  The step builder is module-level so the
# resident drain loop (ops/resident.py) replays the EXACT same verdict
# code for its serial-fallback tail — one implementation, two kernels.
# ---------------------------------------------------------------------------


def make_sig_step(
    sig_req,
    sig_nz,
    sig_allzero,
    sig_ok,
    sig_img,
    alloc,
    allowed,
    w_fit: int,
    w_bal: int,
    w_img: int,
    check_fit: bool,
):
    """Build the one-pod greedy step ``(carry, sig_id) -> (carry, choice)``
    over carried node-usage state ``(used, nz0, nz1, num_pods)`` — the
    sequential-equivalent argmax commit shared by sig_scan and the
    resident loop's tail.  Integer score/feasibility math is bit-identical
    to kubernetes_tpu.fastpath.FastCommitter (property-tested)."""
    R = alloc.shape[1]
    N = alloc.shape[0]
    a0 = alloc[:, LANE_CPU]
    a1 = alloc[:, LANE_MEM]
    h0 = a0 > 0
    h1 = a1 > 0
    fit_w = h0.astype(I64) + h1.astype(I64)
    den_bal = jnp.maximum(a0 * a1, 1)
    ext_lane = jnp.arange(R) >= N_FIXED_LANES  # bool [R]

    def step(carry, s):
        used, nz0, nz1, num_pods = carry
        active = s >= 0
        sc = jnp.maximum(s, 0)
        req = sig_req[sc]  # [R]
        snz0 = sig_nz[sc, 0]
        snz1 = sig_nz[sc, 1]
        ok = sig_ok[sc]  # [N]

        # ---- feasibility (fastpath.FastCommitter.feasible_int) ----
        if check_fit:
            fits_count = num_pods + 1 <= allowed
            avail = alloc - used  # [N, R]
            lane_ok = jnp.where(
                (ext_lane & (req == 0))[None, :], True, req[None, :] <= avail
            )
            fits_lanes = jnp.where(
                sig_allzero[sc], True, jnp.all(lane_ok, axis=1)
            )
            feas = ok & fits_count & fits_lanes
        else:
            feas = ok

        # ---- integer score (fastpath.FastCommitter.score_int) ----
        total = jnp.zeros((N,), I64)
        if w_fit:
            c0 = nz0 + snz0
            c1 = nz1 + snz1
            f0 = jnp.where(c0 > a0, 0, (a0 - c0) * MAX // jnp.maximum(a0, 1))
            f1 = jnp.where(c1 > a1, 0, (a1 - c1) * MAX // jnp.maximum(a1, 1))
            least = jnp.where(
                fit_w > 0,
                (jnp.where(h0, f0, 0) + jnp.where(h1, f1, 0))
                // jnp.maximum(fit_w, 1),
                0,
            )
            total = total + w_fit * least
        if w_bal:
            r0 = jnp.minimum(used[:, LANE_CPU] + req[LANE_CPU], a0)
            r1 = jnp.minimum(used[:, LANE_MEM] + req[LANE_MEM], a1)
            d = jnp.abs(r0 * a1 - r1 * a0)
            bal = jnp.where(
                h0 & h1, MAX - (50 * d + den_bal - 1) // den_bal, MAX
            )
            total = total + w_bal * bal
        if w_img:
            total = total + w_img * sig_img[sc]

        # ---- first-max argmax over feasible nodes + one-hot commit ----
        ranked = jnp.where(feas, total, -1)
        choice = jnp.argmax(ranked).astype(I32)
        any_feas = ranked[choice] >= 0
        choice = jnp.where(active & any_feas, choice, -1)
        rows = usage_carry_update(
            {"used": used, "nz0": nz0, "nz1": nz1, "num_pods": num_pods},
            {"used": req, "nz0": snz0, "nz1": snz1, "num_pods": 1},
            choice,
            choice >= 0,
        )
        carry = (rows["used"], rows["nz0"], rows["nz1"], rows["num_pods"])
        return carry, choice

    return step


# ktpu: axes(sig_ids=i32[P], sig_req=i64[S,Rn], sig_nz=i64[S,2], sig_allzero=bool[S])
# ktpu: axes(sig_ok=bool[S,N], sig_img=i64[S,N], alloc=i64[N,Rn], allowed=i32[N])
# ktpu: axes(used=i64[N,Rn], nz0=i64[N], nz1=i64[N], num_pods=i32[N])
# ktpu: accum(i64, i32, bool)
# ktpu: static(w_fit=1, w_bal=1, w_img=1, check_fit=True)
@functools.partial(
    jax.jit,
    static_argnames=("w_fit", "w_bal", "w_img", "check_fit"),
    donate_argnames=("used", "nz0", "nz1", "num_pods"),
)
def sig_scan(
    sig_ids,  # i32 [P]   per-pod signature id, -1 pads
    sig_req,  # i64 [S, R] request row per signature
    sig_nz,  # i64 [S, 2]  non-zero-defaulted cpu,mem per signature
    sig_allzero,  # bool [S] request row entirely zero (fit check skipped)
    sig_ok,  # bool [S, N] statics-feasible (node_valid & name & unsched
    #                      & taints & node-affinity), from static_eval
    sig_img,  # i64 [S, N] ImageLocality contribution (zeros when unused)
    alloc,  # i64 [N, R]
    allowed,  # i32 [N]
    used,  # i64 [N, R]   — donated, evolves across batches
    nz0,  # i64 [N]       — donated
    nz1,  # i64 [N]       — donated
    num_pods,  # i32 [N]  — donated
    w_fit: int,
    w_bal: int,
    w_img: int,
    check_fit: bool,
):
    """One device dispatch = one batch of the signature fast path.

    Replays the reference's one-pod-at-a-time argmax commit
    (schedule_one.go:65 ScheduleOne → selectHost first-max) as a lax.scan
    whose carried state is the node usage tensors — the device-resident
    analogue of kubernetes_tpu.fastpath.FastCommitter, bit-identical to it
    (property-tested in tests/test_fastpath.py).  Per step: O(N) integer
    score + masked argmax + one-hot commit; no [P, N] tensors exist and the
    state never leaves HBM between batches.

    Returns (choices i32 [P] — node index or -1, new_state tuple).
    """
    step = make_sig_step(
        sig_req,
        sig_nz,
        sig_allzero,
        sig_ok,
        sig_img,
        alloc,
        allowed,
        w_fit,
        w_bal,
        w_img,
        check_fit,
    )
    carry, choices = jax.lax.scan(step, (used, nz0, nz1, num_pods), sig_ids)
    return choices, carry
