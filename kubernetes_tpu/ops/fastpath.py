"""Device half of the fast commit path: per-SIGNATURE static evaluation.

The gang scan (ops/gang.py) is sequential-equivalent but pays one scan step
per pod.  For batches whose only batch-dynamic constraints are resources
(no inter-pod terms, no spread constraints, no host ports, no nominations),
pods collapse into a handful of SIGNATURES (identical requests + static
constraints), and the per-pod work factors as

    total(p, n) = static(sig(p), n) + dynamic_resources(state(n), sig(p))

This module evaluates the static half ONCE per signature on device —
[S, N] instead of [P, N] with S ~ 10 — and ships it to the host, where
kubernetes_tpu.fastpath replays the exact sequential greedy with integer
score math identical to the kernels.  Mirrors the role of
findNodesThatFitPod's static predicate subset (schedule_one.go:460) without
the per-pod loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops import scores as S


@functools.partial(jax.jit, static_argnames=("enabled", "has_images"))
def static_eval(dc, db, enabled: frozenset, has_images: bool):
    """Static filters + raw static scores for a representative batch.

    Returns dict of [S, N] arrays:
      mask        — statics-feasible (node valid, name, unschedulable,
                    taints, node affinity)
      m_taints / m_nodeaff / m_nodename / m_unsched — per-kernel masks
                    (failure diagnosis)
      taint_raw / naff_raw — raw score inputs (the host verifies they are
                    CONSTANT over the feasible set, which makes their
                    normalized contribution argmax-neutral)
      img         — ImageLocality contribution (already weight-free raw,
                    no normalization pass in the reference)
    """
    P = db.valid.shape[0]
    N = dc.node_valid.shape[0]
    true_pn = jnp.ones((P, N), bool)
    tolerated = F._tolerated(dc, db)
    m_nodename = F.mask_node_name(dc, db) if "NodeName" in enabled else true_pn
    m_unsched = (
        F.mask_unschedulable(dc, db)
        if "NodeUnschedulable" in enabled
        else true_pn
    )
    m_taints = (
        F.mask_taints(dc, db, tolerated)
        if "TaintToleration" in enabled
        else true_pn
    )
    m_nodeaff = (
        F.mask_node_affinity(dc, db) if "NodeAffinity" in enabled else true_pn
    )
    mask = (
        dc.node_valid[None, :]
        & db.valid[:, None]
        & m_nodename
        & m_unsched
        & m_taints
        & m_nodeaff
    )
    taint_raw = S.score_taint_toleration(dc, db)
    naff_raw = S.score_node_affinity(dc, db)
    img = (
        S.score_image_locality(dc, db)
        if has_images
        else jnp.zeros((P, N), jnp.int64)
    )
    return {
        "mask": mask,
        "m_nodename": m_nodename,
        "m_unsched": m_unsched,
        "m_taints": m_taints,
        "m_nodeaff": m_nodeaff,
        "taint_raw": taint_raw,
        "naff_raw": naff_raw,
        "img": img,
    }
