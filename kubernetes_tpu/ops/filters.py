"""Batched Filter kernels → ``[P, N]`` feasibility masks.

Each function reproduces one in-tree Filter plugin's semantics
(SURVEY.md §2.3) for every (pending pod, node) pair at once.  Reference
citations point at the Go implementation being matched; the scalar golden
model is kubernetes_tpu.oracle.filters.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops.common import (
    DeviceBatch,
    DeviceCluster,
    I32,
    dnf_any,
    domain_stats,
    eval_table,
    gather_at,
    ns_member,
    per_node_counts,
)
from kubernetes_tpu.snapshot.interner import ABSENT, PAD
from kubernetes_tpu.snapshot.schema import (
    EFFECT_ALL,
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    TERM_PREFERRED_AFFINITY,
    TERM_PREFERRED_ANTI,
    TERM_REQUIRED_AFFINITY,
    TERM_REQUIRED_ANTI,
    TOL_OP_EXISTS,
)


# ---------------------------------------------------------------------------
# NodeName (plugins/nodename/node_name.go)
# ---------------------------------------------------------------------------


def mask_node_name(dc: DeviceCluster, db: DeviceBatch):
    node_name_val = gather_at(dc.node_labels.T, dc.name_key)  # [N]
    tgt = db.target_name_val  # [P]
    return (tgt == ABSENT)[:, None] | (node_name_val[None, :] == tgt[:, None])


# ---------------------------------------------------------------------------
# Taints / tolerations (plugins/tainttoleration/taint_toleration.go:103)
# ---------------------------------------------------------------------------


def any_tolerates(db: DeviceBatch, taint_key, taint_val, taint_effect, slot_use=None):
    """[P, N, T] — does any toleration of pod p tolerate taint slot t of node
    n (api/core/v1/toleration.go ToleratesTaint).

    taint_* are [N, T] arrays; ``slot_use`` optionally restricts which
    toleration slots participate ([P, TL] bool — e.g. the PreferNoSchedule
    effect filter of the TaintToleration score).  The single source of truth
    for toleration matching on device.
    """
    P, TL = db.tol_key.shape
    N, T = taint_key.shape
    out = jnp.zeros((P, N, T), bool)
    for l in range(TL):
        tk = db.tol_key[:, l][:, None, None]
        to = db.tol_op[:, l][:, None, None]
        tv = db.tol_val[:, l][:, None, None]
        te = db.tol_effect[:, l][:, None, None]
        use = db.tol_op[:, l] != PAD
        if slot_use is not None:
            use = use & slot_use[:, l]
        effect_ok = (te == EFFECT_ALL) | (te == taint_effect[None])
        wildcard = (tk == ABSENT) & (to == TOL_OP_EXISTS)
        key_eq = tk == taint_key[None]
        val_ok = (to == TOL_OP_EXISTS) | (tv == taint_val[None])
        out = out | (
            use[:, None, None] & effect_ok & (wildcard | (key_eq & val_ok))
        )
    return out


def _tolerated(dc: DeviceCluster, db: DeviceBatch):
    return any_tolerates(db, dc.taint_key, dc.taint_val, dc.taint_effect)


def mask_taints(dc: DeviceCluster, db: DeviceBatch, tolerated=None):
    if tolerated is None:
        tolerated = _tolerated(dc, db)
    hard = (dc.taint_effect == EFFECT_NO_SCHEDULE) | (
        dc.taint_effect == EFFECT_NO_EXECUTE
    )
    taint_real = dc.taint_key != PAD
    untol = jnp.any((hard & taint_real)[None] & ~tolerated, axis=-1)
    return ~untol


# ---------------------------------------------------------------------------
# NodeUnschedulable (plugins/nodeunschedulable/node_unschedulable.go)
# ---------------------------------------------------------------------------


def mask_unschedulable(dc: DeviceCluster, db: DeviceBatch):
    """Unschedulable nodes pass only if the pod tolerates the synthetic
    node.kubernetes.io/unschedulable:NoSchedule taint."""
    synth_key = jnp.full((1, 1), 0, I32) + dc.unsched_key
    synth_val = jnp.full((1, 1), 0, I32) + dc.empty_val
    synth_eff = jnp.full((1, 1), EFFECT_NO_SCHEDULE, I32)
    tol = any_tolerates(db, synth_key, synth_val, synth_eff)[:, 0, 0]  # [P]
    return (~dc.unschedulable)[None, :] | tol[:, None]


# ---------------------------------------------------------------------------
# NodeResourcesFit (plugins/noderesources/fit.go:423-503)
# ---------------------------------------------------------------------------


def mask_resources(dc: DeviceCluster, db: DeviceBatch, requested=None, num_pods=None):
    """requested/num_pods default to the snapshot's but can be overridden by
    the gang-commit scan's running totals.

    Semantics from fit.go:460 fitsRequest: a pod with an all-zero request
    vector always fits (early return); cpu/mem/ephemeral are compared
    unconditionally after that (a zero request CAN fail on an overcommitted
    node); extended-resource lanes are only compared when the pod requests
    them.  The pod batch may carry more lanes than the snapshot (a pending
    pod requesting a never-seen extended resource) — those lanes have zero
    allocatable everywhere.
    """
    from kubernetes_tpu.snapshot.schema import N_FIXED_LANES

    requested = dc.requested if requested is None else requested
    num_pods = dc.num_pods if num_pods is None else num_pods
    Rn = dc.allocatable.shape[1]
    Rp = db.requests.shape[1]
    fits = (num_pods + 1 <= dc.allowed_pods)[None, :]
    all_zero = jnp.all(db.requests == 0, axis=1)  # [P]
    lane_ok = None
    for r in range(Rp):
        req = db.requests[:, r][:, None]  # [P, 1]
        if r < Rn:
            avail = (dc.allocatable[:, r] - requested[:, r])[None, :]  # [1, N]
        else:
            avail = jnp.zeros((1, dc.allocatable.shape[0]), I32)
        conflict = req > avail
        if r >= N_FIXED_LANES:
            conflict = conflict & (req > 0)  # unrequested scalars are skipped
        lane_ok = ~conflict if lane_ok is None else (lane_ok & ~conflict)
    return fits & (all_zero[:, None] | lane_ok)


# ---------------------------------------------------------------------------
# NodeAffinity (plugins/nodeaffinity/node_affinity.go:182-203)
# ---------------------------------------------------------------------------


def mask_node_affinity(dc: DeviceCluster, db: DeviceBatch):
    terms = eval_table(db.node_sel, dc.node_labels, dc.val_ints)  # [P, T, N]
    return dnf_any(terms)


# ---------------------------------------------------------------------------
# NodePorts (plugins/nodeports/node_ports.go)
# ---------------------------------------------------------------------------


def mask_ports(dc: DeviceCluster, db: DeviceBatch):
    W = db.want_ppk.shape[1]
    U = dc.used_ppk.shape[1]
    P = db.want_ppk.shape[0]
    N = dc.used_ppk.shape[0]
    conflict = jnp.zeros((P, N), bool)
    for w in range(W):
        wk = db.want_ppk[:, w][:, None]
        wi = db.want_ip[:, w][:, None]
        ww = db.want_wild[:, w][:, None]
        w_valid = wk != PAD
        for u in range(U):
            uk = dc.used_ppk[:, u][None, :]
            ui = dc.used_ip[:, u][None, :]
            uw = dc.used_wild[:, u][None, :]
            u_valid = uk != PAD
            conflict = conflict | (
                w_valid
                & u_valid
                & (wk == uk)
                & ((wi == ui) | ww | uw)
            )
    return ~conflict


# ---------------------------------------------------------------------------
# InterPodAffinity (plugins/interpodaffinity/filtering.go:306-365)
# ---------------------------------------------------------------------------


class InterPodPre(NamedTuple):
    """Precomputed inter-pod state shared by the filter and score kernels."""

    # existing pods' term rows vs incoming pods
    ext_match: jnp.ndarray  # bool [M, P] term matches incoming pod
    ext_topo_eq: jnp.ndarray  # bool [M, N] node shares term's topology value
    # incoming pods' term rows vs existing pods
    inc_match: jnp.ndarray  # bool [P, AT, E]
    inc_dv: jnp.ndarray  # i32 [P, AT, N] node's domain id per incoming term
    inc_cnt: jnp.ndarray  # i32 [P, AT, N] matching placed pods per node


def interpod_precompute(dc: DeviceCluster, db: DeviceBatch) -> InterPodPre:
    # Existing terms vs incoming pods (selector evaluated on pod labels,
    # incoming namespace in term's namespace set).
    ext_sel = eval_table(dc.term_table, db.labels, dc.val_ints)[:, 0, :]  # [M, P]
    ext_ns = ns_member(dc.term_ns_all, dc.term_ns_ids, db.ns_id)  # [M, P]
    src_valid = (
        (dc.term_pod >= 0)
        & jnp.take(
            dc.epod_valid, jnp.clip(dc.term_pod, 0, dc.epod_valid.shape[0] - 1)
        )
    )
    ext_match = ext_sel & ext_ns & src_valid[:, None]

    # The term's topology value at its own pod's node, compared to all nodes.
    node_of = jnp.where(
        dc.term_pod >= 0,
        jnp.take(dc.epod_node, jnp.clip(dc.term_pod, 0, dc.epod_node.shape[0] - 1)),
        ABSENT,
    )
    cols = dc.node_labels.T  # [K, N]
    nv = gather_at(cols, dc.term_topo)  # [M, N]
    ev = jnp.take_along_axis(
        nv, jnp.clip(node_of, 0, nv.shape[1] - 1)[:, None], axis=1
    )[:, 0]
    ev = jnp.where(node_of >= 0, ev, ABSENT)
    ext_topo_eq = (ev >= 0)[:, None] & (nv == ev[:, None])

    # Incoming terms vs existing pods.
    inc_sel = eval_table(db.aff_table, dc.epod_labels, dc.val_ints)  # [P, AT, E]
    inc_ns = ns_member(db.aff_ns_all, db.aff_ns_ids, dc.epod_ns)  # [P, AT, E]
    inc_match = inc_sel & inc_ns & dc.epod_valid[None, None, :]
    inc_cnt = per_node_counts(
        inc_match.astype(I32), dc.epod_node, dc.node_labels.shape[0]
    )
    inc_dv = gather_at(cols, db.aff_topo)  # [P, AT, N]
    return InterPodPre(
        ext_match=ext_match,
        ext_topo_eq=ext_topo_eq,
        inc_match=inc_match,
        inc_dv=inc_dv,
        inc_cnt=inc_cnt,
    )


def interpod_weighted_ext(dc: DeviceCluster, pre: InterPodPre, row_weight):
    """Σ over existing-term rows of row_weight · [term matches pod] ·
    [node shares the term's topology value] — the shared masked-matmul core
    of the existing-anti-affinity filter and the symmetric score.

    row_weight: i32 [M]; returns i32 [P, N]."""
    m = (pre.ext_match.astype(I32) * row_weight[:, None]).T  # [P, M]
    return jax.lax.dot_general(
        m,
        pre.ext_topo_eq.astype(I32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=I32,
    )


def interpod_existing_violation(dc: DeviceCluster, pre: InterPodPre):
    """[P, N]: forbidden by some existing pod's required anti-affinity."""
    anti_row = (dc.term_kind == TERM_REQUIRED_ANTI).astype(I32)
    return interpod_weighted_ext(dc, pre, anti_row) > 0


def mask_interpod(
    dc: DeviceCluster, db: DeviceBatch, pre: InterPodPre, v_cap: int
):
    P, AT, N = pre.inc_dv.shape

    # 1. Existing pods' required anti-affinity forbids same-domain nodes.
    viol1 = interpod_existing_violation(dc, pre)  # [P, N]

    # Domain totals of matching placed pods per incoming term.
    dom_tot, _, _, _ = domain_stats(
        pre.inc_cnt, jnp.zeros_like(pre.inc_cnt, bool), pre.inc_dv, v_cap
    )
    topo_present = pre.inc_dv >= 0  # [P, AT, N]

    # 2. Incoming required anti-affinity: any matching placed pod in the
    #    node's domain ⇒ reject (missing topology label ⇒ pass).
    is_anti = db.aff_kind == TERM_REQUIRED_ANTI  # [P, AT]
    viol2 = jnp.any(
        is_anti[:, :, None] & topo_present & (dom_tot > 0), axis=1
    )

    # 3. Incoming required affinity: every term satisfied in-domain, with the
    #    first-pod-in-series escape hatch (filtering.go:336-363).
    is_aff = db.aff_kind == TERM_REQUIRED_AFFINITY
    term_ok = topo_present & (dom_tot > 0)
    aff_ok = jnp.all(~is_aff[:, :, None] | term_ok, axis=1)  # [P, N]

    any_match_anywhere = jnp.any(
        is_aff[:, :, None] & pre.inc_match, axis=(1, 2)
    )  # [P]
    # Self-match: term's selector against the pod's own labels + namespace.
    self_sel = jax.vmap(
        lambda tbl, lbl: eval_table(tbl, lbl[None, :], dc.val_ints)[..., 0]
    )(db.aff_table, db.labels)  # [P, AT]
    self_ns = jax.vmap(
        lambda a, ids, ns: ns_member(a, ids, ns[None])[..., 0]
    )(db.aff_ns_all, db.aff_ns_ids, db.ns_id)  # [P, AT]
    self_all = jnp.all(~is_aff | (self_sel & self_ns), axis=1)
    has_aff = jnp.any(is_aff, axis=1)
    escape = has_aff & ~any_match_anywhere & self_all  # [P]

    # A node missing any required-affinity topology label is rejected before
    # the escape hatch is ever consulted (filtering.go: early return).
    topo_all = jnp.all(~is_aff[:, :, None] | topo_present, axis=1)  # [P, N]
    ok3 = aff_ok | (escape[:, None] & topo_all)
    return ~viol1 & ~viol2 & ok3


# ---------------------------------------------------------------------------
# PodTopologySpread (plugins/podtopologyspread/filtering.go)
# ---------------------------------------------------------------------------


class SpreadPre(NamedTuple):
    """Shared spread-filter state (also reused by the gang scan)."""

    exists: jnp.ndarray  # bool [P, C] constraint slot holds a constraint
    sel_match: jnp.ndarray  # bool [P, C, E] selector matches placed pod
    self_match: jnp.ndarray  # bool [P, C] selector matches the pod itself
    dv: jnp.ndarray  # i32 [P, C, N] domain id per node
    eligible: jnp.ndarray  # bool [P, C, N] inclusion-policy eligibility
    tracked: jnp.ndarray  # bool [P, N] node has all hard topo keys


def spread_precompute(
    dc: DeviceCluster,
    db: DeviceBatch,
    node_affinity_mask,
    taint_mask,
) -> SpreadPre:
    exists = db.tsc_topo != PAD  # [P, C]
    cols = dc.node_labels.T
    dv = gather_at(cols, db.tsc_topo)  # [P, C, N]
    topo_present = dv >= 0

    hard = exists & db.tsc_hard
    tracked = jnp.all(~hard[:, :, None] | topo_present, axis=1)  # [P, N]

    eligible = jnp.where(
        db.tsc_honor_affinity[:, :, None], node_affinity_mask[:, None, :], True
    ) & jnp.where(db.tsc_honor_taints[:, :, None], taint_mask[:, None, :], True)

    sel = eval_table(db.tsc_table, dc.epod_labels, dc.val_ints)  # [P, C, E]
    same_ns = db.ns_id[:, None] == dc.epod_ns[None, :]  # [P, E]
    sel_match = (
        sel
        & same_ns[:, None, :]
        & dc.epod_valid[None, None, :]
        & ~dc.epod_deleting[None, None, :]
    )

    self_match = jax.vmap(
        lambda tbl, lbl: eval_table(tbl, lbl[None, :], dc.val_ints)[..., 0]
    )(db.tsc_table, db.labels)  # [P, C]
    return SpreadPre(exists, sel_match, self_match, dv, eligible, tracked)


def mask_spread(
    dc: DeviceCluster, db: DeviceBatch, pre: SpreadPre, v_cap: int
):
    """DoNotSchedule constraints: matchNum + selfMatch − minMatch > maxSkew
    ⇒ Unschedulable (filtering.go:313-362)."""
    hard = pre.exists & db.tsc_hard  # [P, C]
    N = pre.dv.shape[2]

    cnt_n = per_node_counts(pre.sel_match.astype(I32), dc.epod_node, N)
    counted = pre.tracked[:, None, :] & pre.eligible
    cnt_n = jnp.where(counted, cnt_n, 0)

    dom_tot, dom_pres, dom_min, n_dom = domain_stats(
        cnt_n, counted, pre.dv, v_cap
    )
    min_match = jnp.where(
        (db.tsc_min_domains > 0) & (n_dom < db.tsc_min_domains), 0, dom_min
    )  # [P, C]

    topo_present = pre.dv >= 0
    selfm = pre.self_match.astype(I32)[:, :, None]
    skew = dom_tot + selfm - min_match[:, :, None]
    c_ok = topo_present & (
        ~dom_pres | (skew <= db.tsc_max_skew[:, :, None])
    )
    return jnp.all(~hard[:, :, None] | c_ok, axis=1)


# ---------------------------------------------------------------------------
# Combined
# ---------------------------------------------------------------------------


ALL_FILTER_KERNELS = frozenset(
    {
        "NodeName",
        "NodeUnschedulable",
        "TaintToleration",
        "NodeAffinity",
        "NodePorts",
        "NodeResourcesFit",
        "InterPodAffinity",
        "PodTopologySpread",
    }
)


def all_masks(
    dc: DeviceCluster,
    db: DeviceBatch,
    v_cap: int,
    has_interpod: bool = True,
    has_spread: bool = True,
    enabled: frozenset = ALL_FILTER_KERNELS,
) -> Dict[str, jnp.ndarray]:
    """Run every Filter kernel; returns per-plugin masks plus the AND.

    ``has_interpod``/``has_spread`` are STATIC flags computed host-side from
    the batch + snapshot: when a batch carries no such constraints the
    corresponding kernels (the segment-sum-heavy ones) compile away entirely
    — the analogue of the reference's PreFilter Skip status
    (framework/interface.go:443).

    The combined mask also excludes invalid node slots and invalid pod rows
    (padding in the bucketed batch).
    """
    tolerated = _tolerated(dc, db)
    node_affinity = mask_node_affinity(dc, db)
    taints = mask_taints(dc, db, tolerated)
    masks = {}
    if "NodeName" in enabled:
        masks["NodeName"] = mask_node_name(dc, db)
    if "NodeUnschedulable" in enabled:
        masks["NodeUnschedulable"] = mask_unschedulable(dc, db)
    if "TaintToleration" in enabled:
        masks["TaintToleration"] = taints
    if "NodeAffinity" in enabled:
        masks["NodeAffinity"] = node_affinity
    if "NodePorts" in enabled:
        masks["NodePorts"] = mask_ports(dc, db)
    if "NodeResourcesFit" in enabled:
        masks["NodeResourcesFit"] = mask_resources(dc, db)
    ipre = spre = None
    if has_interpod and "InterPodAffinity" in enabled:
        ipre = interpod_precompute(dc, db)
        masks["InterPodAffinity"] = mask_interpod(dc, db, ipre, v_cap)
    if has_spread and "PodTopologySpread" in enabled:
        spre = spread_precompute(dc, db, node_affinity, taints)
        masks["PodTopologySpread"] = mask_spread(dc, db, spre, v_cap)
    combined = dc.node_valid[None, :] & db.valid[:, None]
    for m in masks.values():
        combined = combined & m
    masks["_combined"] = combined
    masks["_interpod_pre"] = ipre
    masks["_spread_pre"] = spre
    return masks
