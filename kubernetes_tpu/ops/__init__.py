"""Batched device kernels.

Every kernel evaluates one scheduler plugin's Filter/Score semantics for a
whole ``(pods × nodes)`` batch at once — the TPU-native replacement for the
reference's per-node Parallelizer loops (pkg/scheduler/schedule_one.go:588,
framework/runtime/framework.go:1101).  Inputs are the packed int32 tensors
from kubernetes_tpu.snapshot; outputs are ``[P, N]`` boolean feasibility
masks and integer scores, bit-matched against kubernetes_tpu.oracle.
"""

from kubernetes_tpu.ops.common import (  # noqa: F401
    DeviceBatch,
    DeviceCluster,
    eval_table,
)
