"""Gang/coscheduling admission + DRA allocation — the workloads tier.

One fused device dispatch schedules batches that carry PodGroup gangs
and/or DRA resource claims (and volume-topology-masked pods via the static
extra mask), riding the wave dispatch's two-pass shape (ops/wave.py):

  1. **Speculation** — every pod is evaluated in one parallel ``(P × N)``
     pass against the frozen snapshot (zero intra-batch deltas, the
     pre-batch DRA allocation state), exactly the wave's first pass.

  2. **Admission** — a serial scan replays the exact recurrence
     ``choice_i = F_i(S + Σ_{j<i} Δ(choice_j))`` over the TERM-FACTORED
     delta algebra (wave.factored_*: per-term [T, N] spread/inter-pod
     carries) EXTENDED with two allocation carries — ``free [N, DD]``
     device availability and ``claim_node [CL]`` claim pinning
     (ops/dra.py) — so DRA claims participate in conflict resolution like
     any other usage row, with in-batch contention resolved in queue
     order.

  **All-or-nothing gangs.**  The batch planner (workloads/gang.py) lays
  each gang's members out contiguously; the scan snapshots its ENTIRE
  carried state (usage + factored counts + allocation carries + the
  assignment row) at a gang's first member and, at its last member,
  admits the gang only when the members placed this batch cover the
  gang's remaining ``minMember`` need — otherwise the checkpoint is
  restored wholesale: usage rows, topology counts, device grants, and
  the members' own assignments all roll back, and later pods in the
  batch see a state in which the gang never happened.  This is the
  coscheduling plugin's Permit-barrier semantics collapsed into the
  dispatch: members land together or not at all, bit-identically to the
  serial gang/DRA oracle (oracle/workloads.py) replaying the same
  canonical order.

The verdict itself is gang.pod_step — the SAME code as the scan/wave
paths — and the factored dyn builders are imported from ops/wave.py, so
the three serial-recurrence replayers cannot drift.  Routing lives in
scheduler.py behind the ``gangDispatch`` kill-switch; with it off, gang
pods schedule individually and DRA/volume pods fall back to the serial
one-pod host-plugin path (decision-identical — kill-switch identity is
property-tested in tests/test_coscheduling.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import dra as dra_ops
from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops import gang
from kubernetes_tpu.ops.gang import N_DIAG
from kubernetes_tpu.ops import wave
from kubernetes_tpu.ops.common import (
    DeviceBatch,
    DeviceCluster,
    I32,
    I64,
    dnf_any,
    eval_table,
)
from kubernetes_tpu.snapshot.interner import ABSENT


def volume_topology_mask(dc: DeviceCluster, vol_table, vol_valid, vol_bad):
    """The volume-topology filter as a kernel mask: [P, N] bool — every
    bound PV's node-affinity DNF (packed one PV per ``PV2`` slot, ORed
    terms on the DTable term axis) must admit the node; a PV with nil
    affinity is packed invalid (matches everywhere); ``vol_bad`` marks
    pods whose bound PVC points at a missing PV (infeasible everywhere —
    binder.go:868 checkBoundClaims).  Reuses the conjunction evaluator the
    spread/affinity topology terms ride (ops/common.eval_table)."""
    vm = eval_table(vol_table, dc.node_labels, dc.val_ints)  # [P, PV2, T, N]
    per_pv = dnf_any(vm)  # [P, PV2, N]
    vol_mask = jnp.all(
        jnp.where(vol_valid[:, :, None], per_pv, True), axis=1
    )  # [P, N]
    return vol_mask & ~vol_bad[:, None]

# shard-rule roster: like the wave admission scan, the workloads scan
# contracts the factored [T, N] carries over N, and additionally reduces
# the [N, DD] device-availability plane per node (match counts, greedy
# ranks) and gathers the chosen node's take row.  Under a sharded N mesh
# each is a cross-shard collective (ROADMAP item 2 worklist).
_KTPU_N_COLLECTIVES = {
    "workloads_schedule.step": "resolved(collective): term-factored "
    "domain compare+reduce over N + per-node DRA match/take reductions + "
    "chosen-node row gathers (allocation commit, gang checkpoint "
    "restore) — same algebra as wave_schedule.step: per-term counts "
    "psum across node shards at the conflict compare, the chosen-node "
    "row gather is an owning-shard broadcast, rank-1 usage/DRA commits "
    "stay shard-local, and the gang checkpoint save/restore is "
    "elementwise over the carried state (no crossing)",
    "workloads_schedule.spec_one": "resolved(local): frozen-snapshot "
    "speculation — the vmap shards the POD axis (pods-major mesh: each "
    "device speculates its own pods against the replicated/node-sharded "
    "snapshot); the per-node DRA match counts reduce the device axis "
    "(DD), not N, so the reduction is shard-local until the final "
    "rostered argmax",
}

# carried state snapshotted at a gang's first member and restored wholesale
# on rollback (the allocation carries join when the batch has claims)
_CK_KEYS = (
    "requested",
    "nonzero",
    "num_pods",
    "assigned",
    "cnt_sp",
    "cnt_ip",
    "rev_cnt",
)
_CK_DRA_KEYS = ("free", "claim_node")


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch, g=GangStatics, hostname_key=i32)
# ktpu: axes(tid_sp=i32[P,C], rep_sp_p=i32[Tsp], rep_sp_c=i32[Tsp])
# ktpu: axes(tid_ip=i32[P,A], rep_ip_p=i32[Tip], rep_ip_u=i32[Tip], ip_cdv_tab=i32[Kd2,N])
# ktpu: axes(gang_id=i32[P], gang_first=bool[P], gang_last=bool[P], gang_need=i32[P])
# ktpu: axes(dev_key=i32[N,DD,DA], dev_val=i32[N,DD,DA], dev_valid=bool[N,DD], free0=bool[N,DD])
# ktpu: axes(sel_key=i32[P,DQ,DS], sel_op=i32[P,DQ,DS], sel_vals=i32[P,DQ,DS,DV])
# ktpu: axes(req_count=i32[P,DQ], req_all=bool[P,DQ], req_cl=i32[P,DQ], req_bad=bool[P,DQ])
# ktpu: axes(q_valid=bool[P,DQ], ref_cl=i32[P,CQ], claim_node0=i32[CL])
# ktpu: axes(nom_node=i32[G], nom_prio=i32[G], nom_req=i32[G,Rn], extra_score=i64[P,N])
# ktpu: accum(i64, i32, bool)
# ktpu: static(v_cap=16, g_cap=4)
@functools.partial(
    jax.jit,
    static_argnames=(
        "v_cap",
        "g_cap",
        "weights",
        "check_fit",
        "d_cap",
        "d2_cap",
        "fit_strategy",
    ),
)
def workloads_schedule(
    dc: DeviceCluster,
    db: DeviceBatch,
    g: gang.GangStatics,
    hostname_key,
    v_cap: int,
    g_cap: int,
    tid_sp,
    rep_sp_p,
    rep_sp_c,
    tid_ip,
    rep_ip_p,
    rep_ip_u,
    ip_cdv_tab,
    gang_id,
    gang_first,
    gang_last,
    gang_need,
    dev_key=None,
    dev_val=None,
    dev_valid=None,
    free0=None,
    sel_key=None,
    sel_op=None,
    sel_vals=None,
    req_count=None,
    req_all=None,
    req_cl=None,
    req_bad=None,
    q_valid=None,
    ref_cl=None,
    claim_node0=None,
    weights: tuple = gang.DEFAULT_WEIGHTS,
    check_fit: bool = True,
    nom_node=None,
    nom_prio=None,
    nom_req=None,
    d_cap: int = 8,
    d2_cap: int = 8,
    extra_score=None,
    fit_strategy: tuple = gang.DEFAULT_FIT_STRATEGY,
):
    """One fused workloads dispatch: speculation + gang/DRA admission scan.

    Returns (chosen [P], n_feas [P], reason_counts [P, ND], tallies,
    wl) where ``chosen`` is the POST-ROLLBACK assignment (-1 for failed
    and rolled-back pods) and ``wl`` is a dict of workload stats:
    spec [P] speculative choices, raw [P] pre-rollback admission choices,
    gang_admit [G2] (-1 unjudged / 0 rolled back / 1 admitted),
    gang_landed [G2] members placed this batch, claim_node [CL] (or the
    untouched input when the batch has no claims)."""
    P, N = g.static_mask.shape
    C = g.sp_dv.shape[1]
    AT = g.ip_dv.shape[1]
    Tsp = rep_sp_p.shape[0]
    Tip = rep_ip_p.shape[0]
    has_dra = dev_key is not None

    if nom_node is not None:
        nom_oh = (
            nom_node[:, None] == jnp.arange(N, dtype=I32)[None, :]
        ).astype(I32)  # [G, N]
    else:
        nom_oh = None

    true_n = jnp.ones((N,), bool)

    # batch-peer match tensors from the statics (the wave's gathers)
    m_sp_all, m_ip_all, t_anti, t_w = wave.term_match_rows(
        g, rep_sp_p, rep_sp_c, rep_ip_p, rep_ip_u
    )

    # the batched device-matching pass: selectors are static per batch, so
    # the full [P, DQ, N, DD] match tensor is built ONCE outside the scan
    if has_dra:
        match = dra_ops.selector_match(
            dev_key, dev_val, dev_valid, sel_key, sel_op, sel_vals
        )
    else:
        match = None

    def zero_sdyn():
        z = jnp.zeros((C, N), I32)
        return gang.SpreadDyn(z, z, z)

    def zero_idyn():
        return gang.InterpodDyn(
            jnp.zeros((AT, N), I32),
            jnp.zeros((N,), bool),
            jnp.zeros((N,), I64),
            jnp.asarray(False),
        )

    def build_hv(p, sdyn, idyn, m_extra):
        if C:
            m_spread, sp_cnt, _ = gang.spread_constraints(db, g, p, sdyn)
        else:
            m_spread = true_n
            sp_cnt = jnp.zeros((C, N), I32)
        if AT:
            m_interpod, ip_raw, _ = gang.interpod_constraints(g, p, idyn)
        else:
            m_interpod = true_n
            ip_raw = g.ip_sym[p]
        return dict(
            m_portb=m_extra,
            m_spread=m_spread,
            sp_cnt=sp_cnt,
            m_interpod=m_interpod,
            ip_raw=ip_raw,
        )

    step_kw = dict(
        check_fit=check_fit,
        weights=weights,
        d_cap=d_cap,
        fit_strategy=fit_strategy,
        extra_score=extra_score,
        nom_oh=nom_oh,
        nom_prio=nom_prio,
        nom_req=nom_req,
    )

    base = dict(
        requested=dc.requested,
        nonzero=dc.nonzero_req,
        num_pods=dc.num_pods,
        assigned=jnp.full((P,), ABSENT, I32),
    )

    def dra_mask_take(p, free, claim_node):
        if not has_dra:
            return true_n, None
        ok, take = dra_ops.node_feasible(
            match[p],
            free,
            claim_node,
            req_count[p],
            req_all[p],
            req_cl[p],
            q_valid[p],
            req_bad[p],
            ref_cl[p],
        )
        return ok, take

    # ---- pass 1: speculation against the frozen snapshot ------------------
    def spec_one(p):
        m_extra, _ = dra_mask_take(p, free0, claim_node0)
        hv = build_hv(p, zero_sdyn(), zero_idyn(), m_extra)
        _, (choice, _, _) = gang.pod_step(
            dc, db, g, p, base, hv, jnp.asarray(True), commit=False, **step_kw
        )
        return choice

    c0 = jax.vmap(spec_one)(jnp.arange(P, dtype=I32))

    # ---- pass 2: gang/DRA admission over the factored deltas ---------------
    init = dict(
        base,
        **wave.factored_carry_init(Tsp, Tip, N),
        gang_landed=jnp.asarray(0, I32),
        gang_admit=jnp.full((g_cap,), -1, I32),
        gang_landed_out=jnp.zeros((g_cap,), I32),
        # Per-pod outputs ride CARRY buffers (not scan-stacked ys):
        # jaxlib 0.4.37's SPMD partitioner mis-clamps the ys-stacking
        # dynamic_update_slice (s64 scan counter vs its s32 shard
        # arithmetic) when propagation shards the stacking axis; carry
        # scatter writes at an i32 index partition correctly.  NOT in
        # ck_keys: a rolled-back gang keeps its RAW choices recorded,
        # exactly like the ys did.
        out_raw=jnp.full((P,), ABSENT, I32),
        out_nfeas=jnp.zeros((P,), I64),
        out_rc=jnp.zeros((P, N_DIAG), I64),
    )
    ck_keys = _CK_KEYS + (_CK_DRA_KEYS if has_dra else ())
    if has_dra:
        init["free"] = free0
        init["claim_node"] = claim_node0
    for k in ck_keys:
        init["ck_" + k] = init[k]

    def step(state, p):
        in_gang = gang_id[p] >= 0
        is_first = gang_first[p] & in_gang
        # gang checkpoint: snapshot the ENTIRE carried state at the first
        # member so a failed gang restores wholesale (usage, topology
        # counts, allocation carries, assignments)
        ck = {
            k: jnp.where(is_first, state[k], state["ck_" + k])
            for k in ck_keys
        }

        if C:
            sdyn = wave.factored_spread_dyn(
                g, p, tid_sp, state["cnt_sp"], d_cap
            )
        else:
            sdyn = zero_sdyn()
        if AT:
            idyn, ip_aux = wave.factored_interpod_dyn(
                g,
                db,
                p,
                tid_ip,
                ip_cdv_tab,
                d2_cap,
                hostname_key,
                state["cnt_ip"],
                state["rev_cnt"],
                m_ip_all,
                t_anti,
                t_w,
            )
        else:
            idyn = zero_idyn()
            ip_aux = None

        if has_dra:
            m_extra, take_p = dra_mask_take(
                p, state["free"], state["claim_node"]
            )
        else:
            m_extra, take_p = true_n, None
        hv = build_hv(p, sdyn, idyn, m_extra)
        new_state, (choice, n_feas, reason_counts) = gang.pod_step(
            dc, db, g, p, state, hv, jnp.asarray(True), **step_kw
        )

        new_state.update(
            wave.factored_carry_update(
                {k: state[k] for k in ("cnt_sp", "cnt_ip", "rev_cnt")},
                p,
                choice,
                m_sp_all,
                m_ip_all,
                ip_aux,
            )
        )
        if has_dra:
            new_state["free"], new_state["claim_node"] = dra_ops.dra_commit(
                state["free"],
                state["claim_node"],
                choice,
                take_p,
                ref_cl[p],
            )

        # gang bookkeeping: landed counter resets at the first member; the
        # last member's verdict admits or rolls back the whole gang
        landed = jnp.where(is_first, 0, state["gang_landed"]) + (
            (choice >= 0) & in_gang
        ).astype(I32)
        is_last = gang_last[p] & in_gang
        fail = is_last & (landed < gang_need[p])
        for k in ck_keys:
            new_state[k] = jnp.where(fail, ck[k], new_state[k])
            new_state["ck_" + k] = ck[k]
        gid_oh = (jnp.arange(g_cap, dtype=I32) == gang_id[p]) & is_last
        new_state["gang_admit"] = jnp.where(
            gid_oh, jnp.where(fail, 0, 1), state["gang_admit"]
        )
        new_state["gang_landed_out"] = jnp.where(
            gid_oh, landed, state["gang_landed_out"]
        )
        new_state["gang_landed"] = landed
        # p in range by construction; mode="drop" for the clamp rule
        new_state["out_raw"] = state["out_raw"].at[p].set(choice, mode="drop")
        new_state["out_nfeas"] = (
            state["out_nfeas"].at[p].set(n_feas, mode="drop")
        )
        new_state["out_rc"] = (
            state["out_rc"].at[p].set(reason_counts, mode="drop")
        )
        return new_state, None

    state, _ = jax.lax.scan(step, init, jnp.arange(P, dtype=I32))
    raw = state["out_raw"]
    n_feas = state["out_nfeas"]
    reason_counts = state["out_rc"]
    tallies = {
        "requested": state["requested"],
        "nonzero": state["nonzero"],
        "num_pods": state["num_pods"],
    }
    wl = {
        "spec": c0,
        "raw": raw,
        "gang_admit": state["gang_admit"],
        "gang_landed": state["gang_landed_out"],
        "claim_node": state["claim_node"] if has_dra else claim_node0,
    }
    return state["assigned"], n_feas, reason_counts, tallies, wl


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch, hostname_key=i32, extra_mask=bool[P,N])
# ktpu: axes(tid_sp=i32[P,C], rep_sp_p=i32[Tsp], rep_sp_c=i32[Tsp])
# ktpu: axes(tid_ip=i32[P,A], rep_ip_p=i32[Tip], rep_ip_u=i32[Tip], ip_cdv_tab=i32[Kd2,N])
# ktpu: axes(gang_id=i32[P], gang_first=bool[P], gang_last=bool[P], gang_need=i32[P])
# ktpu: axes(dev_key=i32[N,DD,DA], dev_val=i32[N,DD,DA], dev_valid=bool[N,DD], free0=bool[N,DD])
# ktpu: axes(sel_key=i32[P,DQ,DS], sel_op=i32[P,DQ,DS], sel_vals=i32[P,DQ,DS,DV])
# ktpu: axes(req_count=i32[P,DQ], req_all=bool[P,DQ], req_cl=i32[P,DQ], req_bad=bool[P,DQ])
# ktpu: axes(q_valid=bool[P,DQ], ref_cl=i32[P,CQ], claim_node0=i32[CL])
# ktpu: axes(vol_table=DTable[P,PV2,VT], vol_valid=bool[P,PV2], vol_bad=bool[P])
# ktpu: axes(nom_node=i32[G], nom_prio=i32[G], nom_req=i32[G,Rn], extra_score=i64[P,N])
# ktpu: axes(sp_keys=i32[Kd], sp_cdv_tab=i32[Kd,N], ip_keys=i32[Kd2])
# ktpu: accum(i64, i32, bool)
# ktpu: static(v_cap=16, g_cap=4)
@functools.partial(
    jax.jit,
    static_argnames=(
        "v_cap",
        "g_cap",
        "hard_pod_affinity_weight",
        "has_interpod",
        "has_spread",
        "has_images",
        "enabled",
        "weights",
        "d_cap",
        "d2_cap",
        "fit_strategy",
    ),
)
def workloads_run(
    dc: DeviceCluster,
    db: DeviceBatch,
    hostname_key,
    v_cap: int,
    g_cap: int,
    tid_sp,
    rep_sp_p,
    rep_sp_c,
    tid_ip,
    rep_ip_p,
    rep_ip_u,
    ip_cdv_tab,
    gang_id,
    gang_first,
    gang_last,
    gang_need,
    dev_key=None,
    dev_val=None,
    dev_valid=None,
    free0=None,
    sel_key=None,
    sel_op=None,
    sel_vals=None,
    req_count=None,
    req_all=None,
    req_cl=None,
    req_bad=None,
    q_valid=None,
    ref_cl=None,
    claim_node0=None,
    vol_table=None,
    vol_valid=None,
    vol_bad=None,
    hard_pod_affinity_weight: int = 1,
    has_interpod: bool = True,
    has_spread: bool = True,
    has_images: bool = True,
    enabled: frozenset = F.ALL_FILTER_KERNELS,
    weights: tuple = gang.DEFAULT_WEIGHTS,
    extra_mask=None,
    nom_node=None,
    nom_prio=None,
    nom_req=None,
    sp_keys=None,
    sp_cdv_tab=None,
    ip_keys=None,
    d_cap: int = 8,
    d2_cap: int = 8,
    extra_score=None,
    fit_strategy: tuple = gang.DEFAULT_FIT_STRATEGY,
):
    """Fused precompute + workloads admission: ONE device dispatch per
    batch (the workloads counterpart of wave.wave_run — eligibility
    guarantees no in-batch host ports, so the port axis is compiled out).
    The volume-topology kernel mask evaluates in-dispatch and folds into
    the precompute's extra mask, so volume rejections carry the host-veto
    diagnosis lane like any stateful-plugin veto."""
    if vol_table is not None:
        vmask = volume_topology_mask(dc, vol_table, vol_valid, vol_bad)
        extra_mask = vmask if extra_mask is None else (extra_mask & vmask)
    g = gang.precompute(
        dc,
        db,
        hostname_key,
        v_cap,
        hard_pod_affinity_weight,
        has_interpod=has_interpod,
        has_spread=has_spread,
        has_ports=False,
        has_images=has_images,
        enabled=enabled,
        extra_mask=extra_mask,
        sp_keys=sp_keys,
        sp_cdv_tab=sp_cdv_tab,
        ip_keys=ip_keys,
    )
    return workloads_schedule(
        dc,
        db,
        g,
        hostname_key,
        v_cap,
        g_cap,
        tid_sp,
        rep_sp_p,
        rep_sp_c,
        tid_ip,
        rep_ip_p,
        rep_ip_u,
        ip_cdv_tab,
        gang_id,
        gang_first,
        gang_last,
        gang_need,
        dev_key=dev_key,
        dev_val=dev_val,
        dev_valid=dev_valid,
        free0=free0,
        sel_key=sel_key,
        sel_op=sel_op,
        sel_vals=sel_vals,
        req_count=req_count,
        req_all=req_all,
        req_cl=req_cl,
        req_bad=req_bad,
        q_valid=q_valid,
        ref_cl=ref_cl,
        claim_node0=claim_node0,
        weights=weights,
        check_fit="NodeResourcesFit" in enabled,
        nom_node=nom_node,
        nom_prio=nom_prio,
        nom_req=nom_req,
        d_cap=d_cap,
        d2_cap=d2_cap,
        extra_score=extra_score,
        fit_strategy=fit_strategy,
    )
