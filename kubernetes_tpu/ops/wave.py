"""Speculative wave dispatch for cross-pod-constraint batches.

Pods with PodTopologySpread / inter-pod-affinity terms pay a serial data
dependency: each placement mutates the topology counts the next pod's
verdict reads, so the gang scan (ops/gang.py) re-derives every pod's
batch-peer counts from the full ``[C, N, J]`` / ``[AT, N, J]`` peer
contractions, once per scan step.  That per-step volume — not the verdict
math — is what makes the spread/inter-pod configs the slowest lines in the
bench.  This module replaces it with a two-pass wave:

  1. **Speculation** — the entire wave is evaluated as one parallel
     ``(P × N)`` pass against the FROZEN snapshot (zero intra-batch
     deltas): a vmap of the shared per-pod verdict (gang.pod_step), giving
     every pod a candidate placement as if it were first in line.

  2. **Conflict resolution / admission** — a device-side pass that
     recomputes each pod's verdict and argmax under the wave's combined
     usage + topology-count deltas, in queue order.  Its carried state is
     NOT the peer list but a **term-factored delta algebra**: the host
     interaction partitioner dedups the batch's constraint terms into
     ``T ≪ P`` distinct (selector, namespace, topology-key) terms, and the
     pass carries per-term per-node counts ``[T, N]`` (+ per-term
     domain-spread rows for the symmetric inter-pod direction).  Each
     step's batch-peer counts come from ``[C, N, d_cap]``-shaped dense
     compare+reduce over those carries — O(T·N + C·N·D) per pod instead of
     O((C+AT)·N·J) — and commits update the carries with dense rank-1
     outer products (no scatters).

**Admission invariant.**  The admission pass replays the exact serial
recurrence ``choice_i = F_i(S + Σ_{j<i} Δ(choice_j))`` — the unique fixed
point of the wave's combined-delta re-evaluation — so its placements are
bit-identical to processing the wave's pods one at a time in queue order
(the parity oracle's order).  A pod whose speculative candidate survives
the recomputation is **admitted as speculated**; a pod whose candidate is
invalidated by the wave's combined deltas is **demoted** — its corrected
placement still lands in the same dispatch (the next "wave" of the fixed
point is evaluated in place), and the demotion is surfaced to the host
with the conflicting constraint kind + term for the flight recorder /
wave-conflict metrics.  Fully disjoint footprints admit the whole wave at
its speculative placements; fully shared footprints degenerate to the
serial recurrence — exactly the gang scan's semantics at a fraction of its
per-step cost.

**Fallback ladder.**  The factored algebra expresses the whole hot path:
in-batch host-port users ride a dedicated ``[Tpt, N]`` port-occupancy
carry (distinct (proto, port, hostIP-class) tuples dedup into ``Tpt ≪ P``
port terms whose pairwise conflicts are a static host-built matrix), and
sampling-compat / seeded-tie drains replay ``numFeasibleNodesToFind``'s
adaptive window and nodeTree rotation per step (the sampling cut lives in
gang.pod_step and is carry-state, not peer-state, so the factored pass
reproduces it bit-exactly).  What remains off the wave: host-filter-
relevant, extender, and nominated pods take the one-pod paths;
resource-only batches never get here (the signature fast path owns them);
duplicate hostname label values (two nodes claiming one hostname)
disqualify the wave — the factored hostname-topology counts assume node
identity ≡ hostname domain (the uniqueness bit is computed once per
snapshot by the mirror, not per batch).  Every fallback bumps
``scheduler_tpu_wave_fallback_total{reason=}``.

The verdict itself — filters, scores, normalization, tie-break — is the
SAME code as the scan path (gang.pod_step + gang.spread_constraints +
gang.interpod_constraints), so the paths cannot drift: only the production
of the batch-peer count tensors differs.  Equivalence is property-tested
against both gang_schedule and the serial oracle in tests/test_wave.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops import gang
from kubernetes_tpu.ops.gang import N_DIAG
from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster, I32, I64
from kubernetes_tpu.snapshot.interner import ABSENT, PAD
from kubernetes_tpu.snapshot.schema import N_FIXED_LANES, bucket_cap

# demote_kind codes in the wave stats row (host side maps to labels)
DEMOTE_NONE = 0
DEMOTE_SPREAD = 1
DEMOTE_AFFINITY = 2
DEMOTE_SCORE = 3
DEMOTE_FIT = 4
# not a demotion: infeasible in speculation, PLACED by the admission pass
# (a batch peer's commit satisfied a required affinity) — the wave upgraded
# the pod; reported separately, never as a conflict
DEMOTE_UPGRADE = 5
DEMOTE_PORTS = 6
DEMOTE_KINDS = {
    DEMOTE_SPREAD: "spread",
    DEMOTE_AFFINITY: "affinity",
    DEMOTE_SCORE: "score",
    DEMOTE_FIT: "fit",
    DEMOTE_PORTS: "ports",
}

# shard-rule roster: the admission scan's per-step work contracts the
# factored [T, N] carries over N ([C, N, d_cap] compare+reduce) and
# gathers the speculative node's row for demotion attribution.  These
# are the per-term reductions ROADMAP item 2 reduces ACROSS shards —
# the roster is the inventory of exactly where those collectives go.
_KTPU_N_COLLECTIVES = {
    "wave_schedule.step": "resolved(collective): term-factored domain "
    "compare+reduce over N + port-occupancy [Tpt, N] conflict reduce + "
    "speculative-node row gathers (demotion attribution) — the per-term "
    "[T,N]/[Tpt,N] carry counts are per-node integers that reduce "
    "cleanly across a sharded N axis: per-shard partial compare+psum at "
    "the conflict check, owning-shard gather for the speculative row, "
    "and rank-1 carry commits stay local to the shard that owns the "
    "committed node",
    "factored_port_mask": "resolved(collective): port-term occupancy "
    "conflict reduce over the carried [Tpt, N] rows — per-shard partial "
    "conflict bits + cross-shard or-reduce",
}


# ---------------------------------------------------------------------------
# Host-side interaction partitioner
# ---------------------------------------------------------------------------


def _dedup_slots(mat, live):
    """Row-dedup of a [S, W] content matrix over live slots.

    Returns (tid [S] i64 with -1 for dead slots, rep [T] flat indices of
    one representative live slot per distinct row).  Term ids follow
    np.unique's sorted row order — deterministic across hosts."""
    import numpy as np

    tid = np.full(mat.shape[0], -1, np.int64)
    if not live.any():
        return tid, np.zeros((0,), np.int64)
    rows = np.ascontiguousarray(mat[live])
    _, first, inv = np.unique(
        rows, axis=0, return_index=True, return_inverse=True
    )
    live_idx = np.nonzero(live)[0]
    tid[live_idx] = inv.reshape(-1)
    return tid, live_idx[first]


def _slot_content(n_slots, parts):
    """Stack per-slot content columns into one [n_slots, W] i64 matrix."""
    import numpy as np

    cols = [np.asarray(p, np.int64).reshape(n_slots, -1) for p in parts]
    return np.concatenate(cols, axis=1)


def wave_tables(pb, node_label_vals, hostname_id: int, hostnames_unique=None):
    """Dedup the batch's constraint terms into distinct-term tables — the
    host half of the interaction partitioner.

    Two pods share a spread term when (topology key, namespace, packed
    selector) coincide — then their batch-peer counts are the same counter;
    inter-pod terms additionally key on (kind, weight, namespace scope), so
    a term's symmetric weight and violation polarity are term constants.
    In-batch host ports dedup the same way: distinct (proto-port key,
    hostIP, wildcard) tuples become ``Tpt`` port terms with a static
    pairwise conflict matrix, so the admission pass carries per-term
    occupancy instead of the gang scan's pod×pod conflict matrix.

    Returns None only when the batch is not wave-eligible: duplicate
    hostname label values among nodes (the factored hostname-domain counts
    assume hostname ≡ node identity).  ``hostnames_unique`` is the
    once-per-snapshot bit from SnapshotMirror.hostnames_unique; None
    re-derives it here (standalone/test callers).  Otherwise a dict of
    device-ready arrays + static caps:

      tid_sp  i32 [P, C]   distinct spread-term id per slot (-1 empty)
      rep_sp_p/rep_sp_c  i32 [Tsp]  a representative slot per term
      tid_ip  i32 [P, AT]  distinct inter-pod-term id per slot
      rep_ip_p/rep_ip_u  i32 [Tip]
      ip_cdv_tab i32 [Kd2, N]  compact domain ids per inter-pod topology
                 key (row of -1 for the hostname key: identity domains)
      d2_cap  int  static bucket over inter-pod distinct-domain counts
      tid_pt  i32 [P, W]   distinct port-term id per want slot (-1 empty)
      port_conf bool [Tpt, Tpt]  static term-pair conflict matrix
      has_ports bool       batch carries in-batch host ports
      n_terms int  total distinct terms (spread + inter-pod + port)
    """
    import numpy as np

    lv = np.asarray(node_label_vals)
    n_cap, K = lv.shape
    if hostnames_unique is None and 0 <= hostname_id < K:
        col = lv[:, hostname_id]
        vals = col[col >= 0]
        hostnames_unique = len(vals) == len(np.unique(vals))
    if hostnames_unique is False:
        return None  # duplicate hostname labels: identity trick invalid

    P, C = np.asarray(pb.tsc_topo_key).shape
    AT = np.asarray(pb.aff_kind).shape[1]
    ns_id = np.asarray(pb.ns_id)
    tsc_topo = np.asarray(pb.tsc_topo_key)
    aff_kind = np.asarray(pb.aff_kind)
    valid = np.asarray(pb.valid)

    # distinct spread terms: (topology key, pod namespace, packed selector)
    if C:
        sp_content = _slot_content(
            P * C,
            [
                tsc_topo,
                np.broadcast_to(ns_id[:, None], (P, C)),
                pb.tsc_table.req_key,
                pb.tsc_table.req_op,
                pb.tsc_table.req_vals,
                pb.tsc_table.req_rhs,
                pb.tsc_table.term_valid,
            ],
        )
        sp_live = (tsc_topo != PAD).reshape(-1) & np.repeat(valid, C)
        tid_flat, rep_flat = _dedup_slots(sp_content, sp_live)
    else:
        tid_flat = np.zeros((0,), np.int64)
        rep_flat = np.zeros((0,), np.int64)
    tid_sp = tid_flat.reshape(P, C).astype(np.int32)
    t_sp = bucket_cap(max(len(rep_flat), 1), 1)
    rep_sp_p = np.full(t_sp, -1, np.int32)
    rep_sp_c = np.zeros(t_sp, np.int32)
    rep_sp_p[: len(rep_flat)] = rep_flat // C if C else 0
    rep_sp_c[: len(rep_flat)] = rep_flat % C if C else 0
    n_sp = len(rep_flat)

    # distinct inter-pod terms: kind/weight/ns-scope are part of the
    # identity so a term's symmetric weight and polarity are constants
    if AT:
        ip_content = _slot_content(
            P * AT,
            [
                aff_kind,
                pb.aff_topo_key,
                pb.aff_weight,
                pb.aff_ns_all,
                pb.aff_ns_ids,
                pb.aff_table.req_key,
                pb.aff_table.req_op,
                pb.aff_table.req_vals,
                pb.aff_table.req_rhs,
                pb.aff_table.term_valid,
            ],
        )
        ip_live = (aff_kind != PAD).reshape(-1) & np.repeat(valid, AT)
        tid_flat, rep_flat = _dedup_slots(ip_content, ip_live)
    else:
        tid_flat = np.zeros((0,), np.int64)
        rep_flat = np.zeros((0,), np.int64)
    tid_ip = tid_flat.reshape(P, AT).astype(np.int32)
    t_ip = bucket_cap(max(len(rep_flat), 1), 1)
    rep_ip_p = np.full(t_ip, -1, np.int32)
    rep_ip_u = np.zeros(t_ip, np.int32)
    rep_ip_p[: len(rep_flat)] = rep_flat // AT if AT else 0
    rep_ip_u[: len(rep_flat)] = rep_flat % AT if AT else 0
    n_ip = len(rep_flat)

    # distinct port terms: (proto-port key, hostIP, wildcard) — the same
    # content identity node_ports.go compares; the pairwise conflict rule
    # (same proto-port ∧ (same IP ∨ either wildcard)) is evaluated ONCE
    # over the Tpt ≪ P·W distinct tuples instead of per pod pair
    want_ppk = np.asarray(pb.want_ppk)
    W = want_ppk.shape[1]
    n_pt = 0
    if W and (want_ppk != PAD).any():
        pt_content = _slot_content(
            P * W, [want_ppk, pb.want_ip, pb.want_wild]
        )
        pt_live = (want_ppk != PAD).reshape(-1) & np.repeat(valid, W)
        tid_flat, rep_flat = _dedup_slots(pt_content, pt_live)
        tid_pt = tid_flat.reshape(P, W).astype(np.int32)
        n_pt = len(rep_flat)
        t_pt = bucket_cap(max(n_pt, 1), 1)
        r_ppk = want_ppk.reshape(-1)[rep_flat]
        r_ip = np.asarray(pb.want_ip).reshape(-1)[rep_flat]
        r_wild = np.asarray(pb.want_wild).reshape(-1)[rep_flat]
        port_conf = np.zeros((t_pt, t_pt), bool)
        port_conf[:n_pt, :n_pt] = (r_ppk[:, None] == r_ppk[None, :]) & (
            (r_ip[:, None] == r_ip[None, :])
            | r_wild[:, None]
            | r_wild[None, :]
        )
    else:
        tid_pt = np.full((P, W), -1, np.int32)
        port_conf = np.zeros((1, 1), bool)

    # Compact per-key domain ids for the inter-pod keys, batch_tables-style
    # (same distinct-key ordering as gang.batch_tables so g.ip_key_idx rows
    # index both tables).  The hostname key keeps a -1 row: its domains are
    # node identities and never ride the [.., d2_cap] compare+reduce.
    ip_keys = np.unique(np.asarray(pb.aff_topo_key).reshape(-1))
    ip_keys = [int(k) for k in ip_keys if 0 <= int(k) < K]
    kd2 = bucket_cap(max(len(ip_keys), 1), 1)
    ip_cdv_tab = np.full((kd2, n_cap), -1, np.int32)
    d2_max = 1
    for i, k in enumerate(ip_keys):
        if k == hostname_id:
            continue
        col = lv[:, k]
        pos = col >= 0
        if pos.any():
            uniq, inv = np.unique(col[pos], return_inverse=True)
            ip_cdv_tab[i, pos] = inv.astype(np.int32)
            d2_max = max(d2_max, len(uniq))

    return dict(
        tid_sp=jnp.asarray(tid_sp),
        rep_sp_p=jnp.asarray(rep_sp_p),
        rep_sp_c=jnp.asarray(rep_sp_c),
        tid_ip=jnp.asarray(tid_ip),
        rep_ip_p=jnp.asarray(rep_ip_p),
        rep_ip_u=jnp.asarray(rep_ip_u),
        ip_cdv_tab=jnp.asarray(ip_cdv_tab),
        d2_cap=bucket_cap(d2_max, 8),
        tid_pt=jnp.asarray(tid_pt),
        port_conf=jnp.asarray(port_conf),
        has_ports=n_pt > 0,
        n_terms=n_sp + n_ip + n_pt,
    )


def interaction_groups(pods):
    """Partition a batch into components of mutually-interacting pods by
    topology-term / affinity-probe footprint (fastpath-style host probes).

    Two pods land in one group when they share a constraint term
    (spec-content identity) or one pod's term selector ADMITS the other
    (the probe direction — anti-affinity constrains pods that carry no
    terms themselves).  Conservative by construction: probes may claim
    interaction where none exists, never the reverse.  Non-interacting
    groups' placements are independent post-decision, so their binding
    runs flow through the bulk-commit path concurrently.

    Returns (group_id per pod, n_groups).
    """
    from kubernetes_tpu.fastpath import _pod_probes

    n = len(pods)
    parent = list(range(n))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a: int, b: int) -> None:
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[rb] = ra

    def sel_key(sel):
        """Hashable content key of a LabelSelector (match_labels is a
        plain dict, so the dataclass itself doesn't hash)."""
        if sel is None:
            return None
        return (
            tuple(sorted((sel.match_labels or {}).items())),
            tuple(sel.match_expressions or ()),
        )

    # dedup probes by content so template-stamped pods share one probe and
    # the admits sweep runs per (probe, label-group) pair, not per pod²
    probe_owner: dict = {}
    probes = []  # (owner pod index, probe) — distinct by content
    for i, pod in enumerate(pods):
        for pr in _pod_probes(pod):
            try:
                key = (pr.ns_any, pr.namespaces, sel_key(pr.sel))
                hash(key)
            except TypeError:
                key = None
            if key is None:
                probes.append((i, pr))
                continue
            owner = probe_owner.get(key)
            if owner is None:
                probe_owner[key] = i
                probes.append((i, pr))
            else:
                union(i, owner)  # same term content ⇒ same group
    # The admits sweep memoizes by (namespace, labels) group; batches of
    # pods with DISTINCT label sets defeat the cache, so bound the worst
    # case: past ~100k (probe, pod) pairs fall back to one conservative
    # all-interacting component (a single bulk run — always safe).
    if len(probes) * n > 100_000:
        return [0] * n, 1
    hit_cache: dict = {}
    for i, pod in enumerate(pods):
        try:
            lg = (pod.namespace, tuple(sorted(pod.labels.items())))
        except TypeError:
            lg = None
        hits = hit_cache.get(lg) if lg is not None else None
        if hits is None:
            hits = [j for j, (_, pr) in enumerate(probes) if pr.admits(pod)]
            if lg is not None:
                hit_cache[lg] = hits
        for j in hits:
            union(i, probes[j][0])
    roots: dict = {}
    gids = []
    for i in range(n):
        r = find(i)
        gids.append(roots.setdefault(r, len(roots)))
    return gids, len(roots)


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------


def _rep_rows(mat, rp, rc):
    """mat[rp, rc] with -1 representatives masked to zeros/False."""
    safe_p = jnp.clip(rp, 0, mat.shape[0] - 1)
    safe_c = jnp.clip(rc, 0, mat.shape[1] - 1)
    rows = mat[safe_p, safe_c]
    live = rp >= 0
    if rows.dtype == jnp.bool_:
        return rows & live.reshape(live.shape + (1,) * (rows.ndim - 1))
    return rows * live.reshape(live.shape + (1,) * (rows.ndim - 1)).astype(
        rows.dtype
    )


# ---------------------------------------------------------------------------
# The term-factored delta algebra, factored out of the admission scan so
# every serial-recurrence replayer shares ONE definition: wave_schedule's
# conflict-resolution pass below and the workloads tier's gang/DRA
# admission scan (ops/coscheduling.py) produce pod p's batch-peer count
# tensors from the SAME [T, N] carries and commit them through the SAME
# factored_carry_update entry point (whose usage-row twin is
# common.usage_carry_update, called from gang.pod_step) — the paths
# cannot drift.
# ---------------------------------------------------------------------------


def term_match_rows(g, rep_sp_p, rep_sp_c, rep_ip_p, rep_ip_u):
    """Per-dispatch gathers from the statics: which batch pods each
    distinct term matches (the forward AND reverse match matrix —
    ip_bmatch[p,u,j] reads "pod j matches p's term u", so one gather
    serves both sides).  Shared by the wave and workloads admission
    scans.  Returns (m_sp_all [Tsp,P], m_ip_all [Tip,P], t_anti [Tip],
    t_w [Tip] i64)."""
    P = g.static_mask.shape[0]
    C = g.sp_dv.shape[1]
    AT = g.ip_dv.shape[1]
    Tsp = rep_sp_p.shape[0]
    Tip = rep_ip_p.shape[0]
    if C:
        m_sp_all = _rep_rows(g.sp_bmatch, rep_sp_p, rep_sp_c)
    else:
        m_sp_all = jnp.zeros((Tsp, P), bool)
    if AT:
        m_ip_all = _rep_rows(g.ip_bmatch, rep_ip_p, rep_ip_u)
        t_anti = _rep_rows(g.ip_is_anti, rep_ip_p, rep_ip_u)
        t_w = _rep_rows(g.ip_sym_w, rep_ip_p, rep_ip_u)
    else:
        m_ip_all = jnp.zeros((Tip, P), bool)
        t_anti = jnp.zeros((Tip,), bool)
        t_w = jnp.zeros((Tip,), I64)
    return m_sp_all, m_ip_all, t_anti, t_w


def factored_carry_init(Tsp, Tip, N, Tpt=0):
    """Zero factored carries for one admission scan.  Keys present in the
    returned dict are exactly the keys factored_carry_update advances —
    callers thread them through their scan state wholesale."""
    out = dict(
        cnt_sp=jnp.zeros((Tsp, N), I32),
        cnt_ip=jnp.zeros((Tip, N), I32),
        rev_cnt=jnp.zeros((Tip, N), I32),
    )
    if Tpt:
        out["occ_pt"] = jnp.zeros((Tpt, N), I32)
    return out


FACTORED_CARRY_KEYS = ("cnt_sp", "cnt_ip", "rev_cnt", "occ_pt")


def factored_port_mask(tid_pt, port_conf, occ_pt, p):
    """NodePorts verdict for pod p from the factored port-occupancy carry.

    tid_pt [P, W] maps p's want slots onto distinct port-term ids;
    port_conf [Tpt, Tpt] is the static term-pair conflict matrix;
    occ_pt [Tpt, N] carries committed-peer port occupancy.  Returns
    (m_portb [N], pt_cnt [Tpt] — p's own per-term slot counts, the aux
    factored_carry_update commits)."""
    Tpt = occ_pt.shape[0]
    tidw = tid_pt[p]  # [W]
    ohw = (
        (tidw[:, None] == jnp.arange(Tpt, dtype=I32)[None, :])
        & (tidw >= 0)[:, None]
    )  # [W, Tpt]
    mine = jnp.any(ohw, axis=0)  # [Tpt] terms p requests
    conf_p = jnp.any(mine[:, None] & port_conf, axis=0)  # [Tpt]
    blocked = jnp.any(conf_p[:, None] & (occ_pt > 0), axis=0)  # [N]
    # dtype pinned: an i32 sum promotes to i64 under x64, which would
    # drift the occ_pt carry's dtype across scan steps
    return ~blocked, jnp.sum(ohw.astype(I32), axis=0).astype(I32)


def factored_spread_dyn(g, p, tid_sp, cnt_sp, d_cap: int):
    """SpreadDyn for pod p from the factored spread carries.

    tid_sp [P, C] maps p's constraint slots onto distinct-term ids;
    cnt_sp [Tsp, N] carries per-term committed-peer counts."""
    Tsp = cnt_sp.shape[0]
    d_ids = jnp.arange(d_cap, dtype=I32)
    tid = tid_sp[p]  # [C]
    ohc = (
        (tid[:, None] == jnp.arange(Tsp, dtype=I32)[None, :])
        & (tid >= 0)[:, None]
    ).astype(I32)
    cnt_rows = jnp.einsum("ct,tn->cn", ohc, cnt_sp)  # [C,N]
    te = g.sp_te[p].astype(I32)
    cting = g.sp_counting[p].astype(I32)
    cdv = g.sp_cdv[p]
    dom_oh = (
        (cdv[:, :, None] == d_ids[None, None, :])
        & (cdv >= 0)[:, :, None]
    ).astype(I32)  # [C, N, D]
    g1 = jnp.einsum("cn,cnd->cd", cnt_rows * te, dom_oh)
    g2 = jnp.einsum("cn,cnd->cd", cnt_rows * cting, dom_oh)
    dyn_f_dom = jnp.einsum("cd,cnd->cn", g1, dom_oh)
    dyn_dom = jnp.einsum("cd,cnd->cn", g2, dom_oh)
    present = (g.sp_dv[p] >= 0).astype(I32)
    dyn_f = jnp.where(
        g.sp_is_host[p][:, None], cnt_rows * te * present, dyn_f_dom
    )
    return gang.SpreadDyn(dyn_f, cnt_rows, dyn_dom)


def factored_interpod_dyn(
    g,
    db,
    p,
    tid_ip,
    ip_cdv_tab,
    d2_cap: int,
    hostname_key,
    cnt_ip,
    rev_cnt,
    m_ip_all,
    t_anti,
    t_w,
):
    """InterpodDyn for pod p from the factored inter-pod carries, plus the
    aux tuple factored_carry_update needs to spread p's own committed terms
    over their topology domains (ohu, cdv2, dvip, is_host_u, ki)."""
    Tip = cnt_ip.shape[0]
    Kd2 = ip_cdv_tab.shape[0]
    d2_ids = jnp.arange(d2_cap, dtype=I32)
    tidu = tid_ip[p]  # [AT]
    ohu = (
        (tidu[:, None] == jnp.arange(Tip, dtype=I32)[None, :])
        & (tidu >= 0)[:, None]
    ).astype(I32)
    fcnt = jnp.einsum("ut,tn->un", ohu, cnt_ip)  # [AT,N]
    ki = g.ip_key_idx[p]  # [AT]
    cdv2 = ip_cdv_tab[jnp.clip(ki, 0, Kd2 - 1)]  # [AT, N]
    cdv2 = jnp.where((ki >= 0)[:, None], cdv2, -1)
    dom2 = (
        (cdv2[:, :, None] == d2_ids[None, None, :])
        & (cdv2 >= 0)[:, :, None]
    ).astype(I32)  # [AT, N, D2]
    gf = jnp.einsum("un,und->ud", fcnt, dom2)
    ip_dyn_dom = jnp.einsum("ud,und->un", gf, dom2)
    dvip = g.ip_dv[p]
    is_host_u = db.aff_topo[p] == hostname_key  # [AT]
    ip_dyn = jnp.where(
        is_host_u[:, None], fcnt * (dvip >= 0).astype(I32), ip_dyn_dom
    )
    any_dyn = jnp.any(g.ip_is_aff[p] & (jnp.sum(fcnt, axis=1) > 0))
    m_rev = m_ip_all[:, p]  # [Tip]
    viol_b = jnp.any(
        (m_rev & t_anti)[:, None] & (rev_cnt > 0), axis=0
    )
    sym_b = jnp.sum(
        jnp.where(
            m_rev[:, None],
            t_w[:, None] * rev_cnt.astype(I64),
            0,
        ),
        axis=0,
    )
    idyn = gang.InterpodDyn(ip_dyn, viol_b, sym_b, any_dyn)
    return idyn, (ohu, cdv2, dvip, is_host_u, ki)


def factored_carry_update(
    carries, p, choice, m_sp_all, m_ip_all, ip_aux, pt_cnt=None
):
    """Commit pod p's placement into the factored carries — THE shared
    carry-update entry point of every factored admission scan (the wave's
    conflict-resolution pass and the workloads gang/DRA scan): dense
    rank-1 outer products, no scatters.  ``carries`` holds the keys
    factored_carry_init produced; ``ip_aux`` is factored_interpod_dyn's
    aux tuple (None when the batch carries no inter-pod terms) and
    ``pt_cnt`` factored_port_mask's per-term slot counts (None when the
    batch carries no in-batch host ports)."""
    cnt_sp = carries["cnt_sp"]
    cnt_ip = carries["cnt_ip"]
    rev_cnt = carries["rev_cnt"]
    N = cnt_sp.shape[1]
    n_ids = jnp.arange(N, dtype=I32)
    committed = choice >= 0
    onehot_n = ((n_ids == choice) & committed).astype(I32)
    out = dict(
        cnt_sp=cnt_sp + m_sp_all[:, p, None].astype(I32) * onehot_n[None, :],
        cnt_ip=cnt_ip + m_ip_all[:, p, None].astype(I32) * onehot_n[None, :],
        rev_cnt=rev_cnt,
    )
    if pt_cnt is not None:
        out["occ_pt"] = carries["occ_pt"] + pt_cnt[:, None] * onehot_n[None, :]
    if ip_aux is None:
        return out
    ohu, cdv2, dvip, is_host_u, ki = ip_aux
    # p's own terms spread over their topology domains (the
    # reverse/symmetric direction future steps read back)
    val2_at = jnp.sum(
        jnp.where(onehot_n[None, :] > 0, cdv2, 0), axis=1
    )  # [AT] compact id at the chosen node
    dval_at = jnp.sum(
        jnp.where(onehot_n[None, :] > 0, dvip, 0), axis=1
    )  # [AT] label value at the chosen node
    dom_row = jnp.where(
        is_host_u[:, None],
        (onehot_n > 0)[None, :] & (dval_at >= 0)[:, None],
        (cdv2 == val2_at[:, None])
        & (cdv2 >= 0)
        & (val2_at >= 0)[:, None],
    )
    dom_row = dom_row & committed & (ki >= 0)[:, None]
    out["rev_cnt"] = rev_cnt + jnp.einsum(
        "ut,un->tn", ohu, dom_row.astype(I32)
    )
    return out


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch, g=GangStatics, hostname_key=i32)
# ktpu: axes(tid_sp=i32[P,C], rep_sp_p=i32[Tsp], rep_sp_c=i32[Tsp])
# ktpu: axes(tid_ip=i32[P,A], rep_ip_p=i32[Tip], rep_ip_u=i32[Tip], ip_cdv_tab=i32[Kd2,N])
# ktpu: axes(tid_pt=i32[P,UP], port_conf=bool[Tpt,Tpt])
# ktpu: axes(nom_node=i32[G], nom_prio=i32[G], nom_req=i32[G,Rn], extra_score=i64[P,N])
# ktpu: axes(sample_k=i32, sample_start=i32, tie_key=key, attempt_base=i32)
# ktpu: accum(i64, i32, bool)
# ktpu: static(v_cap=16)
@functools.partial(
    jax.jit,
    static_argnames=(
        "v_cap",
        "weights",
        "check_fit",
        "d_cap",
        "d2_cap",
        "fit_strategy",
        "has_ports",
    ),
)
def wave_schedule(
    dc: DeviceCluster,
    db: DeviceBatch,
    g: gang.GangStatics,
    hostname_key,
    v_cap: int,
    tid_sp,
    rep_sp_p,
    rep_sp_c,
    tid_ip,
    rep_ip_p,
    rep_ip_u,
    ip_cdv_tab,
    weights: tuple = gang.DEFAULT_WEIGHTS,
    check_fit: bool = True,
    nom_node=None,
    nom_prio=None,
    nom_req=None,
    d_cap: int = 8,
    d2_cap: int = 8,
    extra_score=None,
    fit_strategy: tuple = gang.DEFAULT_FIT_STRATEGY,
    has_ports: bool = False,
    tid_pt=None,
    port_conf=None,
    sample_k=None,
    sample_start=None,
    tie_key=None,
    attempt_base=None,
):
    """One fused wave dispatch: speculation + factored admission pass.

    ``has_ports`` (static) compiles in the [Tpt, N] port-occupancy carry
    for in-batch host-port users; ``sample_k``/``sample_start``/
    ``tie_key``/``attempt_base`` opt into the bit-compat sampling and
    seeded-tie modes exactly as gang_schedule does — the sampling window,
    nodeTree rotation cursor, and tie-break live in gang.pod_step and read
    only carried state, so the factored pass replays them bit-exactly
    (``tallies["sample_start"]`` returns the advanced cursor).

    Returns (chosen [P], n_feas [P], reason_counts [P, ND], tallies,
    stats [3, P]) where stats rows are (speculative choice, demote kind,
    conflicting term slot) — ``chosen == stats[0]`` per pod is the
    admitted-as-speculated mask the host turns into wave metrics."""
    P, N = g.static_mask.shape
    C = g.sp_dv.shape[1]
    AT = g.ip_dv.shape[1]
    Tsp = rep_sp_p.shape[0]
    Tip = rep_ip_p.shape[0]
    Kd2 = ip_cdv_tab.shape[0]
    Tpt = port_conf.shape[0] if has_ports else 0

    if nom_node is not None:
        nom_oh = (
            nom_node[:, None] == jnp.arange(N, dtype=I32)[None, :]
        ).astype(I32)  # [G, N]
    else:
        nom_oh = None

    true_n = jnp.ones((N,), bool)
    d_ids = jnp.arange(d_cap, dtype=I32)
    d2_ids = jnp.arange(d2_cap, dtype=I32)
    n_ids = jnp.arange(N, dtype=I32)

    m_sp_all, m_ip_all, t_anti, t_w = term_match_rows(
        g, rep_sp_p, rep_sp_c, rep_ip_p, rep_ip_u
    )

    def zero_sdyn():
        z = jnp.zeros((C, N), I32)
        return gang.SpreadDyn(z, z, z)

    def zero_idyn():
        return gang.InterpodDyn(
            jnp.zeros((AT, N), I32),
            jnp.zeros((N,), bool),
            jnp.zeros((N,), I64),
            jnp.asarray(False),
        )

    def build_hv(p, sdyn, idyn, m_portb):
        """hv dict for pod_step + attribution tensors (c_ok, anti_viol)."""
        if C:
            m_spread, sp_cnt, c_ok = gang.spread_constraints(db, g, p, sdyn)
        else:
            m_spread = true_n
            sp_cnt = jnp.zeros((C, N), I32)
            c_ok = jnp.ones((C, N), bool)
        if AT:
            m_interpod, ip_raw, anti_viol = gang.interpod_constraints(
                g, p, idyn
            )
        else:
            m_interpod = true_n
            ip_raw = g.ip_sym[p]
            anti_viol = jnp.zeros((AT, N), bool)
        hv = dict(
            m_portb=m_portb,
            m_spread=m_spread,
            sp_cnt=sp_cnt,
            m_interpod=m_interpod,
            ip_raw=ip_raw,
        )
        return hv, c_ok, anti_viol

    step_kw = dict(
        check_fit=check_fit,
        weights=weights,
        d_cap=d_cap,
        fit_strategy=fit_strategy,
        extra_score=extra_score,
        nom_oh=nom_oh,
        nom_prio=nom_prio,
        nom_req=nom_req,
        sample_k=sample_k,
        tie_key=tie_key,
        attempt_base=attempt_base,
    )

    base = dict(
        requested=dc.requested,
        nonzero=dc.nonzero_req,
        num_pods=dc.num_pods,
        assigned=jnp.full((P,), ABSENT, I32),
    )
    if sample_k is not None:
        base["sample_start"] = jnp.asarray(sample_start, I32)

    # ---- pass 1: speculation — the whole wave against the frozen snapshot
    # (in sampling mode every pod speculates from the INITIAL rotation
    # cursor — the admission pass alone carries the advancing cursor, and
    # speculation feeds only the stats/attribution outputs)
    def spec_one(p):
        hv, _, _ = build_hv(p, zero_sdyn(), zero_idyn(), true_n)
        _, (choice, _, _) = gang.pod_step(
            dc, db, g, p, base, hv, jnp.asarray(True), commit=False, **step_kw
        )
        return choice

    c0 = jax.vmap(spec_one)(jnp.arange(P, dtype=I32))

    # ---- pass 2: conflict resolution / admission over factored deltas
    init = dict(base, **factored_carry_init(Tsp, Tip, N, Tpt))
    # Per-pod outputs ride CARRY buffers written at the pod's own slot
    # instead of scan-stacked ys: jaxlib 0.4.37's SPMD partitioner
    # mis-clamps the ys-stacking dynamic_update_slice (the scan's s64
    # loop counter meets the partitioner's own s32 shard arithmetic in
    # one compare — hlo-verifier rejection after spmd-partitioning)
    # whenever sharding propagation partitions the stacking axis, and
    # replicated constraints on the scan outputs do not reach the
    # in-loop buffers.  Scatter-style carry writes at an i32 index
    # partition correctly — `assigned` has always used this pattern.
    init.update(
        out_choice=jnp.full((P,), ABSENT, I32),
        out_nfeas=jnp.zeros((P,), I64),
        out_rc=jnp.zeros((P, N_DIAG), I64),
        out_kind=jnp.zeros((P,), I32),
        out_cterm=jnp.full((P,), -1, I32),
    )
    carry_keys = FACTORED_CARRY_KEYS[:3] + (("occ_pt",) if Tpt else ())

    def step(state, p):
        if C:
            sdyn = factored_spread_dyn(g, p, tid_sp, state["cnt_sp"], d_cap)
        else:
            sdyn = zero_sdyn()

        if AT:
            idyn, ip_aux = factored_interpod_dyn(
                g,
                db,
                p,
                tid_ip,
                ip_cdv_tab,
                d2_cap,
                hostname_key,
                state["cnt_ip"],
                state["rev_cnt"],
                m_ip_all,
                t_anti,
                t_w,
            )
        else:
            idyn = zero_idyn()
            ip_aux = None

        if has_ports:
            m_portb, pt_cnt = factored_port_mask(
                tid_pt, port_conf, state["occ_pt"], p
            )
        else:
            m_portb, pt_cnt = true_n, None

        hv, c_ok, anti_viol = build_hv(p, sdyn, idyn, m_portb)
        new_state, (choice, n_feas, reason_counts) = gang.pod_step(
            dc, db, g, p, state, hv, jnp.asarray(True), **step_kw
        )

        # carry updates: dense rank-1 outer products, no scatters
        new_state.update(
            factored_carry_update(
                {k: state[k] for k in carry_keys},
                p,
                choice,
                m_sp_all,
                m_ip_all,
                ip_aux,
                pt_cnt=pt_cnt,
            )
        )

        # demotion attribution vs the speculative candidate: evaluated at
        # the pod's own step, where the carries are exactly the serial
        # prefix — "why this speculation failed in the serial order"
        spec = c0[p]
        spec_live = spec >= 0
        at = jnp.clip(spec, 0, N - 1)
        pt_bad = spec_live & ~m_portb[at]
        sp_bad = spec_live & ~hv["m_spread"][at]
        ip_bad = spec_live & ~hv["m_interpod"][at]
        # resource-contention demotion: earlier wave commits consumed the
        # speculative node (the dominant cause on tight clusters) —
        # checked against the PRE-commit state this pod's verdict saw.
        # Nominated-pod charges are not replayed here (attribution only;
        # a nomination-induced fit failure reports as "score").
        if check_fit:
            Rn = dc.requested.shape[1]
            Rp = db.requests.shape[1]
            req = db.requests[p]
            avail = dc.allocatable[at] - state["requested"][at]  # [Rn]
            if Rp > Rn:
                avail = jnp.concatenate(
                    [avail, jnp.zeros((Rp - Rn,), I32)]
                )
            scalar_lane = jnp.arange(Rp) >= N_FIXED_LANES
            conflict = (req > avail) & (~scalar_lane | (req > 0))
            lane_bad = jnp.any(conflict) & ~jnp.all(req == 0)
            pods_bad = state["num_pods"][at] + 1 > dc.allowed_pods[at]
            fit_bad = spec_live & (lane_bad | pods_bad)
        else:
            fit_bad = jnp.asarray(False)
        demoted = choice != spec
        kind = jnp.where(
            ~demoted,
            DEMOTE_NONE,
            jnp.where(
                ~spec_live,
                DEMOTE_UPGRADE,
                jnp.where(
                    pt_bad,
                    DEMOTE_PORTS,
                    jnp.where(
                        sp_bad,
                        DEMOTE_SPREAD,
                        jnp.where(
                            ip_bad,
                            DEMOTE_AFFINITY,
                            jnp.where(fit_bad, DEMOTE_FIT, DEMOTE_SCORE),
                        ),
                    ),
                ),
            ),
        ).astype(I32)
        if C:
            sp_viol = g.sp_hard[p] & ~c_ok[:, at]  # [C]
            sp_term = jnp.argmax(sp_viol).astype(I32)
            sp_term = jnp.where(jnp.any(sp_viol), sp_term, -1)
        else:
            sp_term = jnp.asarray(-1, I32)
        if AT:
            ip_viol = anti_viol[:, at]  # [AT]
            ip_term = jnp.argmax(ip_viol).astype(I32)
            ip_term = jnp.where(jnp.any(ip_viol), ip_term, -1)
        else:
            ip_term = jnp.asarray(-1, I32)
        cterm = jnp.where(
            kind == DEMOTE_SPREAD,
            sp_term,
            jnp.where(kind == DEMOTE_AFFINITY, ip_term, -1),
        )
        # p is the scan index over the batch axis — in range by
        # construction; mode="drop" spells it for the slice-clamp rule
        new_state["out_choice"] = (
            state["out_choice"].at[p].set(choice, mode="drop")
        )
        new_state["out_nfeas"] = (
            state["out_nfeas"].at[p].set(n_feas, mode="drop")
        )
        new_state["out_rc"] = (
            state["out_rc"].at[p].set(reason_counts, mode="drop")
        )
        new_state["out_kind"] = state["out_kind"].at[p].set(kind, mode="drop")
        new_state["out_cterm"] = (
            state["out_cterm"].at[p].set(cterm, mode="drop")
        )
        return new_state, None

    state, _ = jax.lax.scan(step, init, jnp.arange(P, dtype=I32))
    chosen = state["out_choice"]
    n_feas = state["out_nfeas"]
    reason_counts = state["out_rc"]
    kinds = state["out_kind"]
    cterms = state["out_cterm"]
    tallies = {
        "requested": state["requested"],
        "nonzero": state["nonzero"],
        "num_pods": state["num_pods"],
    }
    if sample_k is not None:
        tallies["sample_start"] = state["sample_start"]
    stats = jnp.stack([c0, kinds, cterms])  # [3, P]
    return chosen, n_feas, reason_counts, tallies, stats


# ktpu: axes(dc=DeviceCluster, db=DeviceBatch, hostname_key=i32, extra_mask=bool[P,N])
# ktpu: axes(tid_sp=i32[P,C], rep_sp_p=i32[Tsp], rep_sp_c=i32[Tsp])
# ktpu: axes(tid_ip=i32[P,A], rep_ip_p=i32[Tip], rep_ip_u=i32[Tip], ip_cdv_tab=i32[Kd2,N])
# ktpu: axes(tid_pt=i32[P,UP], port_conf=bool[Tpt,Tpt])
# ktpu: axes(nom_node=i32[G], nom_prio=i32[G], nom_req=i32[G,Rn], extra_score=i64[P,N])
# ktpu: axes(sp_keys=i32[Kd], sp_cdv_tab=i32[Kd,N], ip_keys=i32[Kd2])
# ktpu: axes(sample_k=i32, sample_start=i32, tie_key=key, attempt_base=i32)
# ktpu: accum(i64, i32, bool)
# ktpu: static(v_cap=16)
@functools.partial(
    jax.jit,
    static_argnames=(
        "v_cap",
        "hard_pod_affinity_weight",
        "has_interpod",
        "has_spread",
        "has_images",
        "enabled",
        "weights",
        "d_cap",
        "d2_cap",
        "fit_strategy",
        "has_ports",
    ),
)
def wave_run(
    dc: DeviceCluster,
    db: DeviceBatch,
    hostname_key,
    v_cap: int,
    tid_sp,
    rep_sp_p,
    rep_sp_c,
    tid_ip,
    rep_ip_p,
    rep_ip_u,
    ip_cdv_tab,
    hard_pod_affinity_weight: int = 1,
    has_interpod: bool = True,
    has_spread: bool = True,
    has_images: bool = True,
    enabled: frozenset = F.ALL_FILTER_KERNELS,
    weights: tuple = gang.DEFAULT_WEIGHTS,
    extra_mask=None,
    nom_node=None,
    nom_prio=None,
    nom_req=None,
    sp_keys=None,
    sp_cdv_tab=None,
    ip_keys=None,
    d_cap: int = 8,
    d2_cap: int = 8,
    extra_score=None,
    fit_strategy: tuple = gang.DEFAULT_FIT_STRATEGY,
    has_ports: bool = False,
    tid_pt=None,
    port_conf=None,
    sample_k=None,
    sample_start=None,
    tie_key=None,
    attempt_base=None,
):
    """Fused precompute + wave: ONE device dispatch per batch (the wave
    counterpart of gang.gang_run).  The gang scan's pod×pod port matrix
    stays compiled out (precompute has_ports=False): in-batch host ports
    ride the factored [Tpt, N] occupancy carry instead (``has_ports`` here
    gates THAT carry)."""
    g = gang.precompute(
        dc,
        db,
        hostname_key,
        v_cap,
        hard_pod_affinity_weight,
        has_interpod=has_interpod,
        has_spread=has_spread,
        has_ports=False,
        has_images=has_images,
        enabled=enabled,
        extra_mask=extra_mask,
        sp_keys=sp_keys,
        sp_cdv_tab=sp_cdv_tab,
        ip_keys=ip_keys,
    )
    return wave_schedule(
        dc,
        db,
        g,
        hostname_key,
        v_cap,
        tid_sp,
        rep_sp_p,
        rep_sp_c,
        tid_ip,
        rep_ip_p,
        rep_ip_u,
        ip_cdv_tab,
        weights=weights,
        check_fit="NodeResourcesFit" in enabled,
        nom_node=nom_node,
        nom_prio=nom_prio,
        nom_req=nom_req,
        d_cap=d_cap,
        d2_cap=d2_cap,
        extra_score=extra_score,
        fit_strategy=fit_strategy,
        has_ports=has_ports,
        tid_pt=tid_pt,
        port_conf=port_conf,
        sample_k=sample_k,
        sample_start=sample_start,
        tie_key=tie_key,
        attempt_base=attempt_base,
    )
