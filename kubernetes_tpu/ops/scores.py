"""Batched Score kernels → ``[P, N]`` integer scores + normalization.

Reproduces the default-profile scoring plugins (SURVEY.md §2.3) with the
reference's exact integer arithmetic wherever it is integer in Go, and
fixed-point int64 arithmetic where Go uses float64 (documented per kernel) —
float64 is unavailable on TPU, and float32 would drift from the golden model.
Scalar golden model: kubernetes_tpu.oracle.scores.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops.common import (
    DeviceBatch,
    DeviceCluster,
    I32,
    I64,
    eval_table,
    gather_at,
    per_node_counts,
)
from kubernetes_tpu.ops.filters import InterPodPre, SpreadPre
from kubernetes_tpu.snapshot.interner import ABSENT, PAD
from kubernetes_tpu.snapshot.schema import (
    EFFECT_ALL,
    EFFECT_PREFER_NO_SCHEDULE,
    LANE_CPU,
    LANE_MEM,
    TERM_PREFERRED_AFFINITY,
    TERM_PREFERRED_ANTI,
    TERM_REQUIRED_AFFINITY,
    TOL_OP_EXISTS,
)

MAX_NODE_SCORE = 100
_FX = 32  # fixed-point fractional bits for the spread log weights

# shard-rule roster (ANALYSIS.md): score NORMALIZATION is defined over
# the full feasible node set — min/max over N is inherent to the
# reference semantics (normalize_score.go) and becomes a cross-shard
# reduce on a sharded mesh; image spread counts nodes holding each image
_KTPU_N_COLLECTIVES = {
    "default_normalize": "resolved(collective): max over the feasible N "
    "axis (DefaultNormalizeScore) — cross-shard max-reduce of per-shard "
    "partial maxima (integer scores, order-free)",
    "normalize_interpod": "resolved(collective): min+max over the "
    "feasible N axis (scoring.go:265) — cross-shard min/max-reduce",
    "normalize_spread": "resolved(collective): min+max over the valid N "
    "axis (scoring.go:227) — cross-shard min/max-reduce",
    "score_image_locality": "resolved(collective): image spread counts "
    "nodes per image ([N] sum) — per-shard partial counts + psum",
    "score_spread": "resolved(collective): counted-node totals over the "
    "feasible N axis (topologyNormalizingWeight) — per-shard partial "
    "totals + psum",
}


def default_normalize(raw, feasible, reverse: bool = False):
    """plugins/helper/normalize_score.go DefaultNormalizeScore over the
    feasible set of each pod: score = 100·s/max (optionally reversed)."""
    raw = raw.astype(I64)
    mx = jnp.max(jnp.where(feasible, raw, 0), axis=1, keepdims=True)
    scaled = jnp.where(mx > 0, MAX_NODE_SCORE * raw // jnp.maximum(mx, 1), raw)
    if reverse:
        scaled = jnp.where(
            mx > 0, MAX_NODE_SCORE - scaled, MAX_NODE_SCORE
        )
    return scaled


# ---------------------------------------------------------------------------
# NodeResourcesFit — LeastAllocated (noderesources/least_allocated.go:29-60)
# ---------------------------------------------------------------------------


def score_least_allocated(dc: DeviceCluster, db: DeviceBatch, nonzero_req=None):
    """(alloc−req)·100/alloc averaged over cpu+memory, on the *non-zero
    defaulted* requests (resource_allocation.go:37-115)."""
    nonzero_req = dc.nonzero_req if nonzero_req is None else nonzero_req
    alloc = jnp.stack(
        [dc.allocatable[:, LANE_CPU], dc.allocatable[:, LANE_MEM]], axis=1
    ).astype(I64)  # [N, 2]
    req = (
        nonzero_req[None, :, :].astype(I64)
        + db.nonzero_req[:, None, :].astype(I64)
    )  # [P, N, 2]
    frac = jnp.where(
        req > alloc[None],
        0,
        (alloc[None] - req) * MAX_NODE_SCORE // jnp.maximum(alloc[None], 1),
    )
    lane_ok = (alloc > 0)[None]  # [1, N, 2]
    total = jnp.sum(jnp.where(lane_ok, frac, 0), axis=2)
    wsum = jnp.sum(lane_ok.astype(I64), axis=2)
    return jnp.where(wsum > 0, total // jnp.maximum(wsum, 1), 0)


# ---------------------------------------------------------------------------
# NodeResourcesBalancedAllocation (balanced_allocation.go:138-160)
# ---------------------------------------------------------------------------


def score_balanced_allocation(dc: DeviceCluster, db: DeviceBatch, requested=None):
    """1 − |cpu_frac − mem_frac|/2, scaled to 100.  Computed exactly in
    int64 rationals: score = 100 − ceil(50·|r0·a1 − r1·a0| / (a0·a1))
    (matches Go's float64 path for all realistic quantities)."""
    requested = dc.requested if requested is None else requested
    a0 = dc.allocatable[:, LANE_CPU].astype(I64)
    a1 = dc.allocatable[:, LANE_MEM].astype(I64)
    r0 = requested[:, LANE_CPU].astype(I64)[None] + db.requests[:, LANE_CPU].astype(
        I64
    )[:, None]
    r1 = requested[:, LANE_MEM].astype(I64)[None] + db.requests[:, LANE_MEM].astype(
        I64
    )[:, None]
    r0 = jnp.minimum(r0, a0[None])  # min(fraction, 1)
    r1 = jnp.minimum(r1, a1[None])
    d = jnp.abs(r0 * a1[None] - r1 * a0[None])
    den = jnp.maximum(a0 * a1, 1)[None]
    both = ((a0 > 0) & (a1 > 0))[None]
    score = MAX_NODE_SCORE - (50 * d + den - 1) // den
    return jnp.where(both, score, MAX_NODE_SCORE)


# ---------------------------------------------------------------------------
# NodeAffinity preferred terms (nodeaffinity/node_affinity.go:239)
# ---------------------------------------------------------------------------


def score_node_affinity(dc: DeviceCluster, db: DeviceBatch):
    terms = eval_table(db.pref_node, dc.node_labels, dc.val_ints)  # [P, PT, N]
    w = db.pref_weight.astype(I64)[:, :, None]
    return jnp.sum(jnp.where(terms, w, 0), axis=1)


# ---------------------------------------------------------------------------
# TaintToleration (tainttoleration/taint_toleration.go:164-196)
# ---------------------------------------------------------------------------


def score_taint_toleration(dc: DeviceCluster, db: DeviceBatch):
    """Count of PreferNoSchedule taints not tolerated (tolerations filtered
    to effect ∈ {"", PreferNoSchedule}); lower is better (reversed in
    normalize)."""
    from kubernetes_tpu.ops.filters import any_tolerates

    slot_use = (db.tol_effect == EFFECT_ALL) | (
        db.tol_effect == EFFECT_PREFER_NO_SCHEDULE
    )  # [P, TL]
    tol = any_tolerates(
        db, dc.taint_key, dc.taint_val, dc.taint_effect, slot_use=slot_use
    )
    pns = (dc.taint_effect == EFFECT_PREFER_NO_SCHEDULE) & (dc.taint_key != PAD)
    return jnp.sum((pns[None] & ~tol).astype(I64), axis=-1)


# ---------------------------------------------------------------------------
# InterPodAffinity (interpodaffinity/scoring.go:50-265)
# ---------------------------------------------------------------------------


def score_interpod(
    dc: DeviceCluster,
    db: DeviceBatch,
    pre: InterPodPre,
    v_cap: int,
    hard_pod_affinity_weight: int = 1,
):
    """topo_score aggregation: incoming preferred terms (±w per matching
    placed pod in-domain) + symmetric existing-term contributions."""
    from kubernetes_tpu.ops.common import domain_stats

    # Incoming preferred terms: w · (# matching placed pods in node's domain).
    kind = db.aff_kind
    w = jnp.where(
        kind == TERM_PREFERRED_AFFINITY,
        db.aff_weight,
        jnp.where(kind == TERM_PREFERRED_ANTI, -db.aff_weight, 0),
    ).astype(I64)  # [P, AT]
    dom_tot, _, _, _ = domain_stats(
        pre.inc_cnt, jnp.zeros_like(pre.inc_cnt, bool), pre.inc_dv, v_cap
    )  # [P, AT, N]
    topo_present = pre.inc_dv >= 0
    incoming = jnp.sum(
        jnp.where(topo_present, dom_tot.astype(I64) * w[:, :, None], 0), axis=1
    )  # [P, N]

    sym = interpod_symmetric_score(dc, pre, hard_pod_affinity_weight)
    return incoming + sym


def interpod_symmetric_score(
    dc: DeviceCluster, pre: InterPodPre, hard_pod_affinity_weight: int = 1
):
    """[P, N] i64: existing pods' terms matching the incoming pod, credited
    to nodes sharing the term's topology value (scoring.go processExistingPod
    symmetric paths)."""
    from kubernetes_tpu.ops.filters import interpod_weighted_ext

    ew = jnp.where(
        dc.term_kind == TERM_REQUIRED_AFFINITY,
        hard_pod_affinity_weight,
        jnp.where(
            dc.term_kind == TERM_PREFERRED_AFFINITY,
            dc.term_weight,
            jnp.where(dc.term_kind == TERM_PREFERRED_ANTI, -dc.term_weight, 0),
        ),
    ).astype(I32)  # [M]
    return interpod_weighted_ext(dc, pre, ew).astype(I64)


def normalize_interpod(raw, feasible):
    """scoring.go:265: map [min,max] over feasible → [0,100]."""
    raw = raw.astype(I64)
    big = jnp.iinfo(jnp.int64).max
    mn = jnp.min(jnp.where(feasible, raw, big), axis=1, keepdims=True)
    mx = jnp.max(jnp.where(feasible, raw, -big), axis=1, keepdims=True)
    diff = mx - mn
    return jnp.where(
        diff > 0, MAX_NODE_SCORE * (raw - mn) // jnp.maximum(diff, 1), 0
    )


# ---------------------------------------------------------------------------
# PodTopologySpread (podtopologyspread/scoring.go)
# ---------------------------------------------------------------------------


def score_spread(
    dc: DeviceCluster,
    db: DeviceBatch,
    pre: SpreadPre,
    feasible,
    v_cap: int,
    hostname_val_key,
):
    """ScheduleAnyway constraints: Σ_c count·log(topoSize+2) + (maxSkew−1),
    computed in 32.32 fixed point from a host-precomputed log table so the
    result matches float64 round() bit-for-bit.

    Returns (raw [P,N] i64 fixed-point-rounded ints, valid [P,N] bool) —
    valid=False marks "ignored" nodes (missing topo labels ⇒ score 0 after
    normalize).
    """
    soft = pre.exists & ~db.tsc_hard  # [P, C]
    has_soft = jnp.any(soft, axis=1)  # [P]
    P, C, N = pre.dv.shape

    topo_present = pre.dv >= 0
    ignored = feasible & ~jnp.all(~soft[:, :, None] | topo_present, axis=1)
    counted_node = feasible & ~ignored  # filtered, non-ignored

    is_hostname = db.tsc_topo == hostname_val_key  # [P, C]

    # topoSize: distinct domains among counted nodes (non-hostname keys).
    from kubernetes_tpu.ops.common import domain_stats

    _, _, _, n_dom = domain_stats(
        jnp.zeros((P, C, N), I32),
        counted_node[:, None, :] & jnp.broadcast_to(soft[:, :, None], (P, C, N)),
        pre.dv,
        v_cap,
    )
    n_counted = jnp.sum(counted_node.astype(I32), axis=1)  # [P]
    size = jnp.where(is_hostname, n_counted[:, None], n_dom)  # [P, C]
    w_fx = dc.log_tab[jnp.clip(size, 0, dc.log_tab.shape[0] - 1)]  # [P, C] i64

    # Matching-pod counts: all nodes with all soft topo keys, eligible per
    # inclusion policy; only domains seen among counted nodes accumulate.
    all_keys = jnp.all(~soft[:, :, None] | topo_present, axis=1)  # [P, N]
    cnt_n = per_node_counts(pre.sel_match.astype(I32), dc.epod_node, N)
    pair_init = counted_node[:, None, :] & jnp.broadcast_to(
        soft[:, :, None], (P, C, N)
    ) & ~is_hostname[:, :, None]
    counting = all_keys[:, None, :] & pre.eligible
    dom_tot, dom_pres, _, _ = domain_stats(
        jnp.where(counting, cnt_n, 0), pair_init, pre.dv, v_cap
    )
    # hostname key: per-node count, not per-domain
    cnt = jnp.where(is_hostname[:, :, None], cnt_n, jnp.where(dom_pres, dom_tot, 0))

    contrib = cnt.astype(I64) * w_fx[:, :, None] + (
        (db.tsc_max_skew.astype(I64) - 1)[:, :, None] << _FX
    )
    total_fx = jnp.sum(jnp.where(soft[:, :, None], contrib, 0), axis=1)  # [P, N]

    # round-half-even of total_fx / 2^32
    k = total_fx >> _FX
    frac = total_fx & ((1 << _FX) - 1)
    half = 1 << (_FX - 1)
    up = (frac > half) | ((frac == half) & ((k & 1) == 1))
    raw = k + up.astype(I64)
    raw = jnp.where(has_soft[:, None], raw, 0)
    valid = jnp.where(has_soft[:, None], ~ignored, feasible)
    return raw, valid


def normalize_spread(raw, valid, feasible):
    """scoring.go:227: 100·(max+min−s)/max over valid nodes; invalid → 0."""
    raw = raw.astype(I64)
    big = jnp.iinfo(jnp.int64).max
    use = valid & feasible
    mn = jnp.min(jnp.where(use, raw, big), axis=1, keepdims=True)
    mx = jnp.max(jnp.where(use, raw, -big), axis=1, keepdims=True)
    any_valid = jnp.any(use, axis=1, keepdims=True)
    out = jnp.where(
        mx == 0,
        MAX_NODE_SCORE,
        MAX_NODE_SCORE * (mx + mn - raw) // jnp.maximum(mx, 1),
    )
    return jnp.where(use & any_valid, out, 0)


# ---------------------------------------------------------------------------
# ImageLocality (imagelocality/image_locality.go:54-96)
# ---------------------------------------------------------------------------

_MB = 1024 * 1024
_MIN_THRESHOLD = 23 * _MB
_MAX_CONTAINER_THRESHOLD = 1000 * _MB


def score_image_locality(dc: DeviceCluster, db: DeviceBatch):
    IMG = dc.img_sizes.shape[1]
    spread = jnp.sum(
        ((dc.img_sizes > 0) & dc.node_valid[:, None]).astype(I64), axis=0
    )  # [IMG]
    total = jnp.maximum(dc.n_valid_nodes.astype(I64), 1)

    I = db.img_ids.shape[1]
    sum_scores = jnp.zeros((db.img_ids.shape[0], dc.img_sizes.shape[0]), I64)
    for i in range(I):
        ii = db.img_ids[:, i]
        known = (ii >= 0) & (ii < IMG)
        safe = jnp.clip(ii, 0, IMG - 1)
        size = dc.img_sizes[:, safe].T  # [P, N]
        sp = spread[safe]  # [P]
        contrib = size * sp[:, None] // total
        sum_scores = sum_scores + jnp.where(known[:, None], contrib, 0)

    nc = db.n_containers.astype(I64)[:, None]
    min_th = _MIN_THRESHOLD * nc
    max_th = _MAX_CONTAINER_THRESHOLD * nc
    clamped = jnp.clip(sum_scores, min_th, max_th)
    score = MAX_NODE_SCORE * (clamped - min_th) // jnp.maximum(max_th - min_th, 1)
    has_imgs = jnp.any(db.img_ids >= 0, axis=1)
    return jnp.where(has_imgs[:, None], score, 0)


# ---------------------------------------------------------------------------
# Weighted total (runtime/framework.go:1177-1201)
# ---------------------------------------------------------------------------

DEFAULT_SCORE_WEIGHTS = {
    "TaintToleration": 3,
    "NodeAffinity": 2,
    "PodTopologySpread": 2,
    "InterPodAffinity": 2,
    "NodeResourcesFit": 1,
    "NodeResourcesBalancedAllocation": 1,
    "ImageLocality": 1,
}


def all_scores(
    dc: DeviceCluster,
    db: DeviceBatch,
    feasible,
    ipre: InterPodPre,
    spre: SpreadPre,
    v_cap: int,
    hostname_val_key,
    weights: Dict[str, int] = None,
    requested=None,
    nonzero_req=None,
    has_images: bool = True,
):
    """Weighted sum of normalized plugin scores over the feasible set.

    ``ipre``/``spre`` may be None (batch statically known to carry no such
    constraints); the oracle-equivalent constant then applies — spread
    normalizes to 100 everywhere (normalize_topology_spread with all-zero
    raw), inter-pod normalizes to 0 (diff == 0)."""
    w = DEFAULT_SCORE_WEIGHTS if weights is None else weights
    total = jnp.zeros(feasible.shape, I64)
    per_plugin = {}

    def acc(name, scores):
        per_plugin[name] = scores
        nonlocal total
        total = total + scores.astype(I64) * w.get(name, 0)

    if w.get("TaintToleration"):
        acc(
            "TaintToleration",
            default_normalize(
                score_taint_toleration(dc, db), feasible, reverse=True
            ),
        )
    if w.get("NodeAffinity"):
        acc(
            "NodeAffinity",
            default_normalize(score_node_affinity(dc, db), feasible),
        )
    if w.get("PodTopologySpread"):
        if spre is not None:
            raw, valid = score_spread(
                dc, db, spre, feasible, v_cap, hostname_val_key
            )
            acc("PodTopologySpread", normalize_spread(raw, valid, feasible))
        else:
            acc(
                "PodTopologySpread",
                jnp.where(feasible, MAX_NODE_SCORE, 0).astype(I64),
            )
    if w.get("InterPodAffinity"):
        if ipre is not None:
            acc(
                "InterPodAffinity",
                normalize_interpod(score_interpod(dc, db, ipre, v_cap), feasible),
            )
        else:
            acc("InterPodAffinity", jnp.zeros(feasible.shape, I64))
    if w.get("NodeResourcesFit"):
        acc("NodeResourcesFit", score_least_allocated(dc, db, nonzero_req))
    if w.get("NodeResourcesBalancedAllocation"):
        acc(
            "NodeResourcesBalancedAllocation",
            score_balanced_allocation(dc, db, requested),
        )
    if w.get("ImageLocality"):
        if has_images:
            acc("ImageLocality", score_image_locality(dc, db))
        else:
            acc("ImageLocality", jnp.zeros(feasible.shape, I64))
    return total, per_plugin
