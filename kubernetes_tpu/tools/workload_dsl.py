"""Declarative bench workloads: a YAML op list compiled onto the
scheduler (scheduler_perf.go:447-750's createNodes/createPods/churn/
barrier ops, the way the reference defines every perf workload in
performance-config.yaml instead of code).

A workload is `{"name": ..., "ops": [...]}`; ops execute in order against
one in-proc Scheduler:

  op: createNodes    count, zones=3, cpu="8", memory="32Gi", pods=110,
                     labels={...}                    (appends nodes)
  op: createPods     count, cpuRequest(s), memoryRequest(s),
                     labels={...}, apps=N (app label sharding),
                     antiAffinityGroups=N (hostname anti-affinity),
                     spreadApps=N + maxSkew (zone topology spread),
                     collectMetrics: true            (measured region)
  op: churn          deletePods=N (bound victims), createNodes=N
  op: barrier        drain until every pending pod has an outcome
  op: sleep          seconds

Measurement follows scheduler_perf: only pods created by ops with
``collectMetrics: true`` count toward throughput, and the reported
wall time spans their barrier drains (util.go:367's collector skips
warm-up ops).  Run a workload file:

    python -m kubernetes_tpu.tools.workload_dsl my_workload.yaml
"""

from __future__ import annotations

import json
import random
import time
from typing import Dict, List, Optional

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    LabelSelector,
    Node,
    Pod,
    PodAffinityTerm,
    PodAntiAffinity,
    TopologySpreadConstraint,
)


def _aslist(v) -> List:
    return v if isinstance(v, list) else [v]


class WorkloadRunner:
    """Executes one op list against a fresh Scheduler."""

    def __init__(self, spec: dict, seed: int = 42):
        from kubernetes_tpu.scheduler import Scheduler

        self.spec = spec
        self.rng = random.Random(spec.get("seed", seed))
        self.sched = Scheduler()
        self.bound: Dict[str, str] = {}
        self.sched.binding_sink = (
            lambda pod, node: self.bound.__setitem__(pod.uid, node)
        )
        self._node_count = 0
        self._pod_count = 0
        self._measured_pods = 0
        self._measured_wall = 0.0
        self._pending_measured = False

    # ----- ops --------------------------------------------------------------

    def _op_create_nodes(self, op: dict) -> None:
        zones = op.get("zones", 3)
        caps = {
            "cpu": str(op.get("cpu", "8")),
            "memory": str(op.get("memory", "32Gi")),
            "pods": op.get("pods", 110),
        }
        for _ in range(op["count"]):
            i = self._node_count
            self._node_count += 1
            labels = {
                "topology.kubernetes.io/zone": f"zone-{i % zones}",
                "kubernetes.io/hostname": f"dsl-node-{i}",
                **op.get("labels", {}),
            }
            self.sched.on_node_add(
                Node(
                    name=f"dsl-node-{i}",
                    labels=labels,
                    capacity=Resource.from_map(caps),
                )
            )

    def _mk_pod(self, op: dict) -> Pod:
        i = self._pod_count
        self._pod_count += 1
        labels = dict(op.get("labels", {}))
        if op.get("apps"):
            labels["app"] = f"app-{i % op['apps']}"
        affinity = None
        tsc = ()
        if op.get("antiAffinityGroups"):
            group = f"g{i % op['antiAffinityGroups']}"
            labels["group"] = group
            affinity = Affinity(
                pod_anti_affinity=PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        PodAffinityTerm(
                            topology_key="kubernetes.io/hostname",
                            label_selector=LabelSelector(
                                match_labels={"group": group}
                            ),
                        ),
                    )
                )
            )
        if op.get("spreadApps"):
            app = f"sa{i % op['spreadApps']}"
            labels["sapp"] = app
            tsc = (
                TopologySpreadConstraint(
                    max_skew=op.get("maxSkew", 5),
                    topology_key="topology.kubernetes.io/zone",
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=LabelSelector(match_labels={"sapp": app}),
                ),
            )
        return Pod(
            name=f"dsl-pod-{i}",
            labels=labels,
            affinity=affinity,
            topology_spread_constraints=tsc,
            containers=[
                Container(
                    name="c",
                    requests={
                        "cpu": str(
                            self.rng.choice(
                                _aslist(op.get("cpuRequest", "100m"))
                            )
                        ),
                        "memory": str(
                            self.rng.choice(
                                _aslist(op.get("memoryRequest", "128Mi"))
                            )
                        ),
                    },
                )
            ],
        )

    def _op_create_pods(self, op: dict) -> None:
        for _ in range(op["count"]):
            self.sched.on_pod_add(self._mk_pod(op))
        if op.get("collectMetrics"):
            self._pending_measured = True
            self._measured_pods += op["count"]

    def _op_barrier(self, op: Optional[dict] = None) -> None:
        t0 = time.perf_counter()
        self.sched.schedule_pending()
        if self._pending_measured:
            self._measured_wall += time.perf_counter() - t0
            self._pending_measured = False

    def _op_churn(self, op: dict) -> None:
        import copy

        for uid in list(self.bound)[: op.get("deletePods", 0)]:
            node = self.bound.pop(uid)
            ps = self.sched.cache.pod_states.get(uid)
            if ps is None:
                continue
            dead = copy.copy(ps.pod)
            dead.node_name = node
            self.sched.on_pod_delete(dead)
        if op.get("createNodes"):
            self._op_create_nodes(
                {"count": op["createNodes"], **{k: v for k, v in op.items() if k != "op"}}
            )

    # ----- driver -----------------------------------------------------------

    def run(self) -> dict:
        for op in self.spec.get("ops", []):
            kind = op["op"]
            if kind == "createNodes":
                self._op_create_nodes(op)
            elif kind == "createPods":
                self._op_create_pods(op)
            elif kind == "barrier":
                self._op_barrier(op)
            elif kind == "churn":
                self._op_churn(op)
            elif kind == "sleep":
                time.sleep(op.get("seconds", 0))
            else:
                raise ValueError(f"unknown op {kind!r}")
        # implicit trailing barrier, like scheduler_perf's workload end
        self._op_barrier()
        wall = max(self._measured_wall, 1e-9)
        return {
            "name": self.spec.get("name", "workload"),
            "nodes": self._node_count,
            "pods_created": self._pod_count,
            "pods_bound": len(self.bound),
            "measured_pods": self._measured_pods,
            "measured_wall_s": round(wall, 3),
            "pods_per_s": round(self._measured_pods / wall, 1)
            if self._measured_pods
            else None,
        }


def run_workload(source, seed: int = 42) -> dict:
    """source: YAML path / YAML string / dict."""
    from kubernetes_tpu.util.yamlsource import load_yaml_source

    return WorkloadRunner(load_yaml_source(source), seed=seed).run()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="workload-dsl")
    ap.add_argument("workload", help="YAML workload file")
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args(argv)
    print(json.dumps(run_workload(args.workload, seed=args.seed)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
