"""Kubemark-style scale simulation (SURVEY §4 tier 5).

The reference's kubemark runs thousands of HOLLOW nodes — kubelets with
mocked runtimes (cmd/kubemark/hollow-node.go, pkg/kubemark/
hollow_kubelet.go:87) — against a real control plane, so cluster-scale
behavior is measured without real machines.  This driver is the same
shape for this build's control plane:

    FakeCluster store ← ApiServer (HTTP list/watch)
        ← RemoteClusterSource ← Scheduler ← SchedulerServer loop

Hollow nodes register over HTTP from a thread pool (the registration
storm), then driver threads churn pods — create waves, delete a fraction
of bound pods — while the SchedulerServer's own loop schedules.
Steady-state throughput and p99 attempt latency are scraped from the
SERVED /metrics endpoint (not in-process state), exercising the whole
observable surface.

Run standalone:  python -m kubernetes_tpu.tools.kubemark --nodes 1000 --pods 2000
"""

from __future__ import annotations

import argparse
import json
import re
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import Container, Node, Pod


@dataclass
class ScaleSimResult:
    n_nodes: int
    pods_bound: int
    wall_s: float
    pods_per_s: float
    p99_attempt_s: float
    registration_s: float
    loop_cycles: int


def _parse_histogram_p99(metrics_text: str, name: str) -> float:
    """Quantile from Prometheus text exposition bucket lines (the
    histogram_quantile estimate over the aggregated label sets)."""
    buckets: Dict[float, int] = {}
    total = 0
    for line in metrics_text.splitlines():
        if not line.startswith(name):
            continue
        m = re.match(rf'{name}_bucket{{.*le="([^"]+)".*}} (\d+)', line)
        if m:
            le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
            buckets[le] = buckets.get(le, 0) + int(m.group(2))
        m = re.match(rf"{name}_count(?:{{.*}})? (\d+)", line)
        if m:
            total += int(m.group(1))
    if not buckets or total == 0:
        return 0.0
    rank = 0.99 * total
    prev_le, prev_cum = 0.0, 0
    for le in sorted(buckets):
        cum = buckets[le]
        if cum >= rank:
            if le == float("inf"):
                return prev_le
            frac = (rank - prev_cum) / max(cum - prev_cum, 1)
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    return prev_le


def run_scale_sim(
    n_nodes: int = 1000,
    n_pods: int = 2000,
    churn_waves: int = 4,
    churn_deletes: int = 50,
    registration_threads: int = 16,
    timeout_s: float = 600.0,
    progress=None,
) -> ScaleSimResult:
    from kubernetes_tpu.client import ApiClient, ApiServer, RemoteClusterSource
    from kubernetes_tpu.events import EventBroadcaster
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.server import SchedulerServer
    from kubernetes_tpu.testing.fake_cluster import FakeCluster

    api = FakeCluster(pv_controller=False)
    apiserver = ApiServer(api).start()
    endpoint = f"http://127.0.0.1:{apiserver.port}"

    sched = Scheduler(event_broadcaster=EventBroadcaster())
    sched.event_broadcaster.start_recording_to_sink(api.record_event)
    # bigger drains per loop pass: pre-size the placed-pod axes once
    sched.mirror.e_cap_hint = n_pods + sched.config.batch_size + 128
    source = RemoteClusterSource(endpoint)
    source.connect(sched)
    source.start()
    server = SchedulerServer(sched, poll_interval_s=0.005)
    server.start()

    def log(msg: str) -> None:
        if progress:
            progress(msg)

    fleet = None
    try:
        # ---- hollow node registration storm -----------------------------
        t_reg = time.perf_counter()
        reg_client = ApiClient(endpoint)  # thread-local keep-alive per pool thread

        def register(i: int) -> None:
            reg_client.create_node(
                Node(
                    name=f"hollow-{i}",
                    labels={
                        "topology.kubernetes.io/zone": f"zone-{i % 3}",
                        "kubernetes.io/hostname": f"hollow-{i}",
                    },
                    capacity=Resource.from_map(
                        {"cpu": "8", "memory": "32Gi", "pods": 110}
                    ),
                )
            )

        with ThreadPoolExecutor(registration_threads) as ex:
            list(ex.map(register, range(n_nodes)))
        source.wait_for_sync()
        registration_s = time.perf_counter() - t_reg
        log(f"registered {n_nodes} hollow nodes in {registration_s:.1f}s")

        # hollow-kubelet tier (hollow_kubelet.go:87): heartbeats + pod
        # status reports run for the WHOLE measured window, so the control
        # plane carries the kubelet write load the reference's kubemark
        # clusters generate (first beat immediate, then every 15s ≈ the
        # upstream 10s on this sim's compressed wall time)
        from kubernetes_tpu.kubemark import HollowFleet

        fleet = HollowFleet(endpoint, heartbeat_interval_s=15.0)
        fleet.adopt(
            [
                Node(name=f"hollow-{i}")
                for i in range(n_nodes)
            ]
        )
        fleet.start()

        # ---- pod churn ---------------------------------------------------
        client = ApiClient(endpoint)
        uid_counter = [0]
        uid_lock = threading.Lock()

        def mk_pod() -> Pod:
            with uid_lock:
                i = uid_counter[0]
                uid_counter[0] += 1
            return Pod(
                name=f"load-{i}",
                labels={"app": f"app-{i % 10}"},
                containers=[
                    Container(
                        name="c",
                        requests={"cpu": "100m", "memory": "128Mi"},
                    )
                ],
            )

        def create_many(k: int) -> None:
            with ThreadPoolExecutor(registration_threads) as ex:
                list(ex.map(lambda _: client.create_pod(mk_pod()), range(k)))

        # warm wave (compile shapes) excluded from measurement
        warm = min(max(sched.config.batch_size + 64, 256), n_pods // 2)
        create_many(warm)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline and len(api.bindings) < warm:
            time.sleep(0.05)
        log(f"warm phase: {len(api.bindings)} bound")

        t0 = time.perf_counter()
        bound_at_start = len(api.bindings)
        deleted = [0]  # SUCCESSFUL churn deletes — wait targets derive
        # from this count, not the attempt count, so a delete racing the
        # scheduler can't make the wait loops spin to timeout
        remaining = n_pods - warm
        per_wave = remaining // churn_waves
        for w in range(churn_waves):
            create_many(per_wave if w < churn_waves - 1 else remaining - per_wave * (churn_waves - 1))
            # churn: delete some bound pods (capacity freed, watch events)
            victims = list(api.bindings)[:churn_deletes]
            for uid in victims:
                try:
                    client.delete_pod(uid)
                    deleted[0] += 1
                except Exception:  # noqa: BLE001 — racing the scheduler
                    pass
            target = warm + per_wave * (w + 1) - deleted[0]
            while time.monotonic() < deadline and len(api.bindings) < target:
                time.sleep(0.005)
            log(f"wave {w}: {len(api.bindings)} bound")
        # settle: all created pods either bound or deleted
        expect = uid_counter[0] - deleted[0]
        while time.monotonic() < deadline and len(api.bindings) < expect:
            time.sleep(0.005)
        wall = time.perf_counter() - t0
        pods_bound = len(api.bindings) - bound_at_start

        # ---- scrape the served /metrics ---------------------------------
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()
        p99 = _parse_histogram_p99(
            text, "scheduler_scheduling_attempt_duration_seconds"
        )
        return ScaleSimResult(
            n_nodes=n_nodes,
            pods_bound=pods_bound,
            wall_s=wall,
            pods_per_s=pods_bound / max(wall, 1e-9),
            p99_attempt_s=p99,
            registration_s=registration_s,
            loop_cycles=server.cycles,
        )
    finally:
        if fleet is not None:
            fleet.stop()
        server.stop()
        source.stop()
        apiserver.stop()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="kubemark-sim")
    ap.add_argument("--nodes", type=int, default=1000)
    ap.add_argument("--pods", type=int, default=2000)
    ap.add_argument("--waves", type=int, default=4)
    args = ap.parse_args(argv)
    res = run_scale_sim(args.nodes, args.pods, churn_waves=args.waves, progress=print)
    print(
        json.dumps(
            {
                "nodes": res.n_nodes,
                "pods_bound": res.pods_bound,
                "wall_s": round(res.wall_s, 2),
                "pods_per_s": round(res.pods_per_s, 1),
                "p99_attempt_s": round(res.p99_attempt_s, 4),
                "registration_s": round(res.registration_s, 2),
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
