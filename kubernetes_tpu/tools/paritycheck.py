"""Bench-time decision-parity evidence at north-star scale → PARITY_r*.json.

The flagship claim — "binding decisions identical to default-scheduler" —
needs evidence at scales no CI-budget pytest run can afford.  This tool
produces it once per bench run on the real device:

  * CROSS-BATCH-SIZE identity at 10k nodes / 50k pods: the extended
    device fast path (fastBatchMax=4096, sig_scan pipeline) against a
    64-pod-batch drain (host-greedy committer) — completely different
    machinery whose decisions must be bit-identical because both replay
    the sequential one-pod-at-a-time argmax;
  * SAMPLING-COMPAT vs the serial oracle at 2k nodes / 3k pods over
    3 zones: the device kernel's nodeTree-ordered sampling window,
    rotation cursor, and seeded tie-break against the scalar
    reference-shaped loop (schedule_one semantics).

Writes one JSON artifact {"checks": {...}, "total_diffs": N}; the driver
records it next to BENCH_r*.json.  Run standalone:

    python -m kubernetes_tpu.tools.paritycheck [--out PARITY.json]
"""

from __future__ import annotations

import argparse
import json
import random
import time
from typing import Dict, List, Optional


def _basic_nodes(n, zones=3):
    from kubernetes_tpu.api.resource import Resource
    from kubernetes_tpu.api.types import Node

    return [
        Node(
            name=f"node-{i}",
            labels={
                "topology.kubernetes.io/zone": f"zone-{i % zones}",
                "kubernetes.io/hostname": f"node-{i}",
            },
            capacity=Resource.from_map(
                {"cpu": "8", "memory": "32Gi", "pods": 110}
            ),
        )
        for i in range(n)
    ]


def _basic_pods(n, seed=4242):
    from kubernetes_tpu.api.types import Container, Pod

    rng = random.Random(seed)
    return [
        Pod(
            name=f"pp-{i}",
            labels={"app": f"app-{i % 16}"},
            containers=[
                Container(
                    name="c",
                    requests={
                        "cpu": f"{rng.choice([100, 250, 500])}m",
                        "memory": f"{rng.choice([128, 256, 512])}Mi",
                    },
                )
            ],
        )
        for i in range(n)
    ]


def _drain(nodes, pods, return_sched: bool = False, **cfg_kw):
    from kubernetes_tpu.framework.config import SchedulerConfiguration
    from kubernetes_tpu.scheduler import Scheduler

    cfg = SchedulerConfiguration()
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    s = Scheduler(configuration=cfg)
    got: Dict[str, Optional[str]] = {}
    s.binding_sink = lambda pod, node: got.__setitem__(pod.name, node)
    s.mirror.e_cap_hint = len(pods) + cfg.batch_size + 128
    for n in nodes:
        s.on_node_add(n)
    for p in pods:
        s.on_pod_add(p)
    outs = s.schedule_pending()
    for o in outs:
        got.setdefault(o.pod.name, o.node)
    if return_sched:
        return got, s
    return got


def _diff(a: Dict, b: Dict) -> List:
    keys = set(a) | set(b)
    return sorted(
        (k, a.get(k), b.get(k)) for k in keys if a.get(k) != b.get(k)
    )


def check_cross_batch(n_nodes=10000, n_pods=50000) -> dict:
    """Device sig_scan pipeline (4096-extended batches) vs host-greedy
    64-pod batches — identical bindings at north-star scale."""
    import copy

    nodes = _basic_nodes(n_nodes)
    pods = _basic_pods(n_pods)
    t0 = time.perf_counter()
    big = _drain(nodes, copy.deepcopy(pods))
    small = _drain(
        nodes, copy.deepcopy(pods), batch_size=64, fast_batch_max=64
    )
    diffs = _diff(big, small)
    return {
        "nodes": n_nodes,
        "pods": n_pods,
        "bound_a": sum(1 for v in big.values() if v),
        "bound_b": sum(1 for v in small.values() if v),
        "diffs": len(diffs),
        "first_diffs": diffs[:5],
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def check_compat_vs_oracle(n_nodes=2000, n_pods=3000, seed=77) -> dict:
    """Sampling-compat + seeded-tie device pipeline vs the serial oracle
    (reference-shaped one-pod loop in nodeTree order)."""
    import copy

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubernetes_tpu.oracle.pipeline import (
        feasible_nodes,
        num_feasible_nodes_to_find,
        prioritize,
    )
    from kubernetes_tpu.oracle.state import OracleState

    nodes = _basic_nodes(n_nodes, zones=3)
    pods = _basic_pods(n_pods, seed=seed)
    t0 = time.perf_counter()
    got = _drain(
        nodes,
        copy.deepcopy(pods),
        reference_sampling_compat=True,
        tie_break_seed=seed,
    )

    state = OracleState.build(nodes)
    key = jax.random.PRNGKey(seed)
    # one device call for ALL attempts' tie-break hashes: per-pod
    # random.bits round trips cost ~100ms each over a remote device link
    h_all = np.asarray(
        jax.vmap(
            lambda a: jax.random.bits(
                jax.random.fold_in(key, a), (n_nodes,), dtype=jnp.uint32
            )
        )(jnp.arange(n_pods))
    )
    idx_of = {name: i for i, name in enumerate(state.nodes)}
    start = 0
    attempt = 0
    want: Dict[str, Optional[str]] = {}
    for pod in copy.deepcopy(pods):
        fit = feasible_nodes(pod, state, sample_pct=0, start_index=start)
        start = (start + fit.processed) % n_nodes
        totals = prioritize(pod, state, fit.feasible)
        if not totals:
            want[pod.name] = None
            continue
        h = h_all[attempt]
        attempt += 1
        node = max(totals, key=lambda m: (totals[m], int(h[idx_of[m]])))
        want[pod.name] = node
        pod.node_name = node
        state.place(pod)
    diffs = _diff(got, want)
    return {
        "nodes": n_nodes,
        "pods": n_pods,
        "bound_device": sum(1 for v in got.values() if v),
        "bound_oracle": sum(1 for v in want.values() if v),
        "diffs": len(diffs),
        "first_diffs": diffs[:5],
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def _cross_pod_pods(n, seed=99):
    """Mixed spread / anti-affinity / plain pods — the wave path's diet."""
    from kubernetes_tpu.api.types import (
        Affinity,
        Container,
        LabelSelector,
        Pod,
        PodAffinityTerm,
        PodAntiAffinity,
        TopologySpreadConstraint,
    )

    rng = random.Random(seed)
    pods = []
    for i in range(n):
        kw = {}
        if i % 2 == 0:
            app = f"sp-{i % 12}"
            kw["labels"] = {"app": app}
            kw["topology_spread_constraints"] = (
                TopologySpreadConstraint(
                    max_skew=3,
                    topology_key="topology.kubernetes.io/zone",
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=LabelSelector(match_labels={"app": app}),
                ),
            )
        elif i % 4 == 1:
            grp = f"g{i % 20}"
            kw["labels"] = {"group": grp}
            kw["affinity"] = Affinity(
                pod_anti_affinity=PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        PodAffinityTerm(
                            topology_key="kubernetes.io/hostname",
                            label_selector=LabelSelector(
                                match_labels={"group": grp}
                            ),
                        ),
                    )
                )
            )
        else:
            kw["labels"] = {"app": f"plain-{i % 8}"}
        pods.append(
            Pod(
                name=f"wp-{i}",
                containers=[
                    Container(
                        name="c",
                        requests={
                            "cpu": f"{rng.choice([100, 250])}m",
                            "memory": "128Mi",
                        },
                    )
                ],
                **kw,
            )
        )
    return pods


def check_wave_vs_oracle(n_nodes=500, n_pods=2000) -> dict:
    """Wave-dispatch drain (speculation + factored conflict resolution,
    ops/wave.py) vs the serial oracle on a mixed spread/anti-affinity
    workload — the wave's bit-identity evidence at bench scale."""
    import copy

    from kubernetes_tpu.oracle.pipeline import schedule_one
    from kubernetes_tpu.oracle.state import OracleState

    nodes = _basic_nodes(n_nodes, zones=6)
    pods = _cross_pod_pods(n_pods)
    t0 = time.perf_counter()
    got, sched = _drain(nodes, copy.deepcopy(pods), return_sched=True)
    wave_batches = sched.metrics["wave_batches"]

    state = OracleState.build(nodes)
    want: Dict[str, Optional[str]] = {}
    for pod in copy.deepcopy(pods):
        r = schedule_one(pod, state)
        want[pod.name] = r.node
        if r.node is not None:
            pod.node_name = r.node
            state.place(pod)
    diffs = _diff(got, want)
    n_diffs = len(diffs)
    if wave_batches == 0:
        # the check exists to certify the WAVE path; a silent fallback to
        # the scan would make its zero-diff claim vacuous — fail loud
        n_diffs += 1
        diffs = [("__wave_batches__", 0, ">=1")] + diffs
    return {
        "nodes": n_nodes,
        "pods": n_pods,
        "wave_batches": wave_batches,
        "bound_wave": sum(1 for v in got.values() if v),
        "bound_oracle": sum(1 for v in want.values() if v),
        "diffs": n_diffs,
        "first_diffs": diffs[:5],
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def _port_heavy_pods(n, seed=13, apps=8, prefix="pp"):
    """Port-contended mix: most pods race a couple of (port, proto) pairs
    (some wildcard-IP, some IP-scoped) alongside spread terms — the wave's
    factored [Tpt, N] port-occupancy carry is the only thing standing
    between this workload and the gang scan.  THE workload definition for
    the de-fallback coverage: bench config13 and tests/test_wave.py both
    import it, so the artifacts exercise one mix, not drifting copies."""
    from kubernetes_tpu.api.types import (
        Container,
        ContainerPort,
        LabelSelector,
        Pod,
        TopologySpreadConstraint,
    )

    rng = random.Random(seed)
    pods = []
    for i in range(n):
        kw = {"labels": {"app": f"srv-{i % apps}"}}
        containers = [
            Container(
                name="c",
                requests={
                    "cpu": f"{rng.choice([100, 250])}m",
                    "memory": "128Mi",
                },
            )
        ]
        if i % 3 != 2:
            containers.append(
                Container(
                    name="srv",
                    ports=(
                        ContainerPort(
                            container_port=8080,
                            host_port=rng.choice([8080, 9090]),
                            protocol=rng.choice(["TCP", "UDP"]),
                            host_ip=rng.choice(["", "", "10.0.0.1"]),
                        ),
                    ),
                )
            )
        if i % 2 == 0:
            app = kw["labels"]["app"]
            kw["topology_spread_constraints"] = (
                TopologySpreadConstraint(
                    max_skew=2,
                    topology_key="topology.kubernetes.io/zone",
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=LabelSelector(match_labels={"app": app}),
                ),
            )
        pods.append(Pod(name=f"{prefix}-{i}", containers=containers, **kw))
    return pods


def check_port_carry_vs_oracle(n_nodes=400, n_pods=1600) -> dict:
    """Port-contended wave drain (the factored [Tpt, N] port-occupancy
    carry) vs the serial oracle — the de-fallback's bit-identity evidence.
    Fails loud if the wave never engaged or the retired `ports` fallback
    rung was used."""
    import copy

    from kubernetes_tpu.oracle.pipeline import schedule_one
    from kubernetes_tpu.oracle.state import OracleState

    nodes = _basic_nodes(n_nodes, zones=5)
    pods = _port_heavy_pods(n_pods)
    t0 = time.perf_counter()
    got, sched = _drain(nodes, copy.deepcopy(pods), return_sched=True)
    wave_batches = sched.metrics["wave_batches"]
    port_fallbacks = sched.prom.wave_fallback.value(reason="ports")

    state = OracleState.build(nodes)
    want: Dict[str, Optional[str]] = {}
    for pod in copy.deepcopy(pods):
        r = schedule_one(pod, state)
        want[pod.name] = r.node
        if r.node is not None:
            pod.node_name = r.node
            state.place(pod)
    diffs = _diff(got, want)
    n_diffs = len(diffs)
    if wave_batches == 0:
        n_diffs += 1
        diffs = [("__wave_batches__", 0, ">=1")] + diffs
    if port_fallbacks:
        n_diffs += 1
        diffs = [("__fallback_ports__", port_fallbacks, 0)] + diffs
    return {
        "nodes": n_nodes,
        "pods": n_pods,
        "wave_batches": wave_batches,
        "bound_wave": sum(1 for v in got.values() if v),
        "bound_oracle": sum(1 for v in want.values() if v),
        "diffs": n_diffs,
        "first_diffs": diffs[:5],
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def check_compat_wave_vs_oracle(n_nodes=800, n_pods=1600, seed=47) -> dict:
    """Sampling-compat + seeded-tie drain over a CROSS-POD-constraint
    workload vs the serial oracle: the wave engine replays the adaptive
    window, nodeTree rotation, and seeded tie-break per step, so compat
    drains no longer pay the [C,N,J] gang scan.  Fails loud if the wave
    never engaged or the retired `sampling_compat` rung was used."""
    import copy

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubernetes_tpu.oracle.pipeline import feasible_nodes, prioritize
    from kubernetes_tpu.oracle.state import OracleState

    nodes = _basic_nodes(n_nodes, zones=3)
    pods = _cross_pod_pods(n_pods, seed=seed)
    t0 = time.perf_counter()
    got, sched = _drain(
        nodes,
        copy.deepcopy(pods),
        return_sched=True,
        reference_sampling_compat=True,
        tie_break_seed=seed,
    )
    wave_batches = sched.metrics["wave_batches"]
    compat_fallbacks = sched.prom.wave_fallback.value(
        reason="sampling_compat"
    )

    state = OracleState.build(nodes)
    key = jax.random.PRNGKey(seed)
    h_all = np.asarray(
        jax.vmap(
            lambda a: jax.random.bits(
                jax.random.fold_in(key, a), (n_nodes,), dtype=jnp.uint32
            )
        )(jnp.arange(n_pods))
    )
    idx_of = {name: i for i, name in enumerate(state.nodes)}
    start = 0
    attempt = 0
    want: Dict[str, Optional[str]] = {}
    for pod in copy.deepcopy(pods):
        fit = feasible_nodes(pod, state, sample_pct=0, start_index=start)
        start = (start + fit.processed) % n_nodes
        totals = prioritize(pod, state, fit.feasible)
        h = h_all[attempt]
        attempt += 1
        if not totals:
            want[pod.name] = None
            continue
        node = max(totals, key=lambda m: (totals[m], int(h[idx_of[m]])))
        want[pod.name] = node
        pod.node_name = node
        state.place(pod)
    diffs = _diff(got, want)
    n_diffs = len(diffs)
    if wave_batches == 0:
        n_diffs += 1
        diffs = [("__wave_batches__", 0, ">=1")] + diffs
    if compat_fallbacks:
        n_diffs += 1
        diffs = [("__fallback_sampling_compat__", compat_fallbacks, 0)] + diffs
    return {
        "nodes": n_nodes,
        "pods": n_pods,
        "wave_batches": wave_batches,
        "bound_device": sum(1 for v in got.values() if v),
        "bound_oracle": sum(1 for v in want.values() if v),
        "diffs": n_diffs,
        "first_diffs": diffs[:5],
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def check_resident_vs_oracle(n_nodes=1000, n_pods=5000) -> dict:
    """Resident drain loop (ops/resident.py speculation/admission fixed
    point + tail engine) vs the serial oracle AND vs the residentDrain:false
    drain (sig_scan/host-greedy machinery) — the resident path's
    bit-identity evidence at bench scale, kill switch included."""
    import copy

    from kubernetes_tpu.oracle.pipeline import schedule_one
    from kubernetes_tpu.oracle.state import OracleState

    nodes = _basic_nodes(n_nodes)
    pods = _basic_pods(n_pods, seed=31)
    t0 = time.perf_counter()
    got, sched = _drain(nodes, copy.deepcopy(pods), return_sched=True)
    resident_batches = sched.metrics["resident_batches"]
    off = _drain(nodes, copy.deepcopy(pods), resident_drain=False)

    state = OracleState.build(nodes)
    want: Dict[str, Optional[str]] = {}
    for pod in copy.deepcopy(pods):
        r = schedule_one(pod, state)
        want[pod.name] = r.node
        if r.node is not None:
            pod.node_name = r.node
            state.place(pod)
    diffs = _diff(got, want) + _diff(got, off)
    n_diffs = len(diffs)
    if resident_batches == 0:
        # the check certifies the RESIDENT path; a silent fallback would
        # make its zero-diff claim vacuous — fail loud
        n_diffs += 1
        diffs = [("__resident_batches__", 0, ">=1")] + diffs
    return {
        "nodes": n_nodes,
        "pods": n_pods,
        "resident_batches": resident_batches,
        "bound_resident": sum(1 for v in got.values() if v),
        "bound_oracle": sum(1 for v in want.values() if v),
        "diffs": n_diffs,
        "first_diffs": diffs[:5],
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def _gang_workload(n_nodes, n_gangs, seed=12):
    """Plain pods + gangs of mixed feasibility on tight nodes — partial
    gangs MUST roll back, so the check exercises the rollback algebra."""
    from kubernetes_tpu.api.resource import Resource
    from kubernetes_tpu.api.types import Container, Node, Pod
    from kubernetes_tpu.workloads.gang import PodGroup

    rng = random.Random(seed)
    nodes = [
        Node(
            name=f"node-{i}",
            labels={
                "topology.kubernetes.io/zone": f"zone-{i % 4}",
                "kubernetes.io/hostname": f"node-{i}",
            },
            capacity=Resource.from_map(
                {"cpu": rng.choice(["2", "4"]), "memory": "8Gi", "pods": 110}
            ),
        )
        for i in range(n_nodes)
    ]
    pods, groups = [], {}
    for gi in range(n_gangs):
        size = rng.randrange(2, 6)
        name = f"gang-{gi}"
        groups[f"default/{name}"] = PodGroup(
            name=name, min_member=rng.randrange(2, size + 1)
        )
        for m in range(size):
            pods.append(
                Pod(
                    name=f"{name}-{m}",
                    pod_group=name,
                    containers=[
                        Container(
                            name="c",
                            requests={
                                "cpu": rng.choice(["200m", "800m", "1800m"]),
                                "memory": "256Mi",
                            },
                        )
                    ],
                )
            )
        if gi % 3 == 0:
            pods.append(
                Pod(
                    name=f"plain-{gi}",
                    containers=[
                        Container(name="c", requests={"cpu": "150m"})
                    ],
                )
            )
    return nodes, pods, groups


def check_gang_vs_oracle(n_nodes=60, n_gangs=120) -> dict:
    """Workloads-tier gang admission (ops/coscheduling.py: all-or-nothing
    checkpoint/rollback over the factored algebra) vs the serial gang
    oracle replaying the same canonical order — zero diffs required."""
    import copy

    from kubernetes_tpu.oracle.state import OracleState
    from kubernetes_tpu.oracle.workloads import WorkloadOracle

    nodes, pods, groups = _gang_workload(n_nodes, n_gangs)
    t0 = time.perf_counter()
    got, sched = _drain_workloads(nodes, pods, groups)
    wl_batches = sched.metrics["workload_batches"]

    oracle = WorkloadOracle(
        state=OracleState.build(nodes), groups=copy.deepcopy(groups)
    )
    res = oracle.schedule(copy.deepcopy(pods))
    diffs = _diff(got, res.placements)
    n_diffs = len(diffs)
    if wl_batches == 0:
        n_diffs += 1
        diffs = [("__workload_batches__", 0, ">=1")] + diffs
    if sched.metrics["gang_rolled_back"] == 0:
        # the check certifies ROLLBACK; a workload where no gang ever
        # rolls back would make the claim vacuous — fail loud
        n_diffs += 1
        diffs = [("__gang_rolled_back__", 0, ">=1")] + diffs
    return {
        "nodes": n_nodes,
        "pods": len(pods),
        "gangs": n_gangs,
        "workload_batches": wl_batches,
        "gangs_rolled_back": sched.metrics["gang_rolled_back"],
        "bound_kernel": sum(1 for v in got.values() if v),
        "bound_oracle": sum(1 for v in res.placements.values() if v),
        "diffs": n_diffs,
        "first_diffs": diffs[:5],
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def _dra_workload(n_nodes, n_pods, seed=9):
    from kubernetes_tpu.api import dra
    from kubernetes_tpu.api.types import Container, Pod

    rng = random.Random(seed)
    nodes = _basic_nodes(n_nodes)
    slices = []
    for i in range(n_nodes):
        if i % 2:
            continue
        slices.append(
            dra.ResourceSlice(
                name=f"sl-{i}",
                node_name=f"node-{i}",
                driver="drv",
                pool=f"pool-{i}",
                devices=tuple(
                    dra.Device(
                        name=f"dev-{i}-{j}",
                        attributes=(
                            ("vendor", "x" if j % 2 else "y"),
                            ("mem", rng.choice(["16", "32"])),
                        ),
                    )
                    for j in range(rng.randrange(1, 5))
                ),
            )
        )
    classes = {
        "gpu": dra.DeviceClass(
            name="gpu",
            selectors=(dra.DeviceSelector("vendor", "In", ("x",)),),
        ),
        "any": dra.DeviceClass(name="any"),
    }
    claims, pods = {}, []
    for i in range(n_pods):
        mode_all = rng.random() < 0.2
        c = dra.ResourceClaim(
            name=f"claim-{i}",
            requests=(
                dra.DeviceRequest(
                    name="r",
                    device_class_name=rng.choice(["gpu", "any"]),
                    count=rng.randrange(1, 3),
                    allocation_mode=(
                        dra.ALLOCATION_MODE_ALL
                        if mode_all
                        else dra.ALLOCATION_MODE_EXACT
                    ),
                    selectors=(
                        (dra.DeviceSelector("mem", "In", ("32",)),)
                        if rng.random() < 0.3
                        else ()
                    ),
                ),
            ),
        )
        claims[c.key] = c
        pods.append(
            Pod(
                name=f"dp-{i}",
                containers=[Container(name="c", requests={"cpu": "100m"})],
                resource_claims=(c.name,),
            )
        )
    return nodes, slices, classes, claims, pods


def check_dra_vs_oracle(n_nodes=200, n_pods=600) -> dict:
    """Batched DRA allocation (ops/dra.py device-matching kernel inside
    the workloads admission scan) vs the serial structured-allocator
    oracle — placements AND claim→node pinnings, zero diffs required."""
    import copy

    from kubernetes_tpu.oracle.state import OracleState
    from kubernetes_tpu.oracle.workloads import WorkloadOracle

    nodes, slices, classes, claims, pods = _dra_workload(n_nodes, n_pods)
    t0 = time.perf_counter()
    got, sched = _drain_workloads(
        nodes, pods, {}, slices=slices, classes=classes, claims=claims
    )
    wl_batches = sched.metrics["workload_batches"]

    oracle = WorkloadOracle(
        state=OracleState.build(nodes),
        slices=copy.deepcopy(slices),
        device_classes=copy.deepcopy(classes),
        claims=copy.deepcopy(claims),
    )
    res = oracle.schedule(copy.deepcopy(pods))
    diffs = _diff(got, res.placements)
    # claim pinning identity through the live claim cache
    for key, want_node in res.claim_nodes.items():
        c = sched.claim_cache.get(key)
        have = (
            c.allocation.node_name
            if c is not None and c.allocation is not None
            else None
        )
        if have != want_node:
            diffs.append((f"claim:{key}", have, want_node))
    n_diffs = len(diffs)
    if wl_batches == 0:
        n_diffs += 1
        diffs = [("__workload_batches__", 0, ">=1")] + diffs
    return {
        "nodes": n_nodes,
        "pods": n_pods,
        "workload_batches": wl_batches,
        "bound_kernel": sum(1 for v in got.values() if v),
        "bound_oracle": sum(1 for v in res.placements.values() if v),
        "claims_allocated": len(res.claim_nodes),
        "diffs": n_diffs,
        "first_diffs": diffs[:5],
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def _drain_workloads(
    nodes, pods, groups, slices=(), classes=None, claims=None, **cfg_kw
):
    """A FakeCluster drain wired for the workloads tier (PodGroups +
    DRA objects), returning ({pod: node}, scheduler)."""
    import copy

    from kubernetes_tpu.framework.config import SchedulerConfiguration
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import FakeCluster

    api = FakeCluster()
    cfg = SchedulerConfiguration(batch_size=4096)
    cfg.feature_gates["DynamicResourceAllocation"] = True
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    s = Scheduler(configuration=cfg)
    api.connect(s)
    for n in nodes:
        api.create_node(n)
    for pg in groups.values():
        api.pod_groups.create(pg)
    for cls in (classes or {}).values():
        api.device_classes.create(cls)
    for sl in slices:
        api.resource_slices.create(sl)
    for c in (claims or {}).values():
        api.resource_claims.create(c)
    for p in pods:
        api.create_pod(copy.deepcopy(p))
    got = {}
    for o in s.schedule_pending():
        got[o.pod.name] = o.node
    return got, s


def check_plan_vs_oracle(
    n_nodes=60, n_fill=1500, n_backlog=32, k=24, seed=991
) -> dict:
    """Counterfactual planner tier vs the serial forked-snapshot oracle
    (PLANNER.md): K mixed forks — clone-adds, cordons, evictions,
    capacity scales, removals — over a spread-constrained backlog with a
    gang, per-fork placements / gang verdicts / admission counts /
    density bit-identical.  Fails loud when the K-vmap kernel path is not
    engaged (kernel must cost exactly ONE dispatch for all K forks)."""
    from kubernetes_tpu.api.types import (
        Container,
        LabelSelector,
        Pod,
        TopologySpreadConstraint,
    )
    from kubernetes_tpu.framework.config import SchedulerConfiguration
    from kubernetes_tpu.planner import Fork, simulate_forks
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import FakeCluster
    from kubernetes_tpu.workloads.gang import PodGroup

    rng = random.Random(seed)
    t0 = time.perf_counter()
    api = FakeCluster()
    sched = Scheduler(configuration=SchedulerConfiguration(batch_size=4096))
    api.connect(sched)
    for n in _basic_nodes(n_nodes, zones=3):
        api.create_node(n)
    for p in _basic_pods(n_fill, seed=seed):
        p.priority = 2
        api.create_pod(p)
    sched.schedule_pending()
    backlog = []
    for i in range(n_backlog):
        tsc = ()
        if i % 3 == 0:
            tsc = (
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key="topology.kubernetes.io/zone",
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=LabelSelector(
                        match_labels={"app": "plan"}
                    ),
                ),
            )
        backlog.append(
            Pod(
                name=f"plan-{i}",
                labels={"app": "plan"},
                topology_spread_constraints=tsc,
                containers=[
                    Container(
                        name="c",
                        requests={
                            "cpu": f"{rng.choice([500, 900, 1500])}m",
                            "memory": "256Mi",
                        },
                    )
                ],
            )
        )
    with sched._mu:
        sched.gangs.upsert(PodGroup(name="pg", min_member=3))
    backlog += [
        Pod(
            name=f"pg-{m}",
            pod_group="pg",
            containers=[
                Container(name="c", requests={"cpu": "700m", "memory": "128Mi"})
            ],
        )
        for m in range(3)
    ]
    placed = sched.cache.placed_pods()
    names = [f"node-{i}" for i in range(n_nodes)]
    forks = [Fork(label="baseline")]
    while len(forks) < k:
        i = len(forks)
        kind = i % 5
        if kind == 0:
            t = rng.choice(names)
            forks.append(
                Fork(
                    label=f"add{i}",
                    add=tuple(
                        (t, f"{t}~cf{i}-{j}") for j in range(1 + i % 3)
                    ),
                )
            )
        elif kind == 1:
            forks.append(
                Fork(label=f"cordon{i}", cordon=(rng.choice(names),))
            )
        elif kind == 2:
            forks.append(
                Fork(
                    label=f"evict{i}",
                    evict=tuple(
                        p.uid
                        for p in rng.sample(placed, min(6, len(placed)))
                    ),
                )
            )
        elif kind == 3:
            forks.append(
                Fork(
                    label=f"scale{i}",
                    scale=((rng.choice(names), rng.choice([1, 3]), 2),),
                )
            )
        else:
            forks.append(
                Fork(label=f"remove{i}", remove=(rng.choice(names),))
            )
    kern = simulate_forks(sched, forks, backlog, planner="paritycheck")
    serial = simulate_forks(
        sched, forks, backlog, planner="paritycheck", use_kernel=False
    )
    diffs: List = []
    if kern.engine != "kernel" or kern.dispatches != 1:
        diffs.append(
            ("__kernel_engaged__", (kern.engine, kern.dispatches), ("kernel", 1))
        )
    for fk, fs in zip(kern.forks, serial.forks):
        for key in (
            "placements",
            "admitted",
            "unschedulable",
            "density_ppm",
            "gang_admitted",
        ):
            if fk[key] != fs[key]:
                diffs.append((f"{fk['label']}:{key}", fk[key], fs[key]))
    return {
        "nodes": n_nodes,
        "fill": n_fill,
        "backlog": len(backlog),
        "forks": len(forks),
        "kernel_dispatches": kern.dispatches,
        "admitted_baseline": kern.forks[0]["admitted"],
        "diffs": len(diffs),
        "first_diffs": [
            (lbl, str(a)[:120], str(b)[:120]) for lbl, a, b in diffs[:5]
        ],
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def check_multichip_vs_singlechip(
    n_nodes=120, n_pods=600, n_cross=240, n_gangs=24
) -> dict:
    """Mesh-partitioned admission engine (ISSUE 14 / MULTICHIP.md) vs the
    single-chip kernels: the SAME mixed workload — resident/fast basics,
    wave-shaped cross-pod constraints, gang coscheduling — drains with
    meshDispatch OFF, then ON over a pods-major mesh (all devices on the
    pods axis) and a nodes-major mesh (all devices on the nodes axis).
    Decisions must be bit-identical in all three modes, and on a
    multi-device backend the mesh runs must PROVE engagement (scheduler
    mesh resolved + ledger multi-device dispatches), or the check fails
    loud — a silently-replicated run would make the parity claim vacuous.
    On a single-device backend the check degrades to a 1x1 mesh identity
    (still zero diffs required) and reports devices=1."""
    import copy

    import jax

    devices = len(jax.devices())
    t0 = time.perf_counter()
    nodes = _basic_nodes(n_nodes)
    pods = _basic_pods(n_pods) + _cross_pod_pods(n_cross)
    gnodes, gpods, groups = _gang_workload(max(n_nodes // 2, 8), n_gangs)

    def drains(**cfg_kw):
        got, s = _drain(
            nodes, copy.deepcopy(pods), return_sched=True, **cfg_kw
        )
        got2, s2 = _drain_workloads(
            gnodes, copy.deepcopy(gpods), copy.deepcopy(groups), **cfg_kw
        )
        return got, got2, s, s2

    base, gbase, _s, _s2 = drains(mesh_dispatch=False)
    diffs: List = []
    mesh_runs = {}
    for label, pods_axis in (("pods_major", None), ("nodes_major", 1)):
        got, ggot, s, s2 = drains(
            mesh_dispatch=True, mesh_pods_axis=pods_axis
        )
        diffs += [
            (f"{label}:{k}", a, b) for k, a, b in _diff(base, got)
        ] + [(f"{label}:gang:{k}", a, b) for k, a, b in _diff(gbase, ggot)]
        mesh_shape = f"{s.mesh.shape['pods']}x{s.mesh.shape['nodes']}"
        multi = (
            s.kernels.stats()["multi_device_dispatches"]
            + s2.kernels.stats()["multi_device_dispatches"]
        )
        mesh_runs[label] = {"mesh": mesh_shape, "multi_device_dispatches": multi}
        if s.mesh is None or s2.mesh is None:
            diffs.append((f"__{label}_mesh_resolved__", None, "mesh"))
        if devices > 1 and multi == 0:
            # a mesh run whose dispatches never actually partitioned
            # proves nothing — fail loud rather than certify replication
            diffs.append((f"__{label}_engaged__", 0, ">=1"))
    return {
        "devices": devices,
        "nodes": n_nodes,
        "pods": len(pods),
        "gang_pods": len(gpods),
        "mesh_runs": mesh_runs,
        "diffs": len(diffs),
        "first_diffs": [
            (lbl, str(a)[:80], str(b)[:80]) for lbl, a, b in diffs[:5]
        ],
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def check_breaker_open_vs_oracle(n_nodes=300, n_pods=900) -> dict:
    """Breaker-degraded drain vs the serial oracle (ISSUE 15): with the
    wave AND gang-scan breakers latched open, every cross-pod batch
    drains on the one-pod host-oracle fallback — placements must be
    bit-identical to the oracle (that is the entire point of routing an
    open breaker to a parity-certified engine), and the fallback must
    actually ENGAGE (wave_fallback{reason=breaker} > 0, zero device
    batches) or the claim is vacuous."""
    import copy

    from kubernetes_tpu.framework.config import SchedulerConfiguration
    from kubernetes_tpu.oracle.pipeline import schedule_one
    from kubernetes_tpu.oracle.state import OracleState
    from kubernetes_tpu.scheduler import Scheduler

    nodes = _basic_nodes(n_nodes, zones=6)
    pods = _cross_pod_pods(n_pods)
    t0 = time.perf_counter()
    s = Scheduler(configuration=SchedulerConfiguration())
    s.kernels.force_breaker_open("wave.wave_run")
    s.kernels.force_breaker_open("gang.gang_run")
    s.kernels.force_breaker_open("chain.chain_dispatch")
    got: Dict[str, Optional[str]] = {}
    s.binding_sink = lambda pod, node: got.__setitem__(pod.name, node)
    s.mirror.e_cap_hint = len(pods) + s.config.batch_size + 128
    for n in nodes:
        s.on_node_add(n)
    for p in copy.deepcopy(pods):
        s.on_pod_add(p)
    outs = s.schedule_pending()
    for o in outs:
        got.setdefault(o.pod.name, o.node)
    breaker_fallbacks = int(
        s.prom.wave_fallback.value(reason="breaker")
    )
    device_batches = (
        s.metrics["wave_batches"] + s.metrics["scan_batches"]
    )

    state = OracleState.build(nodes)
    want: Dict[str, Optional[str]] = {}
    for pod in copy.deepcopy(pods):
        r = schedule_one(pod, state)
        want[pod.name] = r.node
        if r.node is not None:
            pod.node_name = r.node
            state.place(pod)
    diffs = _diff(got, want)
    n_diffs = len(diffs)
    if breaker_fallbacks == 0 or device_batches > 0:
        n_diffs += 1
        diffs = [
            ("__breaker_engaged__", breaker_fallbacks, device_batches)
        ] + diffs
    return {
        "nodes": n_nodes,
        "pods": n_pods,
        "breaker_fallbacks": breaker_fallbacks,
        "device_batches": device_batches,
        "bound_degraded": sum(1 for v in got.values() if v),
        "bound_oracle": sum(1 for v in want.values() if v),
        "diffs": n_diffs,
        "first_diffs": diffs[:5],
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def run_checks(ns_nodes=10000, ns_pods=50000) -> dict:
    checks = {
        "cross_batch_devfast_vs_hostgreedy": check_cross_batch(
            ns_nodes, ns_pods
        ),
        "sampling_compat_vs_serial_oracle": check_compat_vs_oracle(),
        "wave_dispatch_vs_serial_oracle": check_wave_vs_oracle(),
        "port_carry_vs_serial_oracle": check_port_carry_vs_oracle(),
        "compat_wave_vs_serial_oracle": check_compat_wave_vs_oracle(),
        "resident_drain_vs_serial_oracle": check_resident_vs_oracle(),
        "gang_admission_vs_serial_oracle": check_gang_vs_oracle(),
        "dra_allocation_vs_serial_oracle": check_dra_vs_oracle(),
        "plan_vs_serial_oracle": check_plan_vs_oracle(),
        "multichip_vs_singlechip": check_multichip_vs_singlechip(),
        "breaker_open_vs_serial_oracle": check_breaker_open_vs_oracle(),
    }
    return {
        "checks": checks,
        "total_diffs": sum(c["diffs"] for c in checks.values()),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="paritycheck")
    ap.add_argument("--out", default="PARITY.json")
    ap.add_argument("--ns-nodes", type=int, default=10000)
    ap.add_argument("--ns-pods", type=int, default=50000)
    args = ap.parse_args(argv)
    result = run_checks(args.ns_nodes, args.ns_pods)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"total_diffs": result["total_diffs"], "out": args.out}))
    return 0 if result["total_diffs"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
