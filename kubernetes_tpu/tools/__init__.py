"""Operational tooling: the kubemark-style scale simulator."""
