"""Host half of the fast commit path: exact sequential-equivalent greedy.

Given per-signature static scores/masks from ops.fastpath.static_eval,
replays the reference's one-pod-at-a-time argmax commit
(schedule_one.go:65 ScheduleOne → selectHost first-max policy) in pure
integer arithmetic IDENTICAL to the gang kernels' formulas (ops/gang.py
scan step: LeastAllocated, BalancedAllocation, resource-fit, pod-count),
so decisions bit-match the scan — property-tested in tests/test_fastpath.py.

Data structure: one lazy heap per signature keyed (-score, node).  A commit
touches exactly one node; fresh entries for that node are pushed into every
ACTIVE signature heap, and stale entries are re-validated on pop (the key
is recomputed; mismatches are re-pushed).  Resource infeasibility is
monotone within a batch (usage only grows), so infeasible pops are dropped
permanently.  Per-pod cost is O(active_signatures · log N) host work with
no device round-trips.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.types import LabelSelector, Pod
from kubernetes_tpu.snapshot.schema import (
    LANE_CPU,
    LANE_MEM,
    MEM_UNIT,
    N_FIXED_LANES,
    NodeTensors,
    ResourceLanes,
)

MAX = 100  # MaxNodeScore


# ---------------------------------------------------------------------------
# Placed-term interaction probes — the fast gate's "could any placed pod's
# (anti-)affinity/spread term admit this newcomer" check (_fast_gate_ok).
# Conservative: may claim interaction where none exists (only costs fast-path
# eligibility, never correctness).
# ---------------------------------------------------------------------------


def _selector_matches(sel: Optional[LabelSelector], labels: Dict[str, str]) -> bool:
    """LabelSelector match; unknown operators match conservatively."""
    if sel is None:
        # a nil selector matches nothing (labels.Nothing()) in spread
        # counting; the callers that mean "everything" pass empty selector
        return False
    for k, v in (sel.match_labels or {}).items():
        if labels.get(k) != v:
            return False
    for e in sel.match_expressions or ():
        op = e.operator
        if op == "In":
            if labels.get(e.key) not in (e.values or ()):
                return False
        elif op == "NotIn":
            if e.key in labels and labels[e.key] in (e.values or ()):
                return False
        elif op == "Exists":
            if e.key not in labels:
                return False
        elif op == "DoesNotExist":
            if e.key in labels:
                return False
        else:  # unknown op: conservative
            return True
    return True


class _Probe:
    """One selector-with-namespace-scope an interacting pod would match."""

    __slots__ = ("sel", "ns_any", "namespaces")

    def __init__(self, sel, ns_any: bool, namespaces: Tuple[str, ...]):
        self.sel = sel
        self.ns_any = ns_any
        self.namespaces = namespaces

    def admits(self, pod: Pod) -> bool:
        if not self.ns_any and pod.namespace not in self.namespaces:
            return False
        return _selector_matches(self.sel, pod.labels)


def _pod_probes(pod: Pod) -> List[_Probe]:
    """Probes for every selector through which ``pod`` could interact with
    a newcomer: spread constraints count same-namespace peers only
    (podtopologyspread/filtering.go:236-310); affinity/anti terms scope by
    their namespace set, a namespaceSelector conservatively admitting
    everything (interpodaffinity/filtering.go:306-365)."""
    probes: List[_Probe] = []
    for c in pod.topology_spread_constraints:
        probes.append(_Probe(c.label_selector, False, (pod.namespace,)))
    aff = pod.affinity
    terms = []
    if aff is not None:
        for grp in (aff.pod_affinity, aff.pod_anti_affinity):
            if grp is None:
                continue
            terms.extend(
                grp.required_during_scheduling_ignored_during_execution or ()
            )
            for wt in (
                grp.preferred_during_scheduling_ignored_during_execution or ()
            ):
                terms.append(wt.pod_affinity_term)
    for t in terms:
        if getattr(t, "namespace_selector", None) is not None:
            probes.append(_Probe(t.label_selector, True, ()))
        else:
            nss = tuple(t.namespaces or ()) or (pod.namespace,)
            probes.append(_Probe(t.label_selector, False, nss))
    return probes


def spec_key(pod: Pod):
    """Content-addressed identity of every spec field signature_key reads —
    pods stamped from the same template share one entry in the scheduler's
    spec→signature cache, so the full computation (quantity parsing, lane
    packing) runs once per distinct spec instead of once per pod.  Returns
    None when a spec field is unhashable (custom mappings) — callers fall
    back to the full computation."""
    try:
        out = (
            tuple(
                (
                    c.name,
                    tuple(sorted((c.requests or {}).items())),
                    c.ports,
                    c.restart_policy,
                )
                for c in pod.containers
            ),
            tuple(
                (
                    c.name,
                    tuple(sorted((c.requests or {}).items())),
                    c.ports,
                    c.restart_policy,
                )
                for c in pod.init_containers
            ),
            tuple(sorted((pod.overhead or {}).items())),
            pod.tolerations,
            tuple(sorted(pod.node_selector.items())),
            pod.affinity,
            pod.images,
            pod.node_name,
            bool(pod.nominated_node_name),
            bool(pod.topology_spread_constraints),
            pod.host_network,
        )
        hash(out)  # selectors etc. hold dicts — probe before caching on it
        return out
    except TypeError:
        return None


_SK_MISSING = object()


def spec_key_memo(pod: Pod):
    """spec_key memoized on the pod object: the tuple build itself costs
    ~µs and the hot paths ask several times per pod.  Safe because spec
    updates arrive as NEW Pod objects (the compute_requests memo
    contract), so the memo can never go stale."""
    d = pod.__dict__
    sk = d.get("_speckey_memo", _SK_MISSING)
    if sk is _SK_MISSING:
        sk = spec_key(pod)
        d["_speckey_memo"] = sk
    return sk


def signature_key(pod: Pod, lanes: ResourceLanes, n_lanes: int):
    """Hashable identity of everything that affects a pod's row in the
    resource-only pipeline; None when the pod is not fast-path eligible
    (spread / inter-pod terms / host ports / preset node / nomination)."""
    if pod.topology_spread_constraints:
        return None
    if pod.affinity is not None and (
        pod.affinity.pod_affinity is not None
        or pod.affinity.pod_anti_affinity is not None
    ):
        return None
    if pod.host_ports() or pod.nominated_node_name:
        return None
    req = pod.compute_requests()
    row = tuple(lanes.request_row(req, n_lanes).tolist())
    nz = req.non_zero_defaulted()
    node_aff = pod.affinity.node_affinity if pod.affinity is not None else None
    return (
        row,
        (nz.milli_cpu, -(-nz.memory // MEM_UNIT)),
        pod.tolerations,
        tuple(sorted(pod.node_selector.items())),
        node_aff,
        pod.images,
        pod.node_name,
    )


@dataclass
class Signature:
    req_row: Tuple[int, ...]
    nz0: int
    nz1: int
    all_zero: bool
    static_ok: np.ndarray  # bool [N]
    img: Optional[List[int]] = None  # i64 per node, None when unused
    sid: int = -1  # row in the device sig_scan stack (scheduler-assigned)
    remaining: int = 0  # pods of this signature still unplaced
    # NOTE: heap/known-score state lives on each FastCommitter (keyed by
    # id(sig)) because Signature objects are shared across committers.


class FastCommitter:
    """One batch's sequential greedy over host state (numpy mirror copy)."""

    def __init__(
        self,
        nodes: NodeTensors,
        weights: Tuple[int, ...],
        check_fit: bool = True,
    ):
        # weights in gang.WEIGHT_ORDER
        (
            self.w_taint,
            self.w_naff,
            self.w_spread,
            self.w_ip,
            self.w_fit,
            self.w_bal,
            self.w_img,
        ) = weights
        self.check_fit = check_fit
        n = nodes.valid.shape[0]
        self.n = n
        self.rn = nodes.allocatable.shape[1]
        # python-int state columns (hot loop avoids numpy scalar overhead)
        self.alloc_rows = nodes.allocatable.tolist()
        self.used_rows = [list(r) for r in nodes.requested.tolist()]
        self.alloc0 = [r[LANE_CPU] for r in self.alloc_rows]
        self.alloc1 = [r[LANE_MEM] for r in self.alloc_rows]
        self.nz0 = [int(x) for x in nodes.nonzero_req[:, 0]]
        self.nz1 = [int(x) for x in nodes.nonzero_req[:, 1]]
        self.num_pods = [int(x) for x in nodes.num_pods.tolist()]
        self.allowed = [int(x) for x in nodes.allowed_pods.tolist()]
        self.touched: set = set()
        # per-committer lazy-heap state, keyed id(sig): Signature objects
        # are SHARED across committers (scheduler + shadow + diag), so the
        # heaps must live here — a heap built against one committer's usage
        # is stale-LOW for another's, which breaks the argmax
        self._heaps: Dict[int, list] = {}
        self._known: Dict[int, List[int]] = {}

    def invalidate_heaps(self) -> None:
        """Drop all per-signature heaps — required after the committer's
        state advanced by REPLAY (device-batch harvests) rather than by its
        own run(): replayed commits can RAISE node scores, which the lazy
        heaps would otherwise never see."""
        self._heaps.clear()
        self._known.clear()

    # ----- integer score/feasibility — MUST match ops/gang.py scan step -----

    def score_int(self, n: int, sig: Signature) -> int:
        a0 = self.alloc0[n]
        a1 = self.alloc1[n]
        total = 0
        if self.w_fit:
            s = 0
            w = 0
            if a0 > 0:
                nz = self.nz0[n] + sig.nz0
                s += 0 if nz > a0 else (a0 - nz) * MAX // a0
                w += 1
            if a1 > 0:
                nz = self.nz1[n] + sig.nz1
                s += 0 if nz > a1 else (a1 - nz) * MAX // a1
                w += 1
            total += self.w_fit * (s // w if w else 0)
        if self.w_bal:
            if a0 > 0 and a1 > 0:
                r0 = self.used_rows[n][LANE_CPU] + sig.req_row[LANE_CPU]
                r1 = self.used_rows[n][LANE_MEM] + sig.req_row[LANE_MEM]
                if r0 > a0:
                    r0 = a0
                if r1 > a1:
                    r1 = a1
                d = r0 * a1 - r1 * a0
                if d < 0:
                    d = -d
                den = a0 * a1
                bal = MAX - (50 * d + den - 1) // den
            else:
                bal = MAX
            total += self.w_bal * bal
        if self.w_img and sig.img is not None:
            total += self.w_img * sig.img[n]
        return total

    def feasible_int(self, n: int, sig: Signature) -> bool:
        if not self.check_fit:
            return True
        if self.num_pods[n] + 1 > self.allowed[n]:
            return False
        if sig.all_zero:
            return True
        used = self.used_rows[n]
        alloc = self.alloc_rows[n]
        rn = self.rn
        for r, v in enumerate(sig.req_row):
            if r >= N_FIXED_LANES and v == 0:
                continue
            avail = (alloc[r] - used[r]) if r < rn else 0
            if v > avail:
                return False
        return True

    # ----- the greedy -------------------------------------------------------

    def _build_heap(self, sig: Signature) -> list:
        # vectorized initial scores (numpy), exact-int formulas
        a0 = np.asarray(self.alloc0, dtype=np.int64)
        a1 = np.asarray(self.alloc1, dtype=np.int64)
        total = np.zeros(self.n, dtype=np.int64)
        if self.w_fit:
            nz0 = np.asarray(self.nz0, dtype=np.int64) + sig.nz0
            nz1 = np.asarray(self.nz1, dtype=np.int64) + sig.nz1
            f0 = np.where(nz0 > a0, 0, (a0 - nz0) * MAX // np.maximum(a0, 1))
            f1 = np.where(nz1 > a1, 0, (a1 - nz1) * MAX // np.maximum(a1, 1))
            h0 = a0 > 0
            h1 = a1 > 0
            w = h0.astype(np.int64) + h1
            least = np.where(
                w > 0,
                (np.where(h0, f0, 0) + np.where(h1, f1, 0)) // np.maximum(w, 1),
                0,
            )
            total += self.w_fit * least
        if self.w_bal:
            u0 = np.asarray([r[LANE_CPU] for r in self.used_rows], np.int64)
            u1 = np.asarray([r[LANE_MEM] for r in self.used_rows], np.int64)
            r0 = np.minimum(u0 + sig.req_row[LANE_CPU], a0)
            r1 = np.minimum(u1 + sig.req_row[LANE_MEM], a1)
            d = np.abs(r0 * a1 - r1 * a0)
            den = np.maximum(a0 * a1, 1)
            bal = np.where(
                (a0 > 0) & (a1 > 0), MAX - (50 * d + den - 1) // den, MAX
            )
            total += self.w_bal * bal
        if self.w_img and sig.img is not None:
            total += self.w_img * np.asarray(sig.img, dtype=np.int64)
        self._known[id(sig)] = total.tolist()
        idx = np.nonzero(sig.static_ok)[0]
        heap = list(zip((-total[idx]).tolist(), idx.tolist()))
        heapq.heapify(heap)
        return heap

    def run(self, pod_sigs: Sequence[Signature]) -> List[int]:
        """pod_sigs[i] is pod i's signature (shared objects).  Returns the
        chosen node index per pod (-1 unschedulable), in batch order.

        The argmax pop-revalidation and the post-commit push-update walk
        inline feasible_int/score_int with hoisted locals — this loop is
        the resident drain's host-side tail engine, so per-visit work is
        a handful of integer ops instead of bound-method calls (the
        formulas are byte-for-byte the same; the shadow/property tests
        pin the equivalence)."""
        for sig in pod_sigs:
            sig.remaining += 1
        active = {id(s): s for s in pod_sigs}
        act_list = list(active.values())
        committed_any = False  # drives the end-of-run stale-heap eviction
        choices: List[int] = []
        heaps = self._heaps
        known_map = self._known
        alloc0 = self.alloc0
        alloc1 = self.alloc1
        alloc_rows = self.alloc_rows
        used_rows = self.used_rows
        nz0l = self.nz0
        nz1l = self.nz1
        num_pods = self.num_pods
        allowed = self.allowed
        rn = self.rn
        check_fit = self.check_fit
        w_fit = self.w_fit
        w_bal = self.w_bal
        w_img = self.w_img
        touched_add = self.touched.add
        heappop = heapq.heappop
        heapreplace = heapq.heapreplace
        heappush = heapq.heappush
        for sig in pod_sigs:
            sid = id(sig)
            heap = heaps.get(sid)
            if heap is None:
                heap = heaps[sid] = self._build_heap(sig)
            known = known_map[sid]
            choice = -1
            s_nz0 = sig.nz0
            s_nz1 = sig.nz1
            s_req = sig.req_row
            s_az = sig.all_zero
            while heap:
                negsc, n = heap[0]
                # ---- feasible_int, inlined ----
                if check_fit:
                    if num_pods[n] + 1 > allowed[n]:
                        heappop(heap)  # monotone: never feasible again
                        continue
                    if not s_az:
                        used = used_rows[n]
                        alloc = alloc_rows[n]
                        bad = False
                        for r, v in enumerate(s_req):
                            if r >= N_FIXED_LANES and v == 0:
                                continue
                            avail = (alloc[r] - used[r]) if r < rn else 0
                            if v > avail:
                                bad = True
                                break
                        if bad:
                            heappop(heap)
                            continue
                # ---- revalidate: _known IS the current score (the
                # push-update walk below maintains it for every feasible
                # node under every seen signature after every commit) ----
                total = known[n]
                if -total == negsc:
                    choice = n
                    break
                heapreplace(heap, (-total, n))  # stale → re-rank
            sig.remaining -= 1
            choices.append(choice)
            if choice < 0:
                continue
            # ---- commit: one node touched; hoist its state once ----
            n = choice
            used = used_rows[n]
            for r, v in enumerate(s_req):
                if r < rn:
                    used[r] += v
            nz0l[n] += s_nz0
            nz1l[n] += s_nz1
            num_pods[n] += 1
            touched_add(n)
            committed_any = True
            # Invariant: heap keys never stale-LOW.  Score decreases are
            # healed by pop-time revalidation; only INCREASES need a fresh
            # push (and only into still-active heaps).
            a0 = alloc0[n]
            a1 = alloc1[n]
            h0 = a0 > 0
            h1 = a1 > 0
            nzn0 = nz0l[n]
            nzn1 = nz1l[n]
            u0 = used[LANE_CPU]
            u1 = used[LANE_MEM]
            den = a0 * a1
            fit_w = (1 if h0 else 0) + (1 if h1 else 0)
            # usage is monotone within a lineage, so a node that no
            # longer fits a signature never fits it again — its heap
            # entries drain via pop-and-drop and no fresh push (or known
            # update) is ever needed.  One pod-count compare skips the
            # whole walk on full nodes (the drain-tail regime).
            node_open = not check_fit or num_pods[n] < allowed[n]
            alloc = alloc_rows[n]
            for other in act_list:
                oid = id(other)
                oheap = heaps.get(oid)
                # NOTE: no remaining-count skip — _known must stay current
                # for every RETAINED heap through the whole run or the
                # read-based revalidation would rank with stale scores
                # (heaps of signatures absent from this run are evicted
                # below, so every retained heap is walked here).
                # Signatures with no heap yet rebuild _known from scratch
                # on first use (_build_heap), so skipping them is safe.
                if oheap is None or not other.static_ok[n]:
                    continue
                if check_fit:
                    if not node_open:
                        continue
                    if not other.all_zero:
                        bad = False
                        for r, v in enumerate(other.req_row):
                            if r >= N_FIXED_LANES and v == 0:
                                continue
                            avail = (alloc[r] - used[r]) if r < rn else 0
                            if v > avail:
                                bad = True
                                break
                        if bad:
                            continue
                total = 0
                if w_fit:
                    s = 0
                    if h0:
                        nzc = nzn0 + other.nz0
                        s += 0 if nzc > a0 else (a0 - nzc) * MAX // a0
                    if h1:
                        nzc = nzn1 + other.nz1
                        s += 0 if nzc > a1 else (a1 - nzc) * MAX // a1
                    total += w_fit * (s // fit_w if fit_w else 0)
                if w_bal:
                    if h0 and h1:
                        oreq = other.req_row
                        r0 = u0 + oreq[LANE_CPU]
                        r1 = u1 + oreq[LANE_MEM]
                        if r0 > a0:
                            r0 = a0
                        if r1 > a1:
                            r1 = a1
                        d = r0 * a1 - r1 * a0
                        if d < 0:
                            d = -d
                        total += w_bal * (MAX - (50 * d + den - 1) // den)
                    else:
                        total += w_bal * MAX
                if w_img and other.img is not None:
                    total += w_img * other.img[n]
                oknown = known_map[oid]
                if total > oknown[n]:
                    heappush(oheap, (-total, n))
                oknown[n] = total
        # Evict heaps of signatures NOT in this run: they were not walked,
        # so their _known went stale the moment anything committed — a
        # later run must rebuild them from current state (_build_heap).
        # This also bounds heap/known memory by the live signature mix
        # instead of every signature the committer ever saw.  Retained
        # heaps (this run's) were walked on every commit, so the
        # read-based revalidation contract holds at the next run's start.
        if committed_any:
            for sid in [s for s in heaps if s not in active]:
                del heaps[sid]
                known_map.pop(sid, None)
        return choices

    # ----- failure diagnosis (per signature, lazy) --------------------------

    def diagnose(self, sig: Signature, masks: Dict[str, np.ndarray], node_valid: np.ndarray) -> Dict[str, int]:
        """Per-kernel rejected-node counts at CURRENT sim state, first-
        failure attribution in chain order (matches gang.DIAG_KERNELS
        semantics for the static kernels + NodeResourcesFit).  ``masks``
        holds this signature's [N] per-kernel mask rows."""
        remaining = node_valid.copy()
        out: Dict[str, int] = {}
        for name, key in (
            ("NodeUnschedulable", "m_unsched"),
            ("NodeName", "m_nodename"),
            ("TaintToleration", "m_taints"),
            ("NodeAffinity", "m_nodeaff"),
        ):
            m = masks[key]
            rej = int(np.sum(remaining & ~m))
            if rej:
                out[name] = rej
            remaining &= m
        if self.check_fit:
            fit = np.fromiter(
                (self.feasible_int(n, sig) for n in range(self.n)),
                dtype=bool,
                count=self.n,
            )
            rej = int(np.sum(remaining & ~fit))
            if rej:
                out["NodeResourcesFit"] = rej
        return out
