"""Resource quantities.

Semantics follow Kubernetes quantity parsing
(staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go) restricted to
what the scheduler consumes, and the scheduler's flattened ``Resource`` struct
(reference pkg/scheduler/framework/types.go:651-744): MilliCPU, Memory,
EphemeralStorage, AllowedPodNumber, ScalarResources.

CPU is tracked in integer millicores, everything else in integer base units
(bytes for memory/storage, counts for extended resources).  Keeping these as
ints on the host mirrors the reference exactly; the device snapshot packs them
into float32/int32 lanes (see kubernetes_tpu/snapshot).
"""

from __future__ import annotations

import functools
import math
import re
from dataclasses import dataclass, field
from typing import Dict, Mapping, Optional

# Binary and decimal suffixes accepted by Kubernetes quantities.
_BIN_SUFFIX = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DEC_SUFFIX = {
    "n": 10**-9,
    "u": 10**-6,
    "m": 10**-3,
    "": 1,
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d+)?|\.\d+)(?P<suffix>(?:[numkMGTPE]|[KMGTPE]i|e[+-]?\d+)?)$"
)

# Well-known resource names (subset the scheduler cares about).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

# Resources whose requests default to a non-zero value for spreading purposes
# (reference pkg/scheduler/framework/types.go:926 calculateResource /
# non-zero requests, util defaults: 100m CPU, 200Mi memory).
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024


def parse_quantity(s: str | int | float) -> float:
    """Parse a Kubernetes quantity string into a float of base units.

    Examples: "100m" → 0.1, "1Gi" → 1073741824, "2" → 2, "1e3" → 1000.

    String parses are memoized: workloads repeat a handful of distinct
    quantity strings across hundreds of thousands of pods, and the regex
    parse dominates compute_requests on large drains.
    """
    if isinstance(s, (int, float)):
        return float(s)
    return _parse_quantity_str(s)


@functools.lru_cache(maxsize=8192)
def _parse_quantity_str(s: str) -> float:
    s = s.strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    sign = -1.0 if m.group("sign") == "-" else 1.0
    num = float(m.group("num"))
    suffix = m.group("suffix")
    if suffix in _BIN_SUFFIX:
        mult = float(_BIN_SUFFIX[suffix])
    elif suffix.startswith("e") or suffix.startswith("E"):
        mult = 10.0 ** float(suffix[1:])
    elif suffix in _DEC_SUFFIX:
        mult = _DEC_SUFFIX[suffix]
    else:
        raise ValueError(f"invalid quantity suffix: {s!r}")
    return sign * num * mult


def parse_cpu_millis(s: str | int | float) -> int:
    """CPU quantity → integer millicores (ceil, as MilliValue does)."""
    return int(math.ceil(parse_quantity(s) * 1000 - 1e-9))


def parse_int_quantity(s: str | int | float) -> int:
    """Non-CPU quantity → integer base units (ceil)."""
    return int(math.ceil(parse_quantity(s) - 1e-9))


@dataclass
class Resource:
    """Flattened resource vector (reference framework/types.go:651).

    ``milli_cpu`` in millicores; ``memory``/``ephemeral_storage`` in bytes;
    ``allowed_pod_number`` a count; ``scalars`` holds extended resources
    (e.g. "nvidia.com/gpu", hugepages-*) in base units.
    """

    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    scalars: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_map(cls, m: Optional[Mapping[str, str | int | float]]) -> "Resource":
        r = cls()
        if not m:
            return r
        for name, q in m.items():
            r.set(name, q)
        return r

    def set(self, name: str, q: str | int | float) -> None:
        if name == CPU:
            self.milli_cpu = parse_cpu_millis(q)
        elif name == MEMORY:
            self.memory = parse_int_quantity(q)
        elif name == EPHEMERAL_STORAGE:
            self.ephemeral_storage = parse_int_quantity(q)
        elif name == PODS:
            self.allowed_pod_number = parse_int_quantity(q)
        else:
            self.scalars[name] = parse_int_quantity(q)

    def get(self, name: str) -> int:
        if name == CPU:
            return self.milli_cpu
        if name == MEMORY:
            return self.memory
        if name == EPHEMERAL_STORAGE:
            return self.ephemeral_storage
        if name == PODS:
            return self.allowed_pod_number
        return self.scalars.get(name, 0)

    def add(self, other: "Resource") -> "Resource":
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.ephemeral_storage += other.ephemeral_storage
        for k, v in other.scalars.items():
            self.scalars[k] = self.scalars.get(k, 0) + v
        return self

    def sub(self, other: "Resource") -> "Resource":
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.ephemeral_storage -= other.ephemeral_storage
        for k, v in other.scalars.items():
            self.scalars[k] = self.scalars.get(k, 0) - v
        return self

    def max_with(self, other: "Resource") -> "Resource":
        """Element-wise max (used for init-container folding)."""
        self.milli_cpu = max(self.milli_cpu, other.milli_cpu)
        self.memory = max(self.memory, other.memory)
        self.ephemeral_storage = max(self.ephemeral_storage, other.ephemeral_storage)
        for k, v in other.scalars.items():
            self.scalars[k] = max(self.scalars.get(k, 0), v)
        return self

    def clone(self) -> "Resource":
        return Resource(
            milli_cpu=self.milli_cpu,
            memory=self.memory,
            ephemeral_storage=self.ephemeral_storage,
            allowed_pod_number=self.allowed_pod_number,
            scalars=dict(self.scalars),
        )

    def non_zero_defaulted(self) -> "Resource":
        """Copy with cpu/memory floored at the spreading defaults.

        Mirrors GetNonzeroRequests (reference uses it for the
        ``NonZeroRequested`` accounting that feeds scoring).
        """
        r = self.clone()
        if r.milli_cpu == 0:
            r.milli_cpu = DEFAULT_MILLI_CPU_REQUEST
        if r.memory == 0:
            r.memory = DEFAULT_MEMORY_REQUEST
        return r
