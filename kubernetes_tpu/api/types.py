"""Scheduler-relevant object model.

A deliberately small mirror of the Kubernetes API surface the scheduler
consumes (reference pkg/scheduler/framework/types.go PodInfo/NodeInfo and the
corev1 types they pre-parse).  Everything the device kernels need is later
interned/packed by kubernetes_tpu.snapshot; these dataclasses are the host
ground truth.

Field names are snake_case versions of the corev1 fields so that test fixtures
read like the reference's testing/wrappers.go builders.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from kubernetes_tpu.api import labels as k8slabels
from kubernetes_tpu.api.resource import Resource

# ---------------------------------------------------------------------------
# Selectors (API-shape; converted to labels.Selector for matching)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LabelSelectorRequirement:
    key: str
    operator: str  # In / NotIn / Exists / DoesNotExist
    values: Tuple[str, ...] = ()


@dataclass(frozen=True)
class LabelSelector:
    match_labels: Optional[Mapping[str, str]] = None
    match_expressions: Tuple[LabelSelectorRequirement, ...] = ()


@dataclass(frozen=True)
class NodeSelectorRequirement:
    key: str
    operator: str  # In / NotIn / Exists / DoesNotExist / Gt / Lt
    values: Tuple[str, ...] = ()


@dataclass(frozen=True)
class NodeSelectorTerm:
    """Requirements are ANDed. An empty term matches nothing
    (component-helpers nodeaffinity: nil/empty term ⇒ no match)."""

    match_expressions: Tuple[NodeSelectorRequirement, ...] = ()
    match_fields: Tuple[NodeSelectorRequirement, ...] = ()


@dataclass(frozen=True)
class NodeSelector:
    """Terms are ORed."""

    node_selector_terms: Tuple[NodeSelectorTerm, ...] = ()


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass(frozen=True)
class NodeAffinity:
    required_during_scheduling_ignored_during_execution: Optional[NodeSelector] = None
    preferred_during_scheduling_ignored_during_execution: Tuple[
        PreferredSchedulingTerm, ...
    ] = ()


@dataclass(frozen=True)
class PodAffinityTerm:
    topology_key: str
    label_selector: Optional[LabelSelector] = None
    namespaces: Tuple[str, ...] = ()
    namespace_selector: Optional[LabelSelector] = None
    match_label_keys: Tuple[str, ...] = ()
    mismatch_label_keys: Tuple[str, ...] = ()


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    pod_affinity_term: PodAffinityTerm


@dataclass(frozen=True)
class PodAffinity:
    required_during_scheduling_ignored_during_execution: Tuple[PodAffinityTerm, ...] = ()
    preferred_during_scheduling_ignored_during_execution: Tuple[
        WeightedPodAffinityTerm, ...
    ] = ()


@dataclass(frozen=True)
class PodAntiAffinity:
    required_during_scheduling_ignored_during_execution: Tuple[PodAffinityTerm, ...] = ()
    preferred_during_scheduling_ignored_during_execution: Tuple[
        WeightedPodAffinityTerm, ...
    ] = ()


@dataclass(frozen=True)
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# ---------------------------------------------------------------------------
# Taints and tolerations
# ---------------------------------------------------------------------------

TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"

TOLERATION_OP_EXISTS = "Exists"
TOLERATION_OP_EQUAL = "Equal"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = TAINT_NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    key: str = ""  # empty key + Exists tolerates everything
    operator: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""  # empty effect matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        """api/core/v1/toleration.go ToleratesTaint semantics."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        # Empty key with Exists tolerates every taint (wildcard).
        if not self.key:
            return self.operator == TOLERATION_OP_EXISTS
        if self.operator == TOLERATION_OP_EXISTS:
            return True
        return self.operator in ("", TOLERATION_OP_EQUAL) and self.value == taint.value


# ---------------------------------------------------------------------------
# Topology spread
# ---------------------------------------------------------------------------

DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"

NODE_INCLUSION_HONOR = "Honor"
NODE_INCLUSION_IGNORE = "Ignore"


@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule / ScheduleAnyway
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None
    node_affinity_policy: str = NODE_INCLUSION_HONOR
    node_taints_policy: str = NODE_INCLUSION_IGNORE
    match_label_keys: Tuple[str, ...] = ()


# ---------------------------------------------------------------------------
# Containers / ports / volumes
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class Container:
    name: str = ""
    requests: Optional[Mapping[str, str | int | float]] = None
    limits: Optional[Mapping[str, str | int | float]] = None
    ports: Tuple[ContainerPort, ...] = ()
    restart_policy: Optional[str] = None  # "Always" ⇒ restartable (sidecar) init


@dataclass(frozen=True)
class Volume:
    """One pod volume.  Either a PVC reference or an inline source
    (gcePersistentDisk / awsElasticBlockStore / azureDisk / csi …) collapsed
    to (kind, opaque id) — what VolumeRestrictions/NodeVolumeLimits compare."""

    name: str = ""
    pvc_name: Optional[str] = None  # persistentVolumeClaim.claimName
    source_kind: str = ""  # "" for PVC-backed; gce-pd / aws-ebs / azure-disk / csi
    source_id: str = ""  # disk name / volume id / driver-scoped handle
    driver: str = ""  # inline CSI volumes: spec.csi.driver
    read_only: bool = False


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass
class Node:
    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    capacity: Resource = field(default_factory=Resource)
    allocatable: Resource = field(default_factory=Resource)
    taints: Tuple[Taint, ...] = ()
    unschedulable: bool = False
    # image name → size bytes (NodeStatus.Images, for ImageLocality)
    images: Dict[str, int] = field(default_factory=dict)
    # NodeStatus.conditions[Ready] + lastHeartbeatTime, collapsed to the
    # two fields the node-lifecycle tier reads (kubelet heartbeats write
    # them through the node status subresource)
    ready: bool = True
    last_heartbeat: float = 0.0

    def __post_init__(self):
        # kubelet defaults allocatable to capacity when no reservation.
        if (
            self.allocatable.milli_cpu == 0
            and self.allocatable.memory == 0
            and self.allocatable.allowed_pod_number == 0
            and not self.allocatable.scalars
            and (self.capacity.milli_cpu or self.capacity.memory)
        ):
            self.allocatable = self.capacity.clone()


# ---------------------------------------------------------------------------
# Pod
# ---------------------------------------------------------------------------

DEFAULT_SCHEDULER_NAME = "default-scheduler"

_uid_counter = itertools.count(1)


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)

    # spec
    node_name: str = ""  # assigned node ("" = pending)
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    priority: int = 0
    priority_class_name: str = ""
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Optional[Mapping[str, str | int | float]] = None
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: Tuple[Toleration, ...] = ()
    topology_spread_constraints: Tuple[TopologySpreadConstraint, ...] = ()
    scheduling_gates: Tuple[str, ...] = ()
    volumes: Tuple[Volume, ...] = ()
    # spec.resourceClaims[*].resourceClaimName (DRA)
    resource_claims: Tuple[str, ...] = ()
    # gang membership (coscheduling): PodGroup name in the pod's namespace
    # (the pod-group.scheduling.sigs.k8s.io/name label works too — see
    # workloads/gang.py group_key_of)
    pod_group: str = ""
    host_network: bool = False
    images: Tuple[str, ...] = ()

    # status
    phase: str = "Pending"
    nominated_node_name: str = ""
    deletion_timestamp: Optional[float] = None
    start_time: Optional[float] = None  # status.startTime (preemption tie-break)

    def __post_init__(self):
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}#{next(_uid_counter)}"

    # -- derived ------------------------------------------------------------

    def compute_requests(self) -> Resource:
        """Pod-level resource request (framework/types.go:926 calculateResource):
        sum of container requests, elementwise-max with each non-restartable
        init container, restartable (sidecar) inits added to the running sum,
        plus pod overhead.  Memoized — callers must treat the result as
        read-only (spec updates arrive as NEW Pod objects)."""
        cached = self.__dict__.get("_req_memo")
        if cached is not None:
            return cached
        total = Resource()
        for c in self.containers:
            total.add(Resource.from_map(c.requests))
        restartable_sum = Resource()
        init_max = Resource()
        for c in self.init_containers:
            r = Resource.from_map(c.requests)
            if c.restart_policy == "Always":
                restartable_sum.add(r)
                init_max.max_with(restartable_sum.clone())
            else:
                peak = restartable_sum.clone().add(r)
                init_max.max_with(peak)
        total.add(restartable_sum)
        total.max_with(init_max)
        if self.overhead:
            total.add(Resource.from_map(self.overhead))
        self.__dict__["_req_memo"] = total
        return total

    def non_zero_requests(self) -> Resource:
        """compute_requests() with the spreading defaults floored in
        (GetNonzeroRequests) — memoized like compute_requests: the cache
        adds/removes it on every assume/bind/forget."""
        cached = self.__dict__.get("_nzreq_memo")
        if cached is not None:
            return cached
        total = self.compute_requests().non_zero_defaulted()
        self.__dict__["_nzreq_memo"] = total
        return total

    def host_ports(self) -> List[ContainerPort]:
        out = []
        for c in self.containers:
            for p in c.ports:
                if p.host_port > 0:
                    out.append(p)
                elif self.host_network and p.container_port > 0:
                    out.append(
                        ContainerPort(
                            container_port=p.container_port,
                            host_port=p.container_port,
                            protocol=p.protocol,
                            host_ip=p.host_ip,
                        )
                    )
        return out

    def pvc_names(self) -> List[str]:
        """Memoized (read-only, like compute_requests): the volume-plugin
        relevance probes ask this once per host filter per pod on the
        batch-extension hot path."""
        cached = self.__dict__.get("_pvc_memo")
        if cached is None:
            cached = self.__dict__["_pvc_memo"] = [
                v.pvc_name for v in self.volumes if v.pvc_name
            ]
        return cached

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# ---------------------------------------------------------------------------
# PodDisruptionBudget (policy/v1; the scheduler only reads selector +
# disruptionsAllowed — preemption.go filterPodsWithPDBViolation)
# ---------------------------------------------------------------------------


@dataclass
class PodDisruptionBudget:
    name: str
    namespace: str = "default"
    selector: Optional[LabelSelector] = None
    # status.disruptionsAllowed — how many more voluntary evictions the
    # budget tolerates right now
    disruptions_allowed: int = 0

    def matches(self, pod: "Pod") -> bool:
        if pod.namespace != self.namespace or self.selector is None:
            return False
        sel = k8slabels.selector_from_label_selector(self.selector)
        return sel.matches(pod.labels)


# ---------------------------------------------------------------------------
# Node-selector matching (component-helpers/scheduling/corev1/nodeaffinity)
# ---------------------------------------------------------------------------


def _node_requirement_matches(req: NodeSelectorRequirement, node: Node) -> bool:
    r = k8slabels.Requirement(req.key, req.operator, tuple(req.values))
    return r.matches(node.labels)


def _node_field_matches(req: NodeSelectorRequirement, node: Node) -> bool:
    # Only metadata.name is a valid field selector (nodeaffinity.go).
    if req.key != "metadata.name":
        return False
    if req.operator == k8slabels.IN:
        return len(req.values) == 1 and node.name in req.values
    if req.operator == k8slabels.NOT_IN:
        return node.name not in req.values
    return False


def node_selector_term_matches(term: NodeSelectorTerm, node: Node) -> bool:
    if not term.match_expressions and not term.match_fields:
        return False  # empty term matches nothing
    return all(
        _node_requirement_matches(r, node) for r in term.match_expressions
    ) and all(_node_field_matches(r, node) for r in term.match_fields)


def node_selector_matches(sel: Optional[NodeSelector], node: Node) -> bool:
    """Terms ORed; nil selector (None) matches everything at this level —
    callers decide presence. Empty term list matches nothing."""
    if sel is None:
        return True
    return any(node_selector_term_matches(t, node) for t in sel.node_selector_terms)


def required_node_affinity_matches(pod: Pod, node: Node) -> bool:
    """RequiredNodeAffinity.Match: spec.nodeSelector AND required node
    affinity (nodeaffinity/node_affinity.go:182)."""
    for k, v in (pod.node_selector or {}).items():
        if node.labels.get(k) != v:
            return False
    if pod.affinity and pod.affinity.node_affinity:
        req = pod.affinity.node_affinity.required_during_scheduling_ignored_during_execution
        if req is not None and not node_selector_matches(req, node):
            return False
    return True


def find_untolerated_taint(
    taints: Sequence[Taint],
    tolerations: Sequence[Toleration],
    effects: Sequence[str] = (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE),
) -> Optional[Taint]:
    """First taint with an effect in ``effects`` not tolerated by any
    toleration (v1helper.FindMatchingUntoleratedTaint)."""
    for t in taints:
        if t.effect not in effects:
            continue
        if not any(tol.tolerates(t) for tol in tolerations):
            return t
    return None
