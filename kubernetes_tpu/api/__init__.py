"""Core object model: the scheduler-relevant subset of the Kubernetes API.

Mirrors the semantics (not the code) of:
  - staging/src/k8s.io/apimachinery/pkg/api/resource (Quantity)
  - pkg/scheduler/framework/types.go (Resource, NodeInfo, PodInfo)
  - staging/src/k8s.io/apimachinery/pkg/labels (selectors)
"""

from kubernetes_tpu.api.resource import (  # noqa: F401
    Resource,
    parse_quantity,
    parse_cpu_millis,
)
from kubernetes_tpu.api.labels import (  # noqa: F401
    Requirement,
    Selector,
    selector_from_label_selector,
)
from kubernetes_tpu.api.types import (  # noqa: F401
    Affinity,
    Container,
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    PodAffinityTerm,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
