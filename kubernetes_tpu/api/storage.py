"""Storage API objects the scheduler consumes.

A scheduler-relevant mirror of the corev1/storagev1 surface used by the
volume plugins (reference: staging/src/k8s.io/api/core/v1 PersistentVolume /
PersistentVolumeClaim and storage/v1 StorageClass / CSINode / CSIDriver /
CSIStorageCapacity, scoped to what
pkg/scheduler/framework/plugins/volumebinding, volumezone,
volumerestrictions and nodevolumelimits actually read).

All objects carry a ``resource_version`` maintained by the API store — the
generic assume cache (kubernetes_tpu/util/assumecache.py) uses it to decide
whether an informer event supersedes an assumed object.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from kubernetes_tpu.api.resource import parse_int_quantity
from kubernetes_tpu.api.types import LabelSelector, NodeSelector

# -- volume binding modes (storagev1.StorageClass) ---------------------------
BINDING_IMMEDIATE = "Immediate"
BINDING_WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"

# -- PV/PVC phases ------------------------------------------------------------
PV_AVAILABLE = "Available"
PV_BOUND = "Bound"
PV_RELEASED = "Released"
PVC_PENDING = "Pending"
PVC_BOUND = "Bound"
PVC_LOST = "Lost"

# -- access modes ---------------------------------------------------------------
RWO = "ReadWriteOnce"
ROX = "ReadOnlyMany"
RWX = "ReadWriteMany"
RWOP = "ReadWriteOncePod"

# Annotation the binder writes on dynamically-provisioned claims so the
# provisioner knows the chosen node (volume/persistentvolume/util).
ANN_SELECTED_NODE = "volume.kubernetes.io/selected-node"
# StorageClass provisioner value that means "no dynamic provisioning"
# (kubernetes.io/no-provisioner — used by local volumes).
NO_PROVISIONER = "kubernetes.io/no-provisioner"

# Zone/region topology label keys VolumeZone compares (volumezone/volume_zone.go
# topologyLabels — both GA and legacy beta forms).
ZONE_LABELS = (
    "topology.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/zone",
)
REGION_LABELS = (
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/region",
)
VOLUME_TOPOLOGY_LABELS = ZONE_LABELS + REGION_LABELS


@dataclass
class ObjectRef:
    """PV.spec.claimRef — which claim a PV is bound to."""

    namespace: str = ""
    name: str = ""
    uid: str = ""


@dataclass
class PersistentVolume:
    """corev1.PersistentVolume, scheduler view.

    ``source_kind``/``source_id`` collapse the one-of volume-source union the
    scheduler inspects (gcePersistentDisk.pdName, awsElasticBlockStore
    .volumeID, azureDisk.diskName, csi.driver+volumeHandle, local, hostPath…)
    into (kind, opaque id) — VolumeRestrictions only compares ids for
    equality, NodeVolumeLimits only maps to a CSI driver name.
    """

    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    capacity: int = 0  # spec.capacity["storage"], bytes
    access_modes: Tuple[str, ...] = (RWO,)
    storage_class_name: str = ""
    node_affinity: Optional[NodeSelector] = None  # spec.nodeAffinity.required
    claim_ref: Optional[ObjectRef] = None
    phase: str = PV_AVAILABLE
    volume_mode: str = "Filesystem"
    source_kind: str = "csi"  # csi / gce-pd / aws-ebs / azure-disk / local / ...
    source_id: str = ""  # driver-scoped volume handle / disk name
    csi_driver: str = ""  # source_kind == "csi": spec.csi.driver
    read_only: bool = False
    resource_version: int = 0

    @classmethod
    def make(cls, name: str, capacity: str | int = "1Gi", **kw) -> "PersistentVolume":
        return cls(name=name, capacity=parse_int_quantity(capacity), **kw)

    @property
    def key(self) -> str:
        return self.name

    def clone(self) -> "PersistentVolume":
        import copy

        return copy.deepcopy(self)


@dataclass
class PersistentVolumeClaim:
    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    # spec.storageClassName; None means "no class" (matches only classless PVs)
    storage_class_name: Optional[str] = None
    access_modes: Tuple[str, ...] = (RWO,)
    request: int = 0  # spec.resources.requests["storage"], bytes
    selector: Optional[LabelSelector] = None
    volume_mode: str = "Filesystem"
    volume_name: str = ""  # spec.volumeName — the bound PV
    phase: str = PVC_PENDING
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0

    @classmethod
    def make(
        cls, name: str, request: str | int = "1Gi", **kw
    ) -> "PersistentVolumeClaim":
        return cls(name=name, request=parse_int_quantity(request), **kw)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def is_fully_bound(self) -> bool:
        """binder.go isPVCFullyBound: bound volume name + Bound phase."""
        return bool(self.volume_name) and self.phase == PVC_BOUND

    def clone(self) -> "PersistentVolumeClaim":
        import copy

        return copy.deepcopy(self)


@dataclass
class TopologySelectorTerm:
    """storagev1 allowedTopologies entry: matchLabelExpressions ANDed,
    each (key, values) requires node.labels[key] ∈ values."""

    match_label_expressions: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()

    def matches(self, node_labels: Dict[str, str]) -> bool:
        for key, values in self.match_label_expressions:
            if node_labels.get(key) not in values:
                return False
        return True


@dataclass
class StorageClass:
    name: str
    provisioner: str = "test.csi.example.com"
    volume_binding_mode: str = BINDING_IMMEDIATE
    allowed_topologies: Tuple[TopologySelectorTerm, ...] = ()
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name

    def is_wait_for_first_consumer(self) -> bool:
        return self.volume_binding_mode == BINDING_WAIT_FOR_FIRST_CONSUMER

    def topology_allows(self, node_labels: Dict[str, str]) -> bool:
        """Terms ORed; empty list allows every node."""
        if not self.allowed_topologies:
            return True
        return any(t.matches(node_labels) for t in self.allowed_topologies)


@dataclass
class CSINodeDriver:
    name: str  # driver name
    node_id: str = ""
    # spec.drivers[].allocatable.count — max attachable volumes; None = no limit
    allocatable_count: Optional[int] = None


@dataclass
class CSINode:
    """storagev1.CSINode — one per node, same name as the node."""

    name: str
    drivers: Tuple[CSINodeDriver, ...] = ()
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name

    def driver(self, name: str) -> Optional[CSINodeDriver]:
        for d in self.drivers:
            if d.name == name:
                return d
        return None


@dataclass
class CSIDriver:
    name: str
    # spec.storageCapacity: whether the scheduler must check
    # CSIStorageCapacity objects before provisioning on a node
    storage_capacity: bool = False
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name


@dataclass
class CSIStorageCapacity:
    """storagev1.CSIStorageCapacity — provisioner-published free capacity
    for (storage class, node topology segment)."""

    name: str
    storage_class_name: str = ""
    # nodeTopology: labels a node must carry to be in this segment
    node_topology: Optional[LabelSelector] = None
    capacity: int = 0  # bytes; 0 = unknown/none
    maximum_volume_size: Optional[int] = None
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name

    def topology_matches(self, node_labels: Dict[str, str]) -> bool:
        from kubernetes_tpu.api import labels as k8slabels

        if self.node_topology is None:
            return True
        sel = k8slabels.selector_from_label_selector(self.node_topology)
        return sel.matches(node_labels)
