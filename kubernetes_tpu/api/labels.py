"""Label selector semantics.

Host-side reference semantics of k8s label selectors
(staging/src/k8s.io/apimachinery/pkg/labels/selector.go) and of the
LabelSelector API type conversion
(apimachinery/pkg/apis/meta/v1/helper: LabelSelectorAsSelector).

The device kernels (kubernetes_tpu/ops) evaluate interned compilations of
these; this module is the golden scalar semantics they are tested against.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List, Mapping, Optional, Sequence

# Operators (labels.selection in the reference).
IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

_OPS = {IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT}


@dataclass(frozen=True)
class Requirement:
    """key <op> values — one conjunct of a selector."""

    key: str
    op: str
    values: tuple = ()

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"unknown selector operator {self.op!r}")
        object.__setattr__(self, "values", tuple(self.values))

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.op == EXISTS:
            return has
        if self.op == DOES_NOT_EXIST:
            return not has
        if self.op == IN:
            return has and labels[self.key] in self.values
        if self.op == NOT_IN:
            # NotIn matches when the key is present with a value outside the
            # set — and ALSO when the key is absent (labels.Requirement.Matches).
            return not has or labels[self.key] not in self.values
        # Gt/Lt: value must exist and parse as integer on both sides
        # (labels/selector.go: non-integer ⇒ no match).
        if not has:
            return False
        try:
            lv = int(labels[self.key])
            rv = int(self.values[0])
        except (ValueError, IndexError):
            return False
        return lv > rv if self.op == GT else lv < rv


@dataclass(frozen=True)
class Selector:
    """Conjunction of requirements. Empty selector matches everything.

    ``match_nothing`` encodes labels.Nothing() — the selector produced from a
    nil LabelSelector, which matches no objects.
    """

    requirements: tuple = ()
    match_nothing: bool = False

    def __post_init__(self):
        object.__setattr__(self, "requirements", tuple(self.requirements))

    def matches(self, labels: Mapping[str, str]) -> bool:
        if self.match_nothing:
            return False
        return all(r.matches(labels) for r in self.requirements)

    @property
    def empty(self) -> bool:
        return not self.match_nothing and not self.requirements


NOTHING = Selector(match_nothing=True)
EVERYTHING = Selector()


def selector_from_map(match_labels: Optional[Mapping[str, str]]) -> Selector:
    if not match_labels:
        return EVERYTHING
    return Selector(
        tuple(Requirement(k, IN, (v,)) for k, v in sorted(match_labels.items()))
    )


def selector_from_label_selector(ls) -> Selector:
    """LabelSelector (matchLabels + matchExpressions) → Selector.

    ``None`` → Nothing (matches no objects); empty selector → Everything.
    Mirrors metav1.LabelSelectorAsSelector.
    """
    if ls is None:
        return NOTHING
    reqs: List[Requirement] = []
    if ls.match_labels:
        for k, v in sorted(ls.match_labels.items()):
            reqs.append(Requirement(k, IN, (v,)))
    for e in ls.match_expressions or ():
        reqs.append(Requirement(e.key, e.operator, tuple(e.values or ())))
    return Selector(tuple(reqs))
