"""Wire codec: API dataclasses ↔ JSON-safe dicts.

The reference's wire format is generated protobuf/JSON marshalers per type
(staging/src/k8s.io/api, apimachinery runtime.Scheme).  Here one generic
codec walks the dataclass type hints recursively — every scheduler-relevant
type (Pod, Node, affinity trees, Resource) round-trips through plain JSON
for the HTTP list/watch tier (client/api_server.py, client/client.py).

Conventions:
  * dataclasses → {"field": value, ...} (fields at defaults are kept —
    the codec prioritizes fidelity over wire size);
  * Tuple[X, ...] / List[X] → JSON arrays, Optional[X] → value or null;
  * Dict/Mapping str→str/int pass through;
  * memoized derived state on Pod (underscore keys) never serializes.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, Optional, get_args, get_origin, get_type_hints

from kubernetes_tpu.api import types as T
from kubernetes_tpu.api.resource import Resource

_HINTS_CACHE: Dict[type, Dict[str, Any]] = {}


def _hints(cls) -> Dict[str, Any]:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        h = _HINTS_CACHE[cls] = get_type_hints(cls)
    return h


def to_wire(obj: Any) -> Any:
    """Dataclass tree → JSON-safe structure."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (list, tuple)):
        return [to_wire(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): to_wire(v) for k, v in obj.items()}
    if dataclasses.is_dataclass(obj):
        out = {}
        for f in dataclasses.fields(obj):
            out[f.name] = to_wire(getattr(obj, f.name))
        return out
    raise TypeError(f"to_wire: unsupported {type(obj)!r}")


def _from_wire_typed(value: Any, hint: Any) -> Any:
    if value is None:
        return None
    origin = get_origin(hint)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in get_args(hint) if a is not type(None)]
        # Optional[X] or unions of primitives (str | int | float)
        if len(args) == 1:
            return _from_wire_typed(value, args[0])
        return value
    if origin in (tuple, list):
        args = get_args(hint)
        elem = args[0] if args else Any
        seq = [_from_wire_typed(v, elem) for v in value]
        return tuple(seq) if origin is tuple else seq
    if origin in (dict, typing.Mapping) or hint in (dict,):
        args = get_args(hint)
        vt = args[1] if len(args) == 2 else Any
        return {k: _from_wire_typed(v, vt) for k, v in value.items()}
    if dataclasses.is_dataclass(hint):
        return from_wire(value, hint)
    if hint in (int, float, str, bool):
        return hint(value)
    # typing.Any / unparameterized Mapping values
    return value


def from_wire(data: Dict[str, Any], cls) -> Any:
    """JSON structure → dataclass instance of ``cls``."""
    hints = _hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        kwargs[f.name] = _from_wire_typed(data[f.name], hints[f.name])
    return cls(**kwargs)


# kind registry for the watch stream's typed envelopes
KINDS = {
    "Pod": T.Pod,
    "Node": T.Node,
    "Resource": Resource,
    "PodDisruptionBudget": T.PodDisruptionBudget,
}


def encode(obj: Any) -> Dict[str, Any]:
    kind = type(obj).__name__
    if kind not in KINDS:
        raise TypeError(f"encode: unregistered kind {kind}")
    return {"kind": kind, "object": to_wire(obj)}


def decode(envelope: Dict[str, Any]) -> Any:
    cls = KINDS.get(envelope.get("kind"))
    if cls is None:
        raise TypeError(f"decode: unregistered kind {envelope.get('kind')!r}")
    return from_wire(envelope["object"], cls)
