"""Dynamic Resource Allocation API objects (resource.k8s.io v1alpha3).

Scheduler-relevant mirror of the structured-parameters DRA surface the
DynamicResources plugin consumes (reference staging/src/k8s.io/api/resource/
v1alpha3/types.go: ResourceClaim :311, DeviceRequest :393, ResourceSlice
:65, Device :190, DeviceClass :944, AllocationResult :701).

One deliberate simplification: device selectors are (attribute, op, values)
requirements rather than CEL expressions — the reference evaluates CEL
against device attributes (:487); the matching semantics (all selectors
must admit the device) are preserved.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

ALLOCATION_MODE_EXACT = "ExactCount"
ALLOCATION_MODE_ALL = "All"


@dataclass(frozen=True)
class DeviceSelector:
    """All requirements must hold for a device to match."""

    attribute: str
    operator: str = "In"  # In / NotIn / Exists / DoesNotExist
    values: Tuple[str, ...] = ()

    def matches(self, attributes: Dict[str, str]) -> bool:
        has = self.attribute in attributes
        if self.operator == "Exists":
            return has
        if self.operator == "DoesNotExist":
            return not has
        if self.operator == "In":
            return has and attributes[self.attribute] in self.values
        if self.operator == "NotIn":
            return not has or attributes[self.attribute] not in self.values
        return False


@dataclass
class DeviceClass:
    name: str
    selectors: Tuple[DeviceSelector, ...] = ()
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name

    def admits(self, attributes: Dict[str, str]) -> bool:
        return all(s.matches(attributes) for s in self.selectors)


@dataclass(frozen=True)
class Device:
    """One device in a ResourceSlice pool (types.go:190)."""

    name: str
    attributes: Tuple[Tuple[str, str], ...] = ()

    def attr_map(self) -> Dict[str, str]:
        return dict(self.attributes)


@dataclass
class ResourceSlice:
    """Driver-published devices for one node's pool (types.go:65)."""

    name: str
    node_name: str = ""
    driver: str = ""
    pool: str = ""
    devices: Tuple[Device, ...] = ()
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name


@dataclass(frozen=True)
class DeviceRequest:
    """One request inside a claim (types.go:393)."""

    name: str
    device_class_name: str
    count: int = 1
    allocation_mode: str = ALLOCATION_MODE_EXACT
    selectors: Tuple[DeviceSelector, ...] = ()


@dataclass(frozen=True)
class DeviceRequestAllocationResult:
    """types.go:756 — which concrete device satisfied which request."""

    request: str
    driver: str
    pool: str
    device: str


@dataclass
class AllocationResult:
    results: Tuple[DeviceRequestAllocationResult, ...] = ()
    node_name: str = ""  # nodeSelector collapsed to the single chosen node


@dataclass
class ResourceClaim:
    name: str
    namespace: str = "default"
    requests: Tuple[DeviceRequest, ...] = ()
    # status
    allocation: Optional[AllocationResult] = None
    reserved_for: Tuple[str, ...] = ()  # pod uids (ReservedFor consumers)
    deallocation_requested: bool = False
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0

    MAX_RESERVED = 32  # resourceapi.ResourceClaimReservedForMaxSize

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "ResourceClaim":
        import copy

        return copy.deepcopy(self)
