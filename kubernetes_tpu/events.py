"""Kubernetes Events: recorder + broadcaster.

The reference emits Events through client-go's events machinery — an
EventBroadcaster started by the server (cmd/kube-scheduler/app/server.go:179)
fans recorded events out to sinks, and each profile gets its own recorder
(pkg/scheduler/profile/profile.go:86).  The scheduler emits:

  * ``Scheduled``        (Normal)  on successful binding
    (schedule_one.go bindingCycle tail);
  * ``FailedScheduling`` (Warning) with the FitError message
    (schedule_one.go:1020 handleSchedulingFailure);
  * ``Preempted``        (Normal)  on each evicted victim
    (framework/preemption/preemption.go:395 prepareCandidate).

The broadcaster here is synchronous fan-out with the events correlator's
visible behavior (events/event_broadcaster.go): identical (object, reason,
action, note) tuples within a series aggregate into one Event with a
bumped ``count`` instead of growing the sink unboundedly.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"


@dataclass
class ObjectRef:
    kind: str
    namespace: str
    name: str
    uid: str = ""

    @classmethod
    def for_pod(cls, pod) -> "ObjectRef":
        return cls("Pod", pod.namespace, pod.name, pod.uid)


@dataclass
class Event:
    regarding: ObjectRef
    event_type: str  # Normal / Warning
    reason: str  # Scheduled / FailedScheduling / Preempted / ...
    action: str
    note: str
    reporting_controller: str = "default-scheduler"
    related: Optional[ObjectRef] = None
    count: int = 1
    first_timestamp: float = field(default_factory=time.time)
    last_timestamp: float = field(default_factory=time.time)

    @property
    def key(self) -> Tuple:
        return (
            self.regarding.uid or f"{self.regarding.namespace}/{self.regarding.name}",
            self.event_type,
            self.reason,
            self.action,
            self.note,
            # per-controller series: two profiles emitting the same tuple
            # must not aggregate into each other's Event
            self.reporting_controller,
        )


class EventBroadcaster:
    """Fan-out + correlation.  Sinks are callables ``sink(event)`` invoked
    under the broadcaster lock; a FakeCluster registers its event store
    here, a real client would register an API-writing sink."""

    def __init__(self, clock: Callable[[], float] = time.time):
        self._sinks: List[Callable[[Event], None]] = []
        self._series: Dict[Tuple, Event] = {}
        self._mu = threading.Lock()
        self._clock = clock
        self.started = False

    def start_recording_to_sink(self, sink: Callable[..., None]) -> None:
        """Sinks receive ``sink(event, is_new)`` — a SNAPSHOT of the
        aggregated event plus whether this key is new (False = an update to
        a previously delivered series; an API-writing sink PATCHes instead
        of POSTing).  Legacy single-argument sinks still work."""
        import inspect

        try:
            params = [
                p
                for p in inspect.signature(sink).parameters.values()
                if p.kind
                in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD, p.VAR_POSITIONAL)
            ]
            two_arg = len(params) >= 2 or any(
                p.kind == p.VAR_POSITIONAL for p in params
            )
        except (TypeError, ValueError):  # builtins/partials: assume legacy
            two_arg = False
        with self._mu:
            self._sinks.append((sink, two_arg))
            self.started = True

    def new_recorder(self, reporting_controller: str) -> "EventRecorder":
        """One recorder per profile (profile.go:86 NewRecorderFactory)."""
        return EventRecorder(self, reporting_controller)

    def emit(self, event: Event) -> None:
        import copy as _copy

        with self._mu:
            prior = self._series.get(event.key)
            is_new = prior is None
            if prior is not None:
                prior.count += 1
                prior.last_timestamp = self._clock()
                # LRU touch: repeats keep hot series resident
                self._series.pop(event.key)
                self._series[event.key] = prior
                event = prior
            else:
                # stamp with the broadcaster's clock (the dataclass default
                # is wall-clock; tests inject a fake clock here)
                event.first_timestamp = event.last_timestamp = self._clock()
                while len(self._series) >= 4096:
                    # evict the least-recently-touched series only — a
                    # wholesale clear would reset every live series' count
                    self._series.pop(next(iter(self._series)))
                self._series[event.key] = event
            # sinks get a SNAPSHOT: the aggregated object keeps mutating on
            # later repeats, and a sink buffering deliveries must not see
            # counts from the future
            snapshot = _copy.copy(event)
            for sink, two_arg in self._sinks:
                # arity resolved at registration (inspect.signature) — a
                # TypeError raised inside a sink must propagate, not
                # trigger a second invocation
                if two_arg:
                    sink(snapshot, is_new)
                else:
                    sink(snapshot)

    def shutdown(self) -> None:
        with self._mu:
            self._sinks.clear()
            self.started = False


class EventRecorder:
    """events.EventRecorder analogue: Eventf(regarding, related, type,
    reason, action, note)."""

    def __init__(self, broadcaster: EventBroadcaster, reporting_controller: str):
        self._b = broadcaster
        self.reporting_controller = reporting_controller

    def eventf(
        self,
        regarding: ObjectRef,
        event_type: str,
        reason: str,
        action: str,
        note: str,
        related: Optional[ObjectRef] = None,
    ) -> None:
        self._b.emit(
            Event(
                regarding=regarding,
                event_type=event_type,
                reason=reason,
                action=action,
                note=note,
                related=related,
                reporting_controller=self.reporting_controller,
            )
        )


class NullRecorder(EventRecorder):
    """Default when no broadcaster is wired (unit tests, bare Scheduler)."""

    def __init__(self):  # noqa: D401 — no broadcaster
        self.reporting_controller = "default-scheduler"

    def eventf(self, *a, **kw) -> None:  # noqa: D401
        pass
