"""Counterfactual fleet planners over the batched [K, P, N] what-if kernel.

See PLANNER.md.  ``Fork`` + ``pack_forks`` build forked-snapshot planes
off the mirror; ``simulate_forks`` runs K what-ifs in one fused dispatch;
``plan_autoscale`` / ``plan_deschedule`` / ``plan_preempt_cost`` are the
planner catalogue behind ``/debug/plan``.
"""

from kubernetes_tpu.planner.forks import (  # noqa: F401
    Fork,
    PackedForks,
    clone_node,
    pack_forks,
    scale_node_lanes,
)
from kubernetes_tpu.planner.plan import (  # noqa: F401
    PLANNERS,
    SimResult,
    backlog_pods,
    plan_autoscale,
    plan_deschedule,
    plan_preempt_cost,
    run_planner,
    simulate_forks,
    whatif_after_evictions,
)
