"""The counterfactual fleet planners — what the reference outsources to
cluster-autoscaler and descheduler, rebuilt on the batched [K, P, N]
what-if kernel (ops/counterfactual.py; PLANNER.md).

``simulate_forks`` is the shared engine: pack K forked snapshots off the
mirror (planner/forks.py), ride ONE fused dispatch + ONE accounted d2h,
and hand back per-fork outcomes.  The three planners on top differ only
in how they generate forks and read recommendations:

  * ``plan_autoscale``    — which node shape admits the unschedulable
                            backlog cheapest (fork axis = candidate shapes
                            × counts, plus per-empty-node removal forks
                            for scale-down);
  * ``plan_deschedule``   — which node drains raise bin-packing density
                            (fork axis = candidate eviction sets: cordon a
                            node, evict its pods, re-place them);
  * ``plan_preempt_cost`` — expected preemption cascade per pending
                            priority class (fork pairs: class backlog with
                            and without every lower-priority victim
                            evicted).

Everything is READ-ONLY: the planners never touch the cache, queue, or
the hot loop's chained device state (fresh uploads, like /debug/explain).
With ``plannerKernel: false`` (or when the factored algebra is
unavailable) the same fork specs replay through the serial forked-
snapshot oracle (oracle/planner.py) — the bit-identity reference the
paritycheck ``plan_vs_serial_oracle`` gate runs against the kernel.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.planner.forks import Fork, collect_clones, pack_forks

# Steering bonus for target-node what-ifs: large enough to dominate every
# weighted normalized score sum, small enough that score + bonus cannot
# overflow i64.
_TARGET_BONUS = 1 << 40


# Lock-discipline registry: the planners' prep (mirror sync, fork packing,
# batch packing) holds the owning Scheduler's _mu like explain does; the
# device dispatch + d2h run OUTSIDE it against immutable arrays.
_KTPU_GUARDED = {
    "PlanScratch": {
        "external_lock": "Scheduler._mu",
    },
}


class PlanScratch:
    """Marker class for the lock registry — planner state is all local."""


@dataclass
class SimResult:
    """One simulate_forks run: per-fork outcomes + coverage bookkeeping."""

    engine: str  # "kernel" | "serial"
    k: int
    dispatches: int  # device dispatches consumed (kernel: 1)
    batch: List[str] = field(default_factory=list)  # pod names, canonical order
    skipped: Dict[str, str] = field(default_factory=dict)  # pod → reason
    forks: List[dict] = field(default_factory=list)
    wall_s: float = 0.0

    def to_json(self) -> dict:
        return {
            "engine": self.engine,
            "k": self.k,
            "dispatches": self.dispatches,
            "batch": self.batch,
            "skipped": self.skipped,
            "forks": self.forks,
            "wall_s": round(self.wall_s, 4),
        }


def _pod_ineligible(sched, fwk, pod) -> Optional[str]:
    """Why a pod cannot ride the planner kernel (None = eligible).  The
    same spec-level disqualifiers as the workloads dispatch, plus DRA
    claims (the planner's fork planes don't carry the allocation ledger
    yet — see PLANNER.md remainders)."""
    if pod.nominated_node_name:
        return "nominated"
    if pod.host_ports():
        return "host_ports"
    if pod.resource_claims:
        return "resource_claims"
    for e in sched.extenders:
        if e.is_interested(pod):
            return "extender"
    for pl in sched._normalizing_score_plugins(fwk):
        if pl.score_relevant(pod):
            return "host_score"
    for pl in fwk.host_score_plugins():
        if fwk.score_weights.get(pl.name, 0) and pl.score_relevant(pod):
            return "host_score"
    if pod.pvc_names() and not sched._vol_kernel_ok(pod):
        return "volume_shape"
    return None


def backlog_pods(sched, fwk, max_pods: int = 256) -> Tuple[list, Dict[str, str]]:
    """The pending backlog the planners simulate: unschedulable pods first
    (they ARE the autoscaler's trigger), then backoff, then active, capped.
    Returns (eligible pods, skipped-pod reasons)."""
    with sched._mu:
        pools = sched.queue.pending_pods()
    seen = set()
    ordered = []
    # gated pods are deliberately excluded: a scheduling gate means "do
    # not schedule", so planning capacity for them would mislead
    for pool in ("unschedulable", "backoff", "active"):
        for p in pools.get(pool, ()):
            if p.uid not in seen:
                seen.add(p.uid)
                ordered.append(p)
    eligible, skipped = [], {}
    for p in ordered:
        why = _pod_ineligible(sched, fwk, p)
        if why is None:
            if len(eligible) < max_pods:
                eligible.append(p)
        else:
            skipped[p.name] = why
    return eligible, skipped


def simulate_forks(
    sched,
    forks: Sequence[Fork],
    pods: Sequence,
    target_node: Optional[str] = None,
    planner: str = "custom",
    use_kernel: Optional[bool] = None,
) -> SimResult:
    """K forked snapshots × one pod batch → per-fork outcomes.

    The kernel path packs fork planes off the mirror and runs ONE
    ``counterfactual_run`` dispatch + ONE ``Scheduler._d2h``; the serial
    path (kill switch / factored-algebra unavailable) replays the same
    fork specs through oracle/planner.py.  ``target_node`` (single-pod
    batches ONLY — enforced) steers the pod toward that node with a
    dominating score bonus, so ``chosen == target`` ⟺ the pod is
    feasible there (the K=1 what-if contract /debug/explain rides).
    """
    import jax.numpy as jnp

    from kubernetes_tpu.ops import counterfactual as cf_ops
    from kubernetes_tpu.ops import gang
    from kubernetes_tpu.ops import wave as wave_ops
    from kubernetes_tpu.ops import wire
    from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster
    from kubernetes_tpu.snapshot.interner import PAD
    from kubernetes_tpu.snapshot.schema import bucket_cap, pack_pod_batch
    from kubernetes_tpu.workloads import gang as wlg

    t0 = time.perf_counter()
    fwk = next(iter(sched.profiles.values()))
    kernel_ok = (
        sched.config.planner_kernel
        if use_kernel is None
        else use_kernel
    ) and not sched._sampling_active(fwk)
    # device-fault tier: an open counterfactual breaker routes fork specs
    # through the serial forked-snapshot oracle (the plannerKernel
    # kill-switch engine — decision-identical per fork)
    if kernel_ok and sched._breaker_blocked(
        "counterfactual.counterfactual_run"
    ):
        kernel_ok = False

    forks = list(forks)
    pods = list(pods)
    if target_node is not None and len(pods) != 1:
        # the target-bonus trick judges pods SEQUENTIALLY on the kernel
        # path (earlier steered pods commit usage at the target) but
        # against the initial state on the serial path — only the
        # single-pod what-if contract is well-defined across engines
        raise ValueError(
            "target_node requires a single-pod batch (the K=1 what-if "
            f"contract); got {len(pods)} pods"
        )
    skipped: Dict[str, str] = {}
    live_pods = []
    for p in pods:
        why = _pod_ineligible(sched, fwk, p)
        if why is None:
            live_pods.append(p)
        else:
            skipped[p.name] = why
    pods = live_pods

    with sched._mu:
        vocab = sched.mirror.vocab
        for p in pods:
            for k, v in p.labels.items():
                vocab.intern_label(k, v)
        sched._sync_mirror_external()
        # clone labels intern BEFORE the repack so a grown value bucket
        # forces the full pack the mirror already knows how to do
        node_objs = {cn.node.name: cn.node for cn in sched.cache.real_nodes()}
        clones = collect_clones(forks, node_objs)
        from kubernetes_tpu.snapshot.selectors import METADATA_NAME_KEY

        for node in clones.values():
            for k, v in node.labels.items():
                vocab.intern_label(k, v)
            vocab.intern_label(METADATA_NAME_KEY, node.name)
        sched._repack_mirror()
        if sched.mirror.nodes is None or not any(sched.mirror.nodes.valid):
            return SimResult(engine="none", k=0, dispatches=0,
                             skipped={"__cluster__": "no nodes in snapshot"})

        kernel_ok = kernel_ok and sched.mirror.hostnames_unique

        # canonical order: gang members contiguous (the oracle replays it)
        order, gang_positions = wlg.plan_batch(
            pods, group_of=sched._workloads_group_of
        )
        ordered = [pods[i] for i in order]
        needs = {}
        for key in gang_positions:
            pg = sched.gangs.get(key)
            needs[key] = max(
                0, (pg.min_member if pg else 0) - sched.gangs.bound_count(key)
            )

        serial_snapshot = None
        if not kernel_ok:
            serial_snapshot = _serial_snapshot(sched, gang_positions)
        if serial_snapshot is None:
            p_cap = bucket_cap(max(len(ordered), 1), 1)
            pf = pack_forks(
                sched.mirror,
                sched.cache,
                forks,
                [p.uid for p in ordered],
                p_cap,
                clones=clones,
            )
            pb = pack_pod_batch(
                ordered,
                vocab,
                k_cap=pf.nt.k_cap,
                p_cap=p_cap,
                namespace_labels=sched.namespace_labels,
            )
            from kubernetes_tpu.oracle.scores import HOSTNAME_LABEL

            hk_id = vocab.label_keys.lookup(HOSTNAME_LABEL)
            tables = dict(
                gang.batch_tables(
                    pb.tsc_topo_key, pb.aff_topo_key, pf.nt.label_vals, hk_id
                )
            )
            wt = wave_ops.wave_tables(
                pb, pf.nt.label_vals, hk_id, hostnames_unique=True
            )
            if wt is None:
                serial_snapshot = _serial_snapshot(sched, gang_positions)
        if serial_snapshot is None:
            gid, gfirst, glast, gneed, g_cap, slot_keys = wlg.gang_arrays(
                p_cap, gang_positions, needs
            )
            volt = sched._vol_tables(ordered, p_cap, vocab)
            has_interpod = bool(
                (pb.aff_kind != PAD).any()
                or (sched.mirror.existing.term_kind != PAD).any()
            )
            has_spread = bool((pb.tsc_topo_key != PAD).any())
            has_images = bool((pb.img_ids >= 0).any())
            enabled = fwk.device_enabled()
            weights = tuple(
                fwk.score_weights.get(n, 0) for n in gang.WEIGHT_ORDER
            )
            # a fresh device view off the EXTENDED node tensors —
            # independent of the hot loop's chained/delta-cached state,
            # like explain
            dc = DeviceCluster.from_host(pf.nt, sched.mirror.existing, vocab)
            db = DeviceBatch.from_host(pb)
            hostname_dev = sched._hostname_dev(vocab)
            v_cap = bucket_cap(len(vocab.label_vals))
            extra_score = None
            target_slot = None
            if target_node is not None:
                target_slot = pf.nt.name_to_idx.get(target_node)
                if target_slot is None:
                    target_slot = pf.clone_slots.get(target_node)
                if target_slot is not None:
                    es = np.zeros((p_cap, pf.nt.n_cap), np.int64)
                    es[:, target_slot] = _TARGET_BONUS
                    extra_score = jnp.asarray(es)
            planes = wire.device_put_packed(
                {k: np.asarray(v) for k, v in pf.planes.items()}
            )
            if sched.mesh is not None:
                # mesh-partitioned what-ifs (MULTICHIP.md): the fork axis
                # is embarrassingly parallel — shard KF over the mesh's
                # pods axis (each device simulates its own forks; the
                # shared snapshot/batch replicate, so the vmap body needs
                # ZERO collectives on a pods-major mesh).  Indivisible
                # KF (e.g. the K=1 whatif reroute) replicates instead.
                import jax as _jax
                from jax.sharding import (
                    NamedSharding as _NS,
                    PartitionSpec as _P,
                )

                from kubernetes_tpu.parallel.mesh import place_cluster

                pa = sched.mesh.shape["pods"]

                def _place_fork(x):
                    spec = (
                        _P("pods", *([None] * (x.ndim - 1)))
                        if pa > 1 and x.shape[0] % pa == 0
                        else _P()
                    )
                    return _jax.device_put(x, _NS(sched.mesh, spec))

                planes = {k: _place_fork(v) for k, v in planes.items()}
                dc = place_cluster(sched.mesh, dc)
            d_cap = tables.pop("d_cap")

    if serial_snapshot is not None:
        # serial replay runs OUTSIDE the lock: K forks of oracle replay
        # can take seconds and must not stall the scheduling loop (the
        # same rule the kernel dispatch follows).  The snapshot's object
        # graph is read-stable — cache objects are replaced, not mutated,
        # on informer updates (the oracle_view discipline).
        nodes_snap, placed_snap, groups_snap, pvs_snap, pvcs_snap = (
            serial_snapshot
        )
        t_ser = time.perf_counter()
        sim = _simulate_serial(
            sched,
            forks,
            ordered,
            needs,
            target_node,
            nodes_snap,
            placed_snap,
            groups_snap,
            pvs_snap,
            pvcs_snap,
        )
        sim.skipped.update(skipped)
        sim.wall_s = time.perf_counter() - t0
        tr = sched.tracer
        if tr.enabled:
            tr.complete(
                "plan.serial", t_ser, cat="plan", planner=planner,
                forks=len(forks),
            )
        _observe(sched, planner, sim)
        return sim

    # the fused dispatch + its d2h run OUTSIDE the lock (device-path rule:
    # a first-shape XLA compile must not stall the scheduling loop)
    from kubernetes_tpu.observability import kernels as kernels_mod

    tr = sched.tracer
    t_disp = time.perf_counter()
    try:
        out_dev = cf_ops.counterfactual_run(
            dc,
            db,
            hostname_dev,
            v_cap,
            g_cap,
            wt["tid_sp"],
            wt["rep_sp_p"],
            wt["rep_sp_c"],
            wt["tid_ip"],
            wt["rep_ip_p"],
            wt["rep_ip_u"],
            wt["ip_cdv_tab"],
            jnp.asarray(gid),
            jnp.asarray(gfirst),
            jnp.asarray(glast),
            jnp.asarray(gneed),
            **planes,
            **(volt or {}),
            has_interpod=has_interpod,
            has_spread=has_spread,
            has_images=has_images,
            enabled=enabled,
            weights=weights,
            extra_score=extra_score,
            d_cap=d_cap,
            d2_cap=wt["d2_cap"],
            fit_strategy=fwk.fit_strategy(),
            **tables,
        )
        # planner dispatches are host-tracer-visible like every scheduling
        # path: dispatch/harvest halves as spans, alongside the
        # scheduler_tpu_plan_* metrics and the `plan` flight event (_observe)
        if tr.enabled:
            tr.complete(
                "dispatch.plan", t_disp, cat="plan", planner=planner,
                forks=len(forks), pods=len(ordered),
            )
        t_harvest = time.perf_counter()
        fetched = {
            k: np.asarray(v)
            for k, v in sched._d2h_guarded(
                out_dev, kernel="counterfactual.counterfactual_run"
            ).items()
        }
    except kernels_mod.DispatchFailed as e:
        # abandoned kernel dispatch: the same fork specs replay through
        # the serial forked-snapshot oracle, decision-identically, while
        # the breaker keeps the kernel parked
        sched._note_dispatch_failure(e)
        with sched._mu:
            snap = _serial_snapshot(sched, gang_positions)
        t_ser = time.perf_counter()
        sim = _simulate_serial(
            sched, forks, ordered, needs, target_node, *snap
        )
        sim.skipped.update(skipped)
        sim.wall_s = time.perf_counter() - t0
        if tr.enabled:
            tr.complete(
                "plan.serial", t_ser, cat="plan", planner=planner,
                forks=len(forks),
            )
        _observe(sched, planner, sim)
        return sim
    if tr.enabled:
        tr.complete(
            "harvest.plan", t_harvest, cat="plan", planner=planner,
            forks=len(forks),
        )

    sim = SimResult(
        engine="kernel",
        k=len(forks),
        dispatches=1,
        batch=[p.name for p in ordered],
        skipped=skipped,
    )
    names = pf.names
    diag = list(gang.DIAG_KERNELS)
    for k, f in enumerate(forks):
        chosen = fetched["chosen"][k]
        live_row = pf.planes["fk_pod_live"][k]
        placements = {}
        target_ok = {}
        for i, p in enumerate(ordered):
            if not live_row[i]:
                continue
            c = int(chosen[i])
            placements[p.name] = (
                names[c] if 0 <= c < len(names) else None
            )
            if target_slot is not None:
                target_ok[p.name] = c == target_slot
        gang_admitted = {
            key: int(fetched["gang_admit"][k][slot])
            for slot, key in enumerate(slot_keys)
        }
        fork_out = {
            "label": f.label,
            "placements": placements,
            "admitted": int(fetched["admitted"][k]),
            "unschedulable": int(fetched["unschedulable"][k]),
            "density_ppm": int(fetched["density_ppm"][k]),
            "reasons": {
                name: int(v)
                for name, v in zip(diag, fetched["reasons"][k])
                if int(v)
            },
            "gang_admitted": gang_admitted,
            "meta": dict(f.meta),
        }
        if target_slot is not None:
            fork_out["target_ok"] = target_ok
        sim.forks.append(fork_out)
    sim.wall_s = time.perf_counter() - t0
    _observe(sched, planner, sim)
    return sim


def _observe(sched, planner: str, sim: SimResult) -> None:
    prom = sched.prom
    prom.plan_forks.inc(sim.k)
    prom.recorder.observe(prom.plan_duration, sim.wall_s, planner=planner)
    # the flight-recorder `plan` breadcrumb (queryable at
    # /debug/flightrecorder?pod=planner): one per planner run, both
    # engines, so what-if traffic is visible next to pod lifecycles
    fl = sched.flight
    if fl.enabled:
        fl.record(
            "planner",
            "plan",
            {
                "planner": planner,
                "engine": sim.engine,
                "forks": sim.k,
                "dispatches": sim.dispatches,
                "wall_s": round(sim.wall_s, 6),
            },
        )


def _serial_snapshot(sched, gang_positions):
    """The serial engine's inputs, snapshotted under sched._mu (caller
    holds it) so the replay itself can run outside the lock."""
    return (
        [cn.node for cn in sched.cache.real_nodes()],
        sched.cache.placed_pods(),
        {
            key: sched.gangs.get(key)
            for key in gang_positions
            if sched.gangs.get(key) is not None
        },
        {o.key: o for o in sched.pv_cache.list()},
        {o.key: o for o in sched.pvc_cache.list()},
    )


def _simulate_serial(
    sched, forks, ordered, needs, target_node, nodes, placed, groups, pvs, pvcs
) -> SimResult:
    """The kill-switch / fallback engine: same fork specs, serial forked-
    snapshot oracle, replayed OUTSIDE the scheduler lock over the
    read-stable snapshot _serial_snapshot took under it."""
    from kubernetes_tpu.oracle.planner import serial_plan

    outcomes = serial_plan(
        nodes=nodes,
        placed=placed,
        pods=ordered,
        forks=forks,
        groups=groups,
        needs=needs,
        pvs=pvs,
        pvcs=pvcs,
        namespace_labels=sched.namespace_labels,
        target_node=target_node,
    )
    sim = SimResult(
        engine="serial",
        k=len(forks),
        dispatches=0,
        batch=[p.name for p in ordered],
    )
    for f, o in zip(forks, outcomes):
        fork_out = {
            "label": f.label,
            "placements": o["placements"],
            "admitted": o["admitted"],
            "unschedulable": o["unschedulable"],
            "density_ppm": o["density_ppm"],
            "reasons": {},
            "gang_admitted": o["gang_admitted"],
            "meta": dict(f.meta),
        }
        if target_node is not None:
            fork_out["target_ok"] = o.get("target_ok", {})
        sim.forks.append(fork_out)
    return sim


# ---------------------------------------------------------------------------
# The planner catalogue
# ---------------------------------------------------------------------------


def _distinct_shapes(sched, max_shapes: int = 4) -> List[str]:
    """One representative node per distinct (cpu, mem, pods) allocatable."""
    seen = {}
    with sched._mu:
        for cn in sched.cache.real_nodes():
            r = cn.node.allocatable
            key = (r.milli_cpu, r.memory, r.allowed_pod_number)
            if key not in seen:
                seen[key] = cn.node.name
    return list(seen.values())[:max_shapes]


def plan_autoscale(
    sched,
    shapes: Optional[Sequence[str]] = None,
    max_count: int = 3,
    max_backlog: int = 256,
) -> dict:
    """Scale-up/down planning: which node shape admits the unschedulable
    backlog cheapest (cost = clones × template milli-cpu), and which empty
    nodes are removable without hurting backlog admission."""
    fwk = next(iter(sched.profiles.values()))
    pods, skipped = backlog_pods(sched, fwk, max_pods=max_backlog)
    if not pods:
        return {
            "planner": "autoscale",
            "error": "no eligible pending backlog to plan for",
            "skipped": skipped,
        }
    shapes = list(shapes) if shapes else _distinct_shapes(sched)
    with sched._mu:
        node_alloc = {
            cn.node.name: cn.node.allocatable.milli_cpu
            for cn in sched.cache.real_nodes()
        }
        empty = [
            cn.node.name
            for cn in sched.cache.real_nodes()
            if not cn.pods
        ]
    forks = [Fork(label="baseline")]
    for s in shapes:
        for m in range(1, max_count + 1):
            forks.append(
                Fork(
                    label=f"add:{s}x{m}",
                    add=tuple((s, f"{s}~cf{i}") for i in range(m)),
                    meta=(("shape", s), ("count", m),
                          ("cost_milli", node_alloc.get(s, 0) * m)),
                )
            )
    scale_down_considered = empty[:16]
    for name in scale_down_considered:
        forks.append(
            Fork(label=f"remove:{name}", remove=(name,),
                 meta=(("scale_down", name),))
        )
    sim = simulate_forks(sched, forks, pods, planner="autoscale")
    out = {
        "planner": "autoscale",
        "backlog": len(pods),
        "shapes": shapes,
        "result": sim.to_json(),
    }
    by_label = {f["label"]: f for f in sim.forks}
    base = by_label.get("baseline")
    if base is not None:
        best = None
        for f in sim.forks:
            meta = f.get("meta", {})
            if "shape" not in meta:
                continue
            gain = f["admitted"] - base["admitted"]
            key = (-f["admitted"], meta.get("cost_milli", 0))
            if gain > 0 and (best is None or key < best[0]):
                best = (key, f, gain)
        if best is not None:
            _, f, gain = best
            out["recommendation"] = {
                "action": "scale_up",
                "shape": f["meta"]["shape"],
                "count": f["meta"]["count"],
                "newly_schedulable": gain,
                "cost_milli": f["meta"]["cost_milli"],
            }
        else:
            out["recommendation"] = {
                "action": "none",
                "reason": "no candidate shape admits more of the backlog",
            }
        out["scale_down"] = [
            f["meta"]["scale_down"]
            for f in sim.forks
            if "scale_down" in f.get("meta", {})
            and f["admitted"] >= base["admitted"]
        ]
        # no silent caps: empty nodes beyond the per-dispatch candidate
        # budget were NOT simulated and must not read as "not removable"
        out["scale_down_considered"] = scale_down_considered
        out["scale_down_unevaluated"] = empty[16:]
    return out


def plan_deschedule(sched, max_candidates: int = 8) -> dict:
    """Defragmentation planning: cordon a lightly-loaded node, evict its
    pods, and see whether they re-place elsewhere and what that does to
    bin-packing density — the descheduler's question as K forks."""
    import copy as _copy

    fwk = next(iter(sched.profiles.values()))
    with sched._mu:
        candidates = sorted(
            (
                cn
                for cn in sched.cache.real_nodes()
                if cn.pods
            ),
            key=lambda cn: (len(cn.pods), cn.node.name),
        )[:max_candidates]
        cand = []
        for cn in candidates:
            pods = [
                p
                for p in cn.pods.values()
                if _pod_ineligible(sched, fwk, p) is None
            ]
            if pods and len(pods) == len(cn.pods):
                cand.append((cn.node.name, pods))
    if not cand:
        return {
            "planner": "deschedule",
            "error": "no drainable candidate nodes (occupied + eligible)",
        }
    batch = []
    forks = [Fork(label="baseline", live=())]
    for name, pods in cand:
        copies = []
        for p in pods:
            c = _copy.deepcopy(p)
            c.node_name = ""
            copies.append(c)
        batch.extend(copies)
        forks.append(
            Fork(
                label=f"drain:{name}",
                cordon=(name,),
                evict=tuple(p.uid for p in pods),
                live=tuple(c.uid for c in copies),
                meta=(("node", name), ("pods", len(pods))),
            )
        )
    sim = simulate_forks(sched, forks, batch, planner="deschedule")
    out = {
        "planner": "deschedule",
        "candidates": [name for name, _ in cand],
        "result": sim.to_json(),
    }
    base = next((f for f in sim.forks if f["label"] == "baseline"), None)
    drains = []
    for f in sim.forks:
        meta = f.get("meta", {})
        if "node" not in meta:
            continue
        drains.append(
            {
                "node": meta["node"],
                "evicted": meta["pods"],
                "replaced": f["admitted"],
                "fully_drainable": f["admitted"] == meta["pods"],
                "density_ppm": f["density_ppm"],
                "density_gain_ppm": (
                    f["density_ppm"] - base["density_ppm"]
                    if base is not None
                    else None
                ),
            }
        )
    drains.sort(
        key=lambda d: (not d["fully_drainable"], -(d["density_gain_ppm"] or 0))
    )
    out["drains"] = drains
    best = next((d for d in drains if d["fully_drainable"]), None)
    out["recommendation"] = (
        {"action": "drain", "node": best["node"],
         "density_gain_ppm": best["density_gain_ppm"]}
        if best is not None
        else {"action": "none", "reason": "no candidate drains fully re-place"}
    )
    return out


def plan_preempt_cost(sched, max_backlog: int = 256, max_classes: int = 8) -> dict:
    """Preemption cost forecast per pending priority class: how many class
    members become schedulable if every strictly-lower-priority placed pod
    were evicted (the cascade's upper bound), vs without evictions."""
    fwk = next(iter(sched.profiles.values()))
    pods, skipped = backlog_pods(sched, fwk, max_pods=max_backlog)
    if not pods:
        return {
            "planner": "preempt_cost",
            "error": "no eligible pending backlog",
            "skipped": skipped,
        }
    classes: Dict[int, list] = {}
    for p in pods:
        classes.setdefault(p.priority, []).append(p)
    prios = sorted(classes, reverse=True)[:max_classes]
    with sched._mu:
        placed = sched.cache.placed_pods()
    forks = []
    for c in prios:
        victims = tuple(p.uid for p in placed if p.priority < c)
        live = tuple(p.uid for p in classes[c])
        forks.append(
            Fork(label=f"class:{c}:base", live=live,
                 meta=(("priority", c), ("kind", "base"),))
        )
        forks.append(
            Fork(
                label=f"class:{c}:preempt",
                evict=victims,
                live=live,
                meta=(
                    ("priority", c),
                    ("kind", "preempt"),
                    ("victims", len(victims)),
                ),
            )
        )
    sim = simulate_forks(sched, forks, pods, planner="preempt_cost")
    by_label = {f["label"]: f for f in sim.forks}
    per_class = []
    for c in prios:
        base = by_label.get(f"class:{c}:base")
        pre = by_label.get(f"class:{c}:preempt")
        if base is None or pre is None:
            continue
        per_class.append(
            {
                "priority": c,
                "pending": len(classes[c]),
                "schedulable_now": base["admitted"],
                "schedulable_with_max_preemption": pre["admitted"],
                "cascade_upper_bound": pre["admitted"] - base["admitted"],
                "victims_considered": pre["meta"].get("victims", 0),
            }
        )
    return {
        "planner": "preempt_cost",
        "classes": per_class,
        "result": sim.to_json(),
    }


def whatif_after_evictions(sched, pod, node_name: str, victim_uids) -> dict:
    """The K=1 counterfactual behind /debug/explain?whatif_node=: evict
    ``victim_uids`` and ask whether ``pod`` is then feasible ON
    ``node_name`` (a dominating target-score bonus makes
    ``chosen == target`` ⟺ feasible-at-target).  Same kernel, same fork
    packer as the batched planners — the single-what-if endpoint cannot
    drift from the fleet tier."""
    import copy as _copy

    if pod.nominated_node_name:
        # a live preemptor is USUALLY nominated already — the what-if asks
        # about the pod minus its nomination state (the caller supplies
        # the eviction set explicitly), so simulate a cleared copy rather
        # than skipping
        pod = _copy.deepcopy(pod)
        pod.nominated_node_name = ""
    fork = Fork(
        label=f"whatif:{node_name}", evict=tuple(victim_uids)
    )
    sim = simulate_forks(
        sched, [fork], [pod], target_node=node_name, planner="whatif"
    )
    out = {"engine": sim.engine, "dispatches": sim.dispatches}
    if pod.name in sim.skipped:
        out["skipped_reason"] = sim.skipped[pod.name]
        return out
    if not sim.forks:
        out["error"] = "simulation unavailable"
        return out
    f0 = sim.forks[0]
    t_ok = f0.get("target_ok", {}).get(pod.name)
    if t_ok is None:
        out["error"] = f"unknown node {node_name!r}"
        return out
    out["feasible"] = bool(t_ok)
    out["placement"] = f0["placements"].get(pod.name)
    return out


PLANNERS = {
    "autoscale": plan_autoscale,
    "deschedule": plan_deschedule,
    "preempt_cost": plan_preempt_cost,
}


def run_planner(sched, name: str, params: Optional[dict] = None) -> dict:
    """The /debug/plan dispatcher: planner name + query params → JSON.
    A debug surface must not 500: malformed params and racy state (e.g.
    a victim pod unbinding between the planner's snapshot and the fork
    pack) come back as an ``error`` field, not an exception."""
    params = params or {}
    if name == "list":
        return {
            "planners": sorted(PLANNERS),
            "kernel": bool(sched.config.planner_kernel),
        }
    fn = PLANNERS.get(name)
    if fn is None:
        return {
            "error": f"unknown planner {name!r}",
            "planners": sorted(PLANNERS),
        }
    kw = {}
    try:
        if name == "autoscale":
            if params.get("shapes"):
                kw["shapes"] = [
                    s for s in str(params["shapes"]).split(",") if s
                ]
            if params.get("max_count"):
                kw["max_count"] = int(params["max_count"])
        elif name == "deschedule":
            if params.get("max_candidates"):
                kw["max_candidates"] = int(params["max_candidates"])
    except ValueError as e:
        return {"error": f"bad parameter: {e}"}
    try:
        return fn(sched, **kw)
    except ValueError as e:
        # planner-level input/race errors (unknown shape template, pod
        # unbound mid-plan, …) — report, don't 500
        return {"error": str(e), "planner": name}
