"""Fork specs + the snapshot fork packer off the mirror.

A ``Fork`` names one counterfactual mutation set over the live snapshot —
nodes added (cloned from an existing shape), removed, or cordoned,
capacities scaled, placed pods evicted, and which batch pods the fork
simulates.  ``pack_forks`` turns a list of forks into the [K, …] fork
planes ``ops.counterfactual.counterfactual_run`` consumes, built off the
SnapshotMirror's packed tensors so every untouched plane is byte-shared
with the production engine's view.

Exactness contract (what makes kernel-vs-oracle parity a theorem rather
than a hope): every per-fork plane must equal what packing the MUTATED
cluster from scratch would produce at the same slots.

  * evictions recompute the touched node's usage rows from the remaining
    pods' Resources in the mirror's own pack arithmetic (request_row /
    ceil-MiB nonzero totals) — subtracting a quantized per-pod row would
    drift on the ceil;
  * capacity scaling is defined in LANE space (``row * num // den``) and
    ``scale_node_lanes`` builds the host-side Node the same way, so the
    oracle's byte-space view re-packs to exactly the scaled lanes;
  * clones are written with the same ``write_node_row`` the mirror uses,
    from a cloned Node object the oracle forks share (``clone_node``);
  * removed (and not-added) slots are neutralized in-kernel
    (ops/counterfactual.fork_cluster_view), which the oracle mirrors by
    simply not materializing the node.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import Node
from kubernetes_tpu.oracle.scores import HOSTNAME_LABEL
from kubernetes_tpu.snapshot.interner import ABSENT, PAD
from kubernetes_tpu.snapshot.schema import (
    MEM_UNIT,
    ResourceLanes,
    bucket_cap,
    write_node_row,
)


@dataclass(frozen=True)
class Fork:
    """One counterfactual: mutations + the batch pods it simulates.

    ``live`` is the uid set of batch pods this fork schedules (None = all);
    ``add`` entries are (template node name, clone name) — clone slots are
    shared across forks by clone NAME, so fork "add 3×shape-A" reuses the
    slots fork "add 2×shape-A" allocated plus one more.
    """

    label: str = ""
    evict: Tuple[str, ...] = ()  # placed-pod uids
    cordon: Tuple[str, ...] = ()  # node names
    remove: Tuple[str, ...] = ()  # node names
    add: Tuple[Tuple[str, str], ...] = ()  # (template name, clone name)
    scale: Tuple[Tuple[str, int, int], ...] = ()  # (node name, num, den)
    live: Optional[Tuple[str, ...]] = None  # batch pod uids (None = all)
    meta: Tuple[Tuple[str, object], ...] = ()  # planner-private annotations


def clone_node(template: Node, name: str) -> Node:
    """A schedulable copy of ``template`` under a fresh identity: new name,
    new (unique) hostname label, zero usage.  Shared by the fork packer and
    the serial oracle fork so both sides pack the identical row."""
    n = copy.deepcopy(template)
    n.name = name
    n.labels = dict(n.labels)
    if HOSTNAME_LABEL in n.labels:
        n.labels[HOSTNAME_LABEL] = name
    return n


def scale_node_lanes(node: Node, num: int, den: int) -> Node:
    """Capacity scaling defined in pack-lane space: milli-cpu, MiB memory /
    ephemeral lanes, and extended scalars each become ``v * num // den``.
    The returned Node re-packs to exactly ``allocatable_row * num // den``,
    which is what the kernel plane applies — byte-space and lane-space
    views cannot drift."""
    r = node.allocatable
    scaled = Resource(
        milli_cpu=r.milli_cpu * num // den,
        memory=((r.memory // MEM_UNIT) * num // den) * MEM_UNIT,
        ephemeral_storage=((r.ephemeral_storage // MEM_UNIT) * num // den)
        * MEM_UNIT,
        allowed_pod_number=r.allowed_pod_number,
        scalars={k: v * num // den for k, v in r.scalars.items()},
    )
    n = copy.copy(node)
    n.labels = dict(node.labels)
    n.allocatable = scaled
    return n


@dataclass
class PackedForks:
    """The kernel's fork planes + the bookkeeping to read results back."""

    planes: Dict[str, np.ndarray]  # fk_* arrays, [K, ...]
    nt: object  # the EXTENDED NodeTensors (clone slots appended)
    clone_slots: Dict[str, int]  # clone name → node slot
    k_used: int  # real forks (the rest is identity padding)
    names: List[str]  # slot → node name (clones included)


def _extend_node_tensors(nt, clones: Dict[str, Node], vocab, n_multiple=1):
    """Copy of ``nt`` with clone rows appended (base-invalid; forks flip
    their own alive bits).  Grows the node bucket only when the clones
    outrun the padding (to the mesh's nodes-axis multiple, like the
    mirror's own packs — cluster_shardings asserts divisibility)."""
    n_used = len(nt.name_to_idx)
    need = n_used + len(clones)
    if need <= nt.n_cap:
        ext = copy.copy(nt)
        for f in (
            "allocatable",
            "requested",
            "nonzero_req",
            "num_pods",
            "allowed_pods",
            "label_vals",
            "val_ints",
            "taint_key",
            "taint_val",
            "taint_effect",
            "unschedulable",
            "valid",
            "used_ppk",
            "used_ip",
            "used_wild",
            "img_sizes",
            "visit_rank",
        ):
            setattr(ext, f, np.array(getattr(nt, f)))
        ext.names = list(nt.names)
        ext.name_to_idx = dict(nt.name_to_idx)
    else:
        from kubernetes_tpu.parallel.mesh import pad_to_multiple

        n_cap = pad_to_multiple(bucket_cap(need), n_multiple)
        ext = copy.copy(nt)

        def grow(a, fill):
            out = np.full((n_cap,) + a.shape[1:], fill, a.dtype)
            out[: a.shape[0]] = a
            return out

        ext.allocatable = grow(nt.allocatable, 0)
        ext.requested = grow(nt.requested, 0)
        ext.nonzero_req = grow(nt.nonzero_req, 0)
        ext.num_pods = grow(nt.num_pods, 0)
        ext.allowed_pods = grow(nt.allowed_pods, 0)
        ext.label_vals = grow(nt.label_vals, ABSENT)
        ext.val_ints = np.array(nt.val_ints)
        ext.taint_key = grow(nt.taint_key, PAD)
        ext.taint_val = grow(nt.taint_val, PAD)
        ext.taint_effect = grow(nt.taint_effect, PAD)
        ext.unschedulable = grow(nt.unschedulable, False)
        ext.valid = grow(nt.valid, False)
        ext.used_ppk = grow(nt.used_ppk, PAD)
        ext.used_ip = grow(nt.used_ip, PAD)
        ext.used_wild = grow(nt.used_wild, False)
        ext.img_sizes = grow(nt.img_sizes, 0)
        ext.visit_rank = grow(nt.visit_rank, -1)
        ext.names = list(nt.names)
        ext.name_to_idx = dict(nt.name_to_idx)

    slots: Dict[str, int] = {}
    cursor = n_used
    for name, node in clones.items():
        write_node_row(ext, cursor, node, vocab)
        # base-invalid + zero visit rank state: alive only per fork; the
        # planner path never samples, so the rank is inert anyway
        ext.valid[cursor] = False
        ext.visit_rank[cursor] = -1
        slots[name] = cursor
        cursor += 1
    return ext, slots


def collect_clones(forks: Sequence[Fork], node_by_name) -> Dict[str, Node]:
    """Clone name → cloned Node object, deduped across forks.  Raises on an
    unknown template or a clone name colliding with a real node."""
    out: Dict[str, Node] = {}
    for f in forks:
        for template, clone_name in f.add:
            if clone_name in out:
                continue
            tmpl = node_by_name.get(template)
            if tmpl is None:
                raise ValueError(f"fork {f.label!r}: unknown template node {template!r}")
            if clone_name in node_by_name:
                raise ValueError(
                    f"fork {f.label!r}: clone name {clone_name!r} collides with a real node"
                )
            out[clone_name] = clone_node(tmpl, clone_name)
    return out


def pack_forks(
    mirror,
    cache,
    forks: Sequence[Fork],
    batch_uids: Sequence[str],
    p_cap: int,
    k_cap: Optional[int] = None,
    clones: Optional[Dict[str, Node]] = None,
) -> PackedForks:
    """Build the [K, …] fork planes off the mirror's packed snapshot.

    Caller holds the scheduler lock and has already synced/repacked the
    mirror (and interned every clone's labels — ``collect_clones`` runs
    before the repack so a val-bucket overflow forces the full pack the
    mirror already knows how to do).
    """
    vocab = mirror.vocab
    node_by_name = {cn.node.name: cn for cn in cache.real_nodes()}
    if clones is None:
        clones = collect_clones(
            forks, {n: cn.node for n, cn in node_by_name.items()}
        )
    nt, clone_slots = _extend_node_tensors(
        mirror.nodes,
        clones,
        vocab,
        n_multiple=getattr(mirror, "node_pad_multiple", 1),
    )
    existing = mirror.existing
    epod_slot = {
        uid: slot for uid, (slot, _pod) in (mirror._epod_slots or {}).items()
    }
    epod_node = np.asarray(existing.node_idx)
    lanes = ResourceLanes(vocab)
    R = nt.allocatable.shape[1]

    K = len(forks)
    k_pad = k_cap or bucket_cap(max(K, 1), 1)
    N = nt.n_cap
    E = existing.valid.shape[0]
    base_valid = np.asarray(nt.valid, bool)
    base_epod_valid = np.asarray(existing.valid, bool)

    fk_alive = np.broadcast_to(base_valid, (k_pad, N)).copy()
    fk_unsched = np.broadcast_to(np.asarray(nt.unschedulable, bool), (k_pad, N)).copy()
    fk_alloc = np.broadcast_to(nt.allocatable, (k_pad, N, R)).copy()
    fk_req = np.broadcast_to(nt.requested, (k_pad, N, R)).copy()
    fk_nz = np.broadcast_to(nt.nonzero_req, (k_pad, N, 2)).copy()
    fk_npods = np.broadcast_to(nt.num_pods, (k_pad, N)).copy()
    fk_epod_valid = np.broadcast_to(base_epod_valid, (k_pad, E)).copy()
    fk_pod_live = np.zeros((k_pad, p_cap), bool)
    fk_pod_live[:K, : len(batch_uids)] = True  # padding forks: no live pods
    fk_pod_live[K:, :] = False
    uid_pos = {uid: i for i, uid in enumerate(batch_uids)}

    for k, f in enumerate(forks):
        for _template, clone_name in f.add:
            fk_alive[k, clone_slots[clone_name]] = True
        for name in f.remove:
            slot = nt.name_to_idx.get(name)
            if slot is None:
                raise ValueError(f"fork {f.label!r}: unknown node {name!r}")
            fk_alive[k, slot] = False
            fk_epod_valid[k] &= epod_node != slot
        for name in f.cordon:
            slot = nt.name_to_idx.get(name)
            if slot is None:
                raise ValueError(f"fork {f.label!r}: unknown node {name!r}")
            fk_unsched[k, slot] = True
        for name, num, den in f.scale:
            slot = nt.name_to_idx.get(name)
            if slot is None:
                raise ValueError(f"fork {f.label!r}: unknown node {name!r}")
            fk_alloc[k, slot] = fk_alloc[k, slot].astype(np.int64) * num // den
        if f.evict:
            evicted = set(f.evict)
            touched: Dict[str, None] = {}
            for uid in f.evict:
                slot = epod_slot.get(uid)
                if slot is None:
                    raise ValueError(
                        f"fork {f.label!r}: evicted pod {uid!r} is not placed"
                    )
                fk_epod_valid[k, slot] = False
                node_name = (
                    nt.names[epod_node[slot]]
                    if 0 <= epod_node[slot] < len(nt.names)
                    else None
                )
                if node_name is not None:
                    touched[node_name] = None
            # exact pack arithmetic: recompute each touched node's usage
            # rows from the REMAINING pods' Resources (the mirror's own
            # formulas) — subtracting quantized rows would drift on ceils
            for node_name in touched:
                cn = node_by_name.get(node_name)
                slot = nt.name_to_idx[node_name]
                remaining = [
                    p for p in cn.pods.values() if p.uid not in evicted
                ]
                req = Resource()
                nz = Resource()
                for p in remaining:
                    pr = p.compute_requests()
                    req.add(pr)
                    nz.add(pr.non_zero_defaulted())
                fk_req[k, slot] = lanes.request_row(req, R)
                fk_nz[k, slot, 0] = nz.milli_cpu
                fk_nz[k, slot, 1] = -(-nz.memory // MEM_UNIT)
                fk_npods[k, slot] = len(remaining)
        if f.live is not None:
            fk_pod_live[k, :] = False
            for uid in f.live:
                pos = uid_pos.get(uid)
                if pos is not None:
                    fk_pod_live[k, pos] = True

    planes = dict(
        fk_alive=fk_alive,
        fk_unsched=fk_unsched,
        fk_alloc=fk_alloc.astype(np.int32),
        fk_req=fk_req.astype(np.int32),
        fk_nz=fk_nz.astype(np.int32),
        fk_npods=fk_npods.astype(np.int32),
        fk_epod_valid=fk_epod_valid,
        fk_nvalid=fk_alive.sum(axis=1).astype(np.int32),
        fk_pod_live=fk_pod_live,
    )
    return PackedForks(
        planes=planes,
        nt=nt,
        clone_slots=clone_slots,
        k_used=K,
        names=list(nt.names),
    )
