"""Golden-model tests: oracle filter/score semantics against hand-computed
expectations (shapes mirror the reference's plugin unit tests, e.g.
noderesources/fit_test.go, interpodaffinity/filtering_test.go)."""

import pytest

from kubernetes_tpu.api import Container, Node, Pod, Resource, Taint, Toleration
from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    PreferredSchedulingTerm,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from kubernetes_tpu.oracle import OracleState, filters as F, scores as S
from kubernetes_tpu.oracle.pipeline import feasible_nodes, schedule_one


def mknode(name, cpu="4", mem="8Gi", labels=None, taints=(), pods_cap=110, **kw):
    return Node(
        name=name,
        labels=labels or {},
        capacity=Resource.from_map({"cpu": cpu, "memory": mem, "pods": pods_cap}),
        taints=tuple(taints),
        **kw,
    )


def mkpod(name, cpu="0", mem="0", node=None, labels=None, ns="default", **kw):
    return Pod(
        name=name,
        namespace=ns,
        labels=labels or {},
        node_name=node or "",
        containers=[Container(requests={"cpu": cpu, "memory": mem})],
        **kw,
    )


class TestResourcesFit:
    def test_fits(self):
        st = OracleState.build([mknode("n1", cpu="2")])
        assert F.filter_node_resources(mkpod("p", cpu="1"), st.nodes["n1"]) == []

    def test_insufficient_cpu(self):
        st = OracleState.build([mknode("n1", cpu="2")], [mkpod("e", cpu="1500m", node="n1")])
        reasons = F.filter_node_resources(mkpod("p", cpu="1"), st.nodes["n1"])
        assert reasons == ["Insufficient cpu"]

    def test_multiple_reasons(self):
        st = OracleState.build([mknode("n1", cpu="1", mem="1Gi")])
        reasons = F.filter_node_resources(mkpod("p", cpu="2", mem="2Gi"), st.nodes["n1"])
        assert set(reasons) == {"Insufficient cpu", "Insufficient memory"}

    def test_pods_limit(self):
        st = OracleState.build(
            [mknode("n1", pods_cap=1)], [mkpod("e", node="n1")]
        )
        assert F.filter_node_resources(mkpod("p"), st.nodes["n1"]) == ["Too many pods"]

    def test_zero_request_always_fits_capacity(self):
        st = OracleState.build([mknode("n1", cpu="1")], [mkpod("e", cpu="1", node="n1")])
        assert F.filter_node_resources(mkpod("p"), st.nodes["n1"]) == []

    def test_extended_resource(self):
        n = mknode("n1")
        n.allocatable.scalars["example.com/foo"] = 2
        st = OracleState.build([n])
        pod = Pod(name="p", containers=[Container(requests={"example.com/foo": "4"})])
        assert F.filter_node_resources(pod, st.nodes["n1"]) == [
            "Insufficient example.com/foo"
        ]


class TestTaints:
    def test_untolerated(self):
        st = OracleState.build([mknode("n1", taints=[Taint(key="k", value="v")])])
        assert F.filter_taints(mkpod("p"), st.nodes["n1"]) is not None

    def test_tolerated(self):
        st = OracleState.build([mknode("n1", taints=[Taint(key="k", value="v")])])
        pod = mkpod("p", tolerations=(Toleration(key="k", operator="Equal", value="v"),))
        assert F.filter_taints(pod, st.nodes["n1"]) is None

    def test_prefer_no_schedule_passes_filter(self):
        st = OracleState.build(
            [mknode("n1", taints=[Taint(key="k", effect="PreferNoSchedule")])]
        )
        assert F.filter_taints(mkpod("p"), st.nodes["n1"]) is None

    def test_score_counts_intolerable_prefer(self):
        st = OracleState.build(
            [
                mknode(
                    "n1",
                    taints=[
                        Taint(key="a", effect="PreferNoSchedule"),
                        Taint(key="b", effect="PreferNoSchedule"),
                    ],
                )
            ]
        )
        pod = mkpod("p", tolerations=(Toleration(key="a", operator="Exists"),))
        assert S.score_taint_toleration(pod, st.nodes["n1"]) == 1
        assert S.normalize_taint_toleration([0, 1, 2]) == [100, 50, 0]


class TestInterPodAffinity:
    def zone_nodes(self):
        return [
            mknode("n1", labels={"zone": "a", "kubernetes.io/hostname": "n1"}),
            mknode("n2", labels={"zone": "b", "kubernetes.io/hostname": "n2"}),
        ]

    def test_required_affinity_needs_match_in_domain(self):
        st = OracleState.build(
            self.zone_nodes(), [mkpod("e", node="n1", labels={"app": "db"})]
        )
        pod = mkpod(
            "p",
            affinity=Affinity(
                pod_affinity=PodAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        PodAffinityTerm(
                            topology_key="zone",
                            label_selector=LabelSelector(match_labels={"app": "db"}),
                        ),
                    )
                )
            ),
        )
        assert F.filter_interpod_affinity(pod, st.nodes["n1"], st) is None
        assert F.filter_interpod_affinity(pod, st.nodes["n2"], st) is not None

    def test_first_pod_self_match_escape(self):
        st = OracleState.build(self.zone_nodes())
        pod = mkpod(
            "p",
            labels={"app": "db"},
            affinity=Affinity(
                pod_affinity=PodAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        PodAffinityTerm(
                            topology_key="zone",
                            label_selector=LabelSelector(match_labels={"app": "db"}),
                        ),
                    )
                )
            ),
        )
        # No pod matches anywhere + self-match ⇒ allowed.
        assert F.filter_interpod_affinity(pod, st.nodes["n1"], st) is None

    def test_incoming_anti_affinity(self):
        st = OracleState.build(
            self.zone_nodes(), [mkpod("e", node="n1", labels={"app": "db"})]
        )
        pod = mkpod(
            "p",
            affinity=Affinity(
                pod_anti_affinity=PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        PodAffinityTerm(
                            topology_key="zone",
                            label_selector=LabelSelector(match_labels={"app": "db"}),
                        ),
                    )
                )
            ),
        )
        assert F.filter_interpod_affinity(pod, st.nodes["n1"], st) is not None
        assert F.filter_interpod_affinity(pod, st.nodes["n2"], st) is None

    def test_existing_anti_affinity_symmetry(self):
        existing = mkpod(
            "e",
            node="n1",
            affinity=Affinity(
                pod_anti_affinity=PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        PodAffinityTerm(
                            topology_key="zone",
                            label_selector=LabelSelector(match_labels={"app": "web"}),
                        ),
                    )
                )
            ),
        )
        st = OracleState.build(self.zone_nodes(), [existing])
        pod = mkpod("p", labels={"app": "web"})
        assert F.filter_interpod_affinity(pod, st.nodes["n1"], st) is not None
        assert F.filter_interpod_affinity(pod, st.nodes["n2"], st) is None

    def test_namespace_scoping(self):
        st = OracleState.build(
            self.zone_nodes(),
            [mkpod("e", node="n1", labels={"app": "db"}, ns="other")],
        )
        pod = mkpod(
            "p",
            affinity=Affinity(
                pod_affinity=PodAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        PodAffinityTerm(
                            topology_key="zone",
                            label_selector=LabelSelector(match_labels={"app": "db"}),
                        ),
                    )
                )
            ),
        )
        # Term defaults to pod's own namespace; existing pod is in "other".
        assert F.filter_interpod_affinity(pod, st.nodes["n1"], st) is not None
        pod2 = mkpod(
            "p2",
            affinity=Affinity(
                pod_affinity=PodAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        PodAffinityTerm(
                            topology_key="zone",
                            namespaces=("other",),
                            label_selector=LabelSelector(match_labels={"app": "db"}),
                        ),
                    )
                )
            ),
        )
        assert F.filter_interpod_affinity(pod2, st.nodes["n1"], st) is None

    def test_preferred_scoring(self):
        st = OracleState.build(
            self.zone_nodes(), [mkpod("e", node="n1", labels={"app": "db"})]
        )
        pod = mkpod(
            "p",
            affinity=Affinity(
                pod_affinity=PodAffinity(
                    preferred_during_scheduling_ignored_during_execution=(
                        WeightedPodAffinityTerm(
                            weight=5,
                            pod_affinity_term=PodAffinityTerm(
                                topology_key="zone",
                                label_selector=LabelSelector(
                                    match_labels={"app": "db"}
                                ),
                            ),
                        ),
                    )
                )
            ),
        )
        raw = S.score_interpod_affinity_all(pod, st, ["n1", "n2"])
        assert raw == [5, 0]
        assert S.normalize_interpod_affinity(raw) == [100, 0]


class TestTopologySpread:
    def nodes(self):
        return [
            mknode("n1", labels={"zone": "a", "kubernetes.io/hostname": "n1"}),
            mknode("n2", labels={"zone": "a", "kubernetes.io/hostname": "n2"}),
            mknode("n3", labels={"zone": "b", "kubernetes.io/hostname": "n3"}),
        ]

    def spread_pod(self, name, max_skew=1, when="DoNotSchedule", **kw):
        return mkpod(
            name,
            labels={"app": "x"},
            topology_spread_constraints=(
                TopologySpreadConstraint(
                    max_skew=max_skew,
                    topology_key="zone",
                    when_unsatisfiable=when,
                    label_selector=LabelSelector(match_labels={"app": "x"}),
                ),
            ),
            **kw,
        )

    def test_skew_rejects(self):
        st = OracleState.build(
            self.nodes(),
            [
                mkpod("e1", node="n1", labels={"app": "x"}),
                mkpod("e2", node="n2", labels={"app": "x"}),
            ],
        )
        pod = self.spread_pod("p")
        # zone a has 2, zone b has 0; placing in a gives skew 3-0 > 1.
        assert F.filter_topology_spread(pod, st.nodes["n1"], st) is not None
        assert F.filter_topology_spread(pod, st.nodes["n3"], st) is None

    def test_missing_label_rejects(self):
        ns = self.nodes() + [mknode("n4", labels={"kubernetes.io/hostname": "n4"})]
        st = OracleState.build(ns)
        pod = self.spread_pod("p")
        assert F.filter_topology_spread(pod, st.nodes["n4"], st) is not None

    def test_min_domains(self):
        st = OracleState.build(
            self.nodes()[:2],  # only zone a exists
            [mkpod("e1", node="n1", labels={"app": "x"})],
        )
        pod = self.spread_pod("p")
        pod.topology_spread_constraints = (
            TopologySpreadConstraint(
                max_skew=1,
                topology_key="zone",
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(match_labels={"app": "x"}),
                min_domains=2,
            ),
        )
        # Only 1 domain < minDomains 2 ⇒ minMatch=0 ⇒ skew=1+1-0=2 > 1.
        assert F.filter_topology_spread(pod, st.nodes["n1"], st) is not None

    def test_soft_scoring_prefers_empty_domain(self):
        st = OracleState.build(
            self.nodes(),
            [
                mkpod("e1", node="n1", labels={"app": "x"}),
                mkpod("e2", node="n2", labels={"app": "x"}),
            ],
        )
        pod = self.spread_pod("p", when="ScheduleAnyway")
        raw = S.score_topology_spread_all(pod, st, ["n1", "n2", "n3"])
        norm = S.normalize_topology_spread(raw)
        assert norm[2] > norm[0] and norm[2] > norm[1]


class TestScores:
    def test_least_allocated(self):
        st = OracleState.build([mknode("n1", cpu="4", mem="4Gi")])
        pod = mkpod("p", cpu="1", mem="1Gi")
        # cpu: (4000-1000)*100/4000=75; mem: (4Gi-1Gi)*100/4Gi=75 → 75
        assert S.score_least_allocated(pod, st.nodes["n1"]) == 75

    def test_least_allocated_nonzero_defaults(self):
        st = OracleState.build([mknode("n1", cpu="1", mem="1000Mi")])
        pod = mkpod("p")  # zero requests default to 100m/200Mi
        # cpu: (1000-100)*100/1000=90; mem: (1000-200)*100/1000=80 → 85
        assert S.score_least_allocated(pod, st.nodes["n1"]) == 85

    def test_balanced_allocation(self):
        st = OracleState.build([mknode("n1", cpu="4", mem="4Gi")])
        pod = mkpod("p", cpu="2", mem="2Gi")
        # fractions equal → std 0 → 100
        assert S.score_balanced_allocation(pod, st.nodes["n1"]) == 100
        pod2 = mkpod("p2", cpu="4", mem="0")
        # fractions 1.0, 0.0 → std 0.5 → 50
        assert S.score_balanced_allocation(pod2, st.nodes["n1"]) == 50

    def test_node_affinity_preferred(self):
        st = OracleState.build([mknode("n1", labels={"disk": "ssd"})])
        pod = mkpod(
            "p",
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    preferred_during_scheduling_ignored_during_execution=(
                        PreferredSchedulingTerm(
                            weight=10,
                            preference=NodeSelectorTerm(
                                match_expressions=(
                                    NodeSelectorRequirement("disk", "In", ("ssd",)),
                                )
                            ),
                        ),
                    )
                )
            ),
        )
        assert S.score_node_affinity(pod, st.nodes["n1"]) == 10


class TestPipeline:
    def test_schedule_one_picks_least_loaded(self):
        st = OracleState.build(
            [mknode("n1"), mknode("n2")],
            [mkpod("e", cpu="2", node="n1")],
        )
        res = schedule_one(mkpod("p", cpu="1"), st)
        assert res.node == "n2"

    def test_schedule_one_unschedulable(self):
        st = OracleState.build([mknode("n1", cpu="1")])
        res = schedule_one(mkpod("p", cpu="2"), st)
        assert res.node is None
        assert "Insufficient cpu" in res.reasons["n1"]

    def test_node_selector_filter(self):
        st = OracleState.build(
            [mknode("n1", labels={"zone": "a"}), mknode("n2", labels={"zone": "b"})]
        )
        res = schedule_one(mkpod("p", node_selector={"zone": "b"}), st)
        assert res.node == "n2"
