"""Perf-regression gate: recorded bench results must clear BENCH_FLOORS.json.

The reference asserts a minimum SchedulingThroughput per scheduler_perf
workload (performance-config.yaml, e.g. :51).  Here the driver's
BENCH_r*.json files are the recorded results; this test fails if the most
recent one dipped below the in-repo floors, so a regression like round 3's
config1 drop (5930 -> 3339 pods/s, unnoticed for a full round) can never
ship silently again.
"""

import glob
import json
import os
import re

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(path):
    with open(path) as f:
        return json.load(f)


def _latest_bench():
    paths = glob.glob(os.path.join(ROOT, "BENCH_r*.json"))
    if not paths:
        return None

    def round_no(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    return _load(max(paths, key=round_no))


def _bench_configs(bench):
    """The driver's BENCH_r files wrap bench.py's JSON line in
    {"parsed": ...}; accept both shapes."""
    parsed = bench.get("parsed", bench)
    out = dict(parsed.get("configs", {}))
    out[parsed["metric"]] = parsed["value"]
    return out


# Presence-without-floor is fine: newly introduced keys (config7_chaos_*
# and friends) may ship in a recorded bench for rounds before anyone
# ratchets a floor for them; only keys BOTH recorded and floored gate.
# Non-scalar entries (config0_phases breakdown dicts) never gate.
def _gateable(results, key):
    v = results.get(key)
    return v if isinstance(v, (int, float)) else None


def _floor_failures(floors, results):
    return [
        f"{key}: {results[key]:.1f} < floor {floor}"
        for key, floor in floors.items()
        if _gateable(results, key) is not None and results[key] < floor
    ]


def _ceiling_failures(ceilings, results):
    return [
        f"{key}: {results[key]:.2f} > ceiling {cap}"
        for key, cap in ceilings.items()
        if _gateable(results, key) is not None and results[key] > cap
    ]


def test_floors_file_is_wellformed():
    doc = _load(os.path.join(ROOT, "BENCH_FLOORS.json"))
    floors = doc["floors"]
    assert floors, "no floors recorded"
    for k, v in floors.items():
        assert v > 0, f"floor {k} must be positive"
    for k, v in doc.get("ceilings", {}).items():
        assert v > 0, f"ceiling {k} must be positive"
        assert k not in floors, f"{k} cannot be both floor and ceiling"


def test_latest_recorded_bench_clears_floors():
    bench = _latest_bench()
    if bench is None:
        pytest.skip("no BENCH_r*.json recorded yet")
    floors_doc = _load(os.path.join(ROOT, "BENCH_FLOORS.json"))
    floors = floors_doc["floors"]
    results = _bench_configs(bench)
    # Floors added AFTER a bench round was recorded only apply to later
    # rounds; config3/4 floors reflect the round-4 kernels, so only check
    # keys present in the recorded results AND not newer than them.
    since = floors_doc.get("floors_since", {})
    failures = _floor_failures(floors, results)
    # Ceilings: lower-is-better wall-clock budgets (the config0 north-star
    # drain).  Same since-round gating as floors, via ceilings_since.
    ceilings = floors_doc.get("ceilings", {})
    ceilings_since = floors_doc.get("ceilings_since", {})
    ceiling_failures = _ceiling_failures(ceilings, results)
    # Round 3's recorded results predate these floors (the floors were
    # introduced because round 3 regressed); enforcement begins with the
    # first bench recorded after this test exists — r4 and later.
    n = max(
        int(re.search(r"BENCH_r(\d+)\.json$", p).group(1))
        for p in glob.glob(os.path.join(ROOT, "BENCH_r*.json"))
    )
    if n <= 3:
        pytest.skip(f"floors enforced from round 4 (latest recorded: r{n})")
    # A round recorded in acknowledged_regressions was caught by this gate
    # and fixed in the NEXT round's code (the entry documents the fix and
    # names the regressed config keys); only those keys are excused — any
    # other floor failure in the same round still fails, and the gate fully
    # re-arms for every round after it.
    # floors introduced in a later round than the recorded bench don't
    # apply to it (floors_since maps key -> first enforced round)
    failures = [
        f for f in failures if since.get(f.split(":")[0], 0) <= n
    ]
    failures += [
        f for f in ceiling_failures if ceilings_since.get(f.split(":")[0], 0) <= n
    ]
    acked = floors_doc.get("acknowledged_regressions", {}).get(str(n))
    if acked:
        excused = set(acked["keys"])
        failures = [f for f in failures if f.split(":")[0] not in excused]
    assert not failures, "bench regression below floors: " + "; ".join(failures)
    # decision-parity gate: a recorded bench that ran the parity checks
    # must show ZERO diffs — wrong decisions are a regression no matter
    # how fast they were made
    if "parity_total_diffs" in results:
        assert results["parity_total_diffs"] == 0, (
            f"parity diffs in recorded bench: {results['parity_total_diffs']}"
        )


def test_no_multichip_floors_from_virtual_device_runs():
    """ISSUE 14 ratchet guard: config8_multichip_* throughput comes from
    forced-host VIRTUAL devices on this CPU box (8 'devices' sharing one
    socket) — an emulation artifact, not a hardware fact.  If the latest
    recorded bench marks its multichip line virtual, a ratcheted
    config8 floor/ceiling is itself the regression: refuse it."""
    bench = _latest_bench()
    if bench is None:
        pytest.skip("no BENCH_r*.json recorded yet")
    results = _bench_configs(bench)
    if not results.get("config8_multichip_virtual_devices"):
        pytest.skip("latest bench has no virtual-device multichip line")
    floors_doc = _load(os.path.join(ROOT, "BENCH_FLOORS.json"))
    offending = [
        k
        for store in ("floors", "ceilings")
        for k in floors_doc.get(store, {})
        if k.startswith("config8_multichip")
    ]
    assert offending == [], (
        "config8_multichip floors/ceilings ratcheted from a VIRTUAL-device "
        f"bench run: {offending} (BENCH_FLOORS _comment_environment "
        "discipline — calibrate on a real multi-device box)"
    )


def test_no_devicefault_floors_from_cpu_only_runs():
    """ISSUE 15 ratchet guard: config15_devicefault_* numbers on this box
    come from a CPU-only backend (no accelerator behind the dispatch
    stream the faults land on) and are marked
    config15_devicefault_cpu_only in the bench JSON.  They are
    recovery/engagement evidence, NOT throughput facts — refuse a
    ratcheted config15 floor/ceiling whenever the latest recorded bench
    is CPU-only."""
    bench = _latest_bench()
    if bench is None:
        pytest.skip("no BENCH_r*.json recorded yet")
    results = _bench_configs(bench)
    if not results.get("config15_devicefault_cpu_only"):
        pytest.skip("latest bench has no CPU-only device-fault line")
    floors_doc = _load(os.path.join(ROOT, "BENCH_FLOORS.json"))
    offending = [
        k
        for store in ("floors", "ceilings")
        for k in floors_doc.get(store, {})
        if k.startswith("config15_devicefault")
    ]
    assert offending == [], (
        "config15_devicefault floors/ceilings ratcheted from a CPU-only "
        f"bench run: {offending} (BENCH_FLOORS _comment_environment "
        "discipline — calibrate degraded-mode throughput on a real "
        "accelerator box)"
    )


def test_no_wire_floors_from_cpu_only_runs():
    """ISSUE 17 ratchet guard: config17_wire_* numbers on this box come
    from a CPU-only backend (wire sweep + hollow soak CPU-box-sized, the
    50k scale rides BENCH_WIRE_* on real boxes) and are marked
    config17_wire_cpu_only in the bench JSON.  They are codec-comparison
    and engagement evidence, NOT throughput facts — refuse a ratcheted
    config17 floor/ceiling whenever the latest recorded bench is
    CPU-only."""
    bench = _latest_bench()
    if bench is None:
        pytest.skip("no BENCH_r*.json recorded yet")
    results = _bench_configs(bench)
    if not results.get("config17_wire_cpu_only"):
        pytest.skip("latest bench has no CPU-only wire line")
    floors_doc = _load(os.path.join(ROOT, "BENCH_FLOORS.json"))
    offending = [
        k
        for store in ("floors", "ceilings")
        for k in floors_doc.get(store, {})
        if k.startswith("config17_wire")
    ]
    assert offending == [], (
        "config17_wire floors/ceilings ratcheted from a CPU-only bench "
        f"run: {offending} (BENCH_FLOORS _comment_environment discipline "
        "— calibrate wire-tier throughput on a real box)"
    )


def test_new_keys_without_floors_are_tolerated():
    """A bench result key with no recorded floor (or a non-scalar value)
    must never fail the gate — new config lines land a round before their
    floors get ratcheted in.  Exercises the REAL gate helpers against a
    synthetic result set containing unfloored, non-scalar, and failing
    keys."""
    floors = {"config1": 100.0}
    ceilings = {"config0_drain_s": 2.5}
    results = {
        "config1": 150.0,  # floored, passing
        "config0_drain_s": 2.0,  # ceilinged, passing
        "config7_chaos_soak_pods_per_s": 1.0,  # present, no floor → ignored
        "config7_chaos_recovery_p99_ms": 1e9,  # present, no ceiling → ignored
        "config0_phases": {"bind": 0.5},  # non-scalar → ignored
    }
    assert _floor_failures(floors, results) == []
    assert _ceiling_failures(ceilings, results) == []
    # and the gate still bites on keys that ARE floored
    results["config1"] = 10.0
    results["config0_drain_s"] = 9.0
    assert _floor_failures(floors, results) == ["config1: 10.0 < floor 100.0"]
    assert _ceiling_failures(ceilings, results) == [
        "config0_drain_s: 9.00 > ceiling 2.5"
    ]
