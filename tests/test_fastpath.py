"""Fast-path decision identity: the signature greedy must bit-match the
gang scan (which is property-tested against the serial oracle)."""

import random

import pytest

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import (
    Container,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Affinity,
    Node,
    Pod,
    Taint,
    Toleration,
)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.fake_cluster import FakeCluster


def _mk_cluster(rng, n_nodes):
    nodes = []
    for i in range(n_nodes):
        taints = ()
        if rng.random() < 0.2:
            taints = (Taint(key="dedicated", value=rng.choice(["a", "b"])),)
        nodes.append(
            Node(
                name=f"n{i:03d}",
                labels={
                    "kubernetes.io/hostname": f"n{i:03d}",
                    "zone": f"z{i % 3}",
                    "disk": rng.choice(["ssd", "hdd"]),
                },
                capacity=Resource.from_map(
                    {
                        "cpu": rng.choice(["2", "4", "8"]),
                        "memory": rng.choice(["8Gi", "16Gi"]),
                        "pods": rng.choice([5, 20]),
                    }
                ),
                taints=taints,
            )
        )
    return nodes


def _mk_pod(rng, i):
    kwargs = {}
    if rng.random() < 0.3:
        kwargs["tolerations"] = (
            Toleration(key="dedicated", operator="Equal", value="a"),
        )
    if rng.random() < 0.3:
        kwargs["node_selector"] = {"disk": rng.choice(["ssd", "hdd"])}
    if rng.random() < 0.2:
        kwargs["affinity"] = Affinity(
            node_affinity=NodeAffinity(
                required_during_scheduling_ignored_during_execution=NodeSelector(
                    (
                        NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(
                                    "zone", "In", (rng.choice(["z0", "z1"]),)
                                ),
                            )
                        ),
                    )
                )
            )
        )
    return Pod(
        name=f"p{i:04d}",
        containers=[
            Container(
                name="c",
                requests={
                    "cpu": rng.choice(["100m", "250m", "500m", "1"]),
                    "memory": rng.choice(["64Mi", "256Mi", "1Gi"]),
                },
            )
        ],
        **kwargs,
    )


def _run(pods_fn, nodes, force_scan: bool):
    cluster = FakeCluster()
    sched = Scheduler()
    if force_scan:
        sched._try_fast_schedule = lambda *a, **k: None
    cluster.connect(sched)
    for n in nodes:
        cluster.create_node(n)
    for p in pods_fn():
        cluster.create_pod(p)
    out = sched.schedule_pending()
    return {o.pod.name: o.node for o in out}, sched


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fast_path_matches_scan(seed):
    rng = random.Random(seed)
    nodes = _mk_cluster(rng, 40)
    spec = [(_mk_pod(random.Random(seed * 1000 + i), i)) for i in range(120)]

    def pods():
        import copy

        return [copy.deepcopy(p) for p in spec]

    fast, s_fast = _run(pods, nodes, force_scan=False)
    scan, s_scan = _run(pods, nodes, force_scan=True)
    assert s_fast.metrics["fast_batches"] > 0, "fast path never engaged"
    assert fast == scan


def test_fast_path_engages_on_basic_workload():
    nodes = [
        Node(
            name=f"n{i}",
            labels={"kubernetes.io/hostname": f"n{i}"},
            capacity=Resource.from_map({"cpu": "4", "memory": "16Gi", "pods": 50}),
        )
        for i in range(10)
    ]

    def pods():
        return [
            Pod(
                name=f"p{i}",
                containers=[Container(name="c", requests={"cpu": "500m"})],
            )
            for i in range(30)
        ]

    got, sched = _run(pods, nodes, force_scan=False)
    assert sched.metrics["fast_batches"] == 1
    assert sched.metrics["scan_batches"] == 0
    assert all(v is not None for v in got.values())


def test_fast_path_falls_back_on_spread():
    from kubernetes_tpu.api.types import LabelSelector, TopologySpreadConstraint

    nodes = [
        Node(
            name=f"n{i}",
            labels={"kubernetes.io/hostname": f"n{i}", "zone": f"z{i%2}"},
            capacity=Resource.from_map({"cpu": "4", "memory": "16Gi", "pods": 50}),
        )
        for i in range(4)
    ]

    def pods():
        return [
            Pod(
                name=f"p{i}",
                labels={"app": "x"},
                topology_spread_constraints=(
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key="zone",
                        when_unsatisfiable="DoNotSchedule",
                        label_selector=LabelSelector(match_labels={"app": "x"}),
                    ),
                ),
                containers=[Container(name="c", requests={"cpu": "100m"})],
            )
            for i in range(8)
        ]

    got, sched = _run(pods, nodes, force_scan=False)
    assert sched.metrics["fast_batches"] == 0
    # spread pods leave the fast path for a cross-pod dispatch — the wave
    # by default, the gang scan when waveDispatch is off
    assert sched.metrics["scan_batches"] + sched.metrics["wave_batches"] >= 1
    assert all(v is not None for v in got.values())


def test_fast_committer_sees_scan_path_commits():
    """A fast batch AFTER a scan batch must account for the scan batch's
    capacity consumption (the committer cache key includes non-fast
    commits)."""
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
    )
    from kubernetes_tpu.scheduler import Scheduler

    sched = Scheduler()
    sched.binding_sink = lambda pod, node: None
    for i in range(2):
        sched.on_node_add(
            Node(
                name=f"n{i}",
                labels={"kubernetes.io/hostname": f"n{i}"},
                capacity=Resource.from_map({"cpu": "1", "memory": "4Gi"}),
            )
        )
    # drain A: plain pod (fast path) — builds the committer
    sched.on_pod_add(
        Pod(name="a", containers=[Container(requests={"cpu": "600m"})])
    )
    outs = sched.schedule_pending()
    assert outs[0].node is not None
    assert sched.metrics["fast_batches"] == 1
    # drain B: anti-affinity pod (scan path) — consumes the other node
    sched.on_pod_add(
        Pod(
            name="b",
            labels={"grp": "g"},
            affinity=Affinity(
                pod_anti_affinity=PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        PodAffinityTerm(
                            topology_key="kubernetes.io/hostname",
                            label_selector=LabelSelector(match_labels={"grp": "g"}),
                        ),
                    )
                )
            ),
            containers=[Container(requests={"cpu": "600m"})],
        )
    )
    outs = sched.schedule_pending()
    assert outs[0].node is not None
    assert (
        sched.metrics["scan_batches"]
        + sched.metrics.get("chain_batches", 0)
        + sched.metrics["wave_batches"]
        >= 1
    )
    # drain C: plain pod (fast path again) — 600m no longer fits anywhere;
    # a stale committer would wrongly place it on the scan batch's node
    sched.on_pod_add(
        Pod(name="c", containers=[Container(requests={"cpu": "600m"})])
    )
    outs = sched.schedule_pending()
    assert outs[0].node is None, outs[0]


@pytest.mark.parametrize("seed", [3, 4, 5])
def test_sig_scan_matches_host_committer(seed):
    """Shadow mode: the device sig_scan kernel's choices must bit-match the
    host FastCommitter replaying the same batches on the same state."""
    rng = random.Random(seed)
    nodes = _mk_cluster(rng, 30)

    def pods():
        import copy

        return [
            copy.deepcopy(_mk_pod(random.Random(seed * 77 + i), i))
            for i in range(90)
        ]

    cluster = FakeCluster()
    sched = Scheduler()
    sched.fast_shadow_check = True  # any divergence raises inside the drain
    cluster.connect(sched)
    for n in nodes:
        cluster.create_node(n)
    for p in pods():
        cluster.create_pod(p)
    sched.schedule_pending()
    assert sched.metrics["fast_batches"] > 0, "fast path never engaged"


def test_extension_stops_at_nonconst_signature_no_pod_loss():
    """Interleave signatures whose static taint raws ARE and are NOT
    constant over their feasible nodes (PreferNoSchedule on a subset of
    nodes makes untolerated pods' taint score vary → scan path).  The
    fast-batch extension must stop at such pods rather than pop them, and
    every pod must drain exactly once through whichever path owns it."""
    from kubernetes_tpu.api.types import Taint, Toleration
    from kubernetes_tpu.scheduler import Scheduler

    nodes = []
    for i in range(12):
        taints = (
            (Taint(key="soft", value="x", effect="PreferNoSchedule"),)
            if i % 3 == 0
            else ()
        )
        nodes.append(
            Node(
                name=f"n{i}",
                labels={"kubernetes.io/hostname": f"n{i}"},
                capacity=Resource.from_map(
                    {"cpu": "16", "memory": "64Gi", "pods": 60}
                ),
                taints=taints,
            )
        )
    pods = []
    for i in range(120):
        tol = (
            (Toleration(key="soft", operator="Equal", value="x"),)
            if i % 4 != 0
            else ()
        )
        pods.append(
            Pod(
                name=f"p{i:03d}",
                tolerations=tol,
                containers=[Container(name="c", requests={"cpu": "100m"})],
            )
        )
    got = {}
    sched = Scheduler()
    sched.config.batch_size = 32  # several batches; extension crosses sigs
    sched.binding_sink = lambda pod, node: got.__setitem__(pod.name, node)
    for n in nodes:
        sched.on_node_add(n)
    for p in pods:
        sched.on_pod_add(p)
    outs = sched.schedule_pending()
    assert len(got) == 120, f"lost pods: {len(got)}"
    assert sorted(got) == sorted(p.name for p in pods)
    assert len(sched.queue) == 0
    # nothing stuck in the in-flight ledger
    assert not sched.queue._in_flight, sched.queue._in_flight
