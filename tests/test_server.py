"""Server tier: serving endpoints, leader election, cache debugger.

Matches cmd/kube-scheduler/app/server.go:163-318 (healthz/readyz/metrics/
configz serving, Lease-based leader election where exactly ONE replica
schedules and a lost lease hands over) and backend/cache/debugger (dump +
cache-vs-informer comparer).
"""

import time
import urllib.request

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import Container, Node, Pod
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.server import LeaseElector, SchedulerServer
from kubernetes_tpu.testing.fake_cluster import FakeCluster


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as r:
        return r.status, r.read().decode()


def _env():
    api = FakeCluster()
    sched = Scheduler()
    api.connect(sched)
    for i in range(4):
        api.create_node(
            Node(
                name=f"n{i}",
                labels={"kubernetes.io/hostname": f"n{i}"},
                capacity=Resource.from_map({"cpu": "4", "memory": "8Gi"}),
            )
        )
    return api, sched


def test_endpoints_serve():
    api, sched = _env()
    server = SchedulerServer(sched, ground_truth=api.ground_truth)
    server.start()
    try:
        assert _get(server.port, "/healthz") == (200, "ok")
        assert _get(server.port, "/readyz") == (200, "ok")
        code, body = _get(server.port, "/metrics")
        assert code == 200 and "scheduler_" in body
        code, body = _get(server.port, "/configz")
        assert code == 200 and "batchSize" in body
        # schedule something through the running loop
        api.create_pod(
            Pod(name="p1", containers=[Container(requests={"cpu": "100m"})])
        )
        deadline = time.time() + 10
        while time.time() < deadline and "default/p1#" not in str(api.bindings):
            if any(True for _ in api.bindings):
                break
            time.sleep(0.05)
        assert api.bindings, "server loop did not schedule"
        code, body = _get(server.port, "/debug/cache")
        assert code == 200 and "cache dump" in body
    finally:
        server.stop()


def test_leader_election_exactly_one_schedules():
    api, s1 = _env()
    s2 = Scheduler()
    api.watch_nodes(s2.on_node_add, s2.on_node_update, s2.on_node_delete)
    api.watch_pods(s2.on_pod_add, s2.on_pod_update, s2.on_pod_delete)
    s2.binding_sink = api.bind

    e1 = LeaseElector(api.lease_store, "replica-1", retry_period_s=0.05)
    e2 = LeaseElector(api.lease_store, "replica-2", retry_period_s=0.05)
    srv1 = SchedulerServer(s1, elector=e1)
    srv2 = SchedulerServer(s2, elector=e2)
    srv1.start()
    time.sleep(0.2)  # let replica-1 take the lease
    srv2.start()
    try:
        for i in range(6):
            api.create_pod(
                Pod(
                    name=f"p{i}",
                    containers=[Container(requests={"cpu": "100m"})],
                )
            )
        deadline = time.time() + 10
        while time.time() < deadline and len(api.bindings) < 6:
            time.sleep(0.05)
        assert len(api.bindings) == 6
        leaders = [srv1.is_leading(), srv2.is_leading()]
        assert leaders.count(True) == 1, leaders
        # only the leader performed scheduling work
        assert (s1.metrics["scheduled"] > 0) != (s2.metrics["scheduled"] > 0)
    finally:
        srv1.stop()
        srv2.stop()


def test_leader_failover():
    api, s1 = _env()
    s2 = Scheduler()
    api.watch_nodes(s2.on_node_add, s2.on_node_update, s2.on_node_delete)
    api.watch_pods(s2.on_pod_add, s2.on_pod_update, s2.on_pod_delete)
    s2.binding_sink = api.bind
    e1 = LeaseElector(
        api.lease_store, "replica-1", lease_duration_s=0.3, retry_period_s=0.05
    )
    e2 = LeaseElector(
        api.lease_store, "replica-2", lease_duration_s=0.3, retry_period_s=0.05
    )
    srv1 = SchedulerServer(s1, elector=e1)
    srv2 = SchedulerServer(s2, elector=e2)
    srv1.start()
    time.sleep(0.2)
    srv2.start()
    try:
        assert srv1.is_leading()
        srv1.stop()  # leader exits (releases the lease)
        deadline = time.time() + 5
        while time.time() < deadline and not srv2.is_leading():
            time.sleep(0.05)
        assert srv2.is_leading()
        api.create_pod(
            Pod(name="after", containers=[Container(requests={"cpu": "100m"})])
        )
        deadline = time.time() + 10
        while time.time() < deadline and not api.bindings:
            time.sleep(0.05)
        assert api.bindings and s2.metrics["scheduled"] >= 1
    finally:
        srv2.stop()


def test_cache_debugger_compare_finds_divergence():
    api, sched = _env()
    server = SchedulerServer(sched, ground_truth=api.ground_truth)
    # inject a ghost node directly into the cache (bypassing the informer)
    sched.cache.add_node(
        Node(name="ghost", capacity=Resource.from_map({"cpu": "1"}))
    )
    problems = server.debugger.compare()
    assert any("ghost" in p for p in problems), problems
    dump = server.debugger.dump()
    assert "cache dump" in dump and "n0" in dump
