"""Binary wire codec + zero-copy watch fanout (client/wire_codec.py).

Covers the ISSUE 17 acceptance surface:
  * every registered kind (and the watch-event / list envelopes around
    them) round-trips through the binary frame to an object EQUAL to the
    JSON path's — asserted as byte-identical canonical JSON;
  * the nested-blob splice (encode once, share across the event frame
    and the list frame) decodes identically to direct encoding;
  * HTTP end-to-end: list + watch payloads decode byte-identical under
    binary and JSON clients against the same apiserver, and a client
    that never asks for binary gets JSON (debuggability default);
  * wire-byte accounting lands in scheduler_tpu_wire_bytes_total on
    scrape, split by codec and direction;
  * the condition-variable watch wakeup: an idle watcher blocks, then
    wakes within milliseconds of the append (no 0.5s poll), asserted
    both on _WatchCache.since directly and via the PR 16 watch_fanout
    hop over the real HTTP path;
  * bind retry idempotence: a binding POST applied by the server whose
    response dies on the wire is retried, observes its own first attempt
    as a 409-with-matching-node, and reports success — while a REAL
    conflict still raises;
  * chaos watch-cut/410/compaction scenarios drive identical journals
    under either codec (fault injection sits above the frame seam).
"""

import json
import threading
import time

import pytest

from kubernetes_tpu.api.codec import KINDS, decode, encode
from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    PodDisruptionBudget,
    Taint,
    Toleration,
)
from kubernetes_tpu.client import wire_codec
from kubernetes_tpu.client.api_server import ApiServer
from kubernetes_tpu.client.client import ApiClient, ApiError, RemoteClusterSource
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.fake_cluster import FakeCluster


def _canon(value) -> bytes:
    return json.dumps(value, sort_keys=True, separators=(",", ":")).encode()


def _node(name="n0"):
    return Node(
        name=name,
        labels={
            "kubernetes.io/hostname": name,
            "topology.kubernetes.io/zone": "zone-a",
            "custom/λ-label": "ünïcode",
        },
        capacity=Resource.from_map(
            {"cpu": "8", "memory": "32Gi", "pods": 110, "tpu.dev/chips": 4}
        ),
        taints=(Taint("dedicated", "tpu", "NoSchedule"),),
    )


def _pod(name="p0", uid=""):
    return Pod(
        name=name,
        uid=uid,
        labels={"app": name},
        annotations={"note": ""},
        containers=[
            Container(
                name="c",
                requests={"cpu": "250m", "memory": "128Mi"},
                limits={"cpu": "1"},
            )
        ],
        tolerations=(Toleration(key="dedicated", operator="Exists"),),
        affinity=Affinity(
            node_affinity=NodeAffinity(
                required_during_scheduling_ignored_during_execution=NodeSelector(
                    node_selector_terms=(
                        NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(
                                    "topology.kubernetes.io/zone",
                                    "In",
                                    ("zone-a",),
                                ),
                            )
                        ),
                    )
                )
            )
        ),
    )


def _samples():
    return [
        _node(),
        _pod(uid="default/p0"),
        Resource.from_map({"cpu": "100m", "memory": "64Mi"}),
        PodDisruptionBudget(
            name="pdb",
            selector=LabelSelector(match_labels={"app": "p0"}),
            disruptions_allowed=1,
        ),
    ]


def _wait(predicate, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


# ---------------------------------------------------------------------------
# codec round-trips
# ---------------------------------------------------------------------------


def test_every_kind_roundtrips_binary_equals_json():
    """Every registered kind's envelope survives frame→decode with the
    decoded value byte-identical (canonical JSON) to the JSON path, and
    api.codec.decode reconstructs an equal object from either."""
    assert set(KINDS) == {"Pod", "Node", "Resource", "PodDisruptionBudget"}
    for obj in _samples():
        env = encode(obj)
        via_binary = wire_codec.decode_frame(wire_codec.encode_frame(env))[0]
        via_json = json.loads(json.dumps(env))
        assert _canon(via_binary) == _canon(via_json) == _canon(env)
        assert decode(via_binary) == decode(via_json) == obj


def test_watch_event_and_list_envelopes_roundtrip():
    for etype in ("ADDED", "MODIFIED", "DELETED"):
        for obj in _samples():
            env = encode(obj)
            nested = wire_codec.encode_nested(env)
            frame = wire_codec.encode_event(etype, 7, nested)
            got, off = wire_codec.decode_frame(frame)
            assert off == len(frame)
            assert _canon(got) == _canon({"type": etype, "rv": 7, "object": env})
    nested = [wire_codec.encode_nested(encode(o)) for o in _samples()]
    lst, _ = wire_codec.decode_frame(wire_codec.encode_list_frame(42, nested))
    assert _canon(lst) == _canon(
        {"resourceVersion": 42, "items": [encode(o) for o in _samples()]}
    )


def test_nested_splice_shares_one_encoding():
    """The SAME nested blob spliced into an event frame and a list frame
    decodes identically in both — the encode-once/zero-copy contract."""
    env = encode(_pod(uid="default/share"))
    blob = wire_codec.encode_nested(env)
    evt, _ = wire_codec.decode_frame(wire_codec.encode_event("ADDED", 1, blob))
    lst, _ = wire_codec.decode_frame(wire_codec.encode_list_frame(1, [blob]))
    assert _canon(evt["object"]) == _canon(lst["items"][0]) == _canon(env)


def test_scalar_edge_values_roundtrip():
    value = {
        "big": 2**70,
        "neg": -(2**70),
        "zero": 0,
        "float": 3.141592653589793,
        "inf_free": 1e308,
        "none": None,
        "true": True,
        "false": False,
        "empty": "",
        "long": "x" * 5000,
        "uni": "schrödinger-猫",
        "list": [1, [2, [3, {"deep": "😀"}]], ""],
        "repeat": ["repeated-key"] * 8,  # dynamic-table hits
    }
    got = wire_codec.decode_frame(wire_codec.encode_frame(value))[0]
    assert got == value
    # trailing garbage is rejected, truncation reads as no frame
    frame = wire_codec.encode_frame(value)
    with pytest.raises(ValueError):
        wire_codec.decode_value(frame[4:] + b"\x00")
    import io

    assert wire_codec.read_frame(io.BytesIO(frame[: len(frame) // 2])) is None


def test_static_table_is_deterministic():
    """The static intern table is part of the wire contract between a
    server and its clients in one process generation — both sides build
    it from the same vocabulary, so it must be stable and collision-free."""
    assert len(set(wire_codec.STATIC_STRINGS)) == len(wire_codec.STATIC_STRINGS)
    for key in ("kind", "object", "type", "labels", "ADDED", "resourceVersion"):
        assert key in wire_codec.STATIC_STRINGS


# ---------------------------------------------------------------------------
# HTTP end-to-end: negotiation + decoded identity
# ---------------------------------------------------------------------------


def test_http_list_and_watch_identical_across_codecs():
    api = FakeCluster(pv_controller=False)
    server = ApiServer(api).start()
    endpoint = f"http://127.0.0.1:{server.port}"
    try:
        api.create_node(_node("wire-n0"))
        for i in range(3):
            api.create_pod(_pod(f"wire-{i}", uid=f"default/wire-{i}"))
        api.bind(Pod(name="wire-0", uid="default/wire-0"), "wire-n0")
        bc = ApiClient(endpoint, codec="binary")
        jc = ApiClient(endpoint, codec="json")
        for res in ("nodes", "pods"):
            assert _canon(bc.list(res)) == _canon(jc.list(res))
        # watch: same events, byte-identical decoded envelopes
        def take(client, res, n):
            out = []
            for evt in client.watch_stream(res, 0):
                if evt.get("type") != "BOOKMARK":
                    out.append(evt)
                if len(out) >= n:
                    return out
            return out

        assert _canon(take(bc, "pods", 4)) == _canon(take(jc, "pods", 4))
    finally:
        server.stop()


def test_json_stays_the_default_without_accept():
    """A client that never asks for binary (curl, the debug endpoints)
    gets JSON — content negotiation, not a flag day."""
    import urllib.request

    api = FakeCluster(pv_controller=False)
    server = ApiServer(api).start()
    try:
        api.create_node(_node())
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/api/v1/nodes"
        ) as resp:
            assert "application/json" in resp.headers.get("Content-Type", "")
            json.loads(resp.read())  # parses as plain JSON
    finally:
        server.stop()


def test_binary_frames_are_smaller_on_the_wire():
    api = FakeCluster(pv_controller=False)
    server = ApiServer(api).start()
    endpoint = f"http://127.0.0.1:{server.port}"
    try:
        for i in range(16):
            api.create_pod(_pod(f"sz-{i}", uid=f"default/sz-{i}"))
        ApiClient(endpoint, codec="binary").list("pods")
        ApiClient(endpoint, codec="json").list("pods")

        def _noted():
            with server._wire_mu:
                return {("binary", "tx"), ("json", "tx")} <= set(
                    server.wire_bytes
                )

        assert _wait(_noted)
        with server._wire_mu:
            wire = dict(server.wire_bytes)
        assert 0 < wire[("binary", "tx")] < wire[("json", "tx")]
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# condition-variable wakeup (no 0.5s poll)
# ---------------------------------------------------------------------------


def test_watch_cache_since_wakes_on_append():
    api = FakeCluster(pv_controller=False)
    server = ApiServer(api).start()
    try:
        cache = server.caches["pods"]
        api.create_pod(_pod("w0", uid="default/w0"))
        rv0 = cache.rv
        woke = {}

        def waiter():
            t0 = time.monotonic()
            events = cache.since(rv0, timeout=10.0)
            woke["latency_s"] = time.monotonic() - woke["recorded_at"]
            woke["blocked_s"] = time.monotonic() - t0
            woke["events"] = events

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.3)  # the watcher is idle, parked on the condvar
        woke["recorded_at"] = time.monotonic()
        api.create_pod(_pod("w1", uid="default/w1"))
        t.join(timeout=5.0)
        assert not t.is_alive()
        assert [e.rv for e in woke["events"]] == [rv0 + 1]
        assert woke["blocked_s"] >= 0.3  # it genuinely waited...
        assert woke["latency_s"] < 0.2  # ...and woke on notify, not a poll
        # an idle wait with nothing appended times out to [] on schedule
        t0 = time.monotonic()
        assert cache.since(cache.rv, timeout=0.05) == []
        assert time.monotonic() - t0 < 1.0
    finally:
        server.stop()


def test_watch_fanout_hop_is_sub_poll_interval_over_http():
    """PR 16's watch_fanout hop (api_write → watch_delivery) measures the
    wakeup the condvar replaced: with the 0.5s poll gone it sits in the
    low milliseconds even for watchers that were idle when the write
    landed."""
    api = FakeCluster(pv_controller=False)
    server = ApiServer(api).start()
    source = RemoteClusterSource(f"http://127.0.0.1:{server.port}")
    sched = Scheduler()
    try:
        source.connect(sched)
        mon = sched.install_controlplane(api_server=server, source=source)
        source.start()
        assert source.wait_for_sync()
        client = ApiClient(f"http://127.0.0.1:{server.port}")
        client.create_node(_node("hop-n0"))
        for i in range(4):
            client.create_pod(_pod(f"hop-{i}", uid=f"default/hop-{i}"))
            time.sleep(0.15)  # idle gaps: each write finds a parked watcher
        assert _wait(lambda: len(sched.queue) >= 4)
        sched.schedule_pending()
        assert _wait(lambda: mon.snapshot()["done_chains"] >= 4)
        fanout = mon.hop_summary()["watch_fanout"]
        assert fanout["count"] >= 4
        assert fanout["p50_s"] < 0.25, (
            f"watch_fanout p50 {fanout['p50_s']:.3f}s — the condvar wakeup "
            "should deliver well under the old 0.5s poll interval"
        )
    finally:
        source.stop()
        server.stop()


# ---------------------------------------------------------------------------
# wire-byte accounting on scrape
# ---------------------------------------------------------------------------


def test_wire_bytes_counters_land_in_metrics():
    api = FakeCluster(pv_controller=False)
    server = ApiServer(api).start()
    sched = Scheduler()
    try:
        sched.install_controlplane(api_server=server)
        bc = ApiClient(f"http://127.0.0.1:{server.port}", codec="binary")
        jc = ApiClient(f"http://127.0.0.1:{server.port}", codec="json")
        bc.create_node(_node("m0"))
        bc.list("nodes")
        jc.list("nodes")
        # the handler notes tx bytes after writing the response — give
        # the accounting a beat before scraping
        def _noted():
            with server._wire_mu:
                return {("binary", "tx"), ("json", "tx")} <= set(
                    server.wire_bytes
                )

        assert _wait(_noted)
        text = sched.expose_metrics()
        assert "scheduler_tpu_wire_bytes_total" in text
        for codec in ("binary", "json"):
            line = next(
                ln
                for ln in text.splitlines()
                if ln.startswith("scheduler_tpu_wire_bytes_total")
                and f'codec="{codec}"' in ln
                and 'direction="tx"' in ln
            )
            assert float(line.rsplit(" ", 1)[1]) > 0
        # counters are cumulative across scrapes (delta sync, no resets)
        before = sched.expose_metrics()
        with server._wire_mu:
            tx0 = server.wire_bytes[("binary", "tx")]
        bc.list("nodes")
        assert _wait(lambda: server.wire_bytes[("binary", "tx")] > tx0)
        after = sched.expose_metrics()

        def tx(text_):
            return sum(
                float(ln.rsplit(" ", 1)[1])
                for ln in text_.splitlines()
                if ln.startswith("scheduler_tpu_wire_bytes_total")
                and 'codec="binary"' in ln
            )

        assert tx(after) > tx(before)
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# bind retry idempotence (kill-after-apply)
# ---------------------------------------------------------------------------


class _KillAfterApply(ApiClient):
    """First binding POST: let the server apply it, then kill the
    response on the way back — the transport shape of a retried write."""

    def __init__(self, endpoint, **kw):
        super().__init__(endpoint, **kw)
        self.kills_left = 1
        self.killed = 0

    def _conn(self, fresh=False):
        real = super()._conn(fresh=fresh)
        outer = self

        class Proxy:
            def request(self, method, path, body=None, headers=None):
                self._arm = "/binding" in path and outer.kills_left > 0
                real.request(method, path, body=body, headers=headers)

            def getresponse(self):
                resp = real.getresponse()
                if self._arm:
                    outer.kills_left -= 1
                    outer.killed += 1
                    resp.read()  # server finished: the apply happened
                    raise ConnectionResetError(
                        "injected: response lost after apply"
                    )
                return resp

        return Proxy()


@pytest.mark.parametrize("codec", ["binary", "json"])
def test_bind_retry_after_lost_response_is_idempotent(codec):
    api = FakeCluster(pv_controller=False)
    server = ApiServer(api).start()
    endpoint = f"http://127.0.0.1:{server.port}"
    try:
        api.create_node(_node("bind-n0"))
        api.create_node(_node("bind-n1"))
        pod = _pod("bind-p0", uid="default/bind-p0")
        api.create_pod(pod)
        client = _KillAfterApply(endpoint, codec=codec)
        client.bind(pod, "bind-n0")  # must NOT raise: retry sees its own 409
        assert client.killed == 1
        assert api.bindings == {"default/bind-p0": "bind-n0"}
        # a REAL conflict — different node — still surfaces as 409
        with pytest.raises(ApiError) as ei:
            ApiClient(endpoint, codec=codec).bind(pod, "bind-n1")
        assert ei.value.code == 409
        assert api.bindings == {"default/bind-p0": "bind-n0"}
    finally:
        server.stop()


@pytest.mark.parametrize("codec", ["binary", "json"])
def test_bind_many_tolerates_conflict_on_retry(codec):
    api = FakeCluster(pv_controller=False)
    server = ApiServer(api).start()
    endpoint = f"http://127.0.0.1:{server.port}"
    try:
        api.create_node(_node("bm-n0"))
        api.create_node(_node("bm-n1"))
        p0 = _pod("bm-p0", uid="default/bm-p0")
        p1 = _pod("bm-p1", uid="default/bm-p1")
        api.create_pod(p0)
        api.create_pod(p1)
        client = ApiClient(endpoint, codec=codec)
        assert client.bind_many([(p0, "bm-n0")]) == [None]
        # replaying the same binding (lost-response retry) is a success;
        # a different node for an already-bound pod is a real error
        errs = client.bind_many([(p0, "bm-n0"), (p1, "bm-n1")])
        assert errs[0] is None and errs[1] is None
        errs = client.bind_many([(p0, "bm-n1")])
        assert errs[0] is not None and "409" in errs[0]
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# chaos over binary frames
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", ["watch-cut", "compaction"])
def test_chaos_watch_faults_over_binary_frames(name, tmp_path):
    """watch-cut and forced-410/compaction faults inject ABOVE the frame
    seam (on decoded events), so they pass the oracle riding binary
    frames, the recorded journal replays to identical placements
    (replay is codec-untouched), and the SAME scenario under the JSON
    codec converges too — drain batching is wall-clock dependent, so
    journal bytes are not compared across codecs."""
    import dataclasses

    from kubernetes_tpu.chaos.journal import replay
    from kubernetes_tpu.chaos.runner import SCENARIOS, run_scenario

    scn = SCENARIOS[name]
    assert scn.mode == "http" and scn.codec == "binary"
    for codec in ("binary", "json"):
        path = str(tmp_path / f"{name}-{codec}.jsonl")
        res = run_scenario(dataclasses.replace(scn, codec=codec), path)
        assert res.problems == [], f"{name}/{codec} oracle: {res.problems}"
        assert res.injected, f"{name}/{codec} injected no faults"
        rr = replay(path)
        assert rr.ok, f"{name}/{codec} replay: {rr.mismatches[:2]}"


@pytest.mark.slow
def test_wire_soak_chaos_enabled_with_hollow_nodes():
    """Tier-1-sized config17 soak shape: control-plane + device faults
    simultaneously, binary frames end to end, a hollow-node fleet riding
    the same apiserver — the post-run invariant oracle must be clean."""
    from kubernetes_tpu.chaos.runner import run_chaos_soak

    out = run_chaos_soak(
        n_nodes=6,
        n_pods=48,
        rounds=2,
        fault_rate=0.1,
        device_fault_rate=0.1,
        codec="binary",
        hollow_nodes=4,
    )
    assert out["problems"] == []
    assert out["bound"] == 48
    assert out["codec"] == "binary" and out["hollow_nodes"] == 4
    assert out["injected_total"] > 0
