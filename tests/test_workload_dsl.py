"""Declarative workload DSL (scheduler_perf.go:447-750's op list): new
bench workloads are data, not code."""

from kubernetes_tpu.tools.workload_dsl import run_workload

YAML = """
name: mini-mixed
ops:
  - op: createNodes
    count: 20
    zones: 4
    cpu: "8"
    memory: 16Gi
  - op: createPods          # warm-up, NOT measured
    count: 30
    cpuRequest: [100m, 250m]
  - op: barrier
  - op: createPods
    count: 60
    apps: 6
    spreadApps: 4
    maxSkew: 3
    collectMetrics: true
  - op: barrier
  - op: churn
    deletePods: 10
    createNodes: 2
  - op: createPods
    count: 40
    antiAffinityGroups: 8
    collectMetrics: true
  - op: barrier
"""


def test_yaml_workload_executes_and_measures():
    out = run_workload(YAML)
    assert out["name"] == "mini-mixed"
    assert out["nodes"] == 22  # 20 + 2 churn-added
    assert out["pods_created"] == 130
    # 10 bound pods were churned away
    assert out["pods_bound"] == 120
    # only the collectMetrics ops count toward throughput
    assert out["measured_pods"] == 100
    assert out["pods_per_s"] is not None and out["pods_per_s"] > 0


def test_unknown_op_rejected():
    import pytest

    with pytest.raises(ValueError, match="unknown op"):
        run_workload({"ops": [{"op": "frobnicate"}]})


def test_anti_affinity_groups_respected():
    out = run_workload(
        {
            "ops": [
                {"op": "createNodes", "count": 12},
                {
                    "op": "createPods",
                    "count": 24,
                    "antiAffinityGroups": 2,
                    "collectMetrics": True,
                },
                {"op": "barrier"},
            ]
        }
    )
    # 2 groups x 12 hostname-exclusive nodes = 24 placeable
    assert out["pods_bound"] == 24
