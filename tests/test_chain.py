"""Chained (pipelined) dispatch must be decision-identical to the direct
path — and to the serial oracle.

chain_dispatch appends each batch's placements into the device cluster
inside the dispatch (ops/chain.py), so consecutive batches pipeline without
host round trips.  Decisions must match a scheduler with the chain disabled
(which the gang tests in turn prove identical to one-pod-at-a-time).
"""

import random

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    LabelSelector,
    Node,
    Pod,
    PodAffinityTerm,
    PodAntiAffinity,
    TopologySpreadConstraint,
)
from kubernetes_tpu.framework import config as cfg
from kubernetes_tpu.scheduler import Scheduler


def _nodes(n=12, zones=3):
    return [
        Node(
            name=f"n{i}",
            labels={
                "kubernetes.io/hostname": f"n{i}",
                "topology.kubernetes.io/zone": f"z{i % zones}",
            },
            capacity=Resource.from_map({"cpu": "4", "memory": "8Gi", "pods": 20}),
        )
        for i in range(n)
    ]


def _mixed_pods(n, rng):
    pods = []
    for i in range(n):
        kind = rng.randrange(3)
        if kind == 0:
            g = f"g{i % 5}"
            pods.append(
                Pod(
                    name=f"p{i}",
                    labels={"grp": g},
                    affinity=Affinity(
                        pod_anti_affinity=PodAntiAffinity(
                            required_during_scheduling_ignored_during_execution=(
                                PodAffinityTerm(
                                    topology_key="kubernetes.io/hostname",
                                    label_selector=LabelSelector(
                                        match_labels={"grp": g}
                                    ),
                                ),
                            )
                        )
                    ),
                    containers=[
                        Container(requests={"cpu": "100m", "memory": "64Mi"})
                    ],
                )
            )
        elif kind == 1:
            app = f"a{i % 4}"
            pods.append(
                Pod(
                    name=f"p{i}",
                    labels={"app": app},
                    topology_spread_constraints=(
                        TopologySpreadConstraint(
                            max_skew=2,
                            topology_key="topology.kubernetes.io/zone",
                            when_unsatisfiable="DoNotSchedule",
                            label_selector=LabelSelector(
                                match_labels={"app": app}
                            ),
                        ),
                    ),
                    containers=[
                        Container(requests={"cpu": "100m", "memory": "64Mi"})
                    ],
                )
            )
        else:
            # plain pods mixed in keep the batch OFF the signature fast
            # path only when combined with the above (they alone would be)
            pods.append(
                Pod(
                    name=f"p{i}",
                    labels={"grp": f"g{i % 5}"},
                    containers=[
                        Container(
                            requests={
                                "cpu": f"{rng.choice([100, 200])}m",
                                "memory": "64Mi",
                            }
                        )
                    ],
                )
            )
    return pods


def _run(pods, batch_size=8, disable_chain=False):
    conf = cfg.SchedulerConfiguration(batch_size=batch_size)
    sched = Scheduler(configuration=conf)
    bindings = {}
    sched.binding_sink = lambda pod, node: bindings.__setitem__(pod.name, node)
    if disable_chain:
        sched._chain_quickcheck = lambda fwk, batch: False
    for n in _nodes():
        sched.on_node_add(n)
    for p in pods:
        sched.on_pod_add(p)
    outs = sched.schedule_pending()
    placements = {o.pod.name: o.node for o in outs}
    return placements, sched


def test_chain_matches_direct_multi_batch():
    for seed in (5, 17):
        rng = random.Random(seed)
        spec = _mixed_pods(40, rng)
        got, s_chain = _run([p for p in spec], batch_size=8)
        rng = random.Random(seed)
        spec2 = _mixed_pods(40, rng)
        want, s_direct = _run([p for p in spec2], batch_size=8, disable_chain=True)
        # cross-pod batches ride the wave inside the chained machinery and
        # count as wave_batches; both kinds flow through chain_dispatch
        chained = s_chain.metrics.get("chain_batches", 0) + s_chain.metrics.get(
            "wave_batches", 0
        )
        assert chained >= 2, s_chain.metrics
        assert got == want, {
            k: (got[k], want[k]) for k in got if got.get(k) != want.get(k)
        }


def test_chain_survives_bind_confirmations():
    """FakeCluster-style confirmation events (assumed-pod adds) must not
    break the chain (they are capacity no-ops)."""
    rng = random.Random(3)
    pods = _mixed_pods(24, rng)
    conf = cfg.SchedulerConfiguration(batch_size=8)
    sched = Scheduler(configuration=conf)

    def sink(pod, node):
        import copy

        bound = copy.copy(pod)
        bound.node_name = node
        sched.on_pod_add(bound)  # the informer confirmation

    sched.binding_sink = sink
    for n in _nodes():
        sched.on_node_add(n)
    for p in pods:
        sched.on_pod_add(p)
    outs = sched.schedule_pending()
    assert all(o.node for o in outs)
    chained = sched.metrics.get("chain_batches", 0) + sched.metrics.get(
        "wave_batches", 0
    )
    assert chained >= 2, sched.metrics


def test_chain_breaks_on_external_event_and_recovers():
    rng = random.Random(9)
    conf = cfg.SchedulerConfiguration(batch_size=8)
    sched = Scheduler(configuration=conf)
    sched.binding_sink = lambda pod, node: None
    for n in _nodes():
        sched.on_node_add(n)
    for p in _mixed_pods(16, rng):
        sched.on_pod_add(p)
    sched.schedule_pending()
    # external assigned pod lands → chain must invalidate...
    sched.on_pod_add(
        Pod(
            name="ext",
            node_name="n0",
            labels={"grp": "g0"},
            containers=[Container(requests={"cpu": "500m"})],
        )
    )
    # ...and the next drain must still schedule correctly (anti-affinity
    # against the external pod's group on n0)
    g0 = Pod(
        name="after",
        labels={"grp": "g0"},
        affinity=Affinity(
            pod_anti_affinity=PodAntiAffinity(
                required_during_scheduling_ignored_during_execution=(
                    PodAffinityTerm(
                        topology_key="kubernetes.io/hostname",
                        label_selector=LabelSelector(match_labels={"grp": "g0"}),
                    ),
                )
            )
        ),
        containers=[Container(requests={"cpu": "100m"})],
    )
    sched.on_pod_add(g0)
    outs = sched.schedule_pending()
    by = {o.pod.name: o for o in outs}
    assert by["after"].node is not None and by["after"].node != "n0"
