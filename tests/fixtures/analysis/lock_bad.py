"""Seeded lock-discipline violations — every marked line MUST be found.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""

import threading

_KTPU_GUARDED = {
    "Owner": {
        "lock": "_mu",
        "guards": {"cache": "Store", "_epoch": None},
        "requires_lock": ["_patch_view"],
    },
    "Store": {
        "external_lock": "Owner._mu",
        "readonly": ["peek"],
    },
}


class Store:
    def __init__(self):
        self.items = {}

    def put(self, k, v):  # mutating — callers must hold Owner._mu
        self.items[k] = v

    def peek(self, k):
        return self.items.get(k)


class Owner:
    def __init__(self):
        self._mu = threading.RLock()
        self.cache = Store()
        self._epoch = 0

    def ok_locked_mutation(self, k, v):
        with self._mu:
            self.cache.put(k, v)
            self._epoch += 1

    def bad_unlocked_call(self, k, v):
        self.cache.put(k, v)  # VIOLATION: mutating call without the lock

    def bad_unlocked_field(self):
        self._epoch += 1  # VIOLATION: guarded field write without the lock

    def bad_unlocked_alias(self, k):
        entry = self.cache.items.get(k)
        entry.value = 1  # VIOLATION: mutation through a cache-derived alias

    def _commit_under_lock(self, k, v):
        # exempt body: the name suffix promises callers hold the lock
        self.cache.put(k, v)
        self._epoch += 1

    def _patch_view(self):
        self._epoch += 1  # exempt: registered in requires_lock

    def ok_verified_caller(self, k, v):
        with self._mu:
            self._commit_under_lock(k, v)
            self._patch_view()

    def bad_unverified_caller(self, k, v):
        self._commit_under_lock(k, v)  # VIOLATION: contract needs the lock
