"""Negative fixture: donation discipline — must stay silent.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""

import functools

import jax
import jax.numpy as jnp


# ktpu: axes()
@functools.partial(jax.jit, donate_argnames=("used",))
def commit(used, delta):
    return used + delta


def caller(used, delta):
    total = used.sum()  # reads BEFORE the donation are fine
    used = commit(used, delta)  # the rebind revives the name
    after = used.sum()  # ...so this reads the fresh buffer
    return after, total


def branches(used, delta, fast):
    if fast:
        used = commit(used, delta)
    else:
        used = commit(used, delta)
    return used  # rebound on both paths — alive


def untouched(state, delta):
    out = commit(state["used"], delta)  # non-name handoffs are the
    return out, state  # holder-dict discipline, not tracked here
