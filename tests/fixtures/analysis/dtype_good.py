"""Negative twin of dtype_bad.py: the same arithmetic with the
promotions spelled out — floor division, explicit astype on bool
operands, integer rescaling, and contract-conforming carries."""

import jax
import jax.numpy as jnp

I32 = jnp.int32
I64 = jnp.int64


# ktpu: axes(scores=i64[P,N], feas=bool[P,N])
@jax.jit
def exact_arithmetic(scores, feas):
    halved = scores // 2
    counted = feas.astype(I32) * 3
    scaled = (scores * 5) // 10
    masked = jnp.where(feas, scores, 0)
    return halved, counted, scaled, masked


# ktpu: axes(rows=i64[S,N])
# ktpu: accum(i64, i32, bool)
@jax.jit
def integer_accumulator(rows):
    acc = jnp.zeros((rows.shape[1],), I64)

    def step(carry, row):
        return carry + row, 0

    out, _ = jax.lax.scan(step, acc, rows)
    return out
