"""Seeded `shard`-rule violations: ops that cross the sharded N axis
(mesh ('pods', 'nodes')) outside any declared collective helper — the
multichip refactor must see every one of these in a roster."""

import jax
import jax.numpy as jnp

I32 = jnp.int32


# ktpu: axes(term_counts=i64[T,N], choice=i32, spec=i64[P,N])
@jax.jit
def crossings(term_counts, choice, spec):
    totals = jnp.sum(term_counts, axis=1)  # VIOLATION
    safe = jnp.maximum(choice, 0)
    row = term_counts[:, safe]  # VIOLATION
    crossed = jnp.einsum("tn,pn->tp", term_counts, spec)  # VIOLATION
    return totals, row, crossed
