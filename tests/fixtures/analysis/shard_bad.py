"""Seeded `shard`-rule violations: ops that cross the sharded N axis
(mesh ('pods', 'nodes')) outside any declared collective helper — the
multichip refactor must see every one of these in a roster."""

import jax
import jax.numpy as jnp

I32 = jnp.int32

# a rostered site WITHOUT a resolved(<mechanism>) sharding story is a
# finding too: the inventory is a burn-down, not a parking lot
_KTPU_N_COLLECTIVES = {
    "unresolved_site": "still thinking about this one",  # VIOLATION
}


# ktpu: axes(term_counts=i64[T,N], spec=i64[P,N])
@jax.jit
def unresolved_site(term_counts, spec):
    return jnp.einsum("tn,pn->tp", term_counts, spec)


# ktpu: axes(term_counts=i64[T,N], choice=i32, spec=i64[P,N])
@jax.jit
def crossings(term_counts, choice, spec):
    totals = jnp.sum(term_counts, axis=1)  # VIOLATION
    safe = jnp.maximum(choice, 0)
    row = term_counts[:, safe]  # VIOLATION
    crossed = jnp.einsum("tn,pn->tp", term_counts, spec)  # VIOLATION
    return totals, row, crossed
