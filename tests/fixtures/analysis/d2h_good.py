"""Negative fixture: disciplined device-boundary code — must stay silent.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""

import jax
import jax.numpy as jnp
import numpy as np


# ktpu: axes()
@jax.jit
def kernel(x):
    return x + 1


class Scheduler:
    def _d2h(self, value):
        # the accounted choke point (counters elided in the fixture)
        return jax.device_get(value)

    def harvest(self, batch):
        results_dev = kernel(batch)
        results_dev.copy_to_host_async()  # non-blocking prefetch is fine
        both = self._d2h(results_dev)  # routed: this is the contract
        if both is None:  # identity check — no device sync
            return None
        host = np.asarray(both)  # host value by now — plain numpy
        return int(host[0])

    def host_math(self, rows):
        arr = np.asarray(rows)  # pure host numpy — never device-resident
        return arr.tolist()  # host .tolist() is not a fetch
