"""Negative twin of shard_bad.py: the same N-crossings under a declared
``_KTPU_N_COLLECTIVES`` roster entry, plus genuinely shard-local work
(elementwise over N, reductions over non-N axes) outside any roster."""

import jax
import jax.numpy as jnp

I32 = jnp.int32

# the declared collective inventory for this module — the analyzer
# sanctions N-crossings under these functions only, and each entry must
# lead with its resolved(<mechanism>) sharding story
_KTPU_N_COLLECTIVES = {
    "reduce_nodes": "resolved(collective): term totals + chosen-node "
    "gather are cross-shard by design (admission readback) — per-shard "
    "partials + psum/all-gather",
}


# ktpu: axes(term_counts=i64[T,N], choice=i32, spec=i64[P,N])
@jax.jit
def reduce_nodes(term_counts, choice, spec):
    totals = jnp.sum(term_counts, axis=1)
    safe = jnp.maximum(choice, 0)
    row = term_counts[:, safe]
    crossed = jnp.einsum("tn,pn->tp", term_counts, spec)
    return totals, row, crossed


# ktpu: axes(term_counts=i64[T,N], spec=i64[P,N])
@jax.jit
def shard_local(term_counts, spec):
    # elementwise over N keeps the shard layout; reducing T does too
    per_node = jnp.sum(term_counts, axis=0)
    masked = spec * (per_node > 0)[None, :].astype(spec.dtype)
    return masked
