"""Negative twin of breaker_bad.py: every jit root carries a breaker
fallback registration — a parity-certified fallback engine or an
explicit no_fallback waiver."""

import jax
import jax.numpy as jnp

_KTPU_BREAKER_FALLBACKS = {
    "breaker_good.covered_root": "fallback(serial-oracle): the host "
    "replay engine answers the batch bit-identically when the breaker "
    "is open",
    "breaker_good.waived_root": "no_fallback: diagnostic-only probe — a "
    "failure surfaces in the debug response, no placement depends on it",
}


# ktpu: axes(x=i64[P])
@jax.jit
def covered_root(x):
    return x + 1


# ktpu: axes(x=i64[P])
@jax.jit
def waived_root(x):
    return x * 2
