"""Seeded jit-boundary violations — every marked line MUST be found.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _helper_syncs(x):
    # reachable from the jitted root below → checked in jit context
    return x.item()  # VIOLATION: host sync


# ktpu: axes()
@functools.partial(jax.jit, static_argnames=("n",))
def kernel(values, mask, n: int):
    total = jnp.sum(values)
    if total > 0:  # VIOLATION: branch on a traced value
        total = -total
    host = np.asarray(values)  # VIOLATION: numpy coercion of a traced value
    flag = bool(mask)  # VIOLATION: bool() on a traced value
    peek = _helper_syncs(total)
    out = jnp.zeros(n)
    for v in values:  # VIOLATION: iteration over a traced value
        out = out + v
    return out, host, flag, peek


# ktpu: axes()
@jax.jit
def loops_on_tracer(xs):
    acc = jnp.zeros_like(xs)
    while xs.sum() > 0:  # VIOLATION: while on a traced condition
        acc = acc + xs
    return acc
