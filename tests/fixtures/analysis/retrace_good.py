"""Negative fixture: committed dtypes + bucketed static sizes — silent.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""

import functools

import jax
import jax.numpy as jnp


def bucket_cap(n, floor=1):
    cap = max(int(floor), 1)
    while cap < n:
        cap *= 2
    return cap


# ktpu: axes()
@functools.partial(jax.jit, static_argnames=("n",))
def kernel(x, scale, n: int):
    return x[:n] * scale


def dispatch(batch):
    scale = jnp.asarray(0.5, jnp.float32)  # committed dtype — no weak type
    n = bucket_cap(len(batch))  # bucketed before it reaches the signature
    a = kernel(batch, scale, n=n)
    b = kernel(batch, scale, n=bucket_cap(len(batch), 16))  # bucketed inline
    return a, b
