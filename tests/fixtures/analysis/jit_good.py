"""Jit-boundary negative fixture — the analyzer must stay silent.

Shape-derived host Python, static_argnames, the optional-array
`is None` idiom, and host-side wrappers (unreachable from any root)
are all legal.  Never imported: the analyzer parses it.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _accumulate(table, label_vals):
    # reachable helper: static shape-driven loops are fine under trace
    R = table.shape[-1]
    ok = None
    for r in range(R):
        col = table[..., r]
        ok = col if ok is None else (ok & col)  # `is None` — not a branch
    if ok is None:
        ok = jnp.ones(label_vals.shape, bool)
    return ok


# ktpu: axes()
@functools.partial(jax.jit, static_argnames=("v_cap", "extra"))
def kernel(dc, batch, v_cap: int, extra=None):
    n = len(batch)  # len() of a tracer is its static leading dim
    width = int(dc.shape[1])  # int() of a static shape value
    masks = _accumulate(dc, batch)
    if extra is not None:  # optional-operand idiom: identity, not a branch
        masks = masks & extra
    if v_cap > 0:  # static_argnames value: compile-time branch
        masks = masks[:v_cap]
    big = jnp.iinfo(jnp.int32).max
    scores = jnp.where(masks, big, 0)
    for a, b in ((scores, masks), (masks, scores)):  # tuple display: static
        scores = jnp.where(b, scores, a)
    return scores[:n], width


def host_wrapper(host_rows):
    # NOT reachable from a jitted root — host numpy/casts are fine here
    arr = np.asarray(host_rows, np.int32)
    total = int(arr.sum())
    return kernel(arr, arr, total)
