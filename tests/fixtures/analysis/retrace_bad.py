"""Seeded retrace violations — every marked line MUST be found.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""

import functools

import jax
import jax.numpy as jnp


# ktpu: axes()
@functools.partial(jax.jit, static_argnames=("n",))
def kernel(x, scale, n: int):
    return x[:n] * scale


def dispatch(batch):
    a = kernel(batch, 0.5, n=8)  # VIOLATION: weak-typed scalar into the signature
    b = kernel(batch, batch[0], n=len(batch))  # VIOLATION: unbucketed len() static arg
    c = kernel(batch, batch[0], n=batch.shape[0])  # VIOLATION: unbucketed .shape static arg
    return a, b, c
