"""Seeded slice-clamp violations — every marked line MUST be found.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""

import functools

import jax
import jax.numpy as jnp


# ktpu: axes()
@functools.partial(jax.jit, static_argnames=("width",))
def window_write(dst, delta, start, width: int):
    out = jax.lax.dynamic_update_slice(dst, delta, (start,))  # VIOLATION: traced start, unpadded dst
    return out


# ktpu: axes()
@jax.jit
def scatter_write(dst, idx, vals):
    return dst.at[idx].set(vals)  # VIOLATION: traced index, no explicit mode=


# ktpu: axes()
@jax.jit
def helper_write(dst, delta, q):
    return _dus(dst, delta, q)


def _dus(full, delta, start):
    starts = (start, jnp.zeros((), jnp.int32))
    return jax.lax.dynamic_update_slice(full, delta, starts)  # VIOLATION: traced start through the helper
