"""Plugin-purity negative fixture — the analyzer must stay silent.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""

_SPECIAL_KINDS = frozenset({"gce-pd", "iscsi"})


class Status:
    @staticmethod
    def skip():
        return Status()

    @staticmethod
    def success():
        return Status()

    @staticmethod
    def unresolvable(*reasons, plugin=None):
        return Status()


class GateFirst:
    """The in-tree shape: spec-only gate, then the impure tail."""

    name = "GateFirst"
    pre_filter_spec_pure = True

    def pre_filter(self, state, pod):
        if not pod.pvc_names():
            return Status.skip()
        # off the spec path: non-gated pods take the per-pod walk
        claims = self.handle.pvc_cache.get(pod.namespace)
        state.write(("k", pod.uid), claims)
        return Status.success()


class SpecDerivedLocals:
    """Locals computed from the pod (and ALL_CAPS constants) stay pure."""

    name = "SpecDerivedLocals"
    pre_filter_spec_pure = True

    def pre_filter(self, state, pod):
        needs_check = any(
            v.source_kind in _SPECIAL_KINDS for v in pod.volumes
        )
        names = pod.pvc_names()
        if not needs_check and not names:
            return Status.skip()
        state.write(("k", pod.uid), set(names))
        return Status.success()


class FullySpecPure:
    """No gate at all — the entire body is (pure) spec path."""

    name = "FullySpecPure"
    pre_filter_spec_pure = True

    def pre_filter(self, state, pod):
        aff = pod.affinity
        required = aff.node_affinity if aff else None
        if required is None and not pod.node_selector:
            return Status.skip()
        return Status.success()


class UndeclaredStateful:
    """No purity flag declared — outside the checker's scope entirely."""

    name = "UndeclaredStateful"

    def pre_filter(self, state, pod):
        self.counter = getattr(self, "counter", 0) + 1
        state.write(("quota", pod.namespace), self.counter)
        return Status.success()
