"""Seeded donation violations — every marked line MUST be found.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""

import functools

import jax
import jax.numpy as jnp


# ktpu: axes()
@functools.partial(jax.jit, donate_argnames=("used",))
def commit(used, delta):
    return used + delta


# ktpu: axes()
@functools.partial(jax.jit, donate_argnums=(0,))
def splice(dst, rows):
    return jnp.concatenate([dst, rows])


def caller(used, delta):
    alias = used
    new_used = commit(used, delta)
    stale = used + 1  # VIOLATION: read after donating `used`
    worse = alias.sum()  # VIOLATION: alias of the donated buffer
    return new_used, stale, worse


def positional(dst, rows):
    out = splice(dst, rows)
    return out, dst.shape, dst  # VIOLATION: `dst` donated by argnum 0
