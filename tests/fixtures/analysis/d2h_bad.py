"""Seeded d2h-leak violations — every marked line MUST be found.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""

import jax
import jax.numpy as jnp
import numpy as np


# ktpu: axes()
@jax.jit
def kernel(x):
    return x * 2


class Scheduler:
    def _d2h(self, value):
        # the choke point itself — the ONE place a raw fetch belongs
        return jax.device_get(value)

    def harvest(self, batch):
        out = kernel(batch)
        host = np.asarray(out)  # VIOLATION: numpy coerces a device value
        peek = out.item()  # VIOLATION: blocking .item()
        raw = jax.device_get(out)  # VIOLATION: device_get outside _d2h
        if out:  # VIOLATION: implicit truthiness blocks on the device
            host = host + 1
        flag = bool(out)  # VIOLATION: bool() coercion of a device value
        return host, peek, raw, flag
