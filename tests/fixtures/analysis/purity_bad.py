"""Seeded plugin-purity violations — every marked line MUST be found.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""


class Status:
    @staticmethod
    def skip():
        return Status()

    @staticmethod
    def success():
        return Status()


class LeakyStateWrite:
    """Writes CycleState before the gate — verdict diverges per pod."""

    name = "LeakyStateWrite"
    pre_filter_spec_pure = True

    def pre_filter(self, state, pod):
        state.write(("k", pod.uid), {})  # VIOLATION: impure call pre-gate
        if not pod.pvc_names():
            return Status.skip()
        return Status.success()


class HandleReadBeforeGate:
    """Reads a handle cache on the spec path."""

    name = "HandleReadBeforeGate"
    pre_filter_spec_pure = True

    def pre_filter(self, state, pod):
        known = self.handle.pvc_cache.get(pod.namespace)  # VIOLATION
        if known is None and not pod.pvc_names():
            return Status.skip()
        return Status.success()


class GateOnInstanceState:
    """Branches the verdict on mutable plugin state — no call involved."""

    name = "GateOnInstanceState"
    pre_filter_spec_pure = True

    def pre_filter(self, state, pod):
        if self.disabled:  # VIOLATION: read of mutable state pre-gate
            return Status.skip()
        if not pod.volumes:
            return Status.skip()
        return Status.success()


class SelfMutation:
    """Caches cross-pod state on the plugin instance."""

    name = "SelfMutation"
    pre_filter_spec_pure = True

    def pre_filter(self, state, pod):
        self.seen = pod.uid  # VIOLATION: write to non-local state
        if not pod.volumes:
            return Status.skip()
        return Status.success()
