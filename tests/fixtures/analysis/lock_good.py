"""Lock-discipline negative fixture — the analyzer must stay silent.

Never imported: the analyzer parses it (tests/test_static_analysis.py).
"""

import threading

_KTPU_GUARDED = {
    "Owner": {
        "lock": "_mu",
        "guards": {"cache": "Store", "_epoch": None},
        "requires_lock": ["_patch_view"],
    },
    "Store": {
        "external_lock": "Owner._mu",
        "readonly": ["peek"],
    },
}


class Store:
    def __init__(self):
        self.items = {}

    def put(self, k, v):
        self.items[k] = v

    def peek(self, k):
        return self.items.get(k)


class Owner:
    def __init__(self):
        self._mu = threading.RLock()
        self.cache = Store()
        self._epoch = 0

    def locked_mutation(self, k, v):
        with self._mu:
            self.cache.put(k, v)
            self._epoch += 1

    def unlocked_read(self, k):
        return self.cache.peek(k)  # readonly method — no lock needed

    def _commit_under_lock(self, k, v):
        self.cache.put(k, v)
        self._patch_view()

    def _patch_view(self):
        self._epoch += 1

    def verified_caller(self, k, v):
        with self._mu:
            self._commit_under_lock(k, v)

    def closure_takes_its_own_lock(self):
        def handler(k, v):
            with self._mu:
                self.cache.put(k, v)

        return handler
