"""Seeded `breaker`-rule violations: jit roots without a breaker
fallback registration, a malformed roster story, and a stale entry —
the fallback roster is a burn-down, not a parking lot."""

import jax
import jax.numpy as jnp

# a malformed story (no fallback(<engine>): / no_fallback: lead) and a
# stale entry naming a vanished root are findings; `orphan_root` below
# has no entry at all
_KTPU_BREAKER_FALLBACKS = {
    "breaker_bad.sloppy_root": "we should think about this",  # VIOLATION
    "breaker_bad.vanished_root": "fallback(serial): long gone",  # VIOLATION
}


# ktpu: axes(x=i64[P])
@jax.jit
def orphan_root(x):  # VIOLATION
    return x + 1


# ktpu: axes(x=i64[P])
@jax.jit
def sloppy_root(x):
    return x * 2
