"""Seeded `shape`-rule findings: named-dim algebra breaks that rank-1
broadcasting would silently absorb whenever the bucketed sizes
coincide.  Markers sit on the lines the analyzer must flag."""

import jax
import jax.numpy as jnp

I64 = jnp.int64


# ktpu: axes(spec=i64[P,N], term_counts=i64[T,N])
@jax.jit
def mixed_axes(spec, term_counts):
    # a [P, N] speculation tensor combined with the [T, N] term counts:
    # valid to jax whenever P == T happens to hold after bucketing
    return spec + term_counts  # VIOLATION


# ktpu: axes(spec=i64[P,N], term_counts=i64[T,N])
@jax.jit
def mixed_contraction(spec, term_counts):
    return jnp.einsum("pn,pn->n", spec, term_counts)  # VIOLATION


# ktpu: axes(term_counts=i64[T,N], readback=i64[C,N])
@jax.jit
def carry_drift(term_counts, readback):
    def step(carry, _):
        return readback, carry[0]

    out, ys = jax.lax.scan(  # VIOLATION
        step, term_counts, jnp.zeros((4,), I64)
    )
    return out, ys


@jax.jit
def unannotated(state):  # VIOLATION
    return state * 2
